"""The prove stage: static proof of every candidate schedule (ISSUE 14).

A synthesized schedule is held to a STRICTLY harder standard than a
hand-written one — three gates, all static, all on any jax line:

1. **Schedule validity** (:func:`check_spans`): the policy's span list
   must exactly tile the shard — full coverage, no overlap, in-bounds,
   and (on the AG side) ascending contiguous order, the same fence
   ``ops.common.resolve_spans`` enforces at emit time. This is where a
   deliberately unbalanced policy (``policies.UNBALANCED_PROBE``) dies
   with a named diagnosis before it ever reaches a kernel.
2. **Protocol proof**: capture + verify the emitted kernel per world in
   {2, 4, 8} through the PR 10 machinery (``analysis/capture.py`` /
   ``verify.py``) — credit balance, static deadlock freedom, chunk-major
   issue order, bounded-wait telemetry density, landing-view coverage.
3. **Seeded-defect harness** (``analysis/defects.py``): the candidate's
   own capture is mutated the way emitter bugs would mutate it (dropped
   wait, dropped/extra signal, missing drain) and the verifier must flag
   every applicable mutation with a slot/site-named diagnosis while the
   clean twin stays silent — a synthesized family enters the tune spaces
   only if the verifier demonstrably HAS teeth on its graph.

``admit.py`` consumes the resulting :class:`Proof` objects; an unproved
candidate is rejected there with this module's diagnosis, never
registered.
"""

from __future__ import annotations

import dataclasses

from triton_dist_tpu.synth import policies as P
from triton_dist_tpu.synth.generate import Candidate

WORLDS = (2, 4, 8)

# (rows, quantum) sample points for the schedule-validity gate — shared
# with generate.py's identity-degeneracy prune (policies.SPAN_SAMPLES)
_SPAN_SAMPLES = P.SPAN_SAMPLES

# defect kinds applicable to the fused-pipeline families (the chunk-order
# swap needs a chunked a2a capture; these families' chunked puts are the
# ring form, checked structurally by the verifier instead)
_DEFECT_KINDS = (
    "dropped_wait", "dropped_signal", "extra_signal", "missing_drain",
)


@dataclasses.dataclass
class Proof:
    candidate: Candidate
    schedule_findings: list[str] = dataclasses.field(default_factory=list)
    reports: list = dataclasses.field(default_factory=list)  # verify.Report
    defect_failures: list[str] = dataclasses.field(default_factory=list)
    defect_notes: list[str] = dataclasses.field(default_factory=list)
    defects_run: int = 0

    @property
    def ok(self) -> bool:
        return (
            not self.schedule_findings
            and bool(self.reports)
            and all(r.ok for r in self.reports)
            and not self.defect_failures
        )

    @property
    def warnings(self) -> int:
        return sum(len(r.warnings) for r in self.reports)

    @property
    def diagnosis(self) -> str:
        """The first failing gate's named finding (empty when ok)."""
        if self.schedule_findings:
            return f"schedule_validity: {self.schedule_findings[0]}"
        for r in self.reports:
            if not r.ok:
                return f"{r.family}[{r.label}] w{r.world}: {r.errors[0]}"
        if self.defect_failures:
            return f"defect_harness: {self.defect_failures[0]}"
        if not self.reports:
            return "no protocol capture produced"
        return ""


def check_spans(
    spans, rows: int, *, ascending_required: bool,
) -> list[str]:
    """Static validity of one span schedule over a ``rows``-row shard:
    in-bounds, positive sizes, exact disjoint coverage, and (when the
    consuming side requires it) ascending contiguous order. Returns
    named findings (empty = valid)."""
    findings: list[str] = []
    if not spans:
        return [f"empty span schedule over {rows} rows"]
    for off, sz in spans:
        if sz < 1:
            findings.append(f"span ({off}, {sz}) has non-positive size")
        if off < 0 or off + sz > rows:
            findings.append(
                f"span ({off}, {sz}) exceeds the {rows}-row shard"
            )
    if findings:
        return findings
    by_off = sorted(spans)
    cursor = 0
    for off, sz in by_off:
        if off < cursor:
            findings.append(
                f"span ({off}, {sz}) OVERLAPS the previous span (rows "
                f"{off}..{cursor - 1} double-covered) — the mirrored "
                f"per-chunk credits no longer describe a partition of the "
                f"shard"
            )
            cursor = max(cursor, off + sz)
            continue
        if off > cursor:
            findings.append(
                f"rows {cursor}..{off - 1} are covered by NO span — the "
                f"shard tail/gap is never transferred"
            )
        cursor = off + sz
    if cursor < rows:
        findings.append(
            f"rows {cursor}..{rows - 1} are covered by NO span — the "
            f"shard tail is never transferred"
        )
    if ascending_required and list(spans) != by_off:
        findings.append(
            "span order is not ascending — the AG gather-group schedule "
            "derives compute coverage from span offsets and cannot "
            "consume a permuted order"
        )
    return findings


def _policy_of(cand: Candidate) -> P.SpanPolicy:
    return P.POLICY_BY_NAME[cand.policy]


def prove_candidate(
    cand: Candidate, worlds=WORLDS, *, defects: bool = True,
    progress=None,
) -> Proof:
    """Run all three gates for one candidate."""
    from triton_dist_tpu.analysis import capture as C
    from triton_dist_tpu.analysis import defects as D
    from triton_dist_tpu.analysis.sweep import verify_family
    from triton_dist_tpu.analysis.verify import Finding, Report

    say = progress or (lambda s: None)
    proof = Proof(cand)
    pol = _policy_of(cand)
    side = {v: k for k, v in P.FAMILY_OF_SIDE.items()}[cand.family]
    ascending = side == "ag"

    # gate 1: schedule validity across sample shapes and worlds
    for world in worlds:
        for rows, quantum in _SPAN_SAMPLES:
            spans = pol.spans(
                rows, cand.cfg.chunks_per_shard, quantum, world,
            )
            for f in check_spans(spans, rows, ascending_required=ascending):
                proof.schedule_findings.append(
                    f"{cand.policy} rows={rows} q={quantum} w={world}: {f}"
                )
        if proof.schedule_findings:
            return proof  # an invalid tiling never reaches a kernel

    # gate 2: capture + verify at every world
    rep_cap = None
    for world in worlds:
        say(f"{cand.family}[{cand.label}] world={world}")
        try:
            rep, cap = verify_family(
                cand.family, world, cand.label, cand.cfg
            )
        except C.CaptureError as exc:
            rep = Report(family=cand.family, world=world, label=cand.label)
            rep.errors.append(Finding("capture", str(exc)))
            proof.reports.append(rep)
            continue
        proof.reports.append(rep)
        if rep.ok and world == worlds[-1]:
            rep_cap = cap

    # gate 3: the seeded-defect harness on the candidate's own capture
    if defects and rep_cap is not None and all(r.ok for r in proof.reports):
        say(f"{cand.family}[{cand.label}] seeded defects")
        from triton_dist_tpu.analysis.verify import verify_capture

        for kind in _DEFECT_KINDS:
            try:
                seeded = D.seed_defect(rep_cap, kind)
            except ValueError as exc:
                proof.defect_notes.append(f"{kind}: not applicable ({exc})")
                continue
            rep = verify_capture(seeded.capture)
            hits = [f for f in rep.errors if f.check == seeded.expect_check]
            if not hits:
                proof.defect_failures.append(
                    f"{kind}: NOT flagged on {cand.family}[{cand.label}] "
                    f"(errors: {[str(f) for f in rep.errors]})"
                )
            elif not any(
                seeded.expect_naming in f.message for f in hits
            ):
                proof.defect_failures.append(
                    f"{kind}: diagnosis does not name "
                    f"{seeded.expect_naming!r}: {hits[0]}"
                )
            proof.defects_run += 1
        if proof.defects_run == 0:
            proof.defect_failures.append(
                "no defect kind applicable to this capture — the harness "
                "cannot demonstrate teeth on the synthesized graph"
            )
    return proof


def prove_all(
    candidates, worlds=WORLDS, *, defects: bool = True, progress=None,
) -> list[Proof]:
    return [
        prove_candidate(c, worlds, defects=defects, progress=progress)
        for c in candidates
    ]
