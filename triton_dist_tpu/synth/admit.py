"""The admit stage: proved schedules enter the tune spaces (ISSUE 14).

``admit`` consumes ``synth/prove.py`` proofs and enforces the loop's two
contracts:

- **No unproved schedule is ever registered.** A candidate whose proof
  failed any gate is REJECTED with the proof's named diagnosis — the
  admission report shows exactly which invariant died and where.
- **The no-regression ordering invariant.** Admitted candidates are
  appended to the family's LIVE tune space strictly AFTER every existing
  candidate (``extend_tune_space`` appends to the list
  ``contextual_autotune`` closes over, so the running process's tuner
  sees them immediately), and the standing registry
  (``synth/admitted.py``) replays the same order at import time — a
  sweep-free walk (``cached_or_first`` / interpreter) can never apply a
  synthesized schedule untimed, pinned by ``tests/test_synth.py``.

Each admitted candidate carries its ``perf_model`` cost term
(:func:`~triton_dist_tpu.perf_model.estimate_span_policy_time_ms` at a
reference decode-regime shard) so the report ranks what the tuner will
time. Registration into ``analysis/sweep.py`` is structural: the sweep
enumerates the tune-space constants, which include the standing registry
— ``scripts/protocol_lint.py`` therefore proves every admitted schedule
on every run, permanently.
"""

from __future__ import annotations

import dataclasses

from triton_dist_tpu.synth.admitted import is_admitted
from triton_dist_tpu.synth.generate import Candidate
from triton_dist_tpu.synth.prove import Proof

# Reference shard for the report's cost ranking: a decode-regime slab
# (256 rows x 4 KiB) at world 8 — the regime the overlap schedules serve
_COST_SHARD_BYTES = 256 * 4096
_COST_WORLD = 8


@dataclasses.dataclass
class Admission:
    candidate: Candidate
    admitted: bool
    standing: bool          # already in the committed registry
    diagnosis: str          # rejection reason (empty when admitted)
    cost_ms: float | None   # perf_model ranking term (admitted only)

    def line(self) -> str:
        c = self.candidate
        if not self.admitted:
            return (
                f"REJECTED  {c.family}[{c.label}] — {self.diagnosis}"
            )
        state = "standing" if self.standing else "newly admitted"
        return (
            f"admitted  {c.family}[{c.label}] ({state}; "
            f"cost {self.cost_ms:.4f} ms @ w{_COST_WORLD} ref shard)"
        )


@dataclasses.dataclass
class AdmissionReport:
    admissions: list[Admission]

    @property
    def admitted(self) -> list[Admission]:
        return [a for a in self.admissions if a.admitted]

    @property
    def rejected(self) -> list[Admission]:
        return [a for a in self.admissions if not a.admitted]

    @property
    def ok(self) -> bool:
        """The loop is healthy when every admitted candidate matches the
        standing registry posture (rejections are expected for probes)."""
        return all(a.standing for a in self.admitted)


def family_op(family: str):
    """The live autotuned op whose tune space a family's admissions
    extend."""
    import importlib

    # importlib, not `from ... import`: the ops package re-exports
    # same-named FUNCTIONS (ops.moe_reduce_rs the op) that shadow the
    # submodules as package attributes
    if family == "ag_group_gemm":
        m = importlib.import_module(
            "triton_dist_tpu.ops.allgather_group_gemm"
        )
        return m.ag_group_gemm_op
    if family == "moe_reduce_rs":
        m = importlib.import_module("triton_dist_tpu.ops.moe_reduce_rs")
        return m.moe_reduce_rs_op
    raise ValueError(f"unknown synthesis family {family!r}")


def extend_tune_space(op, cfg) -> bool:
    """Append ``cfg`` to a wrapped op's live tune space, strictly after
    every existing candidate. ``contextual_autotune`` exposes (and closes
    over) the same list object as ``op.autotune_configs``, so the append
    is visible to subsequent sweeps in this process. Idempotent: a config
    already present (legacy or previously admitted) is never duplicated
    and never moved — admission order can only ever append. Returns
    whether the space grew."""
    space = op.autotune_configs
    if cfg in space:
        return False
    space.append(cfg)
    return True


def admit(proofs: list[Proof]) -> AdmissionReport:
    """Register every PROVED candidate; reject the rest with the proof's
    named diagnosis."""
    from triton_dist_tpu import perf_model

    admissions: list[Admission] = []
    for proof in proofs:
        cand = proof.candidate
        if not proof.ok:
            admissions.append(Admission(
                candidate=cand, admitted=False, standing=False,
                diagnosis=proof.diagnosis or "unproved", cost_ms=None,
            ))
            continue
        standing = is_admitted(cand.family, cand.cfg)
        extend_tune_space(family_op(cand.family), cand.cfg)
        cost = perf_model.estimate_span_policy_time_ms(
            cand.policy, _COST_SHARD_BYTES, _COST_WORLD,
            cand.cfg.chunks_per_shard,
            spec=perf_model.CHIP_SPECS["v5e"],  # fixed ref chip: the
            # report must not depend on the host the script runs on
        )
        admissions.append(Admission(
            candidate=cand, admitted=True, standing=standing,
            diagnosis="", cost_ms=cost,
        ))
    return AdmissionReport(admissions)
