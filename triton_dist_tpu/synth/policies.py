"""Declarative schedule-policy space of the synthesizer (ISSUE 14).

A :class:`SpanPolicy` names one FAMILY of span schedules beyond the legacy
ring/chunked tiling: which pipeline sides may consume it, which chunk
counts are worth enumerating, how it degrades to the legacy single-span
protocol (the emitter identity pin), and why it might win (the rationale
``synth/admit.py`` records). The span MATH lives next to
``chunk_schedule`` in ``ops/common.py`` (``SPAN_POLICIES``) — the only
dependency the kernel host entries take; this module is the declarative
layer ``synth/generate.py`` enumerates over.

The contract with the emitter (``ops/gg_pipeline.py``): a policy is
nothing but a different ``(offset, rows)`` span list — the kernel bodies
consume it UNCHANGED. Per-chunk semaphore slots are positional, every PE
computes the same spans from the same static shapes, so slot agreement
across PEs holds for any policy by SPMD symmetry, exactly as for the
legacy schedule. What a policy can still break — credit balance, deadlock
freedom, issue order, telemetry density, landing-view coverage — is
exactly what ``synth/prove.py`` must prove before ``synth/admit.py`` will
register it.

``UNBALANCED_PROBE`` is the loop's negative control: a deliberately
broken policy (overlapping spans — double-covered rows) that
``generate.py`` never enumerates and ``prove.py`` must REJECT with a
named schedule-validity diagnosis. It exists so the rejection path is
exercised on every synthesis run, not just in tests.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from triton_dist_tpu.ops.common import SPAN_POLICIES, chunk_schedule

# The two pipeline sides the emitter serves (ops/gg_pipeline.py):
# "ag" = the fused AG-GroupGEMM ring (ascending contiguous spans only —
# its gather-group coverage derives from span offsets), "moe_rs" = the
# fused MoE combine push (chunks drained by slot index: order-free).
SIDES = ("ag", "moe_rs")

# side -> the verifier family name of analysis/sweep.py
FAMILY_OF_SIDE = {"ag": "ag_group_gemm", "moe_rs": "moe_reduce_rs"}


@dataclasses.dataclass(frozen=True)
class SpanPolicy:
    """One declarative schedule-policy family."""

    name: str
    sides: tuple[str, ...]       # pipeline sides the policy is valid on
    chunk_axis: tuple[int, ...]  # chunks_per_shard values worth enumerating
    world_adaptive: bool         # spans depend on the world size
    rationale: str               # why it could win (admit.py records this)
    identity: str                # how it degrades to the legacy single span
    _fn: Callable | None = None  # probe-only override (not in SPAN_POLICIES)

    def spans(self, rows: int, chunks: int, quantum: int = 1,
              world: int = 1) -> tuple[tuple[int, int], ...]:
        if self._fn is not None:
            return self._fn(rows, chunks, quantum, world)
        fn, needs_world, _ = SPAN_POLICIES[self.name]
        return fn(rows, chunks, quantum, world) if needs_world else fn(
            rows, chunks, quantum
        )

    def identity_params(self) -> dict:
        """(chunks_per_shard, world) at which this policy's schedule is the
        legacy single span — the tuple the emitter identity pin captures."""
        return {"chunks_per_shard": 1, "world": 2}


WINDOW = SpanPolicy(
    name="window",
    sides=("ag",),
    chunk_axis=(2, 4),
    world_adaptive=False,
    rationale=(
        "arrival-window consumption for the AG ring: geometric ascending "
        "span sizes put the smallest chunk on the wire first, so the "
        "consumer's per-hop first-chunk wait (the exposed bubble of "
        "perf_model.estimate_fused_ring_bubble_ms) shrinks toward one "
        "quantum's wire time while descriptor count stays bounded"
    ),
    identity="chunks_per_shard=1 emits chunk_schedule's single span",
)

INTERLEAVE = SpanPolicy(
    name="interleave",
    sides=("moe_rs",),
    # chunks=2 is identity-degenerate (any both-ends order of 2 chunks IS
    # the contiguous order) — generate.py's schedule-equality prune
    # rejects it with a named reason; the real coverage starts at 4
    chunk_axis=(2, 4),
    world_adaptive=False,
    rationale=(
        "bidirectional chunk interleave for the MoE combine: the pushed "
        "slab's chunks issue alternately from both ends, so the landing "
        "rank's slab grows inward from its first AND last rows and the "
        "final reduce pipeline's first and last tiles are ready earliest; "
        "pure issue-order permutation — same spans, same credits"
    ),
    identity="chunks_per_shard=1 emits chunk_schedule's single span",
)

TORUS2D = SpanPolicy(
    name="torus2d",
    sides=("ag", "moe_rs"),
    chunk_axis=(1,),
    world_adaptive=True,
    rationale=(
        "2-D torus-aware tiling: chunk count = chunks_per_shard x the "
        "inner dimension of the world's most-square torus factorization "
        "(parallel.topology.torus_factor), so each forwarded span matches "
        "one inner-ring hop of the physical 2-D mesh instead of a "
        "world-blind constant"
    ),
    identity=(
        "a line world (inner dim 1, e.g. world 2) at chunks_per_shard=1 "
        "emits chunk_schedule's single span"
    ),
)


def _overlapping_spans(rows, chunks, quantum, world):
    """The probe's deliberately broken schedule: the contiguous tiling
    with every span after the first pulled back one quantum — rows at each
    boundary are double-covered while the shard tail is never sent."""
    base = chunk_schedule(rows, max(2, chunks), quantum)
    if len(base) < 2:
        return base
    q = max(1, min(quantum, rows))
    return (base[0],) + tuple((max(0, off - q), sz) for off, sz in base[1:])


UNBALANCED_PROBE = SpanPolicy(
    name="unbalanced-probe",
    sides=("ag", "moe_rs"),
    chunk_axis=(2,),
    world_adaptive=False,
    rationale=(
        "NEGATIVE CONTROL: overlapping spans double-cover chunk-boundary "
        "rows and drop the shard tail — an unprovable schedule the admit "
        "stage must reject with a named diagnosis, never register"
    ),
    identity="none (the probe is never admitted)",
    _fn=_overlapping_spans,
)

# (rows, quantum) sample points shared by the generate-stage degeneracy
# prune and the prove-stage validity gate: a many-quanta shard, a
# quantum-misaligned tail, and a tiny shard that forces chunk clamping —
# the shapes where tiling bugs (and vacuous schedules) live
SPAN_SAMPLES = ((1024, 128), (1040, 128), (16, 1), (256, 128))

# The enumerable space (generate.py walks this; the probe is NOT in it)
POLICIES: tuple[SpanPolicy, ...] = (WINDOW, INTERLEAVE, TORUS2D)

POLICY_BY_NAME = {p.name: p for p in POLICIES + (UNBALANCED_PROBE,)}
