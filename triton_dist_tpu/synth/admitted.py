"""Standing registry of PROVED synthesized schedules (ISSUE 14).

This is the durable half of the generate → prove → admit loop
(docs/analysis.md "Generate → prove → tune"): every entry here was
produced by ``scripts/synth_schedules.py`` — enumerated by
``synth/generate.py``, proved credit-balanced / deadlock-free /
chunk-ordered / telemetry-dense / landing-view-covered at worlds
{2, 4, 8} by ``synth/prove.py`` (including the seeded-defect harness),
and admitted by ``synth/admit.py``. The family tune-space modules
(``ops/allgather_group_gemm.py``, ``ops/moe_reduce_rs.py``) append
:func:`admitted_tune_extension` STRICTLY AFTER their legacy candidates —
the standing no-regression ordering invariant (docs/autotuner.md): a
sweep-free walk can never apply a synthesized schedule untimed — and
``analysis/sweep.py`` therefore covers every admitted tuple permanently
(``scripts/protocol_lint.py`` proves them on every run, like the
hand-written schedules).

Entries are plain data (family → GroupGemmConfig kwargs) so this module
stays import-light: the ops modules import it at tune-space build time,
and it must not import them back. Never hand-edit an entry into this
table without a proof — ``synth/admit.py`` refuses unproved candidates,
and ``tests/test_synth.py`` re-proves the whole registry in CI.
"""

from __future__ import annotations

# (family, kwargs) in ADMISSION ORDER. The base tile (128, 1024, 512) is
# each family's best-known leader tile; the synthesized axis is the span
# schedule, not the tiling (format/validity axes compose later exactly as
# they do for the legacy candidates).
SYNTH_ADMITTED: tuple[tuple[str, dict], ...] = (
    # window (AG side): geometric ascending spans — the consumer's
    # first-chunk wait covers only the smallest span's wire time
    ("ag_group_gemm",
     dict(block_m=128, block_n=1024, block_k=512, chunks_per_shard=2,
          span_policy="window")),
    ("ag_group_gemm",
     dict(block_m=128, block_n=1024, block_k=512, chunks_per_shard=4,
          span_policy="window")),
    # torus2d (both sides): chunk count adapts to the world's most-square
    # 2-D torus factorization (topology.torus_factor)
    ("ag_group_gemm",
     dict(block_m=128, block_n=1024, block_k=512, chunks_per_shard=1,
          span_policy="torus2d")),
    # interleave (MoE combine side): bidirectional chunk issue order —
    # the landed slab grows inward from both ends. chunks=2 is NOT here:
    # a both-ends order of two chunks is the contiguous order, and
    # generate.py's identity-degeneracy prune rejects it by schedule
    # comparison (the coverage starts where the permutation is real)
    ("moe_reduce_rs",
     dict(block_m=128, block_n=1024, block_k=512, chunks_per_shard=4,
          span_policy="interleave")),
    ("moe_reduce_rs",
     dict(block_m=128, block_n=1024, block_k=512, chunks_per_shard=1,
          span_policy="torus2d")),
)


def admitted_tune_extension(family: str) -> tuple:
    """The admitted synthesized candidates of one family, in admission
    order, as GroupGemmConfig instances — appended by the family tune-space
    modules strictly after their legacy candidates."""
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    return tuple(
        GroupGemmConfig(**kw) for fam, kw in SYNTH_ADMITTED if fam == family
    )


def is_admitted(family: str, cfg) -> bool:
    """Whether ``cfg`` is a standing registry entry of ``family``."""
    return cfg in admitted_tune_extension(family)
