"""Schedule synthesizer: a generate → prove → tune engine over the
overlap-kernel emitter (ISSUE 14; docs/analysis.md "Generate → prove →
tune").

PR 7 made overlap schedules a POLICY of one emitter; PR 10 made protocol
soundness provable in seconds on any jax line. This package closes the
loop into a search engine the hand-written reference cannot match:

- ``policies.py``  — the declarative schedule-policy space beyond the
  legacy ring/chunked spans (arrival-window tilings for the AG ring,
  bidirectional chunk interleave for the MoE combine, 2-D torus-aware
  chunk derivation over ``parallel/topology.py``), each just a different
  span list the ``ops/gg_pipeline.py`` emitter consumes unchanged;
- ``generate.py``  — deterministic candidate enumeration with NAMED
  validity pruning;
- ``prove.py``     — three static gates per candidate: span-schedule
  validity, the full PR 10 protocol proof at worlds {2, 4, 8}, and the
  seeded-defect harness demonstrating the verifier has teeth on the
  synthesized graph;
- ``admit.py``     — proved schedules enter the family tune spaces
  strictly AFTER every existing candidate (the standing no-regression
  ordering invariant) with ``perf_model`` cost terms; unproved candidates
  are rejected with a named diagnosis, never registered;
- ``admitted.py``  — the committed standing registry the tune-space
  modules and ``analysis/sweep.py`` replay at import, so
  ``scripts/protocol_lint.py`` covers every admitted schedule
  permanently.

``scripts/synth_schedules.py`` drives the loop end to end and prints a
byte-identical report across invocations.

Import note: this ``__init__`` stays lazy — ``admitted.py`` is imported
by the ops tune-space modules at import time and must not drag the rest
of the package (which imports those same ops modules) in behind it.
"""

from __future__ import annotations

_SUBMODULES = ("admitted", "policies", "generate", "prove", "admit")

__all__ = list(_SUBMODULES)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
