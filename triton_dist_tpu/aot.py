"""AOT compilation + serialized executables
(≙ reference AOT toolchain: ``tools/compile_aot.py`` (865 LoC),
``tools/compile/compile.py`` (259 LoC), ``tools/runtime/triton_aot_runtime.cc``
(313 C++) and the ``@aot_compile_spaces`` decorator).

The reference pre-compiles Triton kernels to cubins, generates C wrappers +
an algo-dispatch table, and ships a CUDA-driver-API loader. Under XLA the
compile-side toolchain collapses (SURVEY.md §7 design table): ``jax.jit(...)
.lower().compile()`` is the AOT compile and the serialized artifact
replaces the cubin+C-source bundle. The native load side is shipped:
``csrc/pjrt_runner.cc`` executes :func:`export_pjrt` artifacts through
the PJRT C API of any accelerator plugin — no Python in the serving loop
(verified bit-exact against the jitted Python run on a real chip;
``scripts/pjrt_runner_check.sh``).

Three artifact flavors:

- **Portable export** (`save_exported` / `load_exported`): StableHLO via
  ``jax.export`` — survives jax/runtime upgrades, recompiles on load.
- **Compiled executable** (`aot_compile` + `save_compiled`/`load_compiled`):
  ``jax.jit(fn).lower(*args).compile()`` serialized with
  ``jax.experimental.serialize_executable`` — zero-compile load on the
  same topology+version (what the reference's cubin cache achieves).
- **Native serving artifact** (`export_pjrt`): the raw PJRT executable
  bytes for the C++ runner — the reference's cubin + C launcher as one
  file + one binary.

``aot_compile_spaces`` mirrors the reference decorator: a dict of named
specializations, each pre-lowered for its signature.
"""

from __future__ import annotations

import functools
import hashlib
import os
import pickle
from typing import Any, Callable, Mapping, Sequence

import jax


def aot_compile(fn: Callable, *example_args: Any, **jit_kwargs: Any):
    """jit + lower + compile for the example signature. Returns the compiled
    executable (callable with arrays matching the signature)."""
    return jax.jit(fn, **jit_kwargs).lower(*example_args).compile()


# -- portable StableHLO artifacts -------------------------------------------

def save_exported(fn: Callable, example_args: Sequence[Any], path: str, **jit_kwargs: Any) -> None:
    """Serialize `fn` as portable StableHLO (recompiles on load)."""
    exported = jax.export.export(jax.jit(fn, **jit_kwargs))(*example_args)
    data = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def load_exported(path: str) -> Callable:
    with open(path, "rb") as f:
        exported = jax.export.deserialize(f.read())
    return jax.jit(exported.call)


# -- same-topology compiled executables -------------------------------------

def save_compiled(fn: Callable, example_args: Sequence[Any], path: str, **jit_kwargs: Any) -> None:
    """Serialize a fully-compiled executable (zero-compile reload on the
    same jax version + device topology; ≙ the reference's cubin bundle)."""
    from jax.experimental import serialize_executable

    compiled = aot_compile(fn, *example_args, **jit_kwargs)
    payload = serialize_executable.serialize(compiled)
    blob = pickle.dumps(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(_AOT_MAGIC)
        f.write(hashlib.sha256(blob).digest())
        f.write(blob)


_AOT_MAGIC = b"TDTAOT1\x00"


def load_compiled(path: str) -> Callable:
    """Load a compiled-executable artifact written by :func:`save_compiled`.

    The payload is a pickle (what jax's serialize_executable produces), so
    loading one is code execution by construction — artifacts must come from
    a TRUSTED cache. The sha256 in the header rejects truncated/corrupted
    files and casual tampering before any byte reaches the unpickler; it is
    an integrity check, not a signature — do not load artifacts from
    untrusted sources."""
    from jax.experimental import serialize_executable

    with open(path, "rb") as f:
        magic = f.read(len(_AOT_MAGIC))
        if magic != _AOT_MAGIC:
            raise ValueError(
                f"{path}: not a triton_dist_tpu AOT artifact (bad magic)"
            )
        digest = f.read(32)
        blob = f.read()
    if hashlib.sha256(blob).digest() != digest:
        raise ValueError(f"{path}: AOT artifact failed integrity check")
    payload = pickle.loads(blob)
    return serialize_executable.deserialize_and_load(*payload)


# -- native (no-Python) serving artifacts ------------------------------------

def export_pjrt(
    fn: Callable, example_args: Sequence[Any], path: str, **jit_kwargs: Any
) -> str:
    """Serialize the RAW PJRT executable for the native C++ runner
    (`csrc/pjrt_runner.cc` ≙ reference ``tools/runtime/triton_aot_runtime.cc``
    — their cubin + C launcher becomes one PJRT artifact + one binary).

    Unlike :func:`save_compiled` (a pickle for Python reload), this writes
    exactly the bytes ``PJRT_Executable_DeserializeAndLoad`` consumes — no
    Python on the load side. Same-platform, same-libtpu-version only (the
    PJRT contract for serialized executables). Returns a ready-to-run
    ``pjrt_runner`` command line for the example signature."""
    # dtype check FIRST: failing after the (potentially minutes-long)
    # compile would also leave a stray artifact at `path`
    dt_map = {"float32": "f32", "bfloat16": "bf16", "float16": "f16",
              "int32": "i32", "int8": "i8", "uint8": "u8"}
    specs = []
    for a in jax.tree.leaves(tuple(example_args)):
        dt = dt_map.get(str(a.dtype))
        if dt is None:
            raise ValueError(f"pjrt_runner has no input support for {a.dtype}")
        specs.append(f"--input {dt}:" + "x".join(str(d) for d in a.shape))
    compiled = aot_compile(fn, *example_args, **jit_kwargs)
    blob = compiled.runtime_executable().serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return (
        "csrc/pjrt_runner <plugin.so> " + path + " " + " ".join(specs)
    )


# -- specialization spaces ---------------------------------------------------

def aot_compile_spaces(spaces: Mapping[str, Mapping[str, Any]]) -> Callable:
    """Decorator registering named AOT specializations
    (≙ ``@aot_compile_spaces``, reference tools/compile_aot.py:61-77: a dict
    of {name: {signature, grid, triton_algo_infos}} per kernel).

    Here a space is ``{name: {"example_args": tuple, "jit_kwargs": dict}}``.
    The wrapped fn gains ``.aot(name)`` — returning the (lazily compiled,
    cached) executable for that space — and ``.aot_compile_all()``.
    """

    def deco(fn: Callable) -> Callable:
        compiled: dict[str, Any] = {}

        def get(name: str):
            if name not in compiled:
                spec = spaces[name]
                compiled[name] = aot_compile(
                    fn, *spec["example_args"], **spec.get("jit_kwargs", {})
                )
            return compiled[name]

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return fn(*args, **kwargs)

        wrapped.aot = get
        wrapped.aot_spaces = dict(spaces)
        wrapped.aot_compile_all = lambda: [get(k) for k in spaces]
        return wrapped

    return deco
