"""Distributed GQA flash-decode — sequence/context parallelism for decode
(≙ reference ``kernels/nvidia/flash_decode.py``, 1160 LoC, and the SP layer
``layers/nvidia/sp_flash_decode_layer.py``).

The reference pipeline (SURVEY.md §3.5): per-rank split-KV attention over the
local KV shard (``kernel_gqa_fwd_batch_decode_split_kv`` :130) → intra-rank
combine (:393) → LL-protocol allgather of (acc, lse) → inter-rank combine
with the numerically-stable online-softmax merge (:482-530).

TPU-native re-design:

- **split-KV + intra-rank combine collapse into one kernel.** GPU split-KV
  exists to fill idle SMs with independent KV spans; a TPU core executes the
  Pallas grid sequentially with a pipelined memory stream, so the idiomatic
  form is a single online-softmax pass over KV chunks (grid dim = chunk,
  carry (m, l, acc) in VMEM scratch). Nothing to combine intra-rank.
- **The LL protocol is unnecessary.** The reference packs payload+flag into
  8-byte words so receivers spin on data (low_latency_allgather.py:532-571);
  TPU remote DMAs carry data-coupled completion semaphores, so the plain
  ``full_mesh_push`` allgather (allgather.py) IS the low-latency path.
- **Inter-rank combine** keeps the reference's (acc‖lse) merge algebra —
  it is exactly blockwise/ring-attention math — expressed as XLA elementwise
  ops, which fuse into a single kernel without hand-writing one.

Layouts: q ``[batch, q_heads, head_dim]`` (one decode token per sequence),
KV cache ``[batch, kv_heads, seq, head_dim]`` with valid prefix ``kv_lens``
per sequence (contiguous cache; a paged variant would add a block-table via
scalar prefetch in the index_map, same kernel body).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
import re
from typing import Any

import jax
import jax.numpy as jnp

from triton_dist_tpu.utils import axis_size as _axis_size
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.parallel import topology
from triton_dist_tpu.utils import cdiv, pick_block

NEG_INF = float("-inf")

# fp8 KV cache (ISSUE 19): same payload dtype + absmax ceiling as the
# weight path's fp8_e4m3 format (ops/group_gemm.py) — the kernels are
# payload-dtype generic (the in-kernel bf16 upcast covers int8 AND fp8),
# so fp8 only changes the quantizer and the guard/kernel names.
FP8_KV_DTYPE = jnp.float8_e4m3fn
_FP8_KV_MAX = 448.0


def _scoped_vmem_limit_bytes() -> int:
    """XLA's per-kernel scoped-vmem stack limit: pipeline buffers + scratch
    of ONE pallas_call must fit this, regardless of how much physical VMEM
    the generation has — chip-measured r5: a 16.19 MB allocation is
    rejected with "limit 16.00M" on v5e while vmem_bytes() reports 128 MB.

    Deployments override the limit with ``--xla_tpu_scoped_vmem_limit_kib``
    (in XLA_FLAGS or LIBTPU_INIT_ARGS) or ``TDT_SCOPED_VMEM_LIMIT_KIB``;
    the grid auto-selection must respect that, not a baked-in constant —
    read it per call (flags can be set after import), 16 MiB fallback."""
    kib = os.environ.get("TDT_SCOPED_VMEM_LIMIT_KIB")
    if kib is None:
        for var in ("XLA_FLAGS", "LIBTPU_INIT_ARGS"):
            m = re.search(
                r"--xla_tpu_scoped_vmem_limit_kib=(\d+)",
                os.environ.get(var, ""),
            )
            if m:
                kib = m.group(1)
                break
    return int(kib) * 1024 if kib is not None else 16 * 2**20

# Per-step attention span both paged grids aim for when auto-picking
# pages_per_step: the contiguous sweep's winning block_s on chip (r5) —
# smaller spans pay the per-tile mask/max/exp/sum fixed costs too often.
_TARGET_SPAN = 4096


def _auto_pages_per_step(
    slab: int, page_size: int, max_pages: int, resident: int = 0,
) -> int:
    """Page slots per grid step for a paged decode/verify grid whose
    per-page K or V slab is ``slab`` bytes: enough slots to reach the
    target span (at least one when a single page already exceeds it),
    bounded by the table width and by what the double-buffered K+V
    pipeline (4·slab·P) affords under the scoped-VMEM budget after
    ``resident`` bytes (q/out/lse blocks + scratch accumulators the
    grid holds across the whole pass — the verify grids' rows make
    these significant). Returns 0 when not even one slot fits — the
    caller must prefer the other grid.

    Prefers the largest P ≤ the cap that DIVIDES the table width (down
    to cap/2): a non-divisor pads the last step with clamped duplicate
    page fetches — dead DMAs the length mask discards (chip r5: the
    quant fused grid measured 247 µs at the cap P=12 over a 32-page
    table vs 193 at the divisor P=8)."""
    cap = min(
        max(1, _TARGET_SPAN // page_size), max_pages,
        max(0, _fused_slab_vmem_budget() - resident) // (4 * slab),
    )
    for p in range(cap, max(1, cap // 2) - 1, -1):
        if max_pages % p == 0:
            return p
    return cap


def _fused_slab_vmem_budget() -> int:
    """fuse_heads auto-guard: the fused paged kernel's double-buffered K+V
    page slabs must fit this conservative VMEM slice (see
    :func:`paged_flash_decode`). Bounded by BOTH the generation's VMEM
    (half of it — accumulators, q, outs and the compiler's own scratch
    share the rest) and XLA's scoped-vmem stack limit less a 2 MiB
    allowance for those residents. Derived from the topology table (not
    a constant) so a generation with smaller VMEM auto-selects the
    per-head grid instead of failing to compile."""
    return min(
        topology.vmem_bytes() // 2, _scoped_vmem_limit_bytes() - 2 * 2**20
    )


@dataclasses.dataclass(frozen=True)
class FlashDecodeConfig:
    """Tunables (≙ the reference's split-KV block knobs).

    ``block_s=0`` selects the XLA-native formulation instead of the Pallas
    kernel: the same masked softmax-attention program XLA compiles into a
    fused HBM-bandwidth-bound loop. It is a first-class tuning candidate —
    on chips where XLA's fusion already sits at the memory wall (measured
    v5e: XLA 344 µs vs Pallas 460 µs at b=8 hq=64 s=8192) the idiomatic
    TPU answer is to let XLA have the contiguous bf16 case; the Pallas
    kernel remains the only path for paged and int8-quantized caches.

    ``fuse_heads=True`` moves the kv-head loop INSIDE the kernel: the grid
    drops from (b, h_kv, chunks) to (b, chunks) and each step streams one
    K slab + one V slab covering every kv head. At decode shapes the
    per-step work is tiny (the GQA matmuls pad their handful of q rows up
    to the MXU's 128), so the h_kv-fold reduction in grid steps — fewer
    fixed per-step costs, h_kv-fold larger DMA transfers — is what moves
    a kernel sitting below the HBM wall toward it.

    ``soft_cap`` (> 0) applies the logit soft-cap of the reference's
    split-KV kernel (flash_decode.py:103-107; Gemma-2-family models):
    ``s = soft_cap * tanh(s / soft_cap)`` on the SCALED scores before
    masking, identically on every path (Pallas per-head / fused-heads /
    paged / int8 and the XLA goldens, decode AND verify) so the SP merge
    and the golden fallbacks stay exact twins. 0.0 (default) = disabled —
    bit-identical to the pre-knob kernels."""

    block_s: int = 2048  # KV chunk per online-softmax step; 0 = XLA-native
    fuse_heads: bool = False  # kv-head loop inside the kernel body
    soft_cap: float = 0.0  # logit soft-cap; 0 = off


def _kernel_head_dim(d: int) -> int:
    """The head dim the Pallas kernels run at. Power-of-2 dims pass
    through unchanged (today's shapes); a NON-power-of-2 head dim — the
    reference handles these with a BLOCK_DMODEL + BLOCK_DPE tail split
    (flash_decode.py:155-190) — is zero-padded up to the next power of
    two at the host boundary and the output sliced back. Zero d-columns
    are exact: padded q·k terms add 0 to every score and padded v columns
    produce 0 output columns that the slice discards, so (out, lse) are
    bit-identical to the unpadded math. ``scale`` always uses the TRUE
    head dim. The XLA-native goldens take any d natively — they are the
    CPU reference the padded kernels are pinned against."""
    if d < 1:
        raise ValueError(f"head dim must be >= 1, got {d}")
    p = 1
    while p < d:
        p <<= 1
    return p


def _pad_head_dim(x, d_pad: int):
    """Zero-pad the trailing (head) dim of ``x`` up to ``d_pad``."""
    d = x.shape[-1]
    if d == d_pad:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, d_pad - d)])


def _online_softmax_step(
    q, k_b, v_b, ks_row, vs_row, chunk_start, kv_len, scale,
    m_prev, l_prev, acc_prev, soft_cap=0.0,
):
    """One KV-chunk update of one head's online-softmax carry; the single
    source of the decode math for the per-head AND fused-heads kernels.
    Returns ``(m_new, l_new, acc_new)``.

    Both matmuls run in the cache dtype (bf16 MXU fast path, f32
    accumulate); the f32-upcast variant costs a full VPU pass over
    every K/V tile and measured 25% slower than the HBM-bandwidth
    wall this kernel otherwise sits on. ``ks_row``/``vs_row`` are None on
    the plain path; when present (int8 cache) the K/V tiles upcast to bf16
    (riding under the halved DMA time) and the per-position row scales
    fold into the scores / probabilities. ``soft_cap`` > 0 (a static
    Python float — the branch resolves at trace time) squashes the scaled
    scores through ``soft_cap * tanh(s / soft_cap)`` BEFORE the length
    mask, after any int8 dequant scale — the reference's logit soft-cap,
    in the one place all five kernel paths share."""
    if ks_row is not None:
        k_b = k_b.astype(jnp.bfloat16)
        v_b = v_b.astype(jnp.bfloat16)
    s = jax.lax.dot_general(                            # [g, sc]
        q, k_b, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (scale if ks_row is None else ks_row * scale)
    if soft_cap:
        s = soft_cap * jnp.tanh(s / soft_cap)
    span = chunk_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(span < kv_len, s, NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    # all-masked rows keep m_new == -inf: subtract a clamped copy so the
    # update is exp(-inf) = 0, not exp(-inf - -inf) = NaN. The verify
    # kernel hits this (per-ROW lengths — a zero-length row shares its
    # grid step with live rows); the single-position kernels' chunk gate
    # merely made it unreachable.
    m_safe = jnp.maximum(m_new, -1e30)
    alpha = jnp.exp(m_prev - m_safe)
    p = jnp.exp(s - m_safe)                             # [g, sc]
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    pv = p if vs_row is None else p * vs_row
    acc_new = acc_prev * alpha + jax.lax.dot(
        pv.astype(v_b.dtype), v_b, preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def _finalize_softmax(m, l, acc):
    """(out, lse) from a finished carry. kv_len == 0 → l == 0: emit out=0,
    lse=-inf (weight 0 in the SP merge)."""
    out = jnp.where(l > 0, acc / jnp.maximum(l, 1e-30), 0.0)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


def _flash_decode_body(
    kv_lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, lse_ref,
    m_scr, l_scr, acc_scr, *, n_chunks: int, block_s: int, scale: float,
    soft_cap: float = 0.0,
):
    """Per-head online-softmax decode body: grid (b, h_kv, chunk)."""
    b_i = pl.program_id(0)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kv_len = kv_lens_ref[b_i]

    @pl.when(c * block_s < kv_len)
    def _():
        m_scr[:], l_scr[:], acc_scr[:] = _online_softmax_step(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0],
            None if ks_ref is None else ks_ref[0, 0],
            None if vs_ref is None else vs_ref[0, 0],
            c * block_s, kv_len, scale, m_scr[:], l_scr[:], acc_scr[:],
            soft_cap,
        )

    @pl.when(c == n_chunks - 1)
    def _():
        out_ref[0, 0], lse_ref[0, 0] = _finalize_softmax(
            m_scr[:], l_scr[:], acc_scr[:]
        )


def _flash_decode_kernel(
    kv_lens_ref, q_ref, k_ref, v_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr,
    **kw,
):
    _flash_decode_body(
        kv_lens_ref, q_ref, k_ref, v_ref, None, None, out_ref, lse_ref,
        m_scr, l_scr, acc_scr, **kw,
    )


def _fused_heads_core(
    c, gate_len, row_len, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
    lse_ref, m_scr, l_scr, acc_scr,
    *, n_chunks: int, block_s: int, scale: float, h_kv: int,
    soft_cap: float = 0.0,
):
    """Shared ``fuse_heads`` skeleton (decode AND verify): all kv heads of
    the chunk arrive in ONE K slab + ONE V slab, the head loop unrolls
    inside the step, scratches carry a leading h_kv dim. ``gate_len``
    (scalar) skips whole chunks; ``row_len`` (scalar for decode, a
    per-row column for verify) masks inside the step."""
    @pl.when(c == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(c * block_s < gate_len)
    def _():
        for j in range(h_kv):  # static unroll over the slab's heads
            m_scr[j], l_scr[j], acc_scr[j] = _online_softmax_step(
                q_ref[0, j], k_ref[0, j], v_ref[0, j],
                None if ks_ref is None else ks_ref[0, j],
                None if vs_ref is None else vs_ref[0, j],
                c * block_s, row_len, scale,
                m_scr[j], l_scr[j], acc_scr[j], soft_cap,
            )

    @pl.when(c == n_chunks - 1)
    def _():
        out_ref[0], lse_ref[0] = _finalize_softmax(
            m_scr[:], l_scr[:], acc_scr[:]
        )


def _flash_decode_fused_heads_body(
    kv_lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref, lse_ref,
    m_scr, l_scr, acc_scr, **kw,
):
    kv_len = kv_lens_ref[pl.program_id(0)]
    _fused_heads_core(
        pl.program_id(1), kv_len, kv_len, q_ref, k_ref, v_ref, ks_ref,
        vs_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr, **kw,
    )


def _flash_decode_fused_heads_kernel(
    kv_lens_ref, q_ref, k_ref, v_ref, out_ref, lse_ref, m_scr, l_scr, acc_scr,
    **kw,
):
    _flash_decode_fused_heads_body(
        kv_lens_ref, q_ref, k_ref, v_ref, None, None, out_ref, lse_ref,
        m_scr, l_scr, acc_scr, **kw,
    )


def _flash_decode_fused_heads_quant_kernel(*refs, **kw):
    _flash_decode_fused_heads_body(*refs, **kw)


def flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: jax.Array,
    *,
    config: FlashDecodeConfig | None = None,
    return_lse: bool = False,
    interpret: Any = None,
):
    """Single-device GQA batch decode (≙ ``gqa_fwd_batch_decode_intra_rank``,
    reference flash_decode.py:763).

    q: ``[b, q_heads, d]``; k, v: ``[b, kv_heads, s, d]``; kv_lens: ``[b]``
    int32 valid prefix lengths. Returns f32 ``[b, q_heads, d]`` (and the
    per-head log-sum-exp ``[b, q_heads]`` if `return_lse` — the partial pair
    the SP merge consumes).
    """
    return _decode_call(
        q, k, v, None, kv_lens, config=config, return_lse=return_lse,
        interpret=interpret,
    )


def _xla_decode(q, k, v, kv_lens, *, return_lse, soft_cap=0.0):
    """XLA-native GQA decode (``FlashDecodeConfig(block_s=0)``): a masked
    softmax attention XLA fuses into one HBM-bound loop. f32 score/prob
    math matches the Pallas kernel's accumulation precision; the (out, lse)
    contract is identical, so the SP combine consumes either path. Takes
    any head dim natively (no tile padding) — the CPU golden for the
    kernels' non-power-of-2 head-dim padding; ``soft_cap`` applies the
    same pre-mask logit squash as :func:`_online_softmax_step`."""
    b, hq, d = q.shape
    _, h_kv, s_len, _ = k.shape
    g = hq // h_kv
    q4 = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    s = jnp.einsum(
        "bhgd,bhsd->bhgs", q4, k.astype(jnp.float32)
    ) / math.sqrt(d)
    if soft_cap:
        s = soft_cap * jnp.tanh(s / soft_cap)
    span = jnp.arange(s_len, dtype=jnp.int32)
    s = jnp.where(span[None, None, None, :] < kv_lens[:, None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.maximum(m, -1e30)  # kv_len==0 rows: avoid inf-inf
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhgs,bhsd->bhgd", p, v.astype(jnp.float32))
    out = (out / jnp.maximum(l, 1e-30)).reshape(b, hq, d)
    out = jnp.where(l.reshape(b, hq, 1) > 0, out, 0.0)
    if not return_lse:
        return out
    lse = (m_safe + jnp.log(jnp.maximum(l, 1e-30))).reshape(b, hq)
    lse = jnp.where(l.reshape(b, hq) > 0, lse, NEG_INF)
    return out, lse


def _decode_call(q, k, v, scales, kv_lens, *, config, return_lse, interpret):
    """Shared host-side builder for the plain and int8 decode paths; the
    only deltas are the two optional scale operands and the q dtype.

    The bf16 path degrades to :func:`_xla_decode` when the Pallas kernel
    cannot run in this environment (resilience layer, docs/resilience.md);
    int8 caches have no golden slow path, so their failures stay loud."""
    cfg = config or FlashDecodeConfig()
    if cfg.block_s == 0:
        if scales is not None:
            raise ValueError(
                "block_s=0 (XLA-native) supports only the contiguous bf16 "
                "cache; int8/fp8/paged caches need the Pallas kernel"
            )
        return _xla_decode(
            q, k, v, kv_lens.astype(jnp.int32), return_lse=return_lse,
            soft_cap=cfg.soft_cap,
        )
    if scales is None:
        family = "flash_decode"
    else:
        family = (
            "flash_decode_fp8" if k.dtype == FP8_KV_DTYPE
            else "flash_decode_quant"
        )
    return resilience.guarded_call(
        family,
        lambda: _decode_call_fused(
            q, k, v, scales, kv_lens, cfg=cfg, return_lse=return_lse,
            interpret=interpret,
        ),
        None if scales is not None else (
            lambda: _xla_decode(
                q, k, v, kv_lens.astype(jnp.int32), return_lse=return_lse,
                soft_cap=cfg.soft_cap,
            )
        ),
    )


def _decode_call_fused(q, k, v, scales, kv_lens, *, cfg, return_lse, interpret):
    b, hq, d = q.shape
    _, h_kv, s_len, _ = k.shape
    assert hq % h_kv == 0, (hq, h_kv)
    g = hq // h_kv
    sc = pick_block(s_len, cfg.block_s)
    n_chunks = s_len // sc
    scale = 1.0 / math.sqrt(d)  # the TRUE head dim, before any padding
    d_out, d = d, _kernel_head_dim(d)
    if d != d_out:  # non-pow-2 head dim: zero-pad, slice the output back
        q, k, v = (_pad_head_dim(x, d) for x in (q, k, v))
    # the kernel's matmuls run in the cache dtype (bf16 MXU fast path);
    # mixed-precision callers get their q silently matched to the cache —
    # int8 caches upcast in-kernel, so their q rides bf16
    q4 = q.reshape(b, h_kv, g, d).astype(
        jnp.bfloat16 if scales is not None else k.dtype
    )
    args = [kv_lens.astype(jnp.int32), q4, k, v]
    fp8 = scales is not None and k.dtype == FP8_KV_DTYPE
    if scales is None:
        kv_bytes = 2 * b * h_kv * s_len * d * k.dtype.itemsize
    else:
        args += [scales[0].astype(jnp.float32), scales[1].astype(jnp.float32)]
        kv_bytes = 2 * b * h_kv * s_len * (d + 4)  # 1B payload + f32 scale
    cost = pl.CostEstimate(
        flops=4 * b * hq * s_len * d,
        bytes_accessed=kv_bytes,
        transcendentals=b * hq * s_len,
    )
    if cfg.fuse_heads:
        # grid (b, chunk): each step's K/V slab spans every kv head — h_kv×
        # fewer grid steps and h_kv× larger DMAs (see FlashDecodeConfig)
        grid = (b, n_chunks)
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),  # kv_lens
            pl.BlockSpec((1, h_kv, g, d), lambda i, c: (i, 0, 0, 0)),
            pl.BlockSpec((1, h_kv, sc, d), lambda i, c: (i, 0, c, 0)),
            pl.BlockSpec((1, h_kv, sc, d), lambda i, c: (i, 0, c, 0)),
        ]
        if scales is None:
            name, kernel = "flash_decode_fh", _flash_decode_fused_heads_kernel
        else:
            name = "flash_decode_fh_fp8" if fp8 else "flash_decode_fh_quant"
            kernel = _flash_decode_fused_heads_quant_kernel
            scale_spec = pl.BlockSpec(
                (1, h_kv, 1, sc), lambda i, c: (i, 0, 0, c)
            )
            in_specs += [scale_spec, scale_spec]
        out, lse = dist_pallas_call(
            functools.partial(
                kernel, n_chunks=n_chunks, block_s=sc, scale=scale, h_kv=h_kv,
                soft_cap=cfg.soft_cap,
            ),
            name=name,
            grid=grid,
            out_shape=(
                jax.ShapeDtypeStruct((b, h_kv, g, d), jnp.float32),
                jax.ShapeDtypeStruct((b, h_kv, g, 1), jnp.float32),
            ),
            in_specs=in_specs,
            out_specs=(
                pl.BlockSpec((1, h_kv, g, d), lambda i, c: (i, 0, 0, 0)),
                pl.BlockSpec((1, h_kv, g, 1), lambda i, c: (i, 0, 0, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((h_kv, g, 1), jnp.float32),
                pltpu.VMEM((h_kv, g, 1), jnp.float32),
                pltpu.VMEM((h_kv, g, d), jnp.float32),
            ],
            cost_estimate=cost,
            dimension_semantics=("parallel", "arbitrary"),
            uses_barrier=False,
            interpret=interpret,
        )(*args)
        out = out.reshape(b, hq, d)[..., :d_out]
        lse = lse.reshape(b, hq)
        return (out, lse) if return_lse else out
    grid = (b, h_kv, n_chunks)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # kv_lens
        pl.BlockSpec((1, 1, g, d), lambda i, j, c: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, sc, d), lambda i, j, c: (i, j, c, 0)),
        pl.BlockSpec((1, 1, sc, d), lambda i, j, c: (i, j, c, 0)),
    ]
    if scales is None:
        name, kernel = "flash_decode", _flash_decode_kernel
    else:
        name = "flash_decode_fp8" if fp8 else "flash_decode_quant"
        kernel = _flash_decode_quant_kernel
        scale_spec = pl.BlockSpec((1, 1, 1, sc), lambda i, j, c: (i, j, 0, c))
        in_specs += [scale_spec, scale_spec]
    out, lse = dist_pallas_call(
        functools.partial(
            kernel, n_chunks=n_chunks, block_s=sc, scale=scale,
            soft_cap=cfg.soft_cap,
        ),
        name=name,
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct((b, h_kv, g, d), jnp.float32),
            # 4-D with a unit minor dim: Mosaic wants the trailing block dims
            # to equal the array dims (g < 8 sublanes is fine when full).
            jax.ShapeDtypeStruct((b, h_kv, g, 1), jnp.float32),
        ),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, g, d), lambda i, j, c: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, g, 1), lambda i, j, c: (i, j, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
        cost_estimate=cost,
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(*args)
    out = out.reshape(b, hq, d)[..., :d_out]
    lse = lse.reshape(b, hq)
    return (out, lse) if return_lse else out



def _flash_verify_body(
    max_lens_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, out_ref,
    lse_ref, m_scr, l_scr, acc_scr, *, n_chunks: int, block_s: int,
    scale: float, soft_cap: float = 0.0,
):
    """Multi-position (speculative-verify) decode body: grid
    (b, h_kv, chunk) exactly like :func:`_flash_decode_body`, but the q
    block carries ``S*g`` rows — S draft positions × the GQA group — and
    each ROW masks its own cache prefix via a per-row length column
    (``lens_ref``, VMEM). The per-sequence MAX length (SMEM) gates whole
    chunks. The S-fold wider score matmul is the point: the cache streams
    from HBM ONCE for all S draft positions, where S single-token decodes
    would stream it S times — and the MXU sees S*g rows instead of g.
    ``ks_ref``/``vs_ref`` are None on the plain path; when present
    (quantized cache) the per-position row scales fold exactly as in
    :func:`_flash_decode_body`."""
    b_i = pl.program_id(0)
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(c * block_s < max_lens_ref[b_i])
    def _():
        m_scr[:], l_scr[:], acc_scr[:] = _online_softmax_step(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0],
            None if ks_ref is None else ks_ref[0, 0],
            None if vs_ref is None else vs_ref[0, 0],
            c * block_s, lens_ref[0, 0], scale,
            m_scr[:], l_scr[:], acc_scr[:], soft_cap,
        )

    @pl.when(c == n_chunks - 1)
    def _():
        out_ref[0, 0], lse_ref[0, 0] = _finalize_softmax(
            m_scr[:], l_scr[:], acc_scr[:]
        )


def _flash_verify_kernel(
    max_lens_ref, lens_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
    m_scr, l_scr, acc_scr, **kw,
):
    _flash_verify_body(
        max_lens_ref, lens_ref, q_ref, k_ref, v_ref, None, None, out_ref,
        lse_ref, m_scr, l_scr, acc_scr, **kw,
    )


def _flash_verify_quant_kernel(*refs, **kw):
    _flash_verify_body(*refs, **kw)


def _xla_verify(q, k, v, kv_lens, *, return_lse, soft_cap=0.0):
    """XLA-native multi-position decode (block_s=0 sentinel + golden):
    per-(sequence, position) prefix masks over one einsum. Any head dim,
    same ``soft_cap`` contract as :func:`_xla_decode`."""
    b, S, hq, d = q.shape
    _, h_kv, s_len, _ = k.shape
    g = hq // h_kv
    q5 = q.reshape(b, S, h_kv, g, d).astype(jnp.float32)
    s = jnp.einsum(
        "bshgd,bhtd->bshgt", q5, k.astype(jnp.float32)
    ) / math.sqrt(d)
    if soft_cap:
        s = soft_cap * jnp.tanh(s / soft_cap)
    span = jnp.arange(s_len, dtype=jnp.int32)
    mask = span[None, None, :] < kv_lens[:, :, None]       # [b, S, t]
    s = jnp.where(mask[:, :, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bshgt,bhtd->bshgd", p, v.astype(jnp.float32))
    out = (out / jnp.maximum(l, 1e-30)).reshape(b, S, hq, d)
    out = jnp.where(l.reshape(b, S, hq, 1) > 0, out, 0.0)
    if not return_lse:
        return out
    lse = (m_safe + jnp.log(jnp.maximum(l, 1e-30))).reshape(b, S, hq)
    lse = jnp.where(l.reshape(b, S, hq) > 0, lse, NEG_INF)
    return out, lse


def flash_verify(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: jax.Array,
    *,
    config: FlashDecodeConfig | None = None,
    return_lse: bool = False,
    interpret: Any = None,
):
    """Multi-position GQA decode — the speculative-decoding VERIFY
    attention (beyond the reference, whose serving surface stops at
    single-token decode): score S draft positions of every sequence in
    ONE pass over the cache.

    q: ``[b, S, q_heads, d]`` (position i = draft token i); k, v:
    ``[b, kv_heads, s, d]`` with the S draft tokens' own k/v ALREADY
    WRITTEN; kv_lens: ``[b, S]`` int32 — row (b, i) attends cache
    positions ``< kv_lens[b, i]`` (the verify caller passes
    ``pos0+i+1``: its prefix plus draft tokens ``<= i`` — causal within
    the chunk via the cache). Returns f32 ``[b, S, q_heads, d]`` (+
    ``lse [b, S, q_heads]``)."""
    cfg = config or FlashDecodeConfig()
    assert q.shape[2] % k.shape[1] == 0, (q.shape, k.shape)
    kv_lens = kv_lens.astype(jnp.int32)
    if cfg.block_s == 0:
        return _xla_verify(
            q, k, v, kv_lens, return_lse=return_lse, soft_cap=cfg.soft_cap
        )
    return resilience.guarded_call(
        "flash_verify",
        lambda: _flash_verify_fused(
            q, k, v, kv_lens, cfg=cfg, return_lse=return_lse,
            interpret=interpret,
        ),
        lambda: _xla_verify(
            q, k, v, kv_lens, return_lse=return_lse, soft_cap=cfg.soft_cap
        ),
    )


def _flash_verify_fused(q, k, v, kv_lens, *, cfg, return_lse, interpret,
                        scales=None):
    b, S, hq, d = q.shape
    _, h_kv, s_len, _ = k.shape
    g = hq // h_kv
    sc = pick_block(s_len, cfg.block_s)
    n_chunks = s_len // sc
    rows = S * g
    scale = 1.0 / math.sqrt(d)  # the TRUE head dim, before any padding
    d_out, d = d, _kernel_head_dim(d)
    if d != d_out:
        q, k, v = (_pad_head_dim(x, d) for x in (q, k, v))
    # quantized caches upcast in-kernel, so their q rides bf16 (the same
    # contract as _decode_call_fused)
    q5 = (
        q.reshape(b, S, h_kv, g, d)
        .swapaxes(1, 2)
        .reshape(b, h_kv, rows, d)
        .astype(jnp.bfloat16 if scales is not None else k.dtype)
    )
    # per-row length column: row s*g + j masks with kv_lens[b, s]
    lens_rows = jnp.repeat(kv_lens, g, axis=1).reshape(b, 1, rows, 1)
    max_lens = jnp.max(kv_lens, axis=1)
    cost = pl.CostEstimate(
        flops=4 * b * S * hq * s_len * d,
        bytes_accessed=2 * b * h_kv * s_len * (
            (d + 4) if scales is not None else d * k.dtype.itemsize
        ),
        transcendentals=b * S * hq * s_len,
    )
    args = [max_lens, lens_rows, q5, k, v]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # max_lens (chunk gate)
        pl.BlockSpec((1, 1, rows, 1), lambda i, j, c: (i, 0, 0, 0)),
        pl.BlockSpec((1, 1, rows, d), lambda i, j, c: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, sc, d), lambda i, j, c: (i, j, c, 0)),
        pl.BlockSpec((1, 1, sc, d), lambda i, j, c: (i, j, c, 0)),
    ]
    if scales is None:
        name, kernel = "flash_verify", _flash_verify_kernel
    else:
        name = (
            "flash_verify_fp8" if k.dtype == FP8_KV_DTYPE
            else "flash_verify_quant"
        )
        kernel = _flash_verify_quant_kernel
        args += [scales[0].astype(jnp.float32), scales[1].astype(jnp.float32)]
        scale_spec = pl.BlockSpec((1, 1, 1, sc), lambda i, j, c: (i, j, 0, c))
        in_specs += [scale_spec, scale_spec]
    out, lse = dist_pallas_call(
        functools.partial(
            kernel, n_chunks=n_chunks, block_s=sc,
            scale=scale, soft_cap=cfg.soft_cap,
        ),
        name=name,
        grid=(b, h_kv, n_chunks),
        out_shape=(
            jax.ShapeDtypeStruct((b, h_kv, rows, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h_kv, rows, 1), jnp.float32),
        ),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, rows, d), lambda i, j, c: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, rows, 1), lambda i, j, c: (i, j, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
        cost_estimate=cost,
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(*args)
    out = (
        out.reshape(b, h_kv, S, g, d).swapaxes(1, 2)
        .reshape(b, S, hq, d)[..., :d_out]
    )
    lse = lse.reshape(b, h_kv, S, g).swapaxes(1, 2).reshape(b, S, hq)
    return (out, lse) if return_lse else out


def flash_verify_distributed(
    q: jax.Array,
    k_shard: jax.Array,
    v_shard: jax.Array,
    lens_shard: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP form of :func:`flash_verify` (call inside ``jax.shard_map``):
    per-shard multi-position partials, then the same (out ‖ lse)
    allgather-merge the single-token SP decode rides — the S dim folds
    into the payload's row dim."""
    out, lse = flash_verify(
        q, k_shard, v_shard, lens_shard,
        config=config, return_lse=True, interpret=interpret,
    )
    b, S, hq, d = out.shape
    merged = _sp_allgather_combine(
        out.reshape(b * S, hq, d), lse.reshape(b * S, hq), axis, ag_method,
        interpret,
    )
    return merged.reshape(b, S, hq, d)


def _paged_to_contiguous(pages, block_table):
    """Gather a paged pool back into per-sequence contiguous caches:
    ``[n_pages, h_kv, page, d]`` + ``[b, max_pages]`` →
    ``[b, h_kv, max_pages*page, d]`` — a pure XLA gather, so the paged
    entries get a golden slow path with the identical masking contract
    (positions past ``kv_lens`` are masked either way)."""
    b, max_pages = block_table.shape
    x = pages[block_table.astype(jnp.int32)]  # [b, max_pages, h_kv, pg, d]
    _, _, h_kv, page, d = x.shape
    return x.swapaxes(1, 2).reshape(b, h_kv, max_pages * page, d)


def _xla_paged_decode(q, k_pages, v_pages, kv_lens, block_table, *,
                      return_lse=False, soft_cap=0.0):
    """Golden slow path for the paged decode: block-table gather to a
    contiguous cache + the XLA-native masked attention."""
    return _xla_decode(
        q, _paged_to_contiguous(k_pages, block_table),
        _paged_to_contiguous(v_pages, block_table),
        kv_lens, return_lse=return_lse, soft_cap=soft_cap,
    )


def _xla_paged_verify(q, k_pages, v_pages, kv_lens, block_table, *,
                      return_lse=False, soft_cap=0.0):
    """Golden slow path for the paged multi-position verify."""
    return _xla_verify(
        q, _paged_to_contiguous(k_pages, block_table),
        _paged_to_contiguous(v_pages, block_table),
        kv_lens, return_lse=return_lse, soft_cap=soft_cap,
    )


def _paged_flash_verify_kernel(
    max_lens_ref, bt_ref, lens_ref, q_ref, *rest,
    n_steps: int, pages_per_step: int, page_size: int, scale: float,
    h_kv: int, chunk_dim: int, soft_cap: float = 0.0,
):
    """Paged verify over ``pages_per_step`` pages concatenated into one
    [rows, P·page] span per step (same r5 chip finding as
    :func:`_paged_flash_decode_kernel`, whose shared-body shape this
    mirrors: fused grid = pool ``h_kv`` + ``chunk_dim=1``, per-head
    grid = the ``h_kv=1, chunk_dim=2`` instance). The per-sequence max
    length gates whole steps; the per-row length column masks inside
    the span. Clamped duplicate tail slots are length-masked: their
    span positions are >= max_pages*page >= every row length."""
    del bt_ref
    P = pages_per_step
    kv_refs = rest[: 2 * P]
    out_ref, lse_ref, m_scr, l_scr, acc_scr = rest[2 * P :]
    c = pl.program_id(chunk_dim)

    @pl.when(c == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(c * P * page_size < max_lens_ref[pl.program_id(0)])
    def _():
        for j in range(h_kv):  # static unroll over the slab's heads
            k_cat = jnp.concatenate(
                [kv_refs[2 * p][0, j] for p in range(P)], axis=0
            ) if P > 1 else kv_refs[0][0, j]
            v_cat = jnp.concatenate(
                [kv_refs[2 * p + 1][0, j] for p in range(P)], axis=0
            ) if P > 1 else kv_refs[1][0, j]
            m_scr[j], l_scr[j], acc_scr[j] = _online_softmax_step(
                q_ref[0, j], k_cat, v_cat, None, None,
                c * P * page_size, lens_ref[0, 0], scale,
                m_scr[j], l_scr[j], acc_scr[j], soft_cap,
            )

    @pl.when(c == n_steps - 1)
    def _():
        out_ref[0], lse_ref[0] = _finalize_softmax(
            m_scr[:], l_scr[:], acc_scr[:]
        )


def paged_flash_verify(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    kv_lens: jax.Array,
    block_table: jax.Array,
    *,
    fuse_heads: bool | None = None,
    pages_per_step: int | None = None,
    soft_cap: float = 0.0,
    return_lse: bool = False,
    interpret: Any = None,
):
    """Multi-position decode over a PAGED cache — :func:`flash_verify`
    with the block-table indirection of :func:`paged_flash_decode`: q
    ``[b, S, q_heads, d]``, kv_lens ``[b, S]`` per-row prefix lengths,
    pages/table as in the paged decode (the S chunk positions' k/v
    already written into their pages). ``fuse_heads`` /
    ``pages_per_step`` (None = the same span-driven auto as
    :func:`paged_flash_decode`, with the verify rows' larger
    q/out/accumulator residents counted against the VMEM budget);
    ``soft_cap`` as in :class:`FlashDecodeConfig` (the paged entries take
    it directly — their knobs are kwargs, not a config).
    Degrades to the gather-reconstructed :func:`_xla_paged_verify` golden
    when the Pallas kernel cannot run in this environment (resilience
    layer, docs/resilience.md)."""
    assert q.shape[2] % k_pages.shape[1] == 0, (q.shape, k_pages.shape)
    kv_lens = kv_lens.astype(jnp.int32)
    return resilience.guarded_call(
        "paged_flash_verify",
        lambda: _paged_flash_verify_fused(
            q, k_pages, v_pages, kv_lens, block_table,
            fuse_heads=fuse_heads, pages_per_step=pages_per_step,
            soft_cap=soft_cap, return_lse=return_lse, interpret=interpret,
        ),
        lambda: _xla_paged_verify(
            q, k_pages, v_pages, kv_lens, block_table,
            return_lse=return_lse, soft_cap=soft_cap,
        ),
    )


def _paged_flash_verify_fused(
    q, k_pages, v_pages, kv_lens, block_table, *,
    fuse_heads, pages_per_step, soft_cap, return_lse, interpret,
):
    b, S, hq, d = q.shape
    n_pages, h_kv, page_size, _ = k_pages.shape
    g = hq // h_kv
    rows = S * g
    max_pages = block_table.shape[1]
    scale = 1.0 / math.sqrt(d)  # the TRUE head dim, before any padding
    d_out, d = d, _kernel_head_dim(d)
    if d != d_out:  # pad the q and the page pools; slice the output back
        q, k_pages, v_pages = (
            _pad_head_dim(x, d) for x in (q, k_pages, v_pages)
        )
    # per-head-grid resident bytes (q block in the cache dtype, f32
    # out/lse blocks, f32 m/l/acc scratches); the fused grid holds h_kv×
    slab_h = page_size * d * k_pages.dtype.itemsize
    res_h = rows * (
        d * k_pages.dtype.itemsize + (d + 1) * 4 + (d + 2) * 4
    )
    p_f = _auto_pages_per_step(
        h_kv * slab_h, page_size, max_pages, resident=h_kv * res_h
    )
    p_h = _auto_pages_per_step(slab_h, page_size, max_pages, resident=res_h)
    if fuse_heads is None:
        fuse_heads = p_f >= 1 and p_f >= p_h
    if pages_per_step is None and (p_f if fuse_heads else p_h) == 0:
        # the SELECTED grid (auto never picks a dead grid while the other
        # lives, but an explicit fuse_heads can force one) affords not even
        # ONE page slot: without this check the forced pages_per_step=1
        # dies deep inside Mosaic compilation with an allocation error
        # naming none of these numbers
        raise ValueError(
            f"paged_flash_verify: the selected "
            f"{'fused' if fuse_heads else 'per-head'} grid affords no "
            f"single page slot under the scoped-VMEM budget — "
            f"rows=S*g={rows} (S={S}, g={g}), page_size={page_size}, "
            f"head_dim={d}, h_kv={h_kv}: residents "
            f"{(h_kv * res_h) if fuse_heads else res_h} B + one "
            f"double-buffered K+V page slot "
            f"{4 * ((h_kv * slab_h) if fuse_heads else slab_h)} B exceed "
            f"the {_fused_slab_vmem_budget()} B budget "
            f"(--xla_tpu_scoped_vmem_limit_kib / TDT_SCOPED_VMEM_LIMIT_KIB "
            f"raises it). Reduce S or page_size, toggle fuse_heads, or use "
            f"flash_verify on a contiguous cache."
        )
    if pages_per_step is None:
        pages_per_step = max(1, p_f if fuse_heads else p_h)
    P = pages_per_step
    n_steps = cdiv(max_pages, P)
    q5 = (
        q.reshape(b, S, h_kv, g, d)
        .swapaxes(1, 2)
        .reshape(b, h_kv, rows, d)
        .astype(k_pages.dtype)
    )
    lens_rows = jnp.repeat(kv_lens, g, axis=1).reshape(b, 1, rows, 1)
    max_lens = jnp.max(kv_lens, axis=1)
    cost = pl.CostEstimate(
        flops=4 * b * S * hq * max_pages * page_size * d,
        bytes_accessed=(2 * b * h_kv * max_pages * page_size * d)
        * k_pages.dtype.itemsize,
        transcendentals=b * S * hq * max_pages * page_size,
    )
    if fuse_heads:
        def kv_index_map_fh_p(p):
            def index_map(i, c, max_lens_ref, bt_ref):
                return (
                    bt_ref[i, jnp.minimum(c * P + p, max_pages - 1)], 0, 0, 0,
                )
            return index_map

        page_spec = lambda p: pl.BlockSpec(
            (1, h_kv, page_size, d), kv_index_map_fh_p(p)
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_steps),
            in_specs=[
                pl.BlockSpec((1, 1, rows, 1), lambda i, c, *_: (i, 0, 0, 0)),
                pl.BlockSpec((1, h_kv, rows, d), lambda i, c, *_: (i, 0, 0, 0)),
                *(page_spec(p) for p in range(P) for _ in (0, 1)),
            ],
            out_specs=(
                pl.BlockSpec((1, h_kv, rows, d), lambda i, c, *_: (i, 0, 0, 0)),
                pl.BlockSpec((1, h_kv, rows, 1), lambda i, c, *_: (i, 0, 0, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((h_kv, rows, 1), jnp.float32),
                pltpu.VMEM((h_kv, rows, 1), jnp.float32),
                pltpu.VMEM((h_kv, rows, d), jnp.float32),
            ],
        )
        out, lse = dist_pallas_call(
            functools.partial(
                _paged_flash_verify_kernel,
                n_steps=n_steps, pages_per_step=P, page_size=page_size,
                scale=scale, h_kv=h_kv, chunk_dim=1, soft_cap=soft_cap,
            ),
            name="paged_flash_verify_fh",
            grid_spec=grid_spec,
            out_shape=(
                jax.ShapeDtypeStruct((b, h_kv, rows, d), jnp.float32),
                jax.ShapeDtypeStruct((b, h_kv, rows, 1), jnp.float32),
            ),
            cost_estimate=cost,
            dimension_semantics=("parallel", "arbitrary"),
            uses_barrier=False,
            interpret=interpret,
        )(
            max_lens, block_table.astype(jnp.int32), lens_rows, q5,
            *(kv for _ in range(P) for kv in (k_pages, v_pages)),
        )
        out = (
            out.reshape(b, h_kv, S, g, d).swapaxes(1, 2)
            .reshape(b, S, hq, d)[..., :d_out]
        )
        lse = lse.reshape(b, h_kv, S, g).swapaxes(1, 2).reshape(b, S, hq)
        return (out, lse) if return_lse else out

    def kv_index_map_p(p):
        def index_map(i, j, c, max_lens_ref, bt_ref):
            return (bt_ref[i, jnp.minimum(c * P + p, max_pages - 1)], j, 0, 0)
        return index_map

    page_spec = lambda p: pl.BlockSpec(
        (1, 1, page_size, d), kv_index_map_p(p)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h_kv, n_steps),
        in_specs=[
            pl.BlockSpec((1, 1, rows, 1), lambda i, j, c, *_: (i, 0, 0, 0)),
            pl.BlockSpec((1, 1, rows, d), lambda i, j, c, *_: (i, j, 0, 0)),
            *(page_spec(p) for p in range(P) for _ in (0, 1)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, rows, d), lambda i, j, c, *_: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, rows, 1), lambda i, j, c, *_: (i, j, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((1, rows, 1), jnp.float32),
            pltpu.VMEM((1, rows, 1), jnp.float32),
            pltpu.VMEM((1, rows, d), jnp.float32),
        ],
    )
    # the shared body's h_kv=1 instance (leading head dim on scratches)
    out, lse = dist_pallas_call(
        functools.partial(
            _paged_flash_verify_kernel,
            n_steps=n_steps, pages_per_step=P, page_size=page_size,
            scale=scale, h_kv=1, chunk_dim=2, soft_cap=soft_cap,
        ),
        name="paged_flash_verify",
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b, h_kv, rows, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h_kv, rows, 1), jnp.float32),
        ),
        cost_estimate=cost,
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(
        max_lens, block_table.astype(jnp.int32), lens_rows, q5,
        *(kv for _ in range(P) for kv in (k_pages, v_pages)),
    )
    out = (
        out.reshape(b, h_kv, S, g, d).swapaxes(1, 2)
        .reshape(b, S, hq, d)[..., :d_out]
    )
    lse = lse.reshape(b, h_kv, S, g).swapaxes(1, 2).reshape(b, S, hq)
    return (out, lse) if return_lse else out


def paged_flash_verify_distributed(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    lens_shard: jax.Array,
    block_table: jax.Array,
    *,
    axis: str = "tp",
    fuse_heads: bool | None = None,
    pages_per_step: int | None = None,
    soft_cap: float = 0.0,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP form of :func:`paged_flash_verify` (call inside shard_map):
    per-shard multi-position partials over each PE's page pool, merged by
    the shared (out ‖ lse) allgather tail."""
    out, lse = paged_flash_verify(
        q, k_pages, v_pages, lens_shard, block_table,
        fuse_heads=fuse_heads, pages_per_step=pages_per_step,
        soft_cap=soft_cap, return_lse=True, interpret=interpret,
    )
    b, S, hq, d = out.shape
    merged = _sp_allgather_combine(
        out.reshape(b * S, hq, d), lse.reshape(b * S, hq), axis, ag_method,
        interpret,
    )
    return merged.reshape(b, S, hq, d)


def _ranged_local_lens(pos0, S, axis, s_shard):
    """Per-(sequence, range-row) valid prefix in THIS PE's sequence shard
    for a suffix-only ranged prefill: row i of the range attends global
    positions ``<= pos0 + i`` — exact causal masking across the range
    boundary — and this PE covers ``[me*s_shard, (me+1)*s_shard)``."""
    me = jax.lax.axis_index(axis)
    pos_mat = (
        jnp.asarray(pos0, jnp.int32).reshape(-1, 1)
        + jnp.arange(S, dtype=jnp.int32)[None, :]
    )                                                      # [b, S]
    return jnp.clip(pos_mat + 1 - me * s_shard, 0, s_shard).astype(jnp.int32)


def flash_ranged_prefill_distributed(
    q: jax.Array,
    k_shard: jax.Array,
    v_shard: jax.Array,
    pos0: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """Suffix-only RANGED prefill over a contiguous SP cache (call inside
    ``jax.shard_map``) — the flash family's attend-to-prior-cache prefill
    (ROADMAP #2): q carries a prompt RANGE's rows ``[b, S, q_heads, d]``
    at global positions ``pos0 .. pos0+S-1`` whose own k/v are ALREADY
    WRITTEN into the shard; row i attends every landed position
    ``<= pos0+i``. The per-row prefix lengths are derived from ``pos0``
    here and the multi-position verify attention runs unchanged, so
    composing consecutive ranges is bit-identical to one whole-prompt
    pass: every row's mask names the same global prefix either way."""
    S = q.shape[1]
    lens = _ranged_local_lens(pos0, S, axis, k_shard.shape[2])
    return flash_verify_distributed(
        q, k_shard, v_shard, lens,
        axis=axis, config=config, ag_method=ag_method, interpret=interpret,
    )


def paged_flash_ranged_prefill_distributed(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    pos0: jax.Array,
    block_table: jax.Array,
    *,
    axis: str = "tp",
    fuse_heads: bool | None = None,
    pages_per_step: int | None = None,
    soft_cap: float = 0.0,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """Paged twin of :func:`flash_ranged_prefill_distributed`: the same
    suffix-only ranged prefill over each PE's page POOL, with the range's
    prior pages named by ``block_table`` (the reference's block-table
    indirection) — per-row lengths from ``pos0``, then the paged
    multi-position verify."""
    S = q.shape[1]
    s_shard = block_table.shape[1] * k_pages.shape[2]
    lens = _ranged_local_lens(pos0, S, axis, s_shard)
    return paged_flash_verify_distributed(
        q, k_pages, v_pages, lens, block_table,
        axis=axis, fuse_heads=fuse_heads, pages_per_step=pages_per_step,
        soft_cap=soft_cap, ag_method=ag_method, interpret=interpret,
    )


def quantize_kv(k: jax.Array, v: jax.Array):
    """Per-(batch, head, position) absmax int8 quantization of a KV cache
    (k, v ``[b, h_kv, s, d]``) → ``(k_q, v_q, k_scale, v_scale)`` with
    int8 payloads and ``[b, h_kv, 1, s]`` f32 row scales (scale layout is
    lane-major so the kernel broadcasts it over the head group without a
    relayout). Halves the decode kernel's HBM traffic — the resource it is
    bound by — at ~0.4% RMS error per row."""

    def q1(x):
        xf = x.astype(jnp.float32)
        s = jnp.max(jnp.abs(xf), axis=-1) / 127.0            # [b, h, s]
        s = jnp.maximum(s, 1e-8)
        xq = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
        return xq, s[:, :, None, :]                          # [b, h, 1, s]

    k_q, k_s = q1(k)
    v_q, v_s = q1(v)
    return k_q, v_q, k_s, v_s


def _flash_decode_quant_kernel(*refs, **kw):
    _flash_decode_body(*refs, **kw)


def flash_decode_quant(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    kv_lens: jax.Array,
    *,
    config: FlashDecodeConfig | None = None,
    return_lse: bool = False,
    interpret: Any = None,
):
    """GQA batch decode over an int8-quantized KV cache (from
    :func:`quantize_kv`) — same contract as :func:`flash_decode`, half the
    HBM traffic, with one precision delta: `q` is cast to bfloat16 for the
    MXU fast path (the int8 cache upcasts to bf16 in-kernel), so f32
    queries lose precision here that the plain path would keep. Composes
    with the SP merge via ``return_lse``."""
    return _decode_call(
        q, k_q, v_q, (k_scale, v_scale), kv_lens, config=config,
        return_lse=return_lse, interpret=interpret,
    )


def flash_decode_quant_distributed(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    kv_lens_shard: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP/CP decode over an int8 KV cache: per-shard quantized partials,
    standard (out, lse) merge."""
    out, lse = flash_decode_quant(
        q, k_q, v_q, k_scale, v_scale, kv_lens_shard,
        config=config, return_lse=True, interpret=interpret,
    )
    return _sp_allgather_combine(out, lse, axis, ag_method, interpret)


def quantize_kv_fp8(k: jax.Array, v: jax.Array):
    """fp8_e4m3 twin of :func:`quantize_kv` (ISSUE 19): per-(batch, head,
    position) absmax rows at the e4m3 ceiling (448) instead of int8's 127,
    same ``[b, h_kv, 1, s]`` f32 scale layout. The payload is 1 byte like
    int8 — the traffic win over int8 is on the WIRE and weight paths; here
    fp8 trades int8's uniform 8-bit grid for e4m3's tapered one (denser
    near zero, where attention logits live)."""

    def q1(x):
        xf = x.astype(jnp.float32)
        s = jnp.max(jnp.abs(xf), axis=-1) / _FP8_KV_MAX       # [b, h, s]
        s = jnp.maximum(s, 1e-8)
        xq = jnp.clip(xf / s[..., None], -_FP8_KV_MAX, _FP8_KV_MAX).astype(
            FP8_KV_DTYPE
        )
        return xq, s[:, :, None, :]                           # [b, h, 1, s]

    k_q, k_s = q1(k)
    v_q, v_s = q1(v)
    return k_q, v_q, k_s, v_s


def flash_decode_fp8(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    kv_lens: jax.Array,
    *,
    config: FlashDecodeConfig | None = None,
    return_lse: bool = False,
    interpret: Any = None,
):
    """GQA batch decode over an fp8-quantized KV cache (from
    :func:`quantize_kv_fp8`) — the fp8 twin of :func:`flash_decode_quant`:
    the same upcast-in-kernel shape (fp8 tiles rise to bf16 under the
    halved DMA time, row scales fold into scores/probabilities), the same
    q→bf16 contract, ``soft_cap`` and non-pow-2 head dims ride through."""
    return _decode_call(
        q, k_q, v_q, (k_scale, v_scale), kv_lens, config=config,
        return_lse=return_lse, interpret=interpret,
    )


def flash_decode_fp8_distributed(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    kv_lens_shard: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP/CP decode over an fp8 KV cache: per-shard fp8 partials,
    standard (out, lse) merge — the fp8 twin of
    :func:`flash_decode_quant_distributed`."""
    out, lse = flash_decode_fp8(
        q, k_q, v_q, k_scale, v_scale, kv_lens_shard,
        config=config, return_lse=True, interpret=interpret,
    )
    return _sp_allgather_combine(out, lse, axis, ag_method, interpret)


def flash_verify_fp8(
    q: jax.Array,
    k_q: jax.Array,
    v_q: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    kv_lens: jax.Array,
    *,
    config: FlashDecodeConfig | None = None,
    return_lse: bool = False,
    interpret: Any = None,
):
    """Multi-position verify over an fp8 KV cache — :func:`flash_verify`
    with the decode family's quantized-cache contract (per-position row
    scales fold in-kernel, q rides bf16). Quantized caches have no golden
    slow path, so failures stay loud."""
    cfg = config or FlashDecodeConfig()
    assert q.shape[2] % k_q.shape[1] == 0, (q.shape, k_q.shape)
    kv_lens = kv_lens.astype(jnp.int32)
    if cfg.block_s == 0:
        raise ValueError(
            "block_s=0 (XLA-native) supports only the contiguous bf16 "
            "cache; fp8 caches need the Pallas kernel"
        )
    return resilience.guarded_call(
        "flash_verify_fp8",
        lambda: _flash_verify_fused(
            q, k_q, v_q, kv_lens, cfg=cfg, return_lse=return_lse,
            interpret=interpret, scales=(k_scale, v_scale),
        ),
        None,
    )


def flash_ranged_prefill_fp8_distributed(
    q: jax.Array,
    k_q_shard: jax.Array,
    v_q_shard: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    pos0: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """fp8 twin of :func:`flash_ranged_prefill_distributed`: suffix-only
    ranged prefill over a contiguous fp8 SP cache shard (call inside
    ``jax.shard_map``) — per-row prefix lengths from ``pos0``, the fp8
    multi-position verify, then the standard (out ‖ lse) merge."""
    S = q.shape[1]
    lens = _ranged_local_lens(pos0, S, axis, k_q_shard.shape[2])
    out, lse = flash_verify_fp8(
        q, k_q_shard, v_q_shard, k_scale, v_scale, lens,
        config=config, return_lse=True, interpret=interpret,
    )
    b, S, hq, d = out.shape
    merged = _sp_allgather_combine(
        out.reshape(b * S, hq, d), lse.reshape(b * S, hq), axis, ag_method,
        interpret,
    )
    return merged.reshape(b, S, hq, d)


def _paged_flash_decode_kernel(
    kv_lens_ref, block_table_ref, q_ref, *rest,
    n_steps: int, pages_per_step: int, page_size: int,
    scale: float, h_kv: int, chunk_dim: int, quant: bool = False,
    soft_cap: float = 0.0,
):
    """Paged decode over ``pages_per_step`` pages concatenated into one
    [g, P·page] span per step (r5 chip finding: the span, not the page
    indirection, is the cost — the contiguous winner's shape is
    block_s=4096 = 16 pages). ONE body for BOTH grids: the fused-heads
    grid passes the pool's ``h_kv`` and ``chunk_dim=1``; the per-head
    grid is the ``h_kv=1, chunk_dim=2`` instance (its blocks/scratches
    carry a leading head dim of 1). Physical pages arrive via the
    prefetched block table (≙ the reference's block_table indirection,
    flash_decode.py:136,203). ``quant``: int8 page pools — 2P extra
    scale-page slots follow the data slots, concatenated into per-
    position scale rows exactly as :func:`flash_decode_quant` folds
    them (payload DMAs at half the bytes)."""
    del block_table_ref
    P = pages_per_step
    kv_refs = rest[: 2 * P]
    s_refs = rest[2 * P : 4 * P] if quant else ()
    out_ref, lse_ref, m_scr, l_scr, acc_scr = rest[(4 if quant else 2) * P :]
    c = pl.program_id(chunk_dim)
    kv_len = kv_lens_ref[pl.program_id(0)]

    @pl.when(c == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # clamped duplicate tail slots (logical chunk >= max_pages) sit at
    # span positions >= max_pages*page_size >= kv_len: length-masked
    @pl.when(c * P * page_size < kv_len)
    def _():
        for j in range(h_kv):  # static unroll over the slab's heads
            k_cat = jnp.concatenate(
                [kv_refs[2 * p][0, j] for p in range(P)], axis=0
            ) if P > 1 else kv_refs[0][0, j]
            v_cat = jnp.concatenate(
                [kv_refs[2 * p + 1][0, j] for p in range(P)], axis=0
            ) if P > 1 else kv_refs[1][0, j]
            if quant:  # int8 page pools: per-position scale rows ride
                ks_cat = jnp.concatenate(
                    [s_refs[2 * p][0, j] for p in range(P)], axis=1
                ) if P > 1 else s_refs[0][0, j]
                vs_cat = jnp.concatenate(
                    [s_refs[2 * p + 1][0, j] for p in range(P)], axis=1
                ) if P > 1 else s_refs[1][0, j]
            else:
                ks_cat = vs_cat = None
            m_scr[j], l_scr[j], acc_scr[j] = _online_softmax_step(
                q_ref[0, j], k_cat, v_cat, ks_cat, vs_cat,
                c * P * page_size, kv_len, scale,
                m_scr[j], l_scr[j], acc_scr[j], soft_cap,
            )

    @pl.when(c == n_steps - 1)
    def _():
        out_ref[0], lse_ref[0] = _finalize_softmax(
            m_scr[:], l_scr[:], acc_scr[:]
        )


def paged_flash_decode(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    kv_lens: jax.Array,
    block_table: jax.Array,
    *,
    k_scales: jax.Array | None = None,
    v_scales: jax.Array | None = None,
    fuse_heads: bool | None = None,
    pages_per_step: int | None = None,
    soft_cap: float = 0.0,
    return_lse: bool = False,
    interpret: Any = None,
):
    """Single-device GQA batch decode over a PAGED KV cache
    (≙ the reference's paged decode, flash_decode.py:130-280: the KV cache
    is a pool of fixed-size pages; ``block_table[b, i]`` names the physical
    page holding sequence ``b``'s ``i``-th chunk).

    q: ``[b, q_heads, d]``; k_pages, v_pages: ``[n_pages, kv_heads,
    page_size, d]``; kv_lens: ``[b]`` int32; block_table: ``[b, max_pages]``
    int32 physical page ids (entries beyond the valid length may be
    arbitrary in-range values). Returns like :func:`flash_decode`.

    TPU-native form of the indirection: the block table rides scalar
    prefetch (SMEM), and the K/V BlockSpec index_map reads it to steer each
    grid step's page fetch — the double-buffered pipeline then streams
    pages exactly as the contiguous kernel streams chunks.

    ``fuse_heads``: a page holds every kv head's slab, so the fused-heads
    grid (b, step) fetches each physical page in ONE DMA; the per-head
    grid (b, h_kv, step) fetches page_size·d slices. Default (None) =
    auto, decided by the per-step softmax SPAN each grid can afford
    under the scoped-VMEM budget (r5 chip finding: span, not DMA size,
    decides throughput — per-head at span 4096 measured 347 µs where
    fused capped at 1792 gave 392, and the span-256 grids 577). Pass
    True/False to pin.

    ``pages_per_step``: physical pages CONCATENATED into one online-
    softmax span per grid step (each page still its own DMA, P in
    flight). None = auto: reach a 4096 span, bounded by the VMEM
    budget and the table width. The one-page grids measured 571 µs vs
    the contiguous kernel's 359 for identical bytes (r5); the span fix
    recovers all of it and the indirection costs nothing.

    ``k_scales``/``v_scales`` (``[n_pages, kv_heads, 1, page_size]``
    f32, from :func:`quantize_kv_pages`): int8 page pools — the paged
    form of :func:`flash_decode_quant`'s per-position row scales. The
    payload DMAs stream at half the bytes (the resource decode is
    bound by) and the scales ride 2P extra page-slot fetches; this
    completes the serving cache matrix (contiguous/paged ×
    bf16/int8), which the reference's bf16-only paged decode lacks.

    The bf16 pool degrades to the gather-reconstructed
    :func:`_xla_paged_decode` golden when the Pallas kernel cannot run in
    this environment (resilience layer, docs/resilience.md); int8 pools
    have no golden slow path, so their failures stay loud.
    """
    assert q.shape[1] % k_pages.shape[1] == 0, (q.shape, k_pages.shape)
    kv_lens = kv_lens.astype(jnp.int32)
    if k_scales is None:
        family = "paged_flash_decode"
    else:
        family = (
            "paged_flash_decode_fp8" if k_pages.dtype == FP8_KV_DTYPE
            else "paged_flash_decode_q"
        )
    return resilience.guarded_call(
        family,
        lambda: _paged_flash_decode_fused(
            q, k_pages, v_pages, kv_lens, block_table,
            k_scales=k_scales, v_scales=v_scales, fuse_heads=fuse_heads,
            pages_per_step=pages_per_step, soft_cap=soft_cap,
            return_lse=return_lse, interpret=interpret,
        ),
        None if k_scales is not None else (
            lambda: _xla_paged_decode(
                q, k_pages, v_pages, kv_lens, block_table,
                return_lse=return_lse, soft_cap=soft_cap,
            )
        ),
    )


def _paged_flash_decode_fused(
    q, k_pages, v_pages, kv_lens, block_table, *,
    k_scales, v_scales, fuse_heads, pages_per_step, soft_cap, return_lse,
    interpret,
):
    b, hq, d = q.shape
    n_pages, h_kv, page_size, _ = k_pages.shape
    g = hq // h_kv
    max_pages = block_table.shape[1]
    quant = k_scales is not None
    d_out = d
    scale = 1.0 / math.sqrt(d)  # the TRUE head dim, before any padding
    d = _kernel_head_dim(d)
    if d != d_out:  # pad the q and the page pools; slice the output back
        q, k_pages, v_pages = (
            _pad_head_dim(x, d) for x in (q, k_pages, v_pages)
        )
    if quant:
        assert v_scales is not None
        assert k_scales.shape == (n_pages, h_kv, 1, page_size), k_scales.shape
        assert v_scales.shape == k_scales.shape, (v_scales.shape, k_scales.shape)
    # int8 pools stream half the payload bytes plus the f32 scale rows
    slab_h = page_size * (
        d * k_pages.dtype.itemsize + (4 if quant else 0)
    )
    slab_f = h_kv * slab_h
    p_f = _auto_pages_per_step(slab_f, page_size, max_pages)
    p_h = _auto_pages_per_step(slab_h, page_size, max_pages)
    if fuse_heads is None:
        # span-driven choice (r5 chip finding: the per-step softmax span,
        # not the page indirection or DMA size, decides throughput): each
        # grid shape concatenates as many page slots as its double-
        # buffered slabs afford — pick the grid that reaches the wider
        # span; ties go to fused (one DMA per page covers all heads), but
        # only when at least one fused slot actually fits the budget.
        # This preserves the old guarantee that many-kv-head pools never
        # fail to compile: per-head slabs are h_kv× smaller.
        if quant:
            # int8 pools halve payload bytes and add per-page scale
            # fetches: the per-head grid's [page, d] slices drop to tens
            # of KB and the pipeline goes DMA-ISSUE-bound (chip r5:
            # per-head 478 µs vs fused 218 at the serving shape, even
            # though per-head affords the wider span) — prefer the fused
            # grid whenever one of its slots fits.
            fuse_heads = p_f >= 1
        else:
            fuse_heads = p_f >= 1 and p_f >= p_h
    if pages_per_step is None and (p_f if fuse_heads else p_h) == 0:
        # the SELECTED grid (auto never picks a dead grid while the other
        # lives, but an explicit fuse_heads can force one) affords not even
        # ONE page slot: without this check the forced pages_per_step=1
        # dies deep inside Mosaic compilation with an allocation error
        # naming none of these numbers
        raise ValueError(
            f"paged_flash_decode: the selected "
            f"{'fused' if fuse_heads else 'per-head'} grid affords no "
            f"single page slot under the scoped-VMEM budget — "
            f"page_size={page_size}, head_dim={d}, h_kv={h_kv}: one "
            f"double-buffered K+V page slot "
            f"{4 * (slab_f if fuse_heads else slab_h)} B exceeds the "
            f"{_fused_slab_vmem_budget()} B budget "
            f"(--xla_tpu_scoped_vmem_limit_kib / TDT_SCOPED_VMEM_LIMIT_KIB "
            f"raises it). Reduce page_size, toggle fuse_heads, or use "
            f"flash_decode on a contiguous cache."
        )
    # match q to the pool's COMPUTE dtype (int8 pools upcast to bf16 in
    # the kernel — the same contract as flash_decode_quant)
    q4 = q.reshape(b, h_kv, g, d).astype(
        jnp.bfloat16 if quant else k_pages.dtype
    )
    cost = pl.CostEstimate(
        flops=4 * b * hq * max_pages * page_size * d,
        bytes_accessed=(2 * b * h_kv * max_pages * page_size)
        * (d * k_pages.dtype.itemsize + (4 if quant else 0)),
        transcendentals=b * hq * max_pages * page_size,
    )
    if fuse_heads:
        if pages_per_step is None:
            pages_per_step = max(1, p_f)
        P = pages_per_step
        n_steps = cdiv(max_pages, P)

        def kv_index_map_p(p):
            def index_map(i, c, kv_lens_ref, bt_ref):
                return (
                    bt_ref[i, jnp.minimum(c * P + p, max_pages - 1)], 0, 0, 0,
                )
            return index_map

        page_spec = lambda p: pl.BlockSpec(
            (1, h_kv, page_size, d), kv_index_map_p(p)
        )
        scale_spec = lambda p: pl.BlockSpec(
            (1, h_kv, 1, page_size), kv_index_map_p(p)
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_steps),
            in_specs=[
                pl.BlockSpec((1, h_kv, g, d), lambda i, c, *_: (i, 0, 0, 0)),
                *(page_spec(p) for p in range(P) for _ in (0, 1)),
                *(scale_spec(p) for p in range(P) for _ in (0, 1) if quant),
            ],
            out_specs=(
                pl.BlockSpec((1, h_kv, g, d), lambda i, c, *_: (i, 0, 0, 0)),
                pl.BlockSpec((1, h_kv, g, 1), lambda i, c, *_: (i, 0, 0, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((h_kv, g, 1), jnp.float32),
                pltpu.VMEM((h_kv, g, 1), jnp.float32),
                pltpu.VMEM((h_kv, g, d), jnp.float32),
            ],
        )
        out, lse = dist_pallas_call(
            functools.partial(
                _paged_flash_decode_kernel,
                n_steps=n_steps, pages_per_step=P,
                page_size=page_size, scale=scale, h_kv=h_kv, chunk_dim=1,
                quant=quant, soft_cap=soft_cap,
            ),
            name="paged_flash_decode_q_fh" if quant else "paged_flash_decode_fh",
            grid_spec=grid_spec,
            out_shape=(
                jax.ShapeDtypeStruct((b, h_kv, g, d), jnp.float32),
                jax.ShapeDtypeStruct((b, h_kv, g, 1), jnp.float32),
            ),
            cost_estimate=cost,
            dimension_semantics=("parallel", "arbitrary"),
            uses_barrier=False,
            interpret=interpret,
        )(
            kv_lens.astype(jnp.int32), block_table.astype(jnp.int32),
            q4, *(kv for _ in range(P) for kv in (k_pages, v_pages)),
            *(sc for _ in range(P) for sc in (k_scales, v_scales) if quant),
        )
        out = out.reshape(b, hq, d)[..., :d_out]
        lse = lse.reshape(b, hq)
        return (out, lse) if return_lse else out

    if pages_per_step is None:
        pages_per_step = max(1, p_h)
    P = pages_per_step
    n_steps = cdiv(max_pages, P)

    def kv_index_map_p(p):
        def index_map(i, j, c, kv_lens_ref, bt_ref):
            return (bt_ref[i, jnp.minimum(c * P + p, max_pages - 1)], j, 0, 0)
        return index_map

    page_spec = lambda p: pl.BlockSpec(
        (1, 1, page_size, d), kv_index_map_p(p)
    )
    scale_spec = lambda p: pl.BlockSpec(
        (1, 1, 1, page_size), kv_index_map_p(p)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h_kv, n_steps),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda i, j, c, *_: (i, j, 0, 0)),
            *(page_spec(p) for p in range(P) for _ in (0, 1)),
            *(scale_spec(p) for p in range(P) for _ in (0, 1) if quant),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, g, d), lambda i, j, c, *_: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, g, 1), lambda i, j, c, *_: (i, j, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((1, g, 1), jnp.float32),
            pltpu.VMEM((1, g, 1), jnp.float32),
            pltpu.VMEM((1, g, d), jnp.float32),
        ],
    )
    # pages are viewed [n_pages, h_kv, page_size, d] → block (1,1,ps,d);
    # the shared body's h_kv=1 instance (leading head dim on scratches)
    out, lse = dist_pallas_call(
        functools.partial(
            _paged_flash_decode_kernel,
            n_steps=n_steps, pages_per_step=P,
            page_size=page_size, scale=scale, h_kv=1, chunk_dim=2,
            quant=quant, soft_cap=soft_cap,
        ),
        name="paged_flash_decode_q" if quant else "paged_flash_decode",
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b, h_kv, g, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h_kv, g, 1), jnp.float32),
        ),
        cost_estimate=cost,
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(
        kv_lens.astype(jnp.int32), block_table.astype(jnp.int32),
        q4, *(kv for _ in range(P) for kv in (k_pages, v_pages)),
        *(sc for _ in range(P) for sc in (k_scales, v_scales) if quant),
    )
    out = out.reshape(b, hq, d)[..., :d_out]
    lse = lse.reshape(b, hq)
    return (out, lse) if return_lse else out


def quantize_kv_pages(k_pages: jax.Array, v_pages: jax.Array):
    """Per-(page, head, position) absmax int8 quantization of a paged KV
    pool (k_pages, v_pages ``[n_pages, h_kv, page, d]``) →
    ``(k_q, v_q, k_scale, v_scale)`` with int8 payloads and
    ``[n_pages, h_kv, 1, page]`` f32 row scales — the paged layout of
    :func:`quantize_kv`'s scales. The math is dimension-agnostic over
    the leading axis, so this IS :func:`quantize_kv` applied to the
    pool (one implementation: a fix to the shared quantization cannot
    diverge the two cache layouts). Feed to :func:`paged_flash_decode`
    via ``k_scales``/``v_scales``."""
    return quantize_kv(k_pages, v_pages)


def paged_flash_decode_quant(
    q: jax.Array,
    k_pages_q: jax.Array,
    v_pages_q: jax.Array,
    k_scales: jax.Array,
    v_scales: jax.Array,
    kv_lens: jax.Array,
    block_table: jax.Array,
    **kw,
):
    """int8-pool paged decode (:func:`flash_decode_quant` × the paged
    layout — the last cell of the serving cache matrix): thin alias of
    :func:`paged_flash_decode` with the scale pools attached; argument
    order mirrors the contiguous quant entry."""
    return paged_flash_decode(
        q, k_pages_q, v_pages_q, kv_lens, block_table,
        k_scales=k_scales, v_scales=v_scales, **kw,
    )


def quantize_kv_pages_fp8(k_pages: jax.Array, v_pages: jax.Array):
    """fp8 twin of :func:`quantize_kv_pages` — :func:`quantize_kv_fp8`
    applied to the page pool (one implementation, two cache layouts)."""
    return quantize_kv_fp8(k_pages, v_pages)


def paged_flash_decode_fp8(
    q: jax.Array,
    k_pages_q: jax.Array,
    v_pages_q: jax.Array,
    k_scales: jax.Array,
    v_scales: jax.Array,
    kv_lens: jax.Array,
    block_table: jax.Array,
    **kw,
):
    """fp8-pool paged decode (:func:`flash_decode_fp8` × the paged
    layout): thin alias of :func:`paged_flash_decode` with the fp8 scale
    pools attached; argument order mirrors the contiguous fp8 entry."""
    return paged_flash_decode(
        q, k_pages_q, v_pages_q, kv_lens, block_table,
        k_scales=k_scales, v_scales=v_scales, **kw,
    )


def paged_flash_decode_distributed(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    kv_lens_shard: jax.Array,
    block_table: jax.Array,
    *,
    axis: str = "tp",
    fuse_heads: bool | None = None,
    pages_per_step: int | None = None,
    soft_cap: float = 0.0,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP/CP decode over a paged, sequence-sharded KV cache: each PE holds
    its own page pool + block table covering its sequence shard (the paged
    analogue of :func:`flash_decode_distributed`; ≙ the reference SP layer,
    which is paged end-to-end: sp_flash_decode_layer.py:78).
    ``fuse_heads`` / ``pages_per_step`` as in :func:`paged_flash_decode`
    (None = span-driven auto)."""
    out, lse = paged_flash_decode(
        q, k_pages, v_pages, kv_lens_shard, block_table,
        fuse_heads=fuse_heads, pages_per_step=pages_per_step,
        soft_cap=soft_cap, return_lse=True, interpret=interpret,
    )
    return _sp_allgather_combine(out, lse, axis, ag_method, interpret)


def combine_partials(outs: jax.Array, lses: jax.Array) -> jax.Array:
    """Numerically-stable online-softmax merge of partial attention results
    (≙ ``kernel_inter_rank_gqa_fwd_batch_decode_combine_kv``, reference
    flash_decode.py:482-530: ``acc *= exp(m - m_new) ...``).

    outs: ``[n, b, hq, d]`` partial (normalized) outputs; lses: ``[n, b, hq]``
    their log-sum-exps. Returns the exact full-attention result ``[b, hq, d]``.
    """
    m = jnp.max(lses, axis=0)                            # [b, hq]
    # ranks with no KV carry lse=-inf → weight 0; all -inf → output 0
    w = jnp.where(
        jnp.isfinite(lses), jnp.exp(lses - jnp.maximum(m, -1e30)), 0.0
    )                                                    # [n, b, hq]
    denom = jnp.maximum(jnp.sum(w, axis=0), 1e-30)       # [b, hq]
    return jnp.einsum("nbh,nbhd->bhd", w, outs) / denom[..., None]


def flash_decode_distributed(
    q: jax.Array,
    k_shard: jax.Array,
    v_shard: jax.Array,
    kv_lens_shard: jax.Array,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    ag_method: str = "full_mesh_push",
    interpret: Any = None,
) -> jax.Array:
    """SP/CP decode over a KV-sharded cache (call inside ``jax.shard_map``;
    ≙ ``SpGQAFlashDecodeAttention.forward``, sp_flash_decode_layer.py:78).

    Every PE holds the full q and a sequence-shard of the KV cache
    (``kv_lens_shard`` = #valid positions in the LOCAL shard). Local partial
    attention → low-latency allgather of the (out ‖ lse) payload → merge.
    Golden: single-device flash decode over the concatenated cache.
    """
    out, lse = flash_decode(
        q, k_shard, v_shard, kv_lens_shard,
        config=config, return_lse=True, interpret=interpret,
    )
    return _sp_allgather_combine(out, lse, axis, ag_method, interpret)


def _sp_allgather_combine(out, lse, axis, ag_method, interpret) -> jax.Array:
    """Shared SP tail: allgather each PE's (out ‖ lse) payload and merge.

    One flat payload per PE (≙ the staged symm ag_buffer copy,
    sp_flash_decode_layer.py:134-137): [b*hq, d] out rows, then the b*hq
    lse scalars packed densely into ceil(b*hq/d) extra rows.
    """
    n = _axis_size(axis)
    if n == 1:
        return out
    b, hq, d = out.shape
    rows = b * hq
    lse_rows = -(-rows // d)
    lse_packed = jnp.pad(lse.reshape(-1), (0, lse_rows * d - rows)).reshape(lse_rows, d)
    payload = jnp.concatenate([out.reshape(rows, d), lse_packed])
    gathered = all_gather(payload, axis=axis, method=ag_method, interpret=interpret)
    gathered = gathered.reshape(n, rows + lse_rows, d)
    outs = gathered[:, :rows, :].reshape(n, b, hq, d)
    lses = gathered[:, rows:, :].reshape(n, lse_rows * d)[:, :rows].reshape(n, b, hq)
    return combine_partials(outs, lses)


def flash_decode_op(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_lens: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: FlashDecodeConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level SP entry: `k`/`v` ``[b, h_kv, S, d]`` sharded on the
    sequence dim over `axis`, `q`/`kv_lens` replicated (global lengths).
    Each PE derives its local valid length from the global one."""
    n = mesh.shape[axis]
    s_shard = k.shape[2] // n
    if n == 1 and config is not None and config.block_s == 0:
        # world-1 XLA-native sentinel: no SPMD machinery (see ag_gemm_op)
        return _xla_decode(
            q, k, v, kv_lens.astype(jnp.int32), return_lse=False,
            soft_cap=config.soft_cap,
        )

    def fn(q, k_s, v_s, kv_lens):
        me = jax.lax.axis_index(axis)
        local_lens = jnp.clip(kv_lens - me * s_shard, 0, s_shard)
        return flash_decode_distributed(
            q, k_s, v_s, local_lens, axis=axis, config=config, interpret=interpret
        )

    return jit_shard_map(
        fn, mesh,
        (
            P(None, None, None),
            P(None, None, axis, None),
            P(None, None, axis, None),
            P(None),
        ),
        P(None, None, None),
        key=("flash_decode", axis, config, s_shard, str(interpret)),
    )(q, k, v, kv_lens.astype(jnp.int32))


# KV-chunk tune space (≙ the reference's split-KV block sweep); larger
# chunks amortize per-grid-step overhead, smaller ones win on short
# caches. FIRST entry = best-known for the long-cache bench shape
# (applied sweep-free under cached_or_first): the per-head Pallas kernel
# at block_s=4096, which RETIRED the XLA sentinel on chip in the r5
# sweep (359.5 µs vs the sentinel's ~374, vs_baseline 1.04 — the span
# finding: wide per-step softmax spans win; the r3-era "XLA fusion wins"
# measurement was against span-512 chunkings). The sentinel stays as
# the second candidate for shapes where XLA's one-fusion form still
# wins (short caches). Fused-heads chunkings above span 1024 exceed the
# 16 MiB scoped-VMEM stack at h_kv=8 and fail candidate compilation —
# the sweep prices that in by falling through; they remain for
# few-kv-head shapes where their one-DMA-per-chunk slabs fit.
FLASH_DECODE_TUNE_SPACE = (
    FlashDecodeConfig(block_s=4096),
    FlashDecodeConfig(block_s=0),
    FlashDecodeConfig(block_s=8192),
    FlashDecodeConfig(block_s=2048),
    FlashDecodeConfig(block_s=1024),
    FlashDecodeConfig(block_s=512),
    FlashDecodeConfig(block_s=2048, fuse_heads=True),
    FlashDecodeConfig(block_s=1024, fuse_heads=True),
    FlashDecodeConfig(block_s=4096, fuse_heads=True),
    FlashDecodeConfig(block_s=512, fuse_heads=True),
)


def _fd_effective_block(cfg, q, k, v, kv_lens, mesh, *, axis="tp", **_):
    """Configs whose block clamps to the same per-shard chunk are the same
    kernel — time one (pick_block caps block_s at the local KV length)."""
    if cfg.block_s == 0:
        return 0  # XLA-native path: its own kernel
    return (
        pick_block(k.shape[2] // mesh.shape[axis], cfg.block_s),
        cfg.fuse_heads,
    )


def _flash_decode_op_xla(q, k, v, kv_lens, mesh, *, config=None, **_):
    """Op-level golden: the XLA-native masked attention over the full
    cache — no SPMD machinery at all (jit shards the einsums under the
    arrays' placement), so it survives any topology the fused SP
    pipeline cannot. Honors the config's ``soft_cap`` — the golden must
    compute the same capped logits as the kernel it stands in for."""
    del mesh
    return _xla_decode(
        q, k, v, kv_lens.astype(jnp.int32), return_lse=False,
        soft_cap=config.soft_cap if config is not None else 0.0,
    )


flash_decode_op = contextual_autotune(
    FLASH_DECODE_TUNE_SPACE, name="flash_decode", dedupe=_fd_effective_block
)(flash_decode_op)
# guard OUTSIDE the autotuner: the sweep still prices failing candidates;
# only a failure of the whole tuned entry degrades to the XLA golden
flash_decode_op = resilience.guard_op("flash_decode_op", _flash_decode_op_xla)(
    flash_decode_op
)
