"""MoE-Reduce-RS — MoE TP down-projection: grouped GEMM + top-k weighted
reduce + reduce-scatter (≙ reference ``kernels/nvidia/moe_reduce_rs.py``,
1020 LoC).

Reference pipeline: grouped-GEMM producer with a scatter epilogue writing
straight into the reduce-scatter input layout + per-rank notify counters
(:362), consumer doing topk-reduce (:468) then the 2-D reduce-scatter on
side streams (:817, orchestration :882-1020).

TPU-native composition: the scalar-prefetch grouped GEMM produces the
per-assignment rows, the topk-weighted unsort is an XLA fused
scatter-add (moe_utils.scatter_add_unsorted — the notify/counter machinery
has no role when kernels chain in-order on one core), and the result feeds
the fused reduce-scatter kernel, whose one-sided pushes overlap the next
layer's work in the XLA schedule.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.ops.group_gemm import (
    GroupGemmConfig,
    _panel_for,
    group_gemm,
)
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    scatter_add_unsorted,
    valid_rows_from_sorted,
)
from triton_dist_tpu.ops.reduce_scatter import ReduceScatterConfig, reduce_scatter
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def moe_reduce_rs(
    h_sorted: jax.Array,
    w_down: jax.Array,
    alignment: MoEAlignment,
    topk_weights: jax.Array,
    *,
    axis: str = "tp",
    n_tokens: int,
    config: GroupGemmConfig | None = None,
    rs_config: ReduceScatterConfig | None = None,
    rs_method: str = "auto",
    out_dtype: Any = None,
    act_fn: Any = None,
    assume_bijective: bool = True,
    interpret: Any = None,
) -> jax.Array:
    """MoE second GEMM + weighted combine + reduce-scatter (call inside
    ``jax.shard_map``; ≙ ``moe_reduce_rs``, reference moe_reduce_rs.py:882).

    h_sorted: ``[t_pad, f_loc]`` block-aligned expert-major hidden rows
    (the activated output of :func:`ag_group_gemm` — or, with ``act_fn``,
    its PRE-activation output: the activation then rides the grouped
    GEMM's A-tile load instead of paying its own HBM pass, see
    :func:`group_gemm`) — `f_loc` is this PE's TP shard of the expert FFN
    dim. w_down: ``[E, f_loc, H]``. topk_weights: ``[n_tokens, topk]``
    routing weights of the *gathered* tokens. Returns ``[n_tokens / n,
    H]`` — this PE's token chunk of the fully-reduced MoE output.
    """
    out_dtype = out_dtype or h_sorted.dtype
    y_sorted = group_gemm(
        h_sorted, w_down, alignment.expert_ids,
        valid_rows=alignment.valid_rows, config=config,
        out_dtype=jnp.float32, act_fn=act_fn, interpret=interpret,
    )
    partial = scatter_add_unsorted(
        y_sorted, alignment, topk_weights, n_tokens,
        assume_bijective=assume_bijective,
    )
    return reduce_scatter(
        partial.astype(out_dtype), axis=axis, method=rs_method,
        config=rs_config, interpret=interpret,
    )


def rs_block_n_for(
    h_dim: int, want_bn: int, m_out: int, f_loc: int,
    out_itemsize: int, w_itemsize: int, budget: int = 48 * 2**20,
) -> int:
    """H-slab width for the overlapped kernel: the f32 partial accumulator
    (m_out × bn), the staged pushes (2 × m_out × bn) and the streamed
    weight slabs (2 × f_loc × bn) must fit `budget` for ANY m_out/f_loc.
    The cap is floored to a power of two — ``pick_block`` shrinks by
    halving, so a non-power-of-two cap would walk past every divisor of
    h_dim down to bn=1."""
    per_bn = m_out * 4 + 2 * m_out * out_itemsize + 2 * f_loc * w_itemsize
    cap = 2 ** max(7, (budget // per_bn).bit_length() - 1)
    return pick_block(h_dim, min(want_bn, cap))


def _moe_ragged_blk(
    h_buf, w_buf, ids_v, w_v, partial_ref, hslot, slot, b, v, m_out, bm,
    panel, cdt,
):
    """Ragged block step of the fused down-projection (ISSUE 5): the
    ``h_block @ W_down`` dot AND the one-hot combine run only for the
    block's live ``panel``-row panels (``pl.when``-guarded) — the combine's
    FLOPs scale with live rows too, since its contraction dim IS the block
    rows. Dead panels contribute nothing; partial_ref is accumulative so
    skipping is exact."""
    d = ids_v[b]
    w_r = w_v[b]
    for p in range(bm // panel):
        @pl.when(p * panel < v)
        def _(p=p):
            yp = jnp.dot(
                h_buf[hslot, pl.ds(p * panel, panel), :],
                w_buf[slot],
                preferred_element_type=jnp.float32,
            )
            dp = d[p * panel:(p + 1) * panel]
            wp = w_r[p * panel:(p + 1) * panel]
            sel = jax.lax.broadcasted_iota(
                jnp.int32, (m_out, panel), 0
            ) == dp[None, :]
            scat = jnp.where(sel, wp[None, :], 0.0).astype(cdt)
            partial_ref[:] += jnp.dot(
                scat, yp.astype(cdt), preferred_element_type=jnp.float32
            )


def _moe_reduce_rs_overlap_kernel(
    eid_ref, h_ref, w_ref, dst_ref, wrow_ref,
    out_ref, own_buf, landing,
    h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
    hsem, wsem, metasem, stage_sem, recv_sems,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, m_out: int, out_dtype,
    vid_ref=None, panel: int = 0,
):
    """Fused grouped-GEMM → weighted combine → reduce-scatter: destination
    rank c's chunk is computed from ITS aligned rows (rank-major layout:
    chunk c's blocks are contiguous), combined in VMEM, and pushed to c the
    moment its slab is done — while the next chunk's expert GEMMs already
    run (≙ the reference's producer GEMM on side streams feeding the RS
    consumer through per-rank notify counters, moe_reduce_rs.py:362,
    817,882-1020). The top-k weighted scatter is a one-hot-weights matmul
    riding the MXU in the shadow of the weight-slab DMAs instead of a
    per-row scatter pass over HBM."""
    me = shmem.my_pe(axis)
    t_pad_tot, f_loc = h_ref.shape
    t_pad_loc = t_pad_tot // n
    bm = t_pad_loc // nb
    cdt = h_ref.dtype
    if n > 1:
        shmem.barrier_all(axis)

    def _issue_h(c, b, slot):
        pltpu.make_async_copy(
            h_ref.at[pl.ds(c * t_pad_loc + b * bm, bm), :],
            h_buf.at[slot],
            hsem.at[slot],
        ).start()

    for s in range(n):
        # own chunk LAST: remote pushes get the whole kernel to land
        c = jax.lax.rem(me + 1 + s, n) if n > 1 else jnp.int32(0)
        ids_cp = pltpu.make_async_copy(dst_ref.at[c], ids_v, metasem)
        ids_cp.start()
        w_cp = pltpu.make_async_copy(wrow_ref.at[c], w_v, metasem)
        w_cp.start()
        ids_cp.wait()
        w_cp.wait()

        for jn in range(n_jn):
            partial_ref[:] = jnp.zeros_like(partial_ref)
            e0 = eid_ref[c, 0]
            pltpu.make_async_copy(
                w_ref.at[e0, :, pl.ds(jn * bn, bn)], w_buf.at[0], wsem.at[0]
            ).start()
            _issue_h(c, 0, 0)   # h rows stream per block, double-buffered

            def _blk(b, slot):
                e = eid_ref[c, b]
                e_prev = eid_ref[c, jax.lax.max(b - 1, 0)]
                fresh = jnp.logical_or(b == 0, e != e_prev)
                slot = jnp.where(fresh, 1 - slot, slot)

                @pl.when(fresh)
                def _():
                    pltpu.make_async_copy(
                        w_ref.at[e, :, pl.ds(jn * bn, bn)],
                        w_buf.at[slot],
                        wsem.at[slot],
                    ).wait()

                e2 = eid_ref[c, jax.lax.min(b + 1, nb - 1)]

                @pl.when(jnp.logical_and(b + 1 < nb, e2 != e))
                def _():
                    pltpu.make_async_copy(
                        w_ref.at[e2, :, pl.ds(jn * bn, bn)],
                        w_buf.at[1 - slot],
                        wsem.at[1 - slot],
                    ).start()

                hslot = jax.lax.rem(b, 2)
                pltpu.make_async_copy(
                    h_ref.at[pl.ds(0, bm), :], h_buf.at[hslot], hsem.at[hslot]
                ).wait()

                @pl.when(b + 1 < nb)
                def _():
                    pltpu.make_async_copy(
                        h_ref.at[
                            pl.ds(c * t_pad_loc + (b + 1) * bm, bm), :
                        ],
                        h_buf.at[1 - hslot],
                        hsem.at[1 - hslot],
                    ).start()

                if vid_ref is None:
                    y = jnp.dot(
                        h_buf[hslot],
                        w_buf[slot],
                        preferred_element_type=jnp.float32,
                    )
                    d = ids_v[b]                   # [bm] destination tokens
                    w_r = w_v[b]                   # [bm] routing weights
                    sel = jax.lax.broadcasted_iota(
                        jnp.int32, (m_out, bm), 0
                    ) == d[None, :]
                    scat = jnp.where(sel, w_r[None, :], 0.0).astype(cdt)
                    partial_ref[:] += jnp.dot(
                        scat, y.astype(cdt), preferred_element_type=jnp.float32
                    )
                else:
                    # ragged (ISSUE 5): both the down-GEMM and the one-hot
                    # combine shrink to the block's live panels. Sentinel
                    # rows inside the tail panel keep their 0 routing
                    # weight (ranked_scatter_meta), so their computed rows
                    # contribute exact zeros.
                    _moe_ragged_blk(
                        h_buf, w_buf, ids_v, w_v, partial_ref, hslot, slot,
                        b, vid_ref[c, b], m_out, bm, panel, cdt,
                    )
                return slot

            jax.lax.fori_loop(0, nb, _blk, jnp.int32(1))

            pc = s * n_jn + jn
            pslot = pc % 2

            def _stage_wait(sl):
                pltpu.make_async_copy(
                    push_stage.at[sl], own_buf.at[:, pl.ds(0, bn)],
                    stage_sem.at[sl],
                ).wait()

            if pc >= 2:
                _stage_wait(pslot)
            push_stage[pslot] = partial_ref[:].astype(out_dtype)
            if s < n - 1:
                # landing slot index s is the sender-distance convention of
                # _scatter_reduce_kernel: distinct per sender by symmetry.
                # Send completion is accounted on stage_sem by the slot-reuse
                # waits (and the end-of-kernel drain), so the handle is not
                # kept.
                shmem.putmem_nbi_block(
                    landing.at[s, :, pl.ds(jn * bn, bn)],
                    push_stage.at[pslot],
                    c, axis, stage_sem.at[pslot], recv_sems.at[s, jn],
                )
            else:
                pltpu.make_async_copy(
                    push_stage.at[pslot],
                    (out_ref if n == 1 else own_buf).at[:, pl.ds(jn * bn, bn)],
                    stage_sem.at[pslot],
                ).start()

    # drain the last two staged pushes
    total_push = n * n_jn
    if total_push >= 1:
        pltpu.make_async_copy(
            push_stage.at[(total_push - 1) % 2], own_buf.at[:, pl.ds(0, bn)],
            stage_sem.at[(total_push - 1) % 2],
        ).wait()
    if total_push >= 2:
        pltpu.make_async_copy(
            push_stage.at[total_push % 2], own_buf.at[:, pl.ds(0, bn)],
            stage_sem.at[total_push % 2],
        ).wait()
    if n == 1:
        return

    # wait every incoming slab, then one n-way f32 reduction pass
    for d in range(n - 1):
        for jn in range(n_jn):
            pltpu.make_async_copy(
                landing.at[d, :, pl.ds(jn * bn, bn)],
                own_buf.at[:, pl.ds(jn * bn, bn)],
                recv_sems.at[d, jn],
            ).wait()

    h_dim = out_ref.shape[1]
    bmo = pick_block(m_out, 256)
    bno = pick_block(h_dim, 1024)

    def reduce_body(*blks):
        o_blk = blks[-1]
        acc = blks[0][:].astype(jnp.float32)
        for r in blks[1:-1]:
            acc = acc + r[:].astype(jnp.float32)
        o_blk[:] = acc.astype(out_dtype)

    blk = lambda i, j: (i, j)  # noqa: E731
    pltpu.emit_pipeline(
        reduce_body,
        grid=(m_out // bmo, h_dim // bno),
        in_specs=[pl.BlockSpec((bmo, bno), blk)] * n,
        out_specs=[pl.BlockSpec((bmo, bno), blk)],
    )(
        own_buf,
        *(landing.at[d] for d in range(n - 1)),
        out_ref,
    )


def _moe_reduce_rs_overlap_chunked_kernel(
    eid_ref, h_ref, w_ref, dst_ref, wrow_ref,
    out_ref, own_buf, landing,
    h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
    hsem, wsem, metasem, stage_sems, local_sem, recv_sems, sig_sems,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, m_out: int,
    out_dtype, spans, vid_ref=None, panel: int = 0,
):
    """Chunk-granular combine side of the fused MoE down-projection
    (ISSUE 4 tentpole): the schedule of :func:`_moe_reduce_rs_overlap_kernel`
    with every retired (destination, H-slab) output block pushed as the
    ``spans`` chunk DMAs (``shmem.putmem_signal_chunked_nbi_block``) on
    per-(step, slab, chunk) semaphore slots — the first bytes of a
    finished slab are on the wire while the accumulator's copy of the
    later rows still drains, the chunks ride distinct routes, and the
    receiver's final reduction consumes each landing chunk by chunk
    through ``wait_chunk`` (so a dropped chunk signal surfaces as a
    ``chunk_wait`` diagnostic, never corruption). Compute schedule —
    GEMMs, one-hot combine, slab retirement order — is identical to
    legacy; ``chunks=1`` (or world-1) dispatches there."""
    me = shmem.my_pe(axis)
    t_pad_tot, f_loc = h_ref.shape
    t_pad_loc = t_pad_tot // n
    bm = t_pad_loc // nb
    cdt = h_ref.dtype
    shmem.barrier_all(axis)  # n >= 2: the host entry dispatches chunked
    # schedules only on multi-PE worlds

    def _issue_h(c, b, slot):
        pltpu.make_async_copy(
            h_ref.at[pl.ds(c * t_pad_loc + b * bm, bm), :],
            h_buf.at[slot],
            hsem.at[slot],
        ).start()

    pending = {}       # pslot -> send-side drain closure (slot reuse)
    push_handles = {}  # step s -> [ChunkedPutHandle per jn]
    for s in range(n):
        # own chunk LAST: remote pushes get the whole kernel to land
        c = jax.lax.rem(me + 1 + s, n)
        ids_cp = pltpu.make_async_copy(dst_ref.at[c], ids_v, metasem)
        ids_cp.start()
        w_cp = pltpu.make_async_copy(wrow_ref.at[c], w_v, metasem)
        w_cp.start()
        ids_cp.wait()
        w_cp.wait()

        for jn in range(n_jn):
            partial_ref[:] = jnp.zeros_like(partial_ref)
            e0 = eid_ref[c, 0]
            pltpu.make_async_copy(
                w_ref.at[e0, :, pl.ds(jn * bn, bn)], w_buf.at[0], wsem.at[0]
            ).start()
            _issue_h(c, 0, 0)

            def _blk(b, slot):
                e = eid_ref[c, b]
                e_prev = eid_ref[c, jax.lax.max(b - 1, 0)]
                fresh = jnp.logical_or(b == 0, e != e_prev)
                slot = jnp.where(fresh, 1 - slot, slot)

                @pl.when(fresh)
                def _():
                    pltpu.make_async_copy(
                        w_ref.at[e, :, pl.ds(jn * bn, bn)],
                        w_buf.at[slot],
                        wsem.at[slot],
                    ).wait()

                e2 = eid_ref[c, jax.lax.min(b + 1, nb - 1)]

                @pl.when(jnp.logical_and(b + 1 < nb, e2 != e))
                def _():
                    pltpu.make_async_copy(
                        w_ref.at[e2, :, pl.ds(jn * bn, bn)],
                        w_buf.at[1 - slot],
                        wsem.at[1 - slot],
                    ).start()

                hslot = jax.lax.rem(b, 2)
                pltpu.make_async_copy(
                    h_ref.at[pl.ds(0, bm), :], h_buf.at[hslot], hsem.at[hslot]
                ).wait()

                @pl.when(b + 1 < nb)
                def _():
                    pltpu.make_async_copy(
                        h_ref.at[
                            pl.ds(c * t_pad_loc + (b + 1) * bm, bm), :
                        ],
                        h_buf.at[1 - hslot],
                        hsem.at[1 - hslot],
                    ).start()

                if vid_ref is None:
                    y = jnp.dot(
                        h_buf[hslot],
                        w_buf[slot],
                        preferred_element_type=jnp.float32,
                    )
                    d = ids_v[b]
                    w_r = w_v[b]
                    sel = jax.lax.broadcasted_iota(
                        jnp.int32, (m_out, bm), 0
                    ) == d[None, :]
                    scat = jnp.where(sel, w_r[None, :], 0.0).astype(cdt)
                    partial_ref[:] += jnp.dot(
                        scat, y.astype(cdt), preferred_element_type=jnp.float32
                    )
                else:
                    # ragged × chunked (ISSUE 5): the combine-push chunk
                    # schedule spans m_out rows and never consults
                    # valid_rows — ragged adds no signal edges here either
                    _moe_ragged_blk(
                        h_buf, w_buf, ids_v, w_v, partial_ref, hslot, slot,
                        b, vid_ref[c, b], m_out, bm, panel, cdt,
                    )
                return slot

            jax.lax.fori_loop(0, nb, _blk, jnp.int32(1))

            pc = s * n_jn + jn
            pslot = pc % 2
            if pc >= 2:
                pending.pop(pslot)()  # send-side completion before reuse
            push_stage[pslot] = partial_ref[:].astype(out_dtype)
            if s < n - 1:
                # combine-side chunked put: the retired slab ships as
                # per-chunk DMAs on per-(s, jn, chunk) slots; landing slot
                # s is the sender-distance convention of the legacy kernel
                handle = shmem.putmem_signal_chunked_nbi_block(
                    lambda off, rows, s=s, jn=jn: landing.at[
                        s, pl.ds(off, rows), pl.ds(jn * bn, bn)
                    ],
                    lambda off, rows, pslot=pslot: push_stage.at[
                        pslot, pl.ds(off, rows)
                    ],
                    c, axis,
                    lambda j, pslot=pslot: stage_sems.at[pslot, j],
                    lambda j, s=s, jn=jn: recv_sems.at[s, jn, j],
                    lambda j, s=s, jn=jn: sig_sems.at[s, jn, j],
                    spans,
                )
                push_handles.setdefault(s, []).append(handle)
                pending[pslot] = handle.wait_send
            else:
                cp = pltpu.make_async_copy(
                    push_stage.at[pslot],
                    own_buf.at[:, pl.ds(jn * bn, bn)],
                    local_sem.at[pslot],
                )
                cp.start()
                pending[pslot] = cp.wait

    for drain in pending.values():
        drain()

    # consume every incoming slab chunk by chunk (the handle's recv side
    # observes the equal-shaped chunks from the mirror sender, SPMD
    # symmetry — and its sig slot routes through the watchdogged
    # chunk_wait path when armed), then one n-way f32 reduction pass
    for d in range(n - 1):
        for jn in range(n_jn):
            for j in range(len(spans)):
                push_handles[d][jn].wait_recv_chunk(j)

    h_dim = out_ref.shape[1]
    bmo = pick_block(m_out, 256)
    bno = pick_block(h_dim, 1024)

    def reduce_body(*blks):
        o_blk = blks[-1]
        acc = blks[0][:].astype(jnp.float32)
        for r in blks[1:-1]:
            acc = acc + r[:].astype(jnp.float32)
        o_blk[:] = acc.astype(out_dtype)

    blk = lambda i, j: (i, j)  # noqa: E731
    pltpu.emit_pipeline(
        reduce_body,
        grid=(m_out // bmo, h_dim // bno),
        in_specs=[pl.BlockSpec((bmo, bno), blk)] * n,
        out_specs=[pl.BlockSpec((bmo, bno), blk)],
    )(
        own_buf,
        *(landing.at[d] for d in range(n - 1)),
        out_ref,
    )


def _moe_reduce_rs_overlap_ragged_kernel(
    eid_ref, vid_ref, h_ref, w_ref, dst_ref, wrow_ref,
    out_ref, own_buf, landing,
    h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
    hsem, wsem, metasem, stage_sem, recv_sems,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, m_out: int,
    out_dtype, panel: int,
):
    """Ragged entry (ISSUE 5): the legacy schedule with the per-(rank,
    block) live-row map as a second SMEM operand — push/landing/semaphore
    structure identical; only each block's MXU work shrinks."""
    _moe_reduce_rs_overlap_kernel(
        eid_ref, h_ref, w_ref, dst_ref, wrow_ref, out_ref, own_buf, landing,
        h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
        hsem, wsem, metasem, stage_sem, recv_sems,
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, m_out=m_out,
        out_dtype=out_dtype, vid_ref=vid_ref, panel=panel,
    )


def _moe_reduce_rs_overlap_chunked_ragged_kernel(
    eid_ref, vid_ref, h_ref, w_ref, dst_ref, wrow_ref,
    out_ref, own_buf, landing,
    h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
    hsem, wsem, metasem, stage_sems, local_sem, recv_sems, sig_sems,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, m_out: int,
    out_dtype, spans, panel: int,
):
    """Ragged × chunked entry (ISSUE 5 × ISSUE 4): chunked combine pushes
    with ragged per-block compute; the chunk protocol is untouched."""
    _moe_reduce_rs_overlap_chunked_kernel(
        eid_ref, h_ref, w_ref, dst_ref, wrow_ref, out_ref, own_buf, landing,
        h_buf, w_buf, push_stage, ids_v, w_v, partial_ref,
        hsem, wsem, metasem, stage_sems, local_sem, recv_sems, sig_sems,
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, m_out=m_out,
        out_dtype=out_dtype, spans=spans, vid_ref=vid_ref, panel=panel,
    )


def moe_reduce_rs_overlap(
    h_sorted: jax.Array,
    w_down: jax.Array,
    expert_ids: jax.Array,
    dst_ids: jax.Array,
    w_rows: jax.Array,
    *,
    axis: str = "tp",
    m_out: int,
    valid_rows: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    out_dtype: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """Single-kernel overlapped MoE down-projection + combine + RS (call
    inside shard_map). h_sorted: ``[n*t_pad_loc, f_loc]`` rank-major aligned
    rows (the fused up-projection's output); w_down: ``[E, f_loc, H]``;
    expert_ids ``[n, nb]``, and ``(dst_ids, w_rows)`` ``[n, nb, bm]`` from
    :func:`~triton_dist_tpu.ops.moe_utils.ranked_scatter_meta`. Returns
    ``[m_out, H]`` — this PE's fully-reduced token chunk."""
    cfg = config or GroupGemmConfig()
    out_dtype = out_dtype or h_sorted.dtype
    n = _axis_size((axis))
    t_pad_tot, f_loc = h_sorted.shape
    t_pad_loc = t_pad_tot // n
    nb = expert_ids.shape[1]
    bm = t_pad_loc // nb
    assert bm == cfg.block_m, (bm, cfg.block_m)
    if cfg.backend != "pallas":
        raise ValueError(
            "the ragged_dot sentinel backend has no fused overlap form — "
            "route it through the sequential composition (tp_moe_mlp does "
            "this automatically); timing the Pallas pipeline under the "
            "sentinel's label would falsify the A/B"
        )
    ragged = bool(cfg.ragged)
    if ragged and valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs the ranked alignment's "
            "valid_rows map (moe_align_ranked(..., ragged=True))"
        )
    h_dim = w_down.shape[2]
    itemsize = jnp.dtype(h_sorted.dtype).itemsize
    bn = rs_block_n_for(
        h_dim, cfg.block_n, m_out, f_loc,
        jnp.dtype(out_dtype).itemsize, jnp.dtype(w_down.dtype).itemsize,
    )
    n_jn = h_dim // bn
    workspace = [
        jax.ShapeDtypeStruct((m_out, h_dim), out_dtype),            # own_buf
        jax.ShapeDtypeStruct((max(n - 1, 1), m_out, h_dim), out_dtype),
    ]
    from triton_dist_tpu.ops.common import chunk_schedule

    # combine-side chunk schedule (ISSUE 4): spans over the pushed slab's
    # m_out rows, quantized to 128 so every chunk boundary stays
    # tile-aligned in VMEM/HBM for any dtype; a single-span schedule —
    # including every chunks_per_shard=1 config and world-1 — dispatches
    # to the UNCHANGED legacy kernel, bit for bit
    spans = chunk_schedule(
        m_out, max(1, int(getattr(cfg, "chunks_per_shard", 1))) if n > 1 else 1,
        quantum=128,
    )
    ragged_kw = {"panel": _panel_for(bm)} if ragged else {}
    if len(spans) > 1:
        kernel = functools.partial(
            _moe_reduce_rs_overlap_chunked_ragged_kernel if ragged
            else _moe_reduce_rs_overlap_chunked_kernel,
            axis=axis, n=n, nb=nb,
            n_jn=n_jn, bn=bn, m_out=m_out, out_dtype=out_dtype, spans=spans,
            **ragged_kw,
        )
        push_scratch = [
            pltpu.SemaphoreType.DMA((2, len(spans))),   # stage_sems
            pltpu.SemaphoreType.DMA((2,)),              # local_sem
            pltpu.SemaphoreType.DMA((max(n - 1, 1), n_jn, len(spans))),
            # pure chunk-signal slots (REGULAR; armed watchdog only)
            pltpu.SemaphoreType.REGULAR((max(n - 1, 1), n_jn, len(spans))),
        ]
    else:
        kernel = functools.partial(
            _moe_reduce_rs_overlap_ragged_kernel if ragged
            else _moe_reduce_rs_overlap_kernel,
            axis=axis, n=n, nb=nb,
            n_jn=n_jn, bn=bn, m_out=m_out, out_dtype=out_dtype,
            **ragged_kw,
        )
        push_scratch = [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1), n_jn)),
        ]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # expert ids [n, nb]
        # HBM pinned: block/meta slices at dynamic offsets must DMA
        # from untiled HBM, not from VMEM the compiler might choose
        # for small inputs (see ag_group_gemm_overlap)
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # h_sorted
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # w_down
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # dst_ids
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # w_rows
    ]
    args = [expert_ids, h_sorted, w_down, dst_ids, w_rows]
    if ragged:
        # the per-(rank, block) live-row map rides SMEM next to the ids
        in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(1, valid_rows.astype(jnp.int32))
    outs = dist_pallas_call(
        kernel,
        name="moe_reduce_rs_overlap",
        out_shape=(
            jax.ShapeDtypeStruct((m_out, h_dim), out_dtype),
            *workspace,
        ),
        in_specs=in_specs,
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM) for _ in range(3)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bm, f_loc), h_sorted.dtype),
            pltpu.VMEM((2, f_loc, bn), w_down.dtype),
            pltpu.VMEM((2, m_out, bn), out_dtype),
            pltpu.VMEM((nb, bm), jnp.int32),
            pltpu.VMEM((nb, bm), jnp.float32),
            pltpu.VMEM((m_out, bn), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
            *push_scratch,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * t_pad_tot * f_loc * h_dim
            + 2 * n * n_jn * nb * m_out * bm * bn,
            bytes_accessed=(
                t_pad_tot * f_loc + w_down.shape[0] * f_loc * h_dim
                + (2 * n) * m_out * h_dim
            ) * itemsize,
            transcendentals=0,
        ),
        vmem_limit_bytes=min(
            2 * bm * f_loc * itemsize
            + 2 * f_loc * bn * jnp.dtype(w_down.dtype).itemsize
            + (2 * jnp.dtype(out_dtype).itemsize + 4) * m_out * bn
            + 8 * 2**20,
            100 * 2**20,
        ),
        uses_barrier=n > 1,
        interpret=interpret,
    )(*args)
    return outs[0]


def moe_reduce_rs_op(
    h_sorted: jax.Array,
    w_down: jax.Array,
    sorted_token_ids: jax.Array,
    expert_ids: jax.Array,
    topk_weights: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    assume_bijective: bool = True,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: `h_sorted` ``[t_pad, F]`` with F sharded over
    `axis`, `w_down` ``[E, F, H]`` sharded on F; alignment arrays and
    weights replicated. Result ``[n_tokens, H]`` sharded on tokens.

    ``assume_bijective=False`` for externally-built capacity-style
    alignments whose slots may be dropped to the sentinel — see
    :func:`triton_dist_tpu.ops.moe_utils.scatter_add_unsorted`."""
    n_tokens = topk_weights.shape[0]
    topk = topk_weights.shape[1]

    def fn(h, w, sti, eid, tw):
        # every block inside an expert's padded segment has >=1 valid row,
        # so valid-block count * block_m recovers num_tokens_post_pad
        cfg_ = config or GroupGemmConfig()
        if cfg_.ragged and not assume_bijective:
            # capacity-style alignments DROP slots to the sentinel
            # mid-block, which breaks the valid-rows-are-a-block-prefix
            # contract the ragged kernels skip on — degrade to the padded
            # schedule (correct everywhere) rather than skip live rows
            cfg_ = dataclasses.replace(cfg_, ragged=False)
        bm = sti.shape[0] // eid.shape[0]
        block_valid = jnp.any(
            sti.reshape(-1, bm) < n_tokens * topk, axis=1
        )
        alignment = MoEAlignment(
            sorted_token_ids=sti, expert_ids=eid,
            num_tokens_post_pad=(jnp.sum(block_valid) * bm).astype(jnp.int32),
            # externally-built alignment: reconstruct the ragged live-row
            # map from the sentinel layout when the config asks for it
            valid_rows=(
                valid_rows_from_sorted(sti, bm, n_tokens * topk)
                if cfg_.ragged else None
            ),
        )
        return moe_reduce_rs(
            h, w, alignment, tw, axis=axis, n_tokens=n_tokens,
            config=cfg_, assume_bijective=assume_bijective,
            interpret=interpret,
        )

    return jit_shard_map(
        fn, mesh,
        (
            P(None, axis),
            P(None, axis, None),
            P(None),
            P(None),
            P(None, None),
        ),
        P(axis, None),
        key=(
            "moe_reduce_rs", axis, config, n_tokens, topk, assume_bijective,
            str(interpret),
        ),
    )(h_sorted, w_down, sorted_token_ids, expert_ids, topk_weights)


# block_m is pinned by the caller-provided alignment (128 = moe_align
# default); the sweep covers the N/K tiling of the grouped GEMM. FIRST
# entry = best-known default (applied sweep-free under cached_or_first).
# Ragged twins (ISSUE 5) strictly after their padded originals (the
# no-regression ordering invariant).
MOE_RS_TUNE_SPACE = (
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 1024, 1024),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(128, 1024, 512, ragged=True),
)

moe_reduce_rs_op = contextual_autotune(MOE_RS_TUNE_SPACE, name="moe_reduce_rs")(
    moe_reduce_rs_op
)
