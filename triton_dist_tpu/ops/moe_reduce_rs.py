"""MoE-Reduce-RS — MoE TP down-projection: grouped GEMM + top-k weighted
reduce + reduce-scatter (≙ reference ``kernels/nvidia/moe_reduce_rs.py``,
1020 LoC).

Reference pipeline: grouped-GEMM producer with a scatter epilogue writing
straight into the reduce-scatter input layout + per-rank notify counters
(:362), consumer doing topk-reduce (:468) then the 2-D reduce-scatter on
side streams (:817, orchestration :882-1020).

TPU-native composition: the scalar-prefetch grouped GEMM produces the
per-assignment rows, the topk-weighted unsort is an XLA fused
scatter-add (moe_utils.scatter_add_unsorted — the notify/counter machinery
has no role when kernels chain in-order on one core), and the result feeds
the fused reduce-scatter kernel, whose one-sided pushes overlap the next
layer's work in the XLA schedule.

The fused overlap kernel body comes from the pipeline emitter
(:func:`triton_dist_tpu.ops.gg_pipeline.make_moe_rs_overlap_kernel`,
ISSUE 7); this entry builds specs/scratch for the chosen policy tuple,
and ``GroupGemmConfig.w8`` streams int8 ``W_down`` slabs at half the HBM
bytes (scale rows on the weight prefetch chain).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.ops.gg_pipeline import (
    OperandFormat,
    make_moe_rs_overlap_kernel,
)
from triton_dist_tpu.ops.group_gemm import (
    FP8_DTYPE,
    GroupGemmConfig,
    _group_gemm_xla,
    _panel_for,
    group_gemm,
    resolve_w8,
)
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    scatter_add_unsorted,
    valid_rows_from_sorted,
)
from triton_dist_tpu.ops.reduce_scatter import ReduceScatterConfig, reduce_scatter
from triton_dist_tpu.synth.admitted import (
    admitted_tune_extension as _admitted_tune_extension,
)
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def moe_reduce_rs(
    h_sorted: jax.Array,
    w_down: jax.Array,
    alignment: MoEAlignment,
    topk_weights: jax.Array,
    *,
    axis: str = "tp",
    n_tokens: int,
    config: GroupGemmConfig | None = None,
    rs_config: ReduceScatterConfig | None = None,
    rs_method: str = "auto",
    out_dtype: Any = None,
    act_fn: Any = None,
    assume_bijective: bool = True,
    scale: jax.Array | None = None,
    interpret: Any = None,
) -> jax.Array:
    """MoE second GEMM + weighted combine + reduce-scatter (call inside
    ``jax.shard_map``; ≙ ``moe_reduce_rs``, reference moe_reduce_rs.py:882).

    h_sorted: ``[t_pad, f_loc]`` block-aligned expert-major hidden rows
    (the activated output of :func:`ag_group_gemm` — or, with ``act_fn``,
    its PRE-activation output: the activation then rides the grouped
    GEMM's A-tile load instead of paying its own HBM pass, see
    :func:`group_gemm`) — `f_loc` is this PE's TP shard of the expert FFN
    dim. w_down: ``[E, f_loc, H]``. topk_weights: ``[n_tokens, topk]``
    routing weights of the *gathered* tokens. Returns ``[n_tokens / n,
    H]`` — this PE's token chunk of the fully-reduced MoE output.
    """
    out_dtype = out_dtype or h_sorted.dtype
    # an explicit `scale` marks w_down as a pre-quantized int8 pool
    # (ISSUE 8 satellite), same contract as group_gemm / the overlap entry
    y_sorted = group_gemm(
        h_sorted, w_down, alignment.expert_ids,
        valid_rows=alignment.valid_rows, config=config, scale=scale,
        out_dtype=jnp.float32, act_fn=act_fn, interpret=interpret,
    )
    partial = scatter_add_unsorted(
        y_sorted, alignment, topk_weights, n_tokens,
        assume_bijective=assume_bijective,
    )
    return reduce_scatter(
        partial.astype(out_dtype), axis=axis, method=rs_method,
        config=rs_config, interpret=interpret,
    )


def rs_block_n_for(
    h_dim: int, want_bn: int, m_out: int, f_loc: int,
    out_itemsize: int, w_itemsize: int, budget: int = 48 * 2**20,
) -> int:
    """H-slab width for the overlapped kernel: the f32 partial accumulator
    (m_out × bn), the staged pushes (2 × m_out × bn) and the streamed
    weight slabs (2 × f_loc × bn) must fit `budget` for ANY m_out/f_loc.
    The cap is floored to a power of two — ``pick_block`` shrinks by
    halving, so a non-power-of-two cap would walk past every divisor of
    h_dim down to bn=1."""
    per_bn = m_out * 4 + 2 * m_out * out_itemsize + 2 * f_loc * w_itemsize
    cap = 2 ** max(7, (budget // per_bn).bit_length() - 1)
    return pick_block(h_dim, min(want_bn, cap))


def _moe_rs_overlap_xla(
    h_sorted, w_down, scale, expert_ids, dst_ids, w_rows, *, axis, ragged,
    valid_rows, m_out, out_dtype,
):
    """Golden slow path for the fused down-projection: block-gathered
    einsum + scatter-add combine per destination rank + one psum-scatter —
    the program the fused kernel is tested against."""
    n, nb, bm = dst_ids.shape
    h_dim = w_down.shape[2]
    y = _group_gemm_xla(
        h_sorted, w_down, expert_ids.reshape(-1),
        valid_rows=None if valid_rows is None else valid_rows.reshape(-1),
        scale=scale, ragged=ragged, bm=bm, out_dtype=jnp.float32,
        act_fn=None,
    ).reshape(n, nb * bm, h_dim)
    w = w_rows.reshape(n, nb * bm).astype(jnp.float32)
    d = dst_ids.reshape(n, nb * bm)
    c_idx = jnp.repeat(jnp.arange(n, dtype=jnp.int32)[:, None], nb * bm, 1)
    partial = (
        jnp.zeros((n, m_out, h_dim), jnp.float32)
        .at[c_idx, d]
        .add(y * w[..., None])
    )
    # each rank holds the f_loc-shard partial for EVERY destination chunk;
    # destination c's output is the sum over ranks of partial[c]
    return jax.lax.psum_scatter(
        partial.reshape(n * m_out, h_dim), axis, scatter_dimension=0,
        tiled=True,
    ).astype(out_dtype)


def _moe_rs_overlap_fused(
    h_sorted, w_down, scale, expert_ids, dst_ids, w_rows, *, axis, ragged,
    valid_rows, m_out, out_dtype, cfg, interpret,
):
    n = _axis_size((axis))
    t_pad_tot, f_loc = h_sorted.shape
    t_pad_loc = t_pad_tot // n
    nb = expert_ids.shape[1]
    bm = t_pad_loc // nb
    w8 = scale is not None
    # format keyed off the bank dtype (ISSUE 19): a float8 W_down pool
    # streams at quarter-rate HBM bytes through the same w8 slot structure
    fp8 = w8 and w_down.dtype == FP8_DTYPE
    h_dim = w_down.shape[2]
    itemsize = jnp.dtype(h_sorted.dtype).itemsize
    bn = rs_block_n_for(
        h_dim, cfg.block_n, m_out, f_loc,
        jnp.dtype(out_dtype).itemsize, jnp.dtype(w_down.dtype).itemsize,
    )
    n_jn = h_dim // bn
    workspace = [
        jax.ShapeDtypeStruct((m_out, h_dim), out_dtype),            # own_buf
        jax.ShapeDtypeStruct((max(n - 1, 1), m_out, h_dim), out_dtype),
    ]
    from triton_dist_tpu.ops.common import resolve_spans

    # combine-side chunk schedule (ISSUE 4): spans over the pushed slab's
    # m_out rows, quantized to 128 so chunk boundaries stay tile-aligned;
    # a single-span schedule (incl. chunk=1 and world-1) emits the legacy
    # whole-slab push protocol, bit for bit. span_policy (ISSUE 14)
    # dispatches synthesized tilings/orderings — the combine consumes
    # chunks by slot index, so order-permuting policies are valid here
    spans = resolve_spans(
        m_out, max(1, int(getattr(cfg, "chunks_per_shard", 1))) if n > 1 else 1,
        128, policy=getattr(cfg, "span_policy", "contig"), world=n,
        side="moe_rs",
    )
    kernel = make_moe_rs_overlap_kernel(
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, m_out=m_out,
        out_dtype=out_dtype, spans=spans, ragged=ragged,
        panel=_panel_for(bm) if ragged else 0,
        fmt=OperandFormat(w8 and not fp8, fp8),
    )
    if len(spans) > 1:
        push_scratch = [
            pltpu.SemaphoreType.DMA((2, len(spans))),   # stage_sems
            pltpu.SemaphoreType.DMA((2,)),              # local_sem
            pltpu.SemaphoreType.DMA((max(n - 1, 1), n_jn, len(spans))),
            # pure chunk-signal slots (REGULAR; armed watchdog only)
            pltpu.SemaphoreType.REGULAR((max(n - 1, 1), n_jn, len(spans))),
        ]
    else:
        push_scratch = [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1), n_jn)),
        ]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # expert ids [n, nb]
        # HBM pinned: dynamic-offset slices must DMA from untiled HBM,
        # never compiler-chosen VMEM (see ag_group_gemm_overlap)
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # h_sorted
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # w_down
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # dst_ids
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # w_rows
    ]
    args = [expert_ids, h_sorted, w_down, dst_ids, w_rows]
    if ragged:
        # the per-(rank, block) live-row map rides SMEM next to the ids
        in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(1, valid_rows.astype(jnp.int32))
    if w8:
        # the scale bank rides HBM right after the int8 weight pool
        idx = 3 + (1 if ragged else 0)
        in_specs.insert(idx, pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM))
        args.insert(idx, scale.astype(jnp.float32))
    weight_scratch = [pltpu.VMEM((2, f_loc, bn), w_down.dtype)]
    wsem_scratch = [pltpu.SemaphoreType.DMA((2,))]
    if w8:
        weight_scratch.append(pltpu.VMEM((2, 1, bn), jnp.float32))
        wsem_scratch.append(pltpu.SemaphoreType.DMA((2,)))
    outs = dist_pallas_call(
        kernel,
        name="moe_reduce_rs_overlap",
        out_shape=(
            jax.ShapeDtypeStruct((m_out, h_dim), out_dtype),
            *workspace,
        ),
        in_specs=in_specs,
        out_specs=tuple(
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM) for _ in range(3)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bm, f_loc), h_sorted.dtype),
            *weight_scratch,
            pltpu.VMEM((2, m_out, bn), out_dtype),
            pltpu.VMEM((nb, bm), jnp.int32),
            pltpu.VMEM((nb, bm), jnp.float32),
            pltpu.VMEM((m_out, bn), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            *wsem_scratch,
            pltpu.SemaphoreType.DMA(()),
            *push_scratch,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * t_pad_tot * f_loc * h_dim
            + 2 * n * n_jn * nb * m_out * bm * bn,
            bytes_accessed=(
                t_pad_tot * f_loc + (2 * n) * m_out * h_dim
            ) * itemsize
            + w_down.shape[0] * f_loc * h_dim * w_down.dtype.itemsize,
            transcendentals=0,
        ),
        vmem_limit_bytes=min(
            2 * bm * f_loc * itemsize
            + 2 * f_loc * bn * jnp.dtype(w_down.dtype).itemsize
            + (2 * jnp.dtype(out_dtype).itemsize + 4) * m_out * bn
            + 8 * 2**20,
            100 * 2**20,
        ),
        uses_barrier=n > 1,
        interpret=interpret,
    )(*args)
    return outs[0]


def moe_reduce_rs_overlap(
    h_sorted: jax.Array,
    w_down: jax.Array,
    expert_ids: jax.Array,
    dst_ids: jax.Array,
    w_rows: jax.Array,
    *,
    axis: str = "tp",
    m_out: int,
    valid_rows: jax.Array | None = None,
    scale: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    out_dtype: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """Single-kernel overlapped MoE down-projection + combine + RS (call
    inside shard_map). h_sorted: ``[n*t_pad_loc, f_loc]`` rank-major aligned
    rows (the fused up-projection's output); w_down: ``[E, f_loc, H]``;
    expert_ids ``[n, nb]``, and ``(dst_ids, w_rows)`` ``[n, nb, bm]`` from
    :func:`~triton_dist_tpu.ops.moe_utils.ranked_scatter_meta`. ``scale``
    (or ``config.w8`` for on-the-fly quantization) streams int8 ``W_down``
    slabs at half the HBM bytes. Returns ``[m_out, H]`` — this PE's
    fully-reduced token chunk."""
    from triton_dist_tpu import resilience

    cfg = config or GroupGemmConfig()
    out_dtype = out_dtype or h_sorted.dtype
    n = _axis_size((axis))
    t_pad_tot = h_sorted.shape[0]
    t_pad_loc = t_pad_tot // n
    nb = expert_ids.shape[1]
    bm = t_pad_loc // nb
    assert bm == cfg.block_m, (bm, cfg.block_m)
    if cfg.backend != "pallas":
        raise ValueError(
            "the ragged_dot sentinel backend has no fused overlap form — "
            "route it through the sequential composition (tp_moe_mlp does "
            "this automatically); timing the Pallas pipeline under the "
            "sentinel's label would falsify the A/B"
        )
    ragged = bool(cfg.ragged)
    if ragged and valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs the ranked alignment's "
            "valid_rows map (moe_align_ranked(..., ragged=True))"
        )
    w_down, scale = resolve_w8(w_down, scale, cfg)
    if scale is not None:
        assert scale.shape == (w_down.shape[0], 1, w_down.shape[2]), (
            scale.shape, w_down.shape,
        )
    # span-policy fence BEFORE the guard ladder (ISSUE 14): an unknown
    # policy is a config error that must fail loudly, not a kernel
    # failure for guarded_call to downgrade to the golden path
    from triton_dist_tpu.ops.common import validate_span_policy

    validate_span_policy(getattr(cfg, "span_policy", "contig"), "moe_rs")
    return resilience.guarded_call(
        "moe_reduce_rs_overlap",
        functools.partial(_moe_rs_overlap_fused, cfg=cfg, interpret=interpret),
        _moe_rs_overlap_xla,
        h_sorted, w_down, scale, expert_ids, dst_ids, w_rows, axis=axis,
        ragged=ragged, valid_rows=valid_rows, m_out=m_out,
        out_dtype=out_dtype,
    )


def moe_reduce_rs_op(
    h_sorted: jax.Array,
    w_down: jax.Array,
    sorted_token_ids: jax.Array,
    expert_ids: jax.Array,
    topk_weights: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    assume_bijective: bool = True,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: `h_sorted` ``[t_pad, F]`` with F sharded over
    `axis`, `w_down` ``[E, F, H]`` sharded on F; alignment arrays and
    weights replicated. Result ``[n_tokens, H]`` sharded on tokens.

    ``assume_bijective=False`` for externally-built capacity-style
    alignments whose slots may be dropped to the sentinel — see
    :func:`triton_dist_tpu.ops.moe_utils.scatter_add_unsorted`."""
    n_tokens = topk_weights.shape[0]
    topk = topk_weights.shape[1]

    def fn(h, w, sti, eid, tw):
        # every block inside an expert's padded segment has >=1 valid row,
        # so valid-block count * block_m recovers num_tokens_post_pad
        cfg_ = config or GroupGemmConfig()
        if cfg_.ragged and not assume_bijective:
            # capacity-style alignments DROP slots to the sentinel
            # mid-block, which breaks the valid-rows-are-a-block-prefix
            # contract the ragged kernels skip on — degrade to the padded
            # schedule (correct everywhere) rather than skip live rows
            cfg_ = dataclasses.replace(cfg_, ragged=False)
        bm = sti.shape[0] // eid.shape[0]
        block_valid = jnp.any(
            sti.reshape(-1, bm) < n_tokens * topk, axis=1
        )
        alignment = MoEAlignment(
            sorted_token_ids=sti, expert_ids=eid,
            num_tokens_post_pad=(jnp.sum(block_valid) * bm).astype(jnp.int32),
            # externally-built alignment: reconstruct the ragged live-row
            # map from the sentinel layout when the config asks for it
            valid_rows=(
                valid_rows_from_sorted(sti, bm, n_tokens * topk)
                if cfg_.ragged else None
            ),
        )
        return moe_reduce_rs(
            h, w, alignment, tw, axis=axis, n_tokens=n_tokens,
            config=cfg_, assume_bijective=assume_bijective,
            interpret=interpret,
        )

    return jit_shard_map(
        fn, mesh,
        (
            P(None, axis),
            P(None, axis, None),
            P(None),
            P(None),
            P(None, None),
        ),
        P(axis, None),
        key=(
            "moe_reduce_rs", axis, config, n_tokens, topk, assume_bijective,
            str(interpret),
        ),
    )(h_sorted, w_down, sorted_token_ids, expert_ids, topk_weights)


# block_m is pinned by the caller-provided alignment (128 = moe_align
# default); the sweep covers the N/K tiling of the grouped GEMM. FIRST
# entry = best-known default (applied sweep-free under cached_or_first).
# Ragged twins (ISSUE 5) strictly after their padded originals, w8 twins
# (ISSUE 7) strictly after their bf16 twins (the no-regression ordering
# invariant).
MOE_RS_TUNE_SPACE = (
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 1024, 1024),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(128, 1024, 512, ragged=True),
    GroupGemmConfig(128, 1024, 512, w8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, w8=True),
    # fp8 axis (ISSUE 19): fp8_e4m3 W_down slabs at quarter-rate HBM
    # bytes — registered strictly after their w8 twins (legacy < w8 < fp8,
    # append-only)
    GroupGemmConfig(128, 1024, 512, fp8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, fp8=True),
) + _admitted_tune_extension("moe_reduce_rs")
# ^ SYNTHESIZED schedules (ISSUE 14): the standing registry of proved
# span policies (triton_dist_tpu/synth/admitted.py) appends STRICTLY
# AFTER every legacy candidate — the no-regression ordering invariant
# (docs/autotuner.md; pinned by tests/test_synth.py). analysis/sweep.py
# enumerates this constant, so protocol_lint proves them permanently.

moe_reduce_rs_op = contextual_autotune(MOE_RS_TUNE_SPACE, name="moe_reduce_rs")(
    moe_reduce_rs_op
)
