"""Ring attention — sequence-parallel *prefill* for long context.

The reference implements SP only for decode (KV-sharded flash-decode +
lse merge, SURVEY.md §5: "Prefill-side ring attention / Ulysses ... are
NOT implemented"); this framework treats long-context as first-class, so
prefill SP is built on the same one-sided-put layer the other kernels use.

Algorithm (blockwise ring attention, Liu et al. 2023): q stays put,
(k, v) chunks rotate around the ring. At step ``s`` PE ``me`` holds the
chunk of rank ``(me - s) mod n``; it starts forwarding that chunk right —
the ICI transfer rides under the MXU work — then runs blockwise attention
of its local q against the chunk, carrying the online-softmax state
``(m, l, acc)`` in HBM across steps. The final step's epilogue normalizes
``acc / l``. Causal masking is positional (global offsets), so any chunk
arrival order would be correct; the ring order merely makes it efficient.

The decode-side combine (flash_decode.combine_partials) is the same
algebra — this kernel is its prefill-scale sibling.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size

NEG_INF = float("-inf")


@dataclasses.dataclass(frozen=True)
class RingAttentionConfig:
    block_q: int = 512
    block_kv: int = 512


def _tile_offset(rank, tile_start, *, s_loc: int, n: int, layout: str):
    """Global-position offset of a tile starting at local row `tile_start`
    on (q or kv chunk) owner `rank`: global = offset + local_row.

    - "contig": PE r owns rows [r*s_loc, (r+1)*s_loc).
    - "zigzag": PE r owns stripes r and 2n-1-r of length s_loc/2 — the
      causal-load-balanced layout (every PE sees the same mix of early and
      late positions, so masked-out work is even across the ring instead
      of concentrated on the last PE). Tiles never straddle the stripe
      boundary (block sizes divide s_loc/2).
    """
    if layout == "contig":
        return rank * s_loc
    s_half = s_loc // 2
    return jnp.where(
        tile_start < s_half,
        rank * s_half,                        # stripe r
        (2 * n - 1 - rank) * s_half - s_half,  # stripe 2n-1-r
    )


def _attn_step_pipeline(
    bh: int, s_loc: int, d: int, bq: int, bk: int,
    m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
    q_rank, kv_rank, n: int, layout: str, first_step: bool,
):
    """One ring step: blockwise attention of local q vs the current kv
    chunk. The (m, l, acc) state persists across ring steps in HBM; m/l use
    a lane-broadcast minor dim of 128 (Mosaic cannot slice 1-wide minors).
    State blocks are kv-invariant, so they move once per q tile — KV block
    traffic dominates by a factor of n_q_tiles."""
    nq, nkv = s_loc // bq, s_loc // bk

    def body(q_blk, k_blk, v_blk, m_in, l_in, acc_in, m_out, l_out, acc_out):
        qi, kj = pl.program_id(1), pl.program_id(2)

        @pl.when(kj == 0)
        def _():
            if first_step:
                m_scr[:] = jnp.full_like(m_scr, NEG_INF)
                l_scr[:] = jnp.zeros_like(l_scr)
                acc_scr[:] = jnp.zeros_like(acc_scr)
            else:
                m_scr[:] = m_in[0, :, :1]
                l_scr[:] = l_in[0, :, :1]
                acc_scr[:] = acc_in[0]

        q = q_blk[0].astype(jnp.float32) * scale          # [bq, d]
        k = k_blk[0].astype(jnp.float32)                  # [bk, d]
        v = v_blk[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                  # [bq, bk]
        if causal:
            q_off = _tile_offset(q_rank, qi * bq, s_loc=s_loc, n=n, layout=layout)
            kv_off = _tile_offset(kv_rank, kj * bk, s_loc=s_loc, n=n, layout=layout)
            q_pos = q_off + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            kv_pos = kv_off + kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(kv_pos <= q_pos, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # fully-masked tile: m_new stays -inf; exp(-inf - -inf) would be
        # NaN, so pin the shift to a finite value in that case
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(m_prev - shift)
        p = jnp.exp(s - shift)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.where(jnp.isfinite(m_new), m_new, m_prev)

        @pl.when(kj == nkv - 1)
        def _():
            m_out[0] = jnp.broadcast_to(m_scr[:], (bq, 128))
            l_out[0] = jnp.broadcast_to(l_scr[:], (bq, 128))
            acc_out[0] = acc_scr[:]

    state_spec = pl.BlockSpec((1, bq, 128), lambda i, qi, kj: (i, qi, 0))
    acc_spec = pl.BlockSpec((1, bq, d), lambda i, qi, kj: (i, qi, 0))
    return pltpu.emit_pipeline(
        body,
        grid=(bh, nq, nkv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, qi, kj: (i, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda i, qi, kj: (i, kj, 0)),
            pl.BlockSpec((1, bk, d), lambda i, qi, kj: (i, kj, 0)),
            state_spec, state_spec, acc_spec,
        ],
        out_specs=[state_spec, state_spec, acc_spec],
    )


def _ring_attention_kernel(
    q_ref, k_ref, v_ref, out_ref, kv_land, acc_buf, m_buf, l_buf,
    m_scr, l_scr, acc_scr, send_sems, recv_sems,
    *, axis: str, n: int, cfg: RingAttentionConfig, scale: float,
    causal: bool, layout: str, out_dtype,
):
    me = shmem.my_pe(axis)
    bh, s_loc, d = q_ref.shape
    # zigzag: tiles must not straddle the stripe boundary at s_loc/2
    block_span = s_loc // 2 if layout == "zigzag" else s_loc
    bq = pick_block(block_span, cfg.block_q)
    bk = pick_block(block_span, cfg.block_kv)

    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    descs = []
    for s in range(n):
        chunk_rank = jax.lax.rem(me - s + 2 * n, n)
        if s > 0:
            # chunk landed in slot s-1 during step s-1 (two transfers: k, v)
            descs[2 * (s - 1)].wait_recv()
            descs[2 * (s - 1) + 1].wait_recv()
        k_src = k_ref if s == 0 else kv_land.at[s - 1, 0]
        v_src = v_ref if s == 0 else kv_land.at[s - 1, 1]
        if s < n - 1:
            # forward the chunk before computing on it: ICI rides under MXU
            descs.append(
                shmem.putmem_nbi_block(
                    kv_land.at[s, 0], k_src, right, axis,
                    send_sems.at[2 * s], recv_sems.at[2 * s],
                )
            )
            descs.append(
                shmem.putmem_nbi_block(
                    kv_land.at[s, 1], v_src, right, axis,
                    send_sems.at[2 * s + 1], recv_sems.at[2 * s + 1],
                )
            )
        pipeline = _attn_step_pipeline(
            bh, s_loc, d, bq, bk, m_scr, l_scr, acc_scr,
            scale=scale, causal=causal, q_rank=me,
            kv_rank=chunk_rank, n=n, layout=layout, first_step=(s == 0),
        )
        pipeline(
            q_ref, k_src, v_src, m_buf, l_buf, acc_buf, m_buf, l_buf, acc_buf
        )
    shmem.quiet(*descs)

    # epilogue: out = acc / l
    nq = s_loc // bq

    def norm_body(acc_in, l_in, o_blk):
        l = l_in[0, :, :1]
        o_blk[0] = (acc_in[0] / jnp.maximum(l, 1e-30)).astype(out_dtype)

    pltpu.emit_pipeline(
        norm_body,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, qi: (i, qi, 0)),
            pl.BlockSpec((1, bq, 128), lambda i, qi: (i, qi, 0)),
        ],
        out_specs=[pl.BlockSpec((1, bq, d), lambda i, qi: (i, qi, 0))],
    )(acc_buf, l_buf, out_ref)


def zigzag_permutation(n: int, s_tot: int):
    """Row permutation taking the NATURAL sequence order to the zigzag
    sharding order: after ``x[perm]``, contiguous shard ``r`` (of ``n``)
    holds stripes ``r`` and ``2n-1-r`` (each ``s_tot / 2n`` rows) — the
    causal-load-balanced assignment. Returns (perm, inverse)."""
    import numpy as _np

    if s_tot % (2 * n) != 0:
        raise ValueError(
            f"zigzag needs s_tot divisible by 2*n: {s_tot} % {2 * n} != 0"
        )
    s_half = s_tot // (2 * n)
    order = []
    for r in range(n):
        order.extend(range(r * s_half, (r + 1) * s_half))
        order.extend(range((2 * n - 1 - r) * s_half, (2 * n - r) * s_half))
    perm = _np.asarray(order, _np.int32)
    inv = _np.empty_like(perm)
    inv[perm] = _np.arange(perm.shape[0], dtype=_np.int32)
    return perm, inv


def zigzag_positions(me, n: int, s_loc: int):
    """Global positions of PE `me`'s local rows under the zigzag layout
    (feed to RoPE / loss instead of ``me*s_loc + arange``)."""
    s_half = s_loc // 2
    r = jnp.arange(s_loc, dtype=jnp.int32)
    return jnp.where(
        r < s_half,
        me * s_half + r,
        (2 * n - 1 - me) * s_half + (r - s_half),
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "tp",
    causal: bool = True,
    config: RingAttentionConfig | None = None,
    layout: str = "contig",
    return_lse: bool = False,
    interpret: Any = None,
):
    """Sequence-parallel attention over an s-sharded q/k/v (call inside
    ``jax.shard_map``).

    q, k, v: ``[b, h, s_loc, d]`` — the local sequence shard (MHA; GQA via
    repeating kv heads host-side). Returns ``[b, h, s_loc, d]`` in q.dtype
    (plus the per-row log-sum-exp ``[b, h, s_loc]`` f32 if `return_lse` —
    the residual the custom backward consumes, ops/grads.py).
    Golden: full (causal) attention over the gathered sequence.

    ``layout="zigzag"``: the shards are stripe PAIRS (shard r = stripes r
    and 2n-1-r of the global sequence; see :func:`zigzag_permutation`) —
    causal masking then discards the same fraction of work on every PE,
    instead of PE 0 sitting ~idle while PE n-1 computes the full lower
    triangle. Same collective traffic; up to ~2x less wall-clock tail at
    large n for causal prefill.
    """
    cfg = config or RingAttentionConfig()
    n = _axis_size((axis))
    b, h, s_loc, d = q.shape
    if layout not in ("contig", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag" and s_loc % 2 != 0:
        raise ValueError(f"zigzag needs an even s_loc, got {s_loc}")
    scale = 1.0 / math.sqrt(d)
    bh = b * h
    q3 = q.reshape(bh, s_loc, d)
    k3 = k.reshape(bh, s_loc, d)
    v3 = v.reshape(bh, s_loc, d)
    block_span = s_loc // 2 if layout == "zigzag" else s_loc
    bq = pick_block(block_span, cfg.block_q)
    bk = pick_block(block_span, cfg.block_kv)
    n_steps = max(n - 1, 1)
    outs = dist_pallas_call(
        functools.partial(
            _ring_attention_kernel, axis=axis, n=n, cfg=cfg, scale=scale,
            causal=causal, layout=layout, out_dtype=q.dtype,
        ),
        name="ring_attention",
        out_shape=(
            jax.ShapeDtypeStruct((bh, s_loc, d), q.dtype),
            jax.ShapeDtypeStruct((n_steps, 2, bh, s_loc, d), k.dtype),  # kv ring
            jax.ShapeDtypeStruct((bh, s_loc, d), jnp.float32),   # acc
            jax.ShapeDtypeStruct((bh, s_loc, 128), jnp.float32),  # m (lanes)
            jax.ShapeDtypeStruct((bh, s_loc, 128), jnp.float32),  # l (lanes)
        ),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY) for _ in range(5)),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.SemaphoreType.DMA((2 * n_steps,)),
            pltpu.SemaphoreType.DMA((2 * n_steps,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * s_loc * (n * s_loc) * d,
            bytes_accessed=(3 + 2 * n) * bh * s_loc * d * q.dtype.itemsize,
            transcendentals=bh * s_loc * n * s_loc,
        ),
        uses_barrier=n > 1,
        interpret=interpret,
    )(q3, k3, v3)
    out = outs[0].reshape(b, h, s_loc, d)
    if not return_lse:
        return out
    # m/l live lane-replicated in [bh, s_loc, 128] buffers (outs[3], outs[4])
    lse = (
        outs[3][..., 0] + jnp.log(jnp.maximum(outs[4][..., 0], 1e-30))
    ).reshape(b, h, s_loc)
    return out, lse


def ring_attention_op(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    causal: bool = True,
    config: RingAttentionConfig | None = None,
    layout: str = "contig",
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: q/k/v ``[b, h, S, d]`` sharded on the sequence dim
    (pre-permuted with :func:`zigzag_permutation` when ``layout="zigzag"``)."""
    fn = functools.partial(
        ring_attention, axis=axis, causal=causal, config=config,
        layout=layout, interpret=interpret,
    )
    spec = P(None, None, axis, None)
    return jit_shard_map(
        fn, mesh, (spec, spec, spec), spec,
        key=("ring_attention", axis, causal, config, layout, str(interpret)),
    )(q, k, v)
