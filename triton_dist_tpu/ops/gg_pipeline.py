"""One pipeline emitter for the fused grouped-GEMM family (ISSUE 7;
architecture note: docs/moe_overlap.md "One pipeline emitter").

ONE generator per family weaves three composable trace-time policies into
the kernel body, retiring PR 3-5's legacy x chunked x ragged twin matrix;
the host entries in ``ops/{group_gemm,allgather_group_gemm,
moe_reduce_rs}.py`` are thin spec builders over it:

- **schedule** — the ``spans`` chunk schedule: one span emits the legacy
  shard-granular ring/push protocol, several emit PR 3/4's per-(step,
  chunk) signal-slot protocol (armed-watchdog ``chunk_wait`` path);
- **tile validity** — ``vid_ref`` absent (padded full tiles) vs present
  (PR 5's ``pl.when``-guarded ``panel``-row dots, dead rows exact zeros);
- **operand format** — :class:`OperandFormat`: bf16 (identity) vs w8
  (int8 B stream at half the bytes + per-(expert, out-column) f32 scale
  fold BEFORE any ragged mask, the legacy w8-kernel ordering) vs fp8
  (ISSUE 19: fp8_e4m3 B stream — the SAME slot structure as w8, scale
  rows riding the same local weight-prefetch chain; only the payload
  dtype and the host-side quantizer differ, so the kernel trace is the
  w8 trace with an fp8 B operand).

Migration contract: at chunk=1 / ragged=False / bf16 every generated body
traces the SAME statement sequence as the retired legacy kernels, so
outputs are bit-identical — pinned by ``tests/test_emitter.py`` against
verbatim copies of the legacy bodies. w8/fp8 add weight-scale DMAs (local
HBM) and NO signal edges.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block


@dataclasses.dataclass(frozen=True)
class OperandFormat:
    """Weight operand-format policy. The default is the identity (the
    legacy trace, bit for bit); ``w8=True`` upcasts the int8 B tile to the
    activation dtype on the VPU under the halved DMA time and folds the
    per-(expert, out-column) scale into the f32 accumulator BEFORE any
    ragged dead-row mask (live rows match the grid w8 kernel bit for
    bit); ``fp8=True`` (ISSUE 19) is the SAME policy over an fp8_e4m3
    payload — the upcast/fold trace is shared verbatim (``scaled``), the
    formats differ only in which bank dtype the host quantizer emits and
    in the tune-tuple identity the autotuner ranks. Construction keeps
    the historical positional form ``OperandFormat(w8)`` working."""

    w8: bool = False
    fp8: bool = False

    def __post_init__(self):
        if self.w8 and self.fp8:
            raise ValueError("OperandFormat: w8 and fp8 are exclusive")

    @property
    def scaled(self) -> bool:
        """True when a per-(expert, out-column) scale row rides the weight
        stream — the shared structural predicate of the w8 and fp8
        formats (scale slots, fold sites, ref layouts)."""
        return self.w8 or self.fp8

    def mxu_b(self, b_tile, a_dtype):
        """The B tile as the MXU consumes it."""
        return b_tile.astype(a_dtype) if self.scaled else b_tile

    def fold(self, acc, s_row):
        """Finalize an f32 accumulator/tile: fold the scale row (shape
        broadcastable over rows) under w8/fp8; identity otherwise."""
        return acc * s_row if self.scaled else acc


BF16 = OperandFormat(False)
FP8 = OperandFormat(False, True)


# ---------------------------------------------------------------------------
# Grid kernels (ops/group_gemm.py): forward (+w8, +ragged) and dW (+ragged)
# ---------------------------------------------------------------------------

def make_group_gemm_kernel(*, n_k: int, out_dtype, act_fn=None,
                           fmt: OperandFormat = BF16, ragged: bool = False,
                           panel: int = 0):
    """The scalar-prefetch grid grouped-GEMM kernel for one
    (format, validity) choice — replaces the four hand-written twins
    ``_group_gemm{,_w8}{,_ragged}_kernel``.

    Ref layout (Pallas passes positionally): ``e_ref, [v_ref], a_ref,
    b_ref, [s_ref], o_ref, acc_ref`` — ``v_ref`` present iff ragged,
    ``s_ref`` iff w8."""

    def kernel(*refs):
        if ragged:
            e_ref, v_ref, a_ref, b_ref, *rest = refs
        else:
            e_ref, a_ref, b_ref, *rest = refs
            v_ref = None
        if fmt.scaled:
            s_ref, o_ref, acc_ref = rest
        else:
            (o_ref, acc_ref), s_ref = rest, None
        del e_ref  # consumed by the index maps
        kk = pl.program_id(2)
        if ragged:
            i = pl.program_id(1)
            valid = v_ref[i]

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        def _a(tile):
            if act_fn is not None:
                # fused producer activation on the A tile: VPU work under
                # the B-operand DMA (f32, cast back — exact standalone math)
                return act_fn(tile.astype(jnp.float32)).astype(a_ref.dtype)
            return tile

        if not ragged:
            acc_ref[:] += jnp.dot(
                _a(a_ref[:]), fmt.mxu_b(b_ref[0], a_ref.dtype),
                preferred_element_type=jnp.float32,
            )
        else:
            # panel-guarded dots: a panel wholly past valid_rows costs
            # zero MXU time; the output store zero-masks the tail's dead rows
            bm = acc_ref.shape[0]
            for p in range(bm // panel):
                @pl.when(p * panel < valid)
                def _(p=p):
                    acc_ref[pl.ds(p * panel, panel), :] += jnp.dot(
                        _a(a_ref[pl.ds(p * panel, panel), :]),
                        fmt.mxu_b(b_ref[0], a_ref.dtype),
                        preferred_element_type=jnp.float32,
                    )

        @pl.when(kk == n_k - 1)
        def _():
            res = fmt.fold(
                acc_ref[:], s_ref[0] if s_ref is not None else None
            )
            if not ragged:
                o_ref[:] = res.astype(out_dtype)
            else:
                # dead rows exact zeros (0·junk is fine, 0·NaN is not);
                # the scale fold happened above, BEFORE this mask
                rows = jax.lax.broadcasted_iota(jnp.int32, acc_ref.shape, 0)
                o_ref[:] = jnp.where(rows < valid, res, 0.0).astype(out_dtype)

    return kernel


def make_group_gemm_dw_kernel(*, ragged: bool = False, panel: int = 0):
    """The transpose grouped GEMM (``dW[e] += A_iᵀ @ G_i`` over each
    expert's consecutive row-block run) — replaces
    ``_group_gemm_dw{,_ragged}_kernel``. Ref layout: ``e_ref, [v_ref],
    a_ref, g_ref, o_ref, acc_ref``. No w8 axis: weight gradients are
    computed against the full-precision bank (w8 is a forward/serving
    format — ``ops.grads`` strips it from every backward config)."""

    def kernel(*refs):
        if ragged:
            e_ref, v_ref, a_ref, g_ref, o_ref, acc_ref = refs
        else:
            e_ref, a_ref, g_ref, o_ref, acc_ref = refs
            v_ref = None
        i = pl.program_id(2)
        if ragged:
            valid = v_ref[i]
        first_of_run = jnp.logical_or(
            i == 0, e_ref[jnp.maximum(i - 1, 0)] != e_ref[i]
        )

        @pl.when(first_of_run)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        if not ragged:
            acc_ref[:] += jax.lax.dot_general(
                a_ref[:].astype(jnp.float32), g_ref[:].astype(jnp.float32),
                (((0,), (0,)), ((), ())),       # contract the bm rows: AᵀG
                preferred_element_type=jnp.float32,
            )
        else:
            # dead panels skip the contraction; tail masked rows ZEROED on
            # A before AᵀG (a pad row's a·g has no downstream mask)
            bm = a_ref.shape[0]
            for p in range(bm // panel):
                @pl.when(p * panel < valid)
                def _(p=p):
                    a = a_ref[pl.ds(p * panel, panel), :].astype(jnp.float32)
                    rows = (
                        jax.lax.broadcasted_iota(jnp.int32, a.shape, 0)
                        + p * panel
                    )
                    a = jnp.where(rows < valid, a, 0.0)
                    acc_ref[:] += jax.lax.dot_general(
                        a,
                        g_ref[pl.ds(p * panel, panel), :].astype(jnp.float32),
                        (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
        o_ref[0] = acc_ref[:]

    return kernel


# ---------------------------------------------------------------------------
# Shared ragged block emitters (PR 5's panel rule, now format-aware)
# ---------------------------------------------------------------------------

def _ragged_block_emit(
    a_rows, b_tile, out_stage, oslot_base, v, bm, bn, panel, out_dtype,
    fmt: OperandFormat = BF16, s_row=None,
):
    """Ragged compute+stage for one AG-overlap row block: ``pl.when``-
    guarded live ``panel``-row dots, dead rows/panels staged as exact
    zeros (a downstream 0-weight combine can never meet NaN junk);
    ``a_rows`` maps a panel's row span to its A rows, ``oslot_base`` is
    the block's first staged row. Under w8 the scale row folds into each
    live panel BEFORE its mask (grid-kernel ordering)."""
    for p in range(bm // panel):
        live = p * panel < v

        @pl.when(live)
        def _(p=p):
            yp = jnp.dot(
                a_rows(p * panel, panel), b_tile,
                preferred_element_type=jnp.float32,
            )
            yp = fmt.fold(yp, s_row)
            rows = (
                jax.lax.broadcasted_iota(jnp.int32, (panel, bn), 0)
                + p * panel
            )
            out_stage[pl.ds(oslot_base + p * panel, panel), :] = jnp.where(
                rows < v, yp, 0.0
            ).astype(out_dtype)

        @pl.when(jnp.logical_not(live))
        def _(p=p):
            out_stage[pl.ds(oslot_base + p * panel, panel), :] = jnp.zeros(
                (panel, bn), out_dtype
            )


def _moe_ragged_blk(
    h_buf, w_buf, ids_v, w_v, partial_ref, hslot, slot, b, v, m_out, bm,
    panel, cdt, fmt: OperandFormat = BF16, s_row=None,
):
    """Ragged block step of the fused down-projection: the dot AND the
    one-hot combine run only for the block's live ``panel``-row panels
    (the combine's contraction dim IS the block rows); partial_ref is
    accumulative so skipping is exact. Under w8 the scale row folds into
    each live panel's f32 dot before the combine consumes it."""
    d = ids_v[b]
    w_r = w_v[b]
    for p in range(bm // panel):
        @pl.when(p * panel < v)
        def _(p=p):
            yp = jnp.dot(
                h_buf[hslot, pl.ds(p * panel, panel), :],
                fmt.mxu_b(w_buf[slot], cdt),
                preferred_element_type=jnp.float32,
            )
            yp = fmt.fold(yp, s_row)
            dp = d[p * panel:(p + 1) * panel]
            wp = w_r[p * panel:(p + 1) * panel]
            sel = jax.lax.broadcasted_iota(
                jnp.int32, (m_out, panel), 0
            ) == dp[None, :]
            scat = jnp.where(sel, wp[None, :], 0.0).astype(cdt)
            partial_ref[:] += jnp.dot(
                scat, yp.astype(cdt), preferred_element_type=jnp.float32
            )


# ---------------------------------------------------------------------------
# Fused AG-GroupGEMM overlap (ops/allgather_group_gemm.py)
# ---------------------------------------------------------------------------

def make_ag_overlap_kernel(*, axis: str, n: int, nb: int, n_jn: int, bn: int,
                           bpg: int, bm: int, out_dtype, spans,
                           ragged: bool = False, panel: int = 0,
                           fmt: OperandFormat = BF16):
    """Fused ring-AG + grouped GEMM over pre-sorted slabs — replaces the
    four twins ``_ag_group_gemm_overlap{,_chunked}{,_ragged}_kernel``
    (schedule walkthrough: docs/moe_overlap.md). Single span = the legacy
    shard-granular ring bit for bit; several = the PR 4 chunk protocol (a
    gather-group DMA never prefetches across a chunk boundary); ragged =
    panel-guarded dots (no new signal edges); ``fmt.scaled`` = int8 weight
    slabs at half the bytes + a per-(expert, bn-slab) scale row on the
    SAME double-buffered prefetch chain, folded before staging.

    Ref layout: inputs ``eid, [vid], a, b, [s]``; outputs ``out, ag``;
    scratch ``a_all, b_buf, [s_buf], out_stage, copy_sem, send_sems,
    recv_sems, [sig_sems], gsems, bsem, [ssem], outsem`` (``[...]``
    present iff the policy needs it)."""
    chunked = len(spans) > 1

    def kernel(*refs):
        it = list(refs)
        eid_ref = it.pop(0)
        vid_ref = it.pop(0) if ragged else None
        a_ref = it.pop(0)
        b_ref = it.pop(0)
        s_ref = it.pop(0) if fmt.scaled else None
        out_ref = it.pop(0)
        ag_ref = it.pop(0)
        a_all = it.pop(0)
        b_buf = it.pop(0)
        s_buf = it.pop(0) if fmt.scaled else None
        out_stage = it.pop(0)
        copy_sem = it.pop(0)
        send_sems = it.pop(0)
        recv_sems = it.pop(0)
        sig_sems = it.pop(0) if chunked else None
        gsems = it.pop(0)
        bsem = it.pop(0)
        ssem = it.pop(0) if fmt.scaled else None
        (outsem,) = it

        me = shmem.my_pe(axis)
        t_pad_loc = nb * bm
        gq = bpg * bm                    # group quantum: spans align to it
        n_groups = (nb + bpg - 1) // bpg
        it_counter = [0]  # trace-time global (block, jn) iteration count

        def _b_start(e, jn_v, slot):
            """Weight-slab fetch: the [K, bn] B slab and (w8) its [1, bn]
            scale row ride the same prefetch chain and buffer slot."""
            pltpu.make_async_copy(
                b_ref.at[e, :, pl.ds(jn_v * bn, bn)], b_buf.at[slot],
                bsem.at[slot],
            ).start()
            if fmt.scaled:
                pltpu.make_async_copy(
                    s_ref.at[e, :, pl.ds(jn_v * bn, bn)], s_buf.at[slot],
                    ssem.at[slot],
                ).start()

        def _b_wait(e, jn_v, slot):
            # DMA sems are waited via a matching-byte-count descriptor
            pltpu.make_async_copy(
                b_ref.at[e, :, pl.ds(jn_v * bn, bn)], b_buf.at[slot],
                bsem.at[slot],
            ).wait()
            if fmt.scaled:
                pltpu.make_async_copy(
                    s_ref.at[e, :, pl.ds(jn_v * bn, bn)], s_buf.at[slot],
                    ssem.at[slot],
                ).wait()

        # n >= 2 always: the host entry dispatches world-1 to group_gemm
        local = pltpu.make_async_copy(
            a_ref, ag_ref.at[pl.ds(me * t_pad_loc, t_pad_loc)], copy_sem
        )
        local.start()
        local.wait()
        shmem.barrier_all(axis)
        right = jax.lax.rem(me + 1, n)

        # Weight-slab prefetch chain: the double-buffer slot carries across
        # chunks, groups AND ring steps (each boundary's first slab is
        # prefetched by the previous loop's `_iter` boundary arm, riding
        # under the ring-chunk wait); only the very first slab is cold.
        _b_start(eid_ref[me, 0], 0, 0)
        slot_carry = [jnp.int32(1)]  # traced carry: _iter's weight slot

        descs = []
        for s in range(n):
            c = jax.lax.rem(me - s + 2 * n, n)
            # landing view for the step-s forward (ISSUE 8 canary): the
            # left neighbor's step-s send — shard (me-1-s) mod n — lands
            # here and is consumed at step s+1 (the chunked ring
            # allgather's base_in arithmetic)
            base_in = jax.lax.rem(me - 1 - s + 2 * n, n) * t_pad_loc

            def _group_desc(g, slot, c=c):
                base = g * bpg * bm
                cnt = min(bpg * bm, t_pad_loc - base)
                return pltpu.make_async_copy(
                    ag_ref.at[pl.ds(c * t_pad_loc + base, cnt), :],
                    a_all.at[slot, pl.ds(0, cnt), :],
                    gsems.at[slot],
                )

            chunk_handles = []
            for j, (off, rows) in enumerate(spans):
                if s > 0:
                    # chunk/shard j landed during step s-1's compute
                    if chunked:
                        descs[s - 1].wait_recv_chunk(j)
                    else:
                        descs[s - 1].wait_recv()
                if s < n - 1:
                    # forward before computing on it: ICI overlaps MXU
                    sl = pl.ds(c * t_pad_loc + off, rows)
                    if chunked:
                        chunk_handles.append(
                            shmem.putmem_signal2_nbi_block(
                                ag_ref.at[sl], ag_ref.at[sl], right, axis,
                                send_sems.at[s, j], recv_sems.at[s, j],
                                sig_sems.at[s, j], canary=True,
                            )
                        )
                    else:
                        descs.append(
                            shmem.putmem_nbi_block(
                                ag_ref.at[sl], ag_ref.at[sl], right, axis,
                                send_sems.at[s], recv_sems.at[s],
                            )
                        )
                g_lo = off // gq
                g_hi = n_groups if j == len(spans) - 1 else (off + rows) // gq
                _group_desc(g_lo, g_lo % 2).start()
                for g in range(g_lo, g_hi):  # python: group sizes static
                    gslot = g % 2
                    if g + 1 < g_hi:
                        # within-chunk prefetch only: a cross-chunk
                        # group's rows may not have landed yet
                        _group_desc(g + 1, 1 - gslot).start()
                    _group_desc(g, gslot).wait()
                    nb_g = min(bpg, nb - g * bpg)  # blocks in this group

                    # boundary weight-prefetch target (weights are local
                    # HBM, chunk-independent); None = end of schedule
                    if g + 1 < n_groups:
                        e_next = eid_ref[c, (g + 1) * bpg]
                    elif s + 1 < n:
                        c_next = jax.lax.rem(me - (s + 1) + 2 * n, n)
                        e_next = eid_ref[c_next, 0]
                    else:
                        e_next = None
                    it_base = it_counter[0]

                    def _iter(i, slot, g=g, gslot=gslot, nb_g=nb_g,
                              it_base=it_base, e_next=e_next, c=c):
                        jn = i // nb_g
                        b_rel = jax.lax.rem(i, nb_g)
                        b = g * bpg + b_rel
                        e = eid_ref[c, b]
                        prev_rel = jax.lax.rem(jax.lax.max(i - 1, 0), nb_g)
                        fresh = jnp.logical_or(
                            i == 0,
                            jnp.logical_or(
                                jn != jax.lax.max(i - 1, 0) // nb_g,
                                e != eid_ref[c, g * bpg + prev_rel],
                            ),
                        )
                        slot = jnp.where(fresh, 1 - slot, slot)

                        @pl.when(fresh)
                        def _():
                            _b_wait(e, jn, slot)

                        # prefetch the NEXT distinct weight slab while this
                        # dot runs (carries across chunk/group/step bounds)
                        nxt = i + 1
                        jn2 = nxt // nb_g
                        b2 = jax.lax.rem(nxt, nb_g)
                        e2 = eid_ref[c, g * bpg + jax.lax.min(b2, nb_g - 1)]
                        fresh2 = jnp.logical_and(
                            nxt < nb_g * n_jn,
                            jnp.logical_or(jn2 != jn, e2 != e),
                        )
                        jn2v = jn2
                        if e_next is not None:
                            # boundary arm: the last iteration prefetches
                            # the next group's/step's first slab into the
                            # buffer the boundary's i=0 `fresh` wait targets
                            boundary = nxt >= nb_g * n_jn
                            e2 = jnp.where(boundary, e_next, e2)
                            jn2v = jnp.where(boundary, 0, jn2)
                            fresh2 = jnp.logical_or(fresh2, boundary)

                        @pl.when(fresh2)
                        def _():
                            _b_start(e2, jn2v, 1 - slot)

                        if ragged:
                            s_row = s_buf[slot][0] if fmt.scaled else None
                        else:
                            y = jnp.dot(
                                a_all[gslot, pl.ds(b_rel * bm, bm), :],
                                fmt.mxu_b(b_buf[slot], a_ref.dtype),
                                preferred_element_type=jnp.float32,
                            )
                            y = fmt.fold(
                                y, s_buf[slot][0] if fmt.scaled else None
                            )
                        # out_stage slots alternate on the GLOBAL iter
                        # count (group counts may be odd); a slot's
                        # first-ever use has no pending store
                        gi = it_base + i
                        oslot = jax.lax.rem(gi, 2)

                        @pl.when(gi >= 2)
                        def _():
                            pltpu.make_async_copy(
                                out_stage.at[pl.ds(oslot * bm, bm), :],
                                out_ref.at[
                                    pl.ds(c * t_pad_loc + b * bm, bm),
                                    pl.ds(jn * bn, bn),
                                ],
                                outsem.at[oslot],
                            ).wait()

                        if not ragged:
                            out_stage[pl.ds(oslot * bm, bm), :] = y.astype(
                                out_dtype
                            )
                        else:
                            # panel-guarded dots write the staged tile;
                            # dead panels stage zeros AFTER the slot wait
                            _ragged_block_emit(
                                lambda off_, rows_: a_all[
                                    gslot, pl.ds(b_rel * bm + off_, rows_), :
                                ],
                                fmt.mxu_b(b_buf[slot], a_ref.dtype),
                                out_stage, oslot * bm, vid_ref[c, b],
                                bm, bn, panel, out_dtype, fmt, s_row,
                            )
                        pltpu.make_async_copy(
                            out_stage.at[pl.ds(oslot * bm, bm), :],
                            out_ref.at[
                                pl.ds(c * t_pad_loc + b * bm, bm),
                                pl.ds(jn * bn, bn),
                            ],
                            outsem.at[oslot],
                        ).start()
                        return slot

                    slot_carry[0] = jax.lax.fori_loop(
                        0, nb_g * n_jn, _iter, slot_carry[0]
                    )
                    it_counter[0] += nb_g * n_jn
            if chunked and s < n - 1:
                descs.append(shmem.ChunkedPutHandle(
                    chunk_handles,
                    recv_at=lambda off, rows, b=base_in: ag_ref.at[
                        pl.ds(b + off, rows)
                    ],
                    spans=spans,
                ))

        # drain final pending output stores, then local ring-put completion
        total_iters = n * nb * n_jn

        def _drain(oslot):
            pltpu.make_async_copy(
                out_stage.at[pl.ds(oslot * bm, bm), :],
                out_ref.at[pl.ds(0, bm), pl.ds(0, bn)],
                outsem.at[oslot],
            ).wait()

        if total_iters >= 1:
            _drain((total_iters - 1) % 2)
        if total_iters >= 2:
            _drain(total_iters % 2)
        shmem.quiet(*descs)

    return kernel


# ---------------------------------------------------------------------------
# Fused MoE-Reduce-RS overlap (ops/moe_reduce_rs.py)
# ---------------------------------------------------------------------------

def make_moe_rs_overlap_kernel(*, axis: str, n: int, nb: int, n_jn: int,
                               bn: int, m_out: int, out_dtype, spans,
                               ragged: bool = False, panel: int = 0,
                               fmt: OperandFormat = BF16):
    """Fused grouped-GEMM → weighted combine → reduce-scatter — replaces
    the four twins ``_moe_reduce_rs_overlap{,_chunked}{,_ragged}_kernel``
    (schedule walkthrough: docs/moe_overlap.md). Destination rank c's
    chunk is computed from ITS aligned rows, combined in VMEM (one-hot
    matmul), and pushed the moment its slab retires. Single span = the
    legacy whole-slab push bit for bit; several = the PR 4 chunked push on
    per-(step, slab, chunk) slots, consumed chunk by chunk; ragged = the
    panel rule on GEMM and combine both (the push schedule never consults
    valid_rows); ``fmt.scaled`` = int8 W_down slabs + scale rows on the same
    prefetch chain, folded before the combine consumes each tile.

    Ref layout: inputs ``eid, [vid], h, w, [s], dst, wrow``; outputs
    ``out, own_buf, landing``; scratch ``h_buf, w_buf, [s_buf],
    push_stage, ids_v, w_v, partial, hsem, wsem, [ssem], metasem`` then
    ``stage_sem, recv_sems`` (single span) or ``stage_sems, local_sem,
    recv_sems, sig_sems`` (chunked)."""
    chunked = len(spans) > 1

    def kernel(*refs):
        it = list(refs)
        eid_ref = it.pop(0)
        vid_ref = it.pop(0) if ragged else None
        h_ref = it.pop(0)
        w_ref = it.pop(0)
        s_ref = it.pop(0) if fmt.scaled else None
        dst_ref = it.pop(0)
        wrow_ref = it.pop(0)
        out_ref = it.pop(0)
        own_buf = it.pop(0)
        landing = it.pop(0)
        h_buf = it.pop(0)
        w_buf = it.pop(0)
        s_buf = it.pop(0) if fmt.scaled else None
        push_stage = it.pop(0)
        ids_v = it.pop(0)
        w_v = it.pop(0)
        partial_ref = it.pop(0)
        hsem = it.pop(0)
        wsem = it.pop(0)
        ssem = it.pop(0) if fmt.scaled else None
        metasem = it.pop(0)
        if chunked:
            stage_sems, local_sem, recv_sems, sig_sems = it
            stage_sem = None
        else:
            stage_sem, recv_sems = it
            stage_sems = local_sem = sig_sems = None

        me = shmem.my_pe(axis)
        t_pad_tot, f_loc = h_ref.shape
        t_pad_loc = t_pad_tot // n
        bm = t_pad_loc // nb
        cdt = h_ref.dtype
        if n > 1:
            shmem.barrier_all(axis)

        def _w_start(e, jn_v, slot):
            pltpu.make_async_copy(
                w_ref.at[e, :, pl.ds(jn_v * bn, bn)], w_buf.at[slot],
                wsem.at[slot],
            ).start()
            if fmt.scaled:
                pltpu.make_async_copy(
                    s_ref.at[e, :, pl.ds(jn_v * bn, bn)], s_buf.at[slot],
                    ssem.at[slot],
                ).start()

        def _w_wait(e, jn_v, slot):
            pltpu.make_async_copy(
                w_ref.at[e, :, pl.ds(jn_v * bn, bn)], w_buf.at[slot],
                wsem.at[slot],
            ).wait()
            if fmt.scaled:
                pltpu.make_async_copy(
                    s_ref.at[e, :, pl.ds(jn_v * bn, bn)], s_buf.at[slot],
                    ssem.at[slot],
                ).wait()

        def _issue_h(c, b, slot):
            pltpu.make_async_copy(
                h_ref.at[pl.ds(c * t_pad_loc + b * bm, bm), :],
                h_buf.at[slot],
                hsem.at[slot],
            ).start()

        pending = {}       # chunked: pslot -> send-side drain closure
        push_handles = {}  # chunked: step s -> [ChunkedPutHandle per jn]
        for s in range(n):
            # own chunk LAST: remote pushes get the whole kernel to land
            c = jax.lax.rem(me + 1 + s, n) if n > 1 else jnp.int32(0)
            ids_cp = pltpu.make_async_copy(dst_ref.at[c], ids_v, metasem)
            ids_cp.start()
            w_cp = pltpu.make_async_copy(wrow_ref.at[c], w_v, metasem)
            w_cp.start()
            ids_cp.wait()
            w_cp.wait()

            for jn in range(n_jn):
                partial_ref[:] = jnp.zeros_like(partial_ref)
                e0 = eid_ref[c, 0]
                _w_start(e0, jn, 0)
                _issue_h(c, 0, 0)  # h rows stream per block, double-buffered

                def _blk(b, slot, c=c, jn=jn):
                    e = eid_ref[c, b]
                    e_prev = eid_ref[c, jax.lax.max(b - 1, 0)]
                    fresh = jnp.logical_or(b == 0, e != e_prev)
                    slot = jnp.where(fresh, 1 - slot, slot)

                    @pl.when(fresh)
                    def _():
                        _w_wait(e, jn, slot)

                    e2 = eid_ref[c, jax.lax.min(b + 1, nb - 1)]

                    @pl.when(jnp.logical_and(b + 1 < nb, e2 != e))
                    def _():
                        _w_start(e2, jn, 1 - slot)

                    hslot = jax.lax.rem(b, 2)
                    pltpu.make_async_copy(
                        h_ref.at[pl.ds(0, bm), :], h_buf.at[hslot],
                        hsem.at[hslot],
                    ).wait()

                    @pl.when(b + 1 < nb)
                    def _():
                        pltpu.make_async_copy(
                            h_ref.at[
                                pl.ds(c * t_pad_loc + (b + 1) * bm, bm), :
                            ],
                            h_buf.at[1 - hslot],
                            hsem.at[1 - hslot],
                        ).start()

                    if not ragged:
                        y = jnp.dot(
                            h_buf[hslot],
                            fmt.mxu_b(w_buf[slot], cdt),
                            preferred_element_type=jnp.float32,
                        )
                        y = fmt.fold(y, s_buf[slot][0] if fmt.scaled else None)
                        d = ids_v[b]               # [bm] destination tokens
                        w_r = w_v[b]               # [bm] routing weights
                        sel = jax.lax.broadcasted_iota(
                            jnp.int32, (m_out, bm), 0
                        ) == d[None, :]
                        scat = jnp.where(sel, w_r[None, :], 0.0).astype(cdt)
                        partial_ref[:] += jnp.dot(
                            scat, y.astype(cdt),
                            preferred_element_type=jnp.float32,
                        )
                    else:
                        # down-GEMM and combine shrink to live panels;
                        # tail sentinel rows keep their 0 routing weight
                        _moe_ragged_blk(
                            h_buf, w_buf, ids_v, w_v, partial_ref, hslot,
                            slot, b, vid_ref[c, b], m_out, bm, panel, cdt,
                            fmt, s_buf[slot][0] if fmt.scaled else None,
                        )
                    return slot

                jax.lax.fori_loop(0, nb, _blk, jnp.int32(1))

                pc = s * n_jn + jn
                pslot = pc % 2
                if not chunked:
                    def _stage_wait(sl):
                        pltpu.make_async_copy(
                            push_stage.at[sl], own_buf.at[:, pl.ds(0, bn)],
                            stage_sem.at[sl],
                        ).wait()

                    if pc >= 2:
                        _stage_wait(pslot)
                    push_stage[pslot] = partial_ref[:].astype(out_dtype)
                    if s < n - 1:
                        # landing slot index s is the sender-distance
                        # convention of _scatter_reduce_kernel: distinct
                        # per sender by symmetry. Send completion is
                        # accounted on stage_sem by the slot-reuse waits
                        # (and the end-of-kernel drain).
                        shmem.putmem_nbi_block(
                            landing.at[s, :, pl.ds(jn * bn, bn)],
                            push_stage.at[pslot],
                            c, axis, stage_sem.at[pslot],
                            recv_sems.at[s, jn],
                        )
                    else:
                        pltpu.make_async_copy(
                            push_stage.at[pslot],
                            (out_ref if n == 1 else own_buf).at[
                                :, pl.ds(jn * bn, bn)
                            ],
                            stage_sem.at[pslot],
                        ).start()
                else:
                    if pc >= 2:
                        pending.pop(pslot)()  # send-side completion first
                    push_stage[pslot] = partial_ref[:].astype(out_dtype)
                    if s < n - 1:
                        # the retired slab ships as per-(s, jn, chunk)
                        # DMAs; landing slot s = sender-distance
                        # convention, so by SPMD symmetry the slab
                        # incoming at distance s lands at the SAME local
                        # (s, span, jn) coordinates — dst and landing
                        # views coincide (ISSUE 8 canary)
                        handle = shmem.putmem_signal_chunked_nbi_block(
                            lambda off, rows, s=s, jn=jn: landing.at[
                                s, pl.ds(off, rows), pl.ds(jn * bn, bn)
                            ],
                            lambda off, rows, pslot=pslot: push_stage.at[
                                pslot, pl.ds(off, rows)
                            ],
                            c, axis,
                            lambda j, pslot=pslot: stage_sems.at[pslot, j],
                            lambda j, s=s, jn=jn: recv_sems.at[s, jn, j],
                            lambda j, s=s, jn=jn: sig_sems.at[s, jn, j],
                            spans,
                            recv_view=lambda off, rows, s=s, jn=jn: landing.at[
                                s, pl.ds(off, rows), pl.ds(jn * bn, bn)
                            ],
                        )
                        push_handles.setdefault(s, []).append(handle)
                        pending[pslot] = handle.wait_send
                    else:
                        cp = pltpu.make_async_copy(
                            push_stage.at[pslot],
                            own_buf.at[:, pl.ds(jn * bn, bn)],
                            local_sem.at[pslot],
                        )
                        cp.start()
                        pending[pslot] = cp.wait

        if not chunked:
            # drain the last two staged pushes
            total_push = n * n_jn
            if total_push >= 1:
                pltpu.make_async_copy(
                    push_stage.at[(total_push - 1) % 2],
                    own_buf.at[:, pl.ds(0, bn)],
                    stage_sem.at[(total_push - 1) % 2],
                ).wait()
            if total_push >= 2:
                pltpu.make_async_copy(
                    push_stage.at[total_push % 2],
                    own_buf.at[:, pl.ds(0, bn)],
                    stage_sem.at[total_push % 2],
                ).wait()
            if n == 1:
                return
            # wait every incoming slab, then the n-way reduction below
            for d in range(n - 1):
                for jn in range(n_jn):
                    pltpu.make_async_copy(
                        landing.at[d, :, pl.ds(jn * bn, bn)],
                        own_buf.at[:, pl.ds(jn * bn, bn)],
                        recv_sems.at[d, jn],
                    ).wait()
        else:
            for drain in pending.values():
                drain()
            # consume every incoming slab chunk by chunk (SPMD-mirrored
            # chunks; sig slots route through the armed chunk_wait path)
            for d in range(n - 1):
                for jn in range(n_jn):
                    for j in range(len(spans)):
                        push_handles[d][jn].wait_recv_chunk(j)

        h_dim = out_ref.shape[1]
        bmo = pick_block(m_out, 256)
        bno = pick_block(h_dim, 1024)

        def reduce_body(*blks):
            o_blk = blks[-1]
            acc = blks[0][:].astype(jnp.float32)
            for r in blks[1:-1]:
                acc = acc + r[:].astype(jnp.float32)
            o_blk[:] = acc.astype(out_dtype)

        blk = lambda i, j: (i, j)  # noqa: E731
        pltpu.emit_pipeline(
            reduce_body,
            grid=(m_out // bmo, h_dim // bno),
            in_specs=[pl.BlockSpec((bmo, bno), blk)] * n,
            out_specs=[pl.BlockSpec((bmo, bno), blk)],
        )(
            own_buf,
            *(landing.at[d] for d in range(n - 1)),
            out_ref,
        )

    return kernel
