"""KV handoff stream: the chunked-put transfer family of the
disaggregated prefill/decode topology (ISSUE 13 tentpole, ROADMAP #2).

A disaggregated serving fleet splits prefill and decode onto separate
accelerator pools; the moment a prompt's paged KV is complete, its pages
must cross the pool boundary. This module is that wire: one mesh axis
spans BOTH pools (prefill PEs first, decode PEs second — the
``serving/disagg.py`` topology), and every PE exchanges its page slab
with its MIRROR PE in the other pool, ``peer = (me + n/2) mod n`` — an
involution for every even world, so the SPMD program is symmetric: the
prefill→decode direction carries freshly prefilled KV pages, the mirror
direction carries the decode pool's return slab (evicted / migrated
pages — page migration is symmetric by design).

Robustness is the contract (the reference's EP a2a wire pattern —
low-precision payload + signal slots — with the ISSUE 8 integrity layer
on every edge):

- the slab moves **chunk by chunk** through
  ``shmem.putmem_signal_chunked_nbi_block``: per-chunk DMA + per-chunk
  pure signal slots, so a consumer admits on *last-page-landed* instead
  of whole-transfer completion, every chunk wait is watchdog-bounded
  (chunk-granular timeout diagnostics + per-site wait telemetry,
  ISSUE 9), and a dropped chunk signal is individually injectable and
  individually attributed;
- every chunk declares its ``recv_view=`` **landing view** (mirror
  symmetry makes it the same offsets of the local out slab), so the
  payload **canary** rides each chunk signal: a corrupted landing fails
  its checksum at the receiving PE (victim == culprit, the ISSUE 8
  landing-site model) — corrupt KV is never silently decoded;
- the **int8 wire** (``KVStreamConfig(wire="int8")``) streams the page
  payload at int8 with per-row f32 scales riding their own chunked put
  (same spans, own signal slots, own landing views) — half the
  cross-pool bytes, exactly the a2a's low-precision wire shape
  (``layers/ep_a2a_layer.py``); the **fp8 wire** (ISSUE 19) is its
  fp8_e4m3 twin — the same two-put protocol with the same signal/canary
  discipline, the payload at the e4m3 ceiling (448) instead of int8's
  127 (the reference's headline a2a runs fp8 payloads with traveling
  scales);
- the whole family is **proved by the static verifier** like every
  other: ``analysis/sweep.py`` sweeps :data:`KV_STREAM_TUNE_SPACE` at
  worlds {2, 4, 8} — credit balance, deadlock freedom, dense wait-site
  numbering, landing-view coverage (``scripts/protocol_lint.py``).

The host-tier serving plane (``serving/handoff.py``) models this wire's
protocol — chunk canaries, bounded waits, retry ladder — at the
documented host chaos seam (the PR 11 soak discipline); this kernel is
the device tier the ladder degrades FROM, and the verifier proves it on
any jax line, devices or not.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.ops.common import (
    chunk_schedule,
    dist_pallas_call,
    jit_shard_map,
)
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import axis_size as _axis_size

WIRES = ("native", "int8", "fp8")
# the quantized wires share one protocol (payload put + scale put); they
# differ only in payload dtype and quantizer ceiling
QUANT_WIRES = ("int8", "fp8")
FP8_WIRE_DTYPE = jnp.float8_e4m3fn
_FP8_WIRE_MAX = 448.0


@dataclasses.dataclass(frozen=True)
class KVStreamConfig:
    """One tune-space tuple of the KV handoff stream.

    chunks_per_shard: per-transfer chunk count — the landing granularity
        (a decode-side consumer can admit on the last CHUNK, and each
        chunk is its own watchdog-bounded, chaos-injectable signal edge).
    wire: "native" moves the payload as-is; "int8" expects a
        pre-quantized int8 payload plus per-row f32 scales
        (:func:`quantize_kv_wire`) and streams the scales on their own
        chunked put — half the cross-pool bytes on the weight/KV-bound
        decode side, the reference's low-precision a2a wire shape;
        "fp8" is the fp8_e4m3 twin (:func:`quantize_kv_wire_fp8`,
        ISSUE 19) — the same two-put protocol, e4m3's tapered grid on
        the wire.
    """

    chunks_per_shard: int = 1
    wire: str = "native"

    def validate(self) -> "KVStreamConfig":
        if self.chunks_per_shard < 1:
            raise ValueError(
                f"chunks_per_shard must be >= 1, got {self.chunks_per_shard}"
            )
        if self.wire not in WIRES:
            raise ValueError(
                f"wire must be one of {WIRES}, got {self.wire!r}"
            )
        return self


# The tune space the static verifier sweeps (analysis/sweep.py) and the
# serving plane selects from: every wire × chunking combination.
KV_STREAM_TUNE_SPACE = tuple(
    KVStreamConfig(chunks_per_shard=c, wire=w)
    for w in WIRES
    for c in (1, 2, 4)
)


def quantize_kv_wire(pages: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 quantization of a ``[m, w]`` page slab for
    the int8 wire: returns ``(payload int8 [m, w], scales f32 [m, 1])``.
    A KV row (one position × head-feature columns) shares one scale, the
    int8-KV decode family's convention."""
    x = pages.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_kv_wire_fp8(pages: jax.Array) -> tuple[jax.Array, jax.Array]:
    """fp8_e4m3 twin of :func:`quantize_kv_wire` (ISSUE 19): per-row
    absmax at the e4m3 ceiling (448), ``(payload fp8 [m, w], scales f32
    [m, 1])`` — the same wire shape, the same 1-byte payload, e4m3's
    tapered grid instead of int8's uniform one."""
    x = pages.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / _FP8_WIRE_MAX, 1.0)
    q = jnp.clip(x / scale, -_FP8_WIRE_MAX, _FP8_WIRE_MAX).astype(
        FP8_WIRE_DTYPE
    )
    return q, scale.astype(jnp.float32)


def quantize_kv_wire_for(wire: str, pages: jax.Array):
    """The quantizer of a QUANT_WIRES member (dispatch by wire name)."""
    if wire == "int8":
        return quantize_kv_wire(pages)
    if wire == "fp8":
        return quantize_kv_wire_fp8(pages)
    raise ValueError(f"not a quantized wire: {wire!r}")


def dequantize_kv_wire(payload: jax.Array, scales: jax.Array,
                       dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv_wire` (consumer side of the wire)."""
    return (payload.astype(jnp.float32) * scales.astype(jnp.float32)).astype(
        dtype
    )


def mirror_peer(me, n: int):
    """The mirror PE in the other pool: ``(me + n/2) mod n`` — an
    involution for every even ``n``, so the pairwise exchange is SPMD
    symmetric (prefill PE i ↔ decode PE i + n/2)."""
    return jax.lax.rem(me + n // 2, n)


def _kv_stream_kernel(
    x_ref, out_ref, send_sems, recv_sems, sig_sems, *, axis: str, n: int,
    spans,
):
    """Native-wire mirror exchange: this PE's slab streams chunk by chunk
    to its mirror peer; the mirror's equal-shaped slab lands at the SAME
    offsets of ``out_ref`` (the landing view — by pair symmetry only the
    mirror ever writes here)."""
    me = shmem.my_pe(axis)
    peer = mirror_peer(me, n)
    # race shaking (no-op unless config.debug_comm_delay) + the liveness
    # barrier: every PE's out buffer must exist before landings start
    shmem.comm_jitter(axis, salt=9)
    shmem.barrier_all(axis)
    h = shmem.putmem_signal_chunked_nbi_block(
        lambda off, rows: out_ref.at[pl.ds(off, rows)],
        lambda off, rows: x_ref.at[pl.ds(off, rows)],
        peer, axis,
        lambda j: send_sems.at[j],
        lambda j: recv_sems.at[j],
        lambda j: sig_sems.at[j],
        spans,
        # mirror symmetry: the incoming chunk lands at the same offsets
        # we sent from — the payload-integrity opt-in (ISSUE 8)
        recv_view=lambda off, rows: out_ref.at[pl.ds(off, rows)],
    )
    # last-page-landed: chunk-granular arrival waits, in chunk order (a
    # serving consumer would hand each landed chunk to admission here)
    h.wait_recv()
    shmem.quiet(h)


def _kv_stream_w8_kernel(
    x_ref, s_ref, out_ref, s_out_ref,
    send_d, recv_d, sig_d, send_s, recv_s, sig_s,
    *, axis: str, n: int, spans, s_spans,
):
    """int8-wire mirror exchange: the quantized payload and its per-row
    scales ride two chunked puts over the SAME row spans — each with its
    own signal slots and landing views, so the canary covers both (a
    corrupt scale row is as fatal as a corrupt payload chunk)."""
    me = shmem.my_pe(axis)
    peer = mirror_peer(me, n)
    shmem.comm_jitter(axis, salt=10)
    shmem.barrier_all(axis)
    hd = shmem.putmem_signal_chunked_nbi_block(
        lambda off, rows: out_ref.at[pl.ds(off, rows)],
        lambda off, rows: x_ref.at[pl.ds(off, rows)],
        peer, axis,
        lambda j: send_d.at[j], lambda j: recv_d.at[j],
        lambda j: sig_d.at[j],
        spans,
        recv_view=lambda off, rows: out_ref.at[pl.ds(off, rows)],
    )
    hs = shmem.putmem_signal_chunked_nbi_block(
        lambda off, rows: s_out_ref.at[pl.ds(off, rows)],
        lambda off, rows: s_ref.at[pl.ds(off, rows)],
        peer, axis,
        lambda j: send_s.at[j], lambda j: recv_s.at[j],
        lambda j: sig_s.at[j],
        s_spans,
        recv_view=lambda off, rows: s_out_ref.at[pl.ds(off, rows)],
    )
    # consume per chunk: a landed payload chunk is decodable only once
    # its scale rows landed too, so wait them pairwise in chunk order
    for j in range(len(spans)):
        hd.wait_recv_chunk(j)
        hs.wait_recv_chunk(j)
    shmem.quiet(hd, hs)


def _kv_stream_xla(payload, scales=None, *, axis="tp", **_):
    """The golden slow path: the same mirror exchange through XLA's
    ppermute (single- or both-operand)."""
    n = _axis_size((axis))
    if n == 1:
        return payload if scales is None else (payload, scales)
    perm = [(i, (i + n // 2) % n) for i in range(n)]
    out = jax.lax.ppermute(payload, axis, perm)
    if scales is None:
        return out
    return out, jax.lax.ppermute(scales, axis, perm)


def _kv_stream_fused(
    payload: jax.Array,
    scales: jax.Array | None = None,
    *,
    axis: str = "tp",
    config: KVStreamConfig | None = None,
    interpret: Any = None,
):
    """Fused mirror page-slab exchange (call inside ``jax.shard_map``).

    ``payload``: this PE's ``[m, w]`` page slab (the wire's quantized
    dtype when ``config.wire`` is in :data:`QUANT_WIRES`, any dtype
    otherwise); ``scales``: ``[m, 1]`` f32 per-row scales, required iff
    the wire is quantized. Returns the mirror peer's landed slab (and
    scales, quantized wires). World must be even — the two-pool mirror
    pairing has no odd form — and world 1 is the identity (nothing to
    hand off)."""
    cfg = (config or KVStreamConfig()).validate()
    n = _axis_size((axis))
    if (cfg.wire in QUANT_WIRES) != (scales is not None):
        raise ValueError(
            f"KVStreamConfig.wire={cfg.wire!r}: quantized wires "
            f"{QUANT_WIRES} require per-row scales (from "
            f"quantize_kv_wire / quantize_kv_wire_fp8); the native wire "
            f"takes none"
        )
    if n == 1:
        return payload if scales is None else (payload, scales)
    if n % 2:
        raise ValueError(
            f"kv_stream needs an even world (mirror pool pairing); got "
            f"axis {axis!r} size {n}"
        )
    m = payload.shape[0]
    spans = chunk_schedule(m, cfg.chunks_per_shard)
    chunks = len(spans)
    if cfg.wire in QUANT_WIRES:
        if scales.shape[0] != m:
            raise ValueError(
                f"scales rows {scales.shape[0]} != payload rows {m}"
            )
        s_spans = spans  # same row spans: chunk j's scales ride chunk j
        # ONE kernel for both quantized wires (payload-dtype generic —
        # the protocol never reads the payload); distinct launch names
        # keep the guard/telemetry families separate
        out, s_out = dist_pallas_call(
            functools.partial(
                _kv_stream_w8_kernel, axis=axis, n=n, spans=spans,
                s_spans=s_spans,
            ),
            name="kv_stream_fp8" if cfg.wire == "fp8" else "kv_stream_w8",
            out_shape=(
                jax.ShapeDtypeStruct(payload.shape, payload.dtype),
                jax.ShapeDtypeStruct(scales.shape, scales.dtype),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((chunks,)),
                pltpu.SemaphoreType.DMA((chunks,)),
                pltpu.SemaphoreType.REGULAR((chunks,)),
                pltpu.SemaphoreType.DMA((chunks,)),
                pltpu.SemaphoreType.DMA((chunks,)),
                pltpu.SemaphoreType.REGULAR((chunks,)),
            ],
            interpret=interpret,
        )(payload, scales)
        return out, s_out
    return dist_pallas_call(
        functools.partial(_kv_stream_kernel, axis=axis, n=n, spans=spans),
        name="kv_stream",
        out_shape=jax.ShapeDtypeStruct(payload.shape, payload.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((chunks,)),
            pltpu.SemaphoreType.DMA((chunks,)),
            pltpu.SemaphoreType.REGULAR((chunks,)),
        ],
        interpret=interpret,
    )(payload)


def kv_stream(
    payload: jax.Array,
    scales: jax.Array | None = None,
    *,
    axis: str = "tp",
    config: KVStreamConfig | None = None,
    interpret: Any = None,
):
    """Guarded in-shard_map entry: the fused mirror exchange with the
    XLA ppermute golden served automatically when the fused kernel cannot
    run in this environment (resilience layer, docs/resilience.md)."""
    return resilience.guarded_call(
        "kv_stream",
        _kv_stream_fused,
        _kv_stream_xla,
        payload, scales, axis=axis, config=config, interpret=interpret,
    )


def _kv_stream_op_xla(
    payload: jax.Array, mesh: Mesh, *, axis: str = "tp",
    config: KVStreamConfig | None = None, **_
):
    cfg = (config or KVStreamConfig()).validate()
    if cfg.wire in QUANT_WIRES:
        def fn(x):
            q, s = quantize_kv_wire_for(cfg.wire, x)
            q, s = _kv_stream_xla(q, s, axis=axis)
            return dequantize_kv_wire(q, s, x.dtype)
    else:
        fn = functools.partial(_kv_stream_xla, axis=axis)
    return jit_shard_map(
        fn, mesh, P(axis, None), P(axis, None),
        key=("kv_stream_xla", axis, cfg),
    )(payload)


@resilience.guard_op("kv_stream_op", _kv_stream_op_xla)
def kv_stream_op(
    payload: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: KVStreamConfig | None = None,
    interpret: Any = None,
):
    """Host-level entry: ``payload`` is a global ``[n*m, w]`` array
    sharded on dim 0 (each PE's rows are its local page slab); returns
    the globally mirror-exchanged array with the same sharding. On the
    quantized wires (int8 / fp8) the slab is quantized per row before the
    exchange and dequantized after landing — the wire cost is the
    quantization error, the win is the 1-byte payload."""
    cfg = (config or KVStreamConfig()).validate()

    def fn(x):
        if cfg.wire in QUANT_WIRES:
            q, s = quantize_kv_wire_for(cfg.wire, x)
            q, s = kv_stream(q, s, axis=axis, config=cfg,
                             interpret=interpret)
            return dequantize_kv_wire(q, s, x.dtype)
        return kv_stream(x, axis=axis, config=cfg, interpret=interpret)

    return jit_shard_map(
        fn, mesh, P(axis, None), P(axis, None),
        key=("kv_stream", axis, cfg, str(interpret)),
    )(payload)
