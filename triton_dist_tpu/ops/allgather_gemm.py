"""Fused AllGather-GEMM — the flagship overlapped op
(≙ reference ``kernels/nvidia/allgather_gemm.py``, 748 LoC).

The reference splits the op across CUDA streams: cp-engine producers push
shards into a symmetric workspace while a persistent consumer GEMM kernel
spins per-M-tile on readiness flags (``dl.wait`` + ``dl.consume_token``,
allgather_gemm.py:226-227) with a rank-first tile swizzle (:206-219).

TPU-native re-design: one fused Pallas kernel per PE. The ring transfer of
the next shard rides the ICI DMA engines *while* the MXU multiplies the
current shard through an inner ``emit_pipeline`` (HBM→VMEM double-buffered
matmul). The reference's tile swizzle becomes the ring schedule itself:
step s computes shard ``(me - s) % n``, which is exactly "start at own rank,
walk in ring-arrival order" — compute order equals arrival order, so there
is no wait bubble after the first hop.

    step 0:  compute own shard       | send own shard to right neighbor
    step s:  wait shard (me-s)       | forward it right | MXU on it

Used for TP column-parallel layers: A is sharded on M (tokens), B on N
(features); every PE gets the full gathered A and its N-shard of C.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import (
    chunk_schedule,
    dist_pallas_call,
    gemm_add_pipeline,
    gemm_only,
    jit_shard_map,
)
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block as _pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


@dataclasses.dataclass(frozen=True)
class AGGemmConfig:
    """Tunables (≙ ``AllGatherGEMMTensorParallelContext``,
    reference allgather_gemm.py:407-489 — minus the stream/workspace
    plumbing, which the fused kernel does not need)."""

    block_m: int = 512
    block_n: int = 2048
    block_k: int = 512
    # block_m=0: world-1 XLA-native sentinel — dispatch the degenerate
    # no-comm case to jnp.dot (XLA's matmul), a first-class autotune
    # candidate. Non-viable (raises) at n>1, where the fused ring kernel
    # is the whole point.
    # Ring-step payload granularity (ISSUE 3): > 1 splits each shard into
    # that many per-chunk DMAs, the MXU computing on chunk j while chunk
    # j+1 is in flight; 1 reproduces the legacy shard-granular schedule
    # bit for bit (the tuner's no-regression anchor).
    chunks_per_shard: int = 1


def _ag_gemm_kernel(
    a_ref, b_ref, out_ref, ag_ref, acc_ref, copy_sem, send_sems, recv_sems,
    *, axis: str, n: int, cfg: AGGemmConfig, out_dtype,
):
    me = shmem.my_pe(axis)
    m_loc, k_dim = a_ref.shape
    n_loc = b_ref.shape[1]
    bm = _pick_block(m_loc, cfg.block_m)
    bn = _pick_block(n_loc, cfg.block_n)
    bk = _pick_block(k_dim, cfg.block_k)

    local = pltpu.make_async_copy(a_ref, ag_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem)
    local.start()
    local.wait()
    # race shaking (no-op unless config.debug_comm_delay)
    shmem.comm_jitter(axis, salt=8)
    shmem.barrier_all(axis)

    right = jax.lax.rem(me + 1, n)
    pipeline = gemm_add_pipeline(bm, bn, bk, m_loc, n_loc, k_dim, acc_ref, out_dtype)

    descs = []
    for s in range(n):
        c = jax.lax.rem(me - s + 2 * n, n)
        if s > 0:
            descs[s - 1].wait_recv()  # shard c landed during step s-1
        sl = pl.ds(c * m_loc, m_loc)
        if s < n - 1:
            # Forward shard c around the ring *before* computing on it: the
            # ICI transfer overlaps the MXU work below (≙ producer stream).
            descs.append(
                shmem.putmem_nbi_block(
                    ag_ref.at[sl], ag_ref.at[sl], right, axis,
                    send_sems.at[s], recv_sems.at[s],
                )
            )
        pipeline(ag_ref.at[sl], b_ref, out_ref.at[sl])
    shmem.quiet(*descs)


def _ag_gemm_chunked_kernel(
    a_ref, b_ref, out_ref, ag_ref, acc_ref, copy_sem, send_sems, recv_sems,
    sig_sems, *, axis: str, n: int, cfg: AGGemmConfig, out_dtype, spans,
):
    """Chunk-granular fused AG-GEMM (ISSUE 3 tentpole): step ``s`` waits,
    forwards, and COMPUTES shard ``me-s`` chunk by chunk — the MXU runs on
    chunk ``j`` while chunk ``j+1`` is still crossing the ICI, restoring the
    reference's per-M-tile progress (``dl.wait``/``dl.consume_token``,
    allgather_gemm.py:226-227) that the shard-granular port collapsed.
    chunk=1 dispatches to :func:`_ag_gemm_kernel` (bit-identical legacy)."""
    me = shmem.my_pe(axis)
    m_loc, k_dim = a_ref.shape
    n_loc = b_ref.shape[1]
    bn = _pick_block(n_loc, cfg.block_n)
    bk = _pick_block(k_dim, cfg.block_k)
    # one pipeline per distinct chunk row-count (non-divisor spans differ by
    # one row); the f32 accumulator scratch is sized for the largest chunk
    # tile and sliced only for the smaller ones
    bms = [_pick_block(rows, cfg.block_m) for _, rows in spans]
    bm_max = max(bms)
    pipes = []
    for (_, rows), bm_j in zip(spans, bms):
        acc_j = acc_ref if bm_j == bm_max else acc_ref.at[pl.ds(0, bm_j), :]
        pipes.append(
            gemm_add_pipeline(bm_j, bn, bk, rows, n_loc, k_dim, acc_j, out_dtype)
        )

    local = pltpu.make_async_copy(a_ref, ag_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter(axis, salt=8)
    shmem.barrier_all(axis)

    right = jax.lax.rem(me + 1, n)
    descs = []
    for s in range(n):
        c = jax.lax.rem(me - s + 2 * n, n)
        base = c * m_loc
        # the put issued at step s is consumed at step s+1, when the left
        # neighbor's step-s send — shard (me-1-s) mod n — has landed: that
        # shard's rows are the landing view (ISSUE 8 canary; the same
        # arithmetic as the chunked ring allgather's base_in)
        base_in = jax.lax.rem(me - 1 - s + 2 * n, n) * m_loc
        handles = []
        for j, (off, rows) in enumerate(spans):
            if s > 0:
                descs[s - 1].wait_recv_chunk(j)  # chunk j of shard c landed
            sl = pl.ds(base + off, rows)
            if s < n - 1:
                # forward chunk j before computing on it: its ICI hop rides
                # under this chunk's (and later chunks') MXU work
                handles.append(
                    shmem.putmem_signal2_nbi_block(
                        ag_ref.at[sl], ag_ref.at[sl], right, axis,
                        send_sems.at[s, j], recv_sems.at[s, j],
                        sig_sems.at[s, j], canary=True,
                    )
                )
            pipes[j](ag_ref.at[sl], b_ref, out_ref.at[sl])
        if handles:
            descs.append(shmem.ChunkedPutHandle(
                handles,
                recv_at=lambda off, rows, b=base_in: ag_ref.at[
                    pl.ds(b + off, rows)
                ],
                spans=spans,
            ))
    shmem.quiet(*descs)


def _ag_gemm_2d_kernel(
    a_ref, b_ref, out_ref, ag_ref, acc_ref, copy_sem, in_send, in_recv,
    out_send, out_recv, *, outer: str, inner: str, n_o: int, n_i: int,
    cfg: AGGemmConfig, out_dtype,
):
    """Fused hierarchical AG-GEMM over two mesh axes: the 2-D ring allgather
    (see ops/allgather._ring_2d_kernel) with an MXU pipeline consuming every
    chunk the moment it is locally available — compute order = 2-D arrival
    order, the multi-axis generalization of the 1-D swizzle (≙ the
    reference's node-shifted tile swizzle, allgather_gemm.py:206-219)."""
    me_i = shmem.my_pe(inner)
    me_o = shmem.my_pe(outer)
    m_loc, k_dim = a_ref.shape
    n_loc = b_ref.shape[1]
    bm = _pick_block(m_loc, cfg.block_m)
    bn = _pick_block(n_loc, cfg.block_n)
    bk = _pick_block(k_dim, cfg.block_k)
    pipeline = gemm_add_pipeline(bm, bn, bk, m_loc, n_loc, k_dim, acc_ref, out_dtype)

    def slot(o, i):
        return pl.ds((o * n_i + i) * m_loc, m_loc)

    local = pltpu.make_async_copy(a_ref, ag_ref.at[slot(me_o, me_i)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter((outer, inner), salt=9)
    shmem.barrier_all((outer, inner))

    right_i = jax.lax.rem(me_i + 1, n_i)
    down_o = jax.lax.rem(me_o + 1, n_o)
    descs_i = []
    descs_o = [[None] * n_i for _ in range(n_o - 1)]

    for s in range(n_i):
        c = jax.lax.rem(me_i - s + n_i, n_i)
        if s > 0:
            descs_i[s - 1].wait_recv()
        sl = slot(me_o, c)
        if s < n_i - 1:
            descs_i.append(
                shmem.putmem_nbi_block(
                    ag_ref.at[sl], ag_ref.at[sl], right_i, inner,
                    in_send.at[s], in_recv.at[s],
                )
            )
        if n_o > 1:
            descs_o[0][s] = shmem.putmem_nbi_block(
                ag_ref.at[sl], ag_ref.at[sl], down_o, outer,
                out_send.at[0, s], out_recv.at[0, s],
            )
        # both forwards are in flight: the MXU overlaps them
        pipeline(ag_ref.at[sl], b_ref, out_ref.at[sl])

    for t in range(1, n_o):
        row = jax.lax.rem(me_o - t + n_o, n_o)
        for s in range(n_i):
            c = jax.lax.rem(me_i - s + n_i, n_i)
            descs_o[t - 1][s].wait_recv()
            sl = slot(row, c)
            if t < n_o - 1:
                descs_o[t][s] = shmem.putmem_nbi_block(
                    ag_ref.at[sl], ag_ref.at[sl], down_o, outer,
                    out_send.at[t, s], out_recv.at[t, s],
                )
            pipeline(ag_ref.at[sl], b_ref, out_ref.at[sl])
    shmem.quiet(*descs_i, *(d for row_d in descs_o for d in row_d if d is not None))


def _ag_gemm_2d(a, b, *, axes, cfg, gather_output, out_dtype, interpret):
    outer, inner = axes
    n_o = int(jax.lax.axis_size(outer))
    n_i = int(jax.lax.axis_size(inner))
    n = n_o * n_i
    m_loc, k_dim = a.shape
    n_loc = b.shape[1]
    bm = _pick_block(m_loc, cfg.block_m)
    bn = _pick_block(n_loc, cfg.block_n)
    out, ag = dist_pallas_call(
        functools.partial(
            _ag_gemm_2d_kernel, outer=outer, inner=inner, n_o=n_o, n_i=n_i,
            cfg=cfg, out_dtype=out_dtype,
        ),
        name="ag_gemm_2d",
        out_shape=(
            jax.ShapeDtypeStruct((n * m_loc, n_loc), out_dtype),
            jax.ShapeDtypeStruct((n * m_loc, k_dim), a.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((bm, bn), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n_i - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n_i - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n_o - 1, 1), n_i)),
            pltpu.SemaphoreType.DMA((max(n_o - 1, 1), n_i)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n * m_loc * n_loc * k_dim,
            bytes_accessed=(n * m_loc * k_dim + k_dim * n_loc + n * m_loc * n_loc) * a.dtype.itemsize,
            transcendentals=0,
        ),
        uses_barrier=True,
        interpret=interpret,
    )(a, b)
    return (out, ag) if gather_output else out


def _ag_gemm_xla(
    a: jax.Array, b: jax.Array, *, axis="tp", gather_output=False,
    out_dtype=None, **_
):
    """The golden slow path (the program every fused method is tested
    against): XLA's all-gather + dot, single- or multi-axis."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    out_dtype = out_dtype or a.dtype
    ag = jax.lax.all_gather(a, axes, axis=0, tiled=True)
    out = jnp.dot(ag, b, preferred_element_type=out_dtype)
    return (out, ag) if gather_output else out


def ag_gemm(
    a: jax.Array,
    b: jax.Array,
    *,
    axis: str = "tp",
    config: AGGemmConfig | None = None,
    gather_output: bool = False,
    out_dtype: Any = None,
    interpret: Any = None,
):
    """Overlapped ``all_gather(a) @ b`` (call inside ``jax.shard_map``).

    a: ``[m_loc, K]`` — M-sharded activations on this PE.
    b: ``[K, n_loc]`` — N-shard of the weight (column-parallel).
    Returns ``[n*m_loc, n_loc]`` (plus the gathered ``[n*m_loc, K]`` A if
    `gather_output`, ≙ the reference returning its AG workspace for reuse).
    Golden: ``jax.lax.all_gather(a, axis, tiled=True) @ b`` — served
    automatically when the fused kernel cannot run in this environment
    (resilience layer, docs/resilience.md; the same guard every other op
    family carries — its absence here was why a jax line without the
    CompilerParams surface could not trace the TP transformer forward, so
    prefill admission and the serving engine's MXU-rate path failed
    instead of degrading).
    """
    from triton_dist_tpu import resilience

    return resilience.guarded_call(
        "ag_gemm",
        _ag_gemm_fused,
        _ag_gemm_xla,
        a, b, axis=axis, config=config, gather_output=gather_output,
        out_dtype=out_dtype, interpret=interpret,
    )


def _ag_gemm_fused(
    a: jax.Array,
    b: jax.Array,
    *,
    axis: str = "tp",
    config: AGGemmConfig | None = None,
    gather_output: bool = False,
    out_dtype: Any = None,
    interpret: Any = None,
):
    cfg = config or AGGemmConfig()
    out_dtype = out_dtype or a.dtype
    if cfg.block_m == 0:
        # before the 2-D dispatch: _pick_block(m, 0) would ZeroDivide there
        names = axis if isinstance(axis, (tuple, list)) else (axis,)
        n_tot = 1
        for ax in names:
            n_tot *= int(jax.lax.axis_size(ax))
        if n_tot != 1:
            raise ValueError("AGGemmConfig(block_m=0) (XLA dot) is world-1 only")
        out = jnp.dot(a, b, preferred_element_type=out_dtype)
        return (out, a) if gather_output else out
    from triton_dist_tpu.parallel.topology import is_dcn_axis_name as _is_dcn

    if isinstance(axis, (tuple, list)):
        if len(axis) == 1:
            axis = axis[0]
        else:
            assert len(axis) == 2, f"at most 2 axes supported, got {axis}"
            outer_ax, inner_ax = axis
            if _is_dcn(inner_ax) and not _is_dcn(outer_ax):
                # DCN in the INNER slot: composition order must follow the
                # TRANSPORT (fused compute on ICI, outputs shared across
                # DCN), not the tuple order — otherwise the single-axis
                # DCN fallback would gather A across DCN and n_dcn-plicate
                # the FLOPs. AG over (a0, a1) is AG over (a1, a0) with the
                # result's (n_i, n_o) block grid transposed, so route
                # through the efficient DCN-outer branch and fix the row
                # order locally.
                n_o = int(jax.lax.axis_size(outer_ax))
                n_i = int(jax.lax.axis_size(inner_ax))

                def _swap(y):
                    blk = y.shape[0] // (n_o * n_i)
                    return (
                        y.reshape(n_i, n_o, blk, *y.shape[1:])
                        .swapaxes(0, 1)
                        .reshape(y.shape)
                    )

                res = ag_gemm(
                    a, b, axis=(inner_ax, outer_ax), config=config,
                    gather_output=gather_output, out_dtype=out_dtype,
                    interpret=interpret,
                )
                if gather_output:
                    return _swap(res[0]), _swap(res[1])
                return _swap(res)
            if _is_dcn(outer_ax):
                # slice-crossing outer axis: keep the fused ring on the
                # ICI inner axis and gather COMPUTED OUTPUT rows across
                # DCN — each group computes its own rows once (vs
                # gathering A, which would n_o-plicate the FLOPs; ≙ the
                # reference's 2-D internode AG staging its cross-node hop
                # separately, allgather.py:291-375). Both recursive calls
                # route per-axis (a both-DCN tuple lowers everything to
                # XLA).
                from triton_dist_tpu.ops.allgather import all_gather

                res = ag_gemm(
                    a, b, axis=inner_ax, config=config,
                    gather_output=gather_output, out_dtype=out_dtype,
                    interpret=interpret,
                )
                y, ag = res if gather_output else (res, None)
                out = all_gather(y, axis=outer_ax, interpret=interpret)
                if gather_output:
                    return out, all_gather(ag, axis=outer_ax, interpret=interpret)
                return out
            return _ag_gemm_2d(
                a, b, axes=tuple(axis), cfg=cfg, gather_output=gather_output,
                out_dtype=out_dtype, interpret=interpret,
            )
    n = _axis_size(axis)
    m_loc, k_dim = a.shape
    n_loc = b.shape[1]
    if n > 1 and _is_dcn(axis):
        # a purely-DCN TP axis: no ICI for the fused ring — lower to XLA's
        # all-gather + dot and let its scheduler overlap the DCN transfer
        ag = jax.lax.all_gather(a, axis, tiled=True)
        out = jnp.dot(ag, b, preferred_element_type=out_dtype)
        return (out, ag) if gather_output else out
    bm = _pick_block(m_loc, cfg.block_m)
    bn = _pick_block(n_loc, cfg.block_n)
    if n == 1:
        # World-1 degenerates to a plain MXU matmul: routing A through the
        # gather workspace would cost an extra HBM round-trip of the whole
        # activation (measured ~3% at the M=8192 bench shape) for nothing.
        out = gemm_only(
            a, b, cfg=cfg, out_dtype=out_dtype, name="ag_gemm", interpret=interpret
        )
        return (out, a) if gather_output else out
    chunks = max(1, int(cfg.chunks_per_shard))
    # span boundaries quantize to the MXU row tile a chunk of this size
    # would pick, so chunking shrinks tiles predictably (m_loc/chunks)
    # instead of collapsing them on odd row counts (see chunk_schedule)
    spans = chunk_schedule(
        m_loc, chunks,
        quantum=_pick_block(m_loc, min(cfg.block_m, max(1, m_loc // chunks))),
    )
    n_steps = max(n - 1, 1)
    if len(spans) > 1:
        kernel = functools.partial(
            _ag_gemm_chunked_kernel, axis=axis, n=n, cfg=cfg,
            out_dtype=out_dtype, spans=spans,
        )
        bm_acc = max(_pick_block(rows, cfg.block_m) for _, rows in spans)
        sem_shapes = [
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.REGULAR((n_steps, len(spans))),
        ]
    else:
        kernel = functools.partial(
            _ag_gemm_kernel, axis=axis, n=n, cfg=cfg, out_dtype=out_dtype
        )
        bm_acc = bm
        sem_shapes = [
            pltpu.SemaphoreType.DMA((n_steps,)),
            pltpu.SemaphoreType.DMA((n_steps,)),
        ]
    out, ag = dist_pallas_call(
        kernel,
        name="ag_gemm",
        out_shape=(
            jax.ShapeDtypeStruct((n * m_loc, n_loc), out_dtype),
            jax.ShapeDtypeStruct((n * m_loc, k_dim), a.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((bm_acc, bn), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            *sem_shapes,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n * m_loc * n_loc * k_dim,
            bytes_accessed=(n * m_loc * k_dim + k_dim * n_loc + n * m_loc * n_loc) * a.dtype.itemsize,
            transcendentals=0,
        ),
        uses_barrier=n > 1,
        interpret=interpret,
    )(a, b)
    return (out, ag) if gather_output else out


def ag_gemm_op(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: AGGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry (≙ ``ag_gemm``, reference allgather_gemm.py:539):
    `a` sharded on dim 0, `b` sharded on dim 1, result replicated on M and
    sharded on N."""
    if mesh.size == 1 and config is not None and config.block_m == 0:
        # world-1 XLA-dot sentinel: no SPMD machinery at all — the fused
        # entry IS the best XLA program, with zero wrapper overhead
        return jnp.dot(a, b, preferred_element_type=a.dtype)
    fn = functools.partial(ag_gemm, axis=axis, config=config, interpret=interpret)
    return jit_shard_map(
        fn, mesh, (P(axis, None), P(None, axis)), P(None, axis),
        key=("ag_gemm", axis, config, str(interpret)),
    )(a, b)


# Candidate space for the contextual autotuner (≙ the reference's
# triton.Config spaces, allgather_gemm.py:386-404). Swept per input
# signature the first time `ag_gemm_op` is called without an explicit
# config; `pick_block` shrinks oversized tiles, so large-tile candidates
# degrade gracefully on small shards. Candidate ORDER is preference order
# (the sweep's order-margin walk and the first-viable policy both honor
# it): the world-1 XLA-dot sentinel leads — honest paired timing on v5e
# showed XLA's matmul at parity-or-better with the best Pallas chunking
# at the M=8192 bench shape (~188-190 TFLOPS; an earlier 199-vs-188
# reading predated full-output consumption and was DCE-inflated) — and
# (1024, 2048, 1024) is the best-known ring-kernel config at n>1.
AG_GEMM_TUNE_SPACE = (
    # world-1 XLA-dot sentinel LEADS (raises → skipped at n>1, where the
    # cached_or_first policy falls through to the ring kernel below)
    AGGemmConfig(0, 0, 0),
    AGGemmConfig(1024, 2048, 1024),
    AGGemmConfig(512, 2048, 512),
    AGGemmConfig(512, 2048, 1024),
    AGGemmConfig(512, 2048, 2048),
    AGGemmConfig(512, 1024, 512),
    AGGemmConfig(256, 1024, 512),
    # chunks_per_shard axis (ISSUE 3): chunk-granular ring overlap over the
    # best-known tiles. Listed AFTER every chunk=1 candidate, so the
    # sweep-free walks (cached_or_first / interpreter) can never pick a
    # chunked schedule untimed, and a sweep only crowns one that beats the
    # legacy leader by the paired-confirmation margin — the tuner cannot
    # regress below today's schedule by construction.
    AGGemmConfig(1024, 2048, 1024, chunks_per_shard=2),
    AGGemmConfig(1024, 2048, 1024, chunks_per_shard=4),
    AGGemmConfig(512, 2048, 512, chunks_per_shard=4),
    AGGemmConfig(512, 2048, 1024, chunks_per_shard=8),
)

ag_gemm_op = contextual_autotune(AG_GEMM_TUNE_SPACE, name="ag_gemm")(ag_gemm_op)
