"""Custom VJPs for the fused distributed GEMMs — training support.

The reference is an inference kernel library (SURVEY.md §2.3: no DP/PP, no
training-side ops); a TPU framework must also train, and the algebra is a
gift: **the backward of AG-GEMM is GEMM-RS and vice versa**, so the fused
forward kernels are their own fused backward:

  C = AG(A) @ B          (column-parallel fwd)
    dA = psum_scatter(dC @ Bᵀ)  = gemm_rs(dC, Bᵀ)
    dB = AG(A)ᵀ @ dC            (AG(A) is free — the fwd workspace)

  C = psum_scatter(A @ B)  (row-parallel fwd)
    dA = AG(dC) @ Bᵀ            = ag_gemm(dC, Bᵀ)
    dB = Aᵀ @ AG(dC)            (AG(dC) is the ag_gemm workspace)

Use ``ag_gemm_grad`` / ``gemm_rs_grad`` inside ``shard_map`` wherever the
non-differentiable ``ops.ag_gemm`` / ``ops.gemm_rs`` would appear in a
training step.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def ag_gemm_grad(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    ag_config: AGGemmConfig | None = None,
    rs_config: GemmRSConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Differentiable fused ``all_gather(a) @ b`` (call inside shard_map)."""
    return ag_gemm(a, b, axis=axis, config=ag_config, interpret=interpret)


def _ag_gemm_fwd(a, b, axis, ag_config, rs_config, interpret):
    out, a_full = ag_gemm(
        a, b, axis=axis, config=ag_config, gather_output=True, interpret=interpret
    )
    return out, (a_full, b)


def _ag_gemm_bwd(axis, ag_config, rs_config, interpret, res, dc):
    a_full, b = res
    da = gemm_rs(
        dc, b.T, axis=axis, config=rs_config, out_dtype=dc.dtype,
        interpret=interpret,
    )
    db = jnp.dot(
        a_full.T, dc, preferred_element_type=jnp.float32
    ).astype(b.dtype)
    return da, db


ag_gemm_grad.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def gemm_rs_grad(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    rs_config: GemmRSConfig | None = None,
    ag_config: AGGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Differentiable fused ``psum_scatter(a @ b)`` (call inside shard_map)."""
    return gemm_rs(a, b, axis=axis, config=rs_config, interpret=interpret)


def _gemm_rs_fwd(a, b, axis, rs_config, ag_config, interpret):
    out = gemm_rs(a, b, axis=axis, config=rs_config, interpret=interpret)
    return out, (a, b)


def _gemm_rs_bwd(axis, rs_config, ag_config, interpret, res, dc):
    a, b = res
    n = int(jax.lax.axis_size(axis))
    if n == 1:
        dc_full = dc
        da = jnp.dot(dc, b.T, preferred_element_type=jnp.float32).astype(a.dtype)
    else:
        da, dc_full = ag_gemm(
            dc, b.T, axis=axis, config=ag_config, gather_output=True,
            out_dtype=a.dtype, interpret=interpret,
        )
    db = jnp.dot(
        a.T, dc_full, preferred_element_type=jnp.float32
    ).astype(b.dtype)
    return da, db


gemm_rs_grad.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention_grad(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "tp",
    causal: bool = True,
    config: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """Differentiable sequence-parallel ring attention (call inside
    shard_map) — the training-side SP the reference lacks entirely
    (SURVEY.md §5: prefill ring attention is "not implemented" there).

    Forward = the fused ring kernel (ops/ring_attention.py). Backward uses
    the standard flash-attention gradient algebra on the gathered sequence:
    one all_gather of (k ‖ v), local dq for the PE's query rows, and a
    reduce-scatter returning each dk/dv chunk to its owner — two
    collectives total, with the saved per-row log-sum-exp avoiding any
    softmax recomputation instability.
    """
    from triton_dist_tpu.ops.ring_attention import ring_attention

    return ring_attention(
        q, k, v, axis=axis, causal=causal, config=config, interpret=interpret
    )


def _ring_attn_fwd(q, k, v, axis, causal, config, interpret):
    from triton_dist_tpu.ops.ring_attention import ring_attention

    out, lse = ring_attention(
        q, k, v, axis=axis, causal=causal, config=config,
        return_lse=True, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _ring_attn_bwd(axis, causal, config, interpret, res, dout):
    import math

    q, k, v, out, lse = res
    b, h, s_loc, d = q.shape
    bh = b * h
    n = int(jax.lax.axis_size(axis))
    me = jax.lax.axis_index(axis)
    scale = 1.0 / math.sqrt(d)
    f32 = jnp.float32

    q3 = q.reshape(bh, s_loc, d).astype(f32)
    dout3 = dout.reshape(bh, s_loc, d).astype(f32)
    out3 = out.reshape(bh, s_loc, d).astype(f32)
    lse3 = lse.reshape(bh, s_loc)
    delta = jnp.sum(dout3 * out3, axis=-1)           # [bh, s_loc]
    rows = me * s_loc + jnp.arange(s_loc)

    # one gather: (k ‖ v) ride a single collective; kept in input dtype
    kv = jnp.stack([k.reshape(bh, s_loc, d), v.reshape(bh, s_loc, d)])
    kv_full = jax.lax.all_gather(kv, axis, axis=2, tiled=True)
    kv_chunks = kv_full.reshape(2, bh, n, s_loc, d).swapaxes(0, 2)  # [n,bh,2,...]

    # Blockwise over the n gathered KV chunks (flash-attention gradient
    # algebra with the saved lse): peak memory is one [bh, s_loc, s_loc]
    # block, matching the forward's blockwise scaling — never the full
    # [s_loc, S] matrix.
    def chunk_step(dq_acc, inp):
        kv_c, c_idx = inp
        k_c = kv_c[:, 0].astype(f32)                 # [bh, s_loc, d]
        v_c = kv_c[:, 1].astype(f32)
        s_c = jnp.einsum("bqd,bsd->bqs", q3, k_c) * scale
        if causal:
            cols = c_idx * s_loc + jnp.arange(s_loc)
            s_c = jnp.where((cols[None, :] <= rows[:, None])[None], s_c, -jnp.inf)
        p_c = jnp.exp(s_c - lse3[..., None])
        dv_c = jnp.einsum("bqs,bqd->bsd", p_c, dout3)
        ds_c = p_c * (
            jnp.einsum("bqd,bsd->bqs", dout3, v_c) - delta[..., None]
        ) * scale
        dq_acc = dq_acc + jnp.einsum("bqs,bsd->bqd", ds_c, k_c)
        dk_c = jnp.einsum("bqs,bqd->bsd", ds_c, q3)
        return dq_acc, jnp.stack([dk_c, dv_c])

    dq3, dkv_chunks = jax.lax.scan(
        chunk_step, jnp.zeros_like(q3), (kv_chunks, jnp.arange(n))
    )                                                # dkv_chunks [n, 2, bh, s_loc, d]
    # one scatter: (dk ‖ dv) chunks return to their owner PEs pre-reduced
    dkv = jax.lax.psum_scatter(
        jnp.moveaxis(dkv_chunks, 0, 2).reshape(2, bh, n * s_loc, d),
        axis, scatter_dimension=2, tiled=True,
    )
    dq = dq3.reshape(b, h, s_loc, d).astype(q.dtype)
    dk = dkv[0].reshape(b, h, s_loc, d).astype(k.dtype)
    dv = dkv[1].reshape(b, h, s_loc, d).astype(v.dtype)
    return dq, dk, dv


ring_attention_grad.defvjp(_ring_attn_fwd, _ring_attn_bwd)
