"""Custom VJPs for the fused distributed GEMMs — training support.

The reference is an inference kernel library (SURVEY.md §2.3: no DP/PP, no
training-side ops); a TPU framework must also train, and the algebra is a
gift: **the backward of AG-GEMM is GEMM-RS and vice versa**, so the fused
forward kernels are their own fused backward:

  C = AG(A) @ B          (column-parallel fwd)
    dA = psum_scatter(dC @ Bᵀ)  = gemm_rs(dC, Bᵀ)
    dB = AG(A)ᵀ @ dC            (AG(A) is free — the fwd workspace)

  C = psum_scatter(A @ B)  (row-parallel fwd)
    dA = AG(dC) @ Bᵀ            = ag_gemm(dC, Bᵀ)
    dB = Aᵀ @ AG(dC)            (AG(dC) is the ag_gemm workspace)

Use ``ag_gemm_grad`` / ``gemm_rs_grad`` inside ``shard_map`` wherever the
non-differentiable ``ops.ag_gemm`` / ``ops.gemm_rs`` would appear in a
training step.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs
from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
from triton_dist_tpu.utils import axis_size as _axis_size


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def ag_gemm_grad(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    ag_config: AGGemmConfig | None = None,
    rs_config: GemmRSConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Differentiable fused ``all_gather(a) @ b`` (call inside shard_map)."""
    return ag_gemm(a, b, axis=axis, config=ag_config, interpret=interpret)


def _ag_gemm_fwd(a, b, axis, ag_config, rs_config, interpret):
    out, a_full = ag_gemm(
        a, b, axis=axis, config=ag_config, gather_output=True, interpret=interpret
    )
    return out, (a_full, b)


def _ag_gemm_bwd(axis, ag_config, rs_config, interpret, res, dc):
    a_full, b = res
    da = gemm_rs(
        dc, b.T, axis=axis, config=rs_config, out_dtype=dc.dtype,
        interpret=interpret,
    )
    db = jnp.dot(
        a_full.T, dc, preferred_element_type=jnp.float32
    ).astype(b.dtype)
    return da, db


ag_gemm_grad.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def gemm_rs_grad(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    rs_config: GemmRSConfig | None = None,
    ag_config: AGGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Differentiable fused ``psum_scatter(a @ b)`` (call inside shard_map)."""
    return gemm_rs(a, b, axis=axis, config=rs_config, interpret=interpret)


def _gemm_rs_fwd(a, b, axis, rs_config, ag_config, interpret):
    out = gemm_rs(a, b, axis=axis, config=rs_config, interpret=interpret)
    return out, (a, b)


def _gemm_rs_bwd(axis, rs_config, ag_config, interpret, res, dc):
    a, b = res
    n = _axis_size(axis)
    if n == 1:
        dc_full = dc
        da = jnp.dot(dc, b.T, preferred_element_type=jnp.float32).astype(a.dtype)
    else:
        da, dc_full = ag_gemm(
            dc, b.T, axis=axis, config=ag_config, gather_output=True,
            out_dtype=a.dtype, interpret=interpret,
        )
    db = jnp.dot(
        a.T, dc_full, preferred_element_type=jnp.float32
    ).astype(b.dtype)
    return da, db


gemm_rs_grad.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_attention_grad(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "tp",
    causal: bool = True,
    config: Any = None,
    interpret: Any = None,
    layout: str = "contig",
) -> jax.Array:
    """Differentiable sequence-parallel ring attention (call inside
    shard_map) — the training-side SP the reference lacks entirely
    (SURVEY.md §5: prefill ring attention is "not implemented" there).

    Forward = the fused ring kernel (ops/ring_attention.py). Backward uses
    the standard flash-attention gradient algebra on the gathered sequence:
    one all_gather of (k ‖ v), local dq for the PE's query rows, and a
    reduce-scatter returning each dk/dv chunk to its owner — two
    collectives total, with the saved per-row log-sum-exp avoiding any
    softmax recomputation instability.
    """
    from triton_dist_tpu.ops.ring_attention import ring_attention

    return ring_attention(
        q, k, v, axis=axis, causal=causal, config=config, layout=layout,
        interpret=interpret,
    )


def _ring_attn_fwd(q, k, v, axis, causal, config, interpret, layout="contig"):
    from triton_dist_tpu.ops.ring_attention import ring_attention

    out, lse = ring_attention(
        q, k, v, axis=axis, causal=causal, config=config, layout=layout,
        return_lse=True, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _ring_attn_bwd(axis, causal, config, interpret, layout, res, dout):
    import math

    from triton_dist_tpu.ops.ring_attention import zigzag_positions

    q, k, v, out, lse = res
    b, h, s_loc, d = q.shape
    bh = b * h
    n = _axis_size(axis)
    me = jax.lax.axis_index(axis)
    scale = 1.0 / math.sqrt(d)
    f32 = jnp.float32

    q3 = q.reshape(bh, s_loc, d).astype(f32)
    dout3 = dout.reshape(bh, s_loc, d).astype(f32)
    out3 = out.reshape(bh, s_loc, d).astype(f32)
    lse3 = lse.reshape(bh, s_loc)
    delta = jnp.sum(dout3 * out3, axis=-1)           # [bh, s_loc]
    if layout == "zigzag":
        rows = zigzag_positions(me, n, s_loc)
    else:
        rows = me * s_loc + jnp.arange(s_loc)

    # one gather: (k ‖ v) ride a single collective; kept in input dtype
    kv = jnp.stack([k.reshape(bh, s_loc, d), v.reshape(bh, s_loc, d)])
    kv_full = jax.lax.all_gather(kv, axis, axis=2, tiled=True)
    kv_chunks = kv_full.reshape(2, bh, n, s_loc, d).swapaxes(0, 2)  # [n,bh,2,...]

    # Blockwise over the n gathered KV chunks (flash-attention gradient
    # algebra with the saved lse): peak memory is one [bh, s_loc, s_loc]
    # block, matching the forward's blockwise scaling — never the full
    # [s_loc, S] matrix.
    def chunk_step(dq_acc, inp):
        kv_c, c_idx = inp
        k_c = kv_c[:, 0].astype(f32)                 # [bh, s_loc, d]
        v_c = kv_c[:, 1].astype(f32)
        s_c = jnp.einsum("bqd,bsd->bqs", q3, k_c) * scale
        if causal:
            if layout == "zigzag":
                cols = zigzag_positions(c_idx, n, s_loc)
            else:
                cols = c_idx * s_loc + jnp.arange(s_loc)
            s_c = jnp.where((cols[None, :] <= rows[:, None])[None], s_c, -jnp.inf)
        p_c = jnp.exp(s_c - lse3[..., None])
        dv_c = jnp.einsum("bqs,bqd->bsd", p_c, dout3)
        ds_c = p_c * (
            jnp.einsum("bqd,bsd->bqs", dout3, v_c) - delta[..., None]
        ) * scale
        dq_acc = dq_acc + jnp.einsum("bqs,bsd->bqd", ds_c, k_c)
        dk_c = jnp.einsum("bqs,bqd->bsd", ds_c, q3)
        return dq_acc, jnp.stack([dk_c, dv_c])

    dq3, dkv_chunks = jax.lax.scan(
        chunk_step, jnp.zeros_like(q3), (kv_chunks, jnp.arange(n))
    )                                                # dkv_chunks [n, 2, bh, s_loc, d]
    # one scatter: (dk ‖ dv) chunks return to their owner PEs pre-reduced
    dkv = jax.lax.psum_scatter(
        jnp.moveaxis(dkv_chunks, 0, 2).reshape(2, bh, n * s_loc, d),
        axis, scatter_dimension=2, tiled=True,
    )
    dq = dq3.reshape(b, h, s_loc, d).astype(q.dtype)
    dk = dkv[0].reshape(b, h, s_loc, d).astype(k.dtype)
    dv = dkv[1].reshape(b, h, s_loc, d).astype(v.dtype)
    return dq, dk, dv


ring_attention_grad.defvjp(_ring_attn_fwd, _ring_attn_bwd)


def _block_outer_accumulate(
    a_sorted, g_sorted, expert_ids, n_exp, config, interpret=None,
    assume_sorted=False, valid_rows=None,
):
    """``dW[e] = Σ_{blocks of e} A_blkᵀ @ G_blk`` — the transpose grouped
    GEMM, as a fused MXU kernel (``ops.group_gemm.group_gemm_dw``: expert
    ids steer the output BlockSpec, consecutive same-expert visits
    accumulate in VMEM). ``valid_rows`` (ragged, ISSUE 5): dead row panels
    skip the contraction and the tail panel's masked rows are zeroed
    in-kernel."""
    from triton_dist_tpu.ops.group_gemm import group_gemm_dw

    return group_gemm_dw(
        a_sorted, g_sorted, expert_ids, n_exp, valid_rows=valid_rows,
        config=config, assume_sorted=assume_sorted, interpret=interpret,
    )


def _tp_moe_forward_impl(x, w_up, w_down, topk_ids, topk_weights, axis,
                         activation, gg_config, interpret, overlap,
                         w_up_scale=None, w_down_scale=None):
    """Shared forward of the MoE TP MLP. ``overlap=True`` runs the two
    single-kernel overlapped ops over the rank-major alignment (comm rides
    under the grouped GEMMs); ``overlap=False`` is the sequential
    composition (the A/B baseline and the fallback). Both return
    ``(out, res)`` with the SAME residual structure — the backward is
    layout-agnostic through the global-view alignment.

    ``w_up_scale`` / ``w_down_scale`` (ISSUE 8 satellite — the PR 7 noted
    follow-up) mark the banks as PRE-QUANTIZED int8 pools with explicit
    per-(expert, out-column) scales: every grouped GEMM receives the
    ``scale=`` operand directly, so single-pass serving callers stop
    paying ``resolve_w8``'s on-the-fly quantize bank read+write."""
    if (w_up_scale is None) != (w_down_scale is None):
        raise ValueError(
            "pass both w_up_scale and w_down_scale (pre-quantized serving "
            "banks), or neither"
        )
    if w_up_scale is not None:
        from triton_dist_tpu.ops.group_gemm import FP8_DTYPE

        if (w_up.dtype not in (jnp.int8, FP8_DTYPE)
                or w_down.dtype not in (jnp.int8, FP8_DTYPE)):
            raise ValueError(
                f"explicit scales mark the banks as int8/fp8 pools; got "
                f"w_up {w_up.dtype}, w_down {w_down.dtype} — quantize with "
                f"ops.quantize_expert_weights(_fp8) first"
            )
    from triton_dist_tpu.ops.allgather_group_gemm import (
        ag_group_gemm,
        ag_group_gemm_overlap,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.ops.moe_reduce_rs import (
        moe_reduce_rs,
        moe_reduce_rs_overlap,
    )
    from triton_dist_tpu.ops.moe_utils import (
        moe_align_ranked,
        ranked_global_view,
        ranked_scatter_meta,
    )

    n = _axis_size(axis)
    m_loc = x.shape[0]
    n_exp = w_up.shape[0]
    topk = topk_ids.shape[1]
    tw_full = jax.lax.all_gather(topk_weights, axis, tiled=True)
    if overlap and n == 1:
        # world-1: there is nothing to overlap — the up-projection already
        # degenerates to the grid group_gemm and the down-projection to
        # the XLA scatter path, so the "overlap" pipeline IS the
        # sequential composition. Route it there outright (one code path,
        # identical graphs; ≙ ag_gemm's world-1 collapse).
        overlap = False
    if overlap and getattr(
        gg_config or GroupGemmConfig(), "backend", "pallas"
    ) != "pallas":
        # the jax.lax.ragged_dot sentinel (VERDICT r5 #1) needs globally
        # expert-sorted blocks — the rank-major overlap layout is sorted
        # only per rank segment, so the sentinel A/Bs through the
        # sequential composition
        overlap = False
    if overlap:
        cfg = gg_config or GroupGemmConfig()
        ids_full = jax.lax.all_gather(topk_ids, axis, tiled=True)
        ral = moe_align_ranked(
            ids_full.reshape(n, m_loc * topk), n_exp, cfg.block_m, m_loc,
            ragged=cfg.ragged,
        )
        h_sorted, a_sorted = ag_group_gemm_overlap(
            x, w_up, ral, axis=axis, config=cfg, gather_output=True,
            scale=w_up_scale, interpret=interpret,
        )
        act = activation(h_sorted.astype(jnp.float32)).astype(x.dtype)
        alignment = ranked_global_view(ral, m_loc, topk)
        # n >= 2 here: world-1 routed to the sequential branch above
        dst_ids, w_rows = ranked_scatter_meta(ral, tw_full)
        out = moe_reduce_rs_overlap(
            act, w_down, ral.expert_ids, dst_ids, w_rows, axis=axis,
            m_out=m_loc, valid_rows=ral.valid_rows, config=cfg,
            scale=w_down_scale, out_dtype=x.dtype, interpret=interpret,
        ).astype(x.dtype)
    else:
        h_sorted, alignment, a_sorted = ag_group_gemm(
            x, w_up, topk_ids, axis=axis, config=gg_config,
            gather_output=True, scale=w_up_scale, interpret=interpret,
        )
        # no standalone activation pass: it rides the down-GEMM's A-tile
        # load (group_gemm act_fn) — h_sorted stays pre-activation, which
        # is exactly what the backward's residual wants
        out = moe_reduce_rs(
            h_sorted, w_down, alignment, tw_full, axis=axis,
            n_tokens=n * m_loc, config=gg_config, out_dtype=x.dtype,
            act_fn=activation, scale=w_down_scale, interpret=interpret,
        ).astype(x.dtype)
    # a_sorted: block-aligned gathered rows [t_pad, H] — BOTH paths return
    # the sorted slab (the backward's direct input; raw gathered tokens are
    # never needed again). Scales ride the residual so the backward can
    # dequantize int8 banks for its straight-through grouped GEMMs.
    res = (a_sorted, h_sorted, tw_full, alignment, w_up, w_down, m_loc,
           w_up_scale, w_down_scale)
    return out, res


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def tp_moe_mlp_grad(
    x: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    topk_ids: jax.Array,
    topk_weights: jax.Array,
    axis: str = "tp",
    activation=jax.nn.gelu,
    gg_config: Any = None,
    interpret: Any = None,
    overlap: bool = True,
    w_up_scale: jax.Array | None = None,
    w_down_scale: jax.Array | None = None,
) -> jax.Array:
    """Differentiable fused MoE TP MLP (call inside shard_map) — the
    training path the reference lacks for its MoE ops.

    Forward (default ``overlap=True``) = the single-kernel overlapped
    AG-GroupGEMM → activation → single-kernel MoE-Reduce-RS over the
    rank-major alignment (≙ the reference's fused
    ``ag_group_gemm``/``moe_reduce_rs`` pipelines,
    allgather_group_gemm.py:420-470, moe_reduce_rs.py:882-1020);
    ``overlap=False`` keeps the sequential composition. Backward reuses
    the same algebra as the dense pair (grads above): the reduce-scatter's
    transpose is an all-gather of dout, the two grouped GEMMs backprop
    through ``group_gemm`` with per-expert transposed weights (the fused
    kernel is its own backward), expert-weight grads come from the
    block-transpose scan, and dx / d(topk_weights) return to their shards
    via one fused reduce-scatter each. y_sorted is recomputed (flash-style
    remat) rather than stored.

    x: ``[m_loc, H]``; w_up: ``[E, H, F/n]``; w_down: ``[E, F/n, H]``;
    topk_ids/topk_weights: ``[m_loc, topk]`` (ids carry a zero cotangent).
    Returns ``[m_loc, H]``.

    ``gg_config.w8`` (ISSUE 7) streams int8 weight slabs through every
    grouped GEMM of the forward — including both fused overlap kernels;
    the backward strips the axis (straight-through, full-precision banks).

    ``w_up_scale`` / ``w_down_scale`` (ISSUE 8 satellite): explicit
    per-(expert, out-column) f32 scales marking the banks as
    PRE-QUANTIZED int8 pools — the single-pass serving path that skips
    ``resolve_w8``'s on-the-fly quantize (one bank read+write per call).
    The backward stays straight-through: it dequantizes the residual int8
    banks once and differentiates against them; the scales themselves get
    zero cotangents (they are serving constants, not parameters).
    """
    out, _ = _tp_moe_forward_impl(
        x, w_up, w_down, topk_ids, topk_weights, axis, activation,
        gg_config, interpret, overlap, w_up_scale, w_down_scale,
    )
    return out


def _tp_moe_fwd(x, w_up, w_down, topk_ids, topk_weights, axis, activation,
                gg_config, interpret, overlap,
                w_up_scale=None, w_down_scale=None):
    return _tp_moe_forward_impl(
        x, w_up, w_down, topk_ids, topk_weights, axis, activation,
        gg_config, interpret, overlap, w_up_scale, w_down_scale,
    )


def _zero_cotangent(arr):
    """A type-correct zero cotangent: float0 for integer primals (jax's
    convention, as for topk_ids), zeros for float ones."""
    if jnp.issubdtype(jnp.asarray(arr).dtype, jnp.inexact):
        return jnp.zeros_like(arr)
    return np.zeros(jnp.asarray(arr).shape, jax.dtypes.float0)


def _tp_moe_bwd(axis, activation, gg_config, interpret, overlap, res, dout):
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig, group_gemm
    from triton_dist_tpu.ops.reduce_scatter import reduce_scatter

    (a_sorted, h_sorted, tw_full, al, w_up, w_down, m_loc,
     w_up_scale, w_down_scale) = res
    cfg = gg_config or GroupGemmConfig()
    # w8 (ISSUE 7) is a forward/serving format: every backward grouped
    # GEMM, the dw accumulation AND the y_sorted remat run with the axis
    # stripped, differentiating against the FULL-PRECISION residual banks
    # (straight-through — quantization's own derivative is zero a.e.).
    if getattr(cfg, "w8", False) or getattr(cfg, "fp8", False):
        cfg = dataclasses.replace(cfg, w8=False, fp8=False)
    # pre-quantized serving banks (ISSUE 8 satellite): dequantize ONCE for
    # the straight-through backward — the int8 pools are the only residual
    # there is, and the scales are constants (zero cotangents below)
    quantized = w_up_scale is not None
    w_up_q, w_down_q = w_up, w_down
    if quantized:
        w_up = (w_up.astype(jnp.float32) * w_up_scale).astype(a_sorted.dtype)
        w_down = (
            w_down.astype(jnp.float32) * w_down_scale
        ).astype(a_sorted.dtype)
    n_exp = w_up.shape[0]
    f32 = jnp.float32
    m_tot, h_dim = tw_full.shape[0], a_sorted.shape[1]
    topk = tw_full.shape[1]
    t = m_tot * topk

    # transpose of the final reduce-scatter: every PE sees the full dout
    dpartial = jax.lax.all_gather(dout, axis, tiled=True).astype(f32)  # [m_tot, H]

    ids = al.sorted_token_ids                       # [t_pad], sentinel == t
    valid = ids < t
    token_of_row = jnp.clip(ids // topk, 0, m_tot - 1)
    w_row = jnp.where(
        valid, tw_full.reshape(-1)[jnp.clip(ids, 0, t - 1)], 0.0
    ).astype(f32)                                   # [t_pad]

    # recompute act / y_sorted (remat) and the activation's local VJP
    act_f, act_vjp = jax.vjp(
        lambda h: activation(h.astype(f32)), h_sorted
    )
    act = act_f.astype(a_sorted.dtype)
    y_sorted = group_gemm(
        act, w_down, al.expert_ids, valid_rows=al.valid_rows, config=cfg,
        out_dtype=f32, interpret=interpret,
    )                                               # [t_pad, H]

    dpart_rows = dpartial[token_of_row]             # [t_pad, H]
    # d topk_weights: dot(dout_row, y_row) per valid assignment, summed
    # over PEs (each PE holds only its F-shard's contribution)
    dtw_rows = jnp.where(valid, jnp.sum(dpart_rows * y_sorted, -1), 0.0)
    dtw_full = (
        jnp.zeros((t,), f32).at[jnp.clip(ids, 0, t - 1)]
        .add(dtw_rows)  # already zeroed at invalid rows
        .reshape(m_tot, topk)
    )
    # tiny, latency-bound payload: the XLA collective, not the ring kernel
    dtw = jax.lax.psum_scatter(
        dtw_full, axis, scatter_dimension=0, tiled=True
    ).astype(tw_full.dtype)                         # [m_loc, topk]

    # back through the weighted scatter: dy_sorted = w * dout_row
    dy_sorted = (dpart_rows * w_row[:, None]).astype(act.dtype)
    # back through the down grouped GEMM (fused kernel, transposed weights)
    dact = group_gemm(
        dy_sorted, w_down.transpose(0, 2, 1), al.expert_ids,
        valid_rows=al.valid_rows, config=cfg,
        out_dtype=f32, interpret=interpret,
    )
    # global alignment is expert-sorted by construction; the rank-major
    # (overlap) layout sorts only within each rank segment
    # pre-quantized int8 banks get zero cotangents (no master copy in
    # this graph) — skip the expensive block-outer accumulations outright
    # instead of computing and discarding them
    dw_down = None
    if not quantized:
        dw_down = _block_outer_accumulate(
            act, dy_sorted, al.expert_ids, n_exp, cfg, interpret,
            assume_sorted=not overlap, valid_rows=al.valid_rows,
        ).astype(w_down.dtype)
    # through the activation
    (dh_sorted,) = act_vjp(dact)
    dh_sorted = dh_sorted.astype(a_sorted.dtype)
    # back through the up grouped GEMM (the residual IS the sorted slab;
    # sentinel rows hold clamped junk — mask them)
    a_sorted = jnp.where(valid[:, None], a_sorted, 0)
    da_sorted = group_gemm(
        dh_sorted, w_up.transpose(0, 2, 1), al.expert_ids,
        valid_rows=al.valid_rows, config=cfg,
        out_dtype=f32, interpret=interpret,
    )
    dw_up = None
    if not quantized:
        dw_up = _block_outer_accumulate(
            a_sorted, dh_sorted, al.expert_ids, n_exp, cfg, interpret,
            assume_sorted=not overlap, valid_rows=al.valid_rows,
        ).astype(w_up.dtype)
    # unsorted scatter-add back to tokens, then the all-gather's transpose
    da_full = (
        jnp.zeros((m_tot, h_dim), f32)
        .at[token_of_row]
        .add(jnp.where(valid[:, None], da_sorted, 0.0))
    )
    dx = reduce_scatter(
        da_full, axis=axis, interpret=interpret
    ).astype(a_sorted.dtype)                        # [m_loc, H]

    dids = np.zeros((m_loc, topk), jax.dtypes.float0)
    if quantized:
        # int8 primal banks cannot receive the float grads (there is no
        # master copy in this graph) — type-correct zeros, and zeros for
        # the constant scales
        return (dx, _zero_cotangent(w_up_q), _zero_cotangent(w_down_q),
                dids, dtw, jnp.zeros_like(w_up_scale),
                jnp.zeros_like(w_down_scale))
    return dx, dw_up, dw_down, dids, dtw, None, None


tp_moe_mlp_grad.defvjp(_tp_moe_fwd, _tp_moe_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fast_all_to_all_grad(
    tokens: jax.Array,
    splits: jax.Array,
    meta: jax.Array | None = None,
    axis: str = "tp",
    interpret: Any = None,
    config: Any = None,
):
    """Differentiable padded-slab all-to-all (call inside shard_map).

    The slab exchange is a self-inverse permutation of the data (slab j of
    PE i ↔ slab i of PE j; full slabs always ship, splits are metadata), so
    its VJP is the SAME exchange applied to the output cotangent — one
    fused collective each way. splits/meta are integer bookkeeping and
    carry zero cotangents. Always returns ``(recv, recv_splits,
    recv_meta-or-None)``. `config` (an ``A2AConfig``; e.g. a chunk-granular
    schedule, ISSUE 4) applies to BOTH directions — forward and cotangent
    exchange ride the same kernel family.
    """
    from triton_dist_tpu.ops.all_to_all import fast_all_to_all

    out = fast_all_to_all(
        tokens, splits, meta=meta, axis=axis, config=config,
        interpret=interpret,
    )
    if meta is None:
        recv, rs = out
        return recv, rs, None
    return out


def _a2a_fwd(tokens, splits, meta, axis, interpret, config):
    out = fast_all_to_all_grad(tokens, splits, meta, axis, interpret, config)
    # only static shapes are needed for the float0 zeros — don't keep the
    # integer arrays alive across the forward/backward gap
    return out, (out[1], splits.shape, None if meta is None else meta.shape)


def _a2a_bwd(axis, interpret, config, res, cots):
    from triton_dist_tpu.ops.all_to_all import fast_all_to_all

    recv_splits, splits_shape, meta_shape = res
    d_recv = cots[0]  # cotangent dtype matches the primal tokens dtype
    dx, _ = fast_all_to_all(
        d_recv, recv_splits, axis=axis, config=config, interpret=interpret
    )
    d_splits = np.zeros(splits_shape, jax.dtypes.float0)
    d_meta = None if meta_shape is None else np.zeros(meta_shape, jax.dtypes.float0)
    return dx, d_splits, d_meta


fast_all_to_all_grad.defvjp(_a2a_fwd, _a2a_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def group_gemm_grad(
    a_sorted: jax.Array,
    b: jax.Array,
    expert_ids: jax.Array,
    valid_rows: jax.Array | None = None,
    config: Any = None,
    out_dtype: Any = None,
    interpret: Any = None,
    assume_sorted: bool = False,
) -> jax.Array:
    """Differentiable block-aligned grouped GEMM (the scalar-prefetch MXU
    kernel is its own backward with per-expert transposed weights; the
    expert-weight grad is the block-transpose scan). ``valid_rows`` is the
    ragged per-block live-row map (zero cotangent, like ``expert_ids``);
    required when ``config.ragged`` — forward, dA and dW then all skip the
    dead panels."""
    from triton_dist_tpu.ops.group_gemm import group_gemm

    return group_gemm(
        a_sorted, b, expert_ids, valid_rows=valid_rows, config=config,
        out_dtype=out_dtype, interpret=interpret,
    )


def _gg_fwd(a_sorted, b, expert_ids, valid_rows, config, out_dtype,
            interpret, assume_sorted=False):
    out = group_gemm_grad(
        a_sorted, b, expert_ids, valid_rows, config, out_dtype, interpret,
        assume_sorted,
    )
    return out, (a_sorted, b, expert_ids, valid_rows)


def _gg_bwd(config, out_dtype, interpret, assume_sorted, res, dout):
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig, group_gemm

    a_sorted, b, expert_ids, valid_rows = res
    cfg = config or GroupGemmConfig()
    # straight-through w8/fp8: grads flow through the full-precision bank
    if getattr(cfg, "w8", False) or getattr(cfg, "fp8", False):
        cfg = dataclasses.replace(cfg, w8=False, fp8=False)
    da = group_gemm(
        dout.astype(a_sorted.dtype), b.transpose(0, 2, 1), expert_ids,
        valid_rows=valid_rows, config=cfg, out_dtype=jnp.float32,
        interpret=interpret,
    ).astype(a_sorted.dtype)
    db = _block_outer_accumulate(
        a_sorted, dout, expert_ids, b.shape[0], cfg, interpret,
        assume_sorted=assume_sorted, valid_rows=valid_rows,
    ).astype(b.dtype)
    d_ids = np.zeros(expert_ids.shape, jax.dtypes.float0)
    d_valid = (
        None if valid_rows is None
        else np.zeros(valid_rows.shape, jax.dtypes.float0)
    )
    return da, db, d_ids, d_valid


group_gemm_grad.defvjp(_gg_fwd, _gg_bwd)


def tp_moe_mlp_op(
    x: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    topk_ids: jax.Array,
    topk_weights: jax.Array,
    mesh,
    *,
    axis: str = "tp",
    config: Any = None,
    overlap: bool = True,
    activation=jax.nn.gelu,
    w_up_scale: jax.Array | None = None,
    w_down_scale: jax.Array | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry for the full MoE TP MLP (≙ the reference's
    ``ag_group_gemm`` + ``moe_reduce_rs`` test drivers composing both fused
    pipelines): x ``[m_tot, H]`` token-sharded, w_up ``[E, H, F]``
    N-sharded, w_down ``[E, F, H]`` F-sharded, routing token-sharded →
    ``[m_tot, H]`` token-sharded. Autotuned over the grouped-GEMM tiling
    (block_m is also the alignment block, so the sweep trades padding
    against tile shape — the whole two-kernel pipeline is timed per
    config, the reference's contextual-autotune discipline).

    ``w_up_scale`` / ``w_down_scale`` (ISSUE 8 satellite): pre-quantized
    int8 banks with explicit per-(expert, out-column) scales — the
    single-pass serving path that skips the on-the-fly quantize. Scales
    shard with their bank's OUT dimension (w_up's F over the axis,
    w_down's H replicated — the ``moe_quantized_param_specs`` layout)."""
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.common import jit_shard_map

    if (w_up_scale is None) != (w_down_scale is None):
        raise ValueError(
            "pass both w_up_scale and w_down_scale (pre-quantized serving "
            "banks), or neither"
        )
    has_scales = w_up_scale is not None

    def fn(x, wu, wd, ids, tw, *scales):
        us, ds_ = scales if scales else (None, None)
        return tp_moe_mlp_grad(
            x, wu, wd, ids, tw.astype(jnp.float32), axis, activation,
            config, interpret, overlap, us, ds_,
        )

    in_specs = [P(axis, None), P(None, None, axis), P(None, axis, None),
                P(axis, None), P(axis, None)]
    args = [x, w_up, w_down, topk_ids.astype(jnp.int32), topk_weights]
    if has_scales:
        in_specs += [P(None, None, axis), P(None, None, None)]
        args += [w_up_scale, w_down_scale]
    return jit_shard_map(
        fn, mesh, tuple(in_specs), P(axis, None),
        key=("tp_moe_mlp", axis, config, overlap, activation, has_scales,
             str(interpret)),
    )(*args)


def grads_all_finite(grads, *axes):
    """Traced GLOBAL finiteness predicate over a gradient pytree (call
    inside ``shard_map``) — the skip-step gate of
    ``models.tp_transformer.train_step`` (ISSUE 8 containment): a single
    non-finite element in any inexact leaf on ANY PE of the given mesh
    axes makes the whole step bad, because the collective-coupled update
    would smear the poison across every shard. Returns a traced scalar
    bool (True = safe to apply)."""
    bad = jnp.int32(0)
    for g in jax.tree_util.tree_leaves(grads):
        dt = getattr(g, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            continue  # int bookkeeping / float0 zeros cannot be poisoned
        bad = bad + jnp.logical_not(jnp.all(jnp.isfinite(g))).astype(
            jnp.int32
        )
    for ax in axes:
        if ax is not None:
            bad = jax.lax.psum(bad, ax)
    return bad == 0


# Whole-pipeline sweep: both fused kernels (or both halves of the
# sequential composition) are timed together per candidate. FIRST entry =
# best-known default (applied sweep-free under cached_or_first).
#
# Large block_m entries lead: at block_m=128 the grouped GEMM re-fetches
# each expert's K×block_n weight strip once per 128-row block, which at
# Mixtral-class shapes is ~15 GB of B traffic per GEMM — memory-bound at
# ~half the chip's dense MFU. block_m=512 cuts that 4× (the whole
# pipeline goes compute-bound) and costs only the extra alignment padding
# (expected E·block_m/2 rows ≈ 12% at the bench shape), which the
# whole-pipeline timing prices in honestly.
TP_MOE_TUNE_SPACE = (
    GroupGemmConfig(512, 1024, 512),
    GroupGemmConfig(512, 2048, 512),
    # wider-N / deeper-K at block_m=512: if the 512-row tiles close only
    # part of the measured 99.8->=140 TFLOPS gap (r3 chip log), these
    # trade more VMEM for fewer B-operand re-fetches per expert pass
    GroupGemmConfig(512, 4096, 512),
    GroupGemmConfig(512, 1024, 1024),
    # bm=256 at DEEP K (the r5 sweep only had bm=256 with bk=512, which
    # doubles the B re-fetch): half the 512-row alignment-padding tax
    # (~25% of GEMM rows at the bench shape, measured r5) while the
    # bk=1024 tile keeps the extra B traffic under the compute roof
    GroupGemmConfig(256, 1024, 1024),
    GroupGemmConfig(256, 2048, 1024),
    GroupGemmConfig(256, 1024, 512),
    GroupGemmConfig(256, 2048, 512),
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(128, 1024, 1024),
    # ragged axis (ISSUE 5, VERDICT r5 #1): the same tiles with the
    # alignment's per-block valid_rows map consumed in-kernel, so the
    # worst-case E·(block_m−1) pad rows the padded grid always computes
    # (the ~25% MoE padding tax at the bench shape) cost no MXU time.
    # Every ragged candidate sits strictly AFTER its padded twin — the
    # same no-regression ordering as the chunk axis: sweep-free walks keep
    # the proven padded leader, only a timed sweep can crown ragged. The
    # big-block ragged twins are the interesting ones: ragged removes
    # exactly the cost that made block_m=512 pay for its B-traffic win.
    GroupGemmConfig(512, 1024, 512, ragged=True),
    GroupGemmConfig(512, 2048, 512, ragged=True),
    GroupGemmConfig(512, 1024, 1024, ragged=True),
    GroupGemmConfig(256, 1024, 1024, ragged=True),
    GroupGemmConfig(128, 1024, 512, ragged=True),
    # w8 axis (ISSUE 7): int8 expert weights through the WHOLE fused
    # pipeline — both overlapped kernels stream half the weight bytes,
    # the decode regime's bound resource (the unfused moe_w8 metric
    # measured 1.404× of its ~2× ceiling). Strictly AFTER the bf16 twins
    # (quantization is a serving knob — only a timed sweep may crown it);
    # `suggest_w8_overlap` prunes it from compute-bound problems.
    GroupGemmConfig(512, 1024, 512, w8=True),
    GroupGemmConfig(256, 1024, 1024, w8=True),
    GroupGemmConfig(128, 1024, 512, w8=True),
    GroupGemmConfig(512, 1024, 512, ragged=True, w8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, w8=True),
    # fp8 axis (ISSUE 19): fp8_e4m3 expert weights at QUARTER-rate HBM
    # bytes through the same w8 slot structure — strictly after their w8
    # twins (legacy < w8 < fp8, append-only; same weight-bound pruning)
    GroupGemmConfig(512, 1024, 512, fp8=True),
    GroupGemmConfig(128, 1024, 512, fp8=True),
    GroupGemmConfig(512, 1024, 512, ragged=True, fp8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, fp8=True),
    # the XLA sentinel (VERDICT r5 #1): the whole pipeline with both
    # grouped GEMMs lowered to jax.lax.ragged_dot over the same layout
    # (sequential composition — rank-major blocks aren't globally
    # sorted). If XLA's ragged kernel beats the fused pipeline, the sweep
    # says so with a number instead of a belief.
    GroupGemmConfig(512, 1024, 512, backend="ragged_dot"),
    # chunks_per_shard axis (ISSUE 4): chunk-granular EP overlap — the
    # overlapped pipeline's ring ships each rank's aligned slab as
    # per-chunk DMAs consumed group-by-group, and the combine pushes
    # retire chunked. AFTER every chunk=1 candidate (PR 3's ordering
    # invariant): sweep-free walks can never apply one untimed, so the
    # tuner cannot regress below today's schedules.
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2),
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=4),
    GroupGemmConfig(256, 1024, 1024, chunks_per_shard=2),
    GroupGemmConfig(128, 1024, 512, chunks_per_shard=2),
    # ragged × chunked: the three-stage chunk pipeline with ragged blocks
    # (after their padded chunked twins, preserving both orderings)
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2, ragged=True),
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=4, ragged=True),
    # w8 × chunked (× ragged): strictly after the bf16 chunked twins
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2, w8=True),
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2, ragged=True, w8=True),
    # fp8 × chunked (× ragged): strictly after the w8 chunked twins, at
    # the very end of the chunked tail (append-only admission order)
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2, fp8=True),
    GroupGemmConfig(512, 1024, 512, chunks_per_shard=2, ragged=True, fp8=True),
)

def _moe_block_sensible(cfg, x, w_up, w_down, topk_ids, topk_weights,
                        mesh=None, *a, axis: str = "tp", **k):
    """Shape guard for the sweep-free walk: block_m is also the alignment
    block, so each active expert pads to a block_m multiple — expected
    E·block_m/2 padding rows. Candidates whose expected padding exceeds
    ~25% of the problem's t = tokens·topk real rows are never sensible,
    however fast their tiles; the 128-row entries always stay viable.

    Chunked candidates additionally pass the perf model's pruning hook
    (ISSUE 4 satellite): the ring suggester prices the per-rank aligned
    slab this problem would ship per ring step — chunk counts it calls
    dominated are never timed nor applied; chunk=1 candidates always
    survive.

    Ragged candidates (incl. the ragged_dot sentinel) pass the padding-tax
    hook (ISSUE 5): ``perf_model.suggest_ragged`` prices the pad rows the
    padded grid would compute for THIS problem — when the tax is already
    negligible (counts divisible by the block, or the block no bigger than
    the MXU row panel) ragged cannot help and is never timed nor applied;
    padded candidates always survive.

    w8 candidates (ISSUE 7) pass ``perf_model.suggest_w8_overlap``: the
    weight-bound predicate (bf16 weight stream time vs MXU time — purely a
    function of t and E, the K·N factors cancel). bf16 candidates are
    never subject to it, so pruning can never remove a bf16 chunk=1
    candidate."""
    t = topk_ids.shape[0] * topk_ids.shape[1]
    if cfg.block_m > 128 and w_up.shape[0] * cfg.block_m > t // 2:
        return False
    if getattr(cfg, "w8", False) or getattr(cfg, "fp8", False):
        # weight-bound hook (ISSUE 7/19): bf16 candidates are NEVER
        # subject to it — pruning can only remove w8/fp8 candidates, so
        # the bf16 chunk=1 leaders always survive.
        from triton_dist_tpu import perf_model

        if not perf_model.suggest_w8_overlap(t, w_up.shape[0]):
            return False
    if getattr(cfg, "ragged", False) or (
        getattr(cfg, "backend", "pallas") != "pallas"
    ):
        from triton_dist_tpu import perf_model

        # the overlap pipeline aligns PER RANK (n independent alignments,
        # each with its own E·(block_m−1) worst-case slack), so the tax is
        # priced on one rank's t/n rows — the global-t form would
        # under-state the slack n× and prune ragged exactly at the
        # mid-size shapes where it still pays. mesh=None prices one rank
        # conservatively (per-rank tax >= global tax, so pruning only
        # gets LESS aggressive without world knowledge).
        n = 1
        if mesh is not None:
            n = (
                int(mesh.shape[axis]) if axis in mesh.shape
                else int(mesh.devices.size)
            )
        t_loc = max(1, t // max(n, 1))
        counts = None
        try:
            import numpy as _np

            counts = _np.bincount(
                _np.asarray(topk_ids).reshape(-1), minlength=w_up.shape[0]
            ) // max(n, 1)
        except Exception:
            pass  # traced ids: fall back to the expected-tax form
        if not perf_model.suggest_ragged(
            t_loc, w_up.shape[0], cfg.block_m, counts=counts
        ):
            return False
    if getattr(cfg, "chunks_per_shard", 1) <= 1 or mesh is None:
        return True
    from triton_dist_tpu import perf_model

    n = int(mesh.shape[axis]) if axis in mesh.shape else int(mesh.devices.size)
    # per-rank ring-step payload: the block-aligned slab ≈ (local
    # assignments + expert padding) × hidden bytes
    t_pad_loc = t // max(n, 1) + w_up.shape[0] * cfg.block_m // 2
    shard_bytes = t_pad_loc * x.shape[1] * x.dtype.itemsize
    return bool(
        perf_model.prune_chunk_candidates((cfg,), shard_bytes, n)
    )


tp_moe_mlp_op = contextual_autotune(
    TP_MOE_TUNE_SPACE, name="tp_moe_mlp", precondition=_moe_block_sensible
)(tp_moe_mlp_op)
