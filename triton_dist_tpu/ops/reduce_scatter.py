"""ReduceScatter kernel family (≙ reference ``kernels/nvidia/reduce_scatter.py``, 876 LoC).

The reference's ``ReduceScatter2DContext`` pipeline (reduce_scatter.py:47-142)
has two stages we keep, re-designed TPU-native, plus the classic ring:

- ``scatter_reduce`` — every PE pushes chunk j of its partial array directly
  to PE j's landing slots, then each PE locally reduces its n landed chunks
  in one VMEM pass (≙ intra-node scatter :604-637 + ``add_continuous_kernel``
  :185). All DMAs are issued up front with no compute in the dependency
  chain; reduction is a single f32 accumulation (best numerics). Bytes sent
  per PE equal the ring's, but non-neighbor puts are hardware-routed across
  multiple ICI hops, so for large payloads on a torus the ring wins.
- ``ring`` — bandwidth-optimal neighbor ring (≙ the reference's 1-D intra-
  node ring variants :427-521): step s waits chunk ``me-1-s`` from the left,
  adds the local partial, forwards right; the final add lands in ``out_ref``.
  One round-off per hop (carry dtype), like any ring reduce.

Method choice mirrors ``get_auto_all_gather_method`` (allgather.py:44-69):
latency-bound sizes and wraparound-less topologies take ``scatter_reduce``,
large payloads on a ring topology take ``ring``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import chunk_schedule, dist_pallas_call, jit_shard_map
from triton_dist_tpu.parallel import topology
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def _reduce_scatter_xla(x: jax.Array, *, axis="tp", **_) -> jax.Array:
    """The golden slow path: XLA's psum-scatter, single- or multi-axis."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jax.lax.psum_scatter(x, axes, tiled=True)


@dataclasses.dataclass(frozen=True)
class ReduceScatterConfig:
    """Tunables (≙ the tile knobs of ``ReduceScatter2DContext``; stream and
    buffer plumbing is subsumed by the fused kernels). ``method`` pins the
    kernel family (None = honor the call's ``method=`` argument) so the
    autotuner can sweep method × tiles in one space."""

    block_m: int = 256
    block_n: int = 1024
    method: str | None = None
    # Ring-step payload granularity (ISSUE 3): > 1 splits each hop's chunk
    # into that many per-chunk DMAs whose add-pipeline runs the moment each
    # lands; 1 is the legacy shard-granular staging, bit for bit. Ring
    # method only (scatter_reduce's puts are single-hop).
    chunks_per_shard: int = 1


def get_auto_reduce_scatter_method(
    chunk_bytes: int, n_pes: int, devices: Any = None
) -> str:
    from triton_dist_tpu.perf_model import direct_vs_ring_crossover_bytes

    if n_pes <= 2 or not topology.has_wraparound(n_pes, devices):
        return "scatter_reduce"
    # model-driven crossover (same wire shape as the allgather choice:
    # direct routed puts vs neighbor ring; tracks ICI BW)
    if chunk_bytes <= direct_vs_ring_crossover_bytes(n_pes):
        return "scatter_reduce"
    return "ring"


def _add2_pipeline(bm: int, bn: int, m_loc: int, n_dim: int, out_dtype):
    """VMEM-tiled ``o = a + b`` in f32 (≙ ``add_continuous_kernel``,
    reference reduce_scatter.py:185)."""

    def add_body(a_blk, b_blk, o_blk):
        o_blk[:] = (
            a_blk[:].astype(jnp.float32) + b_blk[:].astype(jnp.float32)
        ).astype(out_dtype)

    return pltpu.emit_pipeline(
        add_body,
        grid=(m_loc // bm, n_dim // bn),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )


def _ring_rs_kernel(
    x_ref, out_ref, recv_buf, acc_buf, send_sems, recv_sems,
    *, axis: str, n: int, cfg: ReduceScatterConfig,
):
    # recv_buf/acc_buf are pallas *outputs* used as workspace: an output is
    # how a kernel gets a private HBM allocation, and the TPU interpreter's
    # emit_pipeline only accepts kernel-arg HBM refs.
    me = shmem.my_pe(axis)
    m_loc, n_dim = out_ref.shape
    bm = pick_block(m_loc, cfg.block_m)
    bn = pick_block(n_dim, cfg.block_n)
    add = _add2_pipeline(bm, bn, m_loc, n_dim, out_ref.dtype)

    # race shaking (no-op unless config.debug_comm_delay)
    shmem.comm_jitter(axis, salt=6)
    # All PEs must be inside the kernel before any remote DMA may land in
    # their landing slots (≙ barrier_all before scatter, reference
    # reduce_scatter.py:604-610).
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    sends = []
    # Step 0: own untouched chunk me-1 starts its trip around the ring.
    c0 = pl.ds(jax.lax.rem(me - 1 + n, n) * m_loc, m_loc)
    sends.append(
        shmem.putmem_nbi_block(
            recv_buf.at[0], x_ref.at[c0], right, axis,
            send_sems.at[0], recv_sems.at[0],
        )
    )
    for s in range(1, n):
        c = pl.ds(jax.lax.rem(me - 1 - s + 2 * n, n) * m_loc, m_loc)
        sends[s - 1].wait_recv()  # chunk me-1-s landed in recv_buf[s-1]
        if s == n - 1:
            add(x_ref.at[c], recv_buf.at[s - 1], out_ref)
        else:
            acc = acc_buf.at[s % 2]
            if s >= 3:
                # acc slot s%2 was the source of the step s-2 put.
                sends[s - 2].wait_send()
            add(x_ref.at[c], recv_buf.at[s - 1], acc)
            sends.append(
                shmem.putmem_nbi_block(
                    recv_buf.at[s], acc, right, axis,
                    send_sems.at[s], recv_sems.at[s],
                )
            )
    shmem.quiet(*sends)


def _ring_rs_chunked_kernel(
    x_ref, out_ref, recv_buf, acc_buf, send_sems, recv_sems, sig_sems,
    *, axis: str, n: int, cfg: ReduceScatterConfig, spans,
):
    """Chunk-granular ring reduce-scatter (ISSUE 3 tentpole): the
    add-pipeline of step ``s`` runs on chunk ``j`` the moment chunk ``j``
    of the incoming partial lands, and forwards it immediately — per-hop
    staging exposes one *chunk* of ICI latency, not one m_loc-row shard.
    chunk=1 dispatches to :func:`_ring_rs_kernel` (bit-identical legacy)."""
    me = shmem.my_pe(axis)
    m_loc, n_dim = out_ref.shape
    bn = pick_block(n_dim, cfg.block_n)
    adds = [
        _add2_pipeline(
            pick_block(rows, cfg.block_m), bn, rows, n_dim, out_ref.dtype
        )
        for _, rows in spans
    ]

    shmem.comm_jitter(axis, salt=6)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    sends = []
    # Step 0: own untouched chunk me-1 starts its trip, chunk by chunk.
    # Landing view (ISSUE 8 canary, wired per ISSUE 11): by SPMD symmetry
    # the left neighbor's step-s put lands in OUR recv_buf[s] at the same
    # span coordinates it addressed on us — the dst and landing views
    # coincide for this staging buffer.
    c0base = jax.lax.rem(me - 1 + n, n) * m_loc
    sends.append(
        shmem.putmem_signal_chunked_nbi_block(
            lambda off, rows: recv_buf.at[0, pl.ds(off, rows)],
            lambda off, rows: x_ref.at[pl.ds(c0base + off, rows)],
            right, axis,
            lambda j: send_sems.at[0, j],
            lambda j: recv_sems.at[0, j],
            lambda j: sig_sems.at[0, j],
            spans,
            recv_view=lambda off, rows: recv_buf.at[0, pl.ds(off, rows)],
        )
    )
    for s in range(1, n):
        cbase = jax.lax.rem(me - 1 - s + 2 * n, n) * m_loc
        handles = []
        for j, (off, rows) in enumerate(spans):
            sends[s - 1].wait_recv_chunk(j)  # chunk j of partial landed
            sl_x = pl.ds(cbase + off, rows)
            sl = pl.ds(off, rows)
            if s == n - 1:
                adds[j](x_ref.at[sl_x], recv_buf.at[s - 1, sl], out_ref.at[sl])
            else:
                if s >= 3:
                    # acc rows were the source of the step s-2 put
                    sends[s - 2].wait_send_chunk(j)
                acc = acc_buf.at[s % 2, sl]
                adds[j](x_ref.at[sl_x], recv_buf.at[s - 1, sl], acc)
                handles.append(
                    shmem.putmem_signal2_nbi_block(
                        recv_buf.at[s, sl], acc, right, axis,
                        send_sems.at[s, j], recv_sems.at[s, j],
                        sig_sems.at[s, j], canary=True,
                    )
                )
        if handles:
            sends.append(shmem.ChunkedPutHandle(
                handles,
                recv_at=lambda off, rows, s=s: recv_buf.at[
                    s, pl.ds(off, rows)
                ],
                spans=spans,
            ))
    shmem.quiet(*sends)


def _scatter_reduce_kernel(
    x_ref, out_ref, recv_buf, send_sems, recv_sems,
    *, axis: str, n: int, cfg: ReduceScatterConfig,
):
    me = shmem.my_pe(axis)
    m_loc, n_dim = out_ref.shape
    bm = pick_block(m_loc, cfg.block_m)
    bn = pick_block(n_dim, cfg.block_n)
    shmem.comm_jitter(axis, salt=7)
    shmem.barrier_all(axis)

    # Push chunk me+d of our partial straight to its owner. Landing slot
    # d-1 on the receiver holds the chunk from PE me-d: every sender→
    # receiver pair picks a distinct slot by symmetry, the same trick the
    # reference plays with per-rank segments of its symmetric scatter buf
    # (reduce_scatter.py:614-625).
    sends = []
    for d in range(1, n):
        dst = jax.lax.rem(me + d, n)
        sends.append(
            shmem.putmem_nbi_block(
                recv_buf.at[d - 1], x_ref.at[pl.ds(dst * m_loc, m_loc)],
                dst, axis, send_sems.at[d - 1], recv_sems.at[d - 1],
            )
        )
    # Symmetric SPMD: our own descriptors' recv side counts the incoming
    # equal-sized chunks, so this waits for all n-1 arrivals.
    for desc in sends:
        desc.wait_recv()

    # One n-way f32 accumulation pass over VMEM tiles
    # (≙ add_continuous_kernel, but fused across all sources).
    def reduce_body(*blks):
        o_blk = blks[-1]
        acc = blks[0][:].astype(jnp.float32)
        for b in blks[1:-1]:
            acc = acc + b[:].astype(jnp.float32)
        o_blk[:] = acc.astype(out_ref.dtype)

    blk = lambda i, j: (i, j)  # noqa: E731
    pltpu.emit_pipeline(
        reduce_body,
        grid=(m_loc // bm, n_dim // bn),
        in_specs=[pl.BlockSpec((bm, bn), blk)] * n,
        out_specs=[pl.BlockSpec((bm, bn), blk)],
    )(
        x_ref.at[pl.ds(me * m_loc, m_loc)],
        *(recv_buf.at[d] for d in range(n - 1)),
        out_ref,
    )
    shmem.quiet(*sends)


def reduce_scatter(
    x: jax.Array,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: ReduceScatterConfig | None = None,
    interpret: Any = None,
    devices: Any = None,
) -> jax.Array:
    """Reduce-scatter along mesh `axis` (call inside ``jax.shard_map``).

    `x` is this PE's full partial array ``(n*m_loc, n_dim)``; returns
    ``(m_loc, n_dim)`` — the sum over PEs of rows ``[me*m_loc, (me+1)*m_loc)``.
    Golden: ``jax.lax.psum_scatter(x, axis, tiled=True)``
    (≙ ``reduce_scatter_2d_op``, reference reduce_scatter.py:863) — served
    automatically when the fused kernel cannot run in this environment
    (resilience layer, docs/resilience.md).
    """
    return resilience.guarded_call(
        "reduce_scatter",
        _reduce_scatter_fused,
        _reduce_scatter_xla,
        x, axis=axis, method=method, config=config, interpret=interpret,
        devices=devices,
    )


def _reduce_scatter_fused(
    x: jax.Array,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: ReduceScatterConfig | None = None,
    interpret: Any = None,
    devices: Any = None,
) -> jax.Array:
    if isinstance(axis, (tuple, list)):
        if len(axis) == 1:
            axis = axis[0]
        else:
            # N-D (any >= 2 axes): peel the outermost axis with the permuted
            # staging of the reference's 2-D pipeline (intra-scatter →
            # local reduce → inter hop, reduce_scatter.py:47-142,525-637):
            # chunk (o, r) is laid out slab-major (r, o) so the recursive
            # reduce-scatter over the INNER axes pre-reduces every byte
            # before it crosses the slower outer axis — exactly once, n_r-
            # fold reduced. Ordering matches
            # ``jax.lax.psum_scatter(x, axes, tiled=True)``.
            a0, rest = axis[0], tuple(axis[1:])
            n0 = _axis_size((a0))
            nr = math.prod(_axis_size((a)) for a in rest)
            orig_ndim0 = x.ndim
            if x.ndim == 1:
                x = x.reshape(x.shape[0], 1)
            m_tot0, nd0 = x.shape
            assert m_tot0 % (n0 * nr) == 0, (m_tot0, n0, nr)
            m0 = m_tot0 // (n0 * nr)
            xt = (
                x.reshape(n0, nr, m0, nd0)
                .swapaxes(0, 1)
                .reshape(m_tot0, nd0)
            )
            part = reduce_scatter(
                xt, axis=rest if len(rest) > 1 else rest[0],
                method=method, config=config, interpret=interpret,
            )  # [n0*m0, nd0] pre-reduced over every inner axis
            out = reduce_scatter(
                part, axis=a0, method=method, config=config, interpret=interpret
            )
            if orig_ndim0 == 1:
                out = out.reshape(m0)
            return out
    cfg = config or ReduceScatterConfig()
    n = _axis_size((axis))
    if n == 1:
        return x
    from triton_dist_tpu.parallel.topology import is_dcn_axis_name as _is_dcn

    if _is_dcn(axis):
        # slice-crossing axis: no ICI path for remote DMA — XLA's
        # psum-scatter rides DCN. The N-D recursion above already ordered
        # inner (ICI) axes first, so every byte crossing DCN has been
        # pre-reduced n_inner-fold (≙ the reference's P2P inter-node RS
        # stage running AFTER the intra-node pipeline,
        # reduce_scatter.py:525-560).
        return jax.lax.psum_scatter(x, axis, tiled=True)
    orig_ndim = x.ndim
    if x.ndim == 1:
        x = x.reshape(x.shape[0], 1)
    m_total, n_dim = x.shape
    assert m_total % n == 0, (m_total, n)
    m_loc = m_total // n
    if cfg.method is not None and method == "auto":
        method = cfg.method
    if method == "auto":
        method = get_auto_reduce_scatter_method(
            m_loc * n_dim * x.dtype.itemsize, n, devices
        )
    n_steps = n - 1
    chunks = max(1, int(cfg.chunks_per_shard))
    # quantize spans to the VPU row tile (see chunk_schedule / ag_gemm)
    spans = chunk_schedule(
        m_loc, chunks,
        quantum=pick_block(m_loc, min(cfg.block_m, max(1, m_loc // chunks))),
    )
    scratch = [
        pltpu.SemaphoreType.DMA((n_steps,)),
        pltpu.SemaphoreType.DMA((n_steps,)),
    ]
    workspace = [
        jax.ShapeDtypeStruct((n_steps, m_loc, n_dim), x.dtype),  # landing slots
    ]
    if method == "ring":
        kernel = functools.partial(_ring_rs_kernel, axis=axis, n=n, cfg=cfg)
        workspace.append(jax.ShapeDtypeStruct((2, m_loc, n_dim), x.dtype))  # accumulator
        if len(spans) > 1:
            # chunk-granular ring staging (scatter_reduce's puts are
            # single-hop — chunking buys no cross-hop pipelining there)
            kernel = functools.partial(
                _ring_rs_chunked_kernel, axis=axis, n=n, cfg=cfg, spans=spans
            )
            scratch = [
                pltpu.SemaphoreType.DMA((n_steps, len(spans))),
                pltpu.SemaphoreType.DMA((n_steps, len(spans))),
                pltpu.SemaphoreType.REGULAR((n_steps, len(spans))),
            ]
    elif method == "scatter_reduce":
        kernel = functools.partial(_scatter_reduce_kernel, axis=axis, n=n, cfg=cfg)
    else:
        raise ValueError(f"unknown reduce_scatter method: {method!r}")
    outs = dist_pallas_call(
        kernel,
        name=f"reduce_scatter_{method}",
        out_shape=(jax.ShapeDtypeStruct((m_loc, n_dim), x.dtype), *workspace),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY) for _ in range(1 + len(workspace))),
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=m_total * n_dim,
            bytes_accessed=(m_total + 3 * n_steps * m_loc) * n_dim * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x)
    out = outs[0]
    if orig_ndim == 1:
        out = out.reshape(m_loc)
    return out


def reduce_scatter_2d(
    x: jax.Array,
    *,
    axes: tuple[str, str],
    method: str = "auto",
    config: ReduceScatterConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Hierarchical reduce-scatter over two mesh axes ``(outer, inner)``
    (≙ the reference's 2-D pipeline: intra-node scatter → local reduce →
    inter-node P2P → ring, reduce_scatter.py:47-142,525-637).

    TPU-native staging: phase 1 reduce-scatters over the `inner` (fast ICI)
    axis with the chunk layout transposed so each inner peer ends up owning
    the slab ``S_i = concat_o'(chunk (o', i))``; phase 2 reduce-scatters that
    slab over the `outer` axis. Every byte crosses the slow axis exactly once
    and already (n_i-fold) reduced — the same traffic shape as the
    reference's node-then-ring pipeline. Golden:
    ``jax.lax.psum_scatter(x, axes, tiled=True)``.
    """
    # single implementation: the generic N-D peel in reduce_scatter
    return reduce_scatter(
        x, axis=tuple(axes), method=method, config=config, interpret=interpret
    )


def _reduce_scatter_op_xla(
    x: jax.Array, mesh: Mesh, *, axis: str = "tp", **_
) -> jax.Array:
    """Op-level golden: the same shard_map entry serving XLA's psum-scatter."""

    def wrapped(xs):
        return _reduce_scatter_xla(xs[0], axis=axis)

    in_spec = P(axis, *([None] * (x.ndim - 1)))
    out_spec = P(axis, *([None] * (x.ndim - 2)))
    return jit_shard_map(
        wrapped, mesh, (in_spec,), out_spec, key=("reduce_scatter_xla", axis)
    )(x)


def reduce_scatter_op(
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: ReduceScatterConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: `x` is ``[n, m_total]`` or ``[n, m_total, n_dim]``
    — slice i is PE i's full partial array (sharded on the stacking dim over
    `axis`). Returns ``[m_total, ...]`` = the elementwise sum, sharded on
    dim 0 over `axis` (PE i owns rows ``[i*m_loc, (i+1)*m_loc)``). Collapse
    extra trailing dims before calling (the kernel is 1-D/2-D)."""
    n = mesh.shape[axis]
    assert x.shape[0] == n, (x.shape, n)
    if x.ndim not in (2, 3):
        raise ValueError(f"reduce_scatter_op wants [n, m] or [n, m, d]; got {x.shape}")
    fn = functools.partial(
        reduce_scatter, axis=axis, method=method, config=config,
        interpret=interpret, devices=topology.axis_devices(mesh, axis),
    )

    def wrapped(xs):  # xs block: [1, m_total, ...] → this PE's partial
        return fn(xs[0])

    in_spec = P(axis, *([None] * (x.ndim - 1)))
    out_spec = P(axis, *([None] * (x.ndim - 2)))
    return jit_shard_map(
        wrapped, mesh, (in_spec,), out_spec,
        key=("reduce_scatter", axis, method, config, str(interpret)),
    )(x)


# method × tile sweep (≙ the reference autotuning its RS contexts); configs
# whose method is invalid for the problem (e.g. "ring" on a 2-PE axis still
# runs; no invalid combos here) simply lose the timing race. FIRST entry =
# best-known default (applied sweep-free under cached_or_first).
RS_TUNE_SPACE = (
    ReduceScatterConfig(256, 1024, "scatter_reduce"),
    ReduceScatterConfig(512, 2048, "scatter_reduce"),
    ReduceScatterConfig(256, 1024, "ring"),
    ReduceScatterConfig(512, 2048, "ring"),
    ReduceScatterConfig(128, 512, "scatter_reduce"),
    # chunks_per_shard axis (ISSUE 3): chunk-granular ring staging — after
    # every chunk=1 candidate so sweep-free walks never apply one untimed
    ReduceScatterConfig(256, 1024, "ring", chunks_per_shard=2),
    ReduceScatterConfig(256, 1024, "ring", chunks_per_shard=4),
)

reduce_scatter_op = contextual_autotune(RS_TUNE_SPACE, name="reduce_scatter")(
    reduce_scatter_op
)
# guard OUTSIDE the autotuner: the sweep still prices failing candidates;
# only a failure of the whole tuned entry degrades to the XLA golden
reduce_scatter_op = resilience.guard_op("reduce_scatter_op", _reduce_scatter_op_xla)(
    reduce_scatter_op
)
