"""Fused GEMM-ReduceScatter — TP row-parallel forward
(≙ reference ``kernels/nvidia/gemm_reduce_scatter.py``, 561 LoC).

The reference runs a producer GEMM whose tiles *notify* per-output-rank
counters (``dl.notify`` on the last tile of a rank's rows,
gemm_reduce_scatter.py:224-235) with a rank+1-first threadblock swizzle
(:190-200) so communication for remote ranks starts as early as possible,
while a consumer reduce-scatter pipeline drains finished chunks on separate
high-priority streams (reduce_scatter.py:863).

TPU-native re-design: one fused Pallas kernel per PE; the tile swizzle
becomes the *chunk emission order* of the kernel's outer loop, and the
notify/consumer machinery collapses into the data-coupled receive semaphore
of each one-sided put. Two strategies (auto-selected like the standalone
reduce-scatter):

- ``scatter`` — produce the partial chunk destined for PE ``me+d`` in
  increasing-``d`` order (own chunk LAST — exactly the reference's
  rank+1-first swizzle) and push each chunk to its owner the moment its
  GEMM finishes; the ICI DMA overlaps the next chunk's MXU work. The final
  own-chunk GEMM fuses the n-way reduction of all landed chunks into its
  epilogue, so the reduce costs no extra HBM round-trip.
- ``ring`` — bandwidth-optimal fused ring reduce-scatter: step ``s``
  produces partial chunk ``me-1-s``, fused-adds the partially-reduced chunk
  that arrived from the left during step ``s-1``, and forwards it right;
  the last step's add lands directly in ``out``. Per-hop carry is in
  ``out_dtype`` (one round-off per hop, like any ring reduce).

Used for TP row-parallel layers: A is ``[M, k_loc]`` (K-sharded
activations, e.g. the output of a column-parallel layer), B is
``[k_loc, N]``; every PE gets its M-chunk of the fully-reduced C.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import (
    chunk_schedule,
    dist_pallas_call,
    gemm_add_pipeline,
    gemm_only,
    jit_shard_map,
)
from triton_dist_tpu.ops.reduce_scatter import get_auto_reduce_scatter_method
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def _gemm_rs_xla(
    a: jax.Array, b: jax.Array, *, axis="tp", out_dtype=None, **_
) -> jax.Array:
    """The golden slow path (the same program every fused method is tested
    against): XLA's dot + psum-scatter, single- or multi-axis."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    out_dtype = out_dtype or a.dtype
    return jax.lax.psum_scatter(
        jnp.dot(a, b, preferred_element_type=out_dtype), axes, tiled=True
    )


@dataclasses.dataclass(frozen=True)
class GemmRSConfig:
    """Tunables (≙ the GEMM tile knobs of the reference contexts,
    gemm_reduce_scatter.py:42-86; stream/buffer plumbing is subsumed by the
    fused kernel)."""

    block_m: int = 256
    block_n: int = 1024
    block_k: int = 512
    # block_m=0: world-1 XLA-native sentinel (see AGGemmConfig) — the
    # no-comm degenerate case goes to jnp.dot; raises at n>1.
    # Ring-step payload granularity (ISSUE 3): > 1 splits each ring hop's
    # partial chunk into that many per-chunk DMAs produced/added/forwarded
    # independently; 1 is the legacy shard-granular schedule, bit for bit.
    # Ring method only (the scatter method's puts are single-hop).
    chunks_per_shard: int = 1


def _blocks(cfg: GemmRSConfig, m_loc: int, n_dim: int, k_loc: int):
    return (
        pick_block(m_loc, cfg.block_m),
        pick_block(n_dim, cfg.block_n),
        pick_block(k_loc, cfg.block_k),
    )


def _gemm_rs_scatter_kernel(
    a_ref, b_ref, out_ref, send_buf, recv_buf, acc_ref, send_sems, recv_sems,
    *, axis: str, n: int, cfg: GemmRSConfig, out_dtype,
):
    me = shmem.my_pe(axis)
    m_tot, k_loc = a_ref.shape
    n_dim = b_ref.shape[1]
    m_loc = m_tot // n
    bm, bn, bk = _blocks(cfg, m_loc, n_dim, k_loc)
    gemm = gemm_add_pipeline(bm, bn, bk, m_loc, n_dim, k_loc, acc_ref, out_dtype, 0)
    gemm_reduce = gemm_add_pipeline(
        bm, bn, bk, m_loc, n_dim, k_loc, acc_ref, out_dtype, n - 1
    )

    # race shaking (no-op unless config.debug_comm_delay)
    shmem.comm_jitter(axis, salt=10)
    # All PEs must be inside the kernel before any chunk may land in their
    # slots (≙ the barrier before the scatter stage, reduce_scatter.py:604).
    shmem.barrier_all(axis)

    # Remote chunks first, own chunk last (≙ rank+1-first swizzle,
    # gemm_reduce_scatter.py:190-200). Receiver slot d-1 holds the chunk
    # from PE me-d — distinct per sender by symmetry.
    descs = []
    for d in range(1, n):
        dst = jax.lax.rem(me + d, n)
        slot = (d - 1) % 2
        if d >= 3:
            descs[d - 3].wait_send()  # send_buf slot free again
        gemm(a_ref.at[pl.ds(dst * m_loc, m_loc)], b_ref, send_buf.at[slot])
        descs.append(
            shmem.putmem_nbi_block(
                recv_buf.at[d - 1], send_buf.at[slot], dst, axis,
                send_sems.at[d - 1], recv_sems.at[d - 1],
            )
        )
    # Symmetric SPMD: our descriptors' recv side counts the equal-sized
    # incoming chunks, so this waits for all n-1 arrivals.
    for desc in descs:
        desc.wait_recv()
    # Own chunk's GEMM with the n-way reduction fused into its epilogue.
    gemm_reduce(
        a_ref.at[pl.ds(me * m_loc, m_loc)], b_ref,
        *(recv_buf.at[d] for d in range(n - 1)), out_ref,
    )
    shmem.quiet(*descs)


def _gemm_rs_ring_kernel(
    a_ref, b_ref, out_ref, comp_buf, recv_buf, acc_ref, send_sems, recv_sems,
    *, axis: str, n: int, cfg: GemmRSConfig, out_dtype,
):
    me = shmem.my_pe(axis)
    m_tot, k_loc = a_ref.shape
    n_dim = b_ref.shape[1]
    m_loc = m_tot // n
    bm, bn, bk = _blocks(cfg, m_loc, n_dim, k_loc)
    gemm = gemm_add_pipeline(bm, bn, bk, m_loc, n_dim, k_loc, acc_ref, out_dtype, 0)
    gemm_add = gemm_add_pipeline(bm, bn, bk, m_loc, n_dim, k_loc, acc_ref, out_dtype, 1)

    shmem.comm_jitter(axis, salt=11)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    # Step s: produce partial chunk (me-1-s), fused-add the partial that
    # landed from the left during step s-1, forward right. After n-1 hops
    # the fully-reduced own chunk lands in out_ref.
    descs = []
    for s in range(n):
        c = pl.ds(jax.lax.rem(me - 1 - s + 2 * n, n) * m_loc, m_loc)
        target = out_ref if s == n - 1 else comp_buf.at[s % 2]
        if 2 <= s < n - 1:
            descs[s - 2].wait_send()  # comp_buf slot s%2 free again
        if s == 0:
            gemm(a_ref.at[c], b_ref, target)
        else:
            descs[s - 1].wait_recv()  # partial chunk landed in recv_buf[s-1]
            gemm_add(a_ref.at[c], b_ref, recv_buf.at[s - 1], target)
        if s < n - 1:
            descs.append(
                shmem.putmem_nbi_block(
                    recv_buf.at[s], target, right, axis,
                    send_sems.at[s], recv_sems.at[s],
                )
            )
    shmem.quiet(*descs)


def _gemm_rs_ring_chunked_kernel(
    a_ref, b_ref, out_ref, comp_buf, recv_buf, acc_ref, send_sems, recv_sems,
    sig_sems, *, axis: str, n: int, cfg: GemmRSConfig, out_dtype, spans,
):
    """Chunk-granular fused ring GEMM-RS (ISSUE 3 tentpole): step ``s``
    produces, fused-adds, and forwards its partial chunk in ``len(spans)``
    independent sub-chunks — chunk ``j``'s MXU work runs while chunk ``j+1``
    of the incoming partial is still in flight, so each hop exposes one
    *chunk* of ICI latency instead of one m_loc-row shard. chunk=1
    dispatches to :func:`_gemm_rs_ring_kernel` (bit-identical legacy)."""
    me = shmem.my_pe(axis)
    m_tot, k_loc = a_ref.shape
    n_dim = b_ref.shape[1]
    m_loc = m_tot // n
    bn = pick_block(n_dim, cfg.block_n)
    bk = pick_block(k_loc, cfg.block_k)
    bms = [pick_block(rows, cfg.block_m) for _, rows in spans]
    bm_max = max(bms)
    gemms, gemm_adds = [], []
    for (_, rows), bm_j in zip(spans, bms):
        acc_j = acc_ref if bm_j == bm_max else acc_ref.at[pl.ds(0, bm_j), :]
        gemms.append(
            gemm_add_pipeline(bm_j, bn, bk, rows, n_dim, k_loc, acc_j, out_dtype, 0)
        )
        gemm_adds.append(
            gemm_add_pipeline(bm_j, bn, bk, rows, n_dim, k_loc, acc_j, out_dtype, 1)
        )

    shmem.comm_jitter(axis, salt=11)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    # Step s, chunk j: produce partial rows of chunk (me-1-s), fused-add
    # the partially-reduced rows that landed from the left during step s-1,
    # forward them right — all at chunk granularity.
    descs = []
    for s in range(n):
        cbase = jax.lax.rem(me - 1 - s + 2 * n, n) * m_loc
        handles = []
        for j, (off, rows) in enumerate(spans):
            sl_a = pl.ds(cbase + off, rows)
            target = (
                out_ref.at[pl.ds(off, rows)] if s == n - 1
                else comp_buf.at[s % 2, pl.ds(off, rows)]
            )
            if 2 <= s < n - 1:
                descs[s - 2].wait_send_chunk(j)  # comp_buf rows free again
            if s == 0:
                gemms[j](a_ref.at[sl_a], b_ref, target)
            else:
                descs[s - 1].wait_recv_chunk(j)  # partial chunk j landed
                gemm_adds[j](
                    a_ref.at[sl_a], b_ref,
                    recv_buf.at[s - 1, pl.ds(off, rows)], target,
                )
            if s < n - 1:
                handles.append(
                    shmem.putmem_signal2_nbi_block(
                        recv_buf.at[s, pl.ds(off, rows)], target, right, axis,
                        send_sems.at[s, j], recv_sems.at[s, j],
                        sig_sems.at[s, j], canary=True,
                    )
                )
        if handles:
            # landing view (ISSUE 8 canary): SPMD symmetry — the left
            # neighbor's step-s partial lands in OUR recv_buf[s] at the
            # same span coordinates this put addressed on the right
            descs.append(shmem.ChunkedPutHandle(
                handles,
                recv_at=lambda off, rows, s=s: recv_buf.at[
                    s, pl.ds(off, rows)
                ],
                spans=spans,
            ))
    shmem.quiet(*descs)


def _gemm_rs_2d(a, b, *, axes, method, cfg, out_dtype, interpret):
    """Hierarchical GEMM-RS over two mesh axes ``(outer, inner)``
    (≙ the reference's producer GEMM + 2-D reduce-scatter pipeline,
    reduce_scatter.py:525-637): the fused GEMM-RS runs over the fast `inner`
    axis with A's chunk layout transposed so inner peer i ends up owning
    slab ``S_i = concat_o'(chunk (o', i))`` of the product, already
    inner-reduced; a standalone reduce-scatter then finishes over `outer`.
    Every byte crosses the slow axis once, n_i-fold pre-reduced."""
    from triton_dist_tpu.ops.reduce_scatter import reduce_scatter

    outer, inner = axes
    n_o = int(jax.lax.axis_size(outer))
    n_i = int(jax.lax.axis_size(inner))
    if n_o == 1:
        return gemm_rs(a, b, axis=inner, method=method, config=cfg,
                       out_dtype=out_dtype, interpret=interpret)
    if n_i == 1:
        return gemm_rs(a, b, axis=outer, method=method, config=cfg,
                       out_dtype=out_dtype, interpret=interpret)
    m_tot, k_loc = a.shape
    n = n_o * n_i
    assert m_tot % n == 0, (m_tot, n)
    m_loc = m_tot // n
    a_perm = a.reshape(n_o, n_i, m_loc, k_loc).swapaxes(0, 1).reshape(m_tot, k_loc)
    part = gemm_rs(
        a_perm, b, axis=inner, method=method, config=cfg,
        out_dtype=out_dtype, interpret=interpret,
    )  # [n_o*m_loc, N] = S_me_i's product, summed over the inner group
    # gemm_rs and the standalone reduce_scatter use different method
    # vocabularies ("scatter" vs "scatter_reduce")
    rs_method = {"scatter": "scatter_reduce"}.get(method, method)
    return reduce_scatter(part, axis=outer, method=rs_method, interpret=interpret)


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: GemmRSConfig | None = None,
    out_dtype: Any = None,
    interpret: Any = None,
    devices: Any = None,
) -> jax.Array:
    """Overlapped ``psum_scatter(a @ b)`` (call inside ``jax.shard_map``).

    a: ``[M, k_loc]`` — K-sharded activations on this PE (M = n * m_loc).
    b: ``[k_loc, N]`` — K-shard of the weight (row-parallel).
    Returns ``[m_loc, N]`` — this PE's M-chunk of the fully-reduced product.
    Golden: ``jax.lax.psum_scatter(a @ b, axis, tiled=True)``
    (≙ ``gemm_rs_op``, reference gemm_reduce_scatter.py:498) — served
    automatically when the fused kernel cannot run in this environment
    (resilience layer, docs/resilience.md).
    """
    return resilience.guarded_call(
        "gemm_rs",
        _gemm_rs_fused,
        _gemm_rs_xla,
        a, b, axis=axis, method=method, config=config, out_dtype=out_dtype,
        interpret=interpret, devices=devices,
    )


def _gemm_rs_fused(
    a: jax.Array,
    b: jax.Array,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: GemmRSConfig | None = None,
    out_dtype: Any = None,
    interpret: Any = None,
    devices: Any = None,
) -> jax.Array:
    cfg = config or GemmRSConfig()
    out_dtype = out_dtype or a.dtype
    from triton_dist_tpu.parallel.topology import is_dcn_axis_name as _is_dcn

    if isinstance(axis, (tuple, list)):
        if len(axis) == 1:
            axis = axis[0]
        else:
            assert len(axis) == 2, f"at most 2 axes supported, got {axis}"
            outer_ax, inner_ax = axis
            if _is_dcn(inner_ax) and not _is_dcn(outer_ax):
                # Tuple (ici, dcn): transport order and tuple order agree
                # for free — a's outer-major block layout already groups
                # each ICI slab's blocks contiguously, so the fused ICI
                # GEMM-RS runs DIRECTLY (no swizzle; the dcn-OUTER case
                # below is the one needing the inner-major re-grouping),
                # pre-reducing every byte before the DCN hop's XLA
                # psum-scatter.
                from triton_dist_tpu.ops.reduce_scatter import reduce_scatter

                part = gemm_rs(
                    a, b, axis=outer_ax, method=method, config=config,
                    out_dtype=out_dtype, interpret=interpret,
                )
                return reduce_scatter(part, axis=inner_ax, interpret=interpret)
            if _is_dcn(outer_ax):
                # a slice-crossing axis (either position): fused GEMM-RS on
                # the inner hop first (pre-reducing every byte n_i-fold
                # before the outer hop), then a reduce-scatter on the outer
                # hop — both recursive calls route per-axis, so a DCN hop
                # lowers to XLA's psum-scatter and an ICI hop keeps the
                # fused kernels (≙ the reference's inter-node P2P stage
                # after the intra-node RS pipeline,
                # reduce_scatter.py:525-560). Row layout: chunk (o, i) must
                # end at outer-rank o, inner-rank i — the inner RS keeps
                # rows [i*n_o*m + o*m, ...), so pre-swizzle a to slab-major
                # (i, o) order as the N-D reduce_scatter does.
                from triton_dist_tpu.ops.reduce_scatter import reduce_scatter

                n_o = int(jax.lax.axis_size(outer_ax))
                n_i = int(jax.lax.axis_size(inner_ax))
                m_tot0 = a.shape[0]
                m0 = m_tot0 // (n_o * n_i)
                at = (
                    a.reshape(n_o, n_i, m0, a.shape[1])
                    .swapaxes(0, 1)
                    .reshape(m_tot0, a.shape[1])
                )
                part = gemm_rs(
                    at, b, axis=inner_ax, method=method, config=config,
                    out_dtype=out_dtype, interpret=interpret,
                )  # [n_o*m0, N] pre-reduced over the inner axis
                return reduce_scatter(part, axis=outer_ax, interpret=interpret)
            return _gemm_rs_2d(
                a, b, axes=tuple(axis), method=method, cfg=cfg,
                out_dtype=out_dtype, interpret=interpret,
            )
    n = _axis_size(axis)
    m_tot, k_loc = a.shape
    n_dim = b.shape[1]
    if n > 1 and _is_dcn(axis):
        # a purely-DCN axis: no ICI for the fused producer — XLA's
        # dot + psum-scatter owns the DCN transport
        return jax.lax.psum_scatter(
            jnp.dot(a, b, preferred_element_type=out_dtype), axis, tiled=True
        )
    if cfg.block_m == 0:
        if n != 1:
            raise ValueError("GemmRSConfig(block_m=0) (XLA dot) is world-1 only")
        return jnp.dot(a, b, preferred_element_type=out_dtype)
    if n == 1:
        # World-1 is a plain matmul; run it through the same tuned MXU
        # pipeline the fused kernels use (beats the XLA dot at bench shapes).
        return gemm_only(
            a, b, cfg=cfg, out_dtype=out_dtype, name="gemm_rs", interpret=interpret
        )
    assert m_tot % n == 0, (m_tot, n)
    m_loc = m_tot // n
    if method == "auto":
        method = get_auto_reduce_scatter_method(
            m_loc * n_dim * jnp.dtype(out_dtype).itemsize, n, devices
        )
    # accept the standalone reduce-scatter's method name as an alias
    method = {"scatter_reduce": "scatter"}.get(method, method)
    bm, bn, _ = _blocks(cfg, m_loc, n_dim, k_loc)
    kernels = {"scatter": _gemm_rs_scatter_kernel, "ring": _gemm_rs_ring_kernel}
    if method not in kernels:
        raise ValueError(f"unknown gemm_rs method: {method!r} (want scatter|ring)")
    kernel = kernels[method]
    n_steps = n - 1
    chunks = max(1, int(cfg.chunks_per_shard))
    # quantize spans to the MXU row tile (see chunk_schedule / ag_gemm)
    spans = chunk_schedule(
        m_loc, chunks,
        quantum=pick_block(m_loc, min(cfg.block_m, max(1, m_loc // chunks))),
    )
    sem_shapes = [
        pltpu.SemaphoreType.DMA((n_steps,)),
        pltpu.SemaphoreType.DMA((n_steps,)),
    ]
    kern = functools.partial(kernel, axis=axis, n=n, cfg=cfg, out_dtype=out_dtype)
    acc_bm = bm
    if method == "ring" and len(spans) > 1:
        # chunk-granular ring (the scatter method's puts are single-hop —
        # chunking buys no cross-hop pipelining there)
        kern = functools.partial(
            _gemm_rs_ring_chunked_kernel, axis=axis, n=n, cfg=cfg,
            out_dtype=out_dtype, spans=spans,
        )
        acc_bm = max(pick_block(rows, cfg.block_m) for _, rows in spans)
        sem_shapes = [
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.REGULAR((n_steps, len(spans))),
        ]
    outs = dist_pallas_call(
        kern,
        name=f"gemm_rs_{method}",
        out_shape=(
            jax.ShapeDtypeStruct((m_loc, n_dim), out_dtype),
            # Workspace as outputs: how a kernel gets private HBM, and the
            # interpreter's emit_pipeline only takes kernel-arg HBM refs.
            jax.ShapeDtypeStruct((2, m_loc, n_dim), out_dtype),        # send/comp
            jax.ShapeDtypeStruct((n_steps, m_loc, n_dim), out_dtype),  # landing
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY) for _ in range(3)),
        scratch_shapes=[
            pltpu.VMEM((acc_bm, bn), jnp.float32),
            *sem_shapes,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * m_tot * n_dim * k_loc,
            bytes_accessed=(m_tot * k_loc + k_loc * n_dim) * a.dtype.itemsize
            + (m_tot + 3 * n_steps * m_loc) * n_dim * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(a, b)
    return outs[0]


def _gemm_rs_op_xla(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    **_,
) -> jax.Array:
    """Op-level golden: the same shard_map entry serving XLA's
    dot + psum-scatter."""
    return jit_shard_map(
        functools.partial(_gemm_rs_xla, axis=axis),
        mesh, (P(None, axis), P(axis, None)), P(axis, None),
        key=("gemm_rs_xla", axis),
    )(a, b)


def gemm_rs_op(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    method: str = "auto",
    config: GemmRSConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry (≙ ``gemm_rs_op``, reference
    gemm_reduce_scatter.py:498): `a` sharded on dim 1 (K), `b` sharded on
    dim 0 (K); the reduced result comes back sharded on dim 0 (M)."""
    from triton_dist_tpu.parallel import topology

    if mesh.size == 1 and config is not None and config.block_m == 0:
        # world-1 XLA-dot sentinel: bypass shard_map entirely (see
        # ag_gemm_op)
        return jnp.dot(a, b, preferred_element_type=a.dtype)
    fn = functools.partial(
        gemm_rs, axis=axis, method=method, config=config, interpret=interpret,
        devices=topology.axis_devices(mesh, axis),
    )
    return jit_shard_map(
        fn, mesh, (P(None, axis), P(axis, None)), P(axis, None),
        key=("gemm_rs", axis, method, config, str(interpret)),
    )(a, b)


# ≙ the reference's tune space for gemm_rs (gemm_reduce_scatter.py contexts);
# block_m tiles the per-destination M-chunk, which is M/n — smaller than the
# AG-GEMM tiles for the same problem.
# FIRST entry = best-known config (applied sweep-free under
# TDT_AUTOTUNE_POLICY=cached_or_first): the swept winner at the bench
# shape M=8192 K=14336 N=4096.
GEMM_RS_TUNE_SPACE = (
    GemmRSConfig(0, 0, 0),  # world-1 XLA dot (raises → skipped at n>1);
    # measured v5e world-1: XLA 199 TFLOPS vs best Pallas chunking 176 at
    # M=8192 K=14336 N=4096 — this shape's B-panel restreaming favors XLA
    GemmRSConfig(512, 2048, 1024),
    GemmRSConfig(256, 1024, 512),
    GemmRSConfig(512, 1024, 512),
    GemmRSConfig(256, 2048, 512),
    GemmRSConfig(512, 2048, 512),
    GemmRSConfig(1024, 2048, 1024),
    GemmRSConfig(512, 4096, 2048),
    GemmRSConfig(128, 1024, 512),
    # chunks_per_shard axis (ISSUE 3): chunk-granular ring staging over the
    # best-known tiles — after every chunk=1 candidate so the sweep-free
    # walks never apply a chunked schedule untimed (see AG_GEMM_TUNE_SPACE)
    GemmRSConfig(512, 2048, 1024, chunks_per_shard=2),
    GemmRSConfig(512, 2048, 1024, chunks_per_shard=4),
    GemmRSConfig(256, 1024, 512, chunks_per_shard=4),
)

gemm_rs_op = contextual_autotune(GEMM_RS_TUNE_SPACE, name="gemm_rs")(gemm_rs_op)
# guard OUTSIDE the autotuner: the sweep still prices failing candidates;
# only a failure of the whole tuned entry degrades to the XLA golden
gemm_rs_op = resilience.guard_op("gemm_rs_op", _gemm_rs_op_xla)(gemm_rs_op)
