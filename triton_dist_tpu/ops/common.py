"""Shared kernel-building helpers (≙ reference ``kernels/nvidia/common_ops.py``).

The reference's common_ops holds device barrier kernels and host
stream-signal wrappers (``wait_eq``/``set_signal`` over cuStreamWriteValue,
:196-229). On TPU the host cannot poke device memory mid-program, so the
surviving pieces are: a standalone barrier kernel, collective-id management,
and the ``dist_pallas_call`` wrapper that all distributed kernels use.
"""

from __future__ import annotations

import itertools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu.shmem import device as shmem

# Renamed across jax lines (TPUCompilerParams before ~0.6, CompilerParams
# after); resolving here keeps kernels buildable on both, and a total API
# miss surfaces as an AttributeError the resilience guard recognizes.
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams", None
)


def _shard_map(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the supported jax range: the public API
    (``check_vma``) on newer lines, ``jax.experimental.shard_map``
    (``check_rep``) before it."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    return legacy_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


_collective_id_counter = itertools.count(1)
_collective_ids: dict[str, int] = {}


def collective_id_for(name: str) -> int:
    """Stable collective_id per kernel family (barrier semaphores of
    concurrently-running kernels must not collide). Mosaic supports a small
    fixed pool of collective ids; running out is an error rather than a
    silent wrap that would alias two families' barrier semaphores."""
    if name not in _collective_ids:
        next_id = next(_collective_id_counter)
        if next_id >= 32:
            raise RuntimeError(
                f"out of collective_ids (31 kernel families in use) while "
                f"registering {name!r}; reuse an existing family name in "
                f"dist_pallas_call(name=...) for kernels that never run "
                f"concurrently"
            )
        _collective_ids[name] = next_id
    return _collective_ids[name]


def dist_pallas_call(
    kernel,
    *,
    name: str,
    out_shape: Any,
    in_specs: Sequence[pl.BlockSpec] | None = None,
    out_specs: Any = None,
    grid: tuple[int, ...] | None = None,
    grid_spec: Any = None,
    scratch_shapes: Sequence[Any] = (),
    cost_estimate: pl.CostEstimate | None = None,
    vmem_limit_bytes: int | None = None,
    interpret: Any = None,
    dimension_semantics: tuple[str, ...] | None = None,
    input_output_aliases: dict[int, int] | None = None,
    uses_barrier: bool = True,
):
    """pallas_call with the invariants every distributed kernel needs:
    side effects on (remote DMAs must not be DCE'd), a collective_id for the
    barrier semaphore, and config-resolved interpret mode.

    `uses_barrier` must be False for degenerate single-PE calls: Mosaic
    rejects a collective_id on kernels that never touch the barrier
    semaphore.

    Resilience plumbing (zero-cost unless armed, docs/resilience.md): when
    ``config.timeout_iters > 0`` every kernel gains one extra
    ``int32[DIAG_LEN]`` SMEM output — the watchdog's diagnostic buffer —
    and its body is traced inside a ``watchdog.kernel_scope`` so the SHMEM
    wait primitives become bounded without any kernel changing its
    signature; the traced diag output is stripped from the caller-visible
    result and offered to the ambient ``jit_shard_map`` collection. An
    armed ``config.fault_plan`` opens the scope too (the signal-chaos
    injector needs the family/site bookkeeping) but adds no output."""
    if _COMPILER_PARAMS_CLS is None:
        raise NotImplementedError(
            "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
            "TPUCompilerParams on this jax version; fused distributed "
            "kernels cannot be built — ops degrade to the golden XLA "
            "collective path via triton_dist_tpu.resilience.guarded_call"
        )
    from triton_dist_tpu import obs as _obs
    from triton_dist_tpu.obs import telemetry as _obs_telem
    from triton_dist_tpu.resilience import faults as _faults
    from triton_dist_tpu.resilience import records as _records
    from triton_dist_tpu.resilience import watchdog as _watchdog

    params: dict[str, Any] = dict(has_side_effects=True)
    if uses_barrier:
        params["collective_id"] = collective_id_for(name)
    if vmem_limit_bytes is not None:
        params["vmem_limit_bytes"] = vmem_limit_bytes
    if dimension_semantics is not None:
        params["dimension_semantics"] = dimension_semantics

    cfg = tdt_config.get_config()
    arm_diag = int(cfg.timeout_iters) > 0
    # wait-telemetry tier (ISSUE 9): one more SMEM output recording every
    # bounded wait site's observed spin count — success path included.
    # Requires the armed watchdog (the bounded waits are where the spin
    # count exists); without it the obs request is silently inert, the
    # chunk-signal discipline. Inside a jit_shard_map trace the decision
    # FOLLOWS the collecting scope (telem_wanted — the program being
    # built either consumes the buffer or it doesn't; reading config here
    # could disagree with the program's cache key if obs flipped between
    # wrap and first trace); outside one, config decides (the buffer is
    # dropped there anyway — no host boundary, no decode).
    wanted = _watchdog.telem_wanted()
    arm_telem = arm_diag and (
        _obs.wait_stats_enabled() if wanted is None else wanted
    )
    # a spent (healed) fault plan no longer needs the injector scope
    arm_scope = arm_diag or (
        cfg.fault_plan is not None and not _faults.plan_spent()
    )
    if arm_diag and params.get("dimension_semantics") is not None:
        # megacore chips split 'parallel' grid dims across two TensorCores;
        # the armed diag protocol (zero-init on grid step (0,…,0),
        # first-record-wins, fast-fail budget chaining) relies on in-order
        # execution on ONE core — a watchdogged run trades the parallel
        # split for a sound protocol (diagnostic posture, not a fast path)
        params["dimension_semantics"] = tuple(
            "arbitrary" for _ in params["dimension_semantics"]
        )

    single_out = not isinstance(out_shape, (tuple, list))
    out_shapes = [out_shape] if single_out else list(out_shape)
    n_user_outs = len(out_shapes)
    n_scratch = len(scratch_shapes)
    grid_dims = 0
    if grid_spec is not None:
        n_scratch += len(grid_spec.scratch_shapes)
        grid_dims = len(grid_spec.grid)
    elif grid is not None:
        grid_dims = len(grid)

    n_extra = (2 if arm_telem else 1) if arm_diag else 0
    if arm_diag:
        # the diagnostic buffer (and, when the obs layer arms wait_stats,
        # the telemetry buffer after it): unblocked SMEM, last outputs, so
        # existing input/output aliases and ref positions stay untouched
        out_shapes.append(jax.ShapeDtypeStruct((_records.DIAG_LEN,), jnp.int32))
        if arm_telem:
            out_shapes.append(
                jax.ShapeDtypeStruct((_obs_telem.TELEM_LEN,), jnp.int32)
            )
        extra_specs = tuple(
            pl.BlockSpec(memory_space=pltpu.SMEM) for _ in range(n_extra)
        )
        if grid_spec is not None:
            gs_outs = grid_spec.out_specs
            if not isinstance(gs_outs, (tuple, list)):
                gs_outs = (gs_outs,)
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=grid_spec.num_scalar_prefetch,
                grid=grid_spec.grid,
                in_specs=list(grid_spec.in_specs),
                out_specs=(*gs_outs, *extra_specs),
                scratch_shapes=list(grid_spec.scratch_shapes),
            )
        else:
            if out_specs is None:
                user_specs: tuple = tuple(pl.BlockSpec() for _ in range(n_user_outs))
            elif isinstance(out_specs, (tuple, list)):
                user_specs = tuple(out_specs)
            else:
                user_specs = (out_specs,)
            out_specs = (*user_specs, *extra_specs)

    body = kernel
    if arm_scope:
        def body(*refs):  # noqa: F811 — deliberate armed override
            diag_ref = telem_ref = None
            user_refs = refs
            if arm_diag:
                i = len(refs) - n_scratch - n_extra
                diag_ref = refs[i]
                if arm_telem:
                    telem_ref = refs[i + 1]
                user_refs = refs[:i] + refs[i + n_extra:]

                def _zero_diag():
                    for j in range(_records.DIAG_LEN):
                        diag_ref[j] = jnp.int32(0)
                    if telem_ref is not None:
                        for j in range(_obs_telem.TELEM_LEN):
                            telem_ref[j] = jnp.int32(0)
                        # the telemetry row self-describes its kernel
                        # family (gathered rows from different launches
                        # share one host-side decode)
                        telem_ref[_obs_telem.H_FAMILY] = jnp.int32(
                            _records.family_code_for(name)
                        )

                if grid_dims == 0:
                    _zero_diag()
                else:
                    # compiled outputs start uninitialized: clear once, on
                    # the first grid step (TPU grids execute in order)
                    first = pl.program_id(0) == 0
                    for d in range(1, grid_dims):
                        first = jnp.logical_and(first, pl.program_id(d) == 0)
                    pl.when(first)(_zero_diag)
            with _watchdog.kernel_scope(diag_ref, name, telem_ref=telem_ref):
                kernel(*user_refs)

    kwargs: dict[str, Any] = {}
    if grid_spec is not None:
        kwargs["grid_spec"] = grid_spec
    else:
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = list(in_specs)
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
    if input_output_aliases:
        kwargs["input_output_aliases"] = input_output_aliases
    call = pl.pallas_call(
        body,
        out_shape=tuple(out_shapes) if arm_diag else out_shape,
        scratch_shapes=list(scratch_shapes),
        compiler_params=_COMPILER_PARAMS_CLS(**params),
        cost_estimate=cost_estimate,
        interpret=tdt_config.interpret_params() if interpret is None else interpret,
        name=name,
        **kwargs,
    )
    if not arm_diag:
        return call

    def invoke(*args):
        outs = call(*args)
        if arm_telem:
            *user, diag, telem = outs
        else:
            *user, diag = outs
            telem = None
        if not _watchdog.offer(diag, telem):
            # traced inside a USER-level shard_map, not jit_shard_map: no
            # host boundary will decode this diag and raise, so poison the
            # outputs in-trace — a timed-out launch must never hand back
            # plausible partial data (the telemetry is dropped for the
            # same reason: no host boundary, no decode)
            bad = diag[_records.F_STATUS] != _records.STATUS_OK
            user = [_watchdog.poison(u, bad) for u in user]
        return user[0] if single_out else tuple(user)

    return invoke


def chunk_schedule(
    rows: int, chunks: int, quantum: int = 1
) -> tuple[tuple[int, int], ...]:
    """Static ``(offset, rows)`` spans splitting a shard's `rows` into
    `chunks` contiguous near-equal chunks — the chunk-granular transfer
    schedule of the ring families (ISSUE 3; ≙ the per-M-tile readiness
    granularity of the reference's consumer GEMM, allgather_gemm.py:226).

    `quantum` > 1 aligns every span boundary to a multiple of it (the last
    chunk absorbs any sub-quantum tail): the GEMM families pass their MXU
    row tile here so a non-divisor chunk count can never hand
    ``pick_block`` an odd row count that collapses the tile toward 1 row —
    a silent orders-of-magnitude cliff. With the default quantum=1 counts
    balance to within one row; a request for more chunks than quanta
    clamps. Every PE computes the same spans from the same static shapes,
    so senders and receivers agree on per-chunk semaphore slots and byte
    counts by construction."""
    if rows < 1:
        raise ValueError(f"chunk_schedule: rows must be >= 1, got {rows}")
    if chunks < 1:
        raise ValueError(f"chunk_schedule: chunks must be >= 1, got {chunks}")
    quantum = max(1, min(int(quantum), rows))
    units = rows // quantum
    chunks = min(chunks, max(1, units))
    base, extra = divmod(units, chunks)
    spans, off = [], 0
    for j in range(chunks):
        sz = (base + (1 if j < extra else 0)) * quantum
        if j == chunks - 1:
            sz += rows - units * quantum  # sub-quantum tail
        spans.append((off, sz))
        off += sz
    return tuple(spans)


# ---------------------------------------------------------------------------
# Span-policy schedules (ISSUE 14): alternative span tilings/orderings the
# schedule synthesizer (triton_dist_tpu/synth/) enumerates and the static
# verifier proves. The emitter kernels consume the resulting spans
# UNCHANGED — a policy is purely a different (offset, rows) list. The math
# lives here (next to chunk_schedule, the kernel side's only dependency);
# the declarative policy space over it lives in synth/policies.py.
# ---------------------------------------------------------------------------

def span_window_schedule(
    rows: int, chunks: int, quantum: int = 1
) -> tuple[tuple[int, int], ...]:
    """Arrival-window span tiling (the synthesized ``window`` policy, AG
    side): contiguous ascending spans with geometrically GROWING sizes —
    the first chunk is as small as the quantum allows, each later chunk
    roughly doubles. The consumer's first wait (the exposed first-chunk
    bubble of ``perf_model.estimate_fused_ring_bubble_ms``) then covers
    only the smallest span's wire time, while the tail chunks keep DMA
    descriptor count bounded. ``chunks=1`` (or too few quanta) degrades to
    :func:`chunk_schedule`'s single span — the legacy protocol, bit for
    bit (the synthesizer's identity pin)."""
    if rows < 1:
        raise ValueError(f"span_window_schedule: rows must be >= 1, got {rows}")
    if chunks < 1:
        raise ValueError(
            f"span_window_schedule: chunks must be >= 1, got {chunks}"
        )
    quantum = max(1, min(int(quantum), rows))
    units = rows // quantum
    chunks = min(chunks, max(1, units))
    if chunks == 1:
        return chunk_schedule(rows, 1, quantum)
    # doubling weights 1, 2, 4, ... scaled into the unit budget; every
    # chunk keeps >= 1 unit, the LAST chunk absorbs the remainder (and the
    # sub-quantum tail) so sizes stay ascending
    weights = [1 << j for j in range(chunks)]
    total_w = sum(weights)
    sizes = [max(1, (units * w) // total_w) for w in weights[:-1]]
    head = sum(sizes)
    if head >= units:  # tiny unit budgets: fall back to near-equal spans
        return chunk_schedule(rows, chunks, quantum)
    sizes.append(units - head)
    spans, off = [], 0
    for j, sz_units in enumerate(sizes):
        sz = sz_units * quantum
        if j == chunks - 1:
            sz += rows - units * quantum  # sub-quantum tail
        spans.append((off, sz))
        off += sz
    return tuple(spans)


def span_interleave_schedule(
    rows: int, chunks: int, quantum: int = 1
) -> tuple[tuple[int, int], ...]:
    """Bidirectional chunk interleave (the synthesized ``interleave``
    policy, MoE combine side): the near-equal contiguous tiling of
    :func:`chunk_schedule` ISSUED alternately from both ends —
    ``c0, c_{k-1}, c1, c_{k-2}, …`` — so the landed slab grows inward from
    its first AND last rows. Per-chunk semaphore slots are positional
    (``sig_at(j)``), so issue order is free to permute: every PE computes
    the same permutation from the same static shapes and slot agreement
    holds exactly as for the contiguous order. Valid ONLY where the
    consumer drains chunks by slot index (the combine's
    ``wait_recv_chunk(j)`` loop); the AG gather-group arithmetic requires
    ascending contiguous coverage — :func:`resolve_spans` rejects the
    pairing. ``chunks=1`` is the legacy single span, bit for bit."""
    base = chunk_schedule(rows, chunks, quantum)
    if len(base) <= 2:
        return base
    order, lo, hi = [], 0, len(base) - 1
    while lo <= hi:
        order.append(lo)
        if hi != lo:
            order.append(hi)
        lo, hi = lo + 1, hi - 1
    return tuple(base[i] for i in order)


def span_torus2d_schedule(
    rows: int, chunks: int, quantum: int = 1, world: int = 1
) -> tuple[tuple[int, int], ...]:
    """2-D torus-aware span tiling (the synthesized ``torus2d`` policy):
    the chunk count adapts to the WORLD's most-square 2-D torus
    factorization (``parallel.topology.torus_factor``) — ``chunks ×
    inner_dim(world)`` contiguous near-equal spans, so each ring step
    forwards one span per inner-axis hop of the physical torus and the
    store-and-forward chain pipelines at the inner-ring granularity. On a
    world whose factorization is a line (inner dim 1 — e.g. world 2) the
    schedule degrades to :func:`chunk_schedule` at the caller's chunk
    count; with ``chunks=1`` there, that is the legacy single span — the
    identity pin."""
    from triton_dist_tpu.parallel.topology import torus_factor

    _, inner = torus_factor(max(1, int(world)))
    return chunk_schedule(rows, max(1, int(chunks)) * inner, quantum)


# Registry the overlap host entries dispatch on (GroupGemmConfig
# .span_policy). "contig" is the legacy schedule — the identity the
# emitter pin tests compare against. Each entry: (schedule_fn,
# needs_world, contiguous_ascending).
SPAN_POLICIES = {
    "contig": (chunk_schedule, False, True),
    "window": (span_window_schedule, False, True),
    "interleave": (span_interleave_schedule, False, False),
    "torus2d": (span_torus2d_schedule, True, True),
}


def validate_span_policy(policy: str, side: str) -> None:
    """The span-policy config fence: unknown names and side-invalid
    pairings raise with a named diagnosis. The overlap HOST entries call
    this BEFORE their ``guarded_call`` ladder — a policy misconfiguration
    is a config error that must fail loudly, not a kernel failure the
    guard may silently downgrade to the golden path."""
    try:
        _, _, ascending = SPAN_POLICIES[policy]
    except KeyError:
        raise ValueError(
            f"unknown span_policy {policy!r}; known: {sorted(SPAN_POLICIES)}"
        ) from None
    if side == "ag" and not ascending:
        raise ValueError(
            f"span_policy {policy!r} emits non-contiguous span order, which "
            f"the AG gather-group schedule cannot consume (its group "
            f"coverage is derived from ascending span offsets); valid "
            f"sides: moe_rs"
        )


def resolve_spans(
    rows: int, chunks: int, quantum: int, *, policy: str = "contig",
    world: int = 1, side: str = "moe_rs",
) -> tuple[tuple[int, int], ...]:
    """The span schedule for one overlap launch: dispatch
    ``GroupGemmConfig.span_policy`` to its schedule function.
    ``side="ag"`` (the AG-GroupGEMM ring) requires ascending contiguous
    spans — its gather-group arithmetic derives each span's compute
    coverage from the span offsets, and the last LIST entry absorbs the
    group tail — so order-permuting policies are rejected with a named
    diagnosis (the same validity rule ``synth/generate.py`` prunes on).
    ``policy="contig"`` is byte-for-byte :func:`chunk_schedule`."""
    validate_span_policy(policy, side)
    fn, needs_world, _ = SPAN_POLICIES[policy]
    if needs_world:
        return fn(rows, chunks, quantum, world)
    return fn(rows, chunks, quantum)


def gemm_add_pipeline(
    bm: int, bn: int, bk: int, m_dim: int, n_dim: int, k_dim: int,
    acc_ref, out_dtype, n_adds: int = 0,
):
    """Tiled ``O = A @ B (+ sum(adds))`` as an inner ``emit_pipeline``: f32
    VMEM accumulation over the k grid dim with the optional adds fused into
    the last-k epilogue. The shared MXU workhorse of the fused kernels
    (≙ the consumer/producer GEMM bodies of reference allgather_gemm.py:133
    and gemm_reduce_scatter.py:125). Add operands use a k-invariant index
    map, so Pallas fetches each of their tiles once."""
    n_k = k_dim // bk

    def body(a_blk, b_blk, *rest):
        o_blk = rest[-1]
        adds = rest[:-1]
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jnp.dot(a_blk[:], b_blk[:], preferred_element_type=jnp.float32)

        @pl.when(kk == n_k - 1)
        def _():
            acc = acc_ref[:]
            for r in adds:
                acc = acc + r[:].astype(jnp.float32)
            o_blk[:] = acc.astype(out_dtype)

    return pltpu.emit_pipeline(
        body,
        grid=(m_dim // bm, n_dim // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ]
        + [pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))] * n_adds,
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))],
    )


def gemm_only(a, b, *, cfg, out_dtype, name: str, interpret=None):
    """Pure-MXU pipelined matmul — the world-1 degenerate path shared by the
    fused ops (same inner ``gemm_add_pipeline``, minus workspace and ring).
    `cfg` is any config with block_m/block_n/block_k (AGGemmConfig,
    GemmRSConfig, …); `name` keeps traces/profiles attributed to the real op."""
    from triton_dist_tpu.utils import pick_block

    m_loc, k_dim = a.shape
    n_loc = b.shape[1]
    bm = pick_block(m_loc, cfg.block_m)
    bn = pick_block(n_loc, cfg.block_n)
    bk = pick_block(k_dim, cfg.block_k)

    def _kernel(a_ref, b_ref, out_ref, acc_ref):
        pipeline = gemm_add_pipeline(bm, bn, bk, m_loc, n_loc, k_dim, acc_ref, out_dtype)
        pipeline(a_ref, b_ref, out_ref)

    return dist_pallas_call(
        _kernel,
        name=name,
        out_shape=jax.ShapeDtypeStruct((m_loc, n_loc), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * m_loc * n_loc * k_dim,
            bytes_accessed=(m_loc * k_dim + k_dim * n_loc + m_loc * n_loc) * a.dtype.itemsize,
            transcendentals=0,
        ),
        # the emit_pipeline double-buffers a/b/out tiles; the default 16 MiB
        # budget rejects the large-tile configs the autotuner wants to try
        vmem_limit_bytes=2 * 2 * (bm * bk + bk * bn + bm * bn) * a.dtype.itemsize
        + 4 * bm * bn
        + 2 * 2**20,
        uses_barrier=False,
        interpret=interpret,
    )(a, b)


_jit_cache: dict[Any, Any] = {}
# unarmed dispatch wrappers, keyed like _jit_cache: callers compare entry
# identity (tests pin f1 is f2 for the zero-overhead path), so the span
# wrapper must be as cached as the jitted program it fronts
_wrapper_cache: dict[Any, Any] = {}


def jit_shard_map(
    fn,
    mesh,
    in_specs,
    out_specs,
    *,
    key: Any,
    donate_argnums: tuple = (),
):
    """``jax.jit(jax.shard_map(fn, ...))`` cached across calls.

    ``jax.jit`` keys its cache on the callable's identity; building a fresh
    ``shard_map`` wrapper per invocation (what every ``*_op`` convenience
    entry naturally does) therefore retraces AND recompiles every call —
    measured ~2 s per call on a tunneled TPU. `key` must capture everything
    that changes the traced program besides the mesh/specs (op name, config,
    method, static dims); argument shapes/dtypes are handled by jit itself.

    When the watchdog is armed (``config.timeout_iters > 0``) the traced fn
    runs inside a ``watchdog.collect`` scope: every ``dist_pallas_call`` it
    launches contributes its diagnostic buffer, the merged per-PE record
    rides back as one extra shard_map output (outputs are NaN-poisoned
    in-program on the PEs that tripped), and host-side a non-clean record
    raises :class:`resilience.DistTimeoutError` (or, with
    ``config.raise_on_timeout=False``, returns the poisoned outputs after
    recording the event in ``resilience.health``).
    """
    from triton_dist_tpu import config as _tdt_config
    from triton_dist_tpu import obs as _obs
    from triton_dist_tpu.obs import telemetry as _obs_telem
    from triton_dist_tpu.resilience import faults as _faults
    from triton_dist_tpu.resilience import records as _records
    from triton_dist_tpu.resilience import watchdog as _watchdog

    cfg = _tdt_config.get_config()
    armed = int(cfg.timeout_iters) > 0
    # wait-telemetry tier (ISSUE 9): the traced program grows one more
    # gathered output, so the request is part of the program cache key
    ws = armed and _obs.wait_stats_enabled()
    family = key[0] if isinstance(key, tuple) and key and isinstance(key[0], str) else str(key)

    def _cache_key():
        cfg = _tdt_config.get_config()
        return (
            mesh, str(in_specs), str(out_specs), donate_argnums, key,
            # trace-time config that changes the kernel program (a cached
            # un-delayed program must not serve a race-shaking, watchdogged,
            # or fault-injected run, and vice versa). The fault-plan token
            # flips when a bounded plan's trigger budget is spent, so a
            # healed retry traces — and caches — the clean program.
            cfg.debug_comm_delay, cfg.timeout_iters, _faults.plan_token(), ws,
        )

    def _resolve():
        cache_key = _cache_key()
        hit = _jit_cache.get(cache_key)
        if hit is None:
            if armed:
                def fn_diag(*args):
                    # want_telem rides the scope so the kernels traced
                    # inside arm their telemetry output to MATCH this
                    # program's output structure (see watchdog.collect)
                    with _watchdog.collect(want_telem=ws) as entries:
                        out = fn(*args)
                    diag = _watchdog.merge([d for d, _ in entries])
                    bad = diag[0, _records.F_STATUS] != _records.STATUS_OK
                    if ws:
                        telems = [t for _, t in entries if t is not None]
                        telem = (
                            jnp.stack(telems) if telems
                            else jnp.zeros(
                                (1, _obs_telem.TELEM_LEN), jnp.int32
                            )
                        )
                        return _watchdog.poison(out, bad), diag, telem
                    return _watchdog.poison(out, bad), diag

                diag_out_spec = PartitionSpec(tuple(mesh.axis_names), None)
                armed_out_specs = (
                    (out_specs, diag_out_spec, diag_out_spec) if ws
                    else (out_specs, diag_out_spec)
                )
                hit = jax.jit(
                    _shard_map(fn_diag, mesh, in_specs, armed_out_specs),
                    donate_argnums=donate_argnums,
                )
            else:
                hit = jax.jit(
                    _shard_map(fn, mesh, in_specs, out_specs),
                    donate_argnums=donate_argnums,
                )
            _jit_cache[cache_key] = hit
        return hit

    if not armed:
        # Cached wrapper, keyed like the program cache: unarmed entries
        # with the same key return the IDENTICAL callable (pinned in
        # tests/test_elastic.py). The program is resolved EAGERLY at wrap
        # time and frozen in the closure — exactly the pre-obs semantics:
        # a stored unarmed wrapper must never re-resolve under a config
        # that changed after wrap (re-reading _cache_key per call under a
        # later-armed watchdog would build the unarmed program and cache
        # it under the ARMED key, poisoning the shared program cache).
        # Per-call work is ONE obs.span_enabled() attribute read, so a
        # wrapper stored while obs was disarmed still emits jit spans
        # once obs is armed mid-process; `cached` reports whether this
        # wrapper has dispatched before (jax.jit traces lazily on the
        # first CALL, so that is the trace-vs-cached boundary).
        wrap_key = _cache_key()
        hit = _wrapper_cache.get(wrap_key)
        if hit is not None:
            return hit
        jitted = _resolve()
        state = {"warm": False}

        def unarmed_call(*args):
            if not _obs.span_enabled():
                state["warm"] = True
                return jitted(*args)
            cached = state["warm"]
            state["warm"] = True
            with _obs.span(f"jit:{family}", cat="jit", cached=cached,
                           armed=False):
                return jitted(*args)

        _wrapper_cache[wrap_key] = unarmed_call
        return unarmed_call
    n_world = int(mesh.devices.size)
    # peer attribution is keyed by flattened device index; on a multi-axis
    # mesh the diag rows span the product world while records carry the PE
    # along one comm axis, so attribution only runs on 1-D worlds
    single_axis = mesh.devices.ndim == 1

    def _refuse(reason):
        # the family's collective semaphore state is undefined after an
        # earlier trip (even under raise_on_timeout=False, which raised
        # nothing): refuse the launch with a fallbackable error so an
        # enclosing guard serves the golden path — loud otherwise
        raise NotImplementedError(
            f"distributed kernel family {family!r} refused to launch: "
            f"{reason}; its collective semaphore may hold residue. "
            f"Guarded op entries serve the golden XLA path; see "
            f"docs/resilience.md."
        )

    def _raise_integrity(recs, noted=False):
        # per-chunk canary mismatches (ISSUE 8): corrupt data was
        # DETECTED — outputs arrive NaN-poisoned (the diag status gates
        # the same in-program poison as timeouts), the named PEs are
        # struck directly (victim == culprit under the landing-site
        # model), and the op raises IntegrityError REGARDLESS of
        # raise_on_timeout: poison-and-continue is a timeout posture;
        # silently continuing past known-corrupt data is what this layer
        # exists to prevent. No family pin either — the canary drains its
        # own credits, so there is no semaphore residue to protect.
        from triton_dist_tpu.resilience import elastic as _elastic
        from triton_dist_tpu.resilience import health
        from triton_dist_tpu.resilience import integrity as _integrity

        if not noted:  # mixed-launch callers recorded/struck these already
            health.record_integrity(family, records=recs)
            if _tdt_config.get_config().elastic and single_axis:
                _elastic.note_integrity_records(recs, n_world, family=family)
        err = _integrity.IntegrityError(
            family, _integrity.DET_CANARY, records=recs, world_size=n_world
        )
        err._tdt_recorded = True
        raise err

    def _launch(*args):
        """One resolved-program invocation, normalized to (out, diag):
        the wait-stats variant peels its telemetry output and folds the
        decoded per-site spin records into the obs registry (success and
        failure paths alike — a timed-out launch's surviving sites are
        exactly the attribution a stall question needs)."""
        if ws:
            out, diag, telem = _resolve()(*args)
            _obs_telem.record_decoded(_obs_telem.decode_telem(telem))
        else:
            out, diag = _resolve()(*args)
        return out, diag

    def call(*args):
        from triton_dist_tpu.resilience import health

        reason = health.short_circuited(family)
        if reason is not None:
            _refuse(reason)
        cfg = _tdt_config.get_config()
        policy = cfg.retry_policy
        if policy is None and not cfg.elastic:
            # pre-existing single-attempt path (retry/elastic disabled).
            # Resolved per call, not at wrap time: callers store these
            # wrappers (models/decode serving steps), and a stored wrapper
            # must pick up a healed fault plan's clean program
            out, diag = _launch(*args)
            if cfg.fault_plan is not None:
                _faults.note_launch()
            recs = _records.decode_diag(diag)  # forces the device sync
            if recs:
                to_recs = [r for r in recs if r["status"] != "integrity"]
                if not to_recs:
                    _raise_integrity(recs)
                int_recs = [r for r in recs if r["status"] == "integrity"]
                if int_recs:
                    # mixed launch: the timeout arc below is the louder
                    # event, but the corruption detections must still land
                    # in the registry (attribution strikes need the
                    # elastic path — not this branch, which runs with
                    # elastic disabled)
                    health.record_integrity(family, records=int_recs)
                health.record_timeout(family, to_recs)
                if _tdt_config.get_config().raise_on_timeout:
                    raise _records.DistTimeoutError(
                        family, to_recs, world_size=n_world
                    )
                if int_recs:
                    # poison-and-continue is a TIMEOUT posture only:
                    # detected corruption raises regardless, even when it
                    # co-occurred with a silent timeout
                    _raise_integrity(int_recs, noted=True)
            return out

        # elastic degraded-mode path: transient timeouts are retried with
        # backoff, every failed attempt feeds peer attribution, and
        # exhaustion records the timeout (quarantining the family) and
        # escalates — by which point a persistent straggler has collected
        # enough strikes to be PE-quarantined (docs/resilience.md)
        from triton_dist_tpu.resilience import elastic as _elastic
        from triton_dist_tpu.resilience import retry as _retry

        attempts = policy.max_attempts if policy is not None else 1
        delays = policy.delays(key=family) if policy is not None else ()
        slept = 0.0
        for attempt in range(attempts):
            out, diag = _launch(*args)
            if cfg.fault_plan is not None:
                _faults.note_launch()
            recs = _records.decode_diag(diag)
            if not recs:
                if attempt:
                    health.record_recovery(family, attempt)
                    # stamp the recovery onto the enclosing op:{family}
                    # guard span BY NAME (the guard layer's ladder-rung
                    # record, ISSUE 9) — the innermost open span here is
                    # our own jit:{family} dispatch span
                    _obs.tracer.annotate_span(
                        f"op:{family}", retries=attempt
                    )
                if cfg.elastic:
                    _elastic.note_clean_step(n_world)
                return out
            int_recs = [r for r in recs if r["status"] == "integrity"]
            if int_recs and len(int_recs) == len(recs):
                # pure canary corruption (no timeouts): retried in place
                # under the policy — sound even on compiled TPU, a canary
                # drains its own credits so no semaphore residue exists —
                # counted as integrity_retry (separate from the timeout
                # counters) with the named PEs struck per failed attempt;
                # exhaustion (or a donating entry, whose buffers died with
                # the first attempt) raises IntegrityError
                delay = (
                    delays[attempt] if attempt < len(delays) else 0.0
                )
                over_budget = (
                    policy is not None
                    and policy.total_delay_budget_s is not None
                    and slept + delay > policy.total_delay_budget_s
                )
                if (
                    attempt == attempts - 1 or donate_argnums or over_budget
                ):
                    _raise_integrity(int_recs)  # strikes the named PEs
                if cfg.elastic and single_axis:
                    _elastic.note_integrity_records(
                        int_recs, n_world, family=family
                    )
                health.record_integrity_retry(family, attempt + 1, delay)
                _retry.get_clock().sleep(delay)
                slept += delay
                continue
            # mixed records: the timeout arc below handles the louder
            # event over the timeout records only — but the corruption
            # detections still land in the registry and still strike
            # their named PEs (a persistently corrupt PE that co-occurs
            # with timeouts must not escape attribution)
            if int_recs:
                health.record_integrity(family, records=int_recs)
                if cfg.elastic and single_axis:
                    _elastic.note_integrity_records(
                        int_recs, n_world, family=family
                    )
            recs = [r for r in recs if r["status"] != "integrity"]
            if cfg.elastic and single_axis:
                _elastic.note_timeout_records(recs, n_world, family=family)
            last = attempt == attempts - 1
            if donate_argnums:
                # donated inputs are deleted by the first invocation; a
                # relaunch with the same tuple would read freed buffers.
                # Timeouts on donating entries escalate immediately —
                # host-level retries (ElasticStep) own re-materialization.
                last = True
            if not _tdt_config.interpreting():
                # compiled TPU: the family's collective semaphore may hold
                # residue after the trip (a straggler signal landing after
                # the in-kernel drain) — relaunching the fused kernel on it
                # could pass a wait early and serve stale buffers, so the
                # first trip escalates here. The pin below sends later
                # calls to the golden path, where host-level retries
                # (retry.call_with_retry / ElasticStep) remain safe.
                # Interpret mode rebuilds simulated semaphores per launch,
                # so in-place retry is sound there.
                last = True
            delay = 0.0 if last else delays[attempt]
            over_budget = (
                policy is not None
                and policy.total_delay_budget_s is not None
                and slept + delay > policy.total_delay_budget_s
            )
            if not last and not over_budget:
                health.record_retry(family, attempt + 1, delay, records=recs)
                _retry.get_clock().sleep(delay)
                slept += delay
                continue
            health.record_timeout(family, recs)
            # the elastic world is about to shrink (or already did): in
            # interpret mode the family pin record_timeout just made is
            # hardware-residue protection with nothing to protect — release
            # it so the rebuilt world runs the fused path, not the golden
            _elastic.maybe_release_family_pins()
            if cfg.raise_on_timeout:
                raise _records.DistTimeoutError(family, recs, world_size=n_world)
            if int_recs:
                # corruption raises regardless of the timeout posture —
                # these records were recorded/struck in the mixed handling
                _raise_integrity(int_recs, noted=True)
            return out

    def spanned_call(*args):
        # jit:{family} dispatch span (trace vs cached — the compile-cost
        # attribution ISSUE 9 asks of this boundary). Enablement checked
        # per call so stored wrappers pick up a mid-process arming; the
        # armed path legitimately re-resolves per call (healed fault
        # plans), so `cached` is read from the program cache itself.
        if not _obs.span_enabled():
            return call(*args)
        cached = _cache_key() in _jit_cache
        with _obs.span(f"jit:{family}", cat="jit", cached=cached,
                       armed=True):
            return call(*args)

    return spanned_call


def barrier_all_op(axis: str = "tp", interpret: Any = None) -> None:
    """Standalone device barrier over a mesh axis — call inside shard_map
    (≙ ``barrier_all_on_stream`` / ``barrier_all_intra_node_atomic_cas_block``,
    common_ops.py:87-193)."""

    def _kernel(out_ref):
        shmem.barrier_all(axis)
        out_ref[0] = jnp.int32(1)

    return dist_pallas_call(
        _kernel,
        name="barrier_all",
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        uses_barrier=int(jax.lax.axis_size(axis)) > 1,
        interpret=interpret,
    )()
