"""Shared kernel-building helpers (≙ reference ``kernels/nvidia/common_ops.py``).

The reference's common_ops holds device barrier kernels and host
stream-signal wrappers (``wait_eq``/``set_signal`` over cuStreamWriteValue,
:196-229). On TPU the host cannot poke device memory mid-program, so the
surviving pieces are: a standalone barrier kernel, collective-id management,
and the ``dist_pallas_call`` wrapper that all distributed kernels use.
"""

from __future__ import annotations

import itertools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu.shmem import device as shmem


_collective_id_counter = itertools.count(1)
_collective_ids: dict[str, int] = {}


def collective_id_for(name: str) -> int:
    """Stable collective_id per kernel family (barrier semaphores of
    concurrently-running kernels must not collide). Mosaic supports a small
    fixed pool of collective ids; running out is an error rather than a
    silent wrap that would alias two families' barrier semaphores."""
    if name not in _collective_ids:
        next_id = next(_collective_id_counter)
        if next_id >= 32:
            raise RuntimeError(
                f"out of collective_ids (31 kernel families in use) while "
                f"registering {name!r}; reuse an existing family name in "
                f"dist_pallas_call(name=...) for kernels that never run "
                f"concurrently"
            )
        _collective_ids[name] = next_id
    return _collective_ids[name]


def dist_pallas_call(
    kernel,
    *,
    name: str,
    out_shape: Any,
    in_specs: Sequence[pl.BlockSpec] | None = None,
    out_specs: Any = None,
    grid: tuple[int, ...] | None = None,
    grid_spec: Any = None,
    scratch_shapes: Sequence[Any] = (),
    cost_estimate: pl.CostEstimate | None = None,
    vmem_limit_bytes: int | None = None,
    interpret: Any = None,
    dimension_semantics: tuple[str, ...] | None = None,
    input_output_aliases: dict[int, int] | None = None,
    uses_barrier: bool = True,
):
    """pallas_call with the invariants every distributed kernel needs:
    side effects on (remote DMAs must not be DCE'd), a collective_id for the
    barrier semaphore, and config-resolved interpret mode.

    `uses_barrier` must be False for degenerate single-PE calls: Mosaic
    rejects a collective_id on kernels that never touch the barrier
    semaphore."""
    params: dict[str, Any] = dict(has_side_effects=True)
    if uses_barrier:
        params["collective_id"] = collective_id_for(name)
    if vmem_limit_bytes is not None:
        params["vmem_limit_bytes"] = vmem_limit_bytes
    if dimension_semantics is not None:
        params["dimension_semantics"] = dimension_semantics
    kwargs: dict[str, Any] = {}
    if grid_spec is not None:
        kwargs["grid_spec"] = grid_spec
    else:
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = list(in_specs)
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
    if input_output_aliases:
        kwargs["input_output_aliases"] = input_output_aliases
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        scratch_shapes=list(scratch_shapes),
        compiler_params=pltpu.CompilerParams(**params),
        cost_estimate=cost_estimate,
        interpret=tdt_config.interpret_params() if interpret is None else interpret,
        name=name,
        **kwargs,
    )


def gemm_add_pipeline(
    bm: int, bn: int, bk: int, m_dim: int, n_dim: int, k_dim: int,
    acc_ref, out_dtype, n_adds: int = 0,
):
    """Tiled ``O = A @ B (+ sum(adds))`` as an inner ``emit_pipeline``: f32
    VMEM accumulation over the k grid dim with the optional adds fused into
    the last-k epilogue. The shared MXU workhorse of the fused kernels
    (≙ the consumer/producer GEMM bodies of reference allgather_gemm.py:133
    and gemm_reduce_scatter.py:125). Add operands use a k-invariant index
    map, so Pallas fetches each of their tiles once."""
    n_k = k_dim // bk

    def body(a_blk, b_blk, *rest):
        o_blk = rest[-1]
        adds = rest[:-1]
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jnp.dot(a_blk[:], b_blk[:], preferred_element_type=jnp.float32)

        @pl.when(kk == n_k - 1)
        def _():
            acc = acc_ref[:]
            for r in adds:
                acc = acc + r[:].astype(jnp.float32)
            o_blk[:] = acc.astype(out_dtype)

    return pltpu.emit_pipeline(
        body,
        grid=(m_dim // bm, n_dim // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ]
        + [pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))] * n_adds,
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))],
    )


def gemm_only(a, b, *, cfg, out_dtype, name: str, interpret=None):
    """Pure-MXU pipelined matmul — the world-1 degenerate path shared by the
    fused ops (same inner ``gemm_add_pipeline``, minus workspace and ring).
    `cfg` is any config with block_m/block_n/block_k (AGGemmConfig,
    GemmRSConfig, …); `name` keeps traces/profiles attributed to the real op."""
    from triton_dist_tpu.utils import pick_block

    m_loc, k_dim = a.shape
    n_loc = b.shape[1]
    bm = pick_block(m_loc, cfg.block_m)
    bn = pick_block(n_loc, cfg.block_n)
    bk = pick_block(k_dim, cfg.block_k)

    def _kernel(a_ref, b_ref, out_ref, acc_ref):
        pipeline = gemm_add_pipeline(bm, bn, bk, m_loc, n_loc, k_dim, acc_ref, out_dtype)
        pipeline(a_ref, b_ref, out_ref)

    return dist_pallas_call(
        _kernel,
        name=name,
        out_shape=jax.ShapeDtypeStruct((m_loc, n_loc), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * m_loc * n_loc * k_dim,
            bytes_accessed=(m_loc * k_dim + k_dim * n_loc + m_loc * n_loc) * a.dtype.itemsize,
            transcendentals=0,
        ),
        # the emit_pipeline double-buffers a/b/out tiles; the default 16 MiB
        # budget rejects the large-tile configs the autotuner wants to try
        vmem_limit_bytes=2 * 2 * (bm * bk + bk * bn + bm * bn) * a.dtype.itemsize
        + 4 * bm * bn
        + 2 * 2**20,
        uses_barrier=False,
        interpret=interpret,
    )(a, b)


_jit_cache: dict[Any, Any] = {}


def jit_shard_map(
    fn,
    mesh,
    in_specs,
    out_specs,
    *,
    key: Any,
    donate_argnums: tuple = (),
):
    """``jax.jit(jax.shard_map(fn, ...))`` cached across calls.

    ``jax.jit`` keys its cache on the callable's identity; building a fresh
    ``shard_map`` wrapper per invocation (what every ``*_op`` convenience
    entry naturally does) therefore retraces AND recompiles every call —
    measured ~2 s per call on a tunneled TPU. `key` must capture everything
    that changes the traced program besides the mesh/specs (op name, config,
    method, static dims); argument shapes/dtypes are handled by jit itself.
    """
    from triton_dist_tpu import config as _tdt_config

    cache_key = (
        mesh, str(in_specs), str(out_specs), donate_argnums, key,
        # trace-time config that changes the kernel program (a cached
        # un-delayed program must not serve a race-shaking run)
        _tdt_config.get_config().debug_comm_delay,
    )
    hit = _jit_cache.get(cache_key)
    if hit is None:
        hit = jax.jit(
            jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            ),
            donate_argnums=donate_argnums,
        )
        _jit_cache[cache_key] = hit
    return hit


def barrier_all_op(axis: str = "tp", interpret: Any = None) -> None:
    """Standalone device barrier over a mesh axis — call inside shard_map
    (≙ ``barrier_all_on_stream`` / ``barrier_all_intra_node_atomic_cas_block``,
    common_ops.py:87-193)."""

    def _kernel(out_ref):
        shmem.barrier_all(axis)
        out_ref[0] = jnp.int32(1)

    return dist_pallas_call(
        _kernel,
        name="barrier_all",
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        uses_barrier=int(jax.lax.axis_size(axis)) > 1,
        interpret=interpret,
    )()
