"""Kernel zoo (≙ reference ``python/triton_dist/kernels/nvidia/``)."""

from triton_dist_tpu.ops.gemm import matmul
from triton_dist_tpu.ops.allgather import (
    all_gather,
    all_gather_op,
    get_auto_all_gather_method,
)
from triton_dist_tpu.ops.common import barrier_all_op
