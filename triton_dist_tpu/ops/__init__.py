"""Kernel zoo (≙ reference ``python/triton_dist/kernels/nvidia/``)."""

from triton_dist_tpu.ops.gemm import matmul
from triton_dist_tpu.ops.allgather import (
    all_gather,
    all_gather_op,
    get_auto_all_gather_method,
)
from triton_dist_tpu.ops.common import barrier_all_op
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm, ag_gemm_op
from triton_dist_tpu.ops.reduce_scatter import (
    ReduceScatterConfig,
    get_auto_reduce_scatter_method,
    reduce_scatter,
    reduce_scatter_op,
)
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs, gemm_rs_op
from triton_dist_tpu.ops.grads import (
    ag_gemm_grad,
    gemm_rs_grad,
    tp_moe_mlp_grad,
    tp_moe_mlp_op,
)
from triton_dist_tpu.ops.allgather_group_gemm import (
    ag_group_gemm,
    ag_group_gemm_op,
    ag_group_gemm_overlap,
)
from triton_dist_tpu.ops.group_gemm import (
    GroupGemmConfig,
    group_gemm,
    group_gemm_fp8,
    group_gemm_w8,
    quantize_expert_weights,
    quantize_expert_weights_fp8,
)
from triton_dist_tpu.ops.moe_reduce_rs import (
    moe_reduce_rs,
    moe_reduce_rs_op,
    moe_reduce_rs_overlap,
)
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    RankedAlignment,
    moe_align_block_size,
    moe_align_ranked,
    ranked_global_view,
    ranked_scatter_meta,
    select_experts,
    valid_rows_from_sorted,
)
from triton_dist_tpu.ops.all_to_all import (
    A2AConfig,
    all_to_all_post_process,
    fast_all_to_all,
    fast_all_to_all_op,
)
from triton_dist_tpu.ops.flash_decode import (
    FlashDecodeConfig,
    combine_partials,
    flash_decode,
    flash_decode_distributed,
    flash_decode_op,
    flash_decode_fp8,
    flash_decode_fp8_distributed,
    flash_decode_quant,
    flash_decode_quant_distributed,
    flash_ranged_prefill_fp8_distributed,
    flash_verify,
    flash_verify_distributed,
    flash_verify_fp8,
    paged_flash_decode,
    paged_flash_decode_distributed,
    paged_flash_decode_fp8,
    paged_flash_decode_quant,
    paged_flash_verify,
    paged_flash_verify_distributed,
    quantize_kv,
    quantize_kv_fp8,
    quantize_kv_pages,
    quantize_kv_pages_fp8,
)
# NOTE: the in-shard_map `kv_stream` entry stays module-qualified
# (ops.kv_stream.kv_stream) — re-exporting it here would shadow the
# submodule attribute on the package
from triton_dist_tpu.ops.kv_stream import (
    KVStreamConfig,
    KV_STREAM_TUNE_SPACE,
    dequantize_kv_wire,
    kv_stream_op,
    quantize_kv_wire,
    quantize_kv_wire_fp8,
)
from triton_dist_tpu.ops.grads import ring_attention_grad
from triton_dist_tpu.ops.ring_attention import (
    RingAttentionConfig,
    ring_attention,
    ring_attention_op,
    zigzag_permutation,
    zigzag_positions,
)
from triton_dist_tpu.ops.ulysses import ulysses_attention, usp_attention
