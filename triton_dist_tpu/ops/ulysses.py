"""Ulysses-style sequence parallelism: all-to-all head exchange
(DeepSpeed-Ulysses — the OTHER standard SP recipe; SURVEY.md §5 notes the
reference implements neither Ulysses nor ring prefill. Ring attention
(ops/ring_attention.py) keeps q resident and circulates KV; Ulysses instead
re-shards [seq → heads] with one all-to-all, runs dense LOCAL attention on
each PE's head slice over the full sequence, and re-shards back. Fewer,
bigger collectives — the better trade when heads ≥ world and per-hop
latency dominates.)

Transport is the framework's own ``fast_all_to_all`` slab exchange
(ops/all_to_all.py): head-group slabs are equal-sized, so the padded-slab
contract is exact (no padding waste), and q/k/v ride ONE fused exchange
(their rows concatenated per slab) — two collectives per forward, two per
backward. Differentiable end-to-end via a custom VJP:
the transpose of the head exchange is the reverse exchange, so the backward
is the same two collectives around the local attention's VJP.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from triton_dist_tpu.utils import axis_size as _axis_size

def _exchange(x: jax.Array, axis: str, n: int, interpret: Any):
    """[n, rows, d] slab exchange (slab j → PE j); returns same shape with
    slab i = what PE i sent here. Shapes are static and equal, so splits
    are full. Differentiable (the a2a VJP is the reverse exchange), so
    compositions like :func:`usp_attention` autodiff through it."""
    from triton_dist_tpu.ops.grads import fast_all_to_all_grad

    rows = x.shape[1]
    splits = jnp.full((n,), rows, jnp.int32)
    recv, _, _ = fast_all_to_all_grad(x, splits, None, axis, interpret)
    return recv


def _seq_to_heads(arrs, axis, n, interpret):
    """[b, h, s_loc, d] seq-sharded → [b, h/n, S, d] head-sharded, for a
    tuple of same-shape arrays IN ONE EXCHANGE: slab j carries the arrays'
    head-group-j rows back to back, so q/k/v cost one collective (and one
    barrier), not three."""
    b, h, s_loc, d = arrs[0].shape
    h_loc = h // n
    rows = b * h_loc * s_loc
    # slab j = head group j (all local seq rows), arrays concatenated
    slabs = jnp.concatenate(
        [
            a.reshape(b, n, h_loc, s_loc, d)
            .transpose(1, 0, 2, 3, 4)
            .reshape(n, rows, d)
            for a in arrs
        ],
        axis=1,
    )
    recv = _exchange(slabs, axis, n, interpret)
    # slab i holds seq chunk i of my head group
    return tuple(
        recv[:, i * rows : (i + 1) * rows]
        .reshape(n, b, h_loc, s_loc, d)
        .transpose(1, 2, 0, 3, 4)
        .reshape(b, h_loc, n * s_loc, d)
        for i in range(len(arrs))
    )


def _heads_to_seq(arrs, axis, n, interpret):
    """[b, h/n, S, d] head-sharded → [b, h, s_loc, d] seq-sharded for a
    tuple of same-shape arrays in one exchange (the exact transpose of
    :func:`_seq_to_heads`)."""
    b, h_loc, s_tot, d = arrs[0].shape
    s_loc = s_tot // n
    rows = b * h_loc * s_loc
    slabs = jnp.concatenate(
        [
            a.reshape(b, h_loc, n, s_loc, d)
            .transpose(2, 0, 1, 3, 4)      # slab i = seq chunk i → PE i
            .reshape(n, rows, d)
            for a in arrs
        ],
        axis=1,
    )
    recv = _exchange(slabs, axis, n, interpret)
    # slab j = head group j computed by PE j, for MY seq chunk
    return tuple(
        recv[:, i * rows : (i + 1) * rows]
        .reshape(n, b, h_loc, s_loc, d)
        .transpose(1, 0, 2, 3, 4)
        .reshape(b, n * h_loc, s_loc, d)
        for i in range(len(arrs))
    )


def _local_attention(q, k, v, causal: bool):
    """Dense attention on the local head slice over the FULL sequence."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhsd->bhqs", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if causal:
        s_tot = q.shape[2]
        mask = jnp.tril(jnp.ones((s_tot, s_tot), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqs,bhsd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str = "tp",
    causal: bool = True,
    interpret: Any = None,
) -> jax.Array:
    """Sequence-parallel attention via head exchange (call inside
    ``jax.shard_map``). q, k, v: ``[b, h, s_loc, d]`` sequence shards with
    ``h % axis_size == 0``; returns the same layout. Golden: full (causal)
    attention over the gathered sequence."""
    n = _axis_size((axis))
    if n == 1:
        return _local_attention(q, k, v, causal)
    qh, kh, vh = _seq_to_heads((q, k, v), axis, n, interpret)
    oh = _local_attention(qh, kh, vh, causal)
    return _heads_to_seq((oh,), axis, n, interpret)[0]


def _ulysses_fwd(q, k, v, axis, causal, interpret):
    n = _axis_size((axis))
    if n == 1:
        return _local_attention(q, k, v, causal), (q, k, v)
    qh, kh, vh = _seq_to_heads((q, k, v), axis, n, interpret)
    oh = _local_attention(qh, kh, vh, causal)
    # residuals are the head-sharded inputs in BOTH cases (at n==1 the two
    # layouts coincide); the local attention is recomputed in the backward
    # (flash-style remat) rather than storing its linearization
    return _heads_to_seq((oh,), axis, n, interpret)[0], (qh, kh, vh)


def _ulysses_bwd(axis, causal, interpret, res, dout):
    qh, kh, vh = res
    n = _axis_size((axis))
    _, vjp = jax.vjp(lambda *a: _local_attention(*a, causal), qh, kh, vh)
    if n == 1:
        return vjp(dout)
    # transpose of heads→seq is seq→heads (a permutation both ways)
    (dout_h,) = _seq_to_heads((dout,), axis, n, interpret)
    dqh, dkh, dvh = vjp(dout_h)
    return _heads_to_seq((dqh, dkh, dvh), axis, n, interpret)


ulysses_attention.defvjp(_ulysses_fwd, _ulysses_bwd)


def usp_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    outer: str = "sp",
    inner: str = "tp",
    causal: bool = True,
    ring_config: Any = None,
    layout: str = "contig",
    interpret: Any = None,
) -> jax.Array:
    """Unified sequence parallelism (USP): Ulysses head exchange over the
    `inner` (fast) axis composed with ring attention over the `outer` axis
    — long-context attention over MORE chips than there are heads, the
    regime neither recipe covers alone (Ulysses needs h >= world; a flat
    ring pays n-1 hops of latency).

    q, k, v: ``[b, h, s_loc, d]`` with the sequence sharded over BOTH axes
    outer-major (s_loc = S / (n_o * n_i)) and ``h % n_i == 0``. After the
    inner head exchange each PE holds h/n_i heads of its outer group's
    contiguous sequence block, which is exactly the ring kernel's contig
    layout over `outer` (``layout="zigzag"`` composes as usual: permute
    the GLOBAL sequence with ``zigzag_permutation(n_o, S)``).
    Differentiable end-to-end (ring VJP + self-inverse exchanges).
    """
    from triton_dist_tpu.ops.grads import ring_attention_grad

    n_i = _axis_size((inner))
    if n_i == 1:
        return ring_attention_grad(
            q, k, v, outer, causal, ring_config, interpret, layout
        )
    qh, kh, vh = _seq_to_heads((q, k, v), inner, n_i, interpret)
    oh = ring_attention_grad(
        qh, kh, vh, outer, causal, ring_config, interpret, layout
    )
    return _heads_to_seq((oh,), inner, n_i, interpret)[0]
