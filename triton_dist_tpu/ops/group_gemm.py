"""Block-aligned grouped GEMM for MoE expert compute
(≙ the grouped-GEMM halves of reference ``allgather_group_gemm.py:420``
``kernel_consumer_m_parallel_scatter_group_gemm`` and
``moe_reduce_rs.py:362`` ``kernel_producer_group_gemm_tp_scatter_input``).

Rows of `a` are pre-sorted by expert and padded so every ``block_m`` tile
belongs to one expert (see ``moe_utils.moe_align_block_size``); the owning
expert of each row-block arrives via scalar prefetch, steering the weight
BlockSpec's index_map — the TPU analogue of the reference reading its
device-side ``gather_index``/``expert_index`` tensors per tile; the MXU
pipeline is an ordinary tiled matmul whose B operand hops between experts.

Kernel bodies come from the pipeline emitter
(:mod:`triton_dist_tpu.ops.gg_pipeline`, ISSUE 7): operand format ×
tile validity × schedule as composable policies, the default tuple
bit-exact to the retired legacy kernels. Every public entry runs under
``resilience.guarded_call`` with a golden XLA implementation
(expert-sorted ``ragged_dot`` over the same padded layout) — the
degradation discipline every fused collective family carries (PR 1/6).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.ops.common import dist_pallas_call
from triton_dist_tpu.ops.gg_pipeline import (
    OperandFormat,
    make_group_gemm_dw_kernel,
    make_group_gemm_kernel,
)
from triton_dist_tpu.utils import pick_block


@dataclasses.dataclass(frozen=True)
class GroupGemmConfig:
    block_m: int = 128  # must equal the alignment block size
    block_n: int = 1024
    block_k: int = 512
    # Chunk-granular MoE overlap (ISSUE 4): the OVERLAPPED pipeline kernels
    # (ag_group_gemm_overlap ring + moe_reduce_rs_overlap combine pushes)
    # split each ring-step shard / combine slab into this many per-chunk
    # DMAs consumed the moment each lands. 1 (default) emits the legacy
    # shard-granular schedule bit for bit; the grid-based group_gemm and
    # the sequential compositions ignore it (nothing to chunk there).
    chunks_per_shard: int = 1
    # Ragged grouped GEMM (ISSUE 5, the MegaBlocks move): consume the
    # alignment's per-block (expert_id, valid_rows) map and spend MXU time
    # only on each block's live row panels (quantized to the 128-row MXU
    # tile), instead of computing every alignment pad row. Layout is
    # untouched — big block_m keeps amortizing the B-operand stream while
    # the pad tax (worst-case E·(block_m−1) rows the legacy grid always
    # computes) drops to the panel quantum. False (default) emits the
    # legacy padded schedule bit for bit.
    ragged: bool = False
    # w8 weights (ISSUE 7): quantize the expert bank to int8 + per-
    # (expert, out-column) f32 scales at the op boundary and stream HALF
    # the weight bytes through every grouped GEMM — including both fused
    # overlap pipelines, where the weight stream is the decode regime's
    # bound resource. On-the-fly quantize costs one bank read+write per
    # call, amortized over the pipelines' MANY weight-slab re-reads;
    # single-pass callers should feed pre-quantized pools through the
    # scale= operands instead. SERVING knob: forward-only; every backward
    # strips it (straight-through, ops.grads). False = bit-exact bf16.
    w8: bool = False
    # "pallas" (default) = the fused kernels above. "ragged_dot" = the XLA
    # sentinel (VERDICT r5 #1): the grouped GEMMs lower to
    # ``jax.lax.ragged_dot`` over the same padded layout — an in-tuner A/B
    # against XLA's own ragged kernel. Requires globally expert-sorted
    # blocks, so the MoE pipeline routes it through the sequential
    # composition.
    backend: str = "pallas"
    # Span-schedule policy of the OVERLAPPED pipelines (ISSUE 14): how the
    # per-ring-step shard / combine slab is tiled into chunk spans.
    # "contig" (default) is the legacy near-equal contiguous tiling of
    # ``ops.common.chunk_schedule``, bit for bit; the other names
    # ("window", "interleave", "torus2d" — ``ops.common.SPAN_POLICIES``)
    # are SYNTHESIZED schedules that enter tune spaces only after the
    # generate → prove → admit loop of ``triton_dist_tpu/synth`` proves
    # them credit-balanced and deadlock-free (docs/analysis.md). The grid
    # group_gemm and sequential compositions ignore it, like
    # chunks_per_shard.
    span_policy: str = "contig"
    # fp8 weights (ISSUE 19): quantize the expert bank to fp8_e4m3 + the
    # SAME per-(expert, out-column) f32 scale layout as w8 and stream the
    # weight bytes at quarter rate through every grouped GEMM — one rung
    # below w8 on the precision ladder, the remaining lever for the
    # still-sub-ceiling decode-shaped weight stream. Rides the w8 slot
    # structure verbatim (``OperandFormat.scaled``); exclusive with
    # ``w8``. SERVING knob like w8: forward-only, every backward strips
    # it. False = untouched. (Appended after span_policy so historical
    # positional constructions keep their meaning.)
    fp8: bool = False

    def __post_init__(self):
        if self.w8 and self.fp8:
            raise ValueError(
                "GroupGemmConfig: w8 and fp8 are exclusive operand formats"
            )


# The MXU row tile: live rows are quantized UP to this many before the
# ragged kernels skip a panel (a sub-128-row dot would waste the MXU's
# 128×128 systolic array anyway). Tests monkeypatch this to exercise
# panel skipping at interpreter-friendly block sizes.
_PANEL_ROWS = 128


def _panel_for(block_m: int) -> int:
    """Ragged row-panel size for a block: the largest power-of-2-shrinkable
    divisor of block_m at most the MXU row tile (shared picker semantics
    with the kernels' other block shapes)."""
    return pick_block(block_m, _PANEL_ROWS)


def quantize_expert_weights(b: jax.Array):
    """Per-(expert, out-column) absmax int8 quantization of expert weights
    ``[E, K, N]`` → ``(b_q int8, scale f32 [E, 1, N])`` for
    :func:`group_gemm_w8` / ``GroupGemmConfig(w8=True)``. Column
    granularity keeps the scale application a single row-broadcast multiply
    on the accumulator (the standard weight-only PTQ layout); ~0.2-0.5%
    RMS error on gaussian weights."""
    bf = b.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(bf), axis=1, keepdims=True) / 127.0, 1e-8)
    b_q = jnp.clip(jnp.round(bf / scale), -127, 127).astype(jnp.int8)
    return b_q, scale


# fp8_e4m3fn: the finite-max e4m3 variant every backend ships; 448 is its
# largest normal — the absmax maps onto it exactly as 127 does for int8.
FP8_DTYPE = jnp.float8_e4m3fn
_FP8_MAX = 448.0


def quantize_expert_weights_fp8(b: jax.Array):
    """Per-(expert, out-column) absmax fp8_e4m3 quantization of expert
    weights ``[E, K, N]`` → ``(b_q fp8, scale f32 [E, 1, N])`` for
    :func:`group_gemm_fp8` / ``GroupGemmConfig(fp8=True)`` — the int8
    quantizer's exact shape with 448 (the e4m3 max normal) in 127's seat
    and the rounding left to the dtype cast (e4m3 keeps a mantissa, so
    nearest-even beats pre-rounding). Scale layout is identical to
    :func:`quantize_expert_weights`, so every scale-fold site downstream
    is shared."""
    bf = b.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(bf), axis=1, keepdims=True) / _FP8_MAX, 1e-8
    )
    b_q = jnp.clip(bf / scale, -_FP8_MAX, _FP8_MAX).astype(FP8_DTYPE)
    return b_q, scale


def resolve_w8(b: jax.Array, scale: jax.Array | None, cfg: GroupGemmConfig):
    """The quantized-format config axes at an op boundary: with ``cfg.w8``
    or ``cfg.fp8`` and no caller scales, quantize the float bank on the
    fly; explicit ``scale`` (the pre-quantized serving path) wins.
    Returns ``(b, scale)``."""
    if scale is not None or not (cfg.w8 or getattr(cfg, "fp8", False)):
        return b, scale
    fp8 = getattr(cfg, "fp8", False)
    if not jnp.issubdtype(b.dtype, jnp.floating) or b.dtype == FP8_DTYPE:
        raise ValueError(
            f"GroupGemmConfig.{'fp8' if fp8 else 'w8'} with a pre-quantized "
            "weight bank needs the matching per-(expert, out-column) scale "
            "(pass scale=, from quantize_expert_weights"
            f"{'_fp8' if fp8 else ''})"
        )
    return (quantize_expert_weights_fp8 if fp8 else quantize_expert_weights)(b)


def _ragged_dot_group_gemm(
    a_sorted, b, expert_ids, *, scale, out_dtype, act_fn, n_exp, bm,
):
    """The XLA sentinel (``GroupGemmConfig.backend="ragged_dot"``):
    ``jax.lax.ragged_dot`` over the SAME padded block-aligned layout.
    Blocks must be globally expert-sorted (every in-repo global alignment
    is; the rank-major overlap layout is not — the pipeline routes the
    sentinel through the sequential composition). Pad rows are treated as
    real rows of their block's expert, exactly as the Pallas legacy kernel
    treats them, so outputs agree row for row on live rows."""
    ids = jnp.clip(expert_ids, 0, n_exp - 1)
    group_sizes = (jnp.bincount(ids, length=n_exp) * bm).astype(jnp.int32)
    a = a_sorted
    if act_fn is not None:
        a = act_fn(a.astype(jnp.float32)).astype(a_sorted.dtype)
    out = jax.lax.ragged_dot(
        a, b.astype(a.dtype) if scale is not None else b,
        group_sizes=group_sizes,
        preferred_element_type=jnp.float32,
    )
    if scale is not None:
        # per-row expert scale: rows of block i belong to expert ids[i]
        row_e = jnp.repeat(ids, bm)
        out = out * scale[row_e, 0, :]
    return out.astype(out_dtype)


def _group_gemm_xla(
    a_sorted, b, expert_ids, *, valid_rows, scale, ragged, bm, out_dtype,
    act_fn, **_,
):
    """The golden slow path (the program the kernel is tested against):
    globally expert-sort the blocks, one ``jax.lax.ragged_dot`` over the
    SAME padded layout, unsort — pad rows computed as real rows of their
    block's (clamped) expert, the w8 scale folded in f32 before the
    ragged dead-row mask, exactly the kernel contract. The sort/unsort
    (vs gathering a ``[nb, K, N]`` weight batch) keeps the fallback's
    memory at the bank size — degraded environments must not OOM."""
    n_exp = b.shape[0]
    nb = expert_ids.shape[0]
    ids = jnp.clip(expert_ids, 0, n_exp - 1)
    a = a_sorted
    if act_fn is not None:
        a = act_fn(a.astype(jnp.float32)).astype(a_sorted.dtype)
    order = jnp.argsort(ids, stable=True)
    inv = jnp.argsort(order)
    a3 = a.reshape(nb, bm, -1)
    group_sizes = (jnp.bincount(ids, length=n_exp) * bm).astype(jnp.int32)
    out = jax.lax.ragged_dot(
        a3[order].reshape(nb * bm, -1),
        b.astype(a.dtype) if scale is not None else b,
        group_sizes=group_sizes,
        preferred_element_type=jnp.float32,
    )
    if scale is not None:
        out = out * scale[jnp.repeat(ids[order], bm), 0, :]
    out = out.reshape(nb, bm, -1)[inv]
    if ragged:
        rows = jnp.arange(bm, dtype=jnp.int32)[None, :, None]
        out = jnp.where(rows < valid_rows[:, None, None], out, 0.0)
    return out.reshape(nb * bm, -1).astype(out_dtype)


def _group_gemm_fused(
    a_sorted, b, expert_ids, *, valid_rows, scale, ragged, bm, out_dtype,
    act_fn, cfg, interpret,
):
    t_pad, k_dim = a_sorted.shape
    n_exp, _, n_dim = b.shape
    bn = pick_block(n_dim, cfg.block_n)
    bk = pick_block(k_dim, cfg.block_k)
    n_k = k_dim // bk
    # parallel dims must form a grid prefix: n-tiles first (megablox order)
    grid = (n_dim // bn, t_pad // bm, n_k)
    w8 = scale is not None
    if ragged:
        in_specs = [
            pl.BlockSpec((bm, bk), lambda j, i, kk, e_ref, v_ref: (i, kk)),
            pl.BlockSpec(
                (1, bk, bn),
                lambda j, i, kk, e_ref, v_ref: (e_ref[i], kk, j),
            ),
        ]
        args = [expert_ids, valid_rows.astype(jnp.int32), a_sorted, b]
        out_spec = pl.BlockSpec(
            (bm, bn), lambda j, i, kk, e_ref, v_ref: (i, j)
        )
        if w8:
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, bn),
                    lambda j, i, kk, e_ref, v_ref: (e_ref[i], 0, j),
                )
            )
    else:
        in_specs = [
            pl.BlockSpec((bm, bk), lambda j, i, kk, e_ref: (i, kk)),
            pl.BlockSpec(
                (1, bk, bn), lambda j, i, kk, e_ref: (e_ref[i], kk, j)
            ),
        ]
        args = [expert_ids, a_sorted, b]
        out_spec = pl.BlockSpec((bm, bn), lambda j, i, kk, e_ref: (i, j))
        if w8:
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, bn), lambda j, i, kk, e_ref: (e_ref[i], 0, j)
                )
            )
    fp8 = w8 and b.dtype == FP8_DTYPE  # format keyed off the BANK dtype
    if w8:
        args.append(scale.astype(jnp.float32))
        name = "group_gemm_fp8" if fp8 else "group_gemm_w8"
        w_bytes = n_exp * k_dim * n_dim  # int8/fp8: 1 byte
    else:
        name = "group_gemm"
        w_bytes = n_exp * k_dim * n_dim * b.dtype.itemsize
    kernel = make_group_gemm_kernel(
        n_k=n_k, out_dtype=out_dtype, act_fn=act_fn,
        fmt=OperandFormat(w8 and not fp8, fp8), ragged=ragged,
        panel=_panel_for(bm) if ragged else 0,
    )
    return dist_pallas_call(
        kernel,
        name=name,
        out_shape=jax.ShapeDtypeStruct((t_pad, n_dim), out_dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2 if ragged else 1,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * t_pad * k_dim * n_dim,
            bytes_accessed=(t_pad * k_dim + t_pad * n_dim)
            * a_sorted.dtype.itemsize + w_bytes,
            # the fused act_fn re-runs over every A tile once per n-tile
            transcendentals=(
                t_pad * k_dim * (n_dim // bn) if act_fn is not None else 0
            ),
        ),
        dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(*args)


def group_gemm(
    a_sorted: jax.Array,
    b: jax.Array,
    expert_ids: jax.Array,
    *,
    valid_rows: jax.Array | None = None,
    scale: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    out_dtype: Any = None,
    act_fn: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """``out[i*bm:(i+1)*bm] = a_sorted[i*bm:(i+1)*bm] @ b[expert_ids[i]]``.

    a_sorted: ``[t_pad, K]`` block-aligned rows; b: ``[E, K, N]``;
    expert_ids: ``[t_pad // block_m]`` int32 (runtime values — scalar
    prefetch). Returns ``[t_pad, N]``. Golden: the expert-sorted ragged_dot
    (served automatically when the kernel cannot build — resilience layer).

    ``act_fn`` (e.g. ``jax.nn.silu``) is applied to every A tile inside
    the kernel (f32, cast back to A's dtype) — the fused epilogue→
    producer form of ``group_gemm(act(a), ...)`` that deletes the
    standalone activation's full HBM pass over A; the redundant per-
    n-tile VPU recompute hides under the B-operand stream.

    With ``scale`` (``[E, 1, N]`` f32 from
    :func:`quantize_expert_weights`), `b` is an int8-quantized weight
    pool: B tiles upcast in-kernel, per-(expert, out-column) scales fold
    into the accumulator at the last K step. ``config.w8`` quantizes a
    float bank on the fly instead (:func:`resolve_w8`).

    With ``config.ragged`` (needs ``valid_rows`` — the alignment builders'
    per-block live-row map, ``moe_align_block_size(ragged=True)``) the
    kernel skips every dead 128-row panel instead of computing the
    alignment's worst-case pad rows (the ~25% MoE padding tax, VERDICT r5
    #1); dead rows come back exact zeros. ``ragged=False`` emits the
    legacy schedule bit for bit.
    """
    from triton_dist_tpu import resilience

    cfg = config or GroupGemmConfig()
    t_pad = a_sorted.shape[0]
    n_exp = b.shape[0]
    out_dtype = out_dtype or a_sorted.dtype
    n_blocks = expert_ids.shape[0]
    assert t_pad % n_blocks == 0, (t_pad, n_blocks)
    bm = t_pad // n_blocks
    assert bm == cfg.block_m, (
        f"rows-per-block {bm} != config.block_m {cfg.block_m}: alignment and "
        f"GEMM must use the same block size"
    )
    b, scale = resolve_w8(b, scale, cfg)
    if cfg.backend == "ragged_dot":
        return _ragged_dot_group_gemm(
            a_sorted, b, expert_ids, scale=scale, out_dtype=out_dtype,
            act_fn=act_fn, n_exp=n_exp, bm=bm,
        )
    ragged = bool(cfg.ragged)
    if ragged and valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs the alignment's per-block "
            "valid_rows map — build it with moe_align_block_size(..., "
            "ragged=True) / moe_align_ranked(..., ragged=True)"
        )
    if scale is not None:
        assert scale.shape == (n_exp, 1, b.shape[2]), (scale.shape, b.shape)
    return resilience.guarded_call(
        "group_gemm",
        functools.partial(_group_gemm_fused, cfg=cfg, interpret=interpret),
        _group_gemm_xla,
        a_sorted, b, expert_ids, valid_rows=valid_rows, scale=scale,
        ragged=ragged, bm=bm, out_dtype=out_dtype, act_fn=act_fn,
    )


def group_gemm_w8(
    a_sorted: jax.Array,
    b_q: jax.Array,
    scale: jax.Array,
    expert_ids: jax.Array,
    *,
    valid_rows: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    out_dtype: Any = None,
    act_fn: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """:func:`group_gemm` over int8-quantized expert weights (from
    :func:`quantize_expert_weights`): ``out[i·bm:(i+1)·bm] =
    (a_sorted[i·bm:(i+1)·bm] @ upcast(b_q[e])) · scale[e]``.

    The weight stream dominates grouped-GEMM HBM traffic at decode token
    counts (each expert's slab is read regardless of how few rows route
    to it), so int8 weights halve the bound resource. Thin alias of
    :func:`group_gemm` with the ``scale`` operand."""
    return group_gemm(
        a_sorted, b_q, expert_ids, valid_rows=valid_rows, scale=scale,
        config=config, out_dtype=out_dtype, act_fn=act_fn,
        interpret=interpret,
    )


def group_gemm_fp8(
    a_sorted: jax.Array,
    b_q: jax.Array,
    scale: jax.Array,
    expert_ids: jax.Array,
    *,
    valid_rows: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    out_dtype: Any = None,
    act_fn: Any = None,
    interpret: Any = None,
) -> jax.Array:
    """:func:`group_gemm` over fp8_e4m3-quantized expert weights (from
    :func:`quantize_expert_weights_fp8`) — :func:`group_gemm_w8`'s exact
    twin one precision rung down (ISSUE 19): the fp8 B tiles upcast
    in-kernel and the per-(expert, out-column) scales fold into the
    accumulator at the last K step, the shared ``OperandFormat.scaled``
    trace. Thin alias of :func:`group_gemm` with the ``scale`` operand;
    the format is keyed off the bank dtype."""
    return group_gemm(
        a_sorted, b_q, expert_ids, valid_rows=valid_rows, scale=scale,
        config=config, out_dtype=out_dtype, act_fn=act_fn,
        interpret=interpret,
    )


def _group_gemm_dw_xla(
    a_sorted, g_sorted, expert_ids, n_exp, *, valid_rows, ragged, bm, **_,
):
    """Golden dW: the scan of per-block AᵀG dots the fused kernel exists
    to replace — one ``[K, N]`` outer product per step accumulated onto
    the block's expert, so the fallback's working set is one tile, never
    a ``[nb, K, N]`` batch. Padded contract accumulates every row
    (callers pre-zero pad rows, as for the kernel); ragged zeroes each
    block's dead rows on A first — the kernel's in-kernel junk mask."""
    nb = expert_ids.shape[0]
    k_dim = a_sorted.shape[1]
    n_dim = g_sorted.shape[1]
    ids = jnp.clip(expert_ids, 0, n_exp - 1)
    a3 = a_sorted.reshape(nb, bm, k_dim).astype(jnp.float32)
    g3 = g_sorted.reshape(nb, bm, n_dim).astype(jnp.float32)
    if ragged:
        rows = jnp.arange(bm, dtype=jnp.int32)[None, :, None]
        a3 = jnp.where(rows < valid_rows[:, None, None], a3, 0.0)

    def step(acc, xs):
        a_b, g_b, e = xs
        return acc.at[e].add(
            jax.lax.dot_general(
                a_b, g_b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ), None

    acc0 = jnp.zeros((n_exp, k_dim, n_dim), jnp.float32)
    out, _ = jax.lax.scan(step, acc0, (a3, g3, ids))
    return out


def _group_gemm_dw_fused(
    a_sorted, g_sorted, expert_ids, n_exp, *, valid_rows, ragged, bm, cfg,
    interpret,
):
    t_pad, k_dim = a_sorted.shape
    n_dim = g_sorted.shape[1]
    n_blocks = expert_ids.shape[0]
    bk = pick_block(k_dim, cfg.block_k)
    bn = pick_block(n_dim, cfg.block_n)
    # i innermost: output-block visits for one (kk, nn) tile are grouped by
    # expert run; kk/nn never revisit a previously-left block
    grid = (k_dim // bk, n_dim // bn, n_blocks)
    if ragged:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (bm, bk), lambda kk, nn, i, e_ref, v_ref: (i, kk)
                ),
                pl.BlockSpec(
                    (bm, bn), lambda kk, nn, i, e_ref, v_ref: (i, nn)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, bk, bn),
                lambda kk, nn, i, e_ref, v_ref: (e_ref[i], kk, nn),
            ),
            scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
        )
        args = (expert_ids, valid_rows.astype(jnp.int32), a_sorted, g_sorted)
    else:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bm, bk), lambda kk, nn, i, e_ref: (i, kk)),
                pl.BlockSpec((bm, bn), lambda kk, nn, i, e_ref: (i, nn)),
            ],
            out_specs=pl.BlockSpec(
                (1, bk, bn), lambda kk, nn, i, e_ref: (e_ref[i], kk, nn)
            ),
            scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
        )
        args = (expert_ids, a_sorted, g_sorted)
    kernel = make_group_gemm_dw_kernel(
        ragged=ragged, panel=_panel_for(bm) if ragged else 0
    )
    return dist_pallas_call(
        kernel,
        name="group_gemm_dw",
        out_shape=jax.ShapeDtypeStruct((n_exp, k_dim, n_dim), jnp.float32),
        grid_spec=grid_spec,
        cost_estimate=pl.CostEstimate(
            flops=2 * t_pad * k_dim * n_dim,
            bytes_accessed=(
                t_pad * (k_dim + n_dim) * a_sorted.dtype.itemsize
                + n_exp * k_dim * n_dim * 4
            ),
            transcendentals=0,
        ),
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        uses_barrier=False,
        interpret=interpret,
    )(*args)


def group_gemm_dw(
    a_sorted: jax.Array,
    g_sorted: jax.Array,
    expert_ids: jax.Array,
    n_exp: int,
    *,
    valid_rows: jax.Array | None = None,
    config: GroupGemmConfig | None = None,
    assume_sorted: bool = False,
    interpret: Any = None,
) -> jax.Array:
    """Transpose grouped GEMM: ``dW[e] = Σ_{blocks i of e} A_iᵀ @ G_i``
    (the expert-weight gradient of :func:`group_gemm`; ≙ the dW half the
    reference leaves to torch autograd — here a first-class MXU kernel
    instead of a scan of dots). No w8 axis: gradients accumulate against
    the full-precision bank (``ops.grads`` strips ``w8`` from every
    backward config).

    a_sorted ``[t_pad, K]``, g_sorted ``[t_pad, N]`` block-aligned rows in
    the SAME order; expert_ids ``[t_pad // block_m]``. Returns
    ``[n_exp, K, N]`` f32; experts with no rows come back exactly zero.

    The kernel's output-revisit accumulation needs each expert's blocks
    CONSECUTIVE in grid order, so blocks are grouped by expert up front —
    correctness insurance for arbitrary callers (the forward
    ``group_gemm`` is order-independent, so its VJP must be too). Callers
    whose ids come from ``moe_align_block_size`` (sorted by construction)
    pass ``assume_sorted=True`` to skip the two full-array permutation
    copies on the training hot path.
    """
    from triton_dist_tpu import resilience

    cfg = config or GroupGemmConfig()
    t_pad, k_dim = a_sorted.shape
    n_dim = g_sorted.shape[1]
    # enforce the id-range invariant here rather than by caller convention:
    # an out-of-range id would land its block's AᵀG in expert n_exp-1's dW
    # (the output index_map clamps) while the zero-row mask below counted it
    # as occupying a DIFFERENT bucket — clamping first keeps both consistent
    expert_ids = jnp.clip(expert_ids, 0, n_exp - 1)
    n_blocks = expert_ids.shape[0]
    assert t_pad % n_blocks == 0 and t_pad // n_blocks == cfg.block_m, (
        t_pad, n_blocks, cfg.block_m,
    )
    bm = cfg.block_m
    ragged = bool(cfg.ragged) and cfg.backend == "pallas"
    if ragged and valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs the alignment's per-block "
            "valid_rows map (moe_align_block_size(..., ragged=True))"
        )
    if not assume_sorted:
        order = jnp.argsort(expert_ids, stable=True)
        expert_ids = expert_ids[order]
        if ragged:
            valid_rows = valid_rows[order]
        a_sorted = a_sorted.reshape(n_blocks, bm, k_dim)[order].reshape(
            t_pad, k_dim
        )
        g_sorted = g_sorted.reshape(n_blocks, bm, n_dim)[order].reshape(
            t_pad, n_dim
        )
    out = resilience.guarded_call(
        "group_gemm_dw",
        functools.partial(_group_gemm_dw_fused, cfg=cfg, interpret=interpret),
        _group_gemm_dw_xla,
        a_sorted, g_sorted, expert_ids, n_exp, valid_rows=valid_rows,
        ragged=ragged, bm=bm,
    )
    # an expert with zero rows never has its output block visited — that
    # memory is undefined, not zero; mask it (where, not multiply: the
    # garbage may be NaN)
    counts = jnp.bincount(
        jnp.clip(expert_ids, 0, n_exp - 1), length=n_exp
    )
    return jnp.where(counts[:, None, None] > 0, out, 0.0)
