"""AG-GroupGEMM — MoE TP forward: allgather tokens + grouped expert GEMM
(≙ reference ``kernels/nvidia/allgather_group_gemm.py``, 499 LoC).

Reference pipeline: cp-engine allgather of tokens into symmetric workspace,
C++ ``moe_ag_scatter_align_block_size`` sorts the gathered token→expert
assignments so each tile is single-expert, and a consumer grouped GEMM
waits per-tile on the source rank's flag (SURVEY.md §2.3).

TPU-native composition (two entries):

- :func:`ag_group_gemm` — sequential: ring-AG kernel, XLA alignment gather,
  scalar-prefetch grouped GEMM. The A/B baseline.
- :func:`ag_group_gemm_overlap` — SORT-BEFORE-RING single kernel: each rank
  pre-sorts its OWN tokens into block-aligned expert order with one fused
  XLA gather (routing ids are allgathered first — tiny payload, same move
  the reference makes at allgather_group_gemm.py:272-330), then the ring
  ships already-aligned slabs which the grouped GEMM consumes with one
  bulk DMA per double-buffered group the moment each chunk lands. Compute
  order = ring arrival order — the reference's per-source-segment tile
  swizzle with flag waits (allgather_group_gemm.py:420-470) becomes the
  schedule itself, as in ``_ag_gemm_kernel``.

  Why sort-before-ring (a real-chip finding): Mosaic has no legal
  row-granular dynamic gather — 1-row DMA slices violate sublane tiling,
  and ``tpu.dynamic_gather`` cannot cross vregs — and a per-row DMA loop
  is descriptor-bound on the scalar core anyway. Shipping pre-sorted slabs
  costs ~topk× ICI payload (token rows duplicate per assignment, exactly
  as EP dispatch duplicates them over the network) but the ring rides
  under the grouped GEMM, whose arithmetic intensity dwarfs it.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.ops.group_gemm import (
    GroupGemmConfig,
    _panel_for,
    group_gemm,
)
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    RankedAlignment,
    gather_sorted_rows,
    moe_align_block_size,
    moe_align_ranked,
)
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def ag_group_gemm(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    ag_method: str = "auto",
    gather_output: bool = False,
    interpret: Any = None,
):
    """Sequential MoE up-projection (call inside ``jax.shard_map``;
    ≙ ``ag_group_gemm``, reference allgather_group_gemm.py:272).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]`` expert weights,
    N-sharded (TP); topk_ids: ``[m_loc, topk]`` routing of the local tokens.
    Returns ``(h_sorted [t_pad, n_loc], alignment)`` — the grouped-GEMM
    output in block-aligned expert order over the *gathered* tokens, plus
    the alignment to unsort it (the reference likewise returns scatter
    order for the follow-up reduce). ``gather_output=True`` additionally
    returns the SORTED gathered rows ``a_sorted [t_pad, K]`` (free — the
    GEMM's own input; the training backward consumes exactly this, same
    contract as :func:`ag_group_gemm_overlap`).
    """
    cfg = config or GroupGemmConfig()
    n_exp = b.shape[0]
    topk = topk_ids.shape[1]
    a_full = all_gather(a, axis=axis, method=ag_method, interpret=interpret)
    ids_full = jax.lax.all_gather(topk_ids, axis, tiled=True)  # [m_tot, topk]
    alignment = moe_align_block_size(
        ids_full.reshape(-1), n_exp, cfg.block_m, ragged=cfg.ragged
    )
    a_sorted = gather_sorted_rows(a_full, alignment, topk)
    h_sorted = group_gemm(
        a_sorted, b, alignment.expert_ids, valid_rows=alignment.valid_rows,
        config=cfg, interpret=interpret,
    )
    if gather_output:
        return h_sorted, alignment, a_sorted
    return h_sorted, alignment


def gather_group_blocks_for(
    nb: int, bm: int, k_dim: int, itemsize: int, budget: int = 16 * 2**20
) -> int:
    """Gather-group size for the overlapped kernel: the double-buffered
    resident rows (2 × bpg × bm × K) must stay inside `budget` regardless
    of t_pad_loc, so the kernel is VMEM-bounded for ANY shape (the n=1
    bench shape would otherwise need ~142 MiB resident)."""
    return max(1, min(nb, budget // (2 * bm * k_dim * itemsize)))


def _ragged_block_emit(
    a_rows, b_tile, out_stage, oslot_base, v, bm, bn, panel, out_dtype,
):
    """Ragged compute+stage for one row block of an overlapped kernel
    (ISSUE 5): MXU dots run only for the block's live ``panel``-row panels
    (``pl.when``-guarded), the tail panel's dead rows are zero-masked, and
    dead panels stage exact zeros — so the out buffer is fully defined and
    a downstream 0-weight combine can never meet NaN junk. ``a_rows`` maps
    a panel's row span to its A rows; ``oslot_base`` is the block's first
    staged row."""
    for p in range(bm // panel):
        live = p * panel < v

        @pl.when(live)
        def _(p=p):
            yp = jnp.dot(
                a_rows(p * panel, panel), b_tile,
                preferred_element_type=jnp.float32,
            )
            rows = (
                jax.lax.broadcasted_iota(jnp.int32, (panel, bn), 0)
                + p * panel
            )
            out_stage[pl.ds(oslot_base + p * panel, panel), :] = jnp.where(
                rows < v, yp, 0.0
            ).astype(out_dtype)

        @pl.when(jnp.logical_not(live))
        def _(p=p):
            out_stage[pl.ds(oslot_base + p * panel, panel), :] = jnp.zeros(
                (panel, bn), out_dtype
            )


def _ag_group_gemm_overlap_kernel(
    eid_ref, a_ref, b_ref,
    out_ref, ag_ref,
    a_all, b_buf, out_stage,
    copy_sem, send_sems, recv_sems, gsems, bsem, outsem,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, bpg: int, bm: int,
    out_dtype, vid_ref=None, panel: int = 0,
):
    """Fused ring-AG + grouped GEMM over PRE-SORTED slabs: the ring
    delivers each rank's block-aligned [t_pad_loc, K] slab; arriving chunks
    are streamed into VMEM in double-buffered groups of ``bpg`` blocks (one
    bulk aligned DMA per group — no per-row traffic) and consumed by a
    jn-outer / block-inner MXU loop that re-fetches an expert's weight slab
    only when the expert changes (the consecutive-block reuse the grid-based
    ``group_gemm`` gets from Pallas's index-map equality).

    ``vid_ref`` (ragged mode, ISSUE 5 — fed by the
    ``_ag_group_gemm_overlap_ragged_kernel`` entry) carries the per-(rank,
    block) live-row map: each block's dot runs as ``pl.when``-guarded
    ``panel``-row panels so alignment pad rows cost no MXU time, and dead
    rows stage exact zeros. ``vid_ref=None`` (the legacy entry) traces the
    original schedule unchanged — ring, DMA, and semaphore structure are
    identical in both modes (ragged adds NO signal edges)."""
    me = shmem.my_pe(axis)
    t_pad_loc = nb * bm
    it_counter = [0]  # trace-time global (block, jn) iteration count

    # n >= 2 always: the host entry dispatches world-1 to the grid
    # group_gemm before building this kernel
    local = pltpu.make_async_copy(
        a_ref, ag_ref.at[pl.ds(me * t_pad_loc, t_pad_loc)], copy_sem
    )
    local.start()
    local.wait()
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    # Weight-slab prefetch chain (VERDICT r5 `moe` gap): the FIRST slab of
    # every gather group used to be fetched in the group preamble and
    # waited immediately — a full [K, bn] HBM stall per group/step
    # boundary. Now the double-buffer slot carries across groups AND ring
    # steps, and each boundary's first slab is prefetched from inside the
    # previous group's compute loop (the `_iter` boundary arm below) — so
    # a step boundary's weight fetch also rides under the ring-chunk wait.
    # Only the very first slab of the whole schedule is fetched here.
    pltpu.make_async_copy(
        b_ref.at[eid_ref[me, 0], :, pl.ds(0, bn)], b_buf.at[0], bsem.at[0]
    ).start()
    slot_carry = [jnp.int32(1)]  # traced carry: _iter's weight buffer slot

    descs = []
    for s in range(n):
        c = jax.lax.rem(me - s + 2 * n, n)
        if s > 0:
            descs[s - 1].wait_recv()  # chunk c landed during step s-1
        sl = pl.ds(c * t_pad_loc, t_pad_loc)
        if s < n - 1:
            # forward chunk c before computing on it: ICI overlaps MXU
            descs.append(
                shmem.putmem_nbi_block(
                    ag_ref.at[sl], ag_ref.at[sl], right, axis,
                    send_sems.at[s], recv_sems.at[s],
                )
            )

        n_groups = (nb + bpg - 1) // bpg

        def _group_desc(g, slot, c=c):
            base = g * bpg * bm
            cnt = min(bpg * bm, t_pad_loc - base)
            return pltpu.make_async_copy(
                ag_ref.at[pl.ds(c * t_pad_loc + base, cnt), :],
                a_all.at[slot, pl.ds(0, cnt), :],
                gsems.at[slot],
            )

        _group_desc(0, 0).start()
        for g in range(n_groups):          # python: group sizes are static
            gslot = g % 2
            if g + 1 < n_groups:
                _group_desc(g + 1, 1 - gslot).start()
            _group_desc(g, gslot).wait()
            nb_g = min(bpg, nb - g * bpg)  # blocks in this group

            # first slab of the NEXT group/step: prefetched by this group's
            # last iteration (the `_iter` boundary arm), so the boundary
            # never stalls on a cold weight fetch. None = end of schedule.
            if g + 1 < n_groups:
                e_next = eid_ref[c, (g + 1) * bpg]
            elif s + 1 < n:
                c_next = jax.lax.rem(me - (s + 1) + 2 * n, n)
                e_next = eid_ref[c_next, 0]
            else:
                e_next = None
            it_base = it_counter[0]

            def _iter(i, slot, g=g, gslot=gslot, nb_g=nb_g, it_base=it_base,
                      e_next=e_next):
                jn = i // nb_g
                b_rel = jax.lax.rem(i, nb_g)
                b = g * bpg + b_rel
                e = eid_ref[c, b]
                prev_rel = jax.lax.rem(jax.lax.max(i - 1, 0), nb_g)
                fresh = jnp.logical_or(
                    i == 0,
                    jnp.logical_or(
                        jn != jax.lax.max(i - 1, 0) // nb_g,
                        e != eid_ref[c, g * bpg + prev_rel],
                    ),
                )
                slot = jnp.where(fresh, 1 - slot, slot)

                # DMA semaphores are waited through a descriptor of matching
                # byte count (both Mosaic and the interpreter count bytes)
                @pl.when(fresh)
                def _():
                    pltpu.make_async_copy(
                        b_ref.at[e, :, pl.ds(jn * bn, bn)],
                        b_buf.at[slot],
                        bsem.at[slot],
                    ).wait()

                # prefetch the NEXT distinct weight slab while this dot runs
                nxt = i + 1
                jn2 = nxt // nb_g
                b2 = jax.lax.rem(nxt, nb_g)
                e2 = eid_ref[c, g * bpg + jax.lax.min(b2, nb_g - 1)]
                fresh2 = jnp.logical_and(
                    nxt < nb_g * n_jn,
                    jnp.logical_or(jn2 != jn, e2 != e),
                )
                jn2v = jn2
                if e_next is not None:
                    # boundary arm: the loop's last iteration prefetches the
                    # next group's/step's first slab into the buffer the
                    # boundary's i=0 `fresh` wait will target (slot carries
                    # across loops, so 1-slot here IS that buffer)
                    boundary = nxt >= nb_g * n_jn
                    e2 = jnp.where(boundary, e_next, e2)
                    jn2v = jnp.where(boundary, 0, jn2)
                    fresh2 = jnp.logical_or(fresh2, boundary)

                @pl.when(fresh2)
                def _():
                    pltpu.make_async_copy(
                        b_ref.at[e2, :, pl.ds(jn2v * bn, bn)],
                        b_buf.at[1 - slot],
                        bsem.at[1 - slot],
                    ).start()

                if vid_ref is None:
                    y = jnp.dot(
                        a_all[gslot, pl.ds(b_rel * bm, bm), :],
                        b_buf[slot],
                        preferred_element_type=jnp.float32,
                    )
                # out_stage slots alternate on the GLOBAL iteration count
                # (group iteration counts may be odd); a slot's first-ever
                # use has no pending store to wait for
                gi = it_base + i
                oslot = jax.lax.rem(gi, 2)

                @pl.when(gi >= 2)
                def _():
                    pltpu.make_async_copy(
                        out_stage.at[pl.ds(oslot * bm, bm), :],
                        out_ref.at[
                            pl.ds(c * t_pad_loc + b * bm, bm), pl.ds(jn * bn, bn)
                        ],
                        outsem.at[oslot],
                    ).wait()

                if vid_ref is None:
                    out_stage[pl.ds(oslot * bm, bm), :] = y.astype(out_dtype)
                else:
                    # ragged (ISSUE 5): panel-guarded dots write the staged
                    # tile directly — dead panels stage zeros, so they ride
                    # AFTER the slot-reuse wait like the legacy store
                    _ragged_block_emit(
                        lambda off, rows: a_all[
                            gslot, pl.ds(b_rel * bm + off, rows), :
                        ],
                        b_buf[slot], out_stage, oslot * bm, vid_ref[c, b],
                        bm, bn, panel, out_dtype,
                    )
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(oslot * bm, bm), :],
                    out_ref.at[
                        pl.ds(c * t_pad_loc + b * bm, bm), pl.ds(jn * bn, bn)
                    ],
                    outsem.at[oslot],
                ).start()
                return slot

            slot_carry[0] = jax.lax.fori_loop(
                0, nb_g * n_jn, _iter, slot_carry[0]
            )
            it_counter[0] += nb_g * n_jn
    # Drain the final pending output store per used slot, then wait local
    # send completion of the ring puts.
    total_iters = n * nb * n_jn

    def _drain(oslot):
        pltpu.make_async_copy(
            out_stage.at[pl.ds(oslot * bm, bm), :],
            out_ref.at[pl.ds(0, bm), pl.ds(0, bn)],
            outsem.at[oslot],
        ).wait()

    if total_iters >= 1:
        _drain((total_iters - 1) % 2)
    if total_iters >= 2:
        _drain(total_iters % 2)
    shmem.quiet(*descs)


def _ag_group_gemm_overlap_chunked_kernel(
    eid_ref, a_ref, b_ref,
    out_ref, ag_ref,
    a_all, b_buf, out_stage,
    copy_sem, send_sems, recv_sems, sig_sems, gsems, bsem, outsem,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, bpg: int, bm: int,
    out_dtype, spans, vid_ref=None, panel: int = 0,
):
    """Chunk-granular fused ring-AG + grouped GEMM (ISSUE 4 tentpole): the
    schedule of :func:`_ag_group_gemm_overlap_kernel` with each ring-step
    shard split into the ``spans`` (quantized to the gather-group size, so
    every chunk holds whole groups). Step ``s`` waits chunk ``j`` of the
    previous step, forwards it to the right neighbor immediately, and
    starts group-GEMM work on ITS expert rows while chunk ``j+1`` is still
    crossing the ICI — the group-GEMM no longer stalls until the full peer
    shard arrives, which is the dispatch→GEMM leg of the three-stage MoE
    pipeline (dispatch of chunk j+1, GEMM of chunk j, combine of j−1
    concurrently in flight). The only schedule difference vs legacy is
    that a gather-group DMA is never prefetched across a chunk boundary
    (its rows may not have landed); the weight-slab prefetch chain is
    chunk-independent (weights are local) and carries across chunk, group
    AND step boundaries exactly as in the legacy kernel. ``chunks=1``
    dispatches to the unchanged legacy kernel."""
    me = shmem.my_pe(axis)
    t_pad_loc = nb * bm
    gq = bpg * bm                       # group quantum: spans align to it
    n_groups = (nb + bpg - 1) // bpg
    it_counter = [0]

    local = pltpu.make_async_copy(
        a_ref, ag_ref.at[pl.ds(me * t_pad_loc, t_pad_loc)], copy_sem
    )
    local.start()
    local.wait()
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)

    pltpu.make_async_copy(
        b_ref.at[eid_ref[me, 0], :, pl.ds(0, bn)], b_buf.at[0], bsem.at[0]
    ).start()
    slot_carry = [jnp.int32(1)]  # traced carry: _iter's weight buffer slot

    descs = []
    for s in range(n):
        c = jax.lax.rem(me - s + 2 * n, n)

        def _group_desc(g, slot, c=c):
            base = g * bpg * bm
            cnt = min(bpg * bm, t_pad_loc - base)
            return pltpu.make_async_copy(
                ag_ref.at[pl.ds(c * t_pad_loc + base, cnt), :],
                a_all.at[slot, pl.ds(0, cnt), :],
                gsems.at[slot],
            )

        chunk_handles = []
        for j, (off, rows) in enumerate(spans):
            if s > 0:
                descs[s - 1].wait_recv_chunk(j)  # landed during step s-1
            if s < n - 1:
                # forward chunk j before computing on it (wormhole
                # pipelining across hops, as _ring_1d_chunked_kernel)
                sl = pl.ds(c * t_pad_loc + off, rows)
                chunk_handles.append(
                    shmem.putmem_signal2_nbi_block(
                        ag_ref.at[sl], ag_ref.at[sl], right, axis,
                        send_sems.at[s, j], recv_sems.at[s, j],
                        sig_sems.at[s, j],
                    )
                )
            g_lo = off // gq
            g_hi = n_groups if j == len(spans) - 1 else (off + rows) // gq
            _group_desc(g_lo, g_lo % 2).start()
            for g in range(g_lo, g_hi):  # python: group sizes are static
                gslot = g % 2
                if g + 1 < g_hi:
                    # within-chunk prefetch only: a cross-chunk group's
                    # rows are not guaranteed landed yet
                    _group_desc(g + 1, 1 - gslot).start()
                _group_desc(g, gslot).wait()
                nb_g = min(bpg, nb - g * bpg)

                # boundary weight prefetch target (chunk-independent — the
                # weight bank is local HBM), exactly as legacy
                if g + 1 < n_groups:
                    e_next = eid_ref[c, (g + 1) * bpg]
                elif s + 1 < n:
                    c_next = jax.lax.rem(me - (s + 1) + 2 * n, n)
                    e_next = eid_ref[c_next, 0]
                else:
                    e_next = None
                it_base = it_counter[0]

                def _iter(i, slot, g=g, gslot=gslot, nb_g=nb_g,
                          it_base=it_base, e_next=e_next, c=c):
                    jn = i // nb_g
                    b_rel = jax.lax.rem(i, nb_g)
                    b = g * bpg + b_rel
                    e = eid_ref[c, b]
                    prev_rel = jax.lax.rem(jax.lax.max(i - 1, 0), nb_g)
                    fresh = jnp.logical_or(
                        i == 0,
                        jnp.logical_or(
                            jn != jax.lax.max(i - 1, 0) // nb_g,
                            e != eid_ref[c, g * bpg + prev_rel],
                        ),
                    )
                    slot = jnp.where(fresh, 1 - slot, slot)

                    @pl.when(fresh)
                    def _():
                        pltpu.make_async_copy(
                            b_ref.at[e, :, pl.ds(jn * bn, bn)],
                            b_buf.at[slot],
                            bsem.at[slot],
                        ).wait()

                    # prefetch the NEXT distinct weight slab while this
                    # dot runs (carries across chunk/group/step bounds)
                    nxt = i + 1
                    jn2 = nxt // nb_g
                    b2 = jax.lax.rem(nxt, nb_g)
                    e2 = eid_ref[c, g * bpg + jax.lax.min(b2, nb_g - 1)]
                    fresh2 = jnp.logical_and(
                        nxt < nb_g * n_jn,
                        jnp.logical_or(jn2 != jn, e2 != e),
                    )
                    jn2v = jn2
                    if e_next is not None:
                        boundary = nxt >= nb_g * n_jn
                        e2 = jnp.where(boundary, e_next, e2)
                        jn2v = jnp.where(boundary, 0, jn2)
                        fresh2 = jnp.logical_or(fresh2, boundary)

                    @pl.when(fresh2)
                    def _():
                        pltpu.make_async_copy(
                            b_ref.at[e2, :, pl.ds(jn2v * bn, bn)],
                            b_buf.at[1 - slot],
                            bsem.at[1 - slot],
                        ).start()

                    if vid_ref is None:
                        y = jnp.dot(
                            a_all[gslot, pl.ds(b_rel * bm, bm), :],
                            b_buf[slot],
                            preferred_element_type=jnp.float32,
                        )
                    gi = it_base + i
                    oslot = jax.lax.rem(gi, 2)

                    @pl.when(gi >= 2)
                    def _():
                        pltpu.make_async_copy(
                            out_stage.at[pl.ds(oslot * bm, bm), :],
                            out_ref.at[
                                pl.ds(c * t_pad_loc + b * bm, bm),
                                pl.ds(jn * bn, bn),
                            ],
                            outsem.at[oslot],
                        ).wait()

                    if vid_ref is None:
                        out_stage[pl.ds(oslot * bm, bm), :] = y.astype(
                            out_dtype
                        )
                    else:
                        # ragged × chunked (ISSUE 5): identical panel rule;
                        # the chunk schedule is row-layout-driven and never
                        # consults valid_rows, so ragged adds no signal
                        # edges to the chunk protocol
                        _ragged_block_emit(
                            lambda off, rows: a_all[
                                gslot, pl.ds(b_rel * bm + off, rows), :
                            ],
                            b_buf[slot], out_stage, oslot * bm,
                            vid_ref[c, b], bm, bn, panel, out_dtype,
                        )
                    pltpu.make_async_copy(
                        out_stage.at[pl.ds(oslot * bm, bm), :],
                        out_ref.at[
                            pl.ds(c * t_pad_loc + b * bm, bm),
                            pl.ds(jn * bn, bn),
                        ],
                        outsem.at[oslot],
                    ).start()
                    return slot

                slot_carry[0] = jax.lax.fori_loop(
                    0, nb_g * n_jn, _iter, slot_carry[0]
                )
                it_counter[0] += nb_g * n_jn
        if s < n - 1:
            descs.append(shmem.ChunkedPutHandle(chunk_handles))

    total_iters = n * nb * n_jn

    def _drain(oslot):
        pltpu.make_async_copy(
            out_stage.at[pl.ds(oslot * bm, bm), :],
            out_ref.at[pl.ds(0, bm), pl.ds(0, bn)],
            outsem.at[oslot],
        ).wait()

    if total_iters >= 1:
        _drain((total_iters - 1) % 2)
    if total_iters >= 2:
        _drain(total_iters % 2)
    shmem.quiet(*descs)


def _ag_group_gemm_overlap_ragged_kernel(
    eid_ref, vid_ref, a_ref, b_ref,
    out_ref, ag_ref,
    a_all, b_buf, out_stage,
    copy_sem, send_sems, recv_sems, gsems, bsem, outsem,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, bpg: int, bm: int,
    out_dtype, panel: int,
):
    """Ragged entry (ISSUE 5): the legacy schedule with the per-(rank,
    block) live-row map as a second SMEM operand — see the base kernel's
    docstring. Same ring/DMA/semaphore structure; only the MXU work and
    the staged values differ."""
    _ag_group_gemm_overlap_kernel(
        eid_ref, a_ref, b_ref, out_ref, ag_ref, a_all, b_buf, out_stage,
        copy_sem, send_sems, recv_sems, gsems, bsem, outsem,
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, bpg=bpg, bm=bm,
        out_dtype=out_dtype, vid_ref=vid_ref, panel=panel,
    )


def _ag_group_gemm_overlap_chunked_ragged_kernel(
    eid_ref, vid_ref, a_ref, b_ref,
    out_ref, ag_ref,
    a_all, b_buf, out_stage,
    copy_sem, send_sems, recv_sems, sig_sems, gsems, bsem, outsem,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, bpg: int, bm: int,
    out_dtype, spans, panel: int,
):
    """Ragged × chunked entry (ISSUE 5 × ISSUE 4): chunk schedule and
    signal protocol identical to the chunked kernel; blocks consume the
    live-row map as above."""
    _ag_group_gemm_overlap_chunked_kernel(
        eid_ref, a_ref, b_ref, out_ref, ag_ref, a_all, b_buf, out_stage,
        copy_sem, send_sems, recv_sems, sig_sems, gsems, bsem, outsem,
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, bpg=bpg, bm=bm,
        out_dtype=out_dtype, spans=spans, vid_ref=vid_ref, panel=panel,
    )


def presort_local_rows(a: jax.Array, ral: RankedAlignment, axis: str) -> jax.Array:
    """This rank's block-aligned slab ``[t_pad_loc, K]``: one fused XLA
    gather (HBM-bandwidth pass). Sentinel rows clamp to row 0 of the own
    chunk — junk values, masked by zero combine weights downstream."""
    me = jax.lax.axis_index(axis)
    m_loc = a.shape[0]
    rows_loc = jax.lax.dynamic_index_in_dim(
        ral.src_rows, me, axis=0, keepdims=False
    ) - me * m_loc
    return jnp.take(a, rows_loc, axis=0)


def ag_group_gemm_overlap(
    a: jax.Array,
    b: jax.Array,
    ral: RankedAlignment,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    gather_output: bool = False,
    out_dtype: Any = None,
    gather_group_blocks: int | None = None,
    interpret: Any = None,
):
    """Single-kernel overlapped MoE up-projection (call inside shard_map;
    ≙ the reference's fused producer/consumer ``ag_group_gemm``,
    allgather_group_gemm.py:272,420-470 — there: cp-engine AG + consumer
    GEMM spinning on per-source flags; here: sort-before-ring, see module
    docstring).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]``; `ral` from
    :func:`~triton_dist_tpu.ops.moe_utils.moe_align_ranked` over the
    allgathered routing ids. Returns ``[n*t_pad_loc, n_loc]`` rows in
    rank-major aligned order (+ the SORTED gathered rows
    ``[n*t_pad_loc, K]`` when `gather_output` — the backward's input).

    World-1 degenerates to the scalar-prefetch grid ``group_gemm`` over the
    pre-sorted slab: with no ring to hide, Mosaic's automatic grid
    pipelining is the best schedule (≙ the world-1 XLA-dot sentinels of
    ``ag_gemm``/``gemm_rs``)."""
    cfg = config or GroupGemmConfig()
    out_dtype = out_dtype or a.dtype
    n = _axis_size((axis))
    m_loc, k_dim = a.shape
    n_loc = b.shape[2]
    nb = ral.blocks_per_rank
    bm = ral.block_m
    t_pad_loc = ral.t_pad_loc
    assert bm == cfg.block_m, (bm, cfg.block_m)
    ragged = bool(cfg.ragged) and cfg.backend == "pallas"
    if ragged and ral.valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs a ragged RankedAlignment — build "
            "it with moe_align_ranked(..., ragged=True)"
        )
    if cfg.backend != "pallas" and n > 1:
        raise ValueError(
            "the ragged_dot sentinel backend needs globally expert-sorted "
            "blocks; route it through the sequential composition "
            "(tp_moe_mlp does this automatically)"
        )

    a_srt = presort_local_rows(a, ral, axis)

    if n == 1:
        h = group_gemm(
            a_srt, b, ral.expert_ids[0],
            valid_rows=None if ral.valid_rows is None else ral.valid_rows[0],
            config=cfg, out_dtype=out_dtype, interpret=interpret,
        )
        return (h, a_srt) if gather_output else h

    bn = pick_block(n_loc, cfg.block_n)
    n_jn = n_loc // bn
    itemsize = jnp.dtype(a.dtype).itemsize
    bpg = gather_group_blocks or gather_group_blocks_for(nb, bm, k_dim, itemsize)
    vmem_bytes = (
        2 * bpg * bm * k_dim * itemsize       # double-buffered gather groups
        + 2 * k_dim * bn * itemsize           # double-buffered weight slabs
        + 2 * 2 * bm * bn * jnp.dtype(out_dtype).itemsize
        + 4 * 2**20
    )
    from triton_dist_tpu.ops.common import chunk_schedule

    # chunk-granular ring (ISSUE 4): spans quantized to the gather-group
    # size so every chunk holds whole groups (the unit the compute loop
    # consumes); a schedule that collapses to one span — including every
    # chunks_per_shard=1 config — dispatches to the UNCHANGED legacy
    # kernel, bit for bit
    spans = chunk_schedule(
        t_pad_loc, max(1, int(getattr(cfg, "chunks_per_shard", 1))),
        quantum=bpg * bm,
    )
    ragged_kw = {"panel": _panel_for(bm)} if ragged else {}
    if len(spans) > 1:
        kernel = functools.partial(
            _ag_group_gemm_overlap_chunked_ragged_kernel if ragged
            else _ag_group_gemm_overlap_chunked_kernel,
            axis=axis, n=n, nb=nb,
            n_jn=n_jn, bn=bn, bpg=bpg, bm=bm, out_dtype=out_dtype,
            spans=spans, **ragged_kw,
        )
        ring_scratch = [
            pltpu.SemaphoreType.DMA((max(n - 1, 1), len(spans))),
            pltpu.SemaphoreType.DMA((max(n - 1, 1), len(spans))),
            # pure chunk-signal slots (REGULAR; armed watchdog only)
            pltpu.SemaphoreType.REGULAR((max(n - 1, 1), len(spans))),
        ]
    else:
        kernel = functools.partial(
            _ag_group_gemm_overlap_ragged_kernel if ragged
            else _ag_group_gemm_overlap_kernel,
            axis=axis, n=n, nb=nb,
            n_jn=n_jn, bn=bn, bpg=bpg, bm=bm, out_dtype=out_dtype,
            **ragged_kw,
        )
        ring_scratch = [
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # expert ids [n, nb]
        # HBM pinned (not ANY): chunk slices at traced-but-aligned
        # offsets must DMA from untiled HBM, not from VMEM the
        # compiler might pick for small inputs
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # a_srt
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # b
    ]
    args = [ral.expert_ids, a_srt, b]
    if ragged:
        # the per-(rank, block) live-row map rides SMEM next to the ids
        in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(1, ral.valid_rows.astype(jnp.int32))
    out, ag = dist_pallas_call(
        kernel,
        name="ag_group_gemm_overlap",
        out_shape=(
            jax.ShapeDtypeStruct((n * t_pad_loc, n_loc), out_dtype),
            jax.ShapeDtypeStruct((n * t_pad_loc, k_dim), a.dtype),
        ),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bpg * bm, k_dim), a.dtype),
            pltpu.VMEM((2, k_dim, bn), b.dtype),
            pltpu.VMEM((2 * bm, bn), out_dtype),
            pltpu.SemaphoreType.DMA(()),
            *ring_scratch,
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n * t_pad_loc * k_dim * n_loc,
            bytes_accessed=(
                n * t_pad_loc * k_dim + b.shape[0] * k_dim * n_loc
                + n * t_pad_loc * n_loc
            ) * itemsize,
            transcendentals=0,
        ),
        vmem_limit_bytes=min(vmem_bytes, 100 * 2**20),
        uses_barrier=True,
        interpret=interpret,
    )(*args)
    return (out, ag) if gather_output else out


def ag_group_gemm_op(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: returns the dense per-assignment output
    ``[m_tot * topk, n_loc-sharded N]`` in original token order (sentinel
    rows dropped), for golden comparison and simple use."""
    cfg = config or GroupGemmConfig()
    topk = topk_ids.shape[1]
    m_tot = a.shape[0]

    def fn(a, b, ids):
        h_sorted, alignment = ag_group_gemm(
            a, b, ids, axis=axis, config=cfg, interpret=interpret
        )
        # unsort to assignment order [m_tot*topk, n_loc]
        t = m_tot * topk
        # scatter row index by assignment id; sentinel rows (id == t) drop
        inv = jnp.zeros((t,), jnp.int32).at[alignment.sorted_token_ids].set(
            jnp.arange(alignment.sorted_token_ids.shape[0], dtype=jnp.int32),
            mode="drop",
        )
        return h_sorted[inv]

    return jit_shard_map(
        fn, mesh,
        (P(axis, None), P(None, None, axis), P(axis, None)),
        P(None, axis),
        key=("ag_group_gemm", axis, cfg, m_tot, topk, str(interpret)),
    )(a, b, topk_ids.astype(jnp.int32))


# Grouped-GEMM tile sweep (≙ the reference autotuning its MoE kernels,
# allgather_group_gemm.py:130-180 config lists). block_m is also the
# alignment block, so the sweep may change padding, not just tiling.
# FIRST entry = best-known default (applied sweep-free under
# cached_or_first). Ragged twins (ISSUE 5) sit strictly AFTER their padded
# originals — the no-regression ordering invariant: sweep-free walks can
# never apply a ragged schedule untimed.
AG_GROUP_GEMM_TUNE_SPACE = (
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 1024, 1024),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(256, 1024, 512),
    GroupGemmConfig(128, 1024, 512, ragged=True),
    GroupGemmConfig(256, 1024, 512, ragged=True),
)

ag_group_gemm_op = contextual_autotune(
    AG_GROUP_GEMM_TUNE_SPACE, name="ag_group_gemm"
)(ag_group_gemm_op)
