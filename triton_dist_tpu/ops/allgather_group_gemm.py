"""AG-GroupGEMM — MoE TP forward: allgather tokens + grouped expert GEMM
(≙ reference ``kernels/nvidia/allgather_group_gemm.py``, 499 LoC).

Reference pipeline: cp-engine allgather of tokens into symmetric workspace,
C++ ``moe_ag_scatter_align_block_size`` sorts the gathered token→expert
assignments so each tile is single-expert, and a consumer grouped GEMM
waits per-tile on the source rank's flag (SURVEY.md §2.3).

TPU-native composition: the fused ring allgather kernel moves tokens over
ICI, routing ids are allgathered with an XLA collective (tiny payload), the
jnp alignment (moe_utils) replaces the CUDA sort kernel, and the
scalar-prefetch grouped GEMM (group_gemm) replaces the flag-waiting
consumer — XLA chains the kernels back-to-back on the same core, which is
the TPU analogue of the reference's stream-ordered producer/consumer.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.ops.group_gemm import GroupGemmConfig, group_gemm
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    RankedAlignment,
    gather_sorted_rows,
    moe_align_block_size,
    moe_align_ranked,
)
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import pick_block


def ag_group_gemm(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    ag_method: str = "auto",
    gather_output: bool = False,
    interpret: Any = None,
):
    """Overlapped MoE up-projection (call inside ``jax.shard_map``;
    ≙ ``ag_group_gemm``, reference allgather_group_gemm.py:272).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]`` expert weights,
    N-sharded (TP); topk_ids: ``[m_loc, topk]`` routing of the local tokens.
    Returns ``(h_sorted [t_pad, n_loc], alignment)`` — the grouped-GEMM
    output in block-aligned expert order over the *gathered* tokens, plus
    the alignment to unsort it (the reference likewise returns scatter
    order for the follow-up reduce). ``gather_output=True`` additionally
    returns the gathered tokens ``a_full`` (free — the fwd workspace; the
    training backward wants it, same contract as ``ag_gemm``).
    """
    cfg = config or GroupGemmConfig()
    n_exp = b.shape[0]
    topk = topk_ids.shape[1]
    a_full = all_gather(a, axis=axis, method=ag_method, interpret=interpret)
    ids_full = jax.lax.all_gather(topk_ids, axis, tiled=True)  # [m_tot, topk]
    alignment = moe_align_block_size(
        ids_full.reshape(-1), n_exp, cfg.block_m
    )
    a_sorted = gather_sorted_rows(a_full, alignment, topk)
    h_sorted = group_gemm(
        a_sorted, b, alignment.expert_ids, config=cfg, interpret=interpret
    )
    if gather_output:
        return h_sorted, alignment, a_full
    return h_sorted, alignment


def gather_group_blocks_for(
    nb: int, bm: int, k_dim: int, itemsize: int, budget: int = 16 * 2**20
) -> int:
    """Gather-group size for the overlapped kernel: the double-buffered
    resident rows (2 × bpg × bm × K) must stay inside `budget` regardless
    of t_pad_loc, so the kernel is VMEM-bounded for ANY shape (the n=1
    bench shape would otherwise need ~142 MiB resident)."""
    return max(1, min(nb, budget // (2 * bm * k_dim * itemsize)))


def _ag_group_gemm_overlap_kernel(
    eid_ref, a_ref, b_ref, src_rows_ref,
    out_ref, ag_ref,
    a_all, b_buf, out_stage, ids_sm,
    copy_sem, send_sems, recv_sems, gsems, idsem, bsem, outsem,
    *, axis: str, n: int, nb: int, n_jn: int, bn: int, bpg: int, bm: int,
    out_dtype,
):
    """Fused ring-AG + grouped GEMM: each chunk's rows are row-DMA-gathered
    into VMEM in double-buffered groups the moment the ring delivers the
    chunk, and consumed by a jn-outer / block-inner MXU loop that
    re-fetches an expert's weight slab only when the expert changes (the
    consecutive-block reuse the grid-based ``group_gemm`` gets from
    Pallas's index-map equality). Compute order = ring arrival order — the
    reference's per-source-segment tile swizzle with flag waits
    (allgather_group_gemm.py:420-470) becomes the schedule itself, as in
    ``_ag_gemm_kernel``."""
    me = shmem.my_pe(axis)
    m_loc, k_dim = a_ref.shape
    t_pad_loc = nb * bm
    it_counter = [0]  # trace-time global (block, jn) iteration count

    local = pltpu.make_async_copy(
        a_ref, ag_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem
    )
    local.start()
    if n > 1:
        local.wait()
        shmem.barrier_all(axis)
    # world-1: row gathers read the input directly, so the ag workspace
    # copy (kept for the gather_output contract) runs concurrently with
    # compute instead of gating it
    gather_src = ag_ref if n > 1 else a_ref
    right = jax.lax.rem(me + 1, n)

    descs = []
    for s in range(n):
        c = jax.lax.rem(me - s + 2 * n, n)
        if s > 0:
            descs[s - 1].wait_recv()  # chunk c landed during step s-1
        sl = pl.ds(c * m_loc, m_loc)
        if s < n - 1:
            # forward chunk c before computing on it: ICI overlaps MXU
            descs.append(
                shmem.putmem_nbi_block(
                    ag_ref.at[sl], ag_ref.at[sl], right, axis,
                    send_sems.at[s], recv_sems.at[s],
                )
            )

        # chunk c's gather plan (global src rows) → SMEM; rows are then
        # gathered in double-buffered GROUPS of `bpg` blocks so VMEM stays
        # bounded for any t_pad_loc (group g+1's row DMAs fly while group
        # g's blocks run through the MXU). The whole (lane-padded) row is
        # copied: Mosaic requires lane-dim slices be 128-aligned, which
        # t_pad_loc alone need not be.
        ids_cp = pltpu.make_async_copy(
            src_rows_ref.at[c], ids_sm, idsem
        )
        ids_cp.start()
        ids_cp.wait()

        n_groups = (nb + bpg - 1) // bpg

        def _issue_group(g, slot):
            base = g * bpg * bm
            cnt = min(bpg * bm, t_pad_loc - base)

            def _row(r, _):
                src = ids_sm[base + r]
                pltpu.make_async_copy(
                    gather_src.at[pl.ds(src, 1), :],
                    a_all.at[slot, pl.ds(r, 1), :],
                    gsems.at[slot],
                ).start()
                return 0

            jax.lax.fori_loop(0, cnt, _row, 0)
            return cnt

        cnt0 = _issue_group(0, 0)
        group_rows = [cnt0]
        for g in range(n_groups):          # python: group sizes are static
            gslot = g % 2
            if g + 1 < n_groups:
                group_rows.append(_issue_group(g + 1, 1 - gslot))
            # wait the whole group's row copies (byte-counted: cnt rows of K)
            pltpu.make_async_copy(
                ag_ref.at[pl.ds(0, group_rows[g]), :],
                a_all.at[gslot, pl.ds(0, group_rows[g]), :],
                gsems.at[gslot],
            ).wait()
            nb_g = group_rows[g] // bm     # blocks in this group

            # first weight slab of this group
            e0 = eid_ref[c, g * bpg]
            pltpu.make_async_copy(
                b_ref.at[e0, :, pl.ds(0, bn)], b_buf.at[0], bsem.at[0]
            ).start()
            it_base = it_counter[0]

            def _iter(i, slot, g=g, gslot=gslot, nb_g=nb_g, it_base=it_base):
                jn = i // nb_g
                b_rel = jax.lax.rem(i, nb_g)
                b = g * bpg + b_rel
                e = eid_ref[c, b]
                prev_rel = jax.lax.rem(jax.lax.max(i - 1, 0), nb_g)
                fresh = jnp.logical_or(
                    i == 0,
                    jnp.logical_or(
                        jn != jax.lax.max(i - 1, 0) // nb_g,
                        e != eid_ref[c, g * bpg + prev_rel],
                    ),
                )
                slot = jnp.where(fresh, 1 - slot, slot)

                # DMA semaphores are waited through a descriptor of matching
                # byte count (both Mosaic and the interpreter count bytes)
                @pl.when(fresh)
                def _():
                    pltpu.make_async_copy(
                        b_ref.at[e, :, pl.ds(jn * bn, bn)],
                        b_buf.at[slot],
                        bsem.at[slot],
                    ).wait()

                # prefetch the NEXT distinct weight slab while this dot runs
                nxt = i + 1
                jn2 = nxt // nb_g
                b2 = jax.lax.rem(nxt, nb_g)
                e2 = eid_ref[c, g * bpg + jax.lax.min(b2, nb_g - 1)]
                fresh2 = jnp.logical_and(
                    nxt < nb_g * n_jn,
                    jnp.logical_or(jn2 != jn, e2 != e),
                )

                @pl.when(fresh2)
                def _():
                    pltpu.make_async_copy(
                        b_ref.at[e2, :, pl.ds(jn2 * bn, bn)],
                        b_buf.at[1 - slot],
                        bsem.at[1 - slot],
                    ).start()

                y = jnp.dot(
                    a_all[gslot, pl.ds(b_rel * bm, bm), :],
                    b_buf[slot],
                    preferred_element_type=jnp.float32,
                )
                # out_stage slots alternate on the GLOBAL iteration count
                # (group iteration counts may be odd); a slot's first-ever
                # use has no pending store to wait for
                gi = it_base + i
                oslot = jax.lax.rem(gi, 2)

                @pl.when(gi >= 2)
                def _():
                    pltpu.make_async_copy(
                        out_stage.at[pl.ds(oslot * bm, bm), :],
                        out_ref.at[
                            pl.ds(c * t_pad_loc + b * bm, bm), pl.ds(jn * bn, bn)
                        ],
                        outsem.at[oslot],
                    ).wait()

                out_stage[pl.ds(oslot * bm, bm), :] = y.astype(out_dtype)
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(oslot * bm, bm), :],
                    out_ref.at[
                        pl.ds(c * t_pad_loc + b * bm, bm), pl.ds(jn * bn, bn)
                    ],
                    outsem.at[oslot],
                ).start()
                return slot

            jax.lax.fori_loop(0, nb_g * n_jn, _iter, jnp.int32(1))
            it_counter[0] += nb_g * n_jn
    # Drain the final pending output store per used slot, then wait local
    # send completion of the ring puts.
    total_iters = n * nb * n_jn

    def _drain(oslot):
        pltpu.make_async_copy(
            out_stage.at[pl.ds(oslot * bm, bm), :],
            out_ref.at[pl.ds(0, bm), pl.ds(0, bn)],
            outsem.at[oslot],
        ).wait()

    if total_iters >= 1:
        _drain((total_iters - 1) % 2)
    if total_iters >= 2:
        _drain(total_iters % 2)
    if n == 1:
        local.wait()  # ag workspace copy ran concurrently with compute
    shmem.quiet(*descs)


def ag_group_gemm_overlap(
    a: jax.Array,
    b: jax.Array,
    ral: RankedAlignment,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    gather_output: bool = False,
    out_dtype: Any = None,
    gather_group_blocks: int | None = None,
    interpret: Any = None,
):
    """Single-kernel overlapped MoE up-projection (call inside shard_map;
    ≙ the reference's fused producer/consumer ``ag_group_gemm``,
    allgather_group_gemm.py:272,420-470 — there: cp-engine AG + consumer
    GEMM spinning on per-source flags; here: ring DMA + arrival-order
    grouped GEMM in one Pallas kernel).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]``; `ral` from
    :func:`~triton_dist_tpu.ops.moe_utils.moe_align_ranked` over the
    allgathered routing ids. Returns ``[n*t_pad_loc, n_loc]`` rows in
    rank-major aligned order (+ the gathered ``[n*m_loc, K]`` tokens when
    `gather_output`)."""
    cfg = config or GroupGemmConfig()
    out_dtype = out_dtype or a.dtype
    n = int(jax.lax.axis_size(axis))
    m_loc, k_dim = a.shape
    n_loc = b.shape[2]
    nb = ral.blocks_per_rank
    bm = ral.block_m
    t_pad_loc = ral.t_pad_loc
    assert bm == cfg.block_m, (bm, cfg.block_m)
    bn = pick_block(n_loc, cfg.block_n)
    n_jn = n_loc // bn
    itemsize = jnp.dtype(a.dtype).itemsize
    bpg = gather_group_blocks or gather_group_blocks_for(nb, bm, k_dim, itemsize)
    vmem_bytes = (
        2 * bpg * bm * k_dim * itemsize       # double-buffered gather groups
        + 2 * k_dim * bn * itemsize           # double-buffered weight slabs
        + 2 * 2 * bm * bn * jnp.dtype(out_dtype).itemsize
        + 4 * 2**20
    )
    # lane-pad the gather plan: the kernel copies whole [t_pad] rows to
    # SMEM and Mosaic rejects lane-dim slices not aligned to 128
    sr_pad = -(-t_pad_loc // 128) * 128
    src_rows = ral.src_rows
    if sr_pad != t_pad_loc:
        src_rows = jnp.pad(src_rows, ((0, 0), (0, sr_pad - t_pad_loc)))
    out, ag = dist_pallas_call(
        functools.partial(
            _ag_group_gemm_overlap_kernel, axis=axis, n=n, nb=nb,
            n_jn=n_jn, bn=bn, bpg=bpg, bm=bm, out_dtype=out_dtype,
        ),
        name="ag_group_gemm_overlap",
        out_shape=(
            jax.ShapeDtypeStruct((n * t_pad_loc, n_loc), out_dtype),
            jax.ShapeDtypeStruct((n * m_loc, k_dim), a.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # expert ids [n, nb]
            pl.BlockSpec(memory_space=pl.ANY),       # a
            pl.BlockSpec(memory_space=pl.ANY),       # b
            pl.BlockSpec(memory_space=pl.ANY),       # src rows [n, t_pad_loc]
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bpg * bm, k_dim), a.dtype),
            pltpu.VMEM((2, k_dim, bn), b.dtype),
            pltpu.VMEM((2 * bm, bn), out_dtype),
            pltpu.SMEM((sr_pad,), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n * t_pad_loc * k_dim * n_loc,
            bytes_accessed=(
                n * m_loc * k_dim + b.shape[0] * k_dim * n_loc
                + n * t_pad_loc * n_loc
            ) * itemsize,
            transcendentals=0,
        ),
        vmem_limit_bytes=min(vmem_bytes, 100 * 2**20),
        uses_barrier=n > 1,
        interpret=interpret,
    )(ral.expert_ids, a, b, src_rows)
    return (out, ag) if gather_output else out


def ag_group_gemm_op(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: returns the dense per-assignment output
    ``[m_tot * topk, n_loc-sharded N]`` in original token order (sentinel
    rows dropped), for golden comparison and simple use."""
    cfg = config or GroupGemmConfig()
    topk = topk_ids.shape[1]
    m_tot = a.shape[0]

    def fn(a, b, ids):
        h_sorted, alignment = ag_group_gemm(
            a, b, ids, axis=axis, config=cfg, interpret=interpret
        )
        # unsort to assignment order [m_tot*topk, n_loc]
        t = m_tot * topk
        # scatter row index by assignment id; sentinel rows (id == t) drop
        inv = jnp.zeros((t,), jnp.int32).at[alignment.sorted_token_ids].set(
            jnp.arange(alignment.sorted_token_ids.shape[0], dtype=jnp.int32),
            mode="drop",
        )
        return h_sorted[inv]

    return jit_shard_map(
        fn, mesh,
        (P(axis, None), P(None, None, axis), P(axis, None)),
        P(None, axis),
        key=("ag_group_gemm", axis, cfg, m_tot, topk, str(interpret)),
    )(a, b, topk_ids.astype(jnp.int32))


# Grouped-GEMM tile sweep (≙ the reference autotuning its MoE kernels,
# allgather_group_gemm.py:130-180 config lists). block_m is also the
# alignment block, so the sweep may change padding, not just tiling.
# FIRST entry = best-known default (applied sweep-free under
# cached_or_first).
AG_GROUP_GEMM_TUNE_SPACE = (
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 1024, 1024),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(256, 1024, 512),
)

ag_group_gemm_op = contextual_autotune(
    AG_GROUP_GEMM_TUNE_SPACE, name="ag_group_gemm"
)(ag_group_gemm_op)
