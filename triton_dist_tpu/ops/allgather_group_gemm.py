"""AG-GroupGEMM — MoE TP forward: allgather tokens + grouped expert GEMM
(≙ reference ``kernels/nvidia/allgather_group_gemm.py``, 499 LoC).

Reference pipeline: cp-engine allgather of tokens into symmetric workspace,
C++ ``moe_ag_scatter_align_block_size`` sorts the gathered token→expert
assignments so each tile is single-expert, and a consumer grouped GEMM
waits per-tile on the source rank's flag (SURVEY.md §2.3).

TPU-native composition: the fused ring allgather kernel moves tokens over
ICI, routing ids are allgathered with an XLA collective (tiny payload), the
jnp alignment (moe_utils) replaces the CUDA sort kernel, and the
scalar-prefetch grouped GEMM (group_gemm) replaces the flag-waiting
consumer — XLA chains the kernels back-to-back on the same core, which is
the TPU analogue of the reference's stream-ordered producer/consumer.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import jit_shard_map
from triton_dist_tpu.ops.group_gemm import GroupGemmConfig, group_gemm
from triton_dist_tpu.ops.moe_utils import (
    MoEAlignment,
    gather_sorted_rows,
    moe_align_block_size,
)


def ag_group_gemm(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    ag_method: str = "auto",
    gather_output: bool = False,
    interpret: Any = None,
):
    """Overlapped MoE up-projection (call inside ``jax.shard_map``;
    ≙ ``ag_group_gemm``, reference allgather_group_gemm.py:272).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]`` expert weights,
    N-sharded (TP); topk_ids: ``[m_loc, topk]`` routing of the local tokens.
    Returns ``(h_sorted [t_pad, n_loc], alignment)`` — the grouped-GEMM
    output in block-aligned expert order over the *gathered* tokens, plus
    the alignment to unsort it (the reference likewise returns scatter
    order for the follow-up reduce). ``gather_output=True`` additionally
    returns the gathered tokens ``a_full`` (free — the fwd workspace; the
    training backward wants it, same contract as ``ag_gemm``).
    """
    cfg = config or GroupGemmConfig()
    n_exp = b.shape[0]
    topk = topk_ids.shape[1]
    a_full = all_gather(a, axis=axis, method=ag_method, interpret=interpret)
    ids_full = jax.lax.all_gather(topk_ids, axis, tiled=True)  # [m_tot, topk]
    alignment = moe_align_block_size(
        ids_full.reshape(-1), n_exp, cfg.block_m
    )
    a_sorted = gather_sorted_rows(a_full, alignment, topk)
    h_sorted = group_gemm(
        a_sorted, b, alignment.expert_ids, config=cfg, interpret=interpret
    )
    if gather_output:
        return h_sorted, alignment, a_full
    return h_sorted, alignment


def ag_group_gemm_op(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: returns the dense per-assignment output
    ``[m_tot * topk, n_loc-sharded N]`` in original token order (sentinel
    rows dropped), for golden comparison and simple use."""
    cfg = config or GroupGemmConfig()
    topk = topk_ids.shape[1]
    m_tot = a.shape[0]

    def fn(a, b, ids):
        h_sorted, alignment = ag_group_gemm(
            a, b, ids, axis=axis, config=cfg, interpret=interpret
        )
        # unsort to assignment order [m_tot*topk, n_loc]
        t = m_tot * topk
        # scatter row index by assignment id; sentinel rows (id == t) drop
        inv = jnp.zeros((t,), jnp.int32).at[alignment.sorted_token_ids].set(
            jnp.arange(alignment.sorted_token_ids.shape[0], dtype=jnp.int32),
            mode="drop",
        )
        return h_sorted[inv]

    return jit_shard_map(
        fn, mesh,
        (P(axis, None), P(None, None, axis), P(axis, None)),
        P(None, axis),
        key=("ag_group_gemm", axis, cfg, m_tot, topk, str(interpret)),
    )(a, b, topk_ids.astype(jnp.int32))
