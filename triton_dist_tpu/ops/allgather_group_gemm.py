"""AG-GroupGEMM — MoE TP forward: allgather tokens + grouped expert GEMM
(≙ reference ``kernels/nvidia/allgather_group_gemm.py``, 499 LoC).

Reference pipeline: cp-engine allgather of tokens into symmetric workspace,
C++ ``moe_ag_scatter_align_block_size`` sorts the gathered token→expert
assignments so each tile is single-expert, and a consumer grouped GEMM
waits per-tile on the source rank's flag (SURVEY.md §2.3).

TPU-native composition (two entries):

- :func:`ag_group_gemm` — sequential: ring-AG kernel, XLA alignment gather,
  scalar-prefetch grouped GEMM. The A/B baseline.
- :func:`ag_group_gemm_overlap` — SORT-BEFORE-RING single kernel: each rank
  pre-sorts its OWN tokens into block-aligned expert order with one fused
  XLA gather (routing ids are allgathered first — tiny payload, same move
  the reference makes at allgather_group_gemm.py:272-330), then the ring
  ships already-aligned slabs which the grouped GEMM consumes with one
  bulk DMA per double-buffered group the moment each chunk lands. Compute
  order = ring arrival order — the reference's per-source-segment tile
  swizzle with flag waits (allgather_group_gemm.py:420-470) becomes the
  schedule itself, as in ``_ag_gemm_kernel``.

  Why sort-before-ring (a real-chip finding): Mosaic has no legal
  row-granular dynamic gather — 1-row DMA slices violate sublane tiling,
  and ``tpu.dynamic_gather`` cannot cross vregs — and a per-row DMA loop
  is descriptor-bound on the scalar core anyway. Shipping pre-sorted slabs
  costs ~topk× ICI payload (token rows duplicate per assignment, exactly
  as EP dispatch duplicates them over the network) but the ring rides
  under the grouped GEMM, whose arithmetic intensity dwarfs it.

The overlap kernel body comes from the pipeline emitter
(:func:`triton_dist_tpu.ops.gg_pipeline.make_ag_overlap_kernel`, ISSUE 7);
this entry only builds specs/scratch for the chosen policy tuple, and
``GroupGemmConfig.w8`` streams int8 weight slabs at HALF the HBM bytes —
the decode regime's weight-traffic win, now inside the overlapped path.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.ops.gg_pipeline import OperandFormat, make_ag_overlap_kernel
from triton_dist_tpu.ops.group_gemm import (
    FP8_DTYPE,
    GroupGemmConfig,
    _group_gemm_xla,
    _panel_for,
    group_gemm,
    resolve_w8,
)
from triton_dist_tpu.ops.moe_utils import (
    RankedAlignment,
    gather_sorted_rows,
    moe_align_block_size,
)
from triton_dist_tpu.synth.admitted import (
    admitted_tune_extension as _admitted_tune_extension,
)
from triton_dist_tpu.utils import pick_block
from triton_dist_tpu.utils import axis_size as _axis_size


def ag_group_gemm(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    ag_method: str = "auto",
    gather_output: bool = False,
    scale: jax.Array | None = None,
    interpret: Any = None,
):
    """Sequential MoE up-projection (call inside ``jax.shard_map``;
    ≙ ``ag_group_gemm``, reference allgather_group_gemm.py:272).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]`` expert weights,
    N-sharded (TP); topk_ids: ``[m_loc, topk]`` routing of the local tokens.
    Returns ``(h_sorted [t_pad, n_loc], alignment)`` — the grouped-GEMM
    output in block-aligned expert order over the *gathered* tokens, plus
    the alignment to unsort it (the reference likewise returns scatter
    order for the follow-up reduce). ``gather_output=True`` additionally
    returns the SORTED gathered rows ``a_sorted [t_pad, K]`` (free — the
    GEMM's own input; the training backward consumes exactly this, same
    contract as :func:`ag_group_gemm_overlap`).
    """
    cfg = config or GroupGemmConfig()
    n_exp = b.shape[0]
    topk = topk_ids.shape[1]
    a_full = all_gather(a, axis=axis, method=ag_method, interpret=interpret)
    ids_full = jax.lax.all_gather(topk_ids, axis, tiled=True)  # [m_tot, topk]
    alignment = moe_align_block_size(
        ids_full.reshape(-1), n_exp, cfg.block_m, ragged=cfg.ragged
    )
    a_sorted = gather_sorted_rows(a_full, alignment, topk)
    # pre-quantized w8 path (ISSUE 8 satellite): an explicit scale marks
    # `b` as an int8 pool, exactly as in group_gemm / the overlap entry
    h_sorted = group_gemm(
        a_sorted, b, alignment.expert_ids, valid_rows=alignment.valid_rows,
        scale=scale, config=cfg, interpret=interpret,
    )
    if gather_output:
        return h_sorted, alignment, a_sorted
    return h_sorted, alignment


def gather_group_blocks_for(
    nb: int, bm: int, k_dim: int, itemsize: int, budget: int = 16 * 2**20
) -> int:
    """Gather-group size for the overlapped kernel: the double-buffered
    resident rows (2 × bpg × bm × K) must stay inside `budget` regardless
    of t_pad_loc, so the kernel is VMEM-bounded for ANY shape (the n=1
    bench shape would otherwise need ~142 MiB resident)."""
    return max(1, min(nb, budget // (2 * bm * k_dim * itemsize)))


def presort_local_rows(a: jax.Array, ral: RankedAlignment, axis: str) -> jax.Array:
    """This rank's block-aligned slab ``[t_pad_loc, K]``: one fused XLA
    gather (HBM-bandwidth pass). Sentinel rows clamp to row 0 of the own
    chunk — junk values, masked by zero combine weights downstream."""
    me = jax.lax.axis_index(axis)
    m_loc = a.shape[0]
    rows_loc = jax.lax.dynamic_index_in_dim(
        ral.src_rows, me, axis=0, keepdims=False
    ) - me * m_loc
    return jnp.take(a, rows_loc, axis=0)


def _ag_overlap_xla(
    a_srt, b, scale, ral, *, axis, ragged, gather_output, out_dtype,
):
    """Golden slow path for the overlapped up-projection: XLA all-gather of
    the pre-sorted slabs + the expert-sorted ragged_dot over the rank-major
    layout — the program the fused kernel is tested against."""
    ag = jax.lax.all_gather(a_srt, axis, tiled=True)
    out = _group_gemm_xla(
        ag, b, ral.expert_ids.reshape(-1),
        valid_rows=(
            None if ral.valid_rows is None else ral.valid_rows.reshape(-1)
        ),
        scale=scale, ragged=ragged, bm=ral.block_m, out_dtype=out_dtype,
        act_fn=None,
    )
    return (out, ag) if gather_output else out


def _ag_overlap_fused(
    a_srt, b, scale, ral, *, axis, ragged, gather_output, out_dtype, cfg,
    gather_group_blocks, interpret,
):
    n = _axis_size((axis))
    k_dim = a_srt.shape[1]
    n_loc = b.shape[2]
    nb = ral.blocks_per_rank
    bm = ral.block_m
    t_pad_loc = ral.t_pad_loc
    w8 = scale is not None
    # the operand format is keyed off the bank dtype, not the config: a
    # float8 pool means the scale rows came from quantize_expert_weights_fp8
    # and the slabs stream at quarter-rate HBM bytes (ISSUE 19)
    fp8 = w8 and b.dtype == FP8_DTYPE
    bn = pick_block(n_loc, cfg.block_n)
    n_jn = n_loc // bn
    itemsize = jnp.dtype(a_srt.dtype).itemsize
    bpg = gather_group_blocks or gather_group_blocks_for(nb, bm, k_dim, itemsize)
    vmem_bytes = (
        2 * bpg * bm * k_dim * itemsize       # double-buffered gather groups
        + 2 * k_dim * bn * b.dtype.itemsize   # double-buffered weight slabs
        + 2 * 2 * bm * bn * jnp.dtype(out_dtype).itemsize
        + 4 * 2**20
    )
    from triton_dist_tpu.ops.common import resolve_spans

    # chunk-granular ring (ISSUE 4): spans quantized to the gather-group
    # size so every chunk holds whole groups; a single-span schedule
    # (incl. every chunks_per_shard=1 config) emits the legacy
    # shard-granular protocol, bit for bit. span_policy (ISSUE 14)
    # dispatches synthesized tilings — contiguous-ascending only here (the
    # gather-group coverage below is derived from span offsets)
    spans = resolve_spans(
        t_pad_loc, max(1, int(getattr(cfg, "chunks_per_shard", 1))),
        bpg * bm, policy=getattr(cfg, "span_policy", "contig"), world=n,
        side="ag",
    )
    kernel = make_ag_overlap_kernel(
        axis=axis, n=n, nb=nb, n_jn=n_jn, bn=bn, bpg=bpg, bm=bm,
        out_dtype=out_dtype, spans=spans, ragged=ragged,
        panel=_panel_for(bm) if ragged else 0,
        fmt=OperandFormat(w8 and not fp8, fp8),
    )
    if len(spans) > 1:
        ring_scratch = [
            pltpu.SemaphoreType.DMA((max(n - 1, 1), len(spans))),
            pltpu.SemaphoreType.DMA((max(n - 1, 1), len(spans))),
            # pure chunk-signal slots (REGULAR; armed watchdog only)
            pltpu.SemaphoreType.REGULAR((max(n - 1, 1), len(spans))),
        ]
    else:
        ring_scratch = [
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ]
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # expert ids [n, nb]
        # HBM pinned (not ANY): chunk slices at traced offsets must DMA
        # from untiled HBM, never compiler-chosen VMEM
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # a_srt
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),  # b
    ]
    args = [ral.expert_ids, a_srt, b]
    if ragged:
        # the per-(rank, block) live-row map rides SMEM next to the ids
        in_specs.insert(1, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(1, ral.valid_rows.astype(jnp.int32))
    if w8:
        # the per-(expert, out-column) scale bank, sliced per weight slab
        in_specs.append(pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM))
        args.append(scale.astype(jnp.float32))
    weight_scratch = [pltpu.VMEM((2, k_dim, bn), b.dtype)]
    bsem_scratch = [pltpu.SemaphoreType.DMA((2,))]
    if w8:
        weight_scratch.append(pltpu.VMEM((2, 1, bn), jnp.float32))
        bsem_scratch.append(pltpu.SemaphoreType.DMA((2,)))
    out, ag = dist_pallas_call(
        kernel,
        name="ag_group_gemm_overlap",
        out_shape=(
            jax.ShapeDtypeStruct((n * t_pad_loc, n_loc), out_dtype),
            jax.ShapeDtypeStruct((n * t_pad_loc, k_dim), a_srt.dtype),
        ),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bpg * bm, k_dim), a_srt.dtype),
            *weight_scratch,
            pltpu.VMEM((2 * bm, bn), out_dtype),
            pltpu.SemaphoreType.DMA(()),
            *ring_scratch,
            pltpu.SemaphoreType.DMA((2,)),
            *bsem_scratch,
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * n * t_pad_loc * k_dim * n_loc,
            bytes_accessed=(n * t_pad_loc * k_dim + n * t_pad_loc * n_loc)
            * itemsize + b.shape[0] * k_dim * n_loc * b.dtype.itemsize,
            transcendentals=0,
        ),
        vmem_limit_bytes=min(vmem_bytes, 100 * 2**20),
        uses_barrier=True,
        interpret=interpret,
    )(*args)
    return (out, ag) if gather_output else out


def ag_group_gemm_overlap(
    a: jax.Array,
    b: jax.Array,
    ral: RankedAlignment,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    scale: jax.Array | None = None,
    gather_output: bool = False,
    out_dtype: Any = None,
    gather_group_blocks: int | None = None,
    interpret: Any = None,
):
    """Single-kernel overlapped MoE up-projection (call inside shard_map;
    ≙ the reference's fused producer/consumer ``ag_group_gemm``,
    allgather_group_gemm.py:272,420-470 — there: cp-engine AG + consumer
    GEMM spinning on per-source flags; here: sort-before-ring, see module
    docstring).

    a: ``[m_loc, K]`` token shard; b: ``[E, K, n_loc]``; `ral` from
    :func:`~triton_dist_tpu.ops.moe_utils.moe_align_ranked` over the
    allgathered routing ids. Returns ``[n*t_pad_loc, n_loc]`` rows in
    rank-major aligned order (+ the SORTED gathered rows
    ``[n*t_pad_loc, K]`` when `gather_output` — the backward's input).

    ``scale`` (``[E, 1, n_loc]``) marks `b` as an int8 pool — the w8 axis
    (``config.w8`` quantizes a float bank on the fly instead): weight
    slabs stream at half the HBM bytes, scale rows on the prefetch chain.

    World-1 degenerates to the scalar-prefetch grid ``group_gemm`` over the
    pre-sorted slab: with no ring to hide, Mosaic's automatic grid
    pipelining is the best schedule (≙ the world-1 XLA-dot sentinels of
    ``ag_gemm``/``gemm_rs``)."""
    from triton_dist_tpu import resilience

    cfg = config or GroupGemmConfig()
    out_dtype = out_dtype or a.dtype
    n = _axis_size((axis))
    nb = ral.blocks_per_rank
    bm = ral.block_m
    assert bm == cfg.block_m, (bm, cfg.block_m)
    ragged = bool(cfg.ragged) and cfg.backend == "pallas"
    if ragged and ral.valid_rows is None:
        raise ValueError(
            "GroupGemmConfig.ragged needs a ragged RankedAlignment — build "
            "it with moe_align_ranked(..., ragged=True)"
        )
    if cfg.backend != "pallas" and n > 1:
        raise ValueError(
            "the ragged_dot sentinel backend needs globally expert-sorted "
            "blocks; route it through the sequential composition "
            "(tp_moe_mlp does this automatically)"
        )
    b, scale = resolve_w8(b, scale, cfg)
    if scale is not None:
        assert scale.shape == (b.shape[0], 1, b.shape[2]), (scale.shape, b.shape)

    # span-policy fence BEFORE the guard ladder (ISSUE 14): a side-invalid
    # or unknown policy is a config error that must fail loudly, not a
    # kernel failure for guarded_call to downgrade to the golden path
    from triton_dist_tpu.ops.common import validate_span_policy

    validate_span_policy(getattr(cfg, "span_policy", "contig"), "ag")

    a_srt = presort_local_rows(a, ral, axis)

    if n == 1:
        h = group_gemm(
            a_srt, b, ral.expert_ids[0], scale=scale,
            valid_rows=None if ral.valid_rows is None else ral.valid_rows[0],
            config=cfg, out_dtype=out_dtype, interpret=interpret,
        )
        return (h, a_srt) if gather_output else h

    return resilience.guarded_call(
        "ag_group_gemm_overlap",
        functools.partial(
            _ag_overlap_fused, cfg=cfg,
            gather_group_blocks=gather_group_blocks, interpret=interpret,
        ),
        _ag_overlap_xla,
        a_srt, b, scale, ral, axis=axis, ragged=ragged,
        gather_output=gather_output, out_dtype=out_dtype,
    )


def ag_group_gemm_op(
    a: jax.Array,
    b: jax.Array,
    topk_ids: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: GroupGemmConfig | None = None,
    interpret: Any = None,
) -> jax.Array:
    """Host-level entry: returns the dense per-assignment output
    ``[m_tot * topk, n_loc-sharded N]`` in original token order (sentinel
    rows dropped), for golden comparison and simple use."""
    cfg = config or GroupGemmConfig()
    topk = topk_ids.shape[1]
    m_tot = a.shape[0]

    def fn(a, b, ids):
        h_sorted, alignment = ag_group_gemm(
            a, b, ids, axis=axis, config=cfg, interpret=interpret
        )
        # unsort to assignment order [m_tot*topk, n_loc]
        t = m_tot * topk
        # scatter row index by assignment id; sentinel rows (id == t) drop
        inv = jnp.zeros((t,), jnp.int32).at[alignment.sorted_token_ids].set(
            jnp.arange(alignment.sorted_token_ids.shape[0], dtype=jnp.int32),
            mode="drop",
        )
        return h_sorted[inv]

    return jit_shard_map(
        fn, mesh,
        (P(axis, None), P(None, None, axis), P(axis, None)),
        P(None, axis),
        key=("ag_group_gemm", axis, cfg, m_tot, topk, str(interpret)),
    )(a, b, topk_ids.astype(jnp.int32))


# Grouped-GEMM tile sweep (≙ the reference autotuning its MoE kernels,
# allgather_group_gemm.py:130-180 config lists). block_m is also the
# alignment block, so the sweep may change padding, not just tiling.
# FIRST entry = best-known default (applied sweep-free under
# cached_or_first). Ragged twins (ISSUE 5) sit strictly AFTER their padded
# originals, and w8 twins (ISSUE 7) strictly AFTER their bf16 twins — the
# no-regression ordering invariant: sweep-free walks can never apply a
# ragged, chunked OR quantized schedule untimed.
AG_GROUP_GEMM_TUNE_SPACE = (
    GroupGemmConfig(128, 1024, 512),
    GroupGemmConfig(128, 2048, 512),
    GroupGemmConfig(128, 1024, 1024),
    GroupGemmConfig(128, 512, 512),
    GroupGemmConfig(256, 1024, 512),
    GroupGemmConfig(128, 1024, 512, ragged=True),
    GroupGemmConfig(256, 1024, 512, ragged=True),
    # w8 axis (ISSUE 7): int8 weight slabs at half the HBM bytes through
    # the same schedules — a serving knob (quantization error ~0.2-0.5%
    # RMS), so only a timed sweep may crown it
    GroupGemmConfig(128, 1024, 512, w8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, w8=True),
    # fp8 axis (ISSUE 19): fp8_e4m3 weight slabs at quarter-rate HBM bytes
    # through the SAME slot structure as w8 — registered strictly after
    # their w8 twins (legacy < w8 < fp8, append-only)
    GroupGemmConfig(128, 1024, 512, fp8=True),
    GroupGemmConfig(128, 1024, 512, ragged=True, fp8=True),
) + _admitted_tune_extension("ag_group_gemm")
# ^ SYNTHESIZED schedules (ISSUE 14): the standing registry of proved
# span policies (triton_dist_tpu/synth/admitted.py) appends STRICTLY
# AFTER every legacy candidate — the no-regression ordering invariant
# (docs/autotuner.md; pinned by tests/test_synth.py). analysis/sweep.py
# enumerates this constant, so protocol_lint proves them permanently.

ag_group_gemm_op = contextual_autotune(
    AG_GROUP_GEMM_TUNE_SPACE, name="ag_group_gemm"
)(ag_group_gemm_op)
