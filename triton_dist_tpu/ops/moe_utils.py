"""MoE routing + token alignment utilities
(≙ reference ``select_experts``/``full_moe_align_block_size``
(moe_reduce_rs.py:87,180) and the C++ ``moe_ag_scatter_align_block_size``
CUDA kernel (csrc/lib/moe_utils.cu:36-356)).

The reference sorts token→expert assignments on device with a shared-memory
histogram + cumsum so every GEMM tile processes rows of a single expert,
padding each expert's segment to the tile size. The TPU-native form is a
fortiori simpler: XLA's sort/scan primitives fuse into a handful of kernels,
so the alignment is ~15 lines of jnp. (The reference's CUDA kernel is a
device-side necessity, not a design feature; the C++ host-side equivalent
for native tooling is part of the csrc/ build — see csrc/ when present.)

All shapes are static: the padded row count is the worst case
``T + E*(block_m-1)`` rounded up, with sentinel rows marked by token id
``T`` (gathers clamp, epilogues mask).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from triton_dist_tpu.utils import round_up


def select_experts(
    logits: jax.Array, topk: int
) -> tuple[jax.Array, jax.Array]:
    """Softmax + top-k routing (≙ ``select_experts``, moe_reduce_rs.py:180).

    logits: ``[tokens, E]``. Returns ``(weights [tokens, topk] — softmax
    scores renormalized over the chosen experts, ids [tokens, topk] int32)``.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights, ids = jax.lax.top_k(probs, topk)
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MoEAlignment:
    """Block-aligned token ordering for grouped GEMM.

    sorted_token_ids: ``[t_pad]`` int32 — flattened token-expert assignment
      index (``token*topk + k`` slot) per padded row; sentinel ``T`` for
      padding rows.
    expert_ids: ``[t_pad // block_m]`` int32 — owning expert of each row
      block (every block is single-expert by construction).
    num_tokens_post_pad: scalar int32 — valid padded rows (static shapes
      mean consumers still process all blocks; rows past this are padding).
    """

    sorted_token_ids: jax.Array
    expert_ids: jax.Array
    num_tokens_post_pad: jax.Array
    # Ragged mode (ISSUE 5): live rows per block — ``[t_pad // block_m]``
    # int32 in (0, block_m] for blocks inside an expert's segment, 0 for the
    # trailing worst-case blocks past every segment. Together with
    # expert_ids this is the scalar-prefetched per-block map
    # ``block → (expert_id, valid_rows)`` the ragged grouped-GEMM kernels
    # consume; None under the legacy (padded) contract.
    valid_rows: jax.Array | None = None

    @property
    def block_m(self) -> int:
        return self.sorted_token_ids.shape[0] // self.expert_ids.shape[0]


def moe_align_block_size(
    topk_ids: jax.Array, n_experts: int, block_m: int, *, ragged: bool = False
) -> MoEAlignment:
    """Sort token-expert assignments by expert and pad each expert segment
    to a multiple of `block_m` (≙ ``moe_ag_scatter_align_block_size``,
    csrc/lib/moe_utils.cu:36-356).

    topk_ids: ``[T]`` int32 flattened assignments (T = tokens * topk).

    ``ragged=True`` additionally emits the per-block ``valid_rows`` map
    (true live rows of each block — a tail block carries its real count
    instead of claiming the full ``block_m``), so a ragged-aware consumer
    can skip the pad rows' MXU work entirely. Layout and every other field
    are IDENTICAL to the legacy form: ragged changes what is computed, not
    where rows live, which is what lets every downstream consumer (gather,
    scatter, backward, the rank-major overlap layout) work unchanged.
    """
    t = topk_ids.shape[0]
    t_pad = round_up(t + n_experts * (block_m - 1), block_m)
    counts = jnp.bincount(topk_ids, length=n_experts)
    padded_counts = ((counts + block_m - 1) // block_m) * block_m
    seg_starts = jnp.concatenate(
        [jnp.zeros(1, padded_counts.dtype), jnp.cumsum(padded_counts)[:-1]]
    )
    # stable sort by expert keeps original token order within an expert
    order = jnp.argsort(topk_ids, stable=True)  # [t] assignment indices
    expert_sorted = topk_ids[order]
    cum_counts = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    pos_in_expert = jnp.arange(t) - cum_counts[expert_sorted]
    target = seg_starts[expert_sorted] + pos_in_expert
    sorted_token_ids = jnp.full((t_pad,), t, jnp.int32).at[target].set(
        order.astype(jnp.int32)
    )
    block_starts = jnp.arange(t_pad // block_m) * block_m
    expert_ids = jnp.searchsorted(
        jnp.cumsum(padded_counts), block_starts, side="right"
    ).astype(jnp.int32)
    # blocks past all experts' segments keep a valid (clamped) expert id
    expert_ids = jnp.minimum(expert_ids, n_experts - 1)
    valid_rows = None
    if ragged:
        # live rows of block b: how far expert e's REAL rows reach into it
        # (0 for the worst-case trailing blocks — their clamped expert id
        # never owns them, so the whole block is dead)
        offs = block_starts.astype(jnp.int32) - seg_starts.astype(jnp.int32)[
            expert_ids
        ]
        valid_rows = jnp.clip(
            counts.astype(jnp.int32)[expert_ids] - offs, 0, block_m
        ).astype(jnp.int32)
    return MoEAlignment(
        sorted_token_ids=sorted_token_ids,
        expert_ids=expert_ids,
        num_tokens_post_pad=jnp.sum(padded_counts).astype(jnp.int32),
        valid_rows=valid_rows,
    )


def valid_rows_from_sorted(
    sorted_token_ids: jax.Array, block_m: int, sentinel: int
) -> jax.Array:
    """Reconstruct the ragged per-block ``valid_rows`` map from a sorted-id
    array whose pad rows carry ``sentinel`` (every in-repo alignment
    builder's convention). Valid rows are a prefix of each block by
    construction — real rows pack from the segment start, pad rows trail —
    so the per-block count IS the map. For externally-provided alignments
    (``moe_reduce_rs_op``) where the builder's map isn't in hand."""
    return jnp.sum(
        (sorted_token_ids.reshape(-1, block_m) < sentinel), axis=1
    ).astype(jnp.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RankedAlignment:
    """Per-source-rank block alignment: rank-major, expert-minor.

    Each rank's ``m_loc * topk`` assignments are aligned *independently*
    (same construction as :func:`moe_align_block_size`, applied per rank),
    so every row block draws its tokens from exactly ONE rank's chunk. That
    locality is what lets the fused AG-GroupGEMM consume each chunk the
    moment its ring transfer lands, and the fused MoE-Reduce-RS push each
    destination rank's output as soon as its blocks finish — the TPU form
    of the reference's per-source-segment tile swizzle + per-rank notify
    counters (reference allgather_group_gemm.py:420-470,
    moe_reduce_rs.py:362). The price is per-rank instead of global padding
    (≤ ``E*(block_m-1)`` extra rows *per rank*); the overlap and the
    elimination of the materialized gather buy it back.

    local_ids: ``[n, t_pad_loc]`` int32 — rank-local flattened assignment
      index (``token*topk + k``), sentinel ``t_loc`` for padding rows.
    src_rows: ``[n, t_pad_loc]`` int32 — GLOBAL gathered-A row feeding each
      aligned row (``c*m_loc + token``); sentinel rows clamp to row 0 of
      their own chunk, which is always resident when that chunk is
      processed.
    expert_ids: ``[n, nb]`` int32 — owning expert of each row block.
    """

    local_ids: jax.Array
    src_rows: jax.Array
    expert_ids: jax.Array
    # ragged mode (ISSUE 5): ``[n, nb]`` live rows per (rank, block); None
    # under the legacy padded contract (see MoEAlignment.valid_rows)
    valid_rows: jax.Array | None = None

    @property
    def n_ranks(self) -> int:
        return self.local_ids.shape[0]

    @property
    def t_pad_loc(self) -> int:
        return self.local_ids.shape[1]

    @property
    def blocks_per_rank(self) -> int:
        return self.expert_ids.shape[1]

    @property
    def block_m(self) -> int:
        return self.t_pad_loc // self.blocks_per_rank

def ranked_global_view(al: RankedAlignment, m_loc: int, topk: int) -> MoEAlignment:
    """Express a rank-major :class:`RankedAlignment` as an ordinary global
    :class:`MoEAlignment` over the gathered token set, so every downstream
    consumer (``scatter_add_unsorted``, ``group_gemm`` backward, goldens)
    works unchanged: row ``(c, r)`` maps to global assignment
    ``c*m_loc*topk + local_ids[c, r]`` with the global sentinel
    ``n*m_loc*topk`` for padding rows.

    Two contract deltas vs :func:`moe_align_block_size` output: expert ids
    are sorted only *within* each rank segment (pass ``assume_sorted=False``
    to ``group_gemm_dw``), and because padding blocks are interleaved per
    rank segment there is no valid-prefix — ``num_tokens_post_pad`` is
    therefore the FULL padded length, so a consumer that truncates work at
    it conservatively processes everything (sentinel ids mask the padding
    rows, which every consumer must honor anyway)."""
    n, t_pad_loc = al.local_ids.shape
    t_loc = m_loc * topk
    c = jnp.arange(n, dtype=jnp.int32)[:, None]
    valid = al.local_ids < t_loc
    sorted_token_ids = jnp.where(
        valid, c * t_loc + al.local_ids, n * t_loc
    ).reshape(-1).astype(jnp.int32)
    return MoEAlignment(
        sorted_token_ids=sorted_token_ids,
        expert_ids=al.expert_ids.reshape(-1),
        num_tokens_post_pad=jnp.int32(n * t_pad_loc),
        valid_rows=(
            None if al.valid_rows is None else al.valid_rows.reshape(-1)
        ),
    )


def moe_align_ranked(
    ids_full: jax.Array, n_experts: int, block_m: int, m_loc: int,
    *, ragged: bool = False,
) -> RankedAlignment:
    """Align each rank's routing independently (see
    :class:`RankedAlignment`). ids_full: ``[n, m_loc*topk]`` int32 — the
    allgathered flattened top-k ids (tiny payload; ≙ the reference
    allgathering routing metadata ahead of the token data,
    allgather_group_gemm.py:272-330). ``ragged=True`` carries the
    per-(rank, block) ``valid_rows`` map through (see
    :func:`moe_align_block_size`)."""
    n, t_loc = ids_full.shape
    topk = t_loc // m_loc
    al = jax.vmap(
        lambda ids: moe_align_block_size(ids, n_experts, block_m, ragged=ragged)
    )(ids_full)
    token_of = jnp.clip(al.sorted_token_ids // topk, 0, m_loc - 1)
    valid = al.sorted_token_ids < t_loc
    c = jnp.arange(n, dtype=jnp.int32)[:, None]
    src_rows = c * m_loc + jnp.where(valid, token_of, 0)
    return RankedAlignment(
        local_ids=al.sorted_token_ids.astype(jnp.int32),
        src_rows=src_rows.astype(jnp.int32),
        expert_ids=al.expert_ids.astype(jnp.int32),
        valid_rows=(
            None if al.valid_rows is None
            else al.valid_rows.astype(jnp.int32)
        ),
    )


def ranked_scatter_meta(
    al: RankedAlignment, topk_weights_full: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Per-row combine metadata for the fused MoE-Reduce-RS: destination
    token WITHIN the row's own chunk and the routing weight (0 for sentinel
    rows). topk_weights_full: ``[n*m_loc, topk]`` gathered weights.
    Returns ``(dst_ids [n, nb, bm] int32, w_rows [n, nb, bm] f32)`` shaped
    for per-block VMEM slicing."""
    n, t_pad_loc = al.local_ids.shape
    topk = topk_weights_full.shape[1]
    m_loc = topk_weights_full.shape[0] // n
    t_loc = m_loc * topk
    valid = al.local_ids < t_loc
    local_tok = jnp.clip(al.local_ids // topk, 0, m_loc - 1)
    c = jnp.arange(n, dtype=jnp.int32)[:, None]
    glob_assign = jnp.clip(c * t_loc + al.local_ids, 0, n * t_loc - 1)
    w = jnp.where(
        valid, topk_weights_full.reshape(-1)[glob_assign], 0.0
    ).astype(jnp.float32)
    bm = al.block_m
    return (
        local_tok.astype(jnp.int32).reshape(n, -1, bm),
        w.reshape(n, -1, bm),
    )


def gather_sorted_rows(
    x: jax.Array, alignment: MoEAlignment, topk: int
) -> jax.Array:
    """Expand tokens into block-aligned grouped-GEMM rows: row ``r`` of the
    result is token ``sorted_token_ids[r] // topk`` (sentinels clamp to the
    last token; their outputs are masked on the way back)."""
    token_of_row = jnp.minimum(alignment.sorted_token_ids // topk, x.shape[0] - 1)
    return x[token_of_row]


def scatter_add_unsorted(
    y_sorted: jax.Array,
    alignment: MoEAlignment,
    weights: jax.Array,
    n_tokens: int,
    *,
    assume_bijective: bool = True,
) -> jax.Array:
    """Inverse of :func:`gather_sorted_rows` with the top-k weighted
    reduction fused in (≙ the consumer topk-reduce, moe_reduce_rs.py:468):
    out[token] = Σ_k w[token,k] * y_sorted[row(token,k)].

    NOT a scatter by default: TPU serializes ``.at[].add()`` row scatters
    (measured 4.2 ms for the bench-shape combine — 10× its HBM traffic;
    the 19% pipeline overhead of r5's MFU decomposition). When the
    alignment is a bijection from the flat (token, k) slots to sorted
    rows — every slot placed exactly once, sentinel rows carrying
    ``n_tokens*topk``, which every in-repo alignment builder guarantees —
    a stable argsort of the slot ids IS the inverse permutation, and the
    combine becomes gather + weighted sum, both streaming ops (0.89 ms
    on chip).

    ``assume_bijective`` is that CONTRACT, not a PRODUCTION runtime check
    (a traced guard + ``lax.cond`` costs ~1.1 ms — re-measured r5): pass
    ``False`` for capacity-style alignments that DROP slots (a dropped
    slot would shift every later token onto the wrong rows under the
    gather form) to get the masked-scatter semantics where dropped slots
    contribute zero.

    Under interpret/debug mode (``config.interpreting()``) the contract IS
    validated: the sorted slot ids must be exactly ``arange(t)`` followed
    by sentinels, and a violating alignment is routed to the masked-
    scatter path via ``lax.cond`` — a dropped slot then contributes zero
    instead of silently shifting every later token's rows (ADVICE r5 #1).
    The debug-tier cost never ships: compiled TPU runs keep the unguarded
    gather form."""
    from triton_dist_tpu import config as tdt_config

    topk = weights.shape[1]
    ids = alignment.sorted_token_ids  # [t_pad], sentinel = n_tokens*topk
    t = n_tokens * topk

    def masked_scatter(ids):
        valid = ids < t
        flat_w = jnp.where(
            valid, weights.reshape(-1)[jnp.clip(ids, 0, t - 1)], 0.0
        )
        token_of_row = jnp.clip(ids // topk, 0, n_tokens - 1)
        contrib = y_sorted.astype(jnp.float32) * flat_w[:, None]
        return (
            jnp.zeros((n_tokens, y_sorted.shape[1]), jnp.float32)
            .at[token_of_row].add(jnp.where(valid[:, None], contrib, 0.0))
        )

    def bijective_gather(ids):
        inv = jnp.argsort(ids, stable=True)[:t].reshape(n_tokens, topk)
        w = weights.astype(jnp.float32)
        # one row-gather per k slot: the obvious single [t, k, d] gather
        # measures 2.6x slower on chip (the 3-D intermediate's layout
        # defeats the streaming fusion); topk is small and static
        out = y_sorted[inv[:, 0]].astype(jnp.float32) * w[:, 0][:, None]
        for k in range(1, topk):
            out = out + y_sorted[inv[:, k]].astype(jnp.float32) * w[:, k][:, None]
        return out

    if not assume_bijective:
        return masked_scatter(ids)
    if tdt_config.interpreting():
        sorted_ids = jnp.sort(ids)
        ok = jnp.all(sorted_ids[:t] == jnp.arange(t, dtype=sorted_ids.dtype))
        if ids.shape[0] > t:
            ok = jnp.logical_and(ok, jnp.all(sorted_ids[t:] == t))
        return jax.lax.cond(ok, bijective_gather, masked_scatter, ids)
    return bijective_gather(ids)
