"""Low-latency All-to-All — EP MoE dispatch/combine transport
(≙ reference ``kernels/nvidia/low_latency_all_to_all.py``, 270 LoC, and the
inter-rank transport half of ``ep_a2a.py``).

Reference design (SURVEY.md §3.4): one kernel, grid = WORLD_SIZE, each block
owns a peer — put data + splits, put-signal scale, ``fence``, then
``signal_op``/``signal_wait_until`` on the own slot, with double-buffered
symmetric buffers versioned by ``call_count`` (low_latency_all_to_all.py:36-118).

TPU-native re-design:

- **Padded slabs, static shapes.** Token counts per peer are runtime values;
  XLA needs static shapes, so each PE sends its full ``[max_m, hidden]``
  segment per peer (the reference pads its symmetric buffers to ``max_m``
  the same way, :139-147). The valid count travels as a tiny int32 put into
  the receiver's split slab. A latency-bound MoE dispatch (the 137 µs
  README headline is 128 tokens/rank) is padded-slab-shaped anyway.
- **No signals, no fence, no call_count.** The data-coupled receive
  semaphore of each put IS the signal (arrival implies data, which NVSHMEM
  needs fence + signal_op for), and every call opens with ``barrier_all``
  over fresh DMA semaphores, so the double-buffer/versioning machinery
  drops out entirely.
- **Slot symmetry**: sender ``s`` writes receiver ``r``'s slab ``s`` — every
  (sender, receiver) pair owns a distinct slab, the same trick as the
  reference's per-rank segments of its symmetric recv buffer.

`fast_all_to_all` is its own inverse (with transposed splits), so EP
*combine* is a second call with the dispatch output — the topk-weighted
reduction after combine lives in the MoE layer, not here.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.shmem import device as shmem


def _fast_all_to_all_xla(
    tokens: jax.Array, splits: jax.Array, *, meta=None, axis="tp", **_
):
    """The golden slow path: XLA's all-to-all over the slab dim, with the
    splits (and optional metadata) exchanged the same way — identical slab
    contract to the fused kernel and to its DCN branch."""
    recv = jax.lax.all_to_all(tokens, axis, 0, 0, tiled=True)
    n = tokens.shape[0]
    payload = splits.reshape(n, 1).astype(jnp.int32)
    if meta is not None:
        payload = jnp.concatenate(
            [payload, meta.reshape(n, -1).astype(jnp.int32)], axis=1
        )
    rpayload = jax.lax.all_to_all(payload, axis, 0, 0, tiled=True)
    rsplits = rpayload[:, 0]
    if meta is None:
        return recv, rsplits
    return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)


@dataclasses.dataclass(frozen=True)
class A2AConfig:
    """``puts_per_slab`` splits each peer's data slab into that many
    row-chunk puts: more descriptors, but chunks can ride different ICI
    routes/engines concurrently and the receiver's first rows land sooner.
    1 (one put per peer) is the latency-optimal default for the small slabs
    of the MoE dispatch headline shape; the autotuner sweeps it."""

    puts_per_slab: int = 1


def _a2a_kernel(
    send_ref, splits_ref, recv_ref, rsplits_ref, copy_sems,
    data_send, data_recv, spl_send, spl_recv,
    *, axis: str, n: int, chunks: int,
):
    me = shmem.my_pe(axis)
    max_m = send_ref.shape[1]
    rows = max_m // chunks
    # race shaking (no-op unless config.debug_comm_delay)
    shmem.comm_jitter(axis, salt=5)
    # Own slab moves locally; both copies ride the local DMA engines while
    # the remote puts below are in flight.
    c1 = pltpu.make_async_copy(send_ref.at[me], recv_ref.at[me], copy_sems.at[0])
    c2 = pltpu.make_async_copy(splits_ref.at[me], rsplits_ref.at[me], copy_sems.at[1])
    c1.start()
    c2.start()
    shmem.barrier_all(axis)
    descs = []
    for d in range(1, n):
        dst = jax.lax.rem(me + d, n)
        # splits first: a tiny put the receiver could use to early-out reads
        descs.append(
            shmem.putmem_nbi_block(
                rsplits_ref.at[me], splits_ref.at[dst], dst, axis,
                spl_send.at[d - 1], spl_recv.at[d - 1],
            )
        )
        for k in range(chunks):
            sl = pl.ds(k * rows, rows if k < chunks - 1 else max_m - k * rows)
            descs.append(
                shmem.putmem_nbi_block(
                    recv_ref.at[me, sl], send_ref.at[dst, sl], dst, axis,
                    data_send.at[d - 1, k], data_recv.at[d - 1, k],
                )
            )
    c1.wait()
    c2.wait()
    # Symmetric SPMD: each descriptor's recv side counts the equal-sized
    # incoming slab from peer me-d, so this waits for all arrivals.
    for desc in descs:
        desc.wait_recv()
    shmem.quiet(*descs)


def fast_all_to_all(
    tokens: jax.Array,
    splits: jax.Array,
    *,
    meta: jax.Array | None = None,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
) -> tuple[jax.Array, jax.Array] | tuple[jax.Array, jax.Array, jax.Array]:
    """Exchange padded token slabs between all PEs of `axis` (call inside
    ``jax.shard_map``; ≙ ``fast_all_to_all``, low_latency_all_to_all.py:189).
    Degrades to the golden :func:`_fast_all_to_all_xla` when the fused
    kernel cannot run in this environment (resilience layer,
    docs/resilience.md).

    tokens: ``[n, max_m, hidden]`` — slab ``p`` holds the ``splits[p]``
    tokens this PE sends to PE ``p`` (rows beyond the count are padding).
    splits: ``[n]`` int32 valid counts.
    meta: optional ``[n, K]`` int32 per-slab metadata (e.g. per-row expert
    ids, bitcast routing weights). It rides the *existing* splits put —
    the reference folds routing metadata into the same transport for the
    same reason (its scale tensor travels with the data,
    low_latency_all_to_all.py:94-104) — so attaching metadata costs zero
    extra DMAs, kernel launches, or barriers.

    Returns ``(recv, recv_splits[, recv_meta])``: slab ``j`` of ``recv``
    holds the tokens PE ``j`` sent here (``recv_splits[j]`` valid rows).
    Golden: ``jax.lax.all_to_all`` over the slab dim.
    """
    return resilience.guarded_call(
        "fast_all_to_all",
        _fast_all_to_all_fused,
        _fast_all_to_all_xla,
        tokens, splits, meta=meta, axis=axis, config=config,
        interpret=interpret,
    )


def _fast_all_to_all_fused(
    tokens: jax.Array,
    splits: jax.Array,
    *,
    meta: jax.Array | None = None,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
):
    cfg = config or A2AConfig()
    n = int(jax.lax.axis_size(axis))
    n_slabs, max_m, hidden = tokens.shape
    assert n_slabs == n, (n_slabs, n)
    chunks = max(1, min(cfg.puts_per_slab, max_m))
    splits = splits.reshape(n, 1).astype(jnp.int32)
    payload = splits
    if meta is not None:
        assert meta.shape[0] == n, (meta.shape, n)
        payload = jnp.concatenate(
            [splits, meta.reshape(n, -1).astype(jnp.int32)], axis=1
        )
    w = payload.shape[1]
    if n == 1:
        if meta is None:
            return tokens, splits.reshape(n)
        return tokens, splits.reshape(n), meta
    from triton_dist_tpu.parallel.topology import is_dcn_axis_name as _is_dcn

    if _is_dcn(axis):
        # slice-crossing axis: remote DMA cannot reach across slices, so
        # the slab exchange lowers to XLA's all-to-all on DCN. The slab
        # contract (slab p → PE p, payload alongside) is identical, so
        # callers — including the hierarchical EP's outer phase — are
        # oblivious (≙ the reference's cross-node EP dispatch over IB,
        # ep_a2a.py:36-147).
        recv = jax.lax.all_to_all(tokens, axis, 0, 0, tiled=True)
        rpayload = jax.lax.all_to_all(payload, axis, 0, 0, tiled=True)
        rsplits = rpayload[:, 0]
        if meta is None:
            return recv, rsplits
        return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)
    n_steps = n - 1
    recv, rpayload = dist_pallas_call(
        functools.partial(_a2a_kernel, axis=axis, n=n, chunks=chunks),
        name="fast_all_to_all",
        out_shape=(
            jax.ShapeDtypeStruct((n, max_m, hidden), tokens.dtype),
            jax.ShapeDtypeStruct((n, w), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((n_steps, chunks)),
            pltpu.SemaphoreType.DMA((n_steps, chunks)),
            pltpu.SemaphoreType.DMA((n_steps,)),
            pltpu.SemaphoreType.DMA((n_steps,)),
        ],
        interpret=interpret,
    )(tokens, payload)
    rsplits = rpayload[:, 0]
    if meta is None:
        return recv, rsplits
    return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)


def all_to_all_post_process(
    recv: jax.Array, recv_splits: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Compact the padded recv slabs to the front (≙ ``all_to_all_post_process``,
    low_latency_all_to_all.py:251). Returns ``(packed, total)`` where
    ``packed[:total]`` are the valid tokens in slab order (rows after that
    are zero); shapes stay static as jit requires."""
    n, max_m, hidden = recv.shape
    flat = recv.reshape(n * max_m, hidden)
    slab = jnp.arange(n * max_m) // max_m
    pos = jnp.arange(n * max_m) % max_m
    valid = pos < recv_splits[slab]
    # Stable sort by target position (padding keys to the back): valid rows
    # land densely at the front in slab order.
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(recv_splits)[:-1]])
    keys = jnp.where(valid, offsets[slab] + pos, n * max_m)
    order = jnp.argsort(keys, stable=True)
    packed = jnp.where(valid[order][:, None], flat[order], 0)
    return packed, jnp.sum(recv_splits)


def _fast_all_to_all_op_xla(
    tokens: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    **_,
) -> tuple[jax.Array, jax.Array]:
    """Op-level golden: the same shard_map entry serving XLA's all-to-all
    (identical slab contract, so callers are oblivious to the downgrade)."""
    if mesh.shape[axis] == 1:
        return tokens, splits.astype(jnp.int32)

    def wrapped(t, s):
        r, rs = _fast_all_to_all_xla(t[0], s[0], axis=axis)
        return r[None], rs[None]

    return jit_shard_map(
        wrapped, mesh,
        (P(axis, None, None, None), P(axis, None)),
        (P(axis, None, None, None), P(axis, None)),
        key=("fast_all_to_all_xla", axis),
    )(tokens, splits.astype(jnp.int32))


def fast_all_to_all_op(
    tokens: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
) -> tuple[jax.Array, jax.Array]:
    """Host-level entry: `tokens` ``[n, n, max_m, hidden]`` (dim 0 = owning
    PE, dim 1 = destination slab) and `splits` ``[n, n]``, both sharded on
    dim 0. Returns the exchanged slabs/splits in the same layout."""
    if mesh.shape[axis] == 1:
        # world-1 all-to-all IS the identity: no kernel, no copy
        return tokens, splits.astype(jnp.int32)
    fn = functools.partial(
        fast_all_to_all, axis=axis, config=config, interpret=interpret
    )

    def wrapped(t, s):
        r, rs = fn(t[0], s[0])
        return r[None], rs[None]

    return jit_shard_map(
        wrapped, mesh,
        (P(axis, None, None, None), P(axis, None)),
        (P(axis, None, None, None), P(axis, None)),
        key=("fast_all_to_all", axis, config, str(interpret)),
    )(tokens, splits.astype(jnp.int32))


# FIRST entry = best-known default (one put per peer is latency-optimal
# for the dispatch headline shape; applied sweep-free under cached_or_first)
A2A_TUNE_SPACE = (A2AConfig(1), A2AConfig(2), A2AConfig(4))

fast_all_to_all_op = contextual_autotune(A2A_TUNE_SPACE, name="fast_all_to_all")(
    fast_all_to_all_op
)
# guard OUTSIDE the autotuner: the sweep still prices failing candidates;
# only a failure of the whole tuned entry degrades to the XLA golden
fast_all_to_all_op = resilience.guard_op(
    "fast_all_to_all_op", _fast_all_to_all_op_xla
)(fast_all_to_all_op)
