"""Low-latency All-to-All — EP MoE dispatch/combine transport
(≙ reference ``kernels/nvidia/low_latency_all_to_all.py``, 270 LoC, and the
inter-rank transport half of ``ep_a2a.py``).

Reference design (SURVEY.md §3.4): one kernel, grid = WORLD_SIZE, each block
owns a peer — put data + splits, put-signal scale, ``fence``, then
``signal_op``/``signal_wait_until`` on the own slot, with double-buffered
symmetric buffers versioned by ``call_count`` (low_latency_all_to_all.py:36-118).

TPU-native re-design:

- **Padded slabs, static shapes.** Token counts per peer are runtime values;
  XLA needs static shapes, so each PE sends its full ``[max_m, hidden]``
  segment per peer (the reference pads its symmetric buffers to ``max_m``
  the same way, :139-147). The valid count travels as a tiny int32 put into
  the receiver's split slab. A latency-bound MoE dispatch (the 137 µs
  README headline is 128 tokens/rank) is padded-slab-shaped anyway.
- **No signals, no fence, no call_count.** The data-coupled receive
  semaphore of each put IS the signal (arrival implies data, which NVSHMEM
  needs fence + signal_op for), and every call opens with ``barrier_all``
  over fresh DMA semaphores, so the double-buffer/versioning machinery
  drops out entirely.
- **Slot symmetry**: sender ``s`` writes receiver ``r``'s slab ``s`` — every
  (sender, receiver) pair owns a distinct slab, the same trick as the
  reference's per-rank segments of its symmetric recv buffer.

`fast_all_to_all` is its own inverse (with transposed splits), so EP
*combine* is a second call with the dispatch output — the topk-weighted
reduction after combine lives in the MoE layer, not here.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.autotuner import contextual_autotune
from triton_dist_tpu.ops.common import dist_pallas_call, jit_shard_map
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import axis_size as _axis_size


def _fast_all_to_all_xla(
    tokens: jax.Array, splits: jax.Array, *, meta=None, axis="tp", **_
):
    """The golden slow path: XLA's all-to-all over the slab dim, with the
    splits (and optional metadata) exchanged the same way — identical slab
    contract to the fused kernel and to its DCN branch."""
    recv = jax.lax.all_to_all(tokens, axis, 0, 0, tiled=True)
    n = tokens.shape[0]
    payload = splits.reshape(n, 1).astype(jnp.int32)
    if meta is not None:
        payload = jnp.concatenate(
            [payload, meta.reshape(n, -1).astype(jnp.int32)], axis=1
        )
    rpayload = jax.lax.all_to_all(payload, axis, 0, 0, tiled=True)
    rsplits = rpayload[:, 0]
    if meta is None:
        return recv, rsplits
    return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)


@dataclasses.dataclass(frozen=True)
class A2AConfig:
    """``puts_per_slab`` splits each peer's data slab into that many
    row-chunk puts: more descriptors, but chunks can ride different ICI
    routes/engines concurrently and the receiver's first rows land sooner.
    1 (one put per peer) is the latency-optimal default for the small slabs
    of the MoE dispatch headline shape; the autotuner sweeps it.

    ``chunks_per_shard`` (ISSUE 4) is the chunk-GRANULAR form of the same
    split: per-(peer, chunk) semaphore slots, chunk-major issue order, and
    a receiver that consumes each peer's payload chunk by chunk through
    ``shmem.wait_chunk`` — so the chunk-signal watchdog/chaos machinery
    covers the a2a edges and downstream consumers can overlap on partial
    slabs. 1 (default) dispatches to the UNCHANGED legacy kernel, bit for
    bit; >1 supersedes ``puts_per_slab`` (the chunked schedule subsumes
    it)."""

    puts_per_slab: int = 1
    chunks_per_shard: int = 1


def _a2a_kernel(
    send_ref, splits_ref, recv_ref, rsplits_ref, copy_sems,
    data_send, data_recv, spl_send, spl_recv,
    *, axis: str, n: int, chunks: int,
):
    me = shmem.my_pe(axis)
    max_m = send_ref.shape[1]
    rows = max_m // chunks
    # race shaking (no-op unless config.debug_comm_delay)
    shmem.comm_jitter(axis, salt=5)
    # Own slab moves locally; both copies ride the local DMA engines while
    # the remote puts below are in flight.
    c1 = pltpu.make_async_copy(send_ref.at[me], recv_ref.at[me], copy_sems.at[0])
    c2 = pltpu.make_async_copy(splits_ref.at[me], rsplits_ref.at[me], copy_sems.at[1])
    c1.start()
    c2.start()
    shmem.barrier_all(axis)
    descs = []
    for d in range(1, n):
        dst = jax.lax.rem(me + d, n)
        # splits first: a tiny put the receiver could use to early-out reads
        descs.append(
            shmem.putmem_nbi_block(
                rsplits_ref.at[me], splits_ref.at[dst], dst, axis,
                spl_send.at[d - 1], spl_recv.at[d - 1],
            )
        )
        for k in range(chunks):
            sl = pl.ds(k * rows, rows if k < chunks - 1 else max_m - k * rows)
            descs.append(
                shmem.putmem_nbi_block(
                    recv_ref.at[me, sl], send_ref.at[dst, sl], dst, axis,
                    data_send.at[d - 1, k], data_recv.at[d - 1, k],
                )
            )
    c1.wait()
    c2.wait()
    # Symmetric SPMD: each descriptor's recv side counts the equal-sized
    # incoming slab from peer me-d, so this waits for all arrivals.
    for desc in descs:
        desc.wait_recv()
    shmem.quiet(*descs)


def _a2a_chunked_kernel(
    send_ref, splits_ref, recv_ref, rsplits_ref, copy_sems,
    data_send, data_recv, data_sig, spl_send, spl_recv,
    *, axis: str, n: int, spans,
):
    """Chunk-granular a2a (ISSUE 4 tentpole): each peer's slab moves as
    ``len(spans)`` independent chunk DMAs on per-(peer, chunk) semaphore
    slots, issued chunk-major (every peer's chunk j before any chunk j+1 —
    ``shmem.putmem_signal_chunked_a2a_nbi_block``), and the receiver
    consumes per-peer payloads chunk by chunk in the same order, so the
    earliest-landing chunks unblock first and a chunk-signal fault trips
    the watchdog at a ``chunk_wait`` site instead of corrupting (the
    chunks=1 schedule is exactly :func:`_a2a_kernel` and is dispatched
    there)."""
    me = shmem.my_pe(axis)
    shmem.comm_jitter(axis, salt=5)
    # own slab moves locally, riding under the remote chunk rounds
    c1 = pltpu.make_async_copy(send_ref.at[me], recv_ref.at[me], copy_sems.at[0])
    c2 = pltpu.make_async_copy(splits_ref.at[me], rsplits_ref.at[me], copy_sems.at[1])
    c1.start()
    c2.start()
    shmem.barrier_all(axis)
    peers = [jax.lax.rem(me + d, n) for d in range(1, n)]
    # splits first (tiny): the receiver-side counts land before the bulk
    spl_descs = [
        shmem.putmem_nbi_block(
            rsplits_ref.at[me], splits_ref.at[dst], dst, axis,
            spl_send.at[d], spl_recv.at[d],
        )
        for d, dst in enumerate(peers)
    ]
    handles = shmem.putmem_signal_chunked_a2a_nbi_block(
        lambda i, off, rows, me=me: recv_ref.at[me, pl.ds(off, rows)],
        lambda i, off, rows: send_ref.at[peers[i], pl.ds(off, rows)],
        peers, axis,
        lambda i, j: data_send.at[i, j],
        lambda i, j: data_recv.at[i, j],
        lambda i, j: data_sig.at[i, j],
        spans,
        # handle i's incoming chunks are peer (me-1-i)'s payload, landing
        # in its slab of OUR recv buffer — the payload-integrity landing
        # view (canary + fault injection, ISSUE 8)
        recv_view=lambda i, off, rows, me=me: recv_ref.at[
            jax.lax.rem(me - 1 - i + 2 * n, n), pl.ds(off, rows)
        ],
    )
    c1.wait()
    c2.wait()
    for desc in spl_descs:
        desc.wait_recv()
    # Symmetric SPMD: handle i's recv slots count the equal-shaped chunks
    # arriving from peer me-1-i. Consume chunk-major — the issue order —
    # so each round's waits release as the round lands.
    for j in range(len(spans)):
        for h in handles:
            h.wait_recv_chunk(j)
    shmem.quiet(*spl_descs, *handles)


def fast_all_to_all(
    tokens: jax.Array,
    splits: jax.Array,
    *,
    meta: jax.Array | None = None,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
) -> tuple[jax.Array, jax.Array] | tuple[jax.Array, jax.Array, jax.Array]:
    """Exchange padded token slabs between all PEs of `axis` (call inside
    ``jax.shard_map``; ≙ ``fast_all_to_all``, low_latency_all_to_all.py:189).
    Degrades to the golden :func:`_fast_all_to_all_xla` when the fused
    kernel cannot run in this environment (resilience layer,
    docs/resilience.md).

    tokens: ``[n, max_m, hidden]`` — slab ``p`` holds the ``splits[p]``
    tokens this PE sends to PE ``p`` (rows beyond the count are padding).
    splits: ``[n]`` int32 valid counts.
    meta: optional ``[n, K]`` int32 per-slab metadata (e.g. per-row expert
    ids, bitcast routing weights). It rides the *existing* splits put —
    the reference folds routing metadata into the same transport for the
    same reason (its scale tensor travels with the data,
    low_latency_all_to_all.py:94-104) — so attaching metadata costs zero
    extra DMAs, kernel launches, or barriers.

    Returns ``(recv, recv_splits[, recv_meta])``: slab ``j`` of ``recv``
    holds the tokens PE ``j`` sent here (``recv_splits[j]`` valid rows).
    Golden: ``jax.lax.all_to_all`` over the slab dim.
    """
    return resilience.guarded_call(
        "fast_all_to_all",
        _fast_all_to_all_fused,
        _fast_all_to_all_xla,
        tokens, splits, meta=meta, axis=axis, config=config,
        interpret=interpret,
    )


def _fast_all_to_all_fused(
    tokens: jax.Array,
    splits: jax.Array,
    *,
    meta: jax.Array | None = None,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
):
    cfg = config or A2AConfig()
    n = _axis_size((axis))
    n_slabs, max_m, hidden = tokens.shape
    assert n_slabs == n, (n_slabs, n)
    chunks = max(1, min(cfg.puts_per_slab, max_m))
    splits = splits.reshape(n, 1).astype(jnp.int32)
    payload = splits
    if meta is not None:
        assert meta.shape[0] == n, (meta.shape, n)
        payload = jnp.concatenate(
            [splits, meta.reshape(n, -1).astype(jnp.int32)], axis=1
        )
    w = payload.shape[1]
    if n == 1:
        if meta is None:
            return tokens, splits.reshape(n)
        return tokens, splits.reshape(n), meta
    from triton_dist_tpu.parallel.topology import is_dcn_axis_name as _is_dcn

    if _is_dcn(axis):
        # slice-crossing axis: remote DMA cannot reach across slices, so
        # the slab exchange lowers to XLA's all-to-all on DCN. The slab
        # contract (slab p → PE p, payload alongside) is identical, so
        # callers — including the hierarchical EP's outer phase — are
        # oblivious (≙ the reference's cross-node EP dispatch over IB,
        # ep_a2a.py:36-147).
        recv = jax.lax.all_to_all(tokens, axis, 0, 0, tiled=True)
        rpayload = jax.lax.all_to_all(payload, axis, 0, 0, tiled=True)
        rsplits = rpayload[:, 0]
        if meta is None:
            return recv, rsplits
        return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)
    n_steps = n - 1
    from triton_dist_tpu.ops.common import chunk_schedule

    spans = chunk_schedule(max_m, max(1, int(cfg.chunks_per_shard)))
    if len(spans) > 1:
        # chunk-granular schedule (per-(peer, chunk) slots + chunk-major
        # consumption); chunks_per_shard=1 falls through to the UNCHANGED
        # legacy kernel below, bit for bit. The sig slots are REGULAR:
        # only exercised under an armed watchdog (shmem contract).
        kernel = functools.partial(
            _a2a_chunked_kernel, axis=axis, n=n, spans=spans
        )
        scratch = [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.DMA((n_steps, len(spans))),
            pltpu.SemaphoreType.REGULAR((n_steps, len(spans))),
            pltpu.SemaphoreType.DMA((n_steps,)),
            pltpu.SemaphoreType.DMA((n_steps,)),
        ]
    else:
        kernel = functools.partial(_a2a_kernel, axis=axis, n=n, chunks=chunks)
        scratch = [
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((n_steps, chunks)),
            pltpu.SemaphoreType.DMA((n_steps, chunks)),
            pltpu.SemaphoreType.DMA((n_steps,)),
            pltpu.SemaphoreType.DMA((n_steps,)),
        ]
    recv, rpayload = dist_pallas_call(
        kernel,
        name="fast_all_to_all",
        out_shape=(
            jax.ShapeDtypeStruct((n, max_m, hidden), tokens.dtype),
            jax.ShapeDtypeStruct((n, w), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(tokens, payload)
    rsplits = rpayload[:, 0]
    if meta is None:
        return recv, rsplits
    return recv, rsplits, rpayload[:, 1:].reshape(meta.shape)


def all_to_all_post_process(
    recv: jax.Array, recv_splits: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Compact the padded recv slabs to the front (≙ ``all_to_all_post_process``,
    low_latency_all_to_all.py:251). Returns ``(packed, total)`` where
    ``packed[:total]`` are the valid tokens in slab order (rows after that
    are zero); shapes stay static as jit requires."""
    n, max_m, hidden = recv.shape
    flat = recv.reshape(n * max_m, hidden)
    slab = jnp.arange(n * max_m) // max_m
    pos = jnp.arange(n * max_m) % max_m
    valid = pos < recv_splits[slab]
    # Stable sort by target position (padding keys to the back): valid rows
    # land densely at the front in slab order.
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(recv_splits)[:-1]])
    keys = jnp.where(valid, offsets[slab] + pos, n * max_m)
    order = jnp.argsort(keys, stable=True)
    packed = jnp.where(valid[order][:, None], flat[order], 0)
    return packed, jnp.sum(recv_splits)


def _fast_all_to_all_op_xla(
    tokens: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    **_,
) -> tuple[jax.Array, jax.Array]:
    """Op-level golden: the same shard_map entry serving XLA's all-to-all
    (identical slab contract, so callers are oblivious to the downgrade)."""
    if mesh.shape[axis] == 1:
        return tokens, splits.astype(jnp.int32)

    def wrapped(t, s):
        r, rs = _fast_all_to_all_xla(t[0], s[0], axis=axis)
        return r[None], rs[None]

    return jit_shard_map(
        wrapped, mesh,
        (P(axis, None, None, None), P(axis, None)),
        (P(axis, None, None, None), P(axis, None)),
        key=("fast_all_to_all_xla", axis),
    )(tokens, splits.astype(jnp.int32))


def fast_all_to_all_op(
    tokens: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "tp",
    config: A2AConfig | None = None,
    interpret: Any = None,
) -> tuple[jax.Array, jax.Array]:
    """Host-level entry: `tokens` ``[n, n, max_m, hidden]`` (dim 0 = owning
    PE, dim 1 = destination slab) and `splits` ``[n, n]``, both sharded on
    dim 0. Returns the exchanged slabs/splits in the same layout."""
    if mesh.shape[axis] == 1:
        # world-1 all-to-all IS the identity: no kernel, no copy
        return tokens, splits.astype(jnp.int32)
    fn = functools.partial(
        fast_all_to_all, axis=axis, config=config, interpret=interpret
    )

    def wrapped(t, s):
        r, rs = fn(t[0], s[0])
        return r[None], rs[None]

    return jit_shard_map(
        wrapped, mesh,
        (P(axis, None, None, None), P(axis, None)),
        (P(axis, None, None, None), P(axis, None)),
        key=("fast_all_to_all", axis, config, str(interpret)),
    )(tokens, splits.astype(jnp.int32))


# FIRST entry = best-known default (one put per peer is latency-optimal
# for the dispatch headline shape; applied sweep-free under cached_or_first).
# chunks_per_shard axis (ISSUE 4): chunk-granular schedules AFTER every
# chunk=1 candidate, so the sweep-free walks can never apply one untimed
# and a sweep only crowns one that beats the legacy leader by the paired
# margin — the tuner cannot regress (the PR 3 ordering invariant).
A2A_TUNE_SPACE = (
    A2AConfig(1),
    A2AConfig(2),
    A2AConfig(4),
    A2AConfig(chunks_per_shard=2),
    A2AConfig(chunks_per_shard=4),
)


def _a2a_chunk_sensible(cfg, tokens, splits, mesh, *, axis: str = "tp", **_):
    """Shape guard wiring the perf model into the walk (ISSUE 4
    satellite): chunked candidates the model calls dominated for this slab
    size are never timed (nor applied by a sweep-free walk); chunk=1
    candidates always survive (prune_chunk_candidates keeps the legacy
    anchor by construction)."""
    from triton_dist_tpu import perf_model

    if getattr(cfg, "chunks_per_shard", 1) <= 1:
        return True
    slab_bytes = (
        int(tokens.shape[-2]) * int(tokens.shape[-1]) * tokens.dtype.itemsize
    )
    return bool(
        perf_model.prune_chunk_candidates(
            (cfg,), slab_bytes, int(mesh.shape[axis]),
            suggest=perf_model.suggest_a2a_chunks_per_shard,
        )
    )


fast_all_to_all_op = contextual_autotune(
    A2A_TUNE_SPACE, name="fast_all_to_all", precondition=_a2a_chunk_sensible
)(fast_all_to_all_op)
# guard OUTSIDE the autotuner: the sweep still prices failing candidates;
# only a failure of the whole tuned entry degrades to the XLA golden
fast_all_to_all_op = resilience.guard_op(
    "fast_all_to_all_op", _fast_all_to_all_op_xla
)(fast_all_to_all_op)
