"""AllGather kernel family (≙ reference ``kernels/nvidia/allgather.py``, 591 LoC).

The reference ships cp-engine push/pull, 1-D ring, NUMA-aware 2-D ring, and
inter-node variants, selected by ``get_auto_all_gather_method``
(allgather.py:44-69). The TPU-native set:

- ``ring_1d``        — unidirectional neighbor ring over ICI (≙ ring push
                       :138); bandwidth-optimal for ≥2 chips, n-1 hops.
- ``ring_bidir``     — bidirectional ring: both ICI directions carry
                       traffic, halving latency (the TPU analogue of the
                       reference's 2-D NUMA ring :194 — both exist to use
                       more links simultaneously).
- ``full_mesh_push`` — every PE puts its shard directly to every peer
                       (≙ full-mesh push :79). On TPU non-neighbor RDMA is
                       hardware-routed; best for small latency-bound sizes.

Pull variants (:104) are impossible on TPU (no remote loads — see
``shmem.device.getmem_nbi_block``) and are covered by push symmetry.
All kernels are HBM-resident: chunks move HBM→HBM over ICI without staging
through VMEM, so arbitrarily large gathers work.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu import resilience
from triton_dist_tpu.ops.common import chunk_schedule, dist_pallas_call, jit_shard_map
from triton_dist_tpu.parallel import topology
from triton_dist_tpu.shmem import device as shmem
from triton_dist_tpu.utils import axis_size as _axis_size


def _all_gather_xla(x: jax.Array, *, axis="tp", **_) -> jax.Array:
    """The golden slow path (the same program every fused method is tested
    against): XLA's all-gather, single- or multi-axis."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jax.lax.all_gather(x, axes, tiled=True)


def _is_dcn(axis) -> bool:
    """Whether this mesh axis crosses TPU slice boundaries (DCN, not ICI):
    declared via ``config.dcn_axes`` or auto-detected at mesh creation."""
    return topology.is_dcn_axis_name(axis)


def get_auto_all_gather_method(
    chunk_bytes: int, n_pes: int, devices: Any = None
) -> str:
    """Topology/size-based method choice (≙ ``get_auto_all_gather_method``,
    reference allgather.py:44-69, which keys on NVLink-fullmesh/NUMA).
    `devices` — the mesh-axis devices (``topology.axis_devices``) — enables
    physical wrap detection from their torus coords."""
    from triton_dist_tpu.perf_model import direct_vs_ring_crossover_bytes

    if n_pes <= 2:
        return "ring_1d"
    if not topology.has_wraparound(n_pes, devices):
        # a line topology: a ring's wrap hop would route the long way
        return "full_mesh_push"
    # model-driven crossover (ring SOL vs routed-put SOL; tracks ICI BW)
    if chunk_bytes <= direct_vs_ring_crossover_bytes(n_pes):
        return "full_mesh_push"
    return "ring_bidir"


def _ring_1d_kernel(x_ref, out_ref, copy_sem, send_sems, recv_sems, *, axis: str, n: int):
    me = shmem.my_pe(axis)
    m = x_ref.shape[0]
    # Local shard into its slot, then barrier so every PE's out buffer is
    # live before remote writes land (≙ local_copy_and_barrier_all,
    # reference allgather_gemm.py:100-116).
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    # race shaking (no-op unless config.debug_comm_delay): per-PE skew of
    # barrier entry + DMA issue
    shmem.comm_jitter(axis, salt=1)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    descs = []
    for s in range(n - 1):
        c = jax.lax.rem(me - s + n, n)
        if s > 0:
            descs[s - 1].wait_recv()  # chunk c arrived during step s-1
        sl = pl.ds(c * m, m)
        descs.append(
            shmem.putmem_nbi_block(
                out_ref.at[sl], out_ref.at[sl], right, axis, send_sems.at[s], recv_sems.at[s]
            )
        )
    descs[-1].wait_recv()
    shmem.quiet(*descs)


def _ring_bidir_kernel(
    x_ref, out_ref, copy_sem, send_r, recv_r, send_l, recv_l, *, axis: str, n: int
):
    me = shmem.my_pe(axis)
    m = x_ref.shape[0]
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter(axis, salt=2)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)
    steps_r = (n - 1 + 1) // 2  # chunks travelling rightward
    steps_l = (n - 1) // 2      # chunks travelling leftward
    descs_r, descs_l = [], []
    for s in range(max(steps_r, steps_l)):
        if s < steps_r:
            c = jax.lax.rem(me - s + n, n)
            if s > 0:
                descs_r[s - 1].wait_recv()
            sl = pl.ds(c * m, m)
            descs_r.append(
                shmem.putmem_nbi_block(
                    out_ref.at[sl], out_ref.at[sl], right, axis, send_r.at[s], recv_r.at[s]
                )
            )
        if s < steps_l:
            c = jax.lax.rem(me + s, n)
            if s > 0:
                descs_l[s - 1].wait_recv()
            sl = pl.ds(c * m, m)
            descs_l.append(
                shmem.putmem_nbi_block(
                    out_ref.at[sl], out_ref.at[sl], left, axis, send_l.at[s], recv_l.at[s]
                )
            )
    descs_r[-1].wait_recv()
    if descs_l:
        descs_l[-1].wait_recv()
    shmem.quiet(*descs_r, *descs_l)


def _ring_1d_chunked_kernel(
    x_ref, out_ref, copy_sem, send_sems, recv_sems, sig_sems,
    *, axis: str, n: int, spans,
):
    """Chunk-granular 1-D ring (ISSUE 3 tentpole): each ring-step shard is
    `len(spans)` independent chunk DMAs, and step ``s`` forwards chunk ``j``
    the moment chunk ``j`` of step ``s-1`` lands — so the per-hop exposed
    latency is one *chunk*, not one shard (wormhole pipelining; the chunk=1
    schedule is exactly :func:`_ring_1d_kernel` and is dispatched there)."""
    me = shmem.my_pe(axis)
    m = x_ref.shape[0]
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter(axis, salt=1)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    descs = []
    for s in range(n - 1):
        c = jax.lax.rem(me - s + n, n)
        base = c * m
        # step s's INCOMING chunk is the left neighbor's send: shard
        # (me-1-s) mod n — the landing view for payload integrity
        # (canary checksums + payload-fault injection, ISSUE 8)
        base_in = jax.lax.rem(me - 1 - s + 2 * n, n) * m
        ready = None
        if s > 0:
            prev = descs[s - 1]
            ready = prev.wait_recv_chunk  # chunk j arrived during step s-1
        descs.append(
            shmem.putmem_signal_chunked_nbi_block(
                lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                right, axis,
                lambda j, s=s: send_sems.at[s, j],
                lambda j, s=s: recv_sems.at[s, j],
                lambda j, s=s: sig_sems.at[s, j],
                spans, ready=ready,
                recv_view=lambda off, rows, b=base_in: out_ref.at[
                    pl.ds(b + off, rows)
                ],
            )
        )
    descs[-1].wait_recv()
    shmem.quiet(*descs)


def _ring_bidir_chunked_kernel(
    x_ref, out_ref, copy_sem, send_r, recv_r, sig_r, send_l, recv_l, sig_l,
    *, axis: str, n: int, spans,
):
    """Chunk-granular bidirectional ring: both directions run the chunked
    forward-on-arrival schedule of :func:`_ring_1d_chunked_kernel`."""
    me = shmem.my_pe(axis)
    m = x_ref.shape[0]
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter(axis, salt=2)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)
    steps_r = (n - 1 + 1) // 2
    steps_l = (n - 1) // 2
    descs_r, descs_l = [], []
    for s in range(max(steps_r, steps_l)):
        if s < steps_r:
            c = jax.lax.rem(me - s + n, n)
            base = c * m
            # incoming right-moving chunk: the left neighbor's step-s
            # send, shard (me-1-s) mod n (landing view, ISSUE 8)
            base_in = jax.lax.rem(me - 1 - s + 2 * n, n) * m
            ready = descs_r[s - 1].wait_recv_chunk if s > 0 else None
            descs_r.append(
                shmem.putmem_signal_chunked_nbi_block(
                    lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                    lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                    right, axis,
                    lambda j, s=s: send_r.at[s, j],
                    lambda j, s=s: recv_r.at[s, j],
                    lambda j, s=s: sig_r.at[s, j],
                    spans, ready=ready,
                    recv_view=lambda off, rows, b=base_in: out_ref.at[
                        pl.ds(b + off, rows)
                    ],
                )
            )
        if s < steps_l:
            c = jax.lax.rem(me + s, n)
            base = c * m
            # incoming left-moving chunk: the right neighbor's step-s
            # send, shard (me+1+s) mod n (landing view, ISSUE 8)
            base_in = jax.lax.rem(me + 1 + s, n) * m
            ready = descs_l[s - 1].wait_recv_chunk if s > 0 else None
            descs_l.append(
                shmem.putmem_signal_chunked_nbi_block(
                    lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                    lambda off, rows, base=base: out_ref.at[pl.ds(base + off, rows)],
                    left, axis,
                    lambda j, s=s: send_l.at[s, j],
                    lambda j, s=s: recv_l.at[s, j],
                    lambda j, s=s: sig_l.at[s, j],
                    spans, ready=ready,
                    recv_view=lambda off, rows, b=base_in: out_ref.at[
                        pl.ds(b + off, rows)
                    ],
                )
            )
    descs_r[-1].wait_recv()
    if descs_l:
        descs_l[-1].wait_recv()
    shmem.quiet(*descs_r, *descs_l)


def _full_mesh_push_kernel(x_ref, out_ref, copy_sem, send_sems, recv_sems, *, axis: str, n: int):
    me = shmem.my_pe(axis)
    m = x_ref.shape[0]
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter(axis, salt=3)
    shmem.barrier_all(axis)
    my_sl = pl.ds(me * m, m)
    descs = []
    for d in range(1, n):
        dst = jax.lax.rem(me + d, n)
        descs.append(
            shmem.putmem_nbi_block(
                out_ref.at[my_sl], out_ref.at[my_sl], dst, axis,
                send_sems.at[d - 1], recv_sems.at[d - 1],
            )
        )
    # Symmetric SPMD: peer (me - d) sends me an equal-sized chunk tracked by
    # my recv_sems[d-1], so waiting on our own descriptors waits for all
    # incoming chunks too.
    for desc in descs:
        desc.wait_recv()
    shmem.quiet(*descs)


def _ring_2d_kernel(
    x_ref, out_ref, copy_sem, in_send, in_recv, out_send, out_recv,
    *, outer: str, inner: str, n_o: int, n_i: int,
):
    """Fused hierarchical 2-D ring allgather (≙ the reference's NUMA-aware /
    inter-node 2-D rings, allgather.py:194,291 and the device 2-D
    dissemination producer :377): an inner-axis ring gathers this PE's row
    while every chunk is forwarded along the outer axis the moment it lands,
    so outer-axis hops ride the ICI concurrently with inner-axis hops —
    per-segment pipelining, not phase-staged.

    Global slot layout matches ``jax.lax.all_gather(x, (outer, inner))``:
    chunk of PE (o, i) at rows ``[(o*n_i+i)*m, +m)``.

    Outer-round semantics: round ``t`` carries row ``me_o - t``; senders and
    receivers agree on the (t, s) semaphore slot because all PEs of an outer
    ring share the same inner coordinate (chunk order ``c = me_i - s``).
    """
    me_i = shmem.my_pe(inner)
    me_o = shmem.my_pe(outer)
    m = x_ref.shape[0]

    def slot(o, i):
        return pl.ds((o * n_i + i) * m, m)

    local = pltpu.make_async_copy(x_ref, out_ref.at[slot(me_o, me_i)], copy_sem)
    local.start()
    local.wait()
    shmem.comm_jitter((outer, inner), salt=4)
    shmem.barrier_all((outer, inner))

    right_i = jax.lax.rem(me_i + 1, n_i)
    down_o = jax.lax.rem(me_o + 1, n_o)
    descs_i = []
    descs_o = [[None] * n_i for _ in range(n_o - 1)]

    # Inner ring over own row; each chunk is forwarded outer-wards (round 0)
    # as soon as it is locally available.
    for s in range(n_i):
        c = jax.lax.rem(me_i - s + n_i, n_i)
        if s > 0:
            descs_i[s - 1].wait_recv()  # chunk (me_o, c) landed during s-1
        sl = slot(me_o, c)
        if s < n_i - 1:
            descs_i.append(
                shmem.putmem_nbi_block(
                    out_ref.at[sl], out_ref.at[sl], right_i, inner,
                    in_send.at[s], in_recv.at[s],
                )
            )
        if n_o > 1:
            descs_o[0][s] = shmem.putmem_nbi_block(
                out_ref.at[sl], out_ref.at[sl], down_o, outer,
                out_send.at[0, s], out_recv.at[0, s],
            )

    # Outer forwarding rounds: round t receives row me_o - t chunk by chunk
    # and (except the last round) forwards each chunk onward immediately.
    for t in range(1, n_o):
        row = jax.lax.rem(me_o - t + n_o, n_o)
        for s in range(n_i):
            c = jax.lax.rem(me_i - s + n_i, n_i)
            descs_o[t - 1][s].wait_recv()  # chunk (row, c) landed
            if t < n_o - 1:
                sl = slot(row, c)
                descs_o[t][s] = shmem.putmem_nbi_block(
                    out_ref.at[sl], out_ref.at[sl], down_o, outer,
                    out_send.at[t, s], out_recv.at[t, s],
                )
    shmem.quiet(*descs_i, *(d for row_d in descs_o for d in row_d if d is not None))


_KERNELS = {
    "ring_1d": (_ring_1d_kernel, 1),
    "ring_bidir": (_ring_bidir_kernel, 2),
    "full_mesh_push": (_full_mesh_push_kernel, 1),
}

# chunk-granular variants (ISSUE 3): ring methods only — full_mesh_push is
# a single hardware-routed hop per peer, so chunking buys no cross-hop
# pipelining there (chunks_per_shard is ignored for it, as for DCN/XLA
# fallbacks)
_CHUNKED_KERNELS = {
    "ring_1d": (_ring_1d_chunked_kernel, 1),
    "ring_bidir": (_ring_bidir_chunked_kernel, 2),
}


def all_gather_2d(
    x: jax.Array,
    *,
    axes: tuple[str, str],
    interpret: Any = None,
) -> jax.Array:
    return resilience.guarded_call(
        "all_gather_2d",
        _all_gather_2d_fused,
        functools.partial(_all_gather_xla, axis=tuple(axes)),
        x, axes=axes, interpret=interpret,
    )


def _all_gather_2d_fused(
    x: jax.Array,
    *,
    axes: tuple[str, str],
    interpret: Any = None,
) -> jax.Array:
    """Hierarchical allgather over two mesh axes ``(outer, inner)`` — the
    multi-axis composition VERDICT r1 called for (≙ 2-D rings, reference
    allgather.py:194,291). Call inside ``jax.shard_map``; golden:
    ``jax.lax.all_gather(x, axes, tiled=True)``.

    Map `inner` to the fastest/most-wraparound-rich ICI axis and `outer` to
    the slower axis (second torus dim, or the DCN axis of a multi-slice
    mesh): the inner ring then carries n_i-1 small hops while outer hops
    stream concurrently."""
    outer, inner = axes
    n_o = _axis_size((outer))
    n_i = _axis_size((inner))
    if n_o == 1:
        return all_gather(x, axis=inner, interpret=interpret)
    if n_i == 1:
        return all_gather(x, axis=outer, interpret=interpret)
    orig_shape = x.shape
    if x.ndim == 1:
        x = x.reshape(x.shape[0], 1)
    m = x.shape[0]
    out_shape = (n_o * n_i * m, *x.shape[1:])
    out = dist_pallas_call(
        functools.partial(
            _ring_2d_kernel, outer=outer, inner=inner, n_o=n_o, n_i=n_i
        ),
        name="all_gather_ring_2d",
        out_shape=jax.ShapeDtypeStruct(out_shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n_i - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n_i - 1, 1),)),
            pltpu.SemaphoreType.DMA((n_o - 1, n_i)),
            pltpu.SemaphoreType.DMA((n_o - 1, n_i)),
        ],
        interpret=interpret,
    )(x)
    if len(orig_shape) == 1:
        out = out.reshape(out_shape[0])
    return out


def all_gather(x: jax.Array, *, axis: str = "tp", method: str = "auto", interpret: Any = None, devices: Any = None, chunks_per_shard: int = 1) -> jax.Array:
    """Gather shards along mesh `axis` (call inside ``jax.shard_map``).

    `x` is this PE's shard ``(m, ...)``; returns ``(n*m, ...)`` with shard i
    at rows ``[i*m, (i+1)*m)``. Golden reference:
    ``jax.lax.all_gather(x, axis, tiled=True)`` — served automatically when
    the fused kernel cannot run in this environment (resilience layer,
    docs/resilience.md).

    ``chunks_per_shard > 1`` splits every ring-step payload into that many
    per-chunk DMAs forwarded the moment each lands (chunk-granular overlap,
    ISSUE 3); 1 (default) is the legacy shard-granular schedule, bit for
    bit. Ring methods only — ignored by full_mesh_push and the DCN/XLA
    paths.
    """
    return resilience.guarded_call(
        "all_gather",
        _all_gather_fused,
        _all_gather_xla,
        x, axis=axis, method=method, interpret=interpret, devices=devices,
        chunks_per_shard=chunks_per_shard,
    )


def _all_gather_fused(x: jax.Array, *, axis: str = "tp", method: str = "auto", interpret: Any = None, devices: Any = None, chunks_per_shard: int = 1) -> jax.Array:
    if isinstance(axis, (tuple, list)):
        if len(axis) == 1:
            axis = axis[0]
        elif method != "auto":
            raise ValueError(
                f"multi-axis all_gather always uses the ring hierarchy; got "
                f"method={method!r} (only 'auto' is valid with >1 axis)"
            )
        else:
            # N-D (≙ the reference's 3-D node×numa×gpu push hierarchy,
            # low_latency_allgather.py:401): fused 2-D ring over the two
            # INNERMOST axes, then staged gathers outward — each outer hop
            # streams a block the inner hierarchy already assembled, and
            # the outermost-major concat order matches
            # jax.lax.all_gather(x, axes, tiled=True). A DCN axis (slice
            # boundary: no ICI path, remote DMA cannot reach — see
            # config.dcn_axes) is never fused into the 2-D ring; it peels
            # off to the single-axis path below, which lowers it to the
            # XLA collective (≙ the reference's internode
            # nvshmemx_putmem_signal stage, allgather.py:291-375 — here
            # XLA owns the DCN transport).
            axes = tuple(axis)
            if len(axes) >= 2 and not _is_dcn(axes[-1]) and not _is_dcn(axes[-2]):
                # the fused 2-D ring keeps shard granularity (its inner ring
                # already pipelines per-segment across the outer axis)
                out = all_gather_2d(x, axes=axes[-2:], interpret=interpret)
                rest = axes[:-2]
            else:
                out = all_gather(
                    x, axis=axes[-1], interpret=interpret,
                    chunks_per_shard=chunks_per_shard,
                )
                rest = axes[:-1]
            for a in reversed(rest):
                out = all_gather(
                    out, axis=a, interpret=interpret,
                    chunks_per_shard=chunks_per_shard,
                )
            return out
    n = _axis_size((axis))
    if n == 1:
        return x
    if _is_dcn(axis):
        # slice-crossing axis: XLA's all-gather rides DCN; the fused
        # remote-DMA kernels are ICI-only by construction
        return jax.lax.all_gather(x, axis, tiled=True)
    orig_shape = x.shape
    if x.ndim == 1:
        x = x.reshape(x.shape[0], 1)
    if method == "auto":
        method = get_auto_all_gather_method(
            x.size * x.dtype.itemsize, n, devices
        )
    m = x.shape[0]
    out_shape = (n * m, *x.shape[1:])
    n_steps = max(1, n - 1)
    chunks = max(1, int(chunks_per_shard))
    spans = chunk_schedule(m, chunks)
    if len(spans) > 1 and method in _CHUNKED_KERNELS:
        kernel_fn, n_sem_pairs = _CHUNKED_KERNELS[method]
        kernel = functools.partial(kernel_fn, axis=axis, n=n, spans=spans)
        name = f"all_gather_{method}"  # same family: never runs concurrently
        # per-(step, chunk) DMA sem pairs + the pure chunk-signal slots
        # (REGULAR; only exercised under an armed watchdog — see
        # shmem.putmem_signal_chunked_nbi_block)
        scratch = [pltpu.SemaphoreType.DMA(())]
        for _ in range(n_sem_pairs):
            scratch += [
                pltpu.SemaphoreType.DMA((n_steps, len(spans))),
                pltpu.SemaphoreType.DMA((n_steps, len(spans))),
                pltpu.SemaphoreType.REGULAR((n_steps, len(spans))),
            ]
    else:
        kernel_fn, n_sem_pairs = _KERNELS[method]
        kernel = functools.partial(kernel_fn, axis=axis, n=n)
        name = f"all_gather_{method}"
        scratch = [pltpu.SemaphoreType.DMA(())]
        for _ in range(n_sem_pairs):
            scratch += [pltpu.SemaphoreType.DMA((n_steps,)), pltpu.SemaphoreType.DMA((n_steps,))]
    out = dist_pallas_call(
        kernel,
        name=name,
        out_shape=jax.ShapeDtypeStruct(out_shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        interpret=interpret,
    )(x)
    if len(orig_shape) == 1:
        out = out.reshape(n * orig_shape[0])
    return out


def _all_gather_op_xla(
    x: jax.Array, mesh: Mesh, *, axis: str = "tp", **_
) -> jax.Array:
    """Op-level golden: the same shard_map entry serving XLA's all-gather."""
    in_spec = P(axis, *([None] * (x.ndim - 1)))
    out_spec = P(*([None] * x.ndim))
    return jit_shard_map(
        functools.partial(_all_gather_xla, axis=axis), mesh, in_spec, out_spec,
        key=("all_gather_xla", axis),
    )(x)


@resilience.guard_op("all_gather_op", _all_gather_op_xla)
def all_gather_op(
    x: jax.Array, mesh: Mesh, *, axis: str = "tp", method: str = "auto", interpret: Any = None, chunks_per_shard: int = 1
) -> jax.Array:
    """Convenience wrapper applying shard_map over `mesh` for a global array
    sharded on dim 0 (≙ the host-level ``ag_gemm``-style entry points)."""
    fn = functools.partial(
        all_gather, axis=axis, method=method, interpret=interpret,
        devices=topology.axis_devices(mesh, axis),
        chunks_per_shard=chunks_per_shard,
    )
    in_spec = P(axis, *([None] * (x.ndim - 1)))
    out_spec = P(*([None] * x.ndim))
    return jit_shard_map(
        fn, mesh, in_spec, out_spec,
        key=("all_gather", axis, method, str(interpret), chunks_per_shard),
    )(x)
