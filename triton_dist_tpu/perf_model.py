"""Roofline perf models for TPU chips
(≙ reference ``kernels/nvidia/gemm_perf_model.py`` (237 LoC) and
``comm_perf_model.py`` (106 LoC)).

The reference keeps tensor-core TFLOPS tables keyed by device name and NIC
bandwidth discovered from sysfs, and uses ``estimate_gemm_sol_time_ms`` /
``estimate_reduce_scatter_time`` to budget SMs between GEMM and comm. The
TPU equivalents are per-generation MXU/HBM/ICI tables (public numbers) used
to (a) pick kernel methods by predicted comm time and (b) sanity-check
measured bench results against speed-of-light.
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float          # dense MXU peak
    int8_tops: float
    hbm_gbps: float             # HBM bandwidth, GB/s
    ici_gbps_per_link: float    # one direction, per link, GB/s
    ici_links: int              # torus links per chip
    vmem_mib: int
    # fp8_e4m3 MXU peak; 0 = the generation has no fp8 path (v4), and
    # pricing an fp8 candidate on it is a config error the models raise on
    fp8_tops: float = 0.0


# Public spec-sheet numbers (cloud.google.com/tpu/docs/system-architecture).
CHIP_SPECS = {
    "v4": ChipSpec("v4", 275, 275, 1228, 50, 6, 128, fp8_tops=0),
    "v5e": ChipSpec("v5e", 197, 394, 819, 50, 4, 128, fp8_tops=394),
    "v5p": ChipSpec("v5p", 459, 918, 2765, 100, 6, 128, fp8_tops=918),
    "v6e": ChipSpec("v6e", 918, 1836, 1640, 100, 4, 128, fp8_tops=1836),
}

_KIND_ALIASES = {
    "tpu v4": "v4",
    "tpu v5 lite": "v5e",
    "tpu v5e": "v5e",
    "tpu v5": "v5p",
    "tpu v5p": "v5p",
    "tpu v6 lite": "v6e",
    "tpu v6e": "v6e",
}


def detect_chip(default: str = "v5e") -> ChipSpec:
    """Map ``jax.devices()[0].device_kind`` to a spec (≙ the reference's
    pynvml device-name lookup, gemm_perf_model.py:14-60)."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return CHIP_SPECS[default]
    for alias, name in sorted(_KIND_ALIASES.items(), key=lambda kv: -len(kv[0])):
        if alias in kind:
            return CHIP_SPECS[name]
    return CHIP_SPECS[default]


def estimate_gemm_sol_time_ms(
    m: int, n: int, k: int, dtype_bytes: int = 2, spec: ChipSpec | None = None
) -> float:
    """Speed-of-light GEMM time: max(compute roofline, memory roofline)
    (≙ ``estimate_gemm_sol_time_ms``, reference gemm_perf_model.py:233)."""
    spec = spec or detect_chip()
    flops = 2.0 * m * n * k
    peak = (spec.int8_tops if dtype_bytes == 1 else spec.bf16_tflops) * 1e12
    t_compute = flops / peak
    bytes_moved = (m * k + k * n + m * n) * dtype_bytes
    t_mem = bytes_moved / (spec.hbm_gbps * 1e9)
    return max(t_compute, t_mem) * 1e3


def estimate_ring_collective_time_ms(
    payload_bytes: int,
    n_pes: int,
    spec: ChipSpec | None = None,
    bidirectional: bool = True,
) -> float:
    """Ring allgather / reduce-scatter time over ICI: each PE moves
    ``payload * (n-1)/n`` bytes through its link(s)
    (≙ ``estimate_reduce_scatter_time``, comm_perf_model.py:91)."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    ici = spec.ici_gbps_per_link * 1e9 * (2 if bidirectional else 1)
    return payload_bytes * (n_pes - 1) / n_pes / ici * 1e3


def estimate_dcn_collective_time_ms(
    payload_bytes: int, n_slices: int
) -> float:
    """Inter-slice (DCN) collective time for `payload_bytes` moved by THIS
    stage: ring formula over the per-host DCN NIC (topology.DCN_GBPS)."""
    from triton_dist_tpu.parallel.topology import DCN_GBPS

    if n_slices <= 1:
        return 0.0
    return payload_bytes * (n_slices - 1) / n_slices / (DCN_GBPS * 1e9) * 1e3


def estimate_hierarchical_collective_time_ms(
    payload_bytes: int,
    n_inner: int,
    n_slices: int,
    kind: str = "ag",
    spec: ChipSpec | None = None,
) -> float:
    """(dcn, ici) composed collective: ICI ring inside each slice + DCN
    hop between slices, with each stage billed only the bytes IT moves
    (≙ the reference's inter-node stage after the intra-node pipeline,
    reduce_scatter.py:525-560):

    - ``kind="ag"``: `payload_bytes` = the FULL gathered size. The ICI
      stage assembles each slice's 1/n_slices portion; the DCN stage then
      shares the full payload across slices.
    - ``kind="rs"``: `payload_bytes` = one PE's full partial array. The
      ICI stage reduce-scatters it slice-locally; only the 1/n_inner
      pre-reduced part crosses DCN.

    The two stages pipeline poorly in the XLA schedule (the DCN
    collective consumes the whole ICI result), so the estimate is their
    sum — a deliberate upper bound."""
    if kind == "ag":
        t_ici = estimate_ring_collective_time_ms(
            payload_bytes // max(n_slices, 1), n_inner, spec
        )
        t_dcn = estimate_dcn_collective_time_ms(payload_bytes, n_slices)
    elif kind == "rs":
        t_ici = estimate_ring_collective_time_ms(payload_bytes, n_inner, spec)
        t_dcn = estimate_dcn_collective_time_ms(
            payload_bytes // max(n_inner, 1), n_slices
        )
    else:
        raise ValueError(f"kind must be 'ag' or 'rs', got {kind!r}")
    return t_ici + t_dcn


def estimate_all_to_all_time_ms(
    slab_bytes: int, n_pes: int, spec: ChipSpec | None = None
) -> float:
    """All-to-all: each PE injects ``(n-1) * slab`` bytes; on a 1-D torus
    bisection limits throughput to ~2 links each way."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    inject = slab_bytes * (n_pes - 1)
    return inject / (2 * spec.ici_gbps_per_link * 1e9) * 1e3


# Per-hop ICI latency ballpark (public "How to Scale Your Model" order of
# magnitude; exact value only shifts the crossover linearly).
ICI_HOP_LATENCY_MS = 1e-3


def estimate_ag_ring_time_ms(
    chunk_bytes: int, n_pes: int, spec: ChipSpec | None = None
) -> float:
    """Store-and-forward neighbor ring: (n-1) dependent hops, each paying
    per-hop latency plus the chunk's wire time over the bidirectional link
    pair."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    per_hop = ICI_HOP_LATENCY_MS + chunk_bytes / (2 * spec.ici_gbps_per_link * 1e9) * 1e3
    return (n_pes - 1) * per_hop


def estimate_ring_chunked_time_ms(
    shard_bytes: int,
    n_pes: int,
    chunks_per_shard: int = 1,
    spec: ChipSpec | None = None,
) -> float:
    """Chunk-pipelined store-and-forward ring (ISSUE 3): each ring-step
    shard moves as ``chunks_per_shard`` independent DMAs forwarded the
    moment they land, so the ``n-2`` intermediate hops hide behind the
    chunk stream and the total is ``(n - 2 + chunks)`` stages of one chunk
    each (the classic wormhole pipeline). ``chunks=1`` reduces exactly to
    :func:`estimate_ag_ring_time_ms` — the shard-granular schedule this
    model must stay honest against."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    chunks = max(1, int(chunks_per_shard))
    per_stage = ICI_HOP_LATENCY_MS + (
        shard_bytes / chunks
    ) / (2 * spec.ici_gbps_per_link * 1e9) * 1e3
    return (n_pes - 2 + chunks) * per_stage


def estimate_fused_ring_bubble_ms(
    shard_bytes: int,
    n_pes: int,
    chunks_per_shard: int = 1,
    spec: ChipSpec | None = None,
) -> float:
    """Exposed (non-overlappable) comm bubble of a fused ring op whose MXU
    work dominates: at each of the ``n-1`` hops the MXU stalls only until
    the FIRST chunk of the next shard lands ≈ one chunk's latency + wire
    time, not one shard's — the per-chunk bubble term the chunk-granular
    schedules exist to shrink (ISSUE 3). With ``chunks=1`` this is the
    shard-granular bubble the legacy schedules expose."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    chunks = max(1, int(chunks_per_shard))
    chunk_wire = (shard_bytes / chunks) / (
        2 * spec.ici_gbps_per_link * 1e9
    ) * 1e3
    return (n_pes - 1) * (ICI_HOP_LATENCY_MS + chunk_wire)


def suggest_chunks_per_shard(
    shard_bytes: int,
    n_pes: int,
    spec: ChipSpec | None = None,
    max_chunks: int = 16,
) -> int:
    """Model-driven ``chunks_per_shard`` pick: the power-of-two chunk count
    minimizing :func:`estimate_ring_chunked_time_ms` (more chunks pipeline
    hops but pay one per-chunk latency each; tiny shards want 1). A hint
    for the autotune spaces and the docs' sizing guidance, not a binding
    choice — the tuner still times the real schedules."""
    if n_pes <= 2:
        return 1
    best, best_t = 1, float("inf")
    c = 1
    while c <= max_chunks:
        t = estimate_ring_chunked_time_ms(shard_bytes, n_pes, c, spec)
        if t < best_t:
            best, best_t = c, t
        c *= 2
    return best


def estimate_a2a_chunked_time_ms(
    slab_bytes: int,
    n_pes: int,
    chunks_per_shard: int = 1,
    spec: ChipSpec | None = None,
) -> float:
    """Chunk-granular padded-slab all-to-all (ISSUE 4): every PE still
    injects ``(n-1) * slab`` bytes through ~2 engaged link pairs, but the
    transfer is issued as ``chunks_per_shard`` rounds of per-peer chunk
    DMAs (chunk-major, ``shmem.putmem_signal_chunked_a2a_nbi_block``) —
    each extra round pays one descriptor/hop latency while the wire time
    stays the injection total. ``chunks=1`` reduces exactly to
    :func:`estimate_all_to_all_time_ms` plus the single hop latency — the
    shard-granular schedule this model must stay honest against."""
    if n_pes <= 1:
        return 0.0
    chunks = max(1, int(chunks_per_shard))
    return chunks * ICI_HOP_LATENCY_MS + estimate_all_to_all_time_ms(
        slab_bytes, n_pes, spec
    )


def estimate_a2a_chunk_bubble_ms(
    slab_bytes: int,
    n_pes: int,
    chunks_per_shard: int = 1,
    spec: ChipSpec | None = None,
) -> float:
    """Exposed dispatch bubble of the chunk-granular EP pipeline: a
    chunk-consuming group-GEMM stalls only until the FIRST chunk of a
    peer's slab lands ≈ one hop latency + one chunk's wire time, not one
    slab's — the term the chunked a2a exists to shrink (the a2a analogue
    of :func:`estimate_fused_ring_bubble_ms`). ``chunks=1`` is the
    whole-slab bubble the legacy schedule exposes."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    chunks = max(1, int(chunks_per_shard))
    chunk_wire = (slab_bytes / chunks) / (
        2 * spec.ici_gbps_per_link * 1e9
    ) * 1e3
    return ICI_HOP_LATENCY_MS + chunk_wire


def suggest_a2a_chunks_per_shard(
    slab_bytes: int,
    n_pes: int,
    spec: ChipSpec | None = None,
    max_chunks: int = 8,
) -> int:
    """Model-driven ``chunks_per_shard`` pick for the a2a/EP family: the
    power-of-two count minimizing completion + exposed bubble
    (``C·lat + wire + lat + slab/C/bw`` — more chunks shrink the
    consumer's first-chunk wait but pay one issue latency each; tiny
    slabs want 1). A hint for tune-space pruning
    (:func:`prune_chunk_candidates`), not a binding choice — the tuner
    still times the real schedules."""
    if n_pes <= 1:
        return 1
    best, best_t = 1, float("inf")
    c = 1
    while c <= max_chunks:
        t = estimate_a2a_chunked_time_ms(
            slab_bytes, n_pes, c, spec
        ) + estimate_a2a_chunk_bubble_ms(slab_bytes, n_pes, c, spec)
        if t < best_t:
            best, best_t = c, t
        c *= 2
    return best


def prune_chunk_candidates(
    space,
    shard_bytes: int,
    n_pes: int,
    spec: ChipSpec | None = None,
    suggest=None,
):
    """Tune-space pruning hook (ISSUE 4 satellite): filter chunked
    candidates the model calls obviously dominated for this problem —
    every chunked candidate when the suggester says 1 (per-chunk latency
    swamps the pipelining), otherwise counts beyond 2× the suggestion
    (past the optimum the extra rounds only add latency). ``chunk=1``
    candidates ALWAYS survive, in their original positions, so the
    no-regression ordering invariant (every chunk=1 candidate before any
    chunked one) is preserved by construction and the sweep-free walks
    keep their proven legacy leader.

    `suggest` defaults to the ring model
    (:func:`suggest_chunks_per_shard`); a2a spaces pass
    :func:`suggest_a2a_chunks_per_shard`."""
    suggest = suggest or suggest_chunks_per_shard
    s = int(suggest(shard_bytes, n_pes, spec))
    return tuple(
        cfg for cfg in space
        if getattr(cfg, "chunks_per_shard", 1) <= 1
        or (s > 1 and getattr(cfg, "chunks_per_shard", 1) <= 2 * s)
    )


def estimate_w8_overlap_time_ms(
    shard_bytes: int,
    n_pes: int,
    weight_bytes: int = 0,
    chunks_per_shard: int = 1,
    w8: bool = False,
    fp8: bool = False,
    spec: ChipSpec | None = None,
) -> float:
    """Fused AG-GroupGEMM / MoE-Reduce-RS overlap time model with the
    weight-traffic term (ISSUE 7): the chunked ring term
    (:func:`estimate_ring_chunked_time_ms` — the activation slabs ride the
    ICI) plus the weight-side HBM stream (``weight_bytes`` — the bf16 bank
    bytes, read once per pipeline pass regardless of how few rows route:
    the decode regime's bound resource). ``w8=True`` HALVES the weight
    term (int8 weights; the f32 scale rows are ``1/K`` of the bank —
    noise) and touches nothing else: weights are local, so w8 adds no
    ring/chunk edges.

    ``fp8=True`` (ISSUE 19) QUARTERS the weight term instead — the
    float8_e4m3 slabs stream one byte per bf16-pair element and the
    quarter-rate bank read is the whole point of the second operand
    format; mutually exclusive with ``w8``, and pricing it on a chip
    generation without an fp8 MXU path (``spec.fp8_tops == 0``, v4)
    raises rather than returning a time for hardware that can't run it.

    ``w8=False`` (and ``fp8=False``) reduces EXACTLY to the existing
    chunked ring model plus the full-rate weight term (and with
    ``weight_bytes=0`` to the ring model alone) — the honesty contract
    the unit tests pin. A deliberate sum (upper bound): on chip the
    weight stream partially hides under the ring chunks; the model exists
    to rank chunk/w8/fp8 candidates, not to predict absolutes."""
    spec = spec or detect_chip()
    if w8 and fp8:
        raise ValueError("w8 and fp8 are exclusive operand formats")
    if fp8 and not spec.fp8_tops:
        raise ValueError(
            f"chip {spec.name!r} has no fp8 MXU rate (fp8_tops=0) — an "
            f"fp8 candidate cannot be priced for it"
        )
    t_ring = estimate_ring_chunked_time_ms(
        shard_bytes, n_pes, chunks_per_shard, spec
    )
    if fp8:
        wb = weight_bytes / 4.0
    elif w8:
        wb = weight_bytes / 2.0
    else:
        wb = float(weight_bytes)
    return t_ring + wb / (spec.hbm_gbps * 1e9) * 1e3


def estimate_span_policy_time_ms(
    policy: str,
    shard_bytes: int,
    n_pes: int,
    chunks_per_shard: int = 1,
    spec: ChipSpec | None = None,
) -> float:
    """Ranking cost term for a span-schedule policy (ISSUE 14): completion
    time of the chunk-pipelined ring PLUS the exposed per-hop first-chunk
    bubble — the quantity the synthesized schedules exist to move. Used by
    ``synth/admit.py`` to order admitted candidates within a family (and
    recorded in the admission report); ``contextual_autotune`` still times
    the real schedules, this model only ranks.

    Per-policy terms, each with an honest reduction contract:

    - ``"contig"``: :func:`estimate_ring_chunked_time_ms` +
      :func:`estimate_fused_ring_bubble_ms` — the legacy model, unchanged.
    - ``"window"``: same completion (same total bytes, same stage count),
      but the bubble's chunk fraction is the SMALLEST span of the
      geometric tiling (weight ``1 / (2^chunks - 1)``) instead of
      ``1/chunks``. ``chunks=1`` reduces exactly to ``contig``.
    - ``"interleave"``: identical to ``contig`` — a pure issue-order
      permutation moves no bytes and adds no stages; its win (the
      consumer's inward drain order) is not priced by this wire model,
      which is exactly why only a timed sweep may crown it.
    - ``"torus2d"``: ``contig`` with the chunk count scaled by the inner
      dimension of ``topology.torus_factor(n_pes)``. A line world
      (inner 1) reduces exactly to ``contig``.
    """
    spec = spec or detect_chip()
    chunks = max(1, int(chunks_per_shard))
    if policy == "torus2d":
        from triton_dist_tpu.parallel.topology import torus_factor

        chunks *= torus_factor(max(1, n_pes))[1]
        policy = "contig"
    t = estimate_ring_chunked_time_ms(shard_bytes, n_pes, chunks, spec)
    if policy == "window" and chunks > 1:
        if n_pes <= 1:
            return t
        frac = 1.0 / ((1 << chunks) - 1)
        chunk_wire = shard_bytes * frac / (
            2 * spec.ici_gbps_per_link * 1e9
        ) * 1e3
        return t + (n_pes - 1) * (ICI_HOP_LATENCY_MS + chunk_wire)
    if policy in ("contig", "interleave", "window"):
        return t + estimate_fused_ring_bubble_ms(
            shard_bytes, n_pes, chunks, spec
        )
    raise ValueError(f"unknown span policy {policy!r}")


def suggest_w8_overlap(
    t_rows: int,
    n_experts: int,
    spec: ChipSpec | None = None,
    threshold: float = 1.0,
) -> bool:
    """Model-driven precondition for the w8 tune axis (ISSUE 7): True when
    the grouped GEMM is WEIGHT-BOUND — the bf16 weight stream
    (``E·K·N·2`` bytes, read whatever the routing) takes longer than the
    MXU work (``2·t·K·N`` flops). The K·N factors cancel, so the predicate
    is purely ``n_experts · (peak_flops / hbm_Bps) > threshold · t_rows``
    — decode-shaped problems (few hundred rows) qualify, prefill/training
    shapes (tens of thousands) never do: there the upcast VPU cost buys
    nothing, and the pruning hook keeps the sweep-free walks off it. bf16
    candidates are never subject to this hook — pruning can only remove
    w8 candidates."""
    spec = spec or detect_chip()
    if t_rows <= 0:
        return True
    balance = spec.bf16_tflops * 1e12 / (spec.hbm_gbps * 1e9)
    return n_experts * balance > threshold * t_rows


def estimate_group_gemm_pad_tax(
    t_rows: int,
    n_experts: int,
    block_m: int,
    panel_rows: int = 128,
    counts=None,
) -> float:
    """Fraction of the padded grouped-GEMM's MXU work a ragged schedule
    recovers (ISSUE 5).

    The padded grid computes the alignment's STATIC worst case —
    ``round_up(t + E·(block_m−1), block_m)`` rows, every block a full
    ``block_m``-row tile whatever its live count (that static slack, not
    the expected per-expert padding, is the measured ~25% MoE tax at the
    bench shape: 20480 computed rows for 16384 real ones at block_m=512).
    The ragged schedule computes each expert's rows quantized UP to the
    MXU row panel (``min(panel_rows, block_m)``) plus nothing else.
    Returns ``(padded_rows − ragged_rows) / padded_rows`` — the share of
    MXU time that is pure pad; the predicted throughput recovery is
    ``1 / (1 − tax)``.

    `counts` (per-expert row counts, any array-like) makes the ragged term
    exact; without it the expected ``E·(panel−1)/2`` padding is used.
    Divisible shapes (every count a block_m multiple AND t_rows absorbing
    the worst-case slack) drive the tax toward zero — the precondition
    :func:`suggest_ragged` exists to detect."""
    from triton_dist_tpu.utils import round_up

    if t_rows <= 0 or n_experts <= 0 or block_m <= 0:
        return 0.0
    panel = max(1, min(panel_rows, block_m))
    padded_rows = round_up(t_rows + n_experts * (block_m - 1), block_m)
    if counts is not None:
        ragged_rows = int(sum(round_up(int(c), panel) for c in counts))
    else:
        ragged_rows = t_rows + (n_experts * (panel - 1)) // 2
    ragged_rows = min(ragged_rows, padded_rows)
    return max(0.0, (padded_rows - ragged_rows) / padded_rows)


def suggest_ragged(
    t_rows: int,
    n_experts: int,
    block_m: int,
    panel_rows: int = 128,
    counts=None,
    threshold: float = 0.02,
) -> bool:
    """Model-driven precondition for the ragged tune axis (ISSUE 5): True
    when the padding tax :func:`estimate_group_gemm_pad_tax` would recover
    exceeds `threshold` — i.e. when ragged can actually help. Divisible
    shapes, or huge-t problems whose worst-case slack is a rounding error,
    return False so the sweep-free walks never pay the (tiny but nonzero)
    panel-loop overhead for nothing. Padded candidates are never subject
    to this hook — pruning can only remove ragged candidates."""
    return estimate_group_gemm_pad_tax(
        t_rows, n_experts, block_m, panel_rows, counts
    ) > threshold


def _mean_ring_distance(n_pes: int) -> float:
    """Exact mean shortest-path hops to the n-1 peers on a wrapped 1-D
    axis: mean over d in 1..n-1 of min(d, n-d)."""
    return sum(min(d, n_pes - d) for d in range(1, n_pes)) / (n_pes - 1)


def estimate_ag_push_time_ms(
    chunk_bytes: int, n_pes: int, spec: ChipSpec | None = None
) -> float:
    """Direct hardware-routed puts to every peer: one latency stage, but
    multi-hop packets share links — per-PE injected bytes are inflated by
    the mean route length across the 2 engaged links."""
    spec = spec or detect_chip()
    if n_pes <= 1:
        return 0.0
    avg_dist = _mean_ring_distance(n_pes)
    wire = chunk_bytes * (n_pes - 1) * avg_dist / (2 * spec.ici_gbps_per_link * 1e9) * 1e3
    return ICI_HOP_LATENCY_MS + wire


def direct_vs_ring_crossover_bytes(
    n_pes: int, spec: ChipSpec | None = None
) -> float:
    """Chunk size below which direct full-mesh puts beat the neighbor ring
    (allgather and reduce-scatter share this shape: same wire pattern,
    reversed direction). Solves ``estimate_ag_ring_time_ms ==
    estimate_ag_push_time_ms`` for the chunk size — the model-driven
    replacement for a fixed byte threshold (≙ the reference steering
    resources from its SOL models, gemm_perf_model.py:233,
    comm_perf_model.py:91). Scales linearly with ICI bandwidth: faster
    links amortize the ring's latency chain at larger payloads."""
    spec = spec or detect_chip()
    if n_pes <= 2:
        return float("inf")
    # (n-2)*lat == chunk*(n-1)/(2*ici) * (avg_dist - 1)  [wire-time delta]
    congestion = _mean_ring_distance(n_pes) - 1.0
    if congestion <= 0:
        # all peers one hop away (n == 3 wrapped): routed puts never
        # congest past a ring
        return float("inf")
    ici = 2 * spec.ici_gbps_per_link * 1e9
    return (n_pes - 2) * ICI_HOP_LATENCY_MS * 1e-3 * ici / ((n_pes - 1) * congestion)


def overlap_efficiency(t_fused_ms: float, t_compute_ms: float, t_comm_ms: float) -> float:
    """How much of the comm time the fused kernel hid:
    1.0 = perfect overlap (fused == max(comp, comm)), 0.0 = fully serial.
    The headline metric of the reference's charts (README.md:181-195)."""
    serial = t_compute_ms + t_comm_ms
    ideal = max(t_compute_ms, t_comm_ms)
    if serial <= ideal:
        return 1.0
    return max(0.0, min(1.0, (serial - t_fused_ms) / (serial - ideal)))


def estimate_spec_decode_gain(
    k: int,
    alpha: float,
    *,
    verify_cost_factor: float = 0.0625,
    draft_cost_factor: float = 0.125,
) -> float:
    """Expected tokens-per-step-unit gain of a speculative serving round
    over plain decode (ISSUE 20's break-even surface, Leviathan et al.
    2023 eq. 1 adapted to the serving cost model).

    A plain decode step emits 1 token per 1.0 step unit. A speculative
    round emits the accepted-prefix length plus the bonus token —
    ``E[tokens] = sum_{j=0..k-1} alpha^j`` under per-position acceptance
    probability ``alpha`` (the j-th draft survives only if all j before
    it did; the bonus token is the j=0 term) — and costs
    ``1 + verify_cost_factor*k + draft_cost_factor*k`` units (the
    :class:`~triton_dist_tpu.serving.speculative.SpecDecodeConfig` cost
    model the engine charges through ``virtual_step_s``). The gain is
    their ratio; > 1.0 means speculation wins at this (k, alpha).
    ``k=0`` (dormant) returns exactly 1.0 — the honesty contract: a
    disarmed config predicts no win."""
    if k < 0:
        raise ValueError(f"k must be >= 0, got {k}")
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"alpha must be in [0, 1], got {alpha}")
    if verify_cost_factor < 0 or draft_cost_factor < 0:
        raise ValueError("cost factors must be >= 0")
    if k == 0:
        return 1.0
    expected = sum(alpha ** j for j in range(k))
    cost = 1.0 + verify_cost_factor * k + draft_cost_factor * k
    return expected / cost
