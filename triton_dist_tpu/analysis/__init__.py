"""Static signal-protocol verification (ISSUE 10 tentpole).

The repo's fused kernels live and die by hand-maintained signal
disciplines: data-coupled recv semaphores, per-(step, chunk) signal slots,
residual drains, bounded waits with a shared site numbering. Until now the
only checkers were the Mosaic-interpreter race detector (jax >= 0.6, so it
SKIPs on older lines) and a handful of spy-traced ordering samples. This
package proves the protocol properties from the PROGRAM alone, GPUVerify
style (Betts et al., OOPSLA 2012), on any jax line, on CPU, with no
devices and no interpreter:

- :mod:`capture` — trace each kernel once per rank with recording shims of
  the ``shmem/device.py`` primitive surface (the
  ``tests/test_overlap_structure.py::_spy_comm`` monkeypatch seam,
  promoted to a first-class recording mode) and build its per-rank event
  trace with every SPMD peer expression resolved to a concrete rank;
- :mod:`verify` — check, for every rank of a given world: credit balance
  (every wait producible by matching puts/signals, every slot drained to
  zero at kernel exit), static deadlock freedom (no wait-without-producer,
  no circular wait), chunk-major issue order for the chunked a2a family,
  bounded-wait coverage against the ``resilience/sites.py`` numbering and
  the ``TELEM_SLOTS`` telemetry window, and landing-view (canary) coverage
  of the chunked put families;
- :mod:`defects` — seeded-defect harness: mutate captured traces (dropped
  wait, dropped/extra signal, swapped chunk issue order, missing drain)
  and require an actionable, site-numbered diagnosis for each;
- :mod:`sweep` — drive ``verify_family`` across every tune-space tuple of
  all seven kernel families at worlds {2, 4, 8} (the CLI is
  ``scripts/protocol_lint.py``).

See docs/analysis.md for the graph model, the checked invariants, and the
known limits.
"""

from triton_dist_tpu.analysis.capture import (
    CaptureError,
    WorldCapture,
    capture_world,
)
from triton_dist_tpu.analysis.verify import Report, verify_capture
from triton_dist_tpu.analysis.defects import DEFECTS, seed_defect
from triton_dist_tpu.analysis.sweep import (
    FAMILIES,
    family_tuples,
    run_sweep,
    verify_family,
)
