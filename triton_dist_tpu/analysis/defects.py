"""Seeded-defect harness (ISSUE 10 tentpole).

A verifier that has never seen a broken protocol proves nothing. This
module mutates CAPTURED graphs the way real emitter/schedule bugs would —
a dropped wait, a dropped or duplicated signal, a swapped chunk issue
order, a missing end-of-kernel drain — and the test/CI harness requires
``analysis/verify.py`` to flag every one with an actionable diagnosis that
names the afflicted slot or site (and to stay SILENT on the unmutated
twin: the zero-false-positive half of the contract).

Mutations operate on the captured event lists, not on live kernels: the
defect is injected exactly at the protocol layer the verifier reasons
about, so each seeded graph isolates one invariant.
"""

from __future__ import annotations

import copy
import dataclasses

from triton_dist_tpu.analysis import capture as C
from triton_dist_tpu.analysis.verify import _slot_name, verify_capture


@dataclasses.dataclass
class SeededDefect:
    """One mutated capture plus what the verifier must say about it."""

    name: str
    capture: C.WorldCapture
    expect_check: str      # the Finding.check that must appear
    expect_naming: str     # substring the diagnosis must contain


def _events(cap: C.WorldCapture, rank: int = 0) -> list[C.Event]:
    return cap.traces[rank].launches[-1].events


def _find_last(events, op, pred=lambda e: True) -> int:
    for i in range(len(events) - 1, -1, -1):
        if events[i].op == op and pred(events[i]):
            return i
    raise ValueError(f"capture has no {op!r} event to mutate")


def drop_wait(cap: C.WorldCapture) -> SeededDefect:
    """An emitter that forgets a consuming wait: the matching credit is
    never drained, so the slot ends the launch pre-satisfied."""
    cap = copy.deepcopy(cap)
    events = _events(cap)
    i = _find_last(events, C.WAIT, lambda e: e.slot[0] != "<barrier>")
    ev = events.pop(i)
    return SeededDefect(
        "dropped_wait", cap, "credit_balance", _slot_name(ev.slot)
    )


def drop_signal(cap: C.WorldCapture) -> SeededDefect:
    """A lost/never-emitted signal: the consumer's wait has no producer —
    the static form of the runtime hang the watchdog exists for."""
    cap = copy.deepcopy(cap)
    events = _events(cap)
    i = _find_last(events, C.SIGNAL, lambda e: e.slot[0] != "<barrier>")
    ev = events.pop(i)
    return SeededDefect(
        "dropped_signal", cap, "deadlock", _slot_name(ev.slot)
    )


def extra_signal(cap: C.WorldCapture) -> SeededDefect:
    """A double-issued signal (the dup_signal chaos kind, statically):
    one surplus credit survives the launch."""
    cap = copy.deepcopy(cap)
    events = _events(cap)
    i = _find_last(events, C.SIGNAL, lambda e: e.slot[0] != "<barrier>")
    events.insert(i, copy.deepcopy(events[i]))
    return SeededDefect(
        "extra_signal", cap, "credit_balance", _slot_name(events[i].slot)
    )


def swap_chunk_order(cap: C.WorldCapture) -> SeededDefect:
    """Chunk puts issued peer-major instead of chunk-major: numerically
    invisible (same credits), but it forfeits the first-chunk-latency
    contract of the chunked a2a — only the order check can see it."""
    cap = copy.deepcopy(cap)
    events = _events(cap)
    mark = next(
        (e for e in events
         if e.op == C.CHUNKED and e.meta.get("form") == "a2a"),
        None,
    )
    if mark is None or mark.meta["n_chunks"] < 2:
        # ValueError is the harness's "not applicable to this capture"
        # protocol (run_defect_suite moves on to the next candidate)
        raise ValueError("need a chunked (>1) a2a capture to swap order")
    puts = [i for i, e in enumerate(events) if e.op == C.PUT
            and e.meta.get("chunk_signal")]
    a, b = None, None
    for i in puts:
        for j in puts:
            if j > i and events[j].slot[1][-1] != events[i].slot[1][-1]:
                a, b = i, j
                break
        if a is not None:
            break
    events[a], events[b] = events[b], events[a]
    return SeededDefect(
        "swapped_chunk_order", cap, "chunk_order", "CHUNK-MAJOR"
    )


def drop_drain(cap: C.WorldCapture) -> SeededDefect:
    """A kernel that returns without draining a put's send semaphore
    (a missing quiet / wait_send): residue on the send slot."""
    cap = copy.deepcopy(cap)
    events = _events(cap)
    i = _find_last(events, C.WAIT_SEND)
    ev = events.pop(i)
    return SeededDefect(
        "missing_drain", cap, "credit_balance", _slot_name(ev.slot)
    )


DEFECTS = {
    "dropped_wait": drop_wait,
    "dropped_signal": drop_signal,
    "extra_signal": extra_signal,
    "swapped_chunk_order": swap_chunk_order,
    "missing_drain": drop_drain,
}


def seed_defect(cap: C.WorldCapture, kind: str) -> SeededDefect:
    return DEFECTS[kind](cap)


def run_defect_suite(
    captures: dict[str, C.WorldCapture], *,
    require_all: bool = True, notes: list[str] | None = None,
) -> list[str]:
    """Drive every defect kind against an applicable clean capture and
    return a list of failures (empty = the harness is green). ``captures``
    maps a descriptive key to a clean WorldCapture; defects pick the first
    capture they apply to. Three-way contract per defect: the clean twin
    verifies OK, the mutated graph is flagged with the expected check, and
    the diagnosis names the afflicted slot/site.

    ``require_all=False`` (a family-subset run whose pool cannot offer
    every defect a capture — e.g. no chunked a2a) downgrades "no
    applicable capture" from a failure to an entry in ``notes``; the full
    sweep keeps it a failure, so CI can never silently lose a defect."""
    failures: list[str] = []
    for kind, mutate in DEFECTS.items():
        seeded = None
        for key, cap in captures.items():
            try:
                seeded = mutate(cap)
            except ValueError:
                continue
            clean = verify_capture(cap)
            if not clean.ok:
                failures.append(
                    f"{kind}: clean twin {key} already fails: "
                    f"{clean.errors[0]}"
                )
                break
            rep = verify_capture(seeded.capture)
            hits = [f for f in rep.errors if f.check == seeded.expect_check]
            if not hits:
                failures.append(
                    f"{kind}: NOT flagged on {key} (errors: "
                    f"{[str(f) for f in rep.errors]})"
                )
            elif not any(seeded.expect_naming in f.message for f in hits):
                failures.append(
                    f"{kind}: diagnosis does not name "
                    f"{seeded.expect_naming!r}: {hits[0]}"
                )
            break
        if seeded is None:
            if require_all:
                failures.append(f"{kind}: no applicable capture offered")
            elif notes is not None:
                notes.append(
                    f"defect {kind} skipped: no applicable capture in "
                    f"this family subset"
                )
    return failures
