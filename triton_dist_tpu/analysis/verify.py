"""Static checks over a captured signal graph (ISSUE 10 tentpole).

Input: a :class:`~triton_dist_tpu.analysis.capture.WorldCapture` — one
deterministic per-rank event trace of one kernel tuple. The checks:

1. **Credit balance** — every semaphore slot that participates in the
   signal protocol (received a put/signal credit, or is a put's send side)
   drains to exactly zero on every rank by kernel exit: every wait's
   expected count was producible by matching puts/signals, and no residual
   credit can pre-satisfy the next launch's wait on the (persistent,
   per-collective_id) hardware semaphores — the residual-drain discipline
   the integrity canary depends on.
2. **Static deadlock freedom** — a greedy cross-rank schedule must retire
   every event. Greedy is exact here: slots are per-rank pools (no two
   ranks compete for one credit), every rank's trace is sequential, and
   advancing any rank only ever ADDS credits for others — so a stall is a
   real wait-without-producer / circular wait, and the report names each
   blocked rank's site, slot, and missing credits.
3. **Chunk-major issue order** — inside a chunked-a2a emission, every
   peer's chunk ``j`` must be issued before any peer's chunk ``j+1`` (the
   first-chunk-latency contract of
   ``shmem.putmem_signal_chunked_a2a_nbi_block``).
4. **Bounded-wait coverage** — every wait edge carries a
   ``watchdog.bounded_wait`` site; per launch, the sites are the dense
   ``0..n-1`` numbering of ``resilience/sites.py``; launches whose site
   count exceeds the ``TELEM_SLOTS`` telemetry window are reported (at
   runtime such sites only bump an overflow counter — the schedule is
   still sound, so this is a warning, not an error), UNLESS the family
   carries a reviewed ``sites.TELEM_SITE_WAIVERS`` ceiling — the
   per-launch site-window policy of ISSUE 12 — in which case the
   overflow is an accepted diagnostic posture counted in
   ``stats["telem_waived"]``; outgrowing the waived ceiling warns again.
5. **Landing-view coverage** — chunk-signal puts that declare no
   ``recv_view=`` landing view get no payload canary. As of ISSUE 11 the
   gap set is empty (the fused MoE pipelines and the chunked
   ag_gemm/gemm_rs/reduce_scatter rings all declare views), so this is an
   ERROR: a new chunked family cannot land without opting into payload
   integrity (it was a tracked warning while the gap set was non-empty).

Local DMA chains (slots that never see a put/signal credit) are excluded
from the balance/deadlock model: their start/wait bookkeeping may sit
inside data-dependent compute branches, which the eager capture resolves
for one concrete input only. Every cross-rank edge in these kernels lives
at the unrolled comm level (the overlap-structure invariant), so the
protocol slots are always fully resolved.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict

from triton_dist_tpu.analysis import capture as C
from triton_dist_tpu.resilience import sites as S


@dataclasses.dataclass
class Finding:
    check: str       # "credit_balance" | "deadlock" | "chunk_order" | ...
    message: str

    def __str__(self):
        return f"[{self.check}] {self.message}"


@dataclasses.dataclass
class Report:
    family: str
    world: int
    label: str
    errors: list[Finding] = dataclasses.field(default_factory=list)
    warnings: list[Finding] = dataclasses.field(default_factory=list)
    stats: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.errors

    def summary(self) -> str:
        head = (
            f"{self.family}[{self.label}] world={self.world}: "
            f"{'OK' if self.ok else 'FAIL'} "
            f"(events={self.stats.get('events', 0)}, "
            f"slots={self.stats.get('protocol_slots', 0)}, "
            f"sites/launch={self.stats.get('max_sites', 0)})"
        )
        lines = [head]
        lines += [f"  ERROR {f}" for f in self.errors]
        lines += [f"  warn  {f}" for f in self.warnings]
        return "\n".join(lines)


def _slot_name(slot: tuple) -> str:
    return f"{slot[0]}{list(slot[1])}"


# ---------------------------------------------------------------------------
# The greedy cross-rank schedule (checks 1 + 2)
# ---------------------------------------------------------------------------

_BLOCKING = {C.WAIT, C.WAIT_RECV, C.WAIT_SEND, C.DMA_WAIT}


def _protocol_slots(cap: C.WorldCapture) -> set:
    """Slots the signal protocol owns: any slot credited by a put (recv
    side at the destination, send side at the issuer) or a pure signal.
    Everything else is a local DMA chain — excluded (module docstring)."""
    slots = set()
    for t in cap.traces:
        for l in t.launches:
            for e in l.events:
                if e.op == C.PUT:
                    slots.add(e.slot)
                    slots.add(_send_slot(e))
                elif e.op == C.SIGNAL:
                    slots.add(e.slot)
    return slots


def _send_slot(put_ev: C.Event) -> tuple:
    # the put's send-side slot rides in meta (see capture.putmem_nbi_block)
    return put_ev.meta["send_slot"]


def _launch_events(cap: C.WorldCapture, li: int) -> list[list[C.Event]]:
    return [t.launches[li].events for t in cap.traces]


def _simulate(cap: C.WorldCapture, li: int, report: Report) -> None:
    """Greedy retirement of launch ``li`` across all ranks; appends
    deadlock and credit-balance findings."""
    world = cap.world
    family = cap.traces[0].launches[li].family
    events = _launch_events(cap, li)
    protocol = _protocol_slots(cap)
    pools: dict[tuple, int] = defaultdict(int)  # (rank, slot) -> credits
    pcs = [0] * world

    def tracked(slot) -> bool:
        return slot in protocol

    def runnable(r: int):
        """Whether rank r's next event can retire; returns (ok, why)."""
        e = events[r][pcs[r]]
        if e.op in _BLOCKING and tracked(e.slot):
            need = e.value if e.op == C.WAIT else 1
            have = pools[(r, e.slot)]
            return have >= need, (
                f"{e.op} slot {_slot_name(e.slot)}"
                + (f" site {e.site}" if e.site is not None else "")
                + f" needs {need}, has {have}"
            )
        return True, ""

    def retire(r: int):
        e = events[r][pcs[r]]
        if e.op == C.PUT:
            pools[(e.dst, e.slot)] += 1           # data-coupled recv credit
            ss = _send_slot(e)
            if tracked(ss):
                pools[(r, ss)] += 1               # local send completion
        elif e.op == C.SIGNAL:
            pools[(e.dst, e.slot)] += e.value
        elif e.op == C.DMA_START and tracked(e.slot):
            pools[(r, e.slot)] += 1
        elif e.op in _BLOCKING and tracked(e.slot):
            pools[(r, e.slot)] -= e.value if e.op == C.WAIT else 1
        pcs[r] += 1

    progressed = True
    while progressed:
        progressed = False
        for r in range(world):
            while pcs[r] < len(events[r]):
                ok, _ = runnable(r)
                if not ok:
                    break
                retire(r)
                progressed = True

    stuck = [r for r in range(world) if pcs[r] < len(events[r])]
    if stuck:
        for r in stuck:
            _, why = runnable(r)
            report.errors.append(Finding(
                "deadlock",
                f"{family}: rank {r} blocked at event {pcs[r]} — {why}; "
                f"no matching producer can ever run "
                f"(wait-without-producer or circular wait)",
            ))
        return  # balance over a wedged schedule would double-report

    for (r, slot), credits in sorted(pools.items()):
        if credits != 0:
            what = "residual credit" if credits > 0 else "over-consumed"
            report.errors.append(Finding(
                "credit_balance",
                f"{family}: rank {r} slot {_slot_name(slot)} ends with "
                f"{credits:+d} ({what}) — the slot does not drain to zero "
                f"at kernel exit, so the next launch on this persistent "
                f"semaphore starts pre-{'satisfied' if credits > 0 else 'starved'}",
            ))


# ---------------------------------------------------------------------------
# Checks 3-5: order, site coverage, landing views
# ---------------------------------------------------------------------------

def _check_chunk_order(cap: C.WorldCapture, li: int, report: Report) -> None:
    for t in cap.traces:
        events = t.launches[li].events
        i = 0
        while i < len(events):
            e = events[i]
            if e.op == C.CHUNKED and e.meta.get("form") == "a2a":
                n_peers = e.meta["n_peers"]
                n_chunks = e.meta["n_chunks"]
                puts = []
                j = i + 1
                while j < len(events) and len(puts) < n_peers * n_chunks:
                    if events[j].op == C.PUT:
                        puts.append(events[j])
                    j += 1
                chunk_of = [p.slot[1][-1] for p in puts]
                if chunk_of != sorted(chunk_of):
                    first_bad = next(
                        k for k in range(1, len(chunk_of))
                        if chunk_of[k] < chunk_of[k - 1]
                    )
                    report.errors.append(Finding(
                        "chunk_order",
                        f"{t.launches[li].family}: rank {t.rank} issued "
                        f"chunk {chunk_of[first_bad]} of slot "
                        f"{_slot_name(puts[first_bad].slot)} after chunk "
                        f"{chunk_of[first_bad - 1]} — a2a puts must be "
                        f"CHUNK-MAJOR (every peer's chunk j before any "
                        f"chunk j+1)",
                    ))
                i = j
            else:
                i += 1


def _check_sites(cap: C.WorldCapture, li: int, report: Report) -> None:
    for t in cap.traces:
        l = t.launches[li]
        sites = [e.site for e in l.events if e.op == C.WAIT]
        kinds = [e.kind for e in l.events if e.op == C.WAIT]
        if any(s is None for s in sites):
            report.errors.append(Finding(
                "bounded_wait",
                f"{l.family}: rank {t.rank} has a wait edge with no "
                f"bounded_wait site — it would spin forever on a lost "
                f"signal with no diagnostic",
            ))
            continue
        if sites != list(range(len(sites))) or len(sites) != l.n_wait_sites:
            report.errors.append(Finding(
                "site_numbering",
                f"{l.family}: rank {t.rank} wait sites {sites} are not the "
                f"dense 0..{l.n_wait_sites - 1} numbering of "
                f"resilience/sites.py — diag records and telemetry rows "
                f"would name different waits",
            ))
        if any(k not in S.BOUNDED_KINDS for k in kinds):
            bad = [S.kind_name(k) for k in kinds if k not in S.BOUNDED_KINDS]
            report.errors.append(Finding(
                "bounded_wait",
                f"{l.family}: rank {t.rank} waits with non-bounded "
                f"kind(s) {bad}",
            ))
        if l.n_wait_sites > S.TELEM_SLOTS and t.rank == 0:
            # per-family site-window policy (resilience/sites.py): a
            # reviewed waiver accepts the overflow as a diagnostic
            # posture — counted in stats, not warned — while outgrowing
            # the waived ceiling surfaces as a fresh warning
            budget = S.telem_site_budget(l.family)
            if l.n_wait_sites <= budget:
                report.stats["telem_waived"] = (
                    report.stats.get("telem_waived", 0) + 1
                )
            else:
                report.warnings.append(Finding(
                    "telem_budget",
                    f"{l.family}: {l.n_wait_sites} wait sites per launch "
                    f"exceed the "
                    f"{'waived ceiling ' if budget > S.TELEM_SLOTS else ''}"
                    f"site budget {budget} "
                    f"(TELEM_SLOTS={S.TELEM_SLOTS} telemetry window) — "
                    f"sites past the window only bump the overflow header "
                    f"(obs/telemetry.py); spin attribution for them is "
                    f"lost",
                ))


def _check_landing_views(cap: C.WorldCapture, li: int, report: Report) -> None:
    t = cap.traces[0]
    l = t.launches[li]
    n_chunk_puts = sum(
        1 for e in l.events if e.op == C.PUT and e.meta.get("chunk_signal")
    )
    n_covered = sum(
        1 for e in l.events
        if e.op == C.PUT and e.meta.get("chunk_signal")
        and e.meta.get("landing_view")
    )
    if n_chunk_puts and n_covered < n_chunk_puts:
        report.errors.append(Finding(
            "landing_view",
            f"{l.family}: {n_chunk_puts - n_covered}/{n_chunk_puts} "
            f"chunk-signal puts declare no recv_view= landing view — the "
            f"payload canary (ISSUE 8) cannot cover them. The gap set was "
            f"closed in ISSUE 11; every chunked family must opt in "
            f"(declare the landing view, or reshape the protocol so the "
            f"consumer can name where the mirror chunk lands)",
        ))


def verify_capture(cap: C.WorldCapture) -> Report:
    report = Report(family=cap.family, world=cap.world, label=cap.label)
    n_launches = len(cap.traces[0].launches)
    for li in range(n_launches):
        fams = {t.launches[li].family for t in cap.traces}
        if len(fams) != 1:
            report.errors.append(Finding(
                "structure", f"launch {li} family differs across ranks: {fams}"
            ))
            continue
        _simulate(cap, li, report)
        _check_chunk_order(cap, li, report)
        _check_sites(cap, li, report)
        _check_landing_views(cap, li, report)
    report.stats = report.stats | {
        "events": sum(
            len(l.events) for t in cap.traces for l in t.launches
        ),
        "protocol_slots": len(_protocol_slots(cap)),
        "max_sites": max(
            (l.n_wait_sites for t in cap.traces for l in t.launches),
            default=0,
        ),
        "launches": n_launches,
    }
    return report
