"""Recording capture of a kernel's signal protocol (ISSUE 10 tentpole).

This is ``tests/test_overlap_structure.py::_spy_comm`` promoted into a
first-class recording mode: the ``shmem/device.py`` primitive surface is
replaced by shims that RECORD instead of issuing hardware ops, and the
kernel body runs once per rank as plain eager Python — no Pallas trace, no
interpreter, no devices — so it works on any jax line (this box's
jax 0.4.37 cannot even construct ``TPUCompilerParams(has_side_effects=)``,
let alone interpret a fused kernel).

How a capture runs (``capture_world``):

- ``config.update(timeout_iters=...)`` arms the watchdog posture for the
  duration, so the chunked put families issue their pure chunk signals and
  every wait funnels through the (shimmed) bounded-wait path, allocating
  the SAME trace-time site ordinals a real armed run would
  (``watchdog.KernelDiagScope.next_wait_site`` — the contract of
  ``resilience/sites.py``);
- ``dist_pallas_call`` is replaced per op module by a launcher that builds
  :class:`FakeRef` stand-ins for every input/output/scratch ref and calls
  the kernel body directly inside a ``watchdog.kernel_scope``;
- ``shmem.my_pe`` returns the CONCRETE rank under capture, so every SPMD
  peer expression (``jax.lax.rem(me - s + n, n)`` …) folds to a concrete
  integer — the "resolved symbolically per rank" of the issue;
- ``jax.lax.fori_loop`` / ``pl.when`` are replaced by eager Python
  equivalents (comm never lives inside them — the comm loops unroll in
  Python, the invariant the overlap-structure tests already rely on), and
  ``pltpu.make_async_copy`` / ``pltpu.emit_pipeline`` by recording fakes,
  so the whole body executes concretely;
- the semaphore slot of every put/signal/wait is identified by
  ``(ref position in the kernel signature, index tuple)`` — SPMD symmetry
  makes that key identical on every rank, which is exactly how the
  hardware's symmetric semaphore arrays work.

The result is a :class:`WorldCapture`: one deterministic event trace per
rank (two captures of the same tuple are byte-identical — pinned in
tests/test_analysis.py), the input of ``analysis/verify.py``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Any, Callable

import numpy as np

from triton_dist_tpu.resilience import sites as S

# Event kinds (the trace alphabet). Each event is one Event row below.
PUT = "put"            # one-sided put: credits recv slot at dst + send slot here
SIGNAL = "signal"      # pure semaphore increment at dst (chunk signals, ...)
WAIT = "wait"          # bounded wait (consumes `value` from a local slot)
WAIT_RECV = "wait_recv"  # DMA arrival wait on a put's recv slot (consumes 1)
WAIT_SEND = "wait_send"  # local send-completion wait (consumes 1)
DMA_START = "dma_start"  # local async copy issued (credits its sem slot)
DMA_WAIT = "dma_wait"    # local async copy waited (consumes 1)
CHUNKED = "chunked_put"  # marker: a chunked put family was emitted
# NOTE: barrier_all has no event kind of its own — the capture shim emits
# its dissemination rounds as targeted SIGNAL + bounded WAIT pairs on a
# shared "<barrier>" slot, which is faithful to the hardware (one barrier
# semaphore counter per PE, credits conserved across rounds — see the
# cross-invocation caveat on shmem.barrier_all) and lets the credit model
# reason about barrier reachability like any other slot.


@dataclasses.dataclass
class Event:
    """One protocol event in a rank's program order. ``slot`` is the
    semaphore identity ``(ref_name, index_tuple)``; ``dst`` the target
    rank of a put/signal; ``site`` the bounded-wait ordinal; ``kind`` the
    ``resilience/sites.py`` KIND_* of a wait; ``meta`` carries per-kind
    extras (chunk markers, landing-view declarations, row counts)."""

    op: str
    slot: tuple | None = None
    dst: int | None = None
    value: int = 1
    kind: int | None = None
    site: int | None = None
    meta: dict = dataclasses.field(default_factory=dict)

    def line(self) -> str:
        """Canonical one-line form (byte-identical captures compare on
        these)."""
        parts = [self.op]
        if self.slot is not None:
            parts.append(f"slot={self.slot[0]}{list(self.slot[1])}")
        if self.dst is not None:
            parts.append(f"dst={self.dst}")
        if self.value != 1:
            parts.append(f"value={self.value}")
        if self.kind is not None:
            parts.append(f"kind={S.kind_name(self.kind)}")
        if self.site is not None:
            parts.append(f"site={self.site}")
        for k in sorted(self.meta):
            parts.append(f"{k}={self.meta[k]}")
        return " ".join(parts)


@dataclasses.dataclass
class Launch:
    """One ``dist_pallas_call`` invocation on one rank."""

    family: str
    events: list[Event] = dataclasses.field(default_factory=list)
    n_wait_sites: int = 0


@dataclasses.dataclass
class RankTrace:
    rank: int
    launches: list[Launch] = dataclasses.field(default_factory=list)

    def lines(self) -> list[str]:
        out = []
        for l in self.launches:
            out.append(f"launch {l.family} sites={l.n_wait_sites}")
            out.extend("  " + e.line() for e in l.events)
        return out


@dataclasses.dataclass
class WorldCapture:
    """The verifier's input: one aligned trace per rank of one tuple."""

    family: str
    world: int
    label: str
    traces: list[RankTrace]

    def canonical(self) -> str:
        out = [f"family={self.family} world={self.world} label={self.label}"]
        for t in self.traces:
            out.append(f"rank {t.rank}")
            out.extend("  " + ln for ln in t.lines())
        return "\n".join(out) + "\n"


class CaptureError(RuntimeError):
    """The recording trace could not produce a usable protocol graph."""


# ---------------------------------------------------------------------------
# Fake refs / descriptors / handles
# ---------------------------------------------------------------------------

def _shape_dtype(spec) -> tuple[tuple, Any]:
    """Shape/dtype of an out_shape / scratch entry (ShapeDtypeStruct or
    pallas MemoryRef; semaphore dtypes fall back to int32)."""
    import jax.numpy as jnp

    shape = tuple(getattr(spec, "shape", ()))
    dtype = getattr(spec, "dtype", None)
    try:
        dtype = jnp.dtype(dtype)
    except TypeError:
        dtype = jnp.dtype(jnp.int32)  # semaphores
    return shape, dtype


def _resolve_index(i):
    """One index element → canonical key part. Concrete values fold to
    ints; pl.ds slices to ('ds', start, size); anything unresolvable
    (a traced value — only reachable inside local compute loops) to '?'."""
    if hasattr(i, "start") and hasattr(i, "size"):  # pallas Slice
        return ("ds", _resolve_index(i.start), int(i.size))
    if isinstance(i, slice):
        return ":"
    try:
        return int(i)
    except Exception:
        return "?"


class FakeRef:
    """Stand-in for a Pallas ref: knows shape/dtype/identity, serves zeros
    on read, swallows writes, and composes ``.at[...]`` views while
    recording the index path (semaphore slot identity)."""

    def __init__(self, shape, dtype, name, path=()):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.path = tuple(path)

    @property
    def ndim(self):
        return len(self.shape)

    def key(self) -> tuple:
        return (self.name, self.path)

    # --- view composition ---------------------------------------------
    def _view(self, idx) -> "FakeRef":
        if not isinstance(idx, tuple):
            idx = (idx,)
        shape = []
        parts = []
        dims = list(self.shape)
        for i in idx:
            if i is Ellipsis:
                # keep remaining dims (only ever used terminally here;
                # extend BEFORE recording the marker — parts indexes dims)
                shape.extend(dims[len(parts):])
                parts.append("...")
                return FakeRef(
                    shape, self.dtype, self.name, self.path + tuple(parts)
                )
            parts.append(_resolve_index(i))
            if isinstance(i, slice):
                d = dims[len(parts) - 1]
                start = 0 if i.start is None else int(i.start)
                stop = d if i.stop is None else int(i.stop)
                shape.append(stop - start)
            elif hasattr(i, "start") and hasattr(i, "size"):  # pl.ds Slice
                shape.append(int(i.size))
            else:
                pass  # integer (incl. 0-d array) index: dim dropped
        shape.extend(dims[len(parts):])
        return FakeRef(shape, self.dtype, self.name, self.path + tuple(parts))

    @property
    def at(self):
        ref = self

        class _At:
            def __getitem__(_, idx):
                return ref._view(idx)

        return _At()

    # --- data access (eager zeros; identity does not matter) -----------
    def __getitem__(self, idx):
        import jax.numpy as jnp

        view = self._view(idx)
        return jnp.zeros(view.shape, view.dtype)

    def __setitem__(self, idx, value):
        return None

    def __array__(self, dtype=None):
        return np.zeros(self.shape, dtype or self.dtype)

    def __repr__(self):
        return f"FakeRef({self.name}{list(self.path)}, {self.shape})"


class FakeDesc:
    """Recording stand-in for ``pltpu.make_async_copy``'s descriptor: a
    local DMA chain in the credit model (start credits its semaphore slot,
    wait consumes one). A ``.wait()`` with no local ``.start()`` on that
    slot consumes a REMOTE put's credit — the matching-byte-count recv
    idiom of the scatter kernels."""

    def __init__(self, state, src, dst, sem):
        self._state = state
        self._key = sem.key() if isinstance(sem, FakeRef) else ("<sem>", ())

    def start(self):
        self._state.record(Event(DMA_START, slot=self._key))

    def wait(self):
        self._state.record(Event(DMA_WAIT, slot=self._key))

    # PutHandle-compat spellings used by a few kernels
    wait_send = wait
    wait_recv = wait


# ---------------------------------------------------------------------------
# The capture state + shims
# ---------------------------------------------------------------------------

class _CaptureState:
    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world
        self.trace = RankTrace(rank)
        self._launch: Launch | None = None

    def record(self, ev: Event) -> Event:
        if self._launch is None:
            raise CaptureError(
                "shmem primitive recorded outside a dist_pallas_call launch"
            )
        self._launch.events.append(ev)
        return ev

    @contextlib.contextmanager
    def launch(self, family: str):
        from triton_dist_tpu.resilience import watchdog

        if self._launch is not None:
            raise CaptureError(f"nested kernel launch in capture: {family}")
        self._launch = Launch(family)
        try:
            with watchdog.kernel_scope(None, family) as scope:
                yield
            self._launch.n_wait_sites = scope._wait_sites
        finally:
            self.trace.launches.append(self._launch)
            self._launch = None


def _put_rows(dst_ref) -> int | None:
    if isinstance(dst_ref, FakeRef) and dst_ref.shape:
        return int(dst_ref.shape[0])
    return None


@contextlib.contextmanager
def capture_shims(state: _CaptureState, op_modules: list):
    """Install the recording shims around one rank's capture. Patches are
    name-based (each op module binds ``dist_pallas_call``/``_axis_size``
    at import) plus attribute-based on the ``shmem.device`` module object
    — the same two seams the spy tests use — and every patch is restored
    on exit, including the ``jax.lax.fori_loop`` / ``pl.when`` /
    ``pltpu.make_async_copy`` eager replacements."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from triton_dist_tpu import config as tdt_config
    from triton_dist_tpu import resilience
    from triton_dist_tpu.resilience import watchdog
    from triton_dist_tpu.shmem import device as shmem

    rank, world = state.rank, state.world

    # ---- shmem surface -------------------------------------------------
    def my_pe(axis):
        watchdog.register_pe(rank)
        return jnp.int32(rank)

    def n_pes(axis):
        return world

    class FakeHandle(shmem.PutHandle):
        # subclass so shmem.quiet / ChunkedPutHandle bookkeeping (which
        # isinstance-check and read .send_waited) treat it as the real thing
        def __init__(self, recv_key, send_key):
            self.desc = None
            self.send_waited = False
            self.sig_sem = None
            self._recv_key = recv_key
            self._send_key = send_key

        def wait_send(self):
            state.record(Event(WAIT_SEND, slot=self._send_key))
            self.send_waited = True

        def wait_recv(self):
            state.record(Event(WAIT_RECV, slot=self._recv_key))

        def wait(self):
            self.wait_send()
            self.wait_recv()

    def _sem_key(sem):
        if isinstance(sem, FakeRef):
            return sem.key()
        raise CaptureError(f"semaphore is not a captured ref: {sem!r}")

    def putmem_nbi_block(dst_ref, src_ref, pe, axis, send_sem, recv_sem):
        rk, sk = _sem_key(recv_sem), _sem_key(send_sem)
        state.record(Event(
            PUT, slot=rk, dst=int(pe),
            meta={"send_slot": sk, "rows": _put_rows(dst_ref)},
        ))
        return FakeHandle(rk, sk)

    def signal_op(sem, inc=1, pe=None, axis=None):
        state.record(Event(
            SIGNAL, slot=_sem_key(sem), value=int(inc),
            dst=rank if pe is None else int(pe),
        ))

    def _wait_or_watchdog(sem, value, kind):
        scope = watchdog.active()
        if scope is None:
            raise CaptureError("bounded wait outside a kernel scope")
        state.record(Event(
            WAIT, slot=_sem_key(sem), value=int(value), kind=int(kind),
            site=scope.next_wait_site(),
        ))

    def barrier_all(axis="tp"):
        n = world
        if n == 1:
            return
        scope = watchdog.active()
        # mirror the real dissemination barrier: one signal + one bounded
        # wait (site-numbered, KIND_BARRIER) per round, on a synthetic
        # per-launch slot shared by all ranks
        me = rank
        slot = ("<barrier>", ())
        for r in range(max(1, math.ceil(math.log2(n)))):
            partner = (me + (1 << r)) % n
            state.record(Event(SIGNAL, slot=slot, value=1, dst=partner))
            state.record(Event(
                WAIT, slot=slot, value=1, kind=S.KIND_BARRIER,
                site=scope.next_wait_site(),
            ))

    def barrier_neighbors(axis="tp"):
        n = world
        if n == 1:
            return
        scope = watchdog.active()
        slot = ("<barrier>", ())
        state.record(Event(SIGNAL, slot=slot, value=1, dst=(rank - 1) % n))
        state.record(Event(SIGNAL, slot=slot, value=1, dst=(rank + 1) % n))
        state.record(Event(
            WAIT, slot=slot, value=2, kind=S.KIND_BARRIER,
            site=scope.next_wait_site(),
        ))

    orig_chunked = shmem.putmem_signal_chunked_nbi_block
    orig_chunked_a2a = shmem.putmem_signal_chunked_a2a_nbi_block
    orig_signal2 = shmem.putmem_signal2_nbi_block

    def putmem_signal_chunked_nbi_block(
        dst_at, src_at, pe, axis, send_at, recv_at, sig_at, spans,
        ready=None, recv_view=None,
    ):
        state.record(Event(CHUNKED, meta={
            "form": "ring", "n_chunks": len(spans),
            "landing_view": recv_view is not None,
        }))
        return orig_chunked(
            dst_at, src_at, pe, axis, send_at, recv_at, sig_at, spans,
            ready=ready, recv_view=recv_view,
        )

    def putmem_signal_chunked_a2a_nbi_block(
        dst_at, src_at, peers, axis, send_at, recv_at, sig_at, spans,
        recv_view=None,
    ):
        state.record(Event(CHUNKED, meta={
            "form": "a2a", "n_peers": len(peers), "n_chunks": len(spans),
            "landing_view": recv_view is not None,
        }))
        return orig_chunked_a2a(
            dst_at, src_at, peers, axis, send_at, recv_at, sig_at, spans,
            recv_view=recv_view,
        )

    def putmem_signal2_nbi_block(
        dst_ref, src_ref, pe, axis, send_sem, recv_sem, sig_sem=None,
        canary=False,
    ):
        # delegate to the REAL protocol (which calls the patched
        # putmem/signal primitives), then annotate the put event with its
        # chunk-signal/landing-view declaration for the coverage check
        n_before = len(state._launch.events)
        h = orig_signal2(
            dst_ref, src_ref, pe, axis, send_sem, recv_sem, sig_sem, canary
        )
        for ev in state._launch.events[n_before:]:
            if ev.op == PUT:
                ev.meta["chunk_signal"] = sig_sem is not None
                ev.meta["landing_view"] = bool(canary)
        return h

    # ---- dist_pallas_call: invoke the kernel body on fake refs ---------
    def dist_pallas_call(
        kernel, *, name, out_shape, in_specs=None, out_specs=None,
        grid=None, grid_spec=None, scratch_shapes=(), **_kw,
    ):
        if grid is not None or grid_spec is not None:
            raise CaptureError(
                f"capture supports only grid-free comm kernels; "
                f"{name!r} uses a grid (grid kernels carry no signal "
                f"protocol — verify their host composition instead)"
            )

        def invoke(*args):
            single = not isinstance(out_shape, (tuple, list))
            outs = [out_shape] if single else list(out_shape)
            refs = []
            for i, a in enumerate(args):
                refs.append(FakeRef(a.shape, a.dtype, f"a{i}"))
            base = len(refs)
            for i, o in enumerate(outs):
                sh, dt = _shape_dtype(o)
                refs.append(FakeRef(sh, dt, f"a{base + i}"))
            base = len(refs)
            for i, s in enumerate(scratch_shapes):
                sh, dt = _shape_dtype(s)
                refs.append(FakeRef(sh, dt, f"a{base + i}"))
            with state.launch(name):
                kernel(*refs)
            res = tuple(jnp.zeros(*_shape_dtype(o)) for o in outs)
            return res[0] if single else res

        return invoke

    # ---- eager control flow / local DMA ---------------------------------
    def fori_loop(lower, upper, body, init, **_kw):
        val = init
        for i in range(int(lower), int(upper)):
            val = body(jnp.int32(i), val)
        return val

    def when(condition):
        def _wrapped(f):
            if bool(condition):
                f()

        return _wrapped

    def make_async_copy(src_ref, dst_ref, sem):
        return FakeDesc(state, src_ref, dst_ref, sem)

    def emit_pipeline(body, *, grid=None, in_specs=None, out_specs=None, **_kw):
        def run(*refs, **__kw):
            return None

        return run

    def guarded_call(family, primary, fallback, *args, **kwargs):
        # capture must see the FUSED protocol and fail loudly — a silent
        # golden fallback would verify an empty graph
        return primary(*args, **kwargs)

    def axis_index(axis):
        return jnp.int32(rank)

    # ---- install everything, restore on exit ---------------------------
    _MISSING = object()
    patches: list[tuple[Any, str, Any]] = []

    def patch(obj, attr, val):
        patches.append((obj, attr, getattr(obj, attr, _MISSING)))
        setattr(obj, attr, val)

    old_cfg = {
        "timeout_iters": tdt_config.get_config().timeout_iters,
        "fault_plan": tdt_config.get_config().fault_plan,
        "integrity": tdt_config.get_config().integrity,
        "debug_comm_delay": tdt_config.get_config().debug_comm_delay,
    }
    try:
        # armed-watchdog posture: chunk signals issued, waits bounded
        tdt_config.update(
            timeout_iters=1024, fault_plan=None, integrity=None,
            debug_comm_delay=0,
        )
        patch(shmem, "my_pe", my_pe)
        patch(shmem, "n_pes", n_pes)
        patch(shmem, "putmem_nbi_block", putmem_nbi_block)
        patch(shmem, "signal_op", signal_op)
        patch(shmem, "_wait_or_watchdog", _wait_or_watchdog)
        patch(shmem, "barrier_all", barrier_all)
        patch(shmem, "sync_all", barrier_all)  # module-load alias
        patch(shmem, "barrier_neighbors", barrier_neighbors)
        patch(shmem, "putmem_signal_chunked_nbi_block",
              putmem_signal_chunked_nbi_block)
        patch(shmem, "putmem_signal_chunked_a2a_nbi_block",
              putmem_signal_chunked_a2a_nbi_block)
        patch(shmem, "putmem_signal2_nbi_block", putmem_signal2_nbi_block)
        patch(resilience, "guarded_call", guarded_call)
        patch(jax.lax, "fori_loop", fori_loop)
        patch(jax.lax, "axis_index", axis_index)
        patch(pl, "when", when)
        patch(pltpu, "make_async_copy", make_async_copy)
        patch(pltpu, "emit_pipeline", emit_pipeline)
        if not hasattr(pltpu, "MemorySpace"):
            # jax lines before CompilerParams/MemorySpace: the fused MoE
            # entries name pltpu.MemorySpace.HBM in their BlockSpecs, which
            # the capture launcher ignores anyway — shim the namespace so
            # the entry's spec-building code runs (restored to absent)
            import types

            patch(pltpu, "MemorySpace", types.SimpleNamespace(
                HBM="hbm", ANY="any", SMEM="smem", VMEM="vmem"
            ))
        for mod in op_modules:
            if hasattr(mod, "dist_pallas_call"):
                patch(mod, "dist_pallas_call", dist_pallas_call)
            if hasattr(mod, "_axis_size"):
                patch(mod, "_axis_size", lambda axis, world=world: world)
        yield
    finally:
        for obj, attr, val in reversed(patches):
            if val is _MISSING:
                delattr(obj, attr)
            else:
                setattr(obj, attr, val)
        tdt_config.update(**old_cfg)


def capture_rank(
    fn: Callable, rank: int, world: int, op_modules: list
) -> RankTrace:
    """Run ``fn()`` (a shard-level kernel invocation closed over its
    inputs) under the recording shims as ``rank`` of ``world``."""
    state = _CaptureState(rank, world)
    with capture_shims(state, op_modules):
        fn()
    if not state.trace.launches:
        raise CaptureError(
            "capture recorded no kernel launch — the op served a "
            "non-fused path (check the config/world routing)"
        )
    return state.trace


def capture_world(
    make_fn: Callable[[int], Callable],
    world: int,
    op_modules: list,
    *,
    family: str,
    label: str = "",
) -> WorldCapture:
    """Capture all ``world`` ranks of one kernel tuple. ``make_fn(rank)``
    returns the zero-argument shard-level invocation for that rank (the
    same inputs on every rank — SPMD)."""
    traces = [
        capture_rank(make_fn(r), r, world, op_modules) for r in range(world)
    ]
    names = [tuple(l.family for l in t.launches) for t in traces]
    if len(set(names)) != 1:
        raise CaptureError(
            f"ranks traced different launch sequences (not SPMD?): {names}"
        )
    return WorldCapture(family=family, world=world, label=label, traces=traces)
