"""Tune-space sweep of the static protocol verifier (ISSUE 10 tentpole).

``verify_family`` captures + verifies ONE (family, world, tuple); ``sweep``
drives it across every tune-space tuple of all seven kernel families at
worlds {2, 4, 8} — the coverage the interpreter chaos tier can only sample
(and, on jax lines without the Mosaic interpreter, cannot run at all).
``scripts/protocol_lint.py`` is the CLI; tier-1 and chaos_matrix.sh gate
on it.

Family → tuple spaces:

- ``allgather``       — method {ring_1d, ring_bidir, full_mesh_push} ×
                        chunks_per_shard {1, 2, 4} (its method matrix IS
                        its tune space; full_mesh_push ignores chunking)
- ``reduce_scatter``  — ``RS_TUNE_SPACE`` (method × tiles × chunks)
- ``a2a``             — ``A2A_TUNE_SPACE``
- ``ag_gemm``         — ``AG_GEMM_TUNE_SPACE`` (the world-1 XLA sentinel
                        raises at n>1 by design and is skipped)
- ``gemm_rs``         — ``GEMM_RS_TUNE_SPACE`` × method {ring, scatter}
                        (sentinel skipped; chunking is a ring-only axis)
- ``ag_group_gemm``   — the fused AG-GroupGEMM overlap pipeline over the
                        union of ``AG_GROUP_GEMM_TUNE_SPACE`` and
                        ``TP_MOE_TUNE_SPACE`` (every legacy × chunked ×
                        ragged × w8 tuple the PR 7 emitter can produce;
                        the ragged_dot sentinel has no fused form)
- ``moe_reduce_rs``   — the fused MoE-Reduce-RS overlap pipeline over
                        ``MOE_RS_TUNE_SPACE`` ∪ ``TP_MOE_TUNE_SPACE``
- ``kv_stream``       — the disaggregated-serving KV handoff family
                        (ISSUE 13): ``KV_STREAM_TUNE_SPACE`` — wire
                        {native, int8-with-scales} × chunks {1, 2, 4}
                        mirror-pool exchange, every chunk a signal slot
                        with a declared landing view

Shapes are the smallest that still exercise every protocol arm (enough
rows for the largest chunk count, every expert populated, ≥2 blocks per
rank); the protocols under verification are shape-generic by construction
(chunk_schedule and the ring arithmetic are the same code at any size).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from triton_dist_tpu.analysis import capture as C
from triton_dist_tpu.analysis import defects as D
from triton_dist_tpu.analysis.verify import Finding, Report, verify_capture

WORLDS = (2, 4, 8)


@dataclasses.dataclass(frozen=True)
class FamilySpec:
    name: str
    module_names: tuple[str, ...]   # modules whose seams capture patches
    build: Callable                 # (world, tuple_spec) -> make_fn
    tuples: Callable                # (world) -> list[(label, tuple_spec)]


def _modules(spec: FamilySpec) -> list:
    import importlib

    return [importlib.import_module(m) for m in spec.module_names]


def _uniq(cfgs):
    return list(dict.fromkeys(cfgs))


# --- allgather --------------------------------------------------------------

def _ag_tuples(world):
    out = []
    for method in ("ring_1d", "ring_bidir", "full_mesh_push"):
        chunk_axis = (1, 2, 4) if method != "full_mesh_push" else (1,)
        for chunks in chunk_axis:
            out.append((f"{method}/c{chunks}", (method, chunks)))
    return out


def _ag_build(world, spec):
    import jax.numpy as jnp

    import importlib

    ag = importlib.import_module("triton_dist_tpu.ops.allgather")

    method, chunks = spec
    x = jnp.ones((8, 8), jnp.float32)

    def make_fn(rank):
        return lambda: ag._all_gather_fused(
            x, axis="tp", method=method, chunks_per_shard=chunks
        )

    return make_fn


# --- reduce_scatter ---------------------------------------------------------

def _rs_tuples(world):
    from triton_dist_tpu.ops.reduce_scatter import RS_TUNE_SPACE

    return [
        (f"{c.method}/bm{c.block_m}/c{c.chunks_per_shard}", c)
        for c in RS_TUNE_SPACE
    ]


def _rs_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    rs = importlib.import_module("triton_dist_tpu.ops.reduce_scatter")

    x = jnp.ones((world * 8, 8), jnp.float32)

    def make_fn(rank):
        return lambda: rs._reduce_scatter_fused(x, axis="tp", config=cfg)

    return make_fn


# --- a2a --------------------------------------------------------------------

def _a2a_tuples(world):
    from triton_dist_tpu.ops.all_to_all import A2A_TUNE_SPACE

    return [
        (f"p{c.puts_per_slab}/c{c.chunks_per_shard}", c)
        for c in A2A_TUNE_SPACE
    ]


def _a2a_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    a2a = importlib.import_module("triton_dist_tpu.ops.all_to_all")

    tokens = jnp.ones((world, 8, 8), jnp.float32)
    splits = jnp.ones((world,), jnp.int32)

    def make_fn(rank):
        return lambda: a2a._fast_all_to_all_fused(
            tokens, splits, axis="tp", config=cfg
        )

    return make_fn


# --- ag_gemm ----------------------------------------------------------------

def _ag_gemm_tuples(world):
    from triton_dist_tpu.ops.allgather_gemm import AG_GEMM_TUNE_SPACE

    return [
        (f"bm{c.block_m}/c{c.chunks_per_shard}", c)
        for c in AG_GEMM_TUNE_SPACE
        if c.block_m > 0  # the world-1 XLA-dot sentinel raises at n>1
    ]


def _ag_gemm_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    agg = importlib.import_module("triton_dist_tpu.ops.allgather_gemm")

    a = jnp.ones((16, 16), jnp.float32)
    b = jnp.ones((16, 16), jnp.float32)

    def make_fn(rank):
        return lambda: agg._ag_gemm_fused(a, b, axis="tp", config=cfg)

    return make_fn


# --- gemm_rs ----------------------------------------------------------------

def _gemm_rs_tuples(world):
    from triton_dist_tpu.ops.gemm_reduce_scatter import GEMM_RS_TUNE_SPACE

    out = []
    for c in GEMM_RS_TUNE_SPACE:
        if c.block_m == 0:
            continue  # world-1 XLA-dot sentinel
        out.append((f"ring/bm{c.block_m}/c{c.chunks_per_shard}", ("ring", c)))
        if c.chunks_per_shard == 1:
            # chunking is a ring-only axis; the scatter kernel's protocol
            # is chunk-independent, so one scatter tuple per tile config
            out.append((f"scatter/bm{c.block_m}", ("scatter", c)))
    return out


def _gemm_rs_build(world, spec):
    import jax.numpy as jnp

    import importlib

    grs = importlib.import_module("triton_dist_tpu.ops.gemm_reduce_scatter")

    method, cfg = spec
    a = jnp.ones((world * 8, 8), jnp.float32)
    b = jnp.ones((8, 16), jnp.float32)

    def make_fn(rank):
        return lambda: grs._gemm_rs_fused(
            a, b, axis="tp", method=method, config=cfg
        )

    return make_fn


# --- the two fused MoE overlap pipelines (ops/gg_pipeline.py) ---------------

_E, _TOPK = 4, 2


def _gg_cfgs(extra_space):
    from triton_dist_tpu.ops.grads import TP_MOE_TUNE_SPACE

    return _uniq([
        c for c in tuple(extra_space) + tuple(TP_MOE_TUNE_SPACE)
        if c.backend == "pallas"  # the ragged_dot sentinel has no fused form
    ])


def _gg_label(c):
    pol = getattr(c, "span_policy", "contig")
    return (
        f"bm{c.block_m}/bn{c.block_n}/c{c.chunks_per_shard}"
        + ("/ragged" if c.ragged else "") + ("/w8" if c.w8 else "")
        + ("/fp8" if getattr(c, "fp8", False) else "")
        # synthesized span policies (ISSUE 14) are distinct tuples: the
        # label must separate them from their contig twins
        + (f"/{pol}" if pol != "contig" else "")
    )


def _ranked_inputs(world, cfg, m_loc):
    """Deterministic routing + alignment for the fused pipelines: every
    expert populated, same ids on every rank (SPMD capture needs identical
    shapes only, but identical values keep captures byte-reproducible)."""
    import jax.numpy as jnp

    from triton_dist_tpu.ops.moe_utils import moe_align_ranked

    t_loc = m_loc * _TOPK
    ids = jnp.tile(jnp.arange(t_loc, dtype=jnp.int32) % _E, (world, 1))
    ral = moe_align_ranked(ids, _E, cfg.block_m, m_loc, ragged=cfg.ragged)
    return ids, ral


def _ag_gg_tuples(world):
    from triton_dist_tpu.ops.allgather_group_gemm import (
        AG_GROUP_GEMM_TUNE_SPACE,
    )

    return [(_gg_label(c), c) for c in _gg_cfgs(AG_GROUP_GEMM_TUNE_SPACE)]


def _ag_gg_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    agg = importlib.import_module("triton_dist_tpu.ops.allgather_group_gemm")

    k_dim, n_loc = 8, 16
    m_loc = 8
    _, ral = _ranked_inputs(world, cfg, m_loc)
    a = jnp.ones((m_loc, k_dim), jnp.float32)
    b = jnp.ones((_E, k_dim, n_loc), jnp.float32)

    def make_fn(rank):
        # gather_group_blocks=1 keeps the group quantum at one block, so
        # every chunks_per_shard in the space gets a real multi-span
        # schedule at this shape (and the group/step boundary prefetch
        # arms are exercised maximally)
        return lambda: agg.ag_group_gemm_overlap(
            a, b, ral, axis="tp", config=cfg, gather_group_blocks=1,
        )

    return make_fn


def _moe_rs_tuples(world):
    from triton_dist_tpu.ops.moe_reduce_rs import MOE_RS_TUNE_SPACE

    return [(_gg_label(c), c) for c in _gg_cfgs(MOE_RS_TUNE_SPACE)]


def _moe_rs_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    mrs = importlib.import_module("triton_dist_tpu.ops.moe_reduce_rs")
    from triton_dist_tpu.ops.moe_utils import ranked_scatter_meta

    f_loc, h_dim = 8, 16
    # the combine pushes chunk over m_out rows at a 128-row quantum: give
    # the largest chunk count in the space real spans to schedule
    m_loc = 512
    _, ral = _ranked_inputs(world, cfg, m_loc)
    dst_ids, w_rows = ranked_scatter_meta(
        ral, jnp.ones((world * m_loc, _TOPK), jnp.float32)
    )
    t_pad_loc = ral.local_ids.shape[1]
    h_sorted = jnp.ones((world * t_pad_loc, f_loc), jnp.float32)
    w_down = jnp.ones((_E, f_loc, h_dim), jnp.float32)

    def make_fn(rank):
        return lambda: mrs.moe_reduce_rs_overlap(
            h_sorted, w_down, ral.expert_ids, dst_ids, w_rows, axis="tp",
            m_out=m_loc, valid_rows=ral.valid_rows, config=cfg,
        )

    return make_fn


# --- kv_stream (ISSUE 13: the disaggregated KV handoff wire) ----------------

def _kv_tuples(world):
    from triton_dist_tpu.ops.kv_stream import KV_STREAM_TUNE_SPACE

    return [
        (f"{c.wire}/c{c.chunks_per_shard}", c) for c in KV_STREAM_TUNE_SPACE
    ]


def _kv_build(world, cfg):
    import jax.numpy as jnp

    import importlib

    ks = importlib.import_module("triton_dist_tpu.ops.kv_stream")

    # 16 rows: the largest chunk count in the space gets real multi-row
    # spans; 8 columns stand in for page_size * head_dim
    if cfg.wire in ks.QUANT_WIRES:
        payload = jnp.ones(
            (16, 8), ks.FP8_WIRE_DTYPE if cfg.wire == "fp8" else jnp.int8
        )
        scales = jnp.ones((16, 1), jnp.float32)

        def make_fn(rank):
            return lambda: ks._kv_stream_fused(
                payload, scales, axis="tp", config=cfg
            )
    else:
        payload = jnp.ones((16, 8), jnp.float32)

        def make_fn(rank):
            return lambda: ks._kv_stream_fused(
                payload, axis="tp", config=cfg
            )

    return make_fn


_COMM_MODULES = (
    "triton_dist_tpu.ops.allgather",
    "triton_dist_tpu.ops.reduce_scatter",
    "triton_dist_tpu.ops.all_to_all",
    "triton_dist_tpu.ops.allgather_gemm",
    "triton_dist_tpu.ops.gemm_reduce_scatter",
    "triton_dist_tpu.ops.allgather_group_gemm",
    "triton_dist_tpu.ops.moe_reduce_rs",
    "triton_dist_tpu.ops.group_gemm",
    "triton_dist_tpu.ops.kv_stream",
    "triton_dist_tpu.ops.common",
)

FAMILIES: dict[str, FamilySpec] = {
    "allgather": FamilySpec(
        "allgather", _COMM_MODULES, _ag_build, _ag_tuples
    ),
    "reduce_scatter": FamilySpec(
        "reduce_scatter", _COMM_MODULES, _rs_build, _rs_tuples
    ),
    "a2a": FamilySpec("a2a", _COMM_MODULES, _a2a_build, _a2a_tuples),
    "ag_gemm": FamilySpec(
        "ag_gemm", _COMM_MODULES, _ag_gemm_build, _ag_gemm_tuples
    ),
    "gemm_rs": FamilySpec(
        "gemm_rs", _COMM_MODULES, _gemm_rs_build, _gemm_rs_tuples
    ),
    "ag_group_gemm": FamilySpec(
        "ag_group_gemm", _COMM_MODULES, _ag_gg_build, _ag_gg_tuples
    ),
    "moe_reduce_rs": FamilySpec(
        "moe_reduce_rs", _COMM_MODULES, _moe_rs_build, _moe_rs_tuples
    ),
    "kv_stream": FamilySpec(
        "kv_stream", _COMM_MODULES, _kv_build, _kv_tuples
    ),
}


def family_tuples(family: str, world: int):
    return FAMILIES[family].tuples(world)


def capture_family(family: str, world: int, label: str, spec) -> C.WorldCapture:
    fam = FAMILIES[family]
    make_fn = fam.build(world, spec)
    return C.capture_world(
        make_fn, world, _modules(fam), family=family, label=label
    )


def verify_family(
    family: str, world: int, label: str, spec
) -> tuple[Report, C.WorldCapture]:
    cap = capture_family(family, world, label, spec)
    return verify_capture(cap), cap


@dataclasses.dataclass
class SweepResult:
    reports: list[Report]
    defect_failures: list[str]
    # notes about deliberately-not-run pieces (e.g. the defect harness
    # under a family subset with no representative captures); never fails
    # the sweep, surfaced by the CLI
    skipped: list[str]

    @property
    def ok(self) -> bool:
        return (
            all(r.ok for r in self.reports) and not self.defect_failures
        )


def run_sweep(
    families=None, worlds=WORLDS, *, defects: bool = True,
    progress: Callable[[str], None] | None = None,
) -> SweepResult:
    """Verify every tune-space tuple of the selected families at the
    selected worlds, then (``defects=True``) run the seeded-defect harness
    against representative captures: one simple ring family, one chunked
    ring, and the chunked a2a (the order-sensitive one)."""
    say = progress or (lambda s: None)
    reports: list[Report] = []
    skipped: list[str] = []
    defect_caps: dict[str, C.WorldCapture] = {}
    for family in families or list(FAMILIES):
        for world in worlds:
            for label, spec in family_tuples(family, world):
                say(f"{family}[{label}] world={world}")
                try:
                    rep, cap = verify_family(family, world, label, spec)
                except C.CaptureError as exc:
                    rep = Report(family=family, world=world, label=label)
                    rep.errors.append(Finding("capture", str(exc)))
                    reports.append(rep)
                    continue
                reports.append(rep)
                key = f"{family}/{label}/w{world}"
                # keep a small pool of representative clean captures for
                # the defect harness: chunked a2a (order check), a chunked
                # ring, and a plain ring
                if rep.ok and (
                    ("a2a" == family and "/c4" in label)
                    or (family == "allgather" and label == "ring_1d/c2")
                    or (family == "allgather" and label == "ring_1d/c1")
                ):
                    defect_caps[key] = cap
    failures: list[str] = []
    if defects:
        if not defect_caps:
            # a family/world subset that produced none of the harness's
            # representative captures: note the skip instead of reporting
            # five spurious "no applicable capture" failures — the FULL
            # sweep (CI's posture) always has them
            skipped.append(
                "defect harness skipped: this family/world subset yields "
                "no representative captures (needs allgather ring_1d "
                "c1/c2 and the chunked a2a)"
            )
        else:
            say("seeded-defect harness")
            # order the pool so the chunk-order defect finds the a2a capture
            ordered = dict(
                sorted(defect_caps.items(), key=lambda kv: "a2a" not in kv[0])
            )
            # the FULL sweep must exercise every defect; a family subset
            # that cannot offer one a capture notes the skip instead
            failures = D.run_defect_suite(
                ordered, require_all=families is None, notes=skipped,
            )
    return SweepResult(reports, failures, skipped)
