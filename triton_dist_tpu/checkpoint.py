"""Distributed checkpoint / resume for the model family.

The reference has NO checkpointing (SURVEY.md §5: "absent (no trainer)" —
its only resume-like state is the autotune log dir). This framework ships a
trainer-shaped model family, so it ships the matching aux subsystem: sharded
save/restore built on orbax (the TPU ecosystem's checkpointer), with
restore-onto-any-mesh resharding — the property that makes checkpoints
useful across slice sizes (train on v5p-32, resume debug on an 8-device CPU
mesh).

Design notes (TPU-native, not a port):
- Saves are SPMD-coordinated: every process calls :func:`save` with its
  addressable shards; orbax writes a single logical checkpoint (OCDBT).
- Restore takes the TARGET sharding tree — params land already placed for
  the mesh you resume on, no host-side gather/scatter round-trip.
- Async by default (``wait=False`` returns immediately and overlaps the
  serialization with the next train steps; call :func:`wait_until_saved`
  before exiting) — the standard bandwidth trick for large meshes.
"""

from __future__ import annotations

import os
from typing import Any

import jax

__all__ = [
    "save", "restore", "latest_step", "wait_until_saved", "close",
    "clear_cache",
]

_manager_cache: dict[str, Any] = {}


def _manager(directory: str, *, max_to_keep: int | None = None):
    """Cached orbax CheckpointManager per directory. The first call to a
    directory fixes its retention (`max_to_keep`, default 3); a later call
    with a DIFFERENT explicit value recreates the manager (closing the old
    one) so retention changes take effect."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    hit = _manager_cache.get(directory)
    if hit is not None:
        mgr, kept = hit
        if max_to_keep is None or kept == max_to_keep:
            return mgr
        mgr.close()
        del _manager_cache[directory]
    keep = 3 if max_to_keep is None else max_to_keep
    mgr = ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=True, enable_async_checkpointing=True
        ),
    )
    _manager_cache[directory] = (mgr, keep)
    return mgr


def close(directory: str) -> None:
    """Flush pending async saves and release `directory`'s manager (orbax
    managers hold background threads; long-lived processes checkpointing to
    many directories should close ones they are done with)."""
    directory = os.path.abspath(directory)
    hit = _manager_cache.pop(directory, None)
    if hit is not None:
        hit[0].close()


def clear_cache() -> None:
    """Close every cached manager (see :func:`close`)."""
    for directory in list(_manager_cache):
        close(directory)


def save(
    directory: str, step: int, tree: Any, *, wait: bool = False,
    max_to_keep: int | None = None,
) -> None:
    """Save a (sharded) pytree as checkpoint `step`. All processes must
    call this collectively. ``wait=True`` blocks until durable;
    `max_to_keep` sets the directory's retention (default 3)."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, max_to_keep=max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(tree))
    if wait:
        mgr.wait_until_finished()


def wait_until_saved(directory: str) -> None:
    """Block until every async save to `directory` is durable."""
    _manager(directory).wait_until_finished()


def latest_step(directory: str) -> int | None:
    """Newest checkpoint step in `directory`, or None if empty. Read
    failures (corrupt metadata, permissions) propagate — a resume script
    must not mistake a broken checkpoint dir for a fresh run."""
    return _manager(directory).latest_step()


def restore(directory: str, step: int | None = None, *, like: Any) -> Any:
    """Restore checkpoint `step` (default: latest) resharded to match
    `like` — a pytree of arrays (shapes/dtypes/shardings to restore onto,
    e.g. ``jax.eval_shape`` output placed with ``NamedSharding``s of the
    CURRENT mesh, or simply the freshly-initialized params)."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        like,
    )
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract))
