"""Contextual autotuner for distributed kernels
(≙ reference ``python/triton_dist/autotuner.py``, 256 LoC:
``contextual_autotune(is_dist=True)(fn)``).

The reference wraps Triton's autotuner so that *the whole distributed op*
(not just one kernel) is timed per config, aggregates timings across ranks
(a config must be fastest for the slowest rank), and logs decisions to
``.autotune_logs/rank-N.log``.

TPU-native form: time the whole jitted thunk per candidate config with
``perf_func``; under SPMD one process drives all local devices, so the
cross-rank aggregation the reference needs (NCCL all-reduce of timings)
reduces to the walltime of the slowest device — which walltime already is.
Multi-host runs gather every process's per-config timings
(``multihost_utils.process_allgather``) and pick the config minimizing the
MAX over processes — the reference's slowest-rank rule (autotuner.py:97):
on DCN-attached heterogeneous topologies rank 0's local winner can be a
straggler's worst case. A config that failed on ANY process is
disqualified everywhere, and rank 0's (identical, deterministic) pick is
still broadcast as the authoritative tie-break so all processes apply the
same config or collectives would deadlock.

Decisions persist to ``.autotune_cache/<name>.json`` keyed by the call
signature, so production runs pay zero tuning cost.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import math
import os
from typing import Any, Callable, Iterable, Sequence

import jax

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu.resilience import DistTimeoutError
from triton_dist_tpu.utils import perf_func_loop, perf_pair_loop


_CACHE_DIR = os.environ.get("TDT_AUTOTUNE_CACHE", ".autotune_cache")
_memory_cache: dict[tuple[str, str], Any] = {}


def _sig_key(args: Sequence[Any], kwargs: dict[str, Any]) -> str:
    """Shape/dtype signature of the call (config-independent)."""
    parts = []
    for a in jax.tree.leaves((args, kwargs)):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            parts.append(f"{a.dtype}{list(a.shape)}")
        elif isinstance(a, (int, float, str, bool)) or a is None:
            parts.append(repr(a))
        else:
            # non-array context (Mesh, method enums, …) must key the cache
            # too: distinct contexts with identical array shapes are
            # different tuning problems. Long strings keep a readable
            # prefix plus a hash of the FULL text — a bare truncation let
            # two contexts sharing a 160-char prefix collide and silently
            # serve each other's cached config
            s = str(a)
            if len(s) > 160:
                digest = hashlib.sha256(s.encode("utf-8", "replace")).hexdigest()[:16]
                s = f"{s[:120]}#{digest}"
            parts.append(s)
    try:
        parts.append(f"dev={jax.devices()[0].device_kind}x{len(jax.devices())}")
    except Exception:
        pass
    return ";".join(parts)


def _cache_path(name: str) -> str:
    return os.path.join(_CACHE_DIR, f"{name}.json")


def _load_disk_cache(name: str) -> dict[str, Any]:
    try:
        with open(_cache_path(name)) as f:
            return json.load(f)
    except Exception:
        return {}


def _store_disk_cache(name: str, table: dict[str, Any]) -> None:
    """Atomic merge-write: re-read the table first (another process may have
    tuned other signatures meanwhile), then temp-file + os.replace so a crash
    mid-write can never leave a truncated/corrupt cache."""
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        merged = _load_disk_cache(name)
        merged.update(table)
        table.update(merged)
        tmp = _cache_path(name) + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
        os.replace(tmp, _cache_path(name))
    except Exception:
        pass


@dataclasses.dataclass
class AutotuneResult:
    config: Any
    times_ms: list[float]


def _slowest_rank_best(all_times, margin: float = 0.02) -> int:
    """Min-max cross-rank aggregation (≙ reference ``autotuner.py:97``):
    given ``[n_proc, n_cfg]`` per-process timings, pick the config whose
    SLOWEST process is fastest. ``inf`` anywhere disqualifies the config
    everywhere (it failed on that rank — applying it would desync the
    fleet). The same order-preference walk as the local sweep applies: a
    later candidate must beat the current leader's worst-case time by
    `margin` to displace it, so spaces' best-known leaders keep their seat
    against cross-host timing noise. Returns -1 when every config failed
    somewhere (caller falls back to its local pick)."""
    import numpy as np

    worst = np.max(np.asarray(all_times, np.float64), axis=0)
    finite = np.isfinite(worst)
    if not finite.any():
        return -1
    leader = int(np.argmax(finite))   # first config finite on every rank
    best = leader
    for i in range(leader + 1, worst.size):
        if finite[i] and worst[i] < worst[best] * (1.0 - margin):
            best = i
    return best


def contextual_autotune(
    configs: Iterable[Any],
    *,
    name: str | None = None,
    iters: int = 60,
    trials: int = 3,
    dedupe: Callable[..., Any] | None = None,
    precondition: Callable[..., bool] | None = None,
    sweep_in_interpret: bool = False,
) -> Callable:
    """Decorator: sweep `configs` for the wrapped op on first call per input
    signature, thereafter reuse the winner (≙ ``contextual_autotune``,
    reference autotuner.py:97).

    The wrapped function must accept a ``config=`` keyword. Candidates that
    fail to compile/run are skipped (the reference likewise discards configs
    that raise, autotuner.py:150-170).

    Each candidate is scored by the median of `trials` on-device loop
    timings (``perf_func_loop`` — one compile per config; per-call walltime
    over a tunneled chip was noisy enough to mis-pick by 40%, and iters=15
    windows were still jitter-bound at ms-scale ops: a measured window
    ≳300 ms per sample is what makes candidate ranking trustworthy).

    Under the TPU *interpreter* (CPU tests) timings are meaningless and a
    sweep costs minutes per signature, so the first viable candidate is
    used directly unless ``sweep_in_interpret=True`` (set by the
    autotuner's own unit tests).

    `dedupe`, if given, maps ``(cfg, *args, **kwargs)`` to the config's
    EFFECTIVE key for this problem (e.g. the clamped block shape); configs
    that collapse to the same key are timed once and share the result.

    `precondition`, if given, maps ``(cfg, *args, **kwargs)`` to whether
    the candidate is SENSIBLE for this problem — a shape-aware guard for
    the sweep-free paths (cached_or_first / interpreter), where the walk
    applies the first surviving candidate untimed: a config that is
    best-known at the bench shape can be pathological elsewhere (e.g. a
    512-row MoE alignment block padding a 16-token problem 100×). Filtered
    configs are skipped by sweeps too; if the filter rejects every
    candidate it is ignored outright (never an error). Must be
    deterministic in its arguments — multi-host relies on every process
    walking the same candidate order.
    """
    configs = list(configs)

    def deco(fn: Callable) -> Callable:
        op_name = name or fn.__name__
        disk = _load_disk_cache(op_name)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            if "config" in kwargs and kwargs["config"] is not None:
                return fn(*args, **kwargs)
            kwargs.pop("config", None)
            key = _sig_key(args, kwargs)
            mem_key = (op_name, key)
            if mem_key in _memory_cache:
                return fn(*args, config=_memory_cache[mem_key], **kwargs)
            # disk entries store {"i": index, "cfg": repr} — the repr guards
            # against a reordered/edited candidate list silently applying
            # the wrong config. Multi-host skips the disk fast path: an
            # asymmetric cache hit would leave one host sweeping (and
            # joining collectives) alone — all hosts sweep, rank 0 decides.
            entry = disk.get(key) if jax.process_count() == 1 else None
            if (
                isinstance(entry, dict)
                and 0 <= entry.get("i", -1) < len(configs)
                and entry.get("cfg") == repr(configs[entry["i"]])
            ):
                _memory_cache[mem_key] = configs[entry["i"]]
                return fn(*args, config=_memory_cache[mem_key], **kwargs)

            # shape-aware candidate filter (see docstring); a filter that
            # rejects everything (or raises) is ignored, never fatal
            cands = configs
            if precondition is not None:
                try:
                    ok = [
                        cfg for cfg in configs
                        if precondition(cfg, *args, **kwargs)
                    ]
                except Exception:
                    ok = []
                if ok:
                    cands = ok

            def _first_viable(reason: str):
                """Apply the first candidate that runs — NEVER a sweep.
                Skips are always logged to stderr: demoting the best-known
                config on a transient error must not look like a genuine
                perf regression. Memory-cache only: the disk cache real
                tuned runs consult is never written by these paths."""
                import sys

                last_err: Exception | None = None
                for cfg in cands:
                    try:
                        out = fn(*args, config=cfg, **kwargs)
                    except DistTimeoutError:
                        # a watchdog trip is a peer-loss event, not a
                        # candidate-viability problem: retrying per config
                        # would burn one full timeout budget per candidate
                        # and mask a sick fleet as "all configs failed"
                        raise
                    except Exception as e:
                        last_err = e
                        print(
                            f"[autotune {op_name}] {reason}: candidate "
                            f"{cfg!r} failed ({e!r:.200}); trying next",
                            file=sys.stderr, flush=True,
                        )
                        continue
                    _memory_cache[mem_key] = cfg
                    # obs (ISSUE 9): the sweep-free walks crown a config
                    # too — record it so a timeline reader can tell an
                    # untimed policy pick from a measured sweep winner
                    from triton_dist_tpu import obs as _obs

                    _obs.instant(
                        f"autotune:{op_name}", cat="autotune",
                        policy=reason, crowned=repr(cfg),
                    )
                    return out
                raise RuntimeError(
                    f"autotune({op_name}): every candidate config failed "
                    f"({reason})"
                ) from last_err

            # TDT_AUTOTUNE_POLICY=cached_or_first: signature cache hit
            # (handled above) or the first VIABLE candidate. This is the
            # bounded-time mode for runs inside a budgeted window (the
            # driver bench): a sweep costs a compile + timed loop per
            # candidate. Tune spaces therefore lead with their best-known
            # config. Multi-host intentionally ignores even a warm disk
            # cache here (per-host cache files can diverge and a
            # mismatched config choice deadlocks collectives): every
            # process deterministically walks the same candidate order
            # without coordination.
            if os.environ.get("TDT_AUTOTUNE_POLICY") == "cached_or_first":
                return _first_viable("cached_or_first")

            interp = tdt_config.get_config().interpret
            if interp is None:
                interp = not tdt_config.on_tpu()
            if interp and not sweep_in_interpret:
                # interpreter timings are noise
                return _first_viable("interpreter")

            from triton_dist_tpu import obs as _obs
            from triton_dist_tpu.resilience import retry as _retry

            sweep_t0 = _retry.get_clock().monotonic()
            times = [float("inf")] * len(configs)
            seen: dict[Any, int] = {}
            for i, cfg in enumerate(configs):
                if cfg not in cands:
                    continue  # filtered by the precondition: never timed
                if dedupe is not None:
                    try:
                        eff = dedupe(cfg, *args, **kwargs)
                    except Exception:
                        eff = i
                    if eff in seen:
                        times[i] = times[seen[eff]]  # same effective kernel
                        continue
                    seen[eff] = i
                try:
                    # consume="all": tune spaces mix side-effectful Pallas
                    # candidates with pure XLA-native sentinels; a partial
                    # consumption lets DCE shrink the pure ones to a slice
                    # and they'd "win" every sweep regardless of true speed
                    times[i] = perf_func_loop(
                        functools.partial(fn, config=cfg, **kwargs),
                        args,
                        iters=iters,
                        trials=trials,
                        consume="all",
                    )
                except DistTimeoutError:
                    raise  # peer loss, not a config problem (see above)
                except Exception as e:  # config doesn't fit this problem
                    if tdt_config.get_config().verbose_autotune:
                        print(f"[autotune {op_name}] cfg {cfg} failed: {e!r}")
            if not any(t != float("inf") for t in times):
                raise RuntimeError(
                    f"autotune({op_name}): every candidate config failed"
                )
            # Order-preference walk: spaces LEAD with the best-known /
            # XLA-native-sentinel config, and sweep timings are unpaired
            # samples with a few-% noise floor — so a later candidate must
            # beat the current leader by a real margin to displace it.
            # Without this, ±2% jitter regularly crowns a marginally
            # slower kernel over the sentinel and the bench's paired
            # ratio then reads 0.98 instead of 1.00.
            margin = 0.02
            leader = next(
                i for i in range(len(configs)) if times[i] != float("inf")
            )
            best_i = leader
            for i in range(best_i + 1, len(configs)):
                if times[i] < times[best_i] * (1.0 - margin):
                    best_i = i
            if best_i != leader and jax.process_count() == 1:
                # A displacement measured from unpaired sweep samples can
                # still be jitter (r3 chip evidence: a Pallas config beat
                # the world-1 XLA sentinel in the sweep, then LOST the
                # bench's paired loop 0.998:1). Confirm with the same
                # interleaved paired timing the bench trusts; the leader
                # keeps its seat unless the challenger wins it paired.
                # (Multi-host skips this: the confirm pass would need every
                # rank to join both loops in lockstep — the slowest-rank
                # aggregation below decides from the gathered sweep
                # timings instead.)
                try:
                    _, _, ratio = perf_pair_loop(
                        functools.partial(fn, config=configs[best_i], **kwargs),
                        functools.partial(fn, config=configs[leader], **kwargs),
                        args, iters=iters, rounds=3,
                    )
                    # ratio = t_leader / t_challenger
                    if ratio < 1.0 + margin:
                        best_i = leader
                except Exception:
                    best_i = leader  # confirm failed: trust the order bias
            best_t = times[best_i]
            if jax.process_count() > 1:
                # slowest-rank aggregation (≙ the reference's cross-rank
                # rule, autotuner.py:97): gather every process's timings
                # and pick the config minimizing the max over ranks — on
                # heterogeneous (DCN-attached) topologies rank 0's local
                # winner can be another rank's straggler. Every process
                # computes the same min-max pick from the same gathered
                # matrix; rank 0's broadcast remains the authoritative
                # tie-break (all processes must apply the same config or
                # collectives mismatch).
                from jax.experimental import multihost_utils
                import numpy as _np

                all_times = multihost_utils.process_allgather(
                    _np.asarray(times, _np.float64)
                )
                agg = _slowest_rank_best(all_times, margin)
                if agg >= 0:
                    best_i = agg
                best_i = int(
                    multihost_utils.broadcast_one_to_all(_np.int32(best_i))
                )
                # the logged timing below is THIS RANK'S local sample of
                # the fleet's choice — it can be inf when the config
                # failed here (harmless: the disk cache stores the index)
                best_t = times[best_i]
            if tdt_config.get_config().verbose_autotune:
                t_str = f"{best_t:.3f} ms" if math.isfinite(best_t) else (
                    "n/a locally"  # rank 0's pick; this rank's sample failed
                )
                print(
                    f"[autotune {op_name}] {key} -> {configs[best_i]} "
                    f"({t_str}; all={['%.3f' % t for t in times]})"
                )
            # obs (ISSUE 9): the candidate sweep + crowned config as one
            # span — who was timed, what won, and what the sweep cost
            _obs.record_span(
                f"autotune:{op_name}", sweep_t0,
                _retry.get_clock().monotonic(), cat="autotune",
                track="autotune", n_candidates=len(configs),
                n_timed=sum(1 for t in times if t != float("inf")),
                crowned=repr(configs[best_i]),
                best_ms=(round(best_t, 6) if math.isfinite(best_t)
                         else "inf"),
            )
            _memory_cache[mem_key] = configs[best_i]
            disk[key] = {"i": best_i, "cfg": repr(configs[best_i])}
            _store_disk_cache(op_name, disk)
            return fn(*args, config=configs[best_i], **kwargs)

        wrapped.autotune_configs = configs
        return wrapped

    return deco
