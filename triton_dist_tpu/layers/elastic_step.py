"""ElasticStep — the mesh-level wrapper that makes a serving/training step
survive a sick world (resilience/elastic.py, docs/resilience.md).

The shard-level layers (TPMLP, EPAll2AllLayer, …) run *inside*
``jax.shard_map`` and cannot change the world mid-trace; the elastic
decisions — which mesh to run over, whether to retry a step, when to probe
quarantined PEs back in — are host-level. This wrapper owns them:

- each call resolves the CURRENT surviving world
  (``elastic.effective_mesh``) and builds/caches the step for it, so the
  call after a quarantine runs at reduced parallelism without the caller
  re-plumbing anything;
- transient failures are retried under ``config.retry_policy``
  (``retry.call_with_retry`` — exhaustion feeds peer attribution and
  raises, and the NEXT call sees the shrunk world);
- :meth:`probe` runs the probation barrier and re-admits recovered PEs,
  after which calls run the full world again.

The caller stays in charge of data placement: ``world_size`` says how many
PEs the next call will run over, and the step builder receives the mesh so
it can re-derive its shardings (the op entries' existing divisibility
contracts apply at the reduced size).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from jax.sharding import Mesh

from triton_dist_tpu.resilience import elastic, retry


@dataclasses.dataclass
class ElasticStep:
    """Wrap ``build(mesh) -> step`` so the step always runs over the
    surviving world.

    build:  given the current (possibly shrunk) mesh, return the step
            callable; called once per distinct world and cached, so the
            healthy path costs one dict lookup.
    mesh:   the full world this step was provisioned for.
    axis:   the comm axis quarantined PEs are dropped from.
    family: name for retry/health bookkeeping.
    """

    build: Callable[[Mesh], Callable[..., Any]]
    mesh: Mesh
    axis: str = "tp"
    family: str = "elastic_step"

    def __post_init__(self) -> None:
        self._steps: dict[Any, Callable[..., Any]] = {}

    def current_mesh(self) -> Mesh:
        """The mesh the next call will run over (full world while healthy,
        survivors after a quarantine, full again after re-admission)."""
        return elastic.effective_mesh(self.mesh, axis=self.axis)

    @property
    def world_size(self) -> int:
        ax = tuple(self.mesh.axis_names).index(self.axis)
        return int(self.current_mesh().devices.shape[ax])

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        mesh = self.current_mesh()
        step = self._steps.get(mesh)
        if step is None:
            step = self._steps[mesh] = self.build(mesh)
        return retry.call_with_retry(self.family, step, *args, **kwargs)

    def probe(self) -> dict[int, str]:
        """One probation round over the FULL provisioned mesh: quarantined
        PEs that answer the barrier cleanly are re-admitted (per
        ``config.probation_probes``). Returns {pe: new_state}."""
        return elastic.probe_quarantined(self.mesh, axis=self.axis)
