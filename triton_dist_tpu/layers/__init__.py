"""Module layer (L7) — reusable wrappers over the kernel zoo
(≙ reference ``python/triton_dist/layers/nvidia/``: ``AllGatherLayer``,
``EPAll2AllLayer``, ``SpGQAFlashDecodeAttention``).

The reference layers are torch ``nn.Module``s that own symmetric-buffer
contexts; under JAX the buffers are SPMD-symmetric by construction, so the
layers here are light callable configs — everything stateful lives in the
kernels' own workspaces. All ``__call__``s run inside ``jax.shard_map``,
except :class:`ElasticStep`, the host-level wrapper that picks WHICH world
a step runs over (retry + quarantine shrink + probation re-admission).
"""

from triton_dist_tpu.layers.allgather_layer import AllGatherLayer
from triton_dist_tpu.layers.elastic_step import ElasticStep
from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer, HierEPAll2AllLayer
from triton_dist_tpu.layers.ep_moe_mlp import EPMoEMLP
from triton_dist_tpu.layers.sp_flash_decode_layer import SpGQAFlashDecodeAttention
from triton_dist_tpu.layers.tp_mlp import TPMLP, TPMoEMLP
