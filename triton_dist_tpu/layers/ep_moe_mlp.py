"""Expert-parallel MoE MLP — the full EP block the reference's tests
compose inline (≙ reference ``ep_a2a.py`` dispatch → local grouped expert
compute → combine; its ``EPAll2AllLayer`` only ships the transport, the
expert GEMMs live in the test bodies).

Contrast with :class:`~triton_dist_tpu.layers.tp_mlp.TPMoEMLP`: there every
PE holds a *slice of every* expert and tokens ride AG/RS; here each PE holds
``n_experts / world`` *whole* experts and tokens travel to their experts
over the all-to-all (DeepSeek-style EP). One layer covers both transports:

- flat (``axis=``): single all-to-all over one mesh axis;
- hierarchical (``outer=``/``inner=``): the two-phase node-then-local
  dispatch with cross-node dedup (≙ ``ep_a2a.py:36-147``).

The expert compute between dispatch and combine is the scalar-prefetch
grouped GEMM pair on block-aligned received rows — the same kernel the TP
MoE path uses, with whole-expert weights.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer, HierEPAll2AllLayer
from triton_dist_tpu.ops.grads import group_gemm_grad
from triton_dist_tpu.ops.group_gemm import (
    GroupGemmConfig,
    group_gemm,
    quantize_expert_weights,
)
from triton_dist_tpu.utils import axis_size as _axis_size


def _overflow_message(ov: int) -> str:
    return (
        f"EP dispatch dropped {ov} assignments to slab overflow — "
        f"max_m/max_m2 too small (≙ the reference's assert, "
        f"low_latency_all_to_all.py:212). Raise the capacity or route "
        f"fewer tokens per rank."
    )


def _overflow_guard(overflow) -> None:
    # Diagnose only — raising from inside a debug callback while the
    # shard_map collective is in flight can wedge the runtime instead of
    # failing it (observed intermittent XLA:CPU hangs). The guaranteed-loud
    # failure is the NaN poison applied by the caller; use
    # :func:`assert_no_overflow` for a host-side hard stop after the step.
    ov = int(overflow)
    if ov > 0:
        import sys

        print(f"ERROR: {_overflow_message(ov)}", file=sys.stderr, flush=True)


def assert_no_overflow(overflow) -> None:
    """Host-side hard stop on a fetched overflow counter (call OUTSIDE jit,
    e.g. on the aux output of ``EPMoEMLP(..., with_overflow=True)`` after
    the step completes)."""
    ov = int(overflow)
    if ov > 0:
        raise RuntimeError(_overflow_message(ov))


@dataclasses.dataclass
class EPMoEMLP:
    """Call inside ``jax.shard_map``; x ``[m_loc, H]`` (token-sharded over
    the EP world), w_up ``[E/world, H, F]``, w_down ``[E/world, F, H]``
    (each PE's WHOLE experts), routing ``[m_loc, topk]`` → ``[m_loc, H]``.

    ``max_m`` is the per-(src, dest) slab capacity of the (phase-1)
    dispatch; ``max_m2`` the phase-2 capacity when hierarchical (defaults
    to a worst-case bound from the phase-1 slabs).
    """

    n_experts: int
    topk: int
    max_m: int
    axis: str = "ep"            # flat transport axis …
    outer: str | None = None    # … or set BOTH outer+inner for two-phase
    inner: str | None = None
    max_m2: int | None = None
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu
    gg_config: GroupGemmConfig | None = None
    # int8/fp8 dispatch wire format (inference only — cuts the router
    # gradient; see EPAll2AllLayer.quant)
    quant: str | None = None
    # chunk-granular dispatch/combine transport (ISSUE 4; see
    # EPAll2AllLayer.a2a_config); None/chunk=1 = legacy whole-slab moves
    a2a_config: Any = None
    interpret: Any = None

    def _transport(self):
        if (self.outer is None) != (self.inner is None):
            raise ValueError("set both outer= and inner=, or neither")
        if self.outer is not None:
            n_o = _axis_size(self.outer)
            return HierEPAll2AllLayer(
                n_experts=self.n_experts, topk=self.topk,
                max_m1=self.max_m,
                max_m2=self.max_m2 or n_o * self.max_m * self.topk,
                outer=self.outer, inner=self.inner, quant=self.quant,
                a2a_config=self.a2a_config, interpret=self.interpret,
            )
        return EPAll2AllLayer(
            n_experts=self.n_experts, topk=self.topk, max_m=self.max_m,
            axis=self.axis, quant=self.quant,
            a2a_config=self.a2a_config, interpret=self.interpret,
        )

    def __call__(
        self,
        x: jax.Array,
        w_up: jax.Array,
        w_down: jax.Array,
        topk_ids: jax.Array,
        topk_weights: jax.Array,
        *,
        with_overflow: bool = False,
        w_up_scale: jax.Array | None = None,
        w_down_scale: jax.Array | None = None,
    ):
        """``with_overflow=True`` additionally returns the scalar count of
        assignments dropped by slab overflow — an undersized ``max_m``
        silently zeroes those tokens' expert contributions otherwise (the
        transport layers surface the same counter; don't swallow it in
        anything user-facing).

        ``w_up_scale``/``w_down_scale`` (``[E_loc, 1, N]`` from
        ``ops.quantize_expert_weights``) mark the expert banks as int8:
        the local grouped GEMMs stream half the weight bytes (the
        resource decode-shaped expert compute is bound by) via the
        scale-folding kernel. ``gg_config.w8`` (ISSUE 7) quantizes float
        banks on the fly instead — the same config axis the TP pipeline
        sweeps, so one knob covers both MoE parallelisms. INFERENCE only
        — the int8 path takes the non-VJP grouped GEMM."""
        cfg = self.gg_config or GroupGemmConfig()
        layer = self._transport()
        hier = self.outer is not None
        m_loc = x.shape[0]
        if (w_up_scale is None) != (w_down_scale is None):
            raise ValueError("pass both expert-weight scales, or neither")
        if cfg.w8 and w_up_scale is None:
            # the GroupGemmConfig w8 axis: quantize the local banks here
            # (whole experts — per-(expert, out-column) scales as always).
            # An int8 bank without scales must fail loudly, exactly as
            # ops-level resolve_w8 does — re-quantizing quantized values
            # would silently discard the original scales.
            if not (
                jnp.issubdtype(w_up.dtype, jnp.floating)
                and jnp.issubdtype(w_down.dtype, jnp.floating)
            ):
                raise ValueError(
                    "GroupGemmConfig.w8 with integer expert banks needs "
                    "the matching scales (pass w_up_scale/w_down_scale "
                    "from quantize_expert_weights)"
                )
            w_up, w_up_scale = quantize_expert_weights(w_up)
            w_down, w_down_scale = quantize_expert_weights(w_down)
        w8 = w_up_scale is not None

        if hier:
            recv, info = layer.dispatch(x, topk_ids, topk_weights)
        else:
            recv, info = layer.dispatch(x, topk_ids)

        # local expert compute on block-aligned received rows (sentinel
        # rows land on the clamped last expert and are dropped on scatter;
        # with cfg.ragged the alignment also carries the live-row map and
        # the grouped GEMMs skip the dead panels — incl. the whole virtual
        # padding expert, ISSUE 5)
        al = layer.receiver_alignment(
            info, block_m=cfg.block_m, ragged=cfg.ragged
        )
        rows = recv.reshape(-1, x.shape[-1])            # [R, H]
        r_cap = rows.shape[0]
        a_sorted = rows[jnp.minimum(al.sorted_token_ids, r_cap - 1)]
        if w8:
            # int8 banks: the scale-folding kernel; non-differentiable
            gg = lambda a, w, s: group_gemm(  # noqa: E731
                a, w, al.expert_ids, valid_rows=al.valid_rows, scale=s,
                config=cfg, interpret=self.interpret,
            )
        else:
            # alignment ids are sorted by construction (assume_sorted)
            gg = lambda a, w, s: group_gemm_grad(  # noqa: E731
                a, w, al.expert_ids, al.valid_rows, cfg, None,
                self.interpret, True,
            )
        h1 = gg(a_sorted, w_up, w_up_scale)
        h1 = self.activation(h1.astype(jnp.float32)).astype(x.dtype)
        y_sorted = gg(h1, w_down, w_down_scale)
        # back to the received slab layout: each valid row appears exactly
        # once in the sorted order; the sentinel id R is out of range → drop
        y = (
            jnp.zeros((r_cap, y_sorted.shape[-1]), y_sorted.dtype)
            .at[al.sorted_token_ids]
            .set(y_sorted, mode="drop")
            .reshape(recv.shape[0], recv.shape[1], -1)
            .astype(x.dtype)
        )

        if hier:
            out = layer.combine(y, info, m_loc)
        else:
            out = layer.combine(y, info, topk_weights, m_loc)
        out = out.astype(x.dtype)
        if tdt_config.get_config().debug_ep_overflow:
            # loud failure on dropped assignments: a stderr diagnostic plus
            # NaN poison — any loss downstream goes NaN instead of silently
            # wrong (the callback only prints; see _overflow_guard)
            jax.debug.callback(_overflow_guard, info.overflow)
            out = jnp.where(
                info.overflow > 0, jnp.full_like(out, jnp.nan), out
            )
        return (out, info.overflow) if with_overflow else out
