"""TP transformer MLP blocks built on the fused kernels — the "one model
layer running end-to-end" target of SURVEY.md §7 step 3 (the reference stops
at kernels; these layers are the composition its tests perform inline, e.g.
AG-GEMM feeding GEMM-RS = a megatron column→row parallel MLP forward)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs
from triton_dist_tpu.ops.group_gemm import GroupGemmConfig


@dataclasses.dataclass
class TPMLP:
    """Column→row parallel MLP forward, fully overlapped:
    ``reduce_scatter(act(all_gather(x) @ W_up) @ W_down)`` with AG fused
    into the up-GEMM and RS fused into the down-GEMM. Call inside
    ``jax.shard_map``; x ``[m_loc, H]``, W_up ``[H, F/n]``,
    W_down ``[F/n, H]`` → ``[m_loc, H]``."""

    axis: str = "tp"
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu
    ag_config: AGGemmConfig | None = None
    rs_config: GemmRSConfig | None = None
    interpret: Any = None

    def __call__(self, x: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
        h = ag_gemm(
            x, w_up, axis=self.axis, config=self.ag_config, interpret=self.interpret
        )
        h = self.activation(h)
        return gemm_rs(
            h, w_down, axis=self.axis, config=self.rs_config,
            out_dtype=x.dtype, interpret=self.interpret,
        )


@dataclasses.dataclass
class TPMoEMLP:
    """MoE MLP with tensor-parallel experts: AG-GroupGEMM up-projection,
    activation, MoE-Reduce-RS down-projection (≙ composing the reference's
    ``ag_group_gemm`` + ``moe_reduce_rs`` as its MoE tests do).

    Delegates to :func:`~triton_dist_tpu.ops.grads.tp_moe_mlp_grad` — ONE
    source of truth for the fused MoE forward, and the layer is trainable
    for free (custom VJP, router gradient included).

    Call inside ``jax.shard_map``; x ``[m_loc, H]``, w_up ``[E, H, F/n]``,
    w_down ``[E, F/n, H]``, routing from local logits → ``[m_loc, H]``
    (token-sharded both ends)."""

    axis: str = "tp"
    activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu
    gg_config: GroupGemmConfig | None = None
    interpret: Any = None
    # True: single-kernel overlapped AG-GroupGEMM / MoE-Reduce-RS pair;
    # False: sequential composition (A/B baseline)
    overlap: bool = True

    def __call__(
        self,
        x: jax.Array,
        w_up: jax.Array,
        w_down: jax.Array,
        topk_ids: jax.Array,       # [m_loc, topk]
        topk_weights: jax.Array,   # [m_loc, topk]
    ) -> jax.Array:
        from triton_dist_tpu.ops.grads import tp_moe_mlp_grad

        return tp_moe_mlp_grad(
            x, w_up, w_down, topk_ids, topk_weights.astype(jnp.float32),
            self.axis, self.activation, self.gg_config, self.interpret,
            self.overlap,
        ).astype(x.dtype)
