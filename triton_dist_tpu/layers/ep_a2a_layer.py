"""EP All-to-All layer — expert-parallel MoE dispatch/combine
(≙ reference ``layers/nvidia/ep_a2a_layer.py:41`` ``EPAll2AllLayer`` over
the DeepEP-style kernels of ``ep_a2a.py`` and
``low_latency_all_to_all.py``).

Reference flow: warp-granular put of contiguous token ranges to the
same-local-rank peer, intra-node scatter by expert with atomic slot
allocation, combine via remote ``symm_at`` loads (SURVEY.md §2.3). TPU has
no remote loads, so combine is push-based (the dispatch in reverse) — the
restructuring SURVEY.md §7 calls out. All data moves through the padded-slab
``fast_all_to_all``; routing bookkeeping (sort by destination rank, slab
packing, weighted un-permutation) is XLA gather/scatter.

Expert placement: experts_per_rank = n_experts // world; expert ``e`` lives
on rank ``e // experts_per_rank`` as local expert ``e % experts_per_rank``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.all_to_all import A2AConfig, fast_all_to_all
from triton_dist_tpu.ops.grads import fast_all_to_all_grad
from triton_dist_tpu.ops.moe_utils import MoEAlignment, moe_align_block_size
from triton_dist_tpu.utils import axis_size as _axis_size


# Quantized-dispatch wire formats (≙ the reference's fp8 LL dispatch — its
# headline a2a metric runs fp8 payloads with scales riding the transport,
# README.md:87, low_latency_all_to_all.py:94-104).
_QUANT_FORMATS = {
    "int8": (jnp.int8, 127.0),
    "fp8": (jnp.float8_e4m3fn, 448.0),
}


def _check_quant(quant) -> None:
    """Fail at the call boundary, not with a KeyError mid-trace."""
    if quant is not None and quant not in _QUANT_FORMATS:
        raise ValueError(
            f"quant must be one of {sorted(_QUANT_FORMATS)} or None, "
            f"got {quant!r}"
        )


def _dequantize_rows(recv_q: jax.Array, scale: jax.Array, dtype):
    """Inverse of :func:`_quantize_rows` (kept adjacent so the wire format
    changes in one place).

    GRADIENT SEMANTICS: the integer wire cuts JAX's differentiation graph
    at the int8/fp8 cast — d(anything)/d(dispatched tokens) is ZERO
    through a quant-mode dispatch, silently, by standard JAX
    integer-boundary semantics (a raising custom_vjp cannot catch it:
    the backward subgraph is pruned before any bwd runs, verified
    empirically). Hence quant is a SERVING knob; training configs must
    leave it None — documented on every quant field and asserted by
    tests/test_layers.py::test_quant_dispatch_grad_is_zero."""
    return (recv_q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _quantize_rows(send: jax.Array, quant: str):
    """Per-row absmax quantization of a send slab ``[n, max_m, h]`` →
    ``(slab_q, scale [n, max_m] f32)``; all-zero (padding) rows get scale
    epsilon and quantize to exact zeros."""
    qdt, qmax = _QUANT_FORMATS[quant]
    xf = send.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / qmax, 1e-8)
    q = xf / scale[..., None]
    if quant == "int8":
        q = jnp.clip(jnp.round(q), -qmax, qmax)
    return q.astype(qdt), scale


def _pack_slabs(dest: jax.Array, n_dest: int, max_m: int):
    """Sort-and-slot slab packing shared by all dispatch paths: stable-sort
    assignments by destination, compute each one's slot in its destination
    slab, clamp to capacity. ``dest == n_dest`` is the drop sentinel (it
    indexes out of range, so ``.at[...].set(mode="drop")`` discards it,
    exactly like capacity overflow).

    Returns ``(order, dest_sorted, pos, offsets, clamped, overflow)`` —
    offsets are the UNCLAMPED group starts in the sorted layout (what the
    combine reversal indexes); ``clamped`` is what actually ships.
    """
    t = dest.shape[0]
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)
    dest_sorted = dest[order]
    counts = jnp.bincount(dest, length=n_dest + 1)[:n_dest].astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(t, dtype=jnp.int32) - offsets[
        jnp.clip(dest_sorted, 0, n_dest - 1)
    ]
    clamped = jnp.minimum(counts, max_m)
    overflow = jnp.sum(counts - clamped)
    return order, dest_sorted, pos, offsets, clamped, overflow


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DispatchInfo:
    """Bookkeeping to route combine results back to source tokens."""

    order: jax.Array         # [m_loc*topk] assignment ids sorted by dest rank
    send_splits: jax.Array   # [n] tokens actually sent per destination rank
    send_offsets: jax.Array  # [n] start of each rank's group in `order`
    recv_splits: jax.Array   # [n] tokens received per source rank
    recv_expert: jax.Array   # [n, max_m] LOCAL expert id per received row
    overflow: jax.Array      # [] assignments dropped because a slab overflowed


@dataclasses.dataclass
class EPAll2AllLayer:
    """Dispatch tokens to expert-owning ranks and combine results back.

    max_m is the per-(src,dst)-pair slab capacity; assignments beyond it are
    dropped (≙ the reference's fixed ``max_m`` symmetric buffers,
    low_latency_all_to_all.py:139-147 — size for the worst case).

    ``quant`` ("int8" / "fp8") sends the dispatch slab quantized with
    per-row absmax scales riding the metadata put — the reference's
    headline a2a configuration (fp8 payload + traveling scales,
    README.md:87): the wire bytes drop 2×/2× vs bf16 and the receiver
    dequantizes to the original dtype. INFERENCE dispatch only — rounding
    has no gradient, so quantized dispatch does not differentiate (the
    combine return path stays full-precision either way, as in the
    reference).

    The wire axis composes with the WEIGHT-side ``GroupGemmConfig.w8``
    axis (ISSUE 7): ``quant`` halves what the a2a moves (ICI), ``w8``
    halves what the local grouped GEMMs stream (HBM) — orthogonal
    resources, so the full serving posture sets both
    (``EPMoEMLP(quant="int8", gg_config=GroupGemmConfig(w8=True))``).
    """

    n_experts: int
    topk: int
    max_m: int
    axis: str = "ep"
    quant: str | None = None
    # transport schedule knob (ISSUE 4): an A2AConfig with
    # chunks_per_shard > 1 moves every dispatch/combine slab as
    # chunk-granular per-(peer, chunk) DMAs; None/chunk=1 is the legacy
    # whole-slab exchange, bit for bit
    a2a_config: A2AConfig | None = None
    interpret: Any = None

    def _world(self) -> int:
        return _axis_size(self.axis)

    def dispatch(
        self, tokens: jax.Array, topk_ids: jax.Array
    ) -> tuple[jax.Array, DispatchInfo]:
        """Send each (token, k) assignment to the rank owning its expert
        (call inside ``jax.shard_map``).

        tokens: ``[m_loc, hidden]``; topk_ids: ``[m_loc, topk]`` global
        expert ids. Returns ``(recv [n, max_m, hidden], info)`` — slab j
        holds rank j's assignments for this rank (``info.recv_splits[j]``
        valid, local expert per row in ``info.recv_expert``).
        """
        n = self._world()
        if self.n_experts % n != 0 or self.n_experts < n:
            raise ValueError(
                f"n_experts={self.n_experts} must be a positive multiple of "
                f"the {self.axis!r} axis size {n}"
            )
        _check_quant(self.quant)
        epr = self.n_experts // n
        m_loc, hidden = tokens.shape
        t = m_loc * self.topk
        flat_ids = topk_ids.reshape(-1)
        dest = flat_ids // epr                                   # [t]
        # Slab overflow drops the assignment (static max_m contract), and the
        # splits are clamped to match what was actually transported — the
        # bookkeeping must never claim more rows than the slab holds (the
        # reference fails loudly instead: assert num_tokens <= ctx.max_m,
        # low_latency_all_to_all.py:212). `overflow` surfaces undersized
        # max_m to the caller; check it in tests / debug runs.
        order, dest_sorted, pos, offsets, clamped, overflow = _pack_slabs(
            dest, n, self.max_m
        )
        send = jnp.zeros((n, self.max_m, hidden), tokens.dtype)
        send = send.at[dest_sorted, pos].set(
            tokens[order // self.topk], mode="drop"
        )
        send_exp = jnp.full((n, self.max_m), -1, jnp.int32)
        send_exp = send_exp.at[dest_sorted, pos].set(
            flat_ids[order] % epr, mode="drop"
        )
        if self.quant is not None:
            # quantized wire format: int8/fp8 slab, per-row f32 scales
            # bitcast onto the SAME metadata put as the expert ids — the
            # transport cost of quantized dispatch is the halved payload,
            # zero extra collectives (≙ the reference's scales traveling
            # with the data, low_latency_all_to_all.py:94-104)
            send_q, scale = _quantize_rows(send, self.quant)
            meta = jnp.concatenate(
                [send_exp, jax.lax.bitcast_convert_type(scale, jnp.int32)],
                axis=1,
            )
            recv_q, recv_splits, meta_r = fast_all_to_all(
                send_q, clamped, meta=meta, axis=self.axis,
                config=self.a2a_config, interpret=self.interpret,
            )
            recv_exp = meta_r[:, : self.max_m]
            r_scale = jax.lax.bitcast_convert_type(
                meta_r[:, self.max_m :], jnp.float32
            )
            recv = _dequantize_rows(recv_q, r_scale, tokens.dtype)
        else:
            # expert ids ride the splits payload of the SAME a2a — dispatch
            # costs exactly one collective call (VERDICT r1 weak #7)
            recv, recv_splits, recv_exp = fast_all_to_all_grad(
                send, clamped, send_exp, self.axis, self.interpret,
                self.a2a_config,
            )
        info = DispatchInfo(
            order=order,
            send_splits=clamped,
            send_offsets=offsets,
            recv_splits=recv_splits,
            recv_expert=recv_exp,
            overflow=overflow,
        )
        return recv, info

    def receiver_alignment(
        self, info: DispatchInfo, block_m: int, *, ragged: bool = False
    ) -> MoEAlignment:
        """Block-align the received rows by LOCAL expert for group_gemm.
        Invalid (padding) rows go to a virtual trailing expert whose blocks
        compute garbage on clamped weights; combine drops them —
        ``ragged=True`` skips them in-kernel instead (ISSUE 5)."""
        n = self._world()
        epr = self.n_experts // n
        return _align_received(
            info.recv_expert, info.recv_splits, self.max_m, epr, block_m,
            ragged=ragged,
        )

    def combine(
        self,
        y: jax.Array,
        info: DispatchInfo,
        topk_weights: jax.Array,
        m_loc: int,
    ) -> jax.Array:
        """Return expert outputs to their source ranks and reduce top-k
        (push-based: the dispatch a2a in reverse — ≙ the remote-load
        combine of ep_a2a.py:151-239 restructured as puts).

        y: ``[n, max_m, h]`` expert outputs in the *received* slab layout.
        topk_weights: ``[m_loc, topk]``. Returns ``[m_loc, h]``.
        """
        n = self._world()
        back, _, _ = fast_all_to_all_grad(
            y, info.recv_splits, None, self.axis, self.interpret,
            self.a2a_config,
        )
        # slab p row i ↔ sorted assignment offsets[p]+i ↔ assignment order[...]
        # (offsets from the UNCLAMPED counts — they index the sorted
        # assignment list; validity is bounded by the clamped send_splits)
        h = y.shape[-1]
        offsets = info.send_offsets
        flat = back.reshape(n * self.max_m, h)
        pos = jnp.arange(n * self.max_m, dtype=jnp.int32) % self.max_m
        slab = jnp.arange(n * self.max_m, dtype=jnp.int32) // self.max_m
        valid = pos < info.send_splits[slab]
        sorted_pos = jnp.clip(offsets[slab] + pos, 0, info.order.shape[0] - 1)
        assignment = info.order[sorted_pos]
        w = jnp.where(valid, topk_weights.reshape(-1)[assignment], 0.0)
        token = assignment // self.topk
        out = jnp.zeros((m_loc, h), jnp.float32)
        return out.at[token].add(
            jnp.where(valid[:, None], flat.astype(jnp.float32) * w[:, None], 0.0)
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HierDispatchInfo:
    """Bookkeeping for the two-phase dispatch: phase-1 routes over the
    outer axis (one copy per token per destination NODE), phase-2 scatters
    by expert over the inner axis at the relay."""

    # phase 1 (source PE, outer axis)
    order1: jax.Array         # [m_loc*topk] assignment ids sorted by dest node
    send_splits1: jax.Array   # [n_o] rows actually sent per node
    send_offsets1: jax.Array  # [n_o] group starts in order1's sorted layout
    recv_splits1: jax.Array   # [n_o] rows received per source node
    # phase 2 (relay PE, inner axis)
    order2: jax.Array         # [R*topk] relay assignment ids sorted by dest PE
    send_splits2: jax.Array   # [n_i]
    send_offsets2: jax.Array  # [n_i]
    recv_splits2: jax.Array   # [n_i]
    recv_expert: jax.Array    # [n_i, max_m2] LOCAL expert id per received row
    rel_weights: jax.Array    # [R, topk] f32 routing weights at the relay
    overflow: jax.Array       # [] dropped assignments (either phase)


@dataclasses.dataclass
class HierEPAll2AllLayer:
    """Hierarchical (two-phase) EP dispatch/combine over a 2-D mesh
    ``(outer, inner)`` — the DeepEP-style design of reference
    ``ep_a2a.py:36-147`` (dispatch) / ``:151-239`` (combine), restructured
    push-only for TPU:

    - **Dispatch phase 1** (slow axis): each token crosses the outer axis
      ONCE per destination node — assignments to several experts on the
      same node are deduplicated (the reference's warp-granular contiguous
      range put to the same-local-rank peer). Expert ids and routing
      weights ride the a2a's metadata payload.
    - **Dispatch phase 2** (fast axis): the relay (same outer group as the
      experts) scatters each row to the PEs owning its experts (the
      reference's intra-node scatter with atomic slot allocation —
      here the same sort/scan slotting as the flat layer).
    - **Combine** reverses both: expert outputs return over the inner
      axis, the relay WEIGHT-REDUCES all same-node expert contributions
      per row (the reference's intra-node reduction before the cross-node
      return, :199-203 — the bandwidth point of the hierarchy: only one
      partial per (token, node) re-crosses the slow axis), then the outer
      reverse a2a returns node-partials that the source sums per token.

    Expert placement matches the flat layer over the flattened
    (outer-major) rank order: expert ``e`` on rank ``e // epr`` =
    (outer ``rank // n_i``, inner ``rank % n_i``).

    Differentiable end-to-end with EXACT forward numerics: routing weights
    travel bitcast-f32 on the integer metadata put (the value used in
    combine — no rounding for bf16/fp8 slabs) AND as topk data-slab columns
    (the differentiable channel); a straight-through sum gives combine the
    exact value with the slab channel's gradient.
    """

    n_experts: int
    topk: int
    max_m1: int   # per-(src, dest-node) slab capacity, phase 1
    max_m2: int   # per-(relay, dest-PE) slab capacity, phase 2
    outer: str = "dp"
    inner: str = "tp"
    # "int8" / "fp8": quantize the PHASE-1 payload — the slow (node/DCN)
    # axis, where the hierarchy's bandwidth win lives — with per-row
    # scales riding the metadata put (≙ the reference's fp8 LL dispatch,
    # README.md:87). INFERENCE only: quant mode drops the differentiable
    # slab weight channel (the bitcast-exact metadata weights serve the
    # forward), so the router gradient is cut. Phase 2 (fast ICI) stays
    # in the token dtype.
    quant: str | None = None
    # chunk-granular transport schedule for BOTH phases (ISSUE 4); None /
    # chunk=1 is the legacy whole-slab exchange (see EPAll2AllLayer)
    a2a_config: A2AConfig | None = None
    interpret: Any = None

    def _dims(self) -> tuple[int, int]:
        return _axis_size(self.outer), _axis_size(self.inner)

    def dispatch(
        self,
        tokens: jax.Array,
        topk_ids: jax.Array,
        topk_weights: jax.Array,
    ) -> tuple[jax.Array, HierDispatchInfo]:
        """tokens ``[m_loc, hidden]``, topk_ids/weights ``[m_loc, topk]``.
        Returns ``(recv [n_i, max_m2, hidden], info)`` in the phase-2 slab
        layout (local expert per row in ``info.recv_expert``). Weights are
        carried along so :meth:`combine` takes none."""
        n_o, n_i = self._dims()
        if self.n_experts % (n_o * n_i) != 0:
            raise ValueError(
                f"n_experts={self.n_experts} must divide over the "
                f"{n_o}x{n_i} mesh"
            )
        _check_quant(self.quant)
        epr = self.n_experts // (n_o * n_i)
        m_loc, hidden = tokens.shape
        t = m_loc * self.topk
        my_o = jax.lax.axis_index(self.outer)

        # ---- phase 1: one copy per (token, destination node) ----
        flat_ids = topk_ids.reshape(-1)                       # [t]
        owner = flat_ids // epr
        dest_o = owner // n_i                                 # [t]
        # dedup: keep only the FIRST assignment per (token, node) — an
        # assignment is a duplicate iff an earlier k of the SAME token
        # targets the same node (the hierarchy's traffic win)
        ids2d = dest_o.reshape(m_loc, self.topk)
        dup = jnp.zeros((m_loc, self.topk), bool)
        for k in range(1, self.topk):
            same = (ids2d[:, :k] == ids2d[:, k : k + 1]).any(axis=1)
            dup = dup.at[:, k].set(same)
        keep = ~dup.reshape(-1)                               # [t]

        dest1 = jnp.where(keep, dest_o, n_o)                  # drop sentinel
        order1, dest1_sorted, pos1, offsets1, clamped1, overflow1 = _pack_slabs(
            dest1, n_o, self.max_m1
        )
        # metadata per row: the token's full topk ids + bitcast f32 weights
        # (the relay filters to its own node's experts)
        meta_ids = jnp.full((n_o, self.max_m1, self.topk), -1, jnp.int32)
        meta_w = jnp.zeros((n_o, self.max_m1, self.topk), jnp.int32)
        row_ids = topk_ids.astype(jnp.int32)[order1 // self.topk]
        row_w = jax.lax.bitcast_convert_type(
            topk_weights.astype(jnp.float32), jnp.int32
        )[order1 // self.topk]
        meta_ids = meta_ids.at[dest1_sorted, pos1].set(row_ids, mode="drop")
        meta_w = meta_w.at[dest1_sorted, pos1].set(row_w, mode="drop")
        if self.quant is not None:
            # inference wire format on the slow axis: int8/fp8 token slab
            # (no weight columns — the bitcast-exact metadata weights
            # serve the forward; no gradient in quant mode), per-row
            # scales as a third metadata chunk
            send1 = jnp.zeros((n_o, self.max_m1, hidden), tokens.dtype)
            send1 = send1.at[dest1_sorted, pos1].set(
                tokens[order1 // self.topk], mode="drop"
            )
            send1_q, scale1 = _quantize_rows(send1, self.quant)
            meta1 = jnp.concatenate(
                [
                    meta_ids.reshape(n_o, -1),
                    meta_w.reshape(n_o, -1),
                    jax.lax.bitcast_convert_type(scale1, jnp.int32),
                ],
                axis=1,
            )
            recv1_q, recv_splits1, rmeta1 = fast_all_to_all(
                send1_q, clamped1, meta=meta1, axis=self.outer,
                config=self.a2a_config, interpret=self.interpret,
            )
            k_w = self.max_m1 * self.topk
            rel_ids = rmeta1[:, :k_w].reshape(-1, self.topk)    # [R, topk]
            rel_w = jax.lax.bitcast_convert_type(
                rmeta1[:, k_w : 2 * k_w].reshape(-1, self.topk), jnp.float32
            )
            r_scale1 = jax.lax.bitcast_convert_type(
                rmeta1[:, 2 * k_w :], jnp.float32
            )
            recv1 = _dequantize_rows(recv1_q, r_scale1, tokens.dtype)
            R = n_o * self.max_m1
            rows = recv1.reshape(R, hidden)
        else:
            # routing WEIGHTS travel on BOTH channels: bitcast-exact f32
            # on the int metadata put (the forward VALUE — no rounding,
            # whatever the slab dtype) and as topk extra data-slab columns
            # (the DIFFERENTIABLE channel — int metadata would cut the
            # router gradient). A straight-through combine below uses the
            # exact value with the slab channel's gradient.
            row_payload = jnp.concatenate(
                [tokens, topk_weights.astype(tokens.dtype)], axis=1
            )                                                 # [m_loc, H+topk]
            send1 = jnp.zeros(
                (n_o, self.max_m1, hidden + self.topk), tokens.dtype
            )
            send1 = send1.at[dest1_sorted, pos1].set(
                row_payload[order1 // self.topk], mode="drop"
            )
            meta1 = jnp.concatenate(
                [meta_ids.reshape(n_o, -1), meta_w.reshape(n_o, -1)], axis=1
            )
            recv1, recv_splits1, rmeta1 = fast_all_to_all_grad(
                send1, clamped1, meta1, self.outer, self.interpret,
                self.a2a_config,
            )
            rmeta1 = rmeta1.reshape(n_o, 2, self.max_m1, self.topk)
            rel_ids = rmeta1[:, 0].reshape(-1, self.topk)      # [R, topk]
            exact_w = jax.lax.bitcast_convert_type(
                rmeta1[:, 1].reshape(-1, self.topk), jnp.float32
            )
            R = n_o * self.max_m1
            rows_full = recv1.reshape(R, hidden + self.topk)
            rows = rows_full[:, :hidden]
            slab_w = rows_full[:, hidden:].astype(jnp.float32)  # [R, topk]
            # straight-through: VALUE = the bitcast-exact weights,
            # GRADIENT = the differentiable slab channel's (identity
            # cotangent)
            rel_w = exact_w + (slab_w - jax.lax.stop_gradient(slab_w))

        # ---- phase 2: relay scatters rows to expert-owning inner PEs ----
        pos_r = jnp.arange(R, dtype=jnp.int32) % self.max_m1
        slab_r = jnp.arange(R, dtype=jnp.int32) // self.max_m1
        row_valid = pos_r < recv_splits1[slab_r]               # [R]
        g = rel_ids.reshape(-1)                                # [R*topk]
        g_owner = jnp.where(g >= 0, g // epr, 0)
        g_outer = g_owner // n_i
        g_inner = g_owner % n_i
        amask = (
            jnp.repeat(row_valid, self.topk)
            & (g >= 0)
            & (g_outer == my_o)
        )
        dest2 = jnp.where(amask, g_inner, n_i)
        order2, dest2_sorted, pos2, offsets2, clamped2, overflow2 = _pack_slabs(
            dest2, n_i, self.max_m2
        )
        send2 = jnp.zeros((n_i, self.max_m2, hidden), tokens.dtype)
        send2 = send2.at[dest2_sorted, pos2].set(
            rows[order2 // self.topk], mode="drop"
        )
        send_exp2 = jnp.full((n_i, self.max_m2), -1, jnp.int32)
        send_exp2 = send_exp2.at[dest2_sorted, pos2].set(
            jnp.where(g >= 0, g % epr, -1)[order2], mode="drop"
        )
        recv2, recv_splits2, recv_exp2 = fast_all_to_all_grad(
            send2, clamped2, send_exp2, self.inner, self.interpret,
            self.a2a_config,
        )
        info = HierDispatchInfo(
            order1=order1, send_splits1=clamped1, send_offsets1=offsets1,
            recv_splits1=recv_splits1,
            order2=order2, send_splits2=clamped2, send_offsets2=offsets2,
            recv_splits2=recv_splits2, recv_expert=recv_exp2,
            rel_weights=rel_w, overflow=overflow1 + overflow2,
        )
        return recv2, info

    def receiver_alignment(
        self, info: HierDispatchInfo, block_m: int, *, ragged: bool = False
    ) -> MoEAlignment:
        """Block-align received rows by LOCAL expert for group_gemm (same
        scheme as the flat layer's)."""
        n_o, n_i = self._dims()
        epr = self.n_experts // (n_o * n_i)
        return _align_received(
            info.recv_expert, info.recv_splits2, self.max_m2, epr, block_m,
            ragged=ragged,
        )

    def combine(self, y: jax.Array, info: HierDispatchInfo, m_loc: int) -> jax.Array:
        """Reverse both phases, weight-reducing at the relay so only one
        partial per (token, node) re-crosses the outer axis."""
        n_o, n_i = self._dims()
        h = y.shape[-1]
        R = n_o * self.max_m1

        # reverse phase 2 (inner axis): expert outputs back to the relay
        back2, _, _ = fast_all_to_all_grad(
            y, info.recv_splits2, None, self.inner, self.interpret,
            self.a2a_config,
        )
        flat2 = back2.reshape(n_i * self.max_m2, h)
        pos2 = jnp.arange(n_i * self.max_m2, dtype=jnp.int32) % self.max_m2
        slab2 = jnp.arange(n_i * self.max_m2, dtype=jnp.int32) // self.max_m2
        valid2 = pos2 < info.send_splits2[slab2]
        sorted_pos2 = jnp.clip(
            info.send_offsets2[slab2] + pos2, 0, info.order2.shape[0] - 1
        )
        a2 = info.order2[sorted_pos2]                 # relay assignment ids
        r_row = a2 // self.topk                       # phase-1 row
        k_slot = a2 % self.topk
        w = jnp.where(valid2, info.rel_weights[r_row, k_slot], 0.0)
        partial = jnp.zeros((R, h), jnp.float32)
        partial = partial.at[r_row].add(
            jnp.where(valid2[:, None], flat2.astype(jnp.float32) * w[:, None], 0.0)
        )

        # reverse phase 1 (outer axis): node-partials back to the source
        back1, _, _ = fast_all_to_all_grad(
            partial.reshape(n_o, self.max_m1, h).astype(y.dtype),
            info.recv_splits1, None, self.outer, self.interpret,
            self.a2a_config,
        )
        flat1 = back1.reshape(R, h)
        pos1 = jnp.arange(R, dtype=jnp.int32) % self.max_m1
        slab1 = jnp.arange(R, dtype=jnp.int32) // self.max_m1
        valid1 = pos1 < info.send_splits1[slab1]
        sorted_pos1 = jnp.clip(
            info.send_offsets1[slab1] + pos1, 0, info.order1.shape[0] - 1
        )
        a1 = info.order1[sorted_pos1]
        token = a1 // self.topk
        out = jnp.zeros((m_loc, h), jnp.float32)
        return out.at[token].add(
            jnp.where(valid1[:, None], flat1.astype(jnp.float32), 0.0)
        )


def _align_received(
    recv_expert: jax.Array, recv_splits: jax.Array, max_m: int,
    epr: int, block_m: int, ragged: bool = False,
) -> MoEAlignment:
    """Shared receiver-side block alignment (flat + hierarchical layers).

    ``ragged=True`` (ISSUE 5) additionally carries the per-block live-row
    map, with the virtual trailing expert's blocks zeroed outright: its
    rows are slab-padding tokens whose outputs the combine drops anyway —
    under the padded contract those blocks compute garbage on clamped
    weights; ragged skips them entirely."""
    flat_exp = recv_expert.reshape(-1)
    pos = jnp.arange(flat_exp.shape[0], dtype=jnp.int32) % max_m
    slab = jnp.arange(flat_exp.shape[0], dtype=jnp.int32) // max_m
    valid = pos < recv_splits[slab]
    padded_exp = jnp.where(valid, flat_exp, epr)
    al = moe_align_block_size(padded_exp, epr + 1, block_m, ragged=ragged)
    valid_rows = al.valid_rows
    if ragged:
        valid_rows = jnp.where(al.expert_ids >= epr, 0, valid_rows)
    return MoEAlignment(
        sorted_token_ids=al.sorted_token_ids,
        expert_ids=jnp.minimum(al.expert_ids, epr - 1),
        num_tokens_post_pad=al.num_tokens_post_pad,
        valid_rows=valid_rows,
    )
