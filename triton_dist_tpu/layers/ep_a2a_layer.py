"""EP All-to-All layer — expert-parallel MoE dispatch/combine
(≙ reference ``layers/nvidia/ep_a2a_layer.py:41`` ``EPAll2AllLayer`` over
the DeepEP-style kernels of ``ep_a2a.py`` and
``low_latency_all_to_all.py``).

Reference flow: warp-granular put of contiguous token ranges to the
same-local-rank peer, intra-node scatter by expert with atomic slot
allocation, combine via remote ``symm_at`` loads (SURVEY.md §2.3). TPU has
no remote loads, so combine is push-based (the dispatch in reverse) — the
restructuring SURVEY.md §7 calls out. All data moves through the padded-slab
``fast_all_to_all``; routing bookkeeping (sort by destination rank, slab
packing, weighted un-permutation) is XLA gather/scatter.

Expert placement: experts_per_rank = n_experts // world; expert ``e`` lives
on rank ``e // experts_per_rank`` as local expert ``e % experts_per_rank``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.all_to_all import fast_all_to_all
from triton_dist_tpu.ops.moe_utils import MoEAlignment, moe_align_block_size


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DispatchInfo:
    """Bookkeeping to route combine results back to source tokens."""

    order: jax.Array         # [m_loc*topk] assignment ids sorted by dest rank
    send_splits: jax.Array   # [n] tokens actually sent per destination rank
    send_offsets: jax.Array  # [n] start of each rank's group in `order`
    recv_splits: jax.Array   # [n] tokens received per source rank
    recv_expert: jax.Array   # [n, max_m] LOCAL expert id per received row
    overflow: jax.Array      # [] assignments dropped because a slab overflowed


@dataclasses.dataclass
class EPAll2AllLayer:
    """Dispatch tokens to expert-owning ranks and combine results back.

    max_m is the per-(src,dst)-pair slab capacity; assignments beyond it are
    dropped (≙ the reference's fixed ``max_m`` symmetric buffers,
    low_latency_all_to_all.py:139-147 — size for the worst case).
    """

    n_experts: int
    topk: int
    max_m: int
    axis: str = "ep"
    interpret: Any = None

    def _world(self) -> int:
        return int(jax.lax.axis_size(self.axis))

    def dispatch(
        self, tokens: jax.Array, topk_ids: jax.Array
    ) -> tuple[jax.Array, DispatchInfo]:
        """Send each (token, k) assignment to the rank owning its expert
        (call inside ``jax.shard_map``).

        tokens: ``[m_loc, hidden]``; topk_ids: ``[m_loc, topk]`` global
        expert ids. Returns ``(recv [n, max_m, hidden], info)`` — slab j
        holds rank j's assignments for this rank (``info.recv_splits[j]``
        valid, local expert per row in ``info.recv_expert``).
        """
        n = self._world()
        if self.n_experts % n != 0 or self.n_experts < n:
            raise ValueError(
                f"n_experts={self.n_experts} must be a positive multiple of "
                f"the {self.axis!r} axis size {n}"
            )
        epr = self.n_experts // n
        m_loc, hidden = tokens.shape
        t = m_loc * self.topk
        flat_ids = topk_ids.reshape(-1)
        dest = flat_ids // epr                                   # [t]
        order = jnp.argsort(dest, stable=True).astype(jnp.int32)
        dest_sorted = dest[order]
        counts = jnp.bincount(dest, length=n).astype(jnp.int32)
        offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
        pos = (jnp.arange(t, dtype=jnp.int32) - offsets[dest_sorted])
        # Slab overflow drops the assignment (static max_m contract), and the
        # splits are clamped to match what was actually transported — the
        # bookkeeping must never claim more rows than the slab holds (the
        # reference fails loudly instead: assert num_tokens <= ctx.max_m,
        # low_latency_all_to_all.py:212). `overflow` surfaces undersized
        # max_m to the caller; check it in tests / debug runs.
        clamped = jnp.minimum(counts, self.max_m)
        overflow = jnp.sum(counts - clamped)
        send = jnp.zeros((n, self.max_m, hidden), tokens.dtype)
        send = send.at[dest_sorted, pos].set(
            tokens[order // self.topk], mode="drop"
        )
        send_exp = jnp.full((n, self.max_m), -1, jnp.int32)
        send_exp = send_exp.at[dest_sorted, pos].set(
            flat_ids[order] % epr, mode="drop"
        )
        # expert ids ride the splits payload of the SAME a2a — dispatch
        # costs exactly one collective call (VERDICT r1 weak #7)
        recv, recv_splits, recv_exp = fast_all_to_all(
            send, clamped, meta=send_exp, axis=self.axis,
            interpret=self.interpret,
        )
        info = DispatchInfo(
            order=order,
            send_splits=clamped,
            send_offsets=offsets,
            recv_splits=recv_splits,
            recv_expert=recv_exp,
            overflow=overflow,
        )
        return recv, info

    def receiver_alignment(
        self, info: DispatchInfo, block_m: int
    ) -> MoEAlignment:
        """Block-align the received rows by LOCAL expert for group_gemm.
        Invalid (padding) rows go to a virtual trailing expert whose blocks
        compute garbage on clamped weights; combine drops them."""
        n = self._world()
        epr = self.n_experts // n
        flat_exp = info.recv_expert.reshape(-1)
        pos = jnp.arange(flat_exp.shape[0], dtype=jnp.int32) % self.max_m
        slab = jnp.arange(flat_exp.shape[0], dtype=jnp.int32) // self.max_m
        valid = pos < info.recv_splits[slab]
        padded_exp = jnp.where(valid, flat_exp, epr)
        al = moe_align_block_size(padded_exp, epr + 1, block_m)
        return MoEAlignment(
            sorted_token_ids=al.sorted_token_ids,
            expert_ids=jnp.minimum(al.expert_ids, epr - 1),
            num_tokens_post_pad=al.num_tokens_post_pad,
        )

    def combine(
        self,
        y: jax.Array,
        info: DispatchInfo,
        topk_weights: jax.Array,
        m_loc: int,
    ) -> jax.Array:
        """Return expert outputs to their source ranks and reduce top-k
        (push-based: the dispatch a2a in reverse — ≙ the remote-load
        combine of ep_a2a.py:151-239 restructured as puts).

        y: ``[n, max_m, h]`` expert outputs in the *received* slab layout.
        topk_weights: ``[m_loc, topk]``. Returns ``[m_loc, h]``.
        """
        n = self._world()
        back, _ = fast_all_to_all(
            y, info.recv_splits, axis=self.axis, interpret=self.interpret
        )
        # slab p row i ↔ sorted assignment offsets[p]+i ↔ assignment order[...]
        # (offsets from the UNCLAMPED counts — they index the sorted
        # assignment list; validity is bounded by the clamped send_splits)
        h = y.shape[-1]
        offsets = info.send_offsets
        flat = back.reshape(n * self.max_m, h)
        pos = jnp.arange(n * self.max_m, dtype=jnp.int32) % self.max_m
        slab = jnp.arange(n * self.max_m, dtype=jnp.int32) // self.max_m
        valid = pos < info.send_splits[slab]
        sorted_pos = jnp.clip(offsets[slab] + pos, 0, info.order.shape[0] - 1)
        assignment = info.order[sorted_pos]
        w = jnp.where(valid, topk_weights.reshape(-1)[assignment], 0.0)
        token = assignment // self.topk
        out = jnp.zeros((m_loc, h), jnp.float32)
        return out.at[token].add(
            jnp.where(valid[:, None], flat.astype(jnp.float32) * w[:, None], 0.0)
        )
