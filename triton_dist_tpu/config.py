"""Global configuration for triton_dist_tpu.

The single most important switch is *interpret mode*: every distributed
Pallas kernel in this framework runs either compiled via Mosaic (on real TPU)
or under the TPU interpreter (``pltpu.InterpretParams``) which simulates
remote DMAs, semaphores and multi-core timing on CPU — including an optional
happens-before race detector (``detect_races=True``).

This replaces the reference's noise-injection "race shaking"
(Triton-distributed ``allgather.py:72-76``) with a real race detector, and is
what lets the full SPMD test-suite run on an
``--xla_force_host_platform_device_count=8`` virtual mesh.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import jax


@dataclasses.dataclass
class Config:
    # None = auto: interpret on non-TPU backends, compiled on TPU.
    interpret: bool | None = None
    # Enable the TPU interpreter's happens-before race detector.
    detect_races: bool = False
    # 'on_wait' mimics real DMA async semantics; 'eager' is faster.
    dma_execution_mode: str = "on_wait"
    # Print autotuner decisions.
    verbose_autotune: bool = bool(int(os.environ.get("TDT_VERBOSE_AUTOTUNE", "0")))


_config = Config()


def get_config() -> Config:
    return _config


def update(**kwargs: Any) -> None:
    for k, v in kwargs.items():
        if not hasattr(_config, k):
            raise ValueError(f"unknown config key: {k}")
        setattr(_config, k, v)


def on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


_cpu_tpu_info_registered = False


def _ensure_cpu_tpu_info() -> None:
    """Teach Pallas's TPU-info query about the CPU interpreter.

    ``pltpu.emit_pipeline`` asks for the current device's TPU generation to
    pick tilings; on the CPU backend that lookup fails. The module exposes a
    ``registry`` extension point for unknown device kinds — we register a
    v5e-lookalike for ``"cpu"`` so interpreted kernels tile like a real TPU.
    """
    global _cpu_tpu_info_registered
    if _cpu_tpu_info_registered:
        return
    try:
        from jax._src.pallas.mosaic import tpu_info

        def _cpu_info():
            return tpu_info.TpuInfo(
                chip_version=tpu_info.ChipVersion.TPU_V5E,
                generation=5,
                num_cores=1,
                num_lanes=128,
                num_sublanes=8,
                mxu_column_size=128,
                vmem_capacity_bytes=128 * 1024 * 1024,
                cmem_capacity_bytes=0,
                smem_capacity_bytes=1024 * 1024,
                hbm_capacity_bytes=17_200_000_000,
                mem_bw_bytes_per_second=int(8.20e11),
                bf16_ops_per_second=int(1.97e14),
                int8_ops_per_second=int(3.94e14),
                fp8_ops_per_second=0,
                int4_ops_per_second=int(7.88e14),
            )

        tpu_info.registry.setdefault("cpu", _cpu_info)
    except Exception:
        pass
    _cpu_tpu_info_registered = True


def interpret_params():
    """Resolve the `interpret=` argument for pallas_call.

    Returns False (compiled) on TPU backends, or a ``pltpu.InterpretParams``
    configured from the global config elsewhere (CPU tests, dry runs).
    """
    from jax.experimental.pallas import tpu as pltpu

    cfg = get_config()
    use_interpret = cfg.interpret if cfg.interpret is not None else not on_tpu()
    if not use_interpret:
        return False
    _ensure_cpu_tpu_info()
    return pltpu.InterpretParams(
        detect_races=cfg.detect_races,
        dma_execution_mode=cfg.dma_execution_mode,
        # Distributed kernels intentionally read buffers that are filled by
        # remote DMAs; OOB reads stay fatal but uninit memory must be lax.
        uninitialized_memory="zero",
        out_of_bounds_reads="raise",
    )
