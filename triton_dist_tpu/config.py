"""Global configuration for triton_dist_tpu.

The single most important switch is *interpret mode*: every distributed
Pallas kernel in this framework runs either compiled via Mosaic (on real TPU)
or under the TPU interpreter (``pltpu.InterpretParams``) which simulates
remote DMAs, semaphores and multi-core timing on CPU — including an optional
happens-before race detector (``detect_races=True``).

This replaces the reference's noise-injection "race shaking"
(Triton-distributed ``allgather.py:72-76``) with a real race detector, and is
what lets the full SPMD test-suite run on an
``--xla_force_host_platform_device_count=8`` virtual mesh.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import jax


@dataclasses.dataclass
class Config:
    # None = auto: interpret on non-TPU backends, compiled on TPU.
    interpret: bool | None = None
    # Enable the TPU interpreter's happens-before race detector.
    detect_races: bool = False
    # 'on_wait' mimics real DMA async semantics; 'eager' is faster.
    dma_execution_mode: str = "on_wait"
    # Fail loudly when EP dispatch drops assignments to slab overflow
    # (≙ the reference's assert, low_latency_all_to_all.py:212): prints a
    # host-side diagnostic AND NaN-poisons the layer output so an
    # undersized max_m can never silently zero expert contributions in a
    # training run (see also layers.ep_moe_mlp.assert_no_overflow for a
    # host-side hard stop on the fetched counter).
    debug_ep_overflow: bool = False
    # Print autotuner decisions.
    verbose_autotune: bool = bool(int(os.environ.get("TDT_VERBOSE_AUTOTUNE", "0")))
    # Hardware race shaking (≙ the reference's random comm-stream sleeps,
    # allgather.py:72-76): > 0 inserts a per-PE pseudo-random busy delay
    # of roughly this many VPU loop iterations at the top of every fused
    # comm kernel, skewing issue timing so arrival-order and
    # barrier-aliasing assumptions get exercised under timing variance
    # the interpreter's happens-before detector cannot model (its
    # schedule is data-dependency-driven, not time-driven). Debug/stress
    # only — tpu_smoke.py runs a delayed pass on real chips; keep 0 in
    # production. Env: TDT_COMM_DELAY.
    debug_comm_delay: int = int(os.environ.get("TDT_COMM_DELAY", "0"))
    # USER-DECLARED mesh axes whose hops cross TPU slice boundaries
    # (Multislice DCN, not ICI). Remote-DMA kernels cannot reach across
    # slices, so collective ops lower these axes to XLA collectives
    # (which ride DCN) and keep the fused kernels on the ICI axes. Real
    # Multislice meshes are AUTO-detected separately (scoped per mesh:
    # ``topology.register_mesh_dcn``, called by ``make_mesh``); declare
    # here only for virtual meshes / tests (≙ the reference treating its
    # inter-node plane differently from NVLink, allgather.py:291-375).
    # Ops consult ``topology.is_dcn_axis_name`` = declared ∪ detected.
    dcn_axes: tuple = ()
    # --- resilience subsystem (docs/resilience.md) ---------------------
    # Watchdog budget for every distributed wait (signal_wait_until /
    # wait / barrier_all rounds), in POLL ITERATIONS, not wall time:
    # > 0 arms bounded waits that, on expiry, write a structured
    # diagnostic record into the kernel's diag buffer, NaN-poison the
    # output, and surface host-side as resilience.DistTimeoutError.
    # 0 (default) keeps the classic blocking waits — zero overhead, no
    # extra kernel outputs. Calibrate per deployment: a compiled poll
    # iteration is tens of ns; an interpret-mode iteration costs a host
    # callback (chaos tests use small budgets). Env: TDT_TIMEOUT_ITERS.
    timeout_iters: int = int(os.environ.get("TDT_TIMEOUT_ITERS", "0"))
    # On a watchdog trip: True raises DistTimeoutError from the op entry
    # (serving code sees a loud, decodable failure); False returns the
    # fully NaN-poisoned output instead and only records the event in
    # resilience.health (for pipelines that prefer poison-and-continue).
    raise_on_timeout: bool = True
    # Armed resilience.FaultPlan (interpret-mode signal chaos: drop /
    # duplicate / delay a signal op, straggle a PE) — see
    # resilience/faults.py and tests/test_chaos.py. None = no injection.
    fault_plan: object = None
    # Graceful degradation: let resilience.guarded_call serve the golden
    # jax.lax collective path when a fused op fails for environmental
    # reasons (Mosaic compile failure, unsupported topology, missing jax
    # API), recording the downgrade in resilience.health. False = every
    # failure is loud (CI posture). Env: TDT_FALLBACK_TO_XLA.
    fallback_to_xla: bool = bool(int(os.environ.get("TDT_FALLBACK_TO_XLA", "1")))
    # --- elastic degraded mode (docs/resilience.md) --------------------
    # Armed resilience.RetryPolicy: watchdog-armed op entries retry
    # TRANSIENT failures (DistTimeoutError — comm jitter, one lost
    # signal) with deterministic exponential backoff before escalating;
    # deterministic failures (compile/shape/API) are never retried and
    # keep going straight to the golden-path guard. None (default)
    # disables retry entirely — op entries take the pre-existing
    # single-attempt path with zero added per-step work.
    retry_policy: object = None
    # PE quarantine + topology shrink (resilience/elastic.py): attribute
    # watchdog timeouts to a straggler peer, quarantine it after
    # suspect_threshold strikes, rebuild collectives over the survivors
    # (elastic.effective_mesh), probe with a cheap barrier and re-admit
    # after probation_probes clean probes. False (default) = every
    # elastic entry point is a no-op and effective_mesh is identity.
    elastic: bool = False
    # Timeouts attributed to one peer before it is quarantined (the
    # first strike only marks it suspect; clean steps decay strikes).
    suspect_threshold: int = 2
    # Clean world-barrier probes required to re-admit a quarantined PE.
    probation_probes: int = 1
    # --- data-integrity layer (ISSUE 8, docs/resilience.md) ------------
    # Armed resilience.IntegrityConfig: host-tier output guards (finite
    # check + optional magnitude envelope) at every guarded op entry, the
    # serving engine's per-request NaN-logit quarantine, and — with
    # canary=True on top of an armed watchdog — per-chunk payload
    # checksums riding the chunked puts' existing signal slots. Detection
    # is observation-only on the happy path (clean runs stay bit-exact);
    # a tripped check raises resilience.IntegrityError and runs the
    # recovery ladder (retry → golden fallback → PE strikes). None
    # (default) = no checks, zero added work anywhere.
    integrity: object = None
    # --- observability layer (ISSUE 9, docs/observability.md) ----------
    # Armed obs.ObsConfig: host-side span tracing (guarded op entries
    # with their ladder rung, jit trace-vs-cached dispatch, autotune
    # sweeps, serving lifecycle) on the injectable resilience clock, and
    # — with wait_stats=True on top of an armed watchdog — a per-kernel
    # wait-telemetry buffer recording every bounded wait site's observed
    # spin count (success path included; rides the diag-output plumbing,
    # NO new signal edges). Exported via obs.export_chrome_trace() /
    # obs.snapshot() / bench.py --obs-trace. None (default) = no spans,
    # zero new kernel outputs, bit-exact op results.
    obs: object = None


_config = Config()


def get_config() -> Config:
    return _config


def update(**kwargs: Any) -> None:
    for k, v in kwargs.items():
        if not hasattr(_config, k):
            raise ValueError(f"unknown config key: {k}")
        if k == "fault_plan":
            from triton_dist_tpu.resilience import faults as _faults

            if v is not None:
                if not isinstance(v, _faults.FaultPlan):
                    raise ValueError(
                        f"fault_plan must be a resilience.FaultPlan (or None), "
                        f"got {type(v).__name__}"
                    )
                v.validate()
            # a (re)armed plan starts with a full trigger budget
            _faults.reset_triggers()
        if k == "integrity" and v is not None:
            from triton_dist_tpu.resilience.integrity import IntegrityConfig

            if not isinstance(v, IntegrityConfig):
                raise ValueError(
                    f"integrity must be a resilience.IntegrityConfig (or "
                    f"None), got {type(v).__name__}"
                )
            v.validate()
        if k == "obs" and v is not None:
            from triton_dist_tpu.obs import ObsConfig

            if not isinstance(v, ObsConfig):
                raise ValueError(
                    f"obs must be an obs.ObsConfig (or None), got "
                    f"{type(v).__name__}"
                )
            v.validate()
        if k == "retry_policy" and v is not None:
            from triton_dist_tpu.resilience.retry import RetryPolicy

            if not isinstance(v, RetryPolicy):
                raise ValueError(
                    f"retry_policy must be a resilience.RetryPolicy (or "
                    f"None), got {type(v).__name__}"
                )
            v.validate()
        if k in ("suspect_threshold", "probation_probes") and int(v) < 1:
            raise ValueError(f"{k} must be >= 1, got {v}")
        setattr(_config, k, v)


def interpreting() -> bool:
    """Whether distributed kernels currently resolve to interpret mode
    (the debug/validation posture: CPU tests, dry runs)."""
    cfg = get_config()
    return cfg.interpret if cfg.interpret is not None else not on_tpu()


def on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


_interp_scheduler_patched = False


def _patch_interpreter_scheduler() -> None:
    """De-starve the TPU interpreter's semaphore scheduler on low-core hosts.

    jax 0.9.0's interpreter executes pending DMAs lazily from within
    ``Semaphore.wait`` (``dma_execution_mode='on_wait'``); when a core waits
    on a semaphore whose producing DMA has not been *issued* yet (because the
    producing core is still in compute), the wait busy-spins on the shared
    lock. On a 1-core host the spinners starve the producing thread — a
    livelock for any kernel whose cross-device dependency chain passes
    through compute (exactly what fused GEMM+comm kernels do). This installs
    a copy of ``Semaphore.wait`` whose empty-task-queue branch sleeps briefly
    instead of hot-looping. Interpreter-only; never active on real TPU.
    """
    global _interp_scheduler_patched
    if _interp_scheduler_patched:
        return
    _interp_scheduler_patched = True
    try:
        import jax as _jax

        # The body below is a copy of jax 0.9.x internals with one changed
        # branch; on any other jax line, fall through to the warning (the
        # copied scheduler could silently diverge from upstream semantics).
        if not _jax.__version__.startswith("0.9."):
            raise RuntimeError(
                f"interpreter-scheduler patch was written against jax 0.9.x "
                f"internals; running {_jax.__version__} — refusing to apply "
                f"a stale copy (re-diff jax._src.pallas.mosaic.interpret."
                f"shared_memory.Semaphore.wait and update config.py)"
            )
        import time as _time

        _debug_wait = bool(int(os.environ.get("TDT_DEBUG_WAIT", "0")))

        from jax._src.pallas.mosaic.interpret import shared_memory as _sm
        from jax._src.pallas.mosaic.interpret import vector_clock as _vc

        def _wait(self, value, global_core_id, *, has_tasks=False):
            global_core_id = int(global_core_id)
            clock = None
            if not has_tasks:
                with self.cv:
                    while self.count_by_core[global_core_id] < value:
                        self.cv.wait()
                    self.count_by_core[global_core_id] -= value
                    if self.detect_races:
                        clock = _vc.copy_vector_clock(self.clocks[global_core_id])
                if self.detect_races:
                    with self.shared_memory.lock:
                        _vc.update_vector_clock(
                            self.shared_memory.clocks[global_core_id], clock
                        )
                return
            while True:
                clock = None
                with self.cv:
                    if self.count_by_core[global_core_id] >= value:
                        self.count_by_core[global_core_id] -= value
                        if self.detect_races:
                            clock = _vc.copy_vector_clock(self.clocks[global_core_id])
                        else:
                            return
                if clock is not None:
                    with self.shared_memory.lock:
                        _vc.update_vector_clock(
                            self.shared_memory.clocks[global_core_id], clock
                        )
                    return
                with self.shared_memory.lock:
                    task_queue = self.shared_memory.tasks_by_sem[
                        (self.id, global_core_id)
                    ]
                    task = task_queue.pop() if len(task_queue) > 0 else None
                if task is None:
                    _time.sleep(5e-4)  # the one change vs upstream: no hot spin
                    stalls = getattr(self, "_tdt_stalls", 0) + 1
                    self._tdt_stalls = stalls
                    if _debug_wait and stalls % 2000 == 0:
                        print(
                            f"[tdt-wait] sem={self.id} core={global_core_id} "
                            f"want={value} have={self.count_by_core[global_core_id]} "
                            f"stalls={stalls}",
                            flush=True,
                        )
                    continue
                self._tdt_stalls = 0
                task()

        _sm.Semaphore.wait = _wait
    except Exception as e:  # pragma: no cover - jax version drift
        import warnings

        warnings.warn(
            f"triton_dist_tpu: could not patch the Pallas interpreter "
            f"semaphore scheduler ({e!r}); interpreted distributed kernels "
            f"whose dependency chains pass through compute may livelock on "
            f"low-core hosts",
            RuntimeWarning,
        )


_cpu_tpu_info_registered = False


def _ensure_cpu_tpu_info() -> None:
    """Teach Pallas's TPU-info query about the CPU interpreter.

    ``pltpu.emit_pipeline`` asks for the current device's TPU generation to
    pick tilings; on the CPU backend that lookup fails. The module exposes a
    ``registry`` extension point for unknown device kinds — we register a
    v5e-lookalike for ``"cpu"`` so interpreted kernels tile like a real TPU.
    """
    global _cpu_tpu_info_registered
    if _cpu_tpu_info_registered:
        return
    try:
        from jax._src.pallas.mosaic import tpu_info

        def _cpu_info():
            return tpu_info.TpuInfo(
                chip_version=tpu_info.ChipVersion.TPU_V5E,
                generation=5,
                num_cores=1,
                num_lanes=128,
                num_sublanes=8,
                mxu_column_size=128,
                vmem_capacity_bytes=128 * 1024 * 1024,
                cmem_capacity_bytes=0,
                smem_capacity_bytes=1024 * 1024,
                hbm_capacity_bytes=17_200_000_000,
                mem_bw_bytes_per_second=int(8.20e11),
                bf16_ops_per_second=int(1.97e14),
                int8_ops_per_second=int(3.94e14),
                # v5e runs fp8_e4m3 at the int8 MXU rate (2x bf16); a 0
                # here would make any fp8 roofline silently infinite
                fp8_ops_per_second=int(3.94e14),
                int4_ops_per_second=int(7.88e14),
            )

        tpu_info.registry.setdefault("cpu", _cpu_info)
    except Exception:
        pass
    _cpu_tpu_info_registered = True


def interpret_params():
    """Resolve the `interpret=` argument for pallas_call.

    Returns False (compiled) on TPU backends, or a ``pltpu.InterpretParams``
    configured from the global config elsewhere (CPU tests, dry runs).
    """
    from jax.experimental.pallas import tpu as pltpu

    cfg = get_config()
    if not interpreting():
        return False
    if not hasattr(pltpu, "InterpretParams"):
        # a jax line without the Mosaic TPU interpreter: the fused kernels
        # cannot be simulated — raise a resilience-fallbackable error so
        # guarded op entries degrade to the golden XLA collectives instead
        # of failing deep inside pallas_call
        raise NotImplementedError(
            "jax.experimental.pallas.tpu has no InterpretParams on this jax "
            "version; interpreted distributed kernels need the Mosaic TPU "
            "interpreter (jax >= 0.6). Fused ops degrade to the golden XLA "
            "collective path via triton_dist_tpu.resilience.guarded_call."
        )
    _ensure_cpu_tpu_info()
    _patch_interpreter_scheduler()
    dma_mode = cfg.dma_execution_mode
    if cfg.timeout_iters > 0 or cfg.fault_plan is not None:
        # Watchdogged waits POLL semaphores (semaphore_read) instead of
        # blocking; under 'on_wait' the interpreter only executes pending
        # DMAs from inside Semaphore.wait, so a poll-only consumer would
        # starve its producers and every wait would time out spuriously.
        # Chaos/watchdog runs therefore force eager DMA execution.
        dma_mode = "eager"
    return pltpu.InterpretParams(
        detect_races=cfg.detect_races,
        dma_execution_mode=dma_mode,
        # Distributed kernels intentionally read buffers that are filled by
        # remote DMAs; OOB reads stay fatal but uninit memory must be lax.
        uninitialized_memory="zero",
        out_of_bounds_reads="raise",
    )
