// Native AOT executable runner over the PJRT C API
// (≙ reference tools/runtime/triton_aot_runtime.cc + tools/compile/compile.c:
// their AOT flow emits C launchers linked against a C++ CUDA runtime; the
// TPU-native equivalent loads an XLA executable serialized by
// `triton_dist_tpu.aot.export_pjrt` and drives it through the PJRT C API
// exported by the accelerator plugin — no Python in the serving loop).
//
//   pjrt_runner <plugin.so> <executable.bin> [--input DTYPE:DIMxDIMx...]...
//               [--option KEY=i:INT | KEY=s:STR]... [--iters N]
//
// The plugin is any PJRT C-API .so (libtpu.so for TPU). `--option` pairs
// become PJRT_NamedValue client-create options (plugins like proxied
// backends require e.g. topology/session settings). Inputs are filled
// with a deterministic pattern; outputs are copied back and byte-summed so
// runs are comparable across hosts. Exit 0 = executed and produced every
// output.
//
// ABI note: the PJRT C API is designed for cross-version use — every call
// carries struct_size, and the loader checks the plugin's major version at
// startup (PJRT_Api_Version) instead of assuming header == plugin.

#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <chrono>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

const PJRT_Api* g_api = nullptr;

[[noreturn]] void Die(const std::string& what) {
  fprintf(stderr, "pjrt_runner: %s\n", what.c_str());
  exit(1);
}

void CheckErr(PJRT_Error* err, const char* what) {
  if (err == nullptr) return;
  PJRT_Error_Message_Args m;
  memset(&m, 0, sizeof(m));
  m.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  m.error = err;
  g_api->PJRT_Error_Message(&m);
  std::string msg(m.message, m.message_size);
  PJRT_Error_Destroy_Args d;
  memset(&d, 0, sizeof(d));
  d.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  d.error = err;
  g_api->PJRT_Error_Destroy(&d);
  Die(std::string(what) + ": " + msg);
}

void AwaitAndDestroy(PJRT_Event* event, const char* what) {
  PJRT_Event_Await_Args a;
  memset(&a, 0, sizeof(a));
  a.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  a.event = event;
  CheckErr(g_api->PJRT_Event_Await(&a), what);
  PJRT_Event_Destroy_Args d;
  memset(&d, 0, sizeof(d));
  d.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  d.event = event;
  CheckErr(g_api->PJRT_Event_Destroy(&d), "event destroy");
}

struct InputSpec {
  PJRT_Buffer_Type type;
  size_t elem_bytes;
  std::vector<int64_t> dims;
  size_t nbytes() const {
    size_t n = elem_bytes;
    for (int64_t d : dims) n *= static_cast<size_t>(d);
    return n;
  }
};

InputSpec ParseInput(const std::string& s) {
  // DTYPE:DIMxDIMx... (scalar: "f32:" with no dims)
  auto colon = s.find(':');
  if (colon == std::string::npos) Die("bad --input (want DTYPE:DIMS): " + s);
  std::string dt = s.substr(0, colon);
  InputSpec spec;
  if (dt == "f32") {
    spec.type = PJRT_Buffer_Type_F32;
    spec.elem_bytes = 4;
  } else if (dt == "bf16") {
    spec.type = PJRT_Buffer_Type_BF16;
    spec.elem_bytes = 2;
  } else if (dt == "f16") {
    spec.type = PJRT_Buffer_Type_F16;
    spec.elem_bytes = 2;
  } else if (dt == "i32" || dt == "s32") {
    spec.type = PJRT_Buffer_Type_S32;
    spec.elem_bytes = 4;
  } else if (dt == "i8" || dt == "s8") {
    spec.type = PJRT_Buffer_Type_S8;
    spec.elem_bytes = 1;
  } else if (dt == "u8") {
    spec.type = PJRT_Buffer_Type_U8;
    spec.elem_bytes = 1;
  } else {
    Die("unsupported dtype: " + dt);
  }
  std::string dims = s.substr(colon + 1);
  size_t pos = 0;
  while (pos < dims.size()) {
    auto x = dims.find('x', pos);
    std::string tok = dims.substr(pos, x == std::string::npos ? x : x - pos);
    if (!tok.empty()) spec.dims.push_back(strtoll(tok.c_str(), nullptr, 10));
    if (x == std::string::npos) break;
    pos = x + 1;
  }
  return spec;
}

std::vector<char> ReadFile(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) Die(std::string("cannot open ") + path);
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(n));
  if (fread(buf.data(), 1, buf.size(), f) != buf.size()) Die("short read");
  fclose(f);
  return buf;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s <plugin.so> <executable.bin> "
            "[--input DTYPE:DIMxDIM...]... [--iters N]\n",
            argv[0]);
    return 2;
  }
  std::vector<InputSpec> inputs;
  int iters = 1;
  // --option storage: strings must outlive PJRT_Client_Create
  std::vector<std::string> opt_keys, opt_strs;
  std::vector<int64_t> opt_ints;
  std::vector<int> opt_kind;  // 0 = int, 1 = string
  for (int i = 3; i < argc; i++) {
    if (!strcmp(argv[i], "--input") && i + 1 < argc) {
      inputs.push_back(ParseInput(argv[++i]));
    } else if (!strcmp(argv[i], "--iters") && i + 1 < argc) {
      iters = atoi(argv[++i]);
      if (iters < 1) Die(std::string("--iters must be >= 1, got ") + argv[i]);
    } else if (!strcmp(argv[i], "--option") && i + 1 < argc) {
      std::string kv = argv[++i];
      auto eq = kv.find('=');
      if (eq == std::string::npos || eq + 2 >= kv.size() || kv[eq + 2] != ':') {
        Die("bad --option (want KEY=i:INT or KEY=s:STR): " + kv);
      }
      char kind = kv[eq + 1];
      opt_keys.push_back(kv.substr(0, eq));
      std::string val = kv.substr(eq + 3);
      if (kind == 'i') {
        opt_kind.push_back(0);
        opt_ints.push_back(strtoll(val.c_str(), nullptr, 10));
        opt_strs.emplace_back();
      } else if (kind == 's') {
        opt_kind.push_back(1);
        opt_strs.push_back(val);
        opt_ints.push_back(0);
      } else {
        Die("bad --option type (i or s): " + kv);
      }
    } else {
      Die(std::string("unknown arg ") + argv[i]);
    }
  }

  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_LOCAL);
  if (!lib) Die(std::string("dlopen: ") + dlerror());
  auto get_api = reinterpret_cast<const PJRT_Api* (*)()>(
      dlsym(lib, "GetPjrtApi"));
  if (!get_api) Die("plugin exports no GetPjrtApi");
  g_api = get_api();
  if (!g_api) Die("GetPjrtApi returned null");
  fprintf(stderr, "pjrt_runner: plugin api v%d.%d\n",
          g_api->pjrt_api_version.major_version,
          g_api->pjrt_api_version.minor_version);
  if (g_api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    Die("plugin PJRT major version mismatch vs header");
  }

  {
    PJRT_Plugin_Initialize_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    CheckErr(g_api->PJRT_Plugin_Initialize(&a), "plugin init");
  }

  PJRT_Client* client = nullptr;
  {
    std::vector<PJRT_NamedValue> nvs(opt_keys.size());
    for (size_t i = 0; i < opt_keys.size(); i++) {
      memset(&nvs[i], 0, sizeof(nvs[i]));
      nvs[i].struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nvs[i].name = opt_keys[i].c_str();
      nvs[i].name_size = opt_keys[i].size();
      if (opt_kind[i] == 0) {
        nvs[i].type = PJRT_NamedValue_kInt64;
        nvs[i].int64_value = opt_ints[i];
        nvs[i].value_size = 1;
      } else {
        nvs[i].type = PJRT_NamedValue_kString;
        nvs[i].string_value = opt_strs[i].c_str();
        nvs[i].value_size = opt_strs[i].size();
      }
    }
    PJRT_Client_Create_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
    a.create_options = nvs.data();
    a.num_options = nvs.size();
    CheckErr(g_api->PJRT_Client_Create(&a), "client create");
    client = a.client;
  }

  PJRT_Device* device = nullptr;
  {
    PJRT_Client_AddressableDevices_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    a.client = client;
    CheckErr(g_api->PJRT_Client_AddressableDevices(&a), "devices");
    if (a.num_addressable_devices == 0) Die("no addressable devices");
    device = a.addressable_devices[0];
  }

  std::vector<char> exe_bytes = ReadFile(argv[2]);
  PJRT_LoadedExecutable* loaded = nullptr;
  {
    PJRT_Executable_DeserializeAndLoad_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Executable_DeserializeAndLoad_Args_STRUCT_SIZE;
    a.client = client;
    a.serialized_executable = exe_bytes.data();
    a.serialized_executable_size = exe_bytes.size();
    CheckErr(g_api->PJRT_Executable_DeserializeAndLoad(&a), "deserialize");
    loaded = a.loaded_executable;
  }

  size_t num_outputs = 0;
  {
    PJRT_LoadedExecutable_GetExecutable_Args g;
    memset(&g, 0, sizeof(g));
    g.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    g.loaded_executable = loaded;
    CheckErr(g_api->PJRT_LoadedExecutable_GetExecutable(&g), "get exe");
    PJRT_Executable_NumOutputs_Args n;
    memset(&n, 0, sizeof(n));
    n.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    n.executable = g.executable;
    CheckErr(g_api->PJRT_Executable_NumOutputs(&n), "num outputs");
    num_outputs = n.num_outputs;
  }

  // Stage inputs: deterministic byte pattern (comparable across hosts).
  std::vector<PJRT_Buffer*> arg_bufs;
  std::vector<std::vector<char>> host_inputs;
  for (const InputSpec& spec : inputs) {
    host_inputs.emplace_back(spec.nbytes());
    std::vector<char>& h = host_inputs.back();
    for (size_t i = 0; i < h.size(); i++) {
      h[i] = static_cast<char>((i * 131) % 241 % 63);  // small positive ints
    }
    PJRT_Client_BufferFromHostBuffer_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    a.client = client;
    a.data = h.data();
    a.type = spec.type;
    a.dims = spec.dims.data();
    a.num_dims = spec.dims.size();
    a.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    a.device = device;
    CheckErr(g_api->PJRT_Client_BufferFromHostBuffer(&a), "h2d");
    AwaitAndDestroy(a.done_with_host_buffer, "h2d await");
    arg_bufs.push_back(a.buffer);
  }

  // Execute `iters` times (buffers are not donated: executables whose
  // inputs alias outputs should be exported with donation disabled).
  std::vector<PJRT_Buffer*> outputs(num_outputs, nullptr);
  double total_ms = 0.0;
  for (int it = 0; it < iters; it++) {
    for (PJRT_Buffer* b : outputs) {
      if (b != nullptr) {
        PJRT_Buffer_Destroy_Args d;
        memset(&d, 0, sizeof(d));
        d.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
        d.buffer = b;
        CheckErr(g_api->PJRT_Buffer_Destroy(&d), "out destroy");
      }
    }
    PJRT_ExecuteOptions opts;
    memset(&opts, 0, sizeof(opts));
    opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
    std::vector<int64_t> non_donatable(arg_bufs.size());
    for (size_t i = 0; i < non_donatable.size(); i++) non_donatable[i] = i;
    opts.non_donatable_input_indices = non_donatable.data();
    opts.num_non_donatable_input_indices = non_donatable.size();

    PJRT_Buffer* const* arg_list = arg_bufs.data();
    PJRT_Buffer** out_list = outputs.data();
    PJRT_Event* done = nullptr;

    PJRT_LoadedExecutable_Execute_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
    a.executable = loaded;
    a.options = &opts;
    a.argument_lists = &arg_list;
    a.num_devices = 1;
    a.num_args = arg_bufs.size();
    a.output_lists = &out_list;
    a.device_complete_events = &done;
    a.execute_device = device;
    auto t0 = std::chrono::steady_clock::now();
    CheckErr(g_api->PJRT_LoadedExecutable_Execute(&a), "execute");
    AwaitAndDestroy(done, "execute await");
    auto t1 = std::chrono::steady_clock::now();
    total_ms += std::chrono::duration<double, std::milli>(t1 - t0).count();
  }

  // Copy outputs back; byte-sum for a host-independent fingerprint.
  for (size_t i = 0; i < num_outputs; i++) {
    PJRT_Buffer_ToHostBuffer_Args a;
    memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    a.src = outputs[i];
    CheckErr(g_api->PJRT_Buffer_ToHostBuffer(&a), "d2h size query");
    std::vector<char> host(a.dst_size);
    a.dst = host.data();
    CheckErr(g_api->PJRT_Buffer_ToHostBuffer(&a), "d2h");
    AwaitAndDestroy(a.event, "d2h await");
    uint64_t sum = 0;
    for (char c : host) sum += static_cast<unsigned char>(c);
    printf("output[%zu]: %zu bytes, bytesum=%llu\n", i, host.size(),
           static_cast<unsigned long long>(sum));
  }
  printf("executed %d iter(s), avg %.3f ms\n", iters, total_ms / iters);
  return 0;
}
