"""Test harness: 8 virtual CPU devices + TPU interpreter for all Pallas
kernels (SURVEY.md §4 — this is where we exceed the reference, which can
only test on real multi-GPU hardware).

NOTE: on hosts with very few CPU cores, XLA:CPU's host thread pool can
deadlock when many interpreted remote DMAs move large payloads concurrently
(observed threshold ~16 KiB/chunk in 8-device ring kernels on a 1-core
box). Keep per-DMA test payloads <= ~8 KiB; correctness coverage does not
need more, and real-TPU runs are unaffected.

Runtime budget (1-core box, re-measured 2026-08-01): the `-m quick` tier
is the fast gate (~8 min at 164 tests — it grows with kernel-family
coverage; the whole-loop speculative integration tests moved to the
slow tier when the r5 device-side while_loop rewrite tripled their
interpret-mode cost); the full suite is ~65 min (test_decode ~14 min
and test_models ~9 min dominate). The floor is
structural, not shape-driven: every interpreted pallas_call pays ~44 ms
of host machinery (≈112 io_callbacks + the per-call shared-memory
setup/cleanup barriers across virtual devices — profiled against
jax 0.9 interpret_pallas_call), and a model-level train-step test runs
hundreds of such calls plus a ~35 s trace+XLA-compile of its fwd+bwd
shard_map program that no persistent cache can hold (callback-bearing
executables are not cacheable). Model tests therefore use the smallest
layer count that still covers their property, and serving programs are
shared across tests via the keyed `jit_shard_map` cache."""

import os
import signal

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
from jax.sharding import Mesh  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: second-tier tests (models, tutorials, large shapes, "
        "multi-process) — excluded from the fast `-m quick` CI tier",
    )
    config.addinivalue_line(
        "markers",
        "quick: first-tier kernel-family coverage; `pytest -m quick` is "
        "the fast gate (~8 min on a 1-core box)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: resilience-layer fault injection (tests/test_chaos.py). "
        "Fast interpret-mode cases ride tier-1 automatically; the full "
        "drop/dup/delay/straggler × kernel-family matrix is additionally "
        "marked slow — run it standalone via scripts/chaos_matrix.sh",
    )
    config.addinivalue_line(
        "markers",
        "soak: long seeded multi-fault chaos campaigns "
        "(tests/test_overload.py / resilience/soak.py, ISSUE 11). "
        "Automatically wired slow so tier-1 stays fast; run via "
        "scripts/chaos_soak.py or `pytest -m soak`",
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        # soak implies slow (ISSUE 11): the campaign tier never rides the
        # fast gate, and forgetting the second marker can't break that
        if "soak" in item.keywords and "slow" not in item.keywords:
            item.add_marker(pytest.mark.slow)
        # quick == everything not explicitly marked slow, so the quick
        # tier can't silently lose new tests
        if "slow" not in item.keywords and "soak" not in item.keywords:
            item.add_marker(pytest.mark.quick)


def _cell_alarm(item, phase):
    """Per-cell wall-clock budget (ISSUE 11 satellite): with
    ``TDT_CELL_TIMEOUT_S`` set (scripts/chaos_matrix.sh exports it), a
    SIGALRM fires a TimeoutError inside the hung cell, so it reports as
    one named FAILED/ERROR row instead of stalling the whole matrix.
    Armed around ALL THREE phases (setup / call / teardown — a fixture
    can hang just as hard as a test body). Signal delivery needs the
    main thread + a Python bytecode boundary — true for every
    interpret-mode cell here; a cell wedged inside a C call fails at its
    next return to Python."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        budget = float(os.environ.get("TDT_CELL_TIMEOUT_S", "0") or 0)
        if budget <= 0 or not hasattr(signal, "SIGALRM"):
            yield
            return

        def _alarm(signum, frame):
            raise TimeoutError(
                f"cell {phase} exceeded TDT_CELL_TIMEOUT_S={budget:g}s: "
                f"{item.nodeid}"
            )

        old = signal.signal(signal.SIGALRM, _alarm)
        signal.setitimer(signal.ITIMER_REAL, budget)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old)

    return scope()


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_setup(item):
    with _cell_alarm(item, "setup"):
        yield


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    with _cell_alarm(item, "call"):
        yield


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_teardown(item):
    with _cell_alarm(item, "teardown"):
        yield


@pytest.fixture(scope="session", autouse=True)
def _interpret_mode():
    from triton_dist_tpu import config

    config.update(interpret=True)
    yield


@pytest.fixture(autouse=True)
def _resilience_isolation():
    """The resilience health registry is process-global: a watchdog
    quarantine or downgrade recorded by one test would pin later tests'
    op entries to the golden path, silently changing what they cover.
    Reset around every test — keeping only the environment pins (whether
    this jax install can build fused kernels doesn't change per test, and
    re-paying the failing trace hundreds of times would)."""
    from triton_dist_tpu import resilience
    from triton_dist_tpu.obs import alerts, blackbox, metrics

    def _flight_recorder_reset():
        # the ISSUE 15 flight-recorder registries are process-global like
        # the health registry: series/alerts/bundle census recorded by an
        # armed test must not leak into the next one (the tracer ring and
        # telemetry aggregation stay: test_obs manages those explicitly)
        metrics.reset()
        alerts.reset()
        blackbox.reset()

    resilience.reset(keep_env=True)
    _flight_recorder_reset()
    yield
    resilience.reset(keep_env=True)
    _flight_recorder_reset()


@pytest.fixture(scope="session")
def mesh8() -> Mesh:
    return Mesh(np.array(jax.devices()), ("tp",))


@pytest.fixture(scope="session")
def mesh2x4() -> Mesh:
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))


@pytest.fixture(scope="session")
def mesh4() -> Mesh:
    return Mesh(np.array(jax.devices()[:4]), ("tp",))


@pytest.fixture(scope="session")
def mesh2x2x2() -> Mesh:
    return Mesh(np.array(jax.devices()).reshape(2, 2, 2), ("a", "b", "c"))
