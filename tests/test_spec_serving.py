"""Speculative serving (triton_dist_tpu/serving/speculative.py,
docs/serving.md "Speculative decoding"; ISSUE 20): per-slot acceptance
in the continuous batcher, adaptive-k, and the negative-cost
``shed_speculation`` brownout rung.

Tier structure mirrors tests/test_serving.py:

- **host tier**: SpecDecodeConfig validation (no device work);
- **engine tier** (world-1 mesh, real batcher steps, FakeClock):
  greedy byte-identity + the step-count throughput win, seeded-sampled
  replay, per-slot divergent acceptance through the chaos seam, the
  prefix-cache page audit over BOTH tries, the dormant-k0 ≡ disarmed
  pin, and the adaptive-k backoff unit;
- **chaos tier** (``pytest.mark.chaos``, also run by chaos_matrix.sh):
  the shed_spec rung arc end to end, and the seeded speculative soak
  campaign (straggler × draft corruption on a 4-PE world) with its
  bit-identical replay.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu import resilience
from triton_dist_tpu.models import init_params
from triton_dist_tpu.models.decode import ContinuousBatcher, Request
from triton_dist_tpu.models.tp_transformer import TransformerConfig
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig
from triton_dist_tpu.resilience import health, retry, soak
from triton_dist_tpu.serving import (
    Arrival,
    OverloadConfig,
    PrefixCacheConfig,
    ServingConfig,
    ServingEngine,
    SLOTargets,
    SpecDecodeConfig,
    SpeculativeBatcher,
    TrafficSpec,
    generate_trace,
    shared_prefix_mix,
)
from triton_dist_tpu.serving import overload as ov


@pytest.fixture(autouse=True)
def _restore_config():
    cfg = tdt_config.get_config()
    snap = (cfg.timeout_iters, cfg.fault_plan, cfg.raise_on_timeout,
            cfg.fallback_to_xla, cfg.retry_policy, cfg.elastic,
            cfg.suspect_threshold, cfg.probation_probes)
    yield
    tdt_config.update(
        timeout_iters=snap[0], fault_plan=snap[1], raise_on_timeout=snap[2],
        fallback_to_xla=snap[3], retry_policy=snap[4], elastic=snap[5],
        suspect_threshold=snap[6], probation_probes=snap[7],
    )
    retry.set_clock(None)


@pytest.fixture(scope="session")
def mesh1() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


def _cfg(**over):
    base = dict(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=4, n_kv_heads=2,
        head_dim=8, batch=2, seq=8,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
    )
    base.update(over)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def tiny1():
    cfg = _cfg()
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _self_draft(cfg, params, k=3, **over):
    """Self-draft (draft == target): α = 1 by construction under greedy,
    which isolates the serving machinery — acceptance, rollback, cost
    accounting — from draft quality."""
    return SpecDecodeConfig(draft_cfg=cfg, draft_params=params, k=k, **over)


def _engine(tiny1, mesh1, sd, *, s_max=16, clock=None, **serving_kw):
    cfg, params = tiny1
    clock = clock or retry.FakeClock()
    eng = ServingEngine(
        cfg, params, mesh1, s_max=s_max, clock=clock,
        serving=ServingConfig(virtual_step_s=0.01, speculative=sd,
                              **serving_kw),
    )
    return eng, clock


def _reqs(cfg, spec_list, seed=5, **kw):
    key = jax.random.PRNGKey(seed)
    out = []
    for i, (plen, mx) in enumerate(spec_list):
        toks = list(np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (plen,), 0, cfg.vocab, np.int32
        )))
        out.append(Request([int(t) for t in toks], max_new_tokens=mx,
                           uid=i, **kw))
    return out


# ---------------------------------------------------------------------------
# Host tier: config validation
# ---------------------------------------------------------------------------

def test_spec_config_validation():
    ok = SpecDecodeConfig(draft_cfg=object(), draft_params=object(), k=4)
    assert ok.validate() is ok
    assert SpecDecodeConfig(k=0).validate().k == 0   # dormant needs no draft
    with pytest.raises(ValueError, match="k-1"):
        SpecDecodeConfig(k=1).validate()
    with pytest.raises(ValueError, match="draft_cfg"):
        SpecDecodeConfig(k=2).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        SpecDecodeConfig(draft_cfg=object(), draft_params=object(),
                         alpha_low=0.7, alpha_high=0.7).validate()
    with pytest.raises(ValueError, match="k_min"):
        SpecDecodeConfig(draft_cfg=object(), draft_params=object(),
                         k_min=1).validate()
    with pytest.raises(ValueError, match="k_min"):
        SpecDecodeConfig(draft_cfg=object(), draft_params=object(),
                         k=2, k_min=3).validate()


# ---------------------------------------------------------------------------
# Engine tier: greedy byte-identity + the step-count win
# ---------------------------------------------------------------------------

def test_greedy_byte_identity_and_throughput_gain(tiny1, mesh1):
    """The tentpole acceptance pair on one FakeClock A/B: a self-draft
    speculative engine emits token for token what the plain engine emits
    (greedy), and the step-count accounting (``last_step_units`` scaling
    ``virtual_step_s``) makes it measurably FASTER — outputs long
    relative to k, so the accepted drafts outweigh the draft+verify
    surcharge."""
    cfg, params = tiny1
    shapes = [(3, 12), (2, 12), (4, 12), (2, 12)]

    plain, _ = _engine(tiny1, mesh1, None)
    for r in _reqs(cfg, shapes):
        plain.submit(r)
    want = {u: r.tokens for u, r in plain.run_until_idle().items()}

    spec, _ = _engine(tiny1, mesh1, _self_draft(cfg, params, k=3))
    for r in _reqs(cfg, shapes):
        spec.submit(r)
    got = {u: r.tokens for u, r in spec.run_until_idle().items()}
    assert got == want, "greedy speculative serving is byte-identical"

    psnap, ssnap = plain.snapshot(), spec.snapshot()
    assert "speculative" not in psnap, "disarmed snapshots unchanged"
    sp = ssnap["speculative"]
    assert sp["rounds"] > 0 and sp["k_live"] == 3
    assert sp["tokens_accepted"] > 0
    # α < 1 even for self-draft: it is measured over COMMITTED tokens,
    # and max_new truncation throws the round's drafted overhang away
    assert sp["accept_rate"] is not None and sp["accept_rate"] > 0.6
    assert ssnap["tokens"]["generated"] == psnap["tokens"]["generated"]
    assert ssnap["tokens"]["per_s"] > psnap["tokens"]["per_s"], (
        "the FakeClock A/B must show the step-count win"
    )


def test_sampled_determinism_bit_identical_replay(tiny1, mesh1):
    """Seeded sampling through the rejection-sampling accept path: two
    fresh engines over the same trace emit bit-identical streams (the
    per-slot RNG draw order is fixed), and the speculative tallies
    replay exactly too."""
    spec = TrafficSpec(rate_rps=20.0, n_requests=8, seed=11,
                       prompt_len=("uniform", 2, 4),
                       output_len=("uniform", 6, 12), vocab=32,
                       temperature=0.8)

    def run():
        cfg, params = tiny1
        eng, _ = _engine(tiny1, mesh1, _self_draft(cfg, params, k=3),
                         max_queue=64)
        done = eng.serve(generate_trace(spec))
        return {u: r.tokens for u, r in done.items()}, (
            eng.snapshot()["speculative"]
        )

    a, sp_a = run()
    b, sp_b = run()
    assert a == b
    assert sp_a == sp_b
    assert sp_a["rounds"] > 0


def test_per_slot_divergent_acceptance(tiny1, mesh1):
    """The per-slot claim itself: in ONE round, the slot whose draft was
    corrupted (the chaos seam) accepts nothing while its neighbor
    accepts the full k-1 — a lockstep ``min`` would have stalled both —
    and the corrupted slot's emitted token is still the target's own
    argmax, so the streams stay byte-identical to plain decode."""
    cfg, params = tiny1
    bt = SpeculativeBatcher(cfg, params, mesh1, s_max=16,
                            spec_decode=_self_draft(cfg, params, k=3))
    reqs = _reqs(cfg, [(2, 8), (3, 8)], seed=9)
    for r in reqs:
        bt.submit(r)
    # feed prompts until BOTH slots are generating (spec-eligible)
    for _ in range(8):
        if all(r is not None and bt.slot_fed[i] >= len(r.prompt)
               for i, r in enumerate(bt.slot_req)):
            break
        bt.step()
    else:
        pytest.fail("slots never both became spec-eligible")

    rollback0 = bt.spec_rollback_total
    bt.corrupt_draft_next = True
    bt.step()
    assert bt.spec_draft_faults_injected == 1
    assert not bt.corrupt_draft_next, "seam consumed by the spec round"
    # slot 0 (spec[0], the corrupted one) rejects the flipped token at
    # j=0; slot 1 self-drafts the target's own chain and accepts k-1
    assert bt.last_accepts == {0: 0, 1: 2}, bt.last_accepts
    assert bt.spec_rollback_total - rollback0 >= 2
    assert bt.last_step_units > 1.0

    done = dict(bt.run(max_steps=200))
    plain = ContinuousBatcher(cfg, params, mesh1, s_max=16)
    for r in _reqs(cfg, [(2, 8), (3, 8)], seed=9):
        plain.submit(r)
    assert done == dict(plain.run(max_steps=200))


def test_rollback_page_cursor_audit_under_prefix_cache(tiny1, mesh1):
    """Speculative serving over the paged pool + prefix trie: rejected
    suffixes roll back by cursor, never by page surgery — so after a
    shared-prefix serve BOTH tries (target and draft mirror) still pass
    the full page-accounting partition audit, and the streams match the
    plain paged+prefix engine byte for byte."""
    cfg, params = tiny1
    spec = shared_prefix_mix(s_max=32, rate_rps=10.0, n_requests=8,
                             n_prefixes=2, prefix_tokens=8,
                             vocab=cfg.vocab, seed=4)
    trace = generate_trace(spec)

    def run(sd):
        eng = ServingEngine(
            cfg, params, mesh1, s_max=32, clock=retry.FakeClock(),
            serving=ServingConfig(virtual_step_s=0.01, speculative=sd,
                                  prefix_cache=PrefixCacheConfig(),
                                  max_queue=64),
            page_size=4,
        )
        done = eng.serve(trace)
        return eng, {u: r.tokens for u, r in done.items()}

    _, want = run(None)
    eng, got = run(_self_draft(cfg, params, k=3))
    assert got == want
    bt = eng._batcher
    assert isinstance(bt, SpeculativeBatcher)
    assert bt.spec_rounds > 0
    bt._px.audit()
    assert bt._draft_px is not None, "paged target arms the draft mirror"
    bt._draft_px.audit()
    # rollbacks really happened over pool pages (truncation waste at
    # minimum) and no page leaked through them — that is the audit above
    assert eng.snapshot()["speculative"]["rollback_total"] >= 0


def test_dormant_k0_pinned_to_disarmed(tiny1, mesh1):
    """``SpecDecodeConfig(k=0)`` is dormant, not merely quiet: every
    round delegates to the plain decode path at plain cost, so streams
    AND the virtual clock are identical to a disarmed engine — the only
    visible difference is the (all-zero) snapshot section."""
    cfg, params = tiny1
    shapes = [(3, 6), (2, 5), (4, 4)]

    def run(sd):
        eng, clock = _engine(tiny1, mesh1, sd)
        for r in _reqs(cfg, shapes, seed=3):
            eng.submit(r)
        done = eng.run_until_idle()
        return {u: r.tokens for u, r in done.items()}, clock.monotonic(), eng

    want, t_plain, _ = run(None)
    got, t_dormant, eng = run(SpecDecodeConfig(k=0))
    assert got == want
    assert t_dormant == t_plain, "dormant rounds charge plain step units"
    sp = eng.snapshot()["speculative"]
    assert sp["rounds"] == 0 and sp["tokens_offered"] == 0
    assert sp["accept_rate"] is None


def test_adaptive_k_backoff_unit(tiny1, mesh1):
    """The rolling-α controller in isolation (``_note_round`` is the
    whole surface): k backs off one step per EXHAUSTED window below
    alpha_low down to k_min, regrows above alpha_high up to k, and the
    cleared window is the dwell — one bad round never moves it."""
    cfg, params = tiny1
    seen = []
    bt = SpeculativeBatcher(
        cfg, params, mesh1, s_max=16,
        spec_decode=_self_draft(cfg, params, k=4, adaptive=True,
                                alpha_window=4, k_min=2),
    )
    bt.on_k_change = lambda old, new, alpha: seen.append((old, new))
    assert bt.k_live == 4

    for _ in range(3):
        bt._note_round(0, 3)
    assert bt.k_live == 4, "window not full: no move yet (the dwell)"
    bt._note_round(0, 3)
    assert bt.k_live == 3, "cold window backs off one step"
    for _ in range(4):
        bt._note_round(0, 2)
    assert bt.k_live == 2
    for _ in range(8):
        bt._note_round(0, 1)
    assert bt.k_live == 2, "k_min is the floor"
    for _ in range(4):
        bt._note_round(1, 1)
    assert bt.k_live == 3, "hot window regrows one step"
    for _ in range(4):
        bt._note_round(2, 2)
    assert bt.k_live == 4
    for _ in range(8):
        bt._note_round(3, 3)
    assert bt.k_live == 4, "configured k is the ceiling"
    assert [(o, n) for o, n, _ in bt.spec_k_transitions] == [
        (4, 3), (3, 2), (2, 3), (3, 4)
    ]
    assert seen == [(4, 3), (3, 2), (2, 3), (3, 4)]
    assert all(0.0 <= a <= 1.0 for _, _, a in bt.spec_k_transitions)


# ---------------------------------------------------------------------------
# Chaos tier: the shed_spec rung arc
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_shed_speculation_rung_climb_and_revert(tiny1, mesh1):
    """The negative-cost rung end to end: a flash crowd drives the
    5-state ladder through SHED_SPEC (a counted rebuild that swaps the
    plain batcher in, through the elastic replay machinery), the sparse
    tail walks it back down (a second counted rebuild restores the
    draft), no request is lost, and — greedy self-draft — every stream
    is byte-identical to an unpressured speculative engine."""
    cfg, params = tiny1
    crowd = [
        Arrival(t_s=0.0, request=Request([1, 2], max_new_tokens=4,
                                         uid=f"c{k}"))
        for k in range(8)
    ]
    tail = [
        Arrival(t_s=3.0 + k, request=Request([1, 2], max_new_tokens=1,
                                             uid=f"t{k}"))
        for k in range(4)
    ]

    eng, _ = _engine(
        tiny1, mesh1, _self_draft(cfg, params, k=3),
        max_queue=4, slo=SLOTargets(ttft_ms=5.0),
        overload=OverloadConfig(
            shed_speculation=True, min_dwell_steps=2, window_steps=4,
            enter_pressure=(0.5, 0.6, 0.7, 0.8),
            exit_pressure=(0.3, 0.4, 0.5, 0.6),
        ),
    )
    done = eng.serve(crowd + tail)
    rungs = {t.to for t in eng._overload.transitions}
    assert ov.SHED_SPEC in rungs, eng._overload.transitions
    snap = eng.snapshot()
    assert snap["requests"].get("spec_sheds", 0) >= 1
    assert eng.rebuilds >= 2, "shed AND restore each rebuilt"
    assert not eng._spec_shed, "speculation restored on descent"
    reasons = [e.reason for e in health.events(health.SERVING_REBUILD)]
    assert any("speculation shed" in r for r in reasons)
    assert any("speculation restored" in r for r in reasons)
    assert all(type(r).__name__ == "Finished" for r in done.values())

    # byte-identity: greedy self-draft serving emits plain greedy decode
    # whatever mode flips happened mid-serve
    calm, _ = _engine(tiny1, mesh1, _self_draft(cfg, params, k=3),
                      max_queue=64)
    want = calm.serve(crowd + tail)
    assert {u: r.tokens for u, r in done.items()} == {
        u: r.tokens for u, r in want.items()
    }


@pytest.mark.chaos
def test_shed_rung_armed_on_plain_engine_is_byte_identical(tiny1, mesh1):
    """Armed-untriggered ≡ disarmed, rung edition: the same crowd drives
    a NON-speculative engine through SHED_SPEC — the transition is
    recorded but nothing rebuilds, and the streams match the engine with
    no overload controller at all."""
    crowd = [
        Arrival(t_s=0.0, request=Request([1, 2], max_new_tokens=4,
                                         uid=f"c{k}"))
        for k in range(8)
    ]
    eng, _ = _engine(
        tiny1, mesh1, None,
        max_queue=4, slo=SLOTargets(ttft_ms=5.0),
        overload=OverloadConfig(
            shed_speculation=True, min_dwell_steps=2, window_steps=4,
            enter_pressure=(0.5, 0.6, 0.7, 0.8),
            exit_pressure=(0.3, 0.4, 0.5, 0.6),
        ),
    )
    done = eng.serve(list(crowd))
    assert ov.SHED_SPEC in {t.to for t in eng._overload.transitions}
    assert eng.rebuilds == 0, "nothing to shed on a plain engine"
    calm, _ = _engine(tiny1, mesh1, None, max_queue=64)
    want = calm.serve(list(crowd))
    assert {u: r.tokens for u, r in done.items()} == {
        u: r.tokens for u, r in want.items()
    }


# ---------------------------------------------------------------------------
# Chaos tier: the seeded speculative soak campaign
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_quick_speculative_soak_green():
    """One speculative campaign (self-draft k=3 × persistent straggler ×
    draft corruption on a 4-PE world): speculation survives the full
    quarantine → shrink → replay → regrow arc, every injected draft
    corruption is rejected by the verify pass, and the streams match a
    clean plain reference byte for byte (check_spec_invariants)."""
    res = soak.run_campaign(soak.SoakSpec.speculative(seed=600))
    assert res.error is None, res.error
    assert res.ok, res.failures
    assert res.rebuilds >= 1, "the straggler arc rebuilt mid-speculation"
    sp = res.snapshot.get("speculative") or {}
    assert sp.get("rounds", 0) > 0
    assert sp.get("draft_faults_injected") == res.spec.n_draft_corruptions
    assert sp.get("rollback_total", 0) >= res.spec.n_draft_corruptions


@pytest.mark.chaos
def test_speculative_soak_replay_bit_identical():
    spec = soak.SoakSpec.speculative(seed=601)
    a, b = soak.run_campaign(spec), soak.run_campaign(spec)
    assert a.ok and b.ok, (a.failures, b.failures)
    assert a.fingerprint == b.fingerprint
    assert a.terminals == b.terminals
