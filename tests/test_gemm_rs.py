"""Fused GEMM-RS vs golden (≙ reference test_gemm_rs.py: golden =
torch.matmul + reduce_scatter_tensor; here jnp.dot + lax.psum_scatter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs, gemm_rs_op


def _golden(a, b, mesh, axis="tp"):
    def f(a, b):
        c = jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))
        return jax.lax.psum_scatter(c, axis, scatter_dimension=0, tiled=True).astype(
            a.dtype
        )

    return jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, axis), P(axis, None)),
            out_specs=P(axis, None), check_vma=False,
        )
    )(a, b)


@pytest.mark.parametrize("method", ["scatter", "ring"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gemm_rs(mesh4, method, dtype):
    m_tot, k_tot, n_dim = 64, 256, 256
    a = jax.random.normal(jax.random.PRNGKey(0), (m_tot, k_tot)).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (k_tot, n_dim)).astype(dtype)
    cfg = GemmRSConfig(block_m=16, block_n=128, block_k=64)
    got = gemm_rs_op(a, b, mesh4, method=method, config=cfg)
    want = _golden(a, b, mesh4)
    # bf16 partials are rounded once per transfer before the f32 reduce
    # (same as the reference, whose tiles move in output dtype) — wider
    # tolerance than the all-f32 golden.
    tol = dict(rtol=1e-4, atol=1e-4) if dtype == jnp.float32 else dict(rtol=6e-2, atol=2e-1)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **tol
    )


@pytest.mark.parametrize("method", ["scatter", "ring"])
def test_gemm_rs_world8(mesh8, method):
    m_tot, k_tot, n_dim = 64, 128, 256
    a = jax.random.normal(jax.random.PRNGKey(2), (m_tot, k_tot), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(3), (k_tot, n_dim), jnp.float32)
    cfg = GemmRSConfig(block_m=8, block_n=128, block_k=16)
    got = gemm_rs_op(a, b, mesh8, method=method, config=cfg)
    want = _golden(a, b, mesh8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_gemm_rs_world1():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    a = jax.random.normal(jax.random.PRNGKey(4), (16, 128), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(5), (128, 128), jnp.float32)
    got = gemm_rs_op(a, b, mesh, config=GemmRSConfig(16, 128, 128))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(jnp.dot(a, b)), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_xla_sentinel(mesh4):
    """GemmRSConfig(0,0,0): world-1 dispatches to the XLA dot; n>1 raises."""
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    a = jax.random.normal(jax.random.PRNGKey(6), (16, 128), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(7), (128, 128), jnp.float32)
    got = gemm_rs_op(a, b, mesh1, config=GemmRSConfig(0, 0, 0))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(jnp.dot(a, b)), rtol=1e-4, atol=1e-4
    )
    with pytest.raises(Exception, match="world-1 only"):
        gemm_rs_op(a, b, mesh4, config=GemmRSConfig(0, 0, 0))


def test_gemm_rs_2d(mesh2x4):
    """Hierarchical 2-D GEMM-RS over (dp, tp) vs psum_scatter golden
    (VERDICT r1 item 4: plumb multi-axis through gemm_rs)."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs, GemmRSConfig

    n, m_loc, k_loc, n_dim = 8, 8, 64, 128
    cfg = GemmRSConfig(8, 128, 64)

    def fn(a, b):
        return gemm_rs(a, b, axis=("dp", "tp"), config=cfg)

    def golden(a, b):
        prod = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
        return jax.lax.psum_scatter(prod, ("dp", "tp"), tiled=True)

    specs = dict(
        mesh=mesh2x4,
        in_specs=(P(None, ("dp", "tp")), P(("dp", "tp"), None)),
        out_specs=P(("dp", "tp"), None),
        check_vma=False,
    )
    for it in range(2):
        ka, kb = jax.random.split(jax.random.PRNGKey(50 + it))
        a = jax.random.normal(ka, (n * m_loc, 8 * k_loc), jnp.float32) / 8
        b = jax.random.normal(kb, (8 * k_loc, n_dim), jnp.float32) / 8
        out = jax.jit(jax.shard_map(fn, **specs))(a, b)
        ref = jax.jit(jax.shard_map(golden, **specs))(a, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=1e-4)
