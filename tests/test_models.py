"""Flagship TP transformer vs an unsharded jnp golden (forward parity,
vocab-parallel loss parity, gradient flow through the fused kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # second tier: excluded from the quick CI tier
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import (
    TPTransformer,
    TransformerConfig,
    init_params,
    param_specs,
    train_step,
)
from triton_dist_tpu.models.tp_transformer import (
    _causal_gqa_attention,
    rmsnorm,
    rope,
)
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig


def _cfg(**kw):
    base = dict(
        vocab=64, hidden=32, ffn=64, n_layers=2, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
    )
    base.update(kw)
    return TransformerConfig(**base)


def _ref_forward(tokens, params, cfg):
    """Unsharded pure-jnp forward with the same params/layout."""
    x = params["embed"][tokens.reshape(-1)]
    b, s = cfg.batch, cfg.seq
    g = cfg.n_q_heads // cfg.n_kv_heads
    d = cfg.head_dim
    for p in params["layers"]:
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        # kv-group-major qkv layout (see init_params)
        qkv = (h @ p["wqkv"].reshape(cfg.hidden, -1)).reshape(
            b, s, cfg.n_kv_heads, g + 2, d
        )
        q = qkv[..., :g, :].reshape(b, s, cfg.n_q_heads, d)
        k = qkv[..., g, :]
        v = qkv[..., g + 1, :]
        pos = jnp.arange(s, dtype=jnp.int32)
        q, k = rope(q, pos, cfg.rope_theta), rope(k, pos, cfg.rope_theta)
        attn = _causal_gqa_attention(q, k, v, cfg)
        x = x + attn.reshape(b * s, cfg.q_dim) @ p["wo"]
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        gu = (h @ p["w_gate_up"].reshape(cfg.hidden, -1)).reshape(b * s, -1, 2)
        gate, up = gu[..., 0], gu[..., 1]
        x = x + (jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up) @ p["w_down"]
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["lm_head"]


def _ref_loss(tokens, targets, params, cfg):
    logits = _ref_forward(tokens, params, cfg).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[:, None], axis=1)[:, 0]
    return jnp.mean(lse - tl)


def _put_params(params, cfg, mesh):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, param_specs(cfg),
    )


def test_tp_transformer_forward_parity(mesh4):
    cfg = _cfg()
    model = TPTransformer(cfg)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (cfg.batch * cfg.seq,), 0, cfg.vocab, jnp.int32
    )
    params_sh = _put_params(params, cfg, mesh4)
    got = jax.jit(
        jax.shard_map(
            lambda t, p: model(t, p), mesh=mesh4,
            in_specs=(P("tp"), param_specs(cfg)),
            out_specs=P(None, "tp"), check_vma=False,
        )
    )(tokens, params_sh)
    want = _ref_forward(tokens, params, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_tp_transformer_loss_parity(mesh4):
    cfg = _cfg()
    model = TPTransformer(cfg)
    params = init_params(jax.random.PRNGKey(2), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(jax.random.PRNGKey(3), (m,), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(4), (m,), 0, cfg.vocab, jnp.int32)
    params_sh = _put_params(params, cfg, mesh4)
    got = jax.jit(
        jax.shard_map(
            lambda t, y, p: model.loss(t, y, p)[None], mesh=mesh4,
            in_specs=(P("tp"), P(None), param_specs(cfg)),
            out_specs=P("tp"), check_vma=False,
        )
    )(tokens, targets, params_sh)
    want = float(_ref_loss(tokens, targets, params, cfg))
    # every tp shard computes the identical full-batch loss
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_tp_transformer_train_step_dp_tp(mesh2x4):
    """Full dp(2) x tp(4) training step: loss decreases and sharded/
    replicated grads are consistent with the unsharded reference step."""
    # 1 layer: the train-step property under test; 2-layer stacking stays
    # covered by the much cheaper forward/loss parity tests
    cfg = _cfg(n_layers=1)
    model = TPTransformer(cfg)
    params = init_params(jax.random.PRNGKey(5), cfg)
    m = cfg.batch * cfg.seq
    dp = 2
    tokens = jax.random.randint(jax.random.PRNGKey(6), (dp * m,), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(7), (dp * m,), 0, cfg.vocab, jnp.int32)

    specs = param_specs(cfg)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh2x4, s)), params, specs
    )

    def step(t, y, p):
        # t sharded over (dp, tp); y sharded over dp (replicated in tp)
        return train_step(model, p, t, y.reshape(-1), lr=1e-1)

    step_j = jax.jit(
        jax.shard_map(
            step, mesh=mesh2x4,
            in_specs=(P(("dp", "tp")), P("dp"), specs),
            out_specs=(specs, P()), check_vma=False,
        )
    )
    p1, loss1 = step_j(tokens, targets, params_sh)
    p2, loss2 = step_j(tokens, targets, p1)
    assert float(loss2) < float(loss1)

    # reference step on the dp=0 half must match the dp-mean direction only
    # loosely (different batch); instead check exact grad parity for one
    # replicated param via the unsharded loss on the full batch
    def full_loss(p):
        l = 0.0
        for i in range(dp):
            l = l + _ref_loss(
                tokens[i * m : (i + 1) * m], targets[i * m : (i + 1) * m], p, cfg
            )
        return l / dp

    g_ref = jax.grad(full_loss)(params)
    for name in ("final_norm", "embed"):  # replicated params: exact parity
        got_after = np.asarray(p1[name])
        want_after = np.asarray(params[name]) - 1e-1 * np.asarray(g_ref[name])
        np.testing.assert_allclose(
            got_after, want_after, rtol=2e-3, atol=2e-3, err_msg=name
        )


def _moe_ref_forward(tokens, params, cfg):
    """Dense per-token-expert golden forward (one MoE layer)."""
    from triton_dist_tpu.ops.moe_utils import select_experts

    m = tokens.shape[0]
    x = params["embed"][tokens]
    p = params["layers"][0]
    b, s, g, d = cfg.batch, cfg.seq, cfg.n_q_heads // cfg.n_kv_heads, cfg.head_dim
    h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
    qkv = (h @ p["wqkv"].reshape(cfg.hidden, -1)).reshape(b, s, cfg.n_kv_heads, g + 2, d)
    q = qkv[..., :g, :].reshape(b, s, cfg.n_q_heads, d)
    k, v = qkv[..., g, :], qkv[..., g + 1, :]
    pos = jnp.arange(s, dtype=jnp.int32)
    q, k = rope(q, pos, cfg.rope_theta), rope(k, pos, cfg.rope_theta)
    attn = _causal_gqa_attention(q, k, v, cfg)
    x = x + attn.reshape(m, cfg.q_dim) @ p["wo"]
    h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
    logits = h.astype(jnp.float32) @ p["router"].astype(jnp.float32)
    tw, ids = select_experts(logits, cfg.topk)
    moe_out = np.zeros((m, cfg.hidden), np.float32)
    for t in range(m):
        for kk in range(cfg.topk):
            e = int(ids[t, kk])
            he = jax.nn.gelu(np.asarray(h)[t] @ np.asarray(p["w_up"])[e])
            moe_out[t] += float(tw[t, kk]) * (np.asarray(he) @ np.asarray(p["w_down"])[e])
    x = x + moe_out
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["lm_head"]


@pytest.mark.parametrize("kind", ["tp", "ep"])
def test_moe_transformer_forward_parity(mesh4, kind):
    """MoE decoder forward vs a dense per-token expert golden — the same
    answer whether experts are tensor-parallel (sliced over the FFN dim,
    AG-GroupGEMM/MoE-Reduce-RS) or expert-parallel (whole experts per PE,
    a2a dispatch/combine)."""
    from triton_dist_tpu.models import (
        EPMoETransformer, EPMoETransformerConfig, MoETransformerConfig,
        TPMoETransformer, ep_moe_param_specs, init_moe_params, moe_param_specs,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.ops.moe_utils import select_experts

    shapes = dict(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=4, topk=2,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    if kind == "tp":
        cfg = MoETransformerConfig(**shapes)
        model, specs = TPMoETransformer(cfg), moe_param_specs(cfg)
    else:
        cfg = EPMoETransformerConfig(**shapes)
        model, specs = EPMoETransformer(cfg), ep_moe_param_specs(cfg)
    params = init_moe_params(jax.random.PRNGKey(8), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(jax.random.PRNGKey(9), (m,), 0, cfg.vocab, jnp.int32)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)), params, specs
    )
    got = jax.jit(
        jax.shard_map(
            lambda t, p: model(t, p), mesh=mesh4,
            in_specs=(P("tp"), specs), out_specs=P(None, "tp"), check_vma=False,
        )
    )(tokens, params_sh)

    want = _moe_ref_forward(tokens, params, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=5e-3, atol=5e-3)


def test_ep_moe_transformer_hier_forward(mesh2x4):
    """Hierarchical EP model wiring on a (dp, tp) mesh: attention TP over
    ``tp``, whole experts spread over all 8 PEs, two-phase dispatch over
    (dp, tp); each dp group runs its own token slice, so the golden is the
    dense forward per group."""
    from triton_dist_tpu.models import (
        EPMoETransformer, EPMoETransformerConfig, ep_moe_param_specs,
        init_moe_params,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    dp = 2
    cfg = EPMoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=8, topk=2, ep_outer="dp",
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    model, specs = EPMoETransformer(cfg), ep_moe_param_specs(cfg)
    params = init_moe_params(jax.random.PRNGKey(10), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(
        jax.random.PRNGKey(11), (dp * m,), 0, cfg.vocab, jnp.int32
    )
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh2x4, s)), params, specs
    )
    got = jax.jit(
        jax.shard_map(
            lambda t, p: model(t, p), mesh=mesh2x4,
            in_specs=(P(("dp", "tp")), specs),
            out_specs=P("dp", "tp"), check_vma=False,
        )
    )(tokens, params_sh)
    # drain the interpreted program before dispatching the eager golden:
    # concurrent io_callbacks + eager ops can starve XLA:CPU's thread pool
    # (the conftest deadlock note) on small-core hosts
    jax.block_until_ready(got)
    want = np.concatenate(
        [
            np.asarray(_moe_ref_forward(tokens[g * m : (g + 1) * m], params, cfg))
            for g in range(dp)
        ]
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-3, atol=5e-3)


def test_models_package_imports():
    import triton_dist_tpu.models as m

    assert hasattr(m, "TPTransformer") and hasattr(m, "train_step")


def test_sp_transformer_forward_and_train(mesh4):
    """Context-parallel transformer: forward parity vs a full-sequence
    reference with the same (replicated) params; train step reduces loss."""
    from triton_dist_tpu.models.sp_transformer import (
        SPTransformer, SPTransformerConfig, sp_train_step,
    )
    from triton_dist_tpu.ops.ring_attention import RingAttentionConfig

    b, s = 1, 32
    cfg = SPTransformerConfig(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=2, n_kv_heads=1,
        head_dim=128, batch=b, seq=s,
        ring_config=RingAttentionConfig(block_q=8, block_kv=8),
    )
    model = SPTransformer(cfg)
    params = init_params(jax.random.PRNGKey(10), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(11), (b, s), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(12), (b, s), 0, cfg.vocab, jnp.int32)

    got = jax.jit(
        jax.shard_map(
            lambda t, p: model(t, p), mesh=mesh4,
            in_specs=(P(None, "tp"), P(None)),
            out_specs=P(None, "tp", None), check_vma=False,
        )
    )(tokens, params)
    # reference: same weights through the dense _ref_forward (head-group
    # layout matches; MHA here via repeat inside the model)
    want = _ref_forward(tokens.reshape(-1), params, cfg).reshape(b, s, cfg.vocab)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=3e-3, atol=3e-3)

    step = jax.jit(
        jax.shard_map(
            lambda t, y, p: sp_train_step(model, p, t, y, lr=5e-2),
            mesh=mesh4,
            in_specs=(P(None, "tp"), P(None, "tp"), P(None)),
            out_specs=(P(None), P()), check_vma=False,
        )
    )
    p1, l1 = step(tokens, targets, params)
    p2, l2 = step(tokens, targets, p1)
    assert float(l2) < float(l1)


def test_moe_transformer_train_step(mesh4):
    """MoE decoder trains end-to-end through the fused MoE kernels' custom
    VJP (router included): loss decreases over SGD steps."""
    from triton_dist_tpu.models import (
        MoETransformerConfig, TPMoETransformer, init_moe_params, moe_param_specs,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    cfg = MoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=4, topk=2,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    model = TPMoETransformer(cfg)
    specs = moe_param_specs(cfg)
    params = init_moe_params(jax.random.PRNGKey(20), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(jax.random.PRNGKey(21), (m,), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(22), (m,), 0, cfg.vocab, jnp.int32)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)), params, specs
    )
    step = jax.jit(
        jax.shard_map(
            lambda t, y, p: train_step(model, p, t, y, lr=1e-1, dp_axis=None),
            mesh=mesh4, in_specs=(P("tp"), P(None), specs),
            out_specs=(specs, P()), check_vma=False,
        )
    )
    p1, loss1 = step(tokens, targets, params_sh)
    jax.block_until_ready(loss1)
    p2, loss2 = step(tokens, targets, p1)
    jax.block_until_ready(loss2)
    p3, loss3 = step(tokens, targets, p2)
    assert float(loss2) < float(loss1)
    assert float(loss3) < float(loss2)
    # router actually moved (its grad flows through the routing weights)
    r0 = np.asarray(params["layers"][0]["router"])
    r1 = np.asarray(p1["layers"][0]["router"])
    assert np.abs(r1 - r0).max() > 0


def test_ep_moe_transformer_train_step(mesh4):
    """Flat expert-parallel MoE decoder trains end-to-end (a2a + grouped
    GEMM VJPs compose): loss decreases, router moves."""
    from triton_dist_tpu.models import (
        EPMoETransformer, EPMoETransformerConfig, ep_moe_param_specs,
        init_moe_params,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    cfg = EPMoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=4, topk=2,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    model, specs = EPMoETransformer(cfg), ep_moe_param_specs(cfg)
    params = init_moe_params(jax.random.PRNGKey(30), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(jax.random.PRNGKey(31), (m,), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(32), (m,), 0, cfg.vocab, jnp.int32)
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)), params, specs
    )
    step = jax.jit(
        jax.shard_map(
            lambda t, y, p: train_step(model, p, t, y, lr=1e-1, dp_axis=None),
            mesh=mesh4, in_specs=(P("tp"), P(None), specs),
            out_specs=(specs, P()), check_vma=False,
        )
    )
    p1, loss1 = step(tokens, targets, params_sh)
    jax.block_until_ready(loss1)
    p2, loss2 = step(tokens, targets, p1)
    jax.block_until_ready(loss2)
    assert float(loss2) < float(loss1)
    r0 = np.asarray(params["layers"][0]["router"])
    r1 = np.asarray(p1["layers"][0]["router"])
    assert np.abs(r1 - r0).max() > 0


def test_train_step_rejects_ep_quant():
    """ep_quant is inference-only (the quantized wire zeroes the router
    gradient — test_quant_dispatch_grad_is_zero); train_step must refuse
    it loudly rather than train a dead router silently."""
    import pytest

    from triton_dist_tpu.models import EPMoETransformer, EPMoETransformerConfig

    cfg = EPMoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=4, topk=2, ep_quant="int8",
    )
    model = EPMoETransformer(cfg)
    with pytest.raises(ValueError, match="ep_quant"):
        train_step(model, {}, None, None)


def _moe_dense_forward(tokens, params, cfg):
    """Differentiable dense golden forward for the (1-layer) MoE decoder
    (einsum MoE instead of _moe_ref_forward's numpy loop)."""
    from triton_dist_tpu.ops.moe_utils import select_experts

    m = tokens.shape[0]
    x = params["embed"][tokens]
    p = params["layers"][0]
    b, s, g, d = cfg.batch, cfg.seq, cfg.n_q_heads // cfg.n_kv_heads, cfg.head_dim
    h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
    qkv = (h @ p["wqkv"].reshape(cfg.hidden, -1)).reshape(b, s, cfg.n_kv_heads, g + 2, d)
    q = qkv[..., :g, :].reshape(b, s, cfg.n_q_heads, d)
    k, v = qkv[..., g, :], qkv[..., g + 1, :]
    pos = jnp.arange(s, dtype=jnp.int32)
    q, k = rope(q, pos, cfg.rope_theta), rope(k, pos, cfg.rope_theta)
    attn = _causal_gqa_attention(q, k, v, cfg)
    x = x + attn.reshape(m, cfg.q_dim) @ p["wo"]
    h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
    logits = h.astype(jnp.float32) @ p["router"].astype(jnp.float32)
    tw, ids = select_experts(logits, cfg.topk)
    he = jax.nn.gelu(jnp.einsum("th,tkhf->tkf", h, p["w_up"][ids]))
    y = jnp.einsum("tkf,tkfh->tkh", he, p["w_down"][ids])
    x = x + jnp.sum(tw.astype(jnp.float32)[:, :, None] * y, axis=1)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["lm_head"]


def test_ep_moe_transformer_hier_train_grad_parity(mesh2x4):
    """The dp x tp hierarchical EP training step applies the EXACT gradient
    of the dp-mean loss — in particular the dp-sharded expert banks must
    NOT be pmean'd across dp ranks holding different experts."""
    from triton_dist_tpu.models import (
        EPMoETransformer, EPMoETransformerConfig, ep_moe_param_specs,
        init_moe_params,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    dp, lr = 2, 1e-1
    cfg = EPMoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=8, topk=2, ep_outer="dp",
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    model, specs = EPMoETransformer(cfg), ep_moe_param_specs(cfg)
    params = init_moe_params(jax.random.PRNGKey(40), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(
        jax.random.PRNGKey(41), (dp * m,), 0, cfg.vocab, jnp.int32
    )
    targets = jax.random.randint(
        jax.random.PRNGKey(42), (dp * m,), 0, cfg.vocab, jnp.int32
    )
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh2x4, s)), params, specs
    )
    p1, _ = jax.jit(
        jax.shard_map(
            lambda t, y, p: train_step(model, p, t, y.reshape(-1), lr=lr),
            mesh=mesh2x4, in_specs=(P(("dp", "tp")), P("dp"), specs),
            out_specs=(specs, P()), check_vma=False,
        )
    )(tokens, targets, params_sh)
    jax.block_until_ready(p1)

    def dense_ce(toks, tgts, p):
        logits = _moe_dense_forward(toks, p, cfg).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, tgts[:, None], axis=1)[:, 0]
        return jnp.mean(lse - tl)

    def full_loss(p):
        l = 0.0
        for i in range(dp):
            l = l + dense_ce(
                tokens[i * m : (i + 1) * m], targets[i * m : (i + 1) * m], p
            )
        return l / dp

    g_ref = jax.grad(full_loss)(params)
    for name, got, want_p, want_g in (
        ("w_up", p1["layers"][0]["w_up"], params["layers"][0]["w_up"],
         g_ref["layers"][0]["w_up"]),
        ("w_down", p1["layers"][0]["w_down"], params["layers"][0]["w_down"],
         g_ref["layers"][0]["w_down"]),
        ("router", p1["layers"][0]["router"], params["layers"][0]["router"],
         g_ref["layers"][0]["router"]),
        ("embed", p1["embed"], params["embed"], g_ref["embed"]),
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want_p) - lr * np.asarray(want_g),
            rtol=2e-3, atol=2e-3, err_msg=name,
        )


def test_sp_transformer_zigzag_matches_contig(mesh4):
    """Zigzag SP transformer on permuted tokens produces exactly the
    contiguous model's logits (unpermuted) — same math, balanced causal
    load."""
    from triton_dist_tpu.models.sp_transformer import (
        SPTransformer, SPTransformerConfig,
    )
    from triton_dist_tpu.ops.ring_attention import (
        RingAttentionConfig, zigzag_permutation,
    )

    b, s, n = 1, 32, 4
    base = dict(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=2, n_kv_heads=1,
        head_dim=128, batch=b, seq=s,
        ring_config=RingAttentionConfig(block_q=4, block_kv=4),
    )
    params = init_params(jax.random.PRNGKey(50), SPTransformerConfig(**base))
    tokens = jax.random.randint(jax.random.PRNGKey(51), (b, s), 0, 32, jnp.int32)

    def run(model, toks):
        return jax.jit(
            jax.shard_map(
                lambda t, p: model(t, p), mesh=mesh4,
                in_specs=(P(None, "tp"), P(None)),
                out_specs=P(None, "tp", None), check_vma=False,
            )
        )(toks, params)

    want = run(SPTransformer(SPTransformerConfig(**base)), tokens)
    jax.block_until_ready(want)
    perm, inv = zigzag_permutation(n, s)
    got_z = run(
        SPTransformer(SPTransformerConfig(**base, zigzag=True)),
        tokens[:, perm],
    )
    jax.block_until_ready(got_z)
    np.testing.assert_allclose(
        np.asarray(got_z)[:, inv], np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_train_step_with_optax_adam(mesh4):
    """train_step takes any optax transform: adam state shards via
    opt_state_specs (param-mirroring subtrees get the param specs, counts
    replicate) and the loss decreases."""
    import optax

    from triton_dist_tpu.models import opt_state_specs

    cfg = _cfg(n_layers=1)  # optimizer plumbing, not model depth
    model = TPTransformer(cfg)
    params = init_params(jax.random.PRNGKey(60), cfg)
    m = cfg.batch * cfg.seq
    tokens = jax.random.randint(jax.random.PRNGKey(61), (m,), 0, cfg.vocab, jnp.int32)
    targets = jax.random.randint(jax.random.PRNGKey(62), (m,), 0, cfg.vocab, jnp.int32)
    opt = optax.adam(1e-2)
    specs = param_specs(cfg)
    o_specs = opt_state_specs(opt, params, specs)
    params_sh = _put_params(params, cfg, mesh4)
    opt_state = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)),
        opt.init(params), o_specs,
    )
    step = jax.jit(
        jax.shard_map(
            lambda t, y, p, o: train_step(
                model, p, t, y, dp_axis=None, opt=opt, opt_state=o
            ),
            mesh=mesh4, in_specs=(P("tp"), P(None), specs, o_specs),
            out_specs=(specs, o_specs, P()), check_vma=False,
        )
    )
    p1, o1, loss1 = step(tokens, targets, params_sh, opt_state)
    jax.block_until_ready(loss1)
    p2, o2, loss2 = step(tokens, targets, p1, o1)
    jax.block_until_ready(loss2)
    assert float(loss2) < float(loss1)


def test_ep_moe_transformer_quantized_forward(mesh2x4):
    """EP-MoE forward with serving-quantized expert banks (int8 pools +
    scales, EP expert-dim sharding): logits within weight-quant tolerance
    of the full-precision model — the scales route through EPMoEMLP's
    scale-folding grouped GEMM."""
    from triton_dist_tpu.models import (
        EPMoETransformer, EPMoETransformerConfig, init_moe_params,
        quantize_moe_serving_params, specs_for,
    )
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    cfg = EPMoETransformerConfig(
        vocab=64, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=16, n_experts=8, topk=2, ep_outer="dp",
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(8, 16, 16),
    )
    model = EPMoETransformer(cfg)
    params = init_moe_params(jax.random.PRNGKey(90), cfg)
    q_params = quantize_moe_serving_params(params)
    dp, m = 2, cfg.batch * cfg.seq
    tokens = jax.random.randint(
        jax.random.PRNGKey(91), (dp * m,), 0, cfg.vocab, jnp.int32
    )

    def logits_of(p):
        sp = specs_for(cfg, p)
        p_sh = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh2x4, s)), p, sp
        )
        out = jax.jit(
            jax.shard_map(
                lambda t, pp: model(t, pp), mesh=mesh2x4,
                in_specs=(P(("dp", "tp")), sp),
                out_specs=P("dp", "tp"), check_vma=False,
            )
        )(tokens, p_sh)
        jax.block_until_ready(out)
        return out

    lf = np.asarray(logits_of(params), np.float32)
    lq = np.asarray(logits_of(q_params), np.float32)
    np.testing.assert_allclose(lq, lf, rtol=3e-2, atol=3e-2 * np.abs(lf).max())
