"""Flash decode vs jnp reference (≙ reference test_flash_decode scripts:
golden = torch attention over the full cache)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.ops.flash_decode import (
    FlashDecodeConfig,
    combine_partials,
    flash_decode,
    flash_decode_op,
    paged_flash_decode,
)


def _paginate(k, v, page_size, key=None, n_extra_pages=0):
    """Split a contiguous cache into shuffled pages + block table."""
    b, h_kv, s, d = k.shape
    ppseq = s // page_size
    n_pages = b * ppseq + n_extra_pages
    perm = (
        jax.random.permutation(key, n_pages)[: b * ppseq]
        if key is not None
        else jnp.arange(b * ppseq)
    )
    bt = perm.reshape(b, ppseq).astype(jnp.int32)
    kp = jnp.zeros((n_pages, h_kv, page_size, d), k.dtype)
    vp = jnp.zeros((n_pages, h_kv, page_size, d), v.dtype)
    k_chunks = k.reshape(b, h_kv, ppseq, page_size, d)
    v_chunks = v.reshape(b, h_kv, ppseq, page_size, d)
    for bi in range(b):
        for ci in range(ppseq):
            kp = kp.at[bt[bi, ci]].set(k_chunks[bi, :, ci])
            vp = vp.at[bt[bi, ci]].set(v_chunks[bi, :, ci])
    return kp, vp, bt


def _ref_decode(q, k, v, kv_lens):
    """Pure-jnp masked attention golden."""
    b, hq, d = q.shape
    _, h_kv, s, _ = k.shape
    g = hq // h_kv
    q4 = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhsd->bhgs", q4, k.astype(jnp.float32))
    scores /= jnp.sqrt(jnp.float32(d))
    mask = jnp.arange(s)[None, :] < kv_lens[:, None]  # [b, s]
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bhsd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(b, hq, d)


def _rand_case(key, b, hq, h_kv, s, d, dtype=jnp.float32):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    q = jax.random.normal(k1, (b, hq, d)).astype(dtype)
    k = jax.random.normal(k2, (b, h_kv, s, d)).astype(dtype)
    v = jax.random.normal(k3, (b, h_kv, s, d)).astype(dtype)
    kv_lens = jax.random.randint(k4, (b,), 1, s + 1, jnp.int32)
    return q, k, v, kv_lens


@pytest.mark.parametrize("g", [1, 4])
def test_flash_decode_local(g):
    b, h_kv, s, d = 2, 2, 256, 128
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(0), b, h_kv * g, h_kv, s, d)
    got = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=64))
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_flash_decode_full_and_empty_lens():
    b, h_kv, g, s, d = 3, 1, 2, 128, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(1), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 1, 7], jnp.int32)
    got = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=32))
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_combine_partials_matches_monolithic():
    """Splitting a cache into shards and merging (out, lse) must reproduce
    full attention exactly (the reference's inter-rank combine invariant)."""
    b, h_kv, g, s, d = 2, 2, 1, 256, 128
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(2), b, h_kv * g, h_kv, s, d)
    n = 4
    s_loc = s // n
    outs, lses = [], []
    for i in range(n):
        sl = slice(i * s_loc, (i + 1) * s_loc)
        local_lens = jnp.clip(kv_lens - i * s_loc, 0, s_loc)
        o, l = flash_decode(
            q, k[:, :, sl], v[:, :, sl], local_lens,
            config=FlashDecodeConfig(block_s=32), return_lse=True,
        )
        outs.append(o)
        lses.append(l)
    got = combine_partials(jnp.stack(outs), jnp.stack(lses))
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_flash_decode_sp_op(mesh4):
    """Full SP pipeline: KV sharded over 4 PEs, LL allgather + merge."""
    b, h_kv, g, s, d = 2, 1, 2, 128, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(3), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 40], jnp.int32)  # rank >1 partially/fully empty
    got = flash_decode_op(q, k, v, kv_lens, mesh4, config=FlashDecodeConfig(block_s=32))
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("g", [1, 4])
def test_paged_flash_decode_matches_contiguous(g):
    """Paged (shuffled pages, block-table indirection) must exactly match
    the contiguous kernel — the block table only changes page placement."""
    b, h_kv, s, d, page = 2, 2, 256, 128, 64
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(5), b, h_kv * g, h_kv, s, d)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(6), n_extra_pages=3)
    got = paged_flash_decode(q, kp, vp, kv_lens, bt)
    want = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=page))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)
    ref = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("fuse_heads", [True, False])
def test_paged_flash_decode_quant(fuse_heads):
    """int8 page pools (the paged × int8 cell of the serving cache
    matrix): per-position absmax row scales fold in-kernel; tolerance
    matches the contiguous int8 path's quantization error, and ragged
    lengths mask exactly as in the bf16 kernel."""
    from triton_dist_tpu.ops.flash_decode import (
        paged_flash_decode_quant, quantize_kv_pages,
    )

    b, h_kv, g, s, d, page = 3, 2, 2, 256, 128, 64
    q, k, v, _ = _rand_case(jax.random.PRNGKey(21), b, h_kv * g, h_kv, s, d)
    # min length 1: the dense _ref_decode golden is NaN over an empty
    # prefix (0/0 softmax) while the kernel's contract emits zeros —
    # the zero-length path is covered by the SP-op test's golden
    kv_lens = jnp.array([s, 97, 1], jnp.int32)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(22),
                           n_extra_pages=2)
    k_q, v_q, ks, vs = quantize_kv_pages(kp, vp)
    got = paged_flash_decode_quant(
        q, k_q, v_q, ks, vs, kv_lens, bt, fuse_heads=fuse_heads,
    )
    want = _ref_decode(q, k, v, kv_lens)
    # same tolerance as the contiguous int8 tests — the quantization
    # error is identical by construction (shared quantize_kv math)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-2, atol=3e-2)


def test_paged_flash_decode_ragged_lens():
    """Partial last page + empty sequences mask correctly."""
    b, h_kv, g, s, d, page = 3, 1, 2, 128, 128, 32
    q, k, v, _ = _rand_case(jax.random.PRNGKey(7), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 41, 1], jnp.int32)  # mid-page boundaries
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(8))
    got = paged_flash_decode(q, kp, vp, kv_lens, bt)
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_paged_flash_decode_sp(mesh4):
    """Paged SP decode: each PE's page pool covers its sequence shard."""
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.flash_decode import paged_flash_decode_distributed

    b, h_kv, g, s, d, page = 2, 1, 2, 256, 128, 32
    world = 4
    s_loc = s // world
    q, k, v, _ = _rand_case(jax.random.PRNGKey(9), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 100], jnp.int32)
    # build each PE's pool from its shard; stack pools on a leading axis
    pools = []
    for i in range(world):
        sl = slice(i * s_loc, (i + 1) * s_loc)
        kp, vp, bt = _paginate(
            k[:, :, sl], v[:, :, sl], page, key=jax.random.PRNGKey(10 + i)
        )
        pools.append((kp, vp, bt))
    kps = jnp.stack([p[0] for p in pools])
    vps = jnp.stack([p[1] for p in pools])
    bts = jnp.stack([p[2] for p in pools])

    def fn(q, kps, vps, bts, lens):
        me = jax.lax.axis_index("tp")
        local_lens = jnp.clip(lens - me * s_loc, 0, s_loc)
        return paged_flash_decode_distributed(
            q, kps[0], vps[0], local_lens, bts[0], axis="tp"
        )

    got = jax.jit(
        jax.shard_map(
            fn, mesh=mesh4,
            in_specs=(P(None, None, None), P("tp", None, None, None, None),
                      P("tp", None, None, None, None), P("tp", None, None), P(None)),
            out_specs=P(None, None, None), check_vma=False,
        )
    )(q, kps, vps, bts, kv_lens)
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_flash_decode_sp_world1():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    b, h_kv, g, s, d = 1, 2, 2, 64, 128
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(4), b, h_kv * g, h_kv, s, d)
    got = flash_decode_op(q, k, v, kv_lens, mesh, config=FlashDecodeConfig(block_s=32))
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_flash_decode_xla_candidate(mesh4):
    """block_s=0 (XLA-native formulation): same (out, lse) contract as the
    Pallas kernel at world-1 AND through the SP combine (partial shards,
    one fully-empty shard)."""
    cfg = FlashDecodeConfig(block_s=0)
    b, h_kv, g, s, d = 2, 1, 2, 128, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(6), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 40], jnp.int32)  # rank >1 partially/fully empty
    want = _ref_decode(q, k, v, kv_lens)
    got = flash_decode_op(q, k, v, kv_lens, mesh4, config=cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("tp",))
    got1 = flash_decode_op(q, k, v, kv_lens, mesh1, config=cfg)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(want), rtol=2e-4, atol=2e-4)
    # standalone (out, lse) parity vs the kernel
    out_x, lse_x = flash_decode(q, k, v, kv_lens, config=cfg, return_lse=True)
    out_p, lse_p = flash_decode(
        q, k, v, kv_lens, config=FlashDecodeConfig(block_s=32),
        return_lse=True, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out_x), np.asarray(out_p), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lse_x), np.asarray(lse_p), rtol=2e-4, atol=2e-4)


def test_flash_decode_quant_parity():
    """int8 KV cache (absmax row scales): output within quantization
    tolerance of the f32 path; zero-length rows handled."""
    from triton_dist_tpu.ops.flash_decode import (
        FlashDecodeConfig, flash_decode, flash_decode_quant, quantize_kv,
    )

    b, hq, h_kv, s, d = 2, 4, 2, 64, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(30), b, hq, h_kv, s, d)
    kv_lens = jnp.array([s, 37], jnp.int32)
    cfg = FlashDecodeConfig(block_s=16)
    want = flash_decode(q, k, v, kv_lens, config=cfg)
    k_q, v_q, ks, vs = quantize_kv(k, v)
    got = flash_decode_quant(q, k_q, v_q, ks, vs, kv_lens, config=cfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=3e-2, atol=3e-2
    )


def test_flash_decode_quant_distributed(mesh4):
    """SP decode over a sequence-sharded int8 cache merges to the same
    answer as the f32 distributed path (within quantization error)."""
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.flash_decode import (
        FlashDecodeConfig, flash_decode_distributed,
        flash_decode_quant_distributed, quantize_kv,
    )

    b, hq, h_kv, s, d = 2, 4, 2, 128, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(31), b, hq, h_kv, s, d)
    kv_lens = jnp.array([s, 57], jnp.int32)
    s_loc = s // 4
    cfg = FlashDecodeConfig(block_s=8)

    def local_lens(me):
        return jnp.clip(kv_lens - me * s_loc, 0, s_loc)

    def f32_fn(q, k_s, v_s):
        me = jax.lax.axis_index("tp")
        return flash_decode_distributed(
            q, k_s, v_s, local_lens(me), axis="tp", config=cfg
        )

    def q_fn(q, k_s, v_s):
        me = jax.lax.axis_index("tp")
        k_q, v_q, ks, vs = quantize_kv(k_s, v_s)
        return flash_decode_quant_distributed(
            q, k_q, v_q, ks, vs, local_lens(me), axis="tp", config=cfg
        )

    spec_kv = P(None, None, "tp", None)
    run = lambda fn: jax.jit(
        jax.shard_map(
            fn, mesh=mesh4, in_specs=(P(None, None, None), spec_kv, spec_kv),
            out_specs=P(None, None, None), check_vma=False,
        )
    )(q, k, v)
    want = run(f32_fn)
    jax.block_until_ready(want)
    got = run(q_fn)
    jax.block_until_ready(got)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=3e-2, atol=3e-2
    )


@pytest.mark.parametrize("g", [1, 4])
def test_flash_decode_fused_heads_matches_per_head(g):
    """fuse_heads moves the kv-head loop inside the kernel (one K/V slab
    per chunk step); the math is identical, so it must match the per-head
    kernel bit-for-bit at the same chunking."""
    b, h_kv, s, d = 2, 4, 256, 128
    q, k, v, kv_lens = _rand_case(
        jax.random.PRNGKey(40), b, h_kv * g, h_kv, s, d
    )
    want = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=64))
    got = flash_decode(
        q, k, v, kv_lens,
        config=FlashDecodeConfig(block_s=64, fuse_heads=True),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)
    ref = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_decode_fused_heads_ragged_and_lse():
    """Ragged lens (incl. empty) and the (out, lse) contract under
    fuse_heads — the SP combine consumes either kernel's partials."""
    b, h_kv, g, s, d = 3, 2, 2, 128, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(41), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 37, 0], jnp.int32)
    o_f, l_f = flash_decode(
        q, k, v, kv_lens,
        config=FlashDecodeConfig(block_s=32, fuse_heads=True),
        return_lse=True,
    )
    o_p, l_p = flash_decode(
        q, k, v, kv_lens, config=FlashDecodeConfig(block_s=32),
        return_lse=True,
    )
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_p), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_p), rtol=1e-6, atol=1e-6)


def test_flash_decode_fused_heads_quant():
    """int8 + fuse_heads: per-position scales fold in per head."""
    from triton_dist_tpu.ops.flash_decode import flash_decode_quant, quantize_kv

    b, hq, h_kv, s, d = 2, 8, 4, 64, 128
    q, k, v, _ = _rand_case(jax.random.PRNGKey(42), b, hq, h_kv, s, d)
    kv_lens = jnp.array([s, 19], jnp.int32)
    want = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=16))
    k_q, v_q, ks, vs = quantize_kv(k, v)
    got = flash_decode_quant(
        q, k_q, v_q, ks, vs, kv_lens,
        config=FlashDecodeConfig(block_s=16, fuse_heads=True),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=3e-2, atol=3e-2
    )


@pytest.mark.parametrize("fuse_heads", [True, False])
def test_paged_flash_decode_head_fusion_paths(fuse_heads):
    """Both paged index paths (one DMA per page vs per (head, page)) hit
    the same answer on shuffled pools with ragged lens."""
    b, h_kv, g, s, d, page = 2, 2, 2, 128, 128, 32
    q, k, v, _ = _rand_case(jax.random.PRNGKey(43), b, h_kv * g, h_kv, s, d)
    kv_lens = jnp.array([s, 41], jnp.int32)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(44), n_extra_pages=2)
    got = paged_flash_decode(q, kp, vp, kv_lens, bt, fuse_heads=fuse_heads)
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("fuse_heads", [True, False])
def test_paged_flash_verify_grids(fuse_heads):
    """Multi-position paged verify (speculative serving attention): both
    grid shapes — fused-heads (one DMA per physical page, the serving
    default) and per-head — match the contiguous XLA verify golden over
    a shuffled page pool with per-row prefix lengths."""
    from triton_dist_tpu.ops.flash_decode import _xla_verify, paged_flash_verify

    b, S, h_kv, g, d, page = 2, 3, 2, 2, 64, 8
    hq = h_kv * g
    q = jax.random.normal(jax.random.PRNGKey(70), (b, S, hq, d), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(71), (8, h_kv, page, d), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(72), (8, h_kv, page, d), jnp.float32)
    bt = jnp.array([[6, 2, 4], [1, 3, 5]], jnp.int32)
    # pos0=7 puts row 0's span entirely inside page 0 while the seq's max
    # len (10) admits chunk 1 — a fully-masked row in an ACTIVE chunk, the
    # verify-specific case the online-softmax NaN guard (m_safe) exists for
    pos0 = jnp.array([7, 13], jnp.int32)
    lens = pos0[:, None] + jnp.arange(1, S + 1)[None, :]
    got = paged_flash_verify(q, kp, vp, lens, bt, fuse_heads=fuse_heads)
    kc = kp[bt].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, 3 * page, d)
    vc = vp[bt].transpose(0, 2, 1, 3, 4).reshape(b, h_kv, 3 * page, d)
    want = _xla_verify(q, kc, vc, lens, return_lse=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("fuse_heads", [True, False])
def test_paged_decode_nondivisor_pages_per_step(fuse_heads):
    """Clamped duplicate-tail path (ADVICE r5 #3): P=2 over a 5-page table
    leaves the last step with one real page + one clamped DUPLICATE fetch
    of the table's final entry. Those duplicate span positions sit at
    >= max_pages*page_size >= kv_len, so the length mask must discard
    them — a regression here double-counts the final page's scores."""
    b, h_kv, g, s, d, page = 2, 2, 2, 160, 128, 32  # 5 pages/sequence
    q, k, v, _ = _rand_case(jax.random.PRNGKey(80), b, h_kv * g, h_kv, s, d)
    # one full-length sequence (every tail position live) and one ragged
    kv_lens = jnp.array([s, 77], jnp.int32)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(81), n_extra_pages=2)
    assert bt.shape[1] % 2 == 1  # non-divisor: the tail step is clamped
    got = paged_flash_decode(
        q, kp, vp, kv_lens, bt, fuse_heads=fuse_heads, pages_per_step=2
    )
    want = _ref_decode(q, k, v, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("fuse_heads", [True, False])
def test_paged_verify_nondivisor_pages_per_step(fuse_heads):
    """The verify grids' clamped duplicate tail, same P=2-over-5-pages
    shape, asserted against the contiguous golden with per-row lengths
    reaching into the final (partially duplicated) step."""
    from triton_dist_tpu.ops.flash_decode import _xla_verify, paged_flash_verify

    b, S, h_kv, g, s, d, page = 2, 3, 2, 2, 160, 128, 32
    hq = h_kv * g
    q = jax.random.normal(jax.random.PRNGKey(82), (b, S, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(83), (b, h_kv, s, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(84), (b, h_kv, s, d), jnp.float32)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(85), n_extra_pages=2)
    pos0 = jnp.array([s - S, 100], jnp.int32)  # row spans end inside page 4
    lens = pos0[:, None] + jnp.arange(1, S + 1)[None, :]
    got = paged_flash_verify(
        q, kp, vp, lens, bt, fuse_heads=fuse_heads, pages_per_step=2
    )
    want = _xla_verify(q, k, v, lens, return_lse=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


# ---------------------------------------------------------------------------
# Logit soft-cap + non-pow-2 head dims (ISSUE 14 satellite; VERDICT
# missing #1 — the reference's soft_cap / BLOCK_DPE machinery,
# flash_decode.py:103-107,155-190). CPU goldens: every entry is pinned
# against a local tanh-capped reference; the kernel-level math
# (_online_softmax_step) is exercised directly as plain jnp, so the
# padding/capping algebra is covered even where the Pallas build is
# unavailable. Chip measurement stays deferred (ROADMAP item 1).
# ---------------------------------------------------------------------------

def _ref_decode_capped(q, k, v, kv_lens, soft_cap=0.0):
    """Masked-attention golden with the reference's logit soft-cap:
    ``s = cap * tanh(s / cap)`` on the scaled scores, before masking."""
    b, hq, d = q.shape
    _, h_kv, s_len, _ = k.shape
    g = hq // h_kv
    q4 = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhsd->bhgs", q4, k.astype(jnp.float32))
    scores /= jnp.sqrt(jnp.float32(d))
    if soft_cap:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    mask = jnp.arange(s_len)[None, :] < kv_lens[:, None]
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bhsd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(b, hq, d)


def test_kernel_head_dim_padding_table():
    """Power-of-2 dims pass through (today's shapes, bit-unchanged);
    non-pow-2 dims round up to the next power of two."""
    from triton_dist_tpu.ops.flash_decode import _kernel_head_dim

    assert _kernel_head_dim(64) == 64
    assert _kernel_head_dim(128) == 128
    assert _kernel_head_dim(256) == 256
    assert _kernel_head_dim(80) == 128
    assert _kernel_head_dim(96) == 128
    assert _kernel_head_dim(192) == 256
    with pytest.raises(ValueError):
        _kernel_head_dim(0)


@pytest.mark.parametrize("soft_cap", [0.0, 20.0])
def test_online_softmax_step_padding_exact(soft_cap):
    """The kernel step function (plain jnp — runnable on any box) must be
    EXACT under head-dim zero-padding: padded q·k terms add 0 to every
    score and padded v columns emit 0 output columns. This is the
    algebraic fact the host-level pad-and-slice relies on."""
    from triton_dist_tpu.ops.flash_decode import (
        _finalize_softmax, _kernel_head_dim, _online_softmax_step,
        _pad_head_dim,
    )

    g, sc, d = 4, 64, 96
    key = jax.random.PRNGKey(7)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (g, d), jnp.float32)
    k = jax.random.normal(kk, (sc, d), jnp.float32)
    v = jax.random.normal(kv_, (sc, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def run(qx, kx, vx, dd):
        m0 = jnp.full((g, 1), -jnp.inf)
        l0 = jnp.zeros((g, 1))
        a0 = jnp.zeros((g, dd))
        m, l, a = _online_softmax_step(
            qx, kx, vx, None, None, 0, jnp.int32(50), scale, m0, l0, a0,
            soft_cap,
        )
        return _finalize_softmax(m, l, a)

    out_ref, lse_ref = run(q, k, v, d)
    dp = _kernel_head_dim(d)
    assert dp == 128
    out_pad, lse_pad = run(
        _pad_head_dim(q, dp), _pad_head_dim(k, dp), _pad_head_dim(v, dp), dp
    )
    np.testing.assert_array_equal(np.asarray(out_pad[:, :d]), np.asarray(out_ref))
    np.testing.assert_array_equal(np.asarray(out_pad[:, d:]), 0.0)
    np.testing.assert_array_equal(np.asarray(lse_pad), np.asarray(lse_ref))


@pytest.mark.parametrize("block_s", [0, 64])
def test_flash_decode_soft_cap(block_s):
    """soft_cap on the decode entry (XLA-native and kernel/golden paths)
    vs the tanh-capped reference; cap=0 stays bit-identical to the
    pre-knob result."""
    b, h_kv, g, s, d = 2, 2, 2, 256, 128
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(11), b, h_kv * g, h_kv, s, d)
    got = flash_decode(
        q, k, v, kv_lens, config=FlashDecodeConfig(block_s=block_s, soft_cap=20.0)
    )
    want = _ref_decode_capped(q, k, v, kv_lens, soft_cap=20.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
    # the capped result must actually differ from the uncapped one
    uncapped = flash_decode(q, k, v, kv_lens, config=FlashDecodeConfig(block_s=block_s))
    assert not np.allclose(np.asarray(got), np.asarray(uncapped))
    # soft_cap=0.0 is the identity posture — bit-identical to the default
    zero = flash_decode(
        q, k, v, kv_lens, config=FlashDecodeConfig(block_s=block_s, soft_cap=0.0)
    )
    np.testing.assert_array_equal(np.asarray(zero), np.asarray(uncapped))


def test_flash_verify_soft_cap_and_nonpow2():
    """The verify family: per-row prefix lengths × soft-cap × a d=96
    head dim, against the capped per-row reference."""
    from triton_dist_tpu.ops.flash_decode import flash_verify

    b, S, h_kv, g, s, d = 2, 3, 2, 2, 128, 96
    hq = h_kv * g
    q = jax.random.normal(jax.random.PRNGKey(21), (b, S, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(22), (b, h_kv, s, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(23), (b, h_kv, s, d), jnp.float32)
    pos0 = jnp.array([s - S, 40], jnp.int32)
    lens = pos0[:, None] + jnp.arange(1, S + 1)[None, :]
    got = flash_verify(
        q, k, v, lens, config=FlashDecodeConfig(block_s=32, soft_cap=15.0)
    )
    # per-row golden: one capped decode per draft position
    for i in range(S):
        want = _ref_decode_capped(q[:, i], k, v, lens[:, i], soft_cap=15.0)
        np.testing.assert_allclose(
            np.asarray(got[:, i]), np.asarray(want), rtol=2e-4, atol=2e-4
        )


def test_flash_decode_nonpow2_head_dim():
    """d=96 (the reference's BLOCK_DPE case) through the decode entry —
    XLA path natively, kernel path via pad-and-slice — and through the
    SP merge (lse packing is d-agnostic)."""
    b, h_kv, g, s, d = 2, 2, 2, 256, 96
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(31), b, h_kv * g, h_kv, s, d)
    want = _ref_decode_capped(q, k, v, kv_lens)
    for block_s in (0, 64):
        got = flash_decode(
            q, k, v, kv_lens, config=FlashDecodeConfig(block_s=block_s)
        )
        assert got.shape == (b, h_kv * g, d)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
    # SP merge over shards keeps the exact-combine invariant at d=96
    n, s_loc = 4, s // 4
    outs, lses = [], []
    for i in range(n):
        sl = slice(i * s_loc, (i + 1) * s_loc)
        o, l = flash_decode(
            q, k[:, :, sl], v[:, :, sl],
            jnp.clip(kv_lens - i * s_loc, 0, s_loc),
            config=FlashDecodeConfig(block_s=32), return_lse=True,
        )
        outs.append(o)
        lses.append(l)
    got = combine_partials(jnp.stack(outs), jnp.stack(lses))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_paged_decode_soft_cap_nonpow2():
    """The paged entry takes soft_cap as a kwarg (its knobs are kwargs)
    and pads page pools for non-pow-2 head dims; pinned against the
    contiguous capped reference at d=96."""
    b, h_kv, g, s, d, page = 2, 2, 2, 256, 96, 64
    q, k, v, kv_lens = _rand_case(jax.random.PRNGKey(41), b, h_kv * g, h_kv, s, d)
    kp, vp, bt = _paginate(k, v, page, key=jax.random.PRNGKey(42), n_extra_pages=2)
    got = paged_flash_decode(q, kp, vp, kv_lens, bt, soft_cap=25.0)
    want = _ref_decode_capped(q, k, v, kv_lens, soft_cap=25.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
