"""The real-TPU smoke script must stay runnable: exercise its exact op
sequence through the interpreter so the script can't rot between chip
sessions (on a real accelerator it runs compiled via `python
scripts/tpu_smoke.py`)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # second tier: excluded from the quick CI tier

SCRIPT = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "tpu_smoke.py"
)


def test_tpu_smoke_script_interpreted():
    # one pass: CI guards script rot; the >=20-pass stress discipline is
    # for the real chip (where passes are cheap after the first compile)
    env = dict(
        os.environ, TDT_SMOKE_INTERPRET="1", TDT_SMOKE_ITERS="1",
        JAX_PLATFORMS="cpu",
    )
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1"
    )
    proc = subprocess.run(
        [sys.executable, os.path.abspath(SCRIPT)],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    assert "ops OK" in proc.stdout, proc.stdout
