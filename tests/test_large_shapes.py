"""Scale-hardening: multi-block pipeline tiling and deeper rings than the
default tiny-shape suite exercises (VERDICT r1 weak #5).

The 1-core CI box deadlocks XLA:CPU's threadpool when concurrent interpreted
DMAs move >~8 KiB payloads (tests/conftest.py), so these tests pick shapes
that maximize BLOCK COUNT per kernel (multi-block emit_pipeline tiling,
8-step rings) while keeping each individual DMA under that ceiling. Set
``TDT_LARGE=1`` to add genuinely large payloads on a multi-core host.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # second tier: excluded from the quick CI tier
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather_op
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm_op
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs_op
from triton_dist_tpu.ops.reduce_scatter import reduce_scatter_op

LARGE = os.environ.get("TDT_LARGE") == "1"


def test_ag_gemm_multiblock_pipeline(mesh8):
    """Blocks far smaller than the problem: the inner emit_pipeline runs a
    4x4x4 grid per chunk and the ring runs 7 steps on 8 PEs."""
    world, m_loc, k_dim, n_tot = 8, 32, 64, 128
    ka, kb = jax.random.split(jax.random.PRNGKey(0))
    a = jax.device_put(
        jax.random.normal(ka, (world * m_loc, k_dim), jnp.float32),
        NamedSharding(mesh8, P("tp", None)),
    )
    b = jax.device_put(
        jax.random.normal(kb, (k_dim, n_tot), jnp.float32) / 8,
        NamedSharding(mesh8, P(None, "tp")),
    )
    got = ag_gemm_op(a, b, mesh8, config=AGGemmConfig(8, 32, 16))
    want = np.asarray(a, np.float32) @ np.asarray(
        jax.device_put(b, NamedSharding(mesh8, P(None, None))), np.float32
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_gemm_rs_multiblock_pipeline(mesh8):
    world, m_tot, k_tot, n_dim = 8, 64, 128, 64
    ka, kb = jax.random.split(jax.random.PRNGKey(1))
    a = jax.device_put(
        jax.random.normal(ka, (m_tot, k_tot), jnp.float32) / 4,
        NamedSharding(mesh8, P(None, "tp")),
    )
    b = jax.device_put(
        jax.random.normal(kb, (k_tot, n_dim), jnp.float32) / 4,
        NamedSharding(mesh8, P("tp", None)),
    )
    for method in ("scatter", "ring"):
        got = gemm_rs_op(a, b, mesh8, method=method, config=GemmRSConfig(4, 16, 8))
        a_full = np.asarray(jax.device_put(a, NamedSharding(mesh8, P(None, None))), np.float32)
        b_full = np.asarray(jax.device_put(b, NamedSharding(mesh8, P(None, None))), np.float32)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), a_full @ b_full, rtol=1e-3, atol=1e-3
        )


def test_allgather_8ring_many_rows(mesh8):
    """8-PE ring, 7 in-flight descriptors per PE, row count >> block."""
    world, m_loc, h = 8, 64, 16  # 4 KiB per chunk
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (world * m_loc, h), jnp.float32),
        NamedSharding(mesh8, P("tp", None)),
    )
    for method in ("ring_1d", "ring_bidir", "full_mesh_push"):
        got = all_gather_op(x, mesh8, method=method)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x))


def test_reduce_scatter_8ring(mesh8):
    world, m_tot, n_dim = 8, 64, 16
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (world, m_tot, n_dim), jnp.float32),
        NamedSharding(mesh8, P("tp", None, None)),
    )
    want = np.asarray(x).sum(0)
    for method in ("ring", "scatter_reduce"):
        got = reduce_scatter_op(x, mesh8, method=method)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


@pytest.mark.skipif(not LARGE, reason="TDT_LARGE=1 enables big-payload DMAs (needs multi-core host)")
def test_ag_gemm_large_payload(mesh8):
    world, m_loc, k_dim, n_tot = 8, 256, 512, 1024
    ka, kb = jax.random.split(jax.random.PRNGKey(4))
    a = jax.device_put(
        jax.random.normal(ka, (world * m_loc, k_dim), jnp.bfloat16),
        NamedSharding(mesh8, P("tp", None)),
    )
    b = jax.device_put(
        jax.random.normal(kb, (k_dim, n_tot), jnp.bfloat16) / 16,
        NamedSharding(mesh8, P(None, "tp")),
    )
    got = ag_gemm_op(a, b, mesh8, config=AGGemmConfig(128, 256, 256))
    want = np.asarray(a, np.float32) @ np.asarray(
        jax.device_put(b, NamedSharding(mesh8, P(None, None))), np.float32
    )
    np.testing.assert_allclose(np.asarray(got, np.float32), want, rtol=5e-2, atol=2.0)
