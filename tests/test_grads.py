"""Custom-VJP fused GEMMs vs autodiff of the XLA golden (training-side
support beyond the inference-only reference)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig
from triton_dist_tpu.ops.grads import ag_gemm_grad, gemm_rs_grad

import pytest

pytestmark = pytest.mark.slow  # second tier: excluded from the quick CI tier

AG_CFG = AGGemmConfig(8, 64, 32)
RS_CFG = GemmRSConfig(8, 64, 32)


def _grads(fn, mesh, specs, *args):
    def loss(*a):
        return jnp.sum(fn(*a) ** 2)

    g = jax.grad(loss, argnums=(0, 1))
    return jax.jit(
        jax.shard_map(g, mesh=mesh, in_specs=specs, out_specs=specs, check_vma=False)
    )(*args)


def test_ag_gemm_grad(mesh4):
    m_tot, k_dim, n_dim = 32, 64, 256
    a = jax.random.normal(jax.random.PRNGKey(0), (m_tot, k_dim), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (k_dim, n_dim), jnp.float32)
    specs = (P("tp", None), P(None, "tp"))
    da, db = _grads(
        lambda a, b: ag_gemm_grad(a, b, "tp", AG_CFG, RS_CFG),
        mesh4, specs, a, b,
    )

    def golden(a, b):
        return jnp.sum(jnp.dot(jax.lax.all_gather(a, "tp", tiled=True), b) ** 2)

    wa, wb = jax.jit(
        jax.shard_map(
            jax.grad(golden, argnums=(0, 1)), mesh=mesh4,
            in_specs=specs, out_specs=specs, check_vma=False,
        )
    )(a, b)
    np.testing.assert_allclose(np.asarray(da), np.asarray(wa), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(db), np.asarray(wb), rtol=1e-3, atol=1e-3)


def test_gemm_rs_grad(mesh4):
    m_tot, k_tot, n_dim = 32, 128, 256
    a = jax.random.normal(jax.random.PRNGKey(2), (m_tot, k_tot), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(3), (k_tot, n_dim), jnp.float32)
    specs = (P(None, "tp"), P("tp", None))
    da, db = _grads(
        lambda a, b: gemm_rs_grad(a, b, "tp", RS_CFG, AG_CFG),
        mesh4, specs, a, b,
    )

    def golden(a, b):
        c = jax.lax.psum_scatter(jnp.dot(a, b), "tp", scatter_dimension=0, tiled=True)
        return jnp.sum(c**2)

    wa, wb = jax.jit(
        jax.shard_map(
            jax.grad(golden, argnums=(0, 1)), mesh=mesh4,
            in_specs=specs, out_specs=specs, check_vma=False,
        )
    )(a, b)
    np.testing.assert_allclose(np.asarray(da), np.asarray(wa), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(db), np.asarray(wb), rtol=1e-3, atol=1e-3)


def test_tp_mlp_training_step(mesh4):
    """End-to-end: a TP MLP training step through the fused kernels."""
    m_tot, h_dim, f_dim = 32, 64, 128
    x = jax.random.normal(jax.random.PRNGKey(4), (m_tot, h_dim), jnp.float32)
    w_up = jax.random.normal(jax.random.PRNGKey(5), (h_dim, f_dim)) / 8
    w_down = jax.random.normal(jax.random.PRNGKey(6), (f_dim, h_dim)) / 8

    def fwd(x, w_up, w_down):
        h = ag_gemm_grad(x, w_up, "tp", AG_CFG, RS_CFG)
        h = jax.nn.gelu(h)
        return gemm_rs_grad(h, w_down, "tp", RS_CFG, AG_CFG)

    def loss(params, x):
        return jnp.mean(fwd(x, *params) ** 2)

    def golden_loss(params, x):
        w_up, w_down = params
        x_f = jax.lax.all_gather(x, "tp", tiled=True)
        h = jax.nn.gelu(jnp.dot(x_f, w_up))
        out = jax.lax.psum_scatter(
            jnp.dot(h, w_down), "tp", scatter_dimension=0, tiled=True
        )
        return jnp.mean(out**2)

    specs_p = (P(None, "tp"), P("tp", None))
    run = lambda l: jax.jit(
        jax.shard_map(
            jax.value_and_grad(l), mesh=mesh4,
            in_specs=(specs_p, P("tp", None)), out_specs=(P(), specs_p),
            check_vma=False,
        )
    )((w_up, w_down), x)
    (lv, (gu, gd)) = run(loss)
    (wl, (wu, wd)) = run(golden_loss)
    np.testing.assert_allclose(float(lv), float(wl), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gu), np.asarray(wu), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gd), np.asarray(wd), rtol=1e-3, atol=1e-3)


def test_ring_attention_grad_matches_full(mesh4):
    """SP ring attention VJP vs grads of full causal attention on the
    gathered sequence."""
    from triton_dist_tpu.ops.grads import ring_attention_grad
    from triton_dist_tpu.ops.ring_attention import RingAttentionConfig

    b, h, s, d = 1, 2, 64, 128
    kq, kk, kv, kt = jax.random.split(jax.random.PRNGKey(40), 4)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
    t = jax.random.normal(kt, (b, h, s, d), jnp.float32)  # cotangent seed

    cfg = RingAttentionConfig(block_q=16, block_kv=16)

    def loss_sp(q, k, v, t):
        out = ring_attention_grad(q, k, v, "tp", True, cfg, None)
        return jnp.sum(out * t)

    def grads_sp(q, k, v, t):
        # each output shard appears in exactly ONE PE's local loss, so the
        # per-PE losses partition the global objective: local cotangents
        # are already the global-loss cotangents (no psum needed — this
        # does NOT hold for losses where shards overlap, e.g. a mean over
        # a replicated dim)
        g = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v, t)
        return g

    gq, gk, gv = jax.jit(
        jax.shard_map(
            grads_sp, mesh=mesh4,
            in_specs=(P(None, None, "tp", None),) * 4,
            out_specs=(P(None, None, "tp", None),) * 3, check_vma=False,
        )
    )(q, k, v, t)

    def loss_full(q, k, v):
        scores = jnp.einsum("bhqd,bhsd->bhqs", q, k) / jnp.sqrt(jnp.float32(d))
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
        out = jnp.einsum("bhqs,bhsd->bhqd", jax.nn.softmax(scores, -1), v)
        return jnp.sum(out * t)

    rq, rk, rv = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), rtol=2e-3, atol=2e-3)



def _dense_moe_loss(ids):
    """Dense differentiable MoE golden, shared by the three grad tests."""

    def dense_loss(x, wu, wd, tw):
        he = jax.nn.gelu(jnp.einsum("th,tkhf->tkf", x, wu[ids]))
        y = jnp.einsum("tkf,tkfh->tkh", he, wd[ids])
        out = jnp.sum(tw[:, :, None] * y, axis=1)
        return jnp.sum(out ** 2)

    return dense_loss


def test_tp_moe_mlp_grad(mesh4):
    """Fused MoE TP MLP custom VJP vs the dense differentiable MoE: grads
    for tokens, both expert weight banks, and the routing weights."""
    from triton_dist_tpu.ops.grads import tp_moe_mlp_grad
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.ops.moe_utils import select_experts

    m_tot, h_dim, f_dim, n_exp, topk = 16, 64, 128, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(60), (m_tot, h_dim), jnp.float32)
    w_up = jax.random.normal(jax.random.PRNGKey(61), (n_exp, h_dim, f_dim)) / 8
    w_down = jax.random.normal(jax.random.PRNGKey(62), (n_exp, f_dim, h_dim)) / 8
    tw, ids = select_experts(
        jax.random.normal(jax.random.PRNGKey(63), (m_tot, n_exp)), topk
    )
    tw = tw.astype(jnp.float32)
    cfg = GroupGemmConfig(8, 64, 32)
    specs = (
        P("tp", None), P(None, None, "tp"), P(None, "tp", None),
        P("tp", None), P("tp", None),
    )

    def loss(x, wu, wd, ids, tw):
        return jnp.sum(tp_moe_mlp_grad(x, wu, wd, ids, tw, "tp", jax.nn.gelu, cfg) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2, 4))
    dx, dwu, dwd, dtw = jax.jit(
        jax.shard_map(
            g, mesh=mesh4, in_specs=specs,
            out_specs=(specs[0], specs[1], specs[2], specs[4]),
            check_vma=False,
        )
    )(x, w_up, w_down, ids, tw)
    jax.block_until_ready((dx, dwu, dwd, dtw))

    wx, wwu, wwd, wtw = jax.grad(_dense_moe_loss(ids), argnums=(0, 1, 2, 3))(
        x, w_up, w_down, tw
    )
    np.testing.assert_allclose(np.asarray(dx), np.asarray(wx), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwu), np.asarray(wwu), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwd), np.asarray(wwd), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dtw), np.asarray(wtw), rtol=2e-3, atol=2e-3)


def test_ep_moe_mlp_grad(mesh4):
    """Flat expert-parallel MoE MLP differentiates end-to-end by
    composition (a2a VJP = reverse exchange, grouped-GEMM VJP): grads match
    the dense differentiable MoE for tokens, expert weights, and routing
    weights."""
    from triton_dist_tpu.layers import EPMoEMLP
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.ops.moe_utils import select_experts

    world, m_loc, h_dim, f_dim, n_exp, topk = 4, 4, 64, 128, 4, 2
    m_tot = world * m_loc
    x = jax.random.normal(jax.random.PRNGKey(70), (m_tot, h_dim), jnp.float32)
    w_up = jax.random.normal(jax.random.PRNGKey(71), (n_exp, h_dim, f_dim)) / 8
    w_down = jax.random.normal(jax.random.PRNGKey(72), (n_exp, f_dim, h_dim)) / 8
    tw, ids = select_experts(
        jax.random.normal(jax.random.PRNGKey(73), (m_tot, n_exp)), topk
    )
    tw = tw.astype(jnp.float32)
    layer = EPMoEMLP(
        n_experts=n_exp, topk=topk, max_m=m_loc * topk, axis="tp",
        gg_config=GroupGemmConfig(8, 64, 32),
    )
    specs = (
        P("tp", None), P("tp", None, None), P("tp", None, None),
        P("tp", None), P("tp", None),
    )

    def loss(x, wu, wd, ids, tw):
        return jnp.sum(layer(x, wu, wd, ids, tw) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2, 4))
    dx, dwu, dwd, dtw = jax.jit(
        jax.shard_map(
            g, mesh=mesh4, in_specs=specs,
            out_specs=(specs[0], specs[1], specs[2], specs[4]),
            check_vma=False,
        )
    )(x, w_up, w_down, ids, tw)
    # drain the interpreted program before the eager golden (1-core
    # thread-pool starvation otherwise; see conftest note)
    jax.block_until_ready((dx, dwu, dwd, dtw))

    wx, wwu, wwd, wtw = jax.grad(_dense_moe_loss(ids), argnums=(0, 1, 2, 3))(
        x, w_up, w_down, tw
    )
    np.testing.assert_allclose(np.asarray(dx), np.asarray(wx), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwu), np.asarray(wwu), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwd), np.asarray(wwd), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dtw), np.asarray(wtw), rtol=2e-3, atol=2e-3)


def test_hier_ep_moe_mlp_grad(mesh2x4):
    """Hierarchical two-phase EP MoE differentiates too — routing weights
    ride the data slab (a differentiable channel), so the router gradient
    survives both a2a hops."""
    from triton_dist_tpu.layers import EPMoEMLP
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.ops.moe_utils import select_experts

    n_o, n_i, m_loc, h_dim, f_dim, topk = 2, 4, 4, 32, 64, 2
    world = n_o * n_i
    n_exp = world
    m_tot = world * m_loc
    x = jax.random.normal(jax.random.PRNGKey(80), (m_tot, h_dim), jnp.float32)
    w_up = jax.random.normal(jax.random.PRNGKey(81), (n_exp, h_dim, f_dim)) / 8
    w_down = jax.random.normal(jax.random.PRNGKey(82), (n_exp, f_dim, h_dim)) / 8
    tw, ids = select_experts(
        jax.random.normal(jax.random.PRNGKey(83), (m_tot, n_exp)), topk
    )
    tw = tw.astype(jnp.float32)
    layer = EPMoEMLP(
        n_experts=n_exp, topk=topk, max_m=m_loc * topk,
        outer="dp", inner="tp", gg_config=GroupGemmConfig(8, 32, 32),
    )
    specs = (
        P(("dp", "tp"), None), P(("dp", "tp"), None, None),
        P(("dp", "tp"), None, None), P(("dp", "tp"), None),
        P(("dp", "tp"), None),
    )

    def loss(x, wu, wd, ids, tw):
        return jnp.sum(layer(x, wu, wd, ids, tw) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2, 4))
    dx, dwu, dwd, dtw = jax.jit(
        jax.shard_map(
            g, mesh=mesh2x4, in_specs=specs,
            out_specs=(specs[0], specs[1], specs[2], specs[4]),
            check_vma=False,
        )
    )(x, w_up, w_down, ids, tw)
    # drain the interpreted program before the eager golden (1-core
    # thread-pool starvation otherwise; see conftest note)
    jax.block_until_ready((dx, dwu, dwd, dtw))

    wx, wwu, wwd, wtw = jax.grad(_dense_moe_loss(ids), argnums=(0, 1, 2, 3))(
        x, w_up, w_down, tw
    )
    np.testing.assert_allclose(np.asarray(dx), np.asarray(wx), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwu), np.asarray(wwu), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dwd), np.asarray(wwd), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dtw), np.asarray(wtw), rtol=2e-3, atol=2e-3)
