"""Ring attention (SP prefill) vs full-attention golden."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.ops.ring_attention import (
    RingAttentionConfig,
    ring_attention_op,
)


def _ref_attn(q, k, v, causal):
    b, h, s, d = q.shape
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.float32(d))
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _case(key, b, h, s, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, h, s, d)).astype(dtype)
    k = jax.random.normal(k2, (b, h, s, d)).astype(dtype)
    v = jax.random.normal(k3, (b, h, s, d)).astype(dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention(mesh4, causal):
    b, h, s, d = 1, 2, 128, 128
    q, k, v = _case(jax.random.PRNGKey(0), b, h, s, d)
    got = ring_attention_op(
        q, k, v, mesh4, causal=causal, config=RingAttentionConfig(16, 16)
    )
    want = _ref_attn(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_world1():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    b, h, s, d = 1, 1, 64, 128
    q, k, v = _case(jax.random.PRNGKey(1), b, h, s, d)
    got = ring_attention_op(q, k, v, mesh, config=RingAttentionConfig(16, 16))
    want = _ref_attn(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
