"""Ring attention (SP prefill) vs full-attention golden."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.ops.ring_attention import (
    RingAttentionConfig,
    ring_attention_op,
)


def _ref_attn(q, k, v, causal):
    b, h, s, d = q.shape
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.float32(d))
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _case(key, b, h, s, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, h, s, d)).astype(dtype)
    k = jax.random.normal(k2, (b, h, s, d)).astype(dtype)
    v = jax.random.normal(k3, (b, h, s, d)).astype(dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention(mesh4, causal):
    b, h, s, d = 1, 2, 128, 128
    q, k, v = _case(jax.random.PRNGKey(0), b, h, s, d)
    got = ring_attention_op(
        q, k, v, mesh4, causal=causal, config=RingAttentionConfig(16, 16)
    )
    want = _ref_attn(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_world1():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    b, h, s, d = 1, 1, 64, 128
    q, k, v = _case(jax.random.PRNGKey(1), b, h, s, d)
    got = ring_attention_op(q, k, v, mesh, config=RingAttentionConfig(16, 16))
    want = _ref_attn(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_attention_zigzag(mesh4):
    """Zigzag (causal-load-balanced) layout: permute the sequence into
    stripe pairs, run the ring, unpermute — identical answer to the dense
    causal golden in natural order."""
    from triton_dist_tpu.ops.ring_attention import zigzag_permutation

    b, h, s, d = 1, 2, 128, 128
    n = 4
    q, k, v = _case(jax.random.PRNGKey(4), b, h, s, d)
    perm, inv = zigzag_permutation(n, s)
    got_z = ring_attention_op(
        q[:, :, perm], k[:, :, perm], v[:, :, perm], mesh4,
        causal=True, config=RingAttentionConfig(16, 16), layout="zigzag",
    )
    got = np.asarray(got_z)[:, :, inv]
    want = _ref_attn(q, k, v, True)
    np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_attention_zigzag_grad(mesh4):
    """Zigzag backward: grads match the dense causal golden's."""
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.grads import ring_attention_grad
    from triton_dist_tpu.ops.ring_attention import zigzag_permutation

    b, h, s, d = 1, 1, 64, 128
    n = 4
    q, k, v = _case(jax.random.PRNGKey(5), b, h, s, d)
    perm, inv = zigzag_permutation(n, s)
    spec = P(None, None, "tp", None)

    def loss_sp(q, k, v):
        def f(ql, kl, vl):
            out = ring_attention_grad(
                ql, kl, vl, "tp", True, RingAttentionConfig(8, 8), None,
                "zigzag",
            )
            return jax.lax.psum((out.astype(jnp.float32) ** 2).sum(), "tp")[None]

        return jax.jit(
            jax.shard_map(
                f, mesh=mesh4, in_specs=(spec,) * 3, out_specs=P("tp"),
                check_vma=False,
            )
        )(q, k, v)[0]

    gq, gk, gv = jax.grad(
        lambda q, k, v: loss_sp(q, k, v), argnums=(0, 1, 2)
    )(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    jax.block_until_ready((gq, gk, gv))

    def dense_loss(q, k, v):
        return (_ref_attn(q, k, v, True) ** 2).sum()

    wq, wk, wv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(gq)[:, :, inv], np.asarray(wq), rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(gk)[:, :, inv], np.asarray(wk), rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(gv)[:, :, inv], np.asarray(wv), rtol=2e-3, atol=2e-3
    )
