"""Fast all-to-all vs golden (≙ reference test_low_latency_all_to_all.py:
golden = torch.distributed all_to_all_single; here lax.all_to_all over the
slab dim / a numpy permutation oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu.ops.all_to_all import (
    all_to_all_post_process,
    fast_all_to_all,
    fast_all_to_all_op,
)


def _case(key, n, max_m, hidden, dtype=jnp.float32):
    kd, ks = jax.random.split(key)
    tokens = jax.random.normal(kd, (n, n, max_m, hidden)).astype(dtype)
    splits = jax.random.randint(ks, (n, n), 0, max_m + 1, jnp.int32)
    return tokens, splits


@pytest.mark.parametrize("world", [4, 8])
def test_fast_all_to_all(world):
    mesh = Mesh(np.array(jax.devices()[:world]), ("tp",))
    n, max_m, hidden = world, 8, 128
    tokens, splits = _case(jax.random.PRNGKey(0), n, max_m, hidden)
    recv, rsplits = fast_all_to_all_op(tokens, splits, mesh)
    # golden: recv[r, j] == tokens[j, r] (PE j's slab for r), transposed splits
    want = np.asarray(tokens).transpose(1, 0, 2, 3)
    np.testing.assert_array_equal(np.asarray(recv), want)
    np.testing.assert_array_equal(np.asarray(rsplits), np.asarray(splits).T)


def test_fast_all_to_all_world1():
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    tokens, splits = _case(jax.random.PRNGKey(1), 1, 4, 128)
    recv, rsplits = fast_all_to_all_op(tokens, splits, mesh)
    np.testing.assert_array_equal(np.asarray(recv), np.asarray(tokens))


def test_post_process_compacts():
    n, max_m, hidden = 4, 4, 8
    key = jax.random.PRNGKey(2)
    recv = jax.random.normal(key, (n, max_m, hidden), jnp.float32)
    recv_splits = jnp.array([2, 0, 4, 1], jnp.int32)
    packed, total = jax.jit(all_to_all_post_process)(recv, recv_splits)
    assert int(total) == 7
    want = np.concatenate(
        [np.asarray(recv)[j, : int(recv_splits[j])] for j in range(n)]
    )
    np.testing.assert_array_equal(np.asarray(packed)[:7], want)
    np.testing.assert_array_equal(np.asarray(packed)[7:], 0)


def test_dispatch_combine_roundtrip(mesh4):
    """EP dispatch then combine (a2a is self-inverse with transposed splits):
    every PE must get its own tokens back."""
    n, max_m, hidden = 4, 8, 128
    tokens, splits = _case(jax.random.PRNGKey(3), n, max_m, hidden)
    # zero out padding rows so the roundtrip comparison is exact
    mask = (
        np.arange(max_m)[None, None, :] < np.asarray(splits)[:, :, None]
    )[..., None]
    tokens = jnp.asarray(np.asarray(tokens) * mask)
    recv, rsplits = fast_all_to_all_op(tokens, splits, mesh4)
    back, bsplits = fast_all_to_all_op(recv, rsplits, mesh4)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(tokens))
    np.testing.assert_array_equal(np.asarray(bsplits), np.asarray(splits))


@pytest.mark.parametrize(
    "dtype", [jnp.bfloat16, jnp.float8_e4m3fn, jnp.int8],
    ids=["bf16", "fp8e4m3", "int8"],
)
def test_fast_all_to_all_dtypes(dtype):
    """The slab exchange is a byte mover — quantized payloads (the
    reference's headline a2a is fp8, README.md:87) ride it unchanged."""
    world = 4
    mesh = Mesh(np.array(jax.devices()[:world]), ("tp",))
    n, max_m, hidden = world, 8, 128
    if jnp.issubdtype(dtype, jnp.integer):
        tokens = jax.random.randint(
            jax.random.PRNGKey(5), (n, n, max_m, hidden), -100, 100, jnp.int32
        ).astype(dtype)
    else:
        tokens = jax.random.normal(
            jax.random.PRNGKey(5), (n, n, max_m, hidden)
        ).astype(dtype)
    splits = jnp.full((n, n), max_m, jnp.int32)
    recv, rsplits = fast_all_to_all_op(tokens, splits, mesh)
    assert recv.dtype == dtype
    want = np.asarray(tokens).transpose(1, 0, 2, 3)
    np.testing.assert_array_equal(np.asarray(recv), want)


@pytest.mark.parametrize("chunks", [2, 4])
def test_fast_all_to_all_chunked_puts(chunks):
    """A2AConfig.puts_per_slab splits each slab into row-chunk puts — the
    autotuner's scheduling knob; any granularity must exchange identically."""
    from triton_dist_tpu.ops.all_to_all import A2AConfig

    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    tokens, splits = _case(jax.random.PRNGKey(9), 4, 8, 128)
    recv, rsplits = fast_all_to_all_op(
        tokens, splits, mesh, config=A2AConfig(puts_per_slab=chunks)
    )
    want = np.asarray(tokens).transpose(1, 0, 2, 3)
    np.testing.assert_array_equal(np.asarray(recv), want)
    np.testing.assert_array_equal(np.asarray(rsplits), np.asarray(splits).T)
