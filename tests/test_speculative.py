"""Speculative decoding (models/speculative.py): greedy-exactness against
plain generate, verify-step equivalence to successive decode steps, and
the multi-position kernel's SP form."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import TransformerConfig, init_params
from triton_dist_tpu.models.decode import KVCacheSpec, decode_step, generate, specs_for
from triton_dist_tpu.models.speculative import speculative_generate, verify_step
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.flash_decode import FlashDecodeConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig


def _cfg(**kw):
    base = dict(
        vocab=32, hidden=32, ffn=64, n_layers=2, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=2, seq=8,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_verify_step_matches_successive_decodes(mesh4):
    """One verify forward over an S-chunk == S decode steps: same cache
    writes, near-identical logits (the multi-row kernel re-partitions the
    same f32 accumulations)."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    spec = KVCacheSpec(16)
    n = mesh4.shape[cfg.axis]
    pspecs, cspecs = specs_for(cfg), spec.specs(cfg)
    cache0 = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)),
        spec.init(cfg, n), cspecs,
    )
    params_sh = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh4, s)), params, pspecs
    )
    S = 3
    chunk = jax.random.randint(jax.random.PRNGKey(1), (cfg.batch, S), 0, cfg.vocab)
    fd = FlashDecodeConfig(block_s=4)

    ver = jax.jit(
        jax.shard_map(
            lambda p, c, t: verify_step(
                cfg, p, c, t, 0, spec=spec, fd_config=fd
            ),
            mesh=mesh4, in_specs=(pspecs, cspecs, P(None, None)),
            out_specs=(P(None, None, None), cspecs), check_vma=False,
        )
    )
    v_logits, v_cache = ver(params_sh, cache0, chunk)
    jax.block_until_ready(v_logits)

    step = jax.jit(
        jax.shard_map(
            lambda p, c, t, i: decode_step(
                cfg, p, c, t, i, spec=spec, fd_config=fd
            ),
            mesh=mesh4, in_specs=(pspecs, cspecs, P(None), P()),
            out_specs=(P(None, None), cspecs), check_vma=False,
        )
    )
    cache = cache0
    for i in range(S):
        lg, cache = step(params_sh, cache, chunk[:, i], jnp.int32(i))
        jax.block_until_ready(lg)
        np.testing.assert_allclose(
            np.asarray(v_logits[:, i]), np.asarray(lg), rtol=2e-3, atol=2e-3
        )
    # identical cache contents (both wrote positions 0..S-1)
    for k in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(v_cache[k], np.float32),
            np.asarray(cache[k], np.float32), rtol=1e-3, atol=1e-3,
        )


@pytest.mark.slow  # whole-loop interpret-mode integration (~4 min/case
# since the r5 device-side while_loop rewrite); the quick tier keeps the
# verify-kernel equivalence test above
@pytest.mark.parametrize("moe", [False, True])
def test_speculative_matches_greedy_generate(mesh4, moe):
    """The whole speculative loop emits EXACTLY the target model's greedy
    tokens — with a weaker draft (fewer layers), so rounds mix accepts
    and rejects."""
    if moe:
        from triton_dist_tpu.models import (
            MoETransformerConfig, init_moe_params,
        )
        from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

        kw = dict(
            vocab=32, hidden=32, ffn=64, n_q_heads=8, n_kv_heads=4,
            head_dim=8, batch=2, seq=8, n_experts=4, topk=2,
            ag_config=AGGemmConfig(8, 16, 16),
            rs_config=GemmRSConfig(8, 16, 16),
            gg_config=GroupGemmConfig(8, 16, 16),
        )
        cfg = MoETransformerConfig(n_layers=2, **kw)
        params = init_moe_params(jax.random.PRNGKey(2), cfg)
        draft_cfg = MoETransformerConfig(n_layers=1, **kw)
        draft_params = init_moe_params(jax.random.PRNGKey(3), draft_cfg)
    else:
        cfg = _cfg(n_layers=2)
        params = init_params(jax.random.PRNGKey(2), cfg)
        draft_cfg = _cfg(n_layers=1)
        draft_params = init_params(jax.random.PRNGKey(3), draft_cfg)

    # prompt_len 4: b*L divides the 4-PE mesh (the prefill warm-up shard)
    b, prompt_len, n_steps, s_max = cfg.batch, 4, 6, 16
    prompt = jax.random.randint(
        jax.random.PRNGKey(4), (b, prompt_len), 0, cfg.vocab, jnp.int32
    )
    fd = FlashDecodeConfig(block_s=4)
    want = generate(
        cfg, params, prompt, n_steps, mesh4, s_max=s_max, fd_config=fd
    )
    got = speculative_generate(
        cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh4,
        s_max=s_max, draft_k=3, fd_config=fd, draft_fd_config=fd,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # MXU-rate prefill warm-up: same tokens on both model families
    got_pf = speculative_generate(
        cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh4,
        s_max=s_max, draft_k=3, fd_config=fd, draft_fd_config=fd,
        prefill=True,
    )
    np.testing.assert_array_equal(np.asarray(got_pf), np.asarray(want))

    if not moe:  # paged pools + static tables: the serving cache layout
        got_paged = speculative_generate(
            cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh4,
            s_max=s_max, draft_k=3, page_size=2,
        )
        np.testing.assert_array_equal(np.asarray(got_paged), np.asarray(want))

    # self-speculation (draft == target): every draft accepted, same tokens
    got_self = speculative_generate(
        cfg, params, cfg, params, prompt, n_steps, mesh4,
        s_max=s_max, draft_k=3, fd_config=fd, draft_fd_config=fd,
    )
    np.testing.assert_array_equal(np.asarray(got_self), np.asarray(want))


@pytest.mark.slow  # see test_speculative_matches_greedy_generate
def test_speculative_hier_ep_target(mesh2x4, mesh4):
    """The two round-5 serving features compose: a dense draft speculates
    for a HIERARCHICAL EP-MoE target on the 2-axis mesh — emitted tokens
    equal the flat-EP greedy decode of the same weights."""
    from triton_dist_tpu.models import EPMoETransformerConfig, init_moe_params
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    b, prompt_len, n_steps, s_max = 8, 3, 5, 16
    kw = dict(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=8, n_kv_heads=4,
        head_dim=8, batch=b, seq=8, n_experts=8, topk=2,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(4, 32, 32),
    )
    flat_cfg = EPMoETransformerConfig(**kw)
    hier_cfg = EPMoETransformerConfig(**kw, ep_outer="dp")
    params = init_moe_params(jax.random.PRNGKey(7), flat_cfg)
    draft_cfg = _cfg(n_layers=1, batch=b)
    draft_params = init_params(jax.random.PRNGKey(8), draft_cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(9), (b, prompt_len), 0, flat_cfg.vocab, jnp.int32
    )
    fd = FlashDecodeConfig(block_s=4)
    want = generate(
        flat_cfg, params, prompt, n_steps, mesh4, s_max=s_max, fd_config=fd
    )
    got = speculative_generate(
        hier_cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh2x4,
        s_max=s_max, draft_k=3, fd_config=fd, draft_fd_config=fd,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # prefill warm-up on the 2-axis deployment: the hier target's prompt
    # shards over (outer, inner) — 8 PEs — while the flat draft's shards
    # over the inner 4 alone; same tokens either way
    got_pf = speculative_generate(
        hier_cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh2x4,
        s_max=s_max, draft_k=3, fd_config=fd, draft_fd_config=fd,
        prefill=True,
    )
    np.testing.assert_array_equal(np.asarray(got_pf), np.asarray(want))

    # paged pools on the 2-axis deployment: per-group batch slices over
    # composite (outer, inner) pool sharding, block tables per PE
    got_paged = speculative_generate(
        hier_cfg, params, draft_cfg, draft_params, prompt, n_steps, mesh2x4,
        s_max=s_max, draft_k=3, page_size=2,
    )
    np.testing.assert_array_equal(np.asarray(got_paged), np.asarray(want))


def test_accept_lengths_per_slot_vs_lockstep():
    """The shared acceptance core (ISSUE 20): ``accept_lengths`` returns
    PER-SLOT counts — the serving batcher consumes the rows directly,
    the lockstep loop here advances by the batch ``min`` of the same
    rows — and the np/jnp namespaces agree element for element, so the
    per-slot/lockstep equivalence is structural, not coincidental."""
    from triton_dist_tpu.models.speculative import accept_lengths

    k = 3
    drafts = np.array([
        [5, 6, 7],    # full agreement: capped at k-1 = 2
        [5, 9, 7],    # diverges at j=1 (the later re-match must NOT count)
        [1, 2, 3],    # diverges immediately
    ], np.int32)
    preds = np.array([
        [5, 6, 7, 8],
        [5, 6, 7, 8],
        [9, 9, 9, 9],
    ], np.int32)
    per_slot = accept_lengths(drafts, preds, k)
    assert per_slot.tolist() == [2, 1, 0]
    got_j = accept_lengths(
        jnp.asarray(drafts), jnp.asarray(preds), k, xp=jnp
    )
    assert np.asarray(got_j).tolist() == [2, 1, 0]
    # the lockstep round advance is the min over the same per-slot rows:
    # one cold slot stalls every neighbor — exactly what the serving
    # batcher's per-slot consume avoids (tests/test_spec_serving.py)
    assert int(per_slot.min()) == 0
