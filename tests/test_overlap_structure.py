"""Overlap-STRUCTURE assertions for the fused ring kernels (VERDICT r4
weak #4 / next #5): world-1 hardware cannot measure overlap efficiency,
so until a multi-chip window exists, pin the property the fused kernels
exist for — each ring step ISSUES its DMA before the MXU pipeline that
hides it and defers the arrival wait until that compute is done — as a
test that fails if a refactor serializes the kernel (DMA → wait → compute
would still be numerically correct and would still pass every golden).

Method: the comm primitives (`shmem.putmem_nbi_block`) and the compute
pipeline factory (`gemm_add_pipeline`) are spied at the module boundary
and the kernel body is re-traced; the recorded order is the kernel's
PROGRAM order — exactly the issue order Mosaic compiles (the comm loops
unroll in Python; there is no reordering across the async-copy
start/wait pair). The assertion is therefore about the program structure
the hardware overlaps, the honest CPU-side proxy for the reference's
measured overlap discipline (test_ag_gemm.py --case perf).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import pytest


class _SpyHandle:
    """Wraps a PutHandle; logs when its arrival is awaited."""

    def __init__(self, handle, events, tag):
        self._h, self._ev, self._tag = handle, events, tag

    def wait_recv(self):
        self._ev.append(("wait_recv", self._tag))
        return self._h.wait_recv()

    def wait_send(self):
        return self._h.wait_send()

    def wait(self):
        self._ev.append(("wait_recv", self._tag))
        return self._h.wait()

    @property
    def send_waited(self):
        return self._h.send_waited

    @property
    def desc(self):
        return self._h.desc


def _spy_comm(monkeypatch, op_module, events):
    """Instrument put-issue / compute / arrival-wait order in `op_module`
    (which imports `shmem` and `gemm_add_pipeline` at module level)."""
    orig_put = op_module.shmem.putmem_nbi_block

    def spy_put(*a, **k):
        h = orig_put(*a, **k)
        tag = sum(1 for e in events if e[0] == "put_issue")
        events.append(("put_issue", tag))
        return _SpyHandle(h, events, tag)

    # quiet() drains handles at kernel end; unwrap the spies
    orig_quiet = op_module.shmem.quiet

    def spy_quiet(*handles):
        return orig_quiet(*[getattr(h, "_h", h) for h in handles])

    monkeypatch.setattr(op_module.shmem, "putmem_nbi_block", spy_put)
    monkeypatch.setattr(op_module.shmem, "quiet", spy_quiet)

    orig_pipe = op_module.gemm_add_pipeline

    def spy_pipe(*a, **k):
        p = orig_pipe(*a, **k)

        def run(*pa, **pk):
            events.append(("compute", None))
            return p(*pa, **pk)

        return run

    monkeypatch.setattr(op_module, "gemm_add_pipeline", spy_pipe)


def _assert_overlapped(events, n_puts_min, drain_allowance=0):
    """Every issued put must have ≥1 compute between its issue and its
    arrival wait — the DMA rides the ICI while the MXU works.
    ``drain_allowance`` exempts that many trailing transfers: a kernel
    that hides all comm under compute still ends with one arrival that
    has no local work left to run under (the pipeline drain — it
    overlaps the PEER's compute, which a single-program trace can't
    show)."""
    puts = [i for i, e in enumerate(events) if e[0] == "put_issue"]
    assert len(puts) >= n_puts_min, events
    computes = [i for i, e in enumerate(events) if e[0] == "compute"]
    assert computes, events
    unhidden = []
    for i, e in enumerate(events):
        if e[0] != "put_issue":
            continue
        tag = e[1]
        waits = [
            j for j, w in enumerate(events)
            if w == ("wait_recv", tag) and j > i
        ]
        if not waits:
            continue  # own-shard put with no local arrival wait
        j = waits[0]
        if not any(i < c < j for c in computes):
            unhidden.append((tag, i, j))
    assert len(unhidden) <= drain_allowance, (
        f"{len(unhidden)} put(s) awaited with NO compute between issue "
        f"and wait (> drain allowance {drain_allowance}) — the kernel "
        f"serialized ring steps: {unhidden} in {events}"
    )


def test_ag_gemm_overlap_structure(mesh8, monkeypatch):
    from triton_dist_tpu.ops import allgather_gemm as ag

    events: list = []
    _spy_comm(monkeypatch, ag, events)
    n = 8
    # unique shape → jit_shard_map's keyed cache cannot return a stale
    # compiled program (the spies only see a fresh trace)
    m_loc, kd, nd = 16, 32, 8 * 7
    a = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (n * m_loc, kd), jnp.float32),
        NamedSharding(mesh8, P("tp", None)),
    )
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (kd, nd), jnp.float32),
        NamedSharding(mesh8, P(None, "tp")),
    )
    out = ag.ag_gemm_op(a, b, mesh8, config=ag.AGGemmConfig(8, 8, 16))
    jax.block_until_ready(out)
    # n-1 ring forwards, each hidden under that step's MXU pipeline
    _assert_overlapped(events, n_puts_min=n - 1)
    # correctness unchanged under the spies
    ref = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref, atol=1e-3, rtol=1e-3)


def test_gemm_rs_overlap_structure(mesh8, monkeypatch):
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs

    events: list = []
    _spy_comm(monkeypatch, grs, events)
    n = 8
    m_loc, kd, nd = 16, 8 * 8, 24
    a = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (n * m_loc, kd), jnp.float32) / 8,
        NamedSharding(mesh8, P(None, "tp")),
    )
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (kd, nd), jnp.float32) / 8,
        NamedSharding(mesh8, P("tp", None)),
    )
    out = grs.gemm_rs_op(a, b, mesh8, config=grs.GemmRSConfig(8, 8, 16))
    jax.block_until_ready(out)
    # the scatter kernel batches its arrival waits at the drain: the last
    # transfer overlaps the peers' reduce, not local compute
    _assert_overlapped(events, n_puts_min=n - 1, drain_allowance=1)
    gold = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    got = np.asarray(out, np.float32)
    np.testing.assert_allclose(got, gold[: len(got)], atol=1e-2, rtol=1e-2)


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-x", "-q"]))
