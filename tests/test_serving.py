"""Serving engine (triton_dist_tpu/serving/, docs/serving.md "Serving
engine"; ISSUE 6): SLO metrics, replayable traffic, lifecycle/backpressure
/admission semantics, deterministic virtual-clock latency, and the elastic
serving arc — a step timeout mid-serving quarantines the straggler, the
engine rebuilds on the serviceable survivor mesh with every in-flight
request prefix-replayed, probation re-admission regrows the world, and
every submitted request finishes exactly once with tokens byte-identical
to an uninterrupted run.

Tier structure mirrors tests/test_elastic.py:

- **host tier** (no device work): histograms, SLO math, traffic replay,
  serviceable-mesh selection, prefill-bucket bound, bench emission shape;
- **engine tier**: real ``ContinuousBatcher`` steps on a world-1 mesh
  (tiny 1-block model; the keyed ``jit_shard_map`` cache shares the step
  program across tests);
- **chaos tier** (``pytest.mark.chaos``, runs in ``chaos_matrix.sh``):
  the elastic serving arcs on a 4-PE mesh with fabricated
  ``DistTimeoutError``s driving the production engine paths — only the
  in-kernel wait is simulated, exactly like the host-level arc of
  tests/test_elastic.py.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu import resilience
from triton_dist_tpu.models import init_params
from triton_dist_tpu.models.decode import ContinuousBatcher, Request
from triton_dist_tpu.models.tp_transformer import TransformerConfig
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig
from triton_dist_tpu.resilience import elastic, health, retry
from triton_dist_tpu.resilience.records import DistTimeoutError
from triton_dist_tpu.serving import (
    Rejected,
    ServingConfig,
    ServingEngine,
    ServingMetrics,
    SLOTargets,
    StreamingHistogram,
    TrafficSpec,
    generate_trace,
    preset_mix,
    trace_fingerprint,
)
from triton_dist_tpu.serving import bench as sbench
from triton_dist_tpu.serving import traffic as traffic_mod


@pytest.fixture(autouse=True)
def _restore_config():
    cfg = tdt_config.get_config()
    snap = (cfg.timeout_iters, cfg.fault_plan, cfg.raise_on_timeout,
            cfg.fallback_to_xla, cfg.retry_policy, cfg.elastic,
            cfg.suspect_threshold, cfg.probation_probes)
    yield
    tdt_config.update(
        timeout_iters=snap[0], fault_plan=snap[1], raise_on_timeout=snap[2],
        fallback_to_xla=snap[3], retry_policy=snap[4], elastic=snap[5],
        suspect_threshold=snap[6], probation_probes=snap[7],
    )
    retry.set_clock(None)


@pytest.fixture(scope="session")
def mesh1() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


def _cfg(**over):
    base = dict(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=4, n_kv_heads=2,
        head_dim=8, batch=2, seq=8,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
    )
    base.update(over)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def tiny1():
    cfg = _cfg()
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def tiny4():
    # n_kv_heads=4 so the 3-survivor world is model-INVALID and the
    # serviceable mesh must degrade further to 2 — the interesting case
    cfg = _cfg(n_kv_heads=4)
    return cfg, init_params(jax.random.PRNGKey(1), cfg)


def _recs(pes):
    return [{"pe": pe, "kind": "barrier_all", "site": 0, "status": "timeout",
             "expected": 1, "observed": 0, "budget": 10} for pe in pes]


# ---------------------------------------------------------------------------
# Host tier: metrics
# ---------------------------------------------------------------------------

def test_histogram_record_percentile_merge():
    h = StreamingHistogram(lo=1.0, hi=1e4, bins_per_decade=8)
    for v in (2.0, 3.0, 50.0, 60.0, 700.0):
        h.record(v)
    assert h.total == 5 and h.max == 700.0
    # percentiles are bin upper edges: monotone, bracketing the samples
    assert 2.0 <= h.percentile(0.2) <= 4.0
    assert h.percentile(0.5) <= h.percentile(0.95) <= h.percentile(1.0)
    assert 700.0 <= h.percentile(1.0) <= 1000.0
    # merge == recording the union
    h2 = StreamingHistogram(lo=1.0, hi=1e4, bins_per_decade=8)
    for v in (5.0, 5000.0):
        h2.record(v)
    h.merge(h2)
    assert h.total == 7
    both = StreamingHistogram(lo=1.0, hi=1e4, bins_per_decade=8)
    for v in (2.0, 3.0, 50.0, 60.0, 700.0, 5.0, 5000.0):
        both.record(v)
    assert h.counts == both.counts and h.snapshot() == both.snapshot()


def test_histogram_bounds_and_geometry():
    h = StreamingHistogram(lo=1.0, hi=100.0, bins_per_decade=4)
    h.record(0.01)     # underflow
    h.record(1e9)      # overflow
    assert h.counts[0] == 1 and h.counts[-1] == 1
    assert h.percentile(0.5) == 1.0      # underflow reports lo
    assert h.percentile(1.0) == 100.0    # overflow reports hi
    # fraction_le: the SLO estimate counts whole bins only
    h2 = StreamingHistogram(lo=1.0, hi=100.0, bins_per_decade=4)
    for v in (2.0, 2.0, 50.0):
        h2.record(v)
    assert h2.fraction_le(10.0) == pytest.approx(2 / 3)
    assert h2.fraction_le(1000.0) == 1.0
    with pytest.raises(ValueError, match="geometry"):
        h.merge(StreamingHistogram(lo=1.0, hi=100.0, bins_per_decade=8))
    assert StreamingHistogram().percentile(0.5) == 0.0  # empty
    assert StreamingHistogram().fraction_le(1.0) == 1.0


def test_slo_attainment_fractions():
    m = ServingMetrics(slo=SLOTargets(ttft_ms=100.0, e2e_ms=1000.0))
    m.observe_finished(ttft_ms=50.0, e2e_ms=500.0, tpot_ms=10.0, n_tokens=4)
    m.observe_finished(ttft_ms=150.0, e2e_ms=500.0, tpot_ms=10.0, n_tokens=4)
    m.observe_finished(ttft_ms=50.0, e2e_ms=2000.0, tpot_ms=None, n_tokens=1)
    slo = m.snapshot()["slo"]
    assert slo["scored"] == 3
    assert slo["attained"] == pytest.approx(1 / 3)
    assert slo["attained_ttft_ms"] == pytest.approx(2 / 3)
    assert slo["attained_e2e_ms"] == pytest.approx(2 / 3)
    # no targets -> no SLO section, but the histograms still fill
    m2 = ServingMetrics()
    m2.observe_finished(ttft_ms=1.0, e2e_ms=2.0, tpot_ms=None, n_tokens=1)
    snap = m2.snapshot()
    assert snap["slo"] is None and snap["latency_ms"]["e2e"]["count"] == 1
    json.dumps(snap)  # snapshot must stay JSON-able


# ---------------------------------------------------------------------------
# Host tier: traffic
# ---------------------------------------------------------------------------

def test_traffic_trace_byte_identical_replay():
    spec = TrafficSpec(rate_rps=7.0, n_requests=20, process="poisson",
                       prompt_len=("mix", ((0.7, 2, 4), (0.3, 5, 9))),
                       output_len=("uniform", 1, 6), vocab=64,
                       temperature=0.5, seed=11)
    t1, t2 = generate_trace(spec), generate_trace(spec)
    assert trace_fingerprint(t1) == trace_fingerprint(t2)
    assert [a.t_s for a in t1] == [a.t_s for a in t2]
    assert [a.request.prompt for a in t1] == [a.request.prompt for a in t2]
    # a different seed must actually move the trace
    other = generate_trace(dataclasses.replace(spec, seed=12))
    assert trace_fingerprint(other) != trace_fingerprint(t1)
    # deterministic process: exact 1/λ spacing
    det = generate_trace(dataclasses.replace(spec, process="deterministic"))
    gaps = np.diff([a.t_s for a in det])
    np.testing.assert_allclose(gaps, 1.0 / 7.0, rtol=1e-12)
    # per-request seeds are distinct (neighbor-independent sampling)
    seeds = [a.request.seed for a in t1]
    assert len(set(seeds)) == len(seeds)
    with pytest.raises(ValueError, match="rate_rps"):
        TrafficSpec(rate_rps=0, n_requests=1).validate()
    with pytest.raises(ValueError, match="prompt_len"):
        TrafficSpec(rate_rps=1, n_requests=1,
                    prompt_len=("bogus", 1)).validate()


def test_traffic_preset_mix_admissible():
    s_max = 64
    spec = preset_mix("mixtral-8x7b", s_max=s_max, rate_rps=3.0,
                      n_requests=50, seed=4, vocab=128)
    assert spec.vocab == 128  # override for shrunk serving heads
    assert (traffic_mod.max_length(spec.prompt_len)
            + traffic_mod.max_length(spec.output_len)) <= s_max
    trace = generate_trace(spec)
    for a in trace:
        assert 1 <= len(a.request.prompt) + a.request.max_new_tokens <= s_max
        assert all(0 <= t < 128 for t in a.request.prompt)
    # the default vocabulary comes from the preset's architecture table
    full = preset_mix("llama-3.1-8b", s_max=s_max, rate_rps=1.0, n_requests=1)
    assert full.vocab == 128256


# ---------------------------------------------------------------------------
# Host tier: serviceable mesh + prefill buckets + bench emission
# ---------------------------------------------------------------------------

def test_serviceable_mesh_degrades_to_model_valid_world(mesh4):
    tdt_config.update(elastic=True)
    ok = lambda n: n in (1, 2, 4)  # noqa: E731 — kv-head-style constraint
    assert elastic.serviceable_mesh(mesh4, validate=ok) is mesh4
    elastic.quarantine(3, reason="test")
    m = elastic.serviceable_mesh(mesh4, validate=ok)
    assert m.devices.shape == (2,), "3 survivors are model-invalid -> 2"
    assert list(m.devices.flat) == list(mesh4.devices.flat)[:2]
    # no predicate: plain effective_mesh semantics (3 survivors)
    assert elastic.serviceable_mesh(mesh4).devices.shape == (3,)
    with pytest.raises(ValueError, match="no serviceable"):
        elastic.serviceable_mesh(mesh4, validate=lambda n: False)


def test_prefill_bucket_bound_mixed_lengths(tiny1, mesh1):
    """Recompilation-storm guard (ISSUE 6 satellite): every prompt length
    in 3..200 maps into the power-of-two bucket set, so a mixed workload
    compiles at most log2(s_max) prefill programs — never one per
    length."""
    cfg, params = tiny1
    b = ContinuousBatcher(cfg, params, mesh1, s_max=256, prefill=True)
    buckets = {b._bucket(length) for length in range(3, 201)}
    assert buckets <= {4, 8, 16, 32, 64, 128, 256}
    assert len(buckets) <= 7
    assert all(bk & (bk - 1) == 0 for bk in buckets), "powers of two"
    assert b.prefill_bucket_count == 0, "no compiles before admission"


def test_steps_exhausted_error_contract():
    """Tier-1 pin for the satellite bugfix surface (the full batcher run
    lives in the slow tier, tests/test_decode.py): the exhaustion error
    is a RuntimeError (existing handlers keep working), names both uid
    rosters, and points at drain_finished()."""
    from triton_dist_tpu.models.decode import StepsExhaustedError

    err = StepsExhaustedError(7, ["s1", "s2"], ["done1"])
    assert isinstance(err, RuntimeError)
    assert err.max_steps == 7
    assert err.pending_uids == ("s1", "s2")
    assert err.finished_uids == ("done1",)
    assert "drain_finished" in str(err) and "max_steps=7" in str(err)


def test_bench_info_lines_shape():
    """The bench_serving emission contract: info lines only — no
    vs_baseline anywhere, so scripts/perf_gate.sh (which only collects
    vs_baseline-bearing lines) structurally cannot gate them."""
    m = ServingMetrics(slo=SLOTargets(ttft_ms=100.0))
    m.observe_finished(ttft_ms=10.0, e2e_ms=20.0, tpot_ms=5.0, n_tokens=3)
    m.observe_step(queue_depth=2, occupied=1, slots=2)
    snap = m.snapshot()
    snap["tokens"]["per_s"] = 1.5
    snap["tokens"]["goodput_per_s"] = 1.5  # the engine-added twin
    rows = [{"rate_rps": 2.5, "snapshot": snap, "n_finished": 1}]
    lines = sbench.info_lines(rows, tag="_t")
    names = [n for n, _, _ in lines]
    assert f"serving_ttft_p50_ms_lam2.5_t" in names
    assert f"serving_slo_attainment_lam2.5_t" in names
    assert f"serving_goodput_per_s_lam2.5_t" in names
    assert len(set(names)) == len(names)
    for name, value, unit in lines:
        payload = json.dumps({"metric": name, "value": value, "unit": unit})
        assert "vs_baseline" not in payload


# ---------------------------------------------------------------------------
# Engine tier (world-1 mesh; real batcher steps)
# ---------------------------------------------------------------------------

def _reqs(cfg, spec_list, seed=5):
    key = jax.random.PRNGKey(seed)
    out = []
    for i, (plen, mx) in enumerate(spec_list):
        toks = list(np.asarray(jax.random.randint(
            jax.random.fold_in(key, i), (plen,), 0, cfg.vocab, np.int32
        )))
        out.append(Request([int(t) for t in toks], max_new_tokens=mx, uid=i))
    return out


def test_engine_matches_direct_batcher_and_lifecycle(tiny1, mesh1):
    cfg, params = tiny1
    shapes = [(3, 4), (5, 3), (2, 5)]

    direct = ContinuousBatcher(cfg, params, mesh1, s_max=16)
    for r in _reqs(cfg, shapes):
        direct.submit(r)
    want = dict(direct.run(max_steps=200))

    clock = retry.FakeClock()
    eng = ServingEngine(cfg, params, mesh1, s_max=16, clock=clock,
                        serving=ServingConfig(virtual_step_s=0.01))
    for r in _reqs(cfg, shapes):
        assert eng.submit(r) == r.uid
    done = eng.run_until_idle()
    assert set(done) == set(want)
    for uid, res in done.items():
        assert res.tokens == want[uid], f"request {uid}"
        assert res.t_enqueue <= res.t_admitted <= res.t_first_token
        assert res.t_first_token <= res.t_finished
        assert res.resumed == 0
    snap = eng.snapshot()
    assert snap["requests"]["submitted"] == 3
    assert snap["requests"]["finished"] == 3
    assert snap["tokens"]["generated"] == sum(len(t) for t in want.values())
    assert snap["latency_ms"]["ttft"]["count"] == 3
    assert snap["engine"]["world_size"] == 1
    json.dumps(snap)


def test_engine_backpressure_reject(tiny1, mesh1):
    cfg, params = tiny1
    eng = ServingEngine(cfg, params, mesh1, s_max=16,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(max_queue=1))
    reqs = _reqs(cfg, [(2, 2)] * 4, seed=6)
    assert eng.submit(reqs[0]) == 0   # -> slot
    assert eng.submit(reqs[1]) == 1   # -> slot (batch=2)
    assert eng.submit(reqs[2]) == 2   # -> queue (1/1)
    rej = eng.submit(reqs[3])
    assert isinstance(rej, Rejected) and rej.uid == 3
    assert rej.queue_depth == 1
    done = eng.run_until_idle()
    assert set(done) == {0, 1, 2}, "the rejected request was never enqueued"
    snap = eng.snapshot()
    assert snap["requests"]["rejected"] == 1
    assert snap["requests"]["submitted"] == 4
    # invalid requests are rejected loudly at submit, not mid-serve
    with pytest.raises(ValueError, match="exceeds s_max"):
        eng.submit(Request([1] * 10, max_new_tokens=10, uid="big"))


def test_engine_backpressure_block(tiny1, mesh1):
    cfg, params = tiny1
    eng = ServingEngine(cfg, params, mesh1, s_max=16,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(max_queue=1,
                                              backpressure="block",
                                              virtual_step_s=0.01))
    for r in _reqs(cfg, [(2, 2)] * 5, seed=7):
        out = eng.submit(r)     # blocks (steps the engine) when full
        assert not isinstance(out, Rejected)
    done = eng.run_until_idle()
    assert set(done) == {0, 1, 2, 3, 4}
    assert "rejected" not in eng.snapshot()["requests"]


def test_engine_admission_shortest_prompt_first(tiny1, mesh1):
    cfg, params = tiny1
    cfg1 = dataclasses.replace(cfg, batch=1)
    params1 = init_params(jax.random.PRNGKey(0), cfg1)
    eng = ServingEngine(cfg1, params1, mesh1, s_max=16,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(admission="spf",
                                              virtual_step_s=0.01))
    long, mid, short = _reqs(cfg1, [(6, 2), (5, 2), (2, 2)], seed=8)
    eng.submit(long)            # admitted immediately (free slot)
    eng.submit(mid)             # queued
    eng.submit(short)           # queued behind mid, but shorter
    done = eng.run_until_idle()
    assert set(done) == {0, 1, 2}
    assert done[2].t_admitted < done[1].t_admitted, (
        "shortest-prompt-first must admit the short request before the "
        "earlier-but-longer one"
    )


def test_engine_deterministic_latency_under_fake_clock(tiny1, mesh1):
    """ISSUE 6 acceptance: two runs with the same traffic seed and a
    FakeClock produce IDENTICAL metric snapshots — latency percentiles
    included."""
    cfg, params = tiny1
    spec = TrafficSpec(rate_rps=8.0, n_requests=8,
                       prompt_len=("uniform", 2, 4),
                       output_len=("uniform", 2, 5), vocab=cfg.vocab, seed=3)

    def run():
        eng = ServingEngine(
            cfg, params, mesh1, s_max=16, clock=retry.FakeClock(),
            serving=ServingConfig(virtual_step_s=0.05,
                                  slo=SLOTargets(ttft_ms=1e3, e2e_ms=5e3)),
        )
        done = eng.serve(generate_trace(spec))
        return done, eng.snapshot()

    done1, snap1 = run()
    done2, snap2 = run()
    assert snap1 == snap2
    assert {u: r.tokens for u, r in done1.items()} == {
        u: r.tokens for u, r in done2.items()
    }
    assert snap1["latency_ms"]["ttft"]["p50"] > 0
    assert snap1["slo"]["attained"] == 1.0


def test_engine_stop_drain_and_cancel(tiny1, mesh1):
    cfg, params = tiny1
    # graceful drain: everything already enqueued still completes
    eng = ServingEngine(cfg, params, mesh1, s_max=16,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(virtual_step_s=0.01))
    for r in _reqs(cfg, [(2, 2)] * 4, seed=9):
        eng.submit(r)
    eng.stop(drain=True)
    assert set(eng.run_until_idle()) == {0, 1, 2, 3}
    # fast stop: the arrival queue is cancelled (counted), in-flight
    # slots still finish — abandoning device work loses tokens for free
    eng2 = ServingEngine(cfg, params, mesh1, s_max=16,
                         clock=retry.FakeClock(),
                         serving=ServingConfig(virtual_step_s=0.01))
    for r in _reqs(cfg, [(2, 3)] * 4, seed=10):
        eng2.submit(r)          # 2 slots + 2 queued
    eng2.stop(drain=False)
    done = eng2.run_until_idle()
    assert set(done) == {0, 1}
    assert eng2.snapshot()["requests"]["cancelled"] == 2


def test_engine_default_clock_via_clock_scope(tiny1, mesh1):
    """An engine built with no explicit clock resolves the resilience
    module clock, so retry.clock_scope(FakeClock()) puts backoffs AND
    serving timestamps on one deterministic timeline — and the scope
    restores the previous clock on exit."""
    cfg, params = tiny1
    prev = retry.get_clock()
    with retry.clock_scope(retry.FakeClock()) as clock:
        assert retry.get_clock() is clock
        eng = ServingEngine(cfg, params, mesh1, s_max=16,
                            serving=ServingConfig(virtual_step_s=0.25))
        assert eng.clock is clock
        eng.submit(Request([1, 2], max_new_tokens=2, uid="c"))
        done = eng.run_until_idle()
        assert len(done["c"].tokens) == 2
        # time passed only on the fake clock: one step per fed/generated
        # token at the configured virtual cost
        assert clock.now == pytest.approx(0.25 * 3)
    assert retry.get_clock() is prev, "scope must restore the clock"


def test_engine_prefill_bucket_gauge(tiny1, mesh1):
    """The compile-cache size is observable through the engine snapshot
    and grows with BUCKETS, not with distinct prompt lengths."""
    cfg, params = tiny1
    eng = ServingEngine(cfg, params, mesh1, s_max=16, prefill=True,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(virtual_step_s=0.01))
    for r in _reqs(cfg, [(3, 2), (4, 2), (7, 2)], seed=11):
        eng.submit(r)           # lengths 3, 4 -> bucket 4; 7 -> bucket 8
    done = eng.run_until_idle()
    assert set(done) == {0, 1, 2}
    assert eng.snapshot()["engine"]["prefill_bucket_programs"] == 2


def test_sampling_guarantee_neighbor_mix_and_slot_change(tiny1, mesh1):
    """docs/serving.md's sampling guarantee, pinned (ISSUE 6 satellite):
    the same Request(seed=...) yields identical tokens (a) under a
    different batch-neighbor mix and (b) after eviction + re-admission
    into a DIFFERENT slot over a dirty cache."""
    cfg, params = tiny1
    b = ContinuousBatcher(cfg, params, mesh1, s_max=16)
    mk = lambda uid: Request([3, 1, 4], max_new_tokens=5, temperature=0.9,  # noqa: E731
                             top_k=4, seed=123, uid=uid)
    # round 1: R in slot 0, short greedy neighbor in slot 1
    b.submit(mk("r1"))
    b.submit(Request([2, 2], max_new_tokens=2, uid="n1"))
    first = dict(b.run(max_steps=100))
    # round 2 (same batcher, dirty cache): a long sampled dummy claims
    # slot 0 first, so R re-admits into slot 1 beside a different neighbor
    b.submit(Request([5, 6, 7, 8], max_new_tokens=6, temperature=0.7,
                     seed=999, uid="d"))
    b.submit(mk("r2"))
    second = dict(b.run(max_steps=100))
    assert first["r1"] == second["r2"], (
        "seeded sampling must not depend on slot index, cache dirt, or "
        "batch neighbors"
    )


def test_engine_replay_preserves_greedy_and_sampled_streams(tiny1, mesh1,
                                                            monkeypatch):
    """Prefix replay without any elastic machinery: a step timeout on a
    healthy world rebuilds the batcher in place and re-queues prompt +
    tokens-so-far. Greedy AND seeded-sampled outputs must be
    byte-identical to an uninterrupted run (the sampled stream continues
    through the live RNG that rides the replay request)."""
    cfg, params = tiny1
    reqs = lambda: [  # noqa: E731
        Request([1, 2, 3], max_new_tokens=6, uid="g"),
        Request([4, 5], max_new_tokens=6, temperature=0.8, top_k=6,
                seed=77, uid="s"),
    ]
    golden_eng = ServingEngine(cfg, params, mesh1, s_max=16,
                               clock=retry.FakeClock(),
                               serving=ServingConfig(virtual_step_s=0.01))
    for r in reqs():
        golden_eng.submit(r)
    golden = golden_eng.run_until_idle()

    calls = {"n": 0}
    real_step = ContinuousBatcher.step

    def flaky(self):
        calls["n"] += 1
        if calls["n"] == 4:  # mid-generation, both slots past first token
            raise DistTimeoutError("batcher_step", _recs([0]), world_size=1)
        return real_step(self)

    monkeypatch.setattr(ContinuousBatcher, "step", flaky)
    eng = ServingEngine(cfg, params, mesh1, s_max=16,
                        clock=retry.FakeClock(),
                        serving=ServingConfig(virtual_step_s=0.01))
    for r in reqs():
        eng.submit(r)
    done = eng.run_until_idle()
    assert {u: r.tokens for u, r in done.items()} == {
        u: r.tokens for u, r in golden.items()
    }
    assert done["g"].resumed == 1 and done["s"].resumed == 1
    assert eng.rebuilds == 1
    snap = eng.snapshot()
    assert snap["requests"]["resumed"] == 2
    assert snap["latency_ms"]["resumed_ttft"]["count"] >= 1, (
        "TTFT after a disruption is re-measured as a resumed event"
    )


# ---------------------------------------------------------------------------
# Chaos tier: the elastic serving arcs (mesh4)
# ---------------------------------------------------------------------------

def _serve_tiny4(tiny4, mesh4, *, fault_at=None, fault_recs=None,
                 probe_interval=3, max_failures=8):
    """One traffic-driven serve over tiny4/mesh4 with an optional
    fabricated step timeout at call #fault_at (the host-level arc: only
    the in-kernel wait is simulated; retry/attribution/shrink/replay/
    probe are the production paths)."""
    cfg, params = tiny4
    spec = TrafficSpec(rate_rps=50.0, n_requests=5,
                       prompt_len=("uniform", 2, 4),
                       output_len=("uniform", 3, 6), vocab=cfg.vocab, seed=7)
    clock = retry.FakeClock()
    retry.set_clock(clock)
    eng = ServingEngine(
        cfg, params, mesh4, s_max=16, clock=clock,
        serving=ServingConfig(virtual_step_s=0.05,
                              probe_interval_steps=probe_interval,
                              max_step_failures=max_failures),
    )
    calls = {"n": 0}
    real_step = ContinuousBatcher.step

    def flaky(self):
        calls["n"] += 1
        if fault_at is not None and calls["n"] in (
            fault_at if isinstance(fault_at, tuple) else (fault_at,)
        ):
            raise DistTimeoutError("batcher_step", fault_recs, world_size=4)
        return real_step(self)

    ContinuousBatcher.step = flaky
    try:
        done = eng.serve(generate_trace(spec))
    finally:
        ContinuousBatcher.step = real_step
    return eng, done


@pytest.mark.chaos
def test_serving_elastic_arc(tiny4, mesh4):
    """ISSUE 6 acceptance: persistent-straggler step timeout mid-serving →
    PE quarantined → the engine shrinks to the serviceable world (2: the
    3-survivor count is model-invalid) and keeps serving with every
    in-flight request prefix-replayed → probation re-admits → the world
    regrows to 4 mid-serving → every submitted request finishes exactly
    once with tokens byte-identical to the uninterrupted run."""
    golden_eng, golden = _serve_tiny4(tiny4, mesh4)
    assert golden_eng.rebuilds == 0 and len(golden) == 5

    resilience.reset(keep_env=True)
    tdt_config.update(elastic=True, suspect_threshold=1, probation_probes=1)
    eng, done = _serve_tiny4(tiny4, mesh4, fault_at=3,
                             fault_recs=_recs([0, 2, 3]))
    assert set(done) == set(golden)
    for uid in golden:
        assert done[uid].tokens == golden[uid].tokens, f"request {uid}"
    assert eng.rebuilds == 2, "one shrink + one regrow"
    assert eng.world_size == 4, "probation re-admission regrew the world"
    counters = health.snapshot()["counters"]
    assert counters["pe1:pe_quarantine"] == 1
    assert counters["pe1:pe_readmit"] == 1
    assert counters["serving_engine:serving_rebuild"] == 2
    worlds = [e.reason.split(":")[0] for e in
              health.events(health.SERVING_REBUILD)]
    assert worlds == ["world=2", "world=4"], (
        "shrink must land on the largest MODEL-VALID world (2, not 3)"
    )
    assert any(r.resumed for r in done.values()), "prefix replay happened"
    assert eng.snapshot()["requests"]["resumed"] >= 1


@pytest.mark.chaos
def test_serving_arc_unattributable_timeout_keeps_full_world(tiny4, mesh4):
    """Every PE tripping (fabric-wide) must not quarantine anyone: the
    engine rebuilds on the FULL world and service continues losslessly."""
    golden_eng, golden = _serve_tiny4(tiny4, mesh4)
    resilience.reset(keep_env=True)
    tdt_config.update(elastic=True, suspect_threshold=1)
    eng, done = _serve_tiny4(tiny4, mesh4, fault_at=3,
                             fault_recs=_recs([0, 1, 2, 3]))
    assert elastic.quarantined_pes() == ()
    assert eng.world_size == 4 and eng.rebuilds == 1
    assert {u: r.tokens for u, r in done.items()} == {
        u: r.tokens for u, r in golden.items()
    }


@pytest.mark.chaos
def test_serving_engine_escalates_after_max_failures(tiny4, mesh4):
    """A timeout storm the rebuild/replay loop cannot absorb must
    escalate loudly, not spin forever."""
    resilience.reset(keep_env=True)
    with pytest.raises(RuntimeError, match="consecutive step timeouts"):
        _serve_tiny4(tiny4, mesh4, fault_at=tuple(range(1, 20)),
                     fault_recs=_recs([0, 2, 3]), max_failures=2)
