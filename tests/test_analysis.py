"""Host-tier tests for the static signal-protocol verifier (ISSUE 10).

Everything here runs on ANY jax line, CPU, no interpreter — that is the
whole point of the analysis package: the capture layer replaces the
``shmem/device.py`` primitive surface and the kernel launcher with
recording shims, so these cells exercise the same seams on jax 0.4.37
that the (gated) interpreter chaos tiers exercise on jax >= 0.6.

Covered (the ISSUE 10 satellite list): capture determinism, credit-balance
proofs for chunk=1 ≡ legacy tuples, every seeded defect flagged with the
right slot/site, the a2a chunk-major order check, the TELEM_SLOTS budget
check, and the cross-check cell pinning the verifier's wait-site inventory
to the set the obs telemetry decode reports for the same launch.
"""

from __future__ import annotations

from unittest import mock

import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.analysis import capture as C
from triton_dist_tpu.analysis import defects as D
from triton_dist_tpu.analysis import sweep as S
from triton_dist_tpu.analysis.verify import verify_capture
from triton_dist_tpu.obs import telemetry as T
from triton_dist_tpu.resilience import records as R
from triton_dist_tpu.resilience import sites as sites


def _cap(family, world, label, spec=None):
    if spec is None:
        spec = dict(S.family_tuples(family, world))[label]
    return S.capture_family(family, world, label, spec)


# ---------------------------------------------------------------------------
# The shared site table (satellite: one numbering, three consumers)
# ---------------------------------------------------------------------------

def test_sites_table_is_the_single_source():
    # records re-exports the table, telemetry derives its window from it,
    # and the kind names decode identically everywhere
    assert R.KIND_SIGNAL is sites.KIND_SIGNAL
    assert R.KIND_CHUNK is sites.KIND_CHUNK
    assert R.KIND_INTEGRITY is sites.KIND_INTEGRITY
    assert R.kind_name is sites.kind_name
    assert T.TELEM_SLOTS == sites.TELEM_SLOTS
    assert sites.kind_name(sites.KIND_CHUNK) == "chunk_wait"
    assert sites.BOUNDED_KINDS == {
        sites.KIND_SIGNAL, sites.KIND_WAIT, sites.KIND_BARRIER,
        sites.KIND_CHUNK,
    }


# ---------------------------------------------------------------------------
# Capture determinism + chunk=1 ≡ legacy
# ---------------------------------------------------------------------------

def test_capture_byte_identical_across_runs():
    a = _cap("a2a", 2, "p1/c2")
    b = _cap("a2a", 2, "p1/c2")
    assert a.canonical() == b.canonical()


def test_chunk1_capture_identical_to_legacy_tuple():
    """chunks_per_shard=1 dispatches to the UNCHANGED legacy kernel — the
    capture layer must see the IDENTICAL protocol, event for event."""
    from triton_dist_tpu.ops.all_to_all import A2AConfig

    legacy = S.capture_family("a2a", 2, "x", A2AConfig(puts_per_slab=1))
    chunk1 = S.capture_family("a2a", 2, "x", A2AConfig(chunks_per_shard=1))
    assert legacy.canonical() == chunk1.canonical()


@pytest.mark.parametrize("family,label", [
    ("allgather", "ring_1d/c1"),
    ("allgather", "ring_bidir/c1"),
    ("allgather", "full_mesh_push/c1"),
    ("reduce_scatter", "scatter_reduce/bm256/c1"),
    ("a2a", "p1/c1"),
    ("gemm_rs", "scatter/bm512"),
])
def test_legacy_tuples_prove_credit_balance(family, label):
    rep = verify_capture(_cap(family, 2, label))
    assert rep.ok, rep.summary()
    # legacy (unchunked) schedules predate the canary: no landing-view
    # warnings either — completely silent reports
    assert not rep.warnings, rep.summary()


def test_chunked_ring_proves_credit_balance_with_sites():
    cap = _cap("allgather", 4, "ring_1d/c2")
    rep = verify_capture(cap)
    assert rep.ok, rep.summary()
    # every chunk wait is a bounded site of the shared numbering
    launch = cap.traces[0].launches[0]
    kinds = {e.kind for e in launch.events if e.op == C.WAIT}
    assert sites.KIND_CHUNK in kinds and sites.KIND_BARRIER in kinds
    assert launch.n_wait_sites <= sites.TELEM_SLOTS


def test_fused_moe_pipeline_chunk1_proves():
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig

    rep = verify_capture(
        S.capture_family(
            "ag_group_gemm", 2, "bm128/c1", GroupGemmConfig(128, 1024, 512)
        )
    )
    assert rep.ok, rep.summary()


# ---------------------------------------------------------------------------
# Seeded defects (analysis/defects.py): each flagged, slot/site named.
# chaos-marked: these are the static twins of the fault-injection matrix
# (scripts/chaos_matrix.sh runs them via the marker AND the full
# protocol_lint sweep; unlike the live cells they never skip)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def defect_pool():
    return {
        "a2a/p1/c4/w2": _cap("a2a", 2, "p1/c4"),
        "allgather/ring_1d/c2/w2": _cap("allgather", 2, "ring_1d/c2"),
        "allgather/ring_1d/c1/w2": _cap("allgather", 2, "ring_1d/c1"),
    }


@pytest.mark.chaos
def test_every_seeded_defect_flagged(defect_pool):
    failures = D.run_defect_suite(defect_pool)
    assert not failures, failures


@pytest.mark.chaos
def test_dropped_signal_diagnosis_names_site_and_slot(defect_pool):
    cap = defect_pool["a2a/p1/c4/w2"]
    seeded = D.seed_defect(cap, "dropped_signal")
    rep = verify_capture(seeded.capture)
    (finding,) = [f for f in rep.errors if f.check == "deadlock"][:1]
    assert seeded.expect_naming in finding.message      # the slot
    assert "site" in finding.message                    # the wait site
    assert "fast_all_to_all" in finding.message         # the family


@pytest.mark.chaos
def test_dropped_wait_leaves_named_residue(defect_pool):
    cap = defect_pool["allgather/ring_1d/c2/w2"]
    seeded = D.seed_defect(cap, "dropped_wait")
    rep = verify_capture(seeded.capture)
    msgs = [f.message for f in rep.errors if f.check == "credit_balance"]
    assert msgs and any(seeded.expect_naming in m for m in msgs), rep.summary()
    assert any("does not drain to zero" in m for m in msgs)


@pytest.mark.chaos
def test_missing_drain_flagged_on_send_slot(defect_pool):
    cap = defect_pool["allgather/ring_1d/c1/w2"]
    seeded = D.seed_defect(cap, "missing_drain")
    rep = verify_capture(seeded.capture)
    msgs = [f.message for f in rep.errors if f.check == "credit_balance"]
    assert msgs and any(seeded.expect_naming in m for m in msgs), rep.summary()


# ---------------------------------------------------------------------------
# a2a chunk-major order (check 3)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_a2a_chunk_major_clean_and_swapped(defect_pool):
    cap = defect_pool["a2a/p1/c4/w2"]
    assert verify_capture(cap).ok
    seeded = D.seed_defect(cap, "swapped_chunk_order")
    rep = verify_capture(seeded.capture)
    hits = [f for f in rep.errors if f.check == "chunk_order"]
    assert hits and "CHUNK-MAJOR" in hits[0].message, rep.summary()
    # the order defect is numerically invisible: credits still balance
    assert not [f for f in rep.errors if f.check == "credit_balance"]


def test_a2a_chunk_major_at_world4():
    cap = _cap("a2a", 4, "p1/c4")
    rep = verify_capture(cap)
    assert rep.ok, rep.summary()
    # the capture really is chunk-major: put slots' chunk index is
    # non-decreasing within the chunked emission on every rank
    for t in cap.traces:
        chunk_ids = [
            e.slot[1][-1] for e in t.launches[0].events
            if e.op == C.PUT and e.meta.get("chunk_signal")
        ]
        assert chunk_ids == sorted(chunk_ids)


# ---------------------------------------------------------------------------
# TELEM_SLOTS budget (check 4)
# ---------------------------------------------------------------------------

def test_telem_budget_overflow_reported():
    # 7 ring steps x 8 chunks = 56 chunk-wait sites + 3 barrier rounds:
    # past the 32-slot telemetry window — the verifier reports at trace
    # time what the runtime would only count in the overflow header
    cap = S.capture_family("allgather", 8, "ring_1d/c8", ("ring_1d", 8))
    rep = verify_capture(cap)
    assert rep.ok, rep.summary()  # the schedule itself is sound
    assert any(w.check == "telem_budget" for w in rep.warnings), (
        rep.summary()
    )
    assert rep.stats["max_sites"] > sites.TELEM_SLOTS


def test_telem_budget_quiet_under_window():
    rep = verify_capture(_cap("allgather", 4, "ring_1d/c4"))
    assert not [w for w in rep.warnings if w.check == "telem_budget"]


def test_telem_budget_waiver_accepts_ag_gemm_c8():
    """ISSUE 12 satellite: the ag_gemm chunks=8 overflow (59 sites at
    world 8) is retired by the documented per-launch site-window policy
    (resilience/sites.py TELEM_SITE_WAIVERS) — counted as a waived stat,
    not a warning, so a clean lint run is 0 warnings; outgrowing the
    waived ceiling would warn again (the allgather c8 cell above pins
    the unwaived behavior stays a warning)."""
    assert sites.telem_site_budget("ag_gemm") == 64
    assert sites.telem_site_budget("allgather") == sites.TELEM_SLOTS
    cap = S.capture_family(
        "ag_gemm", 8, "bm512/c8",
        next(c for _, c in S.FAMILIES["ag_gemm"].tuples(8)
             if getattr(c, "chunks_per_shard", 1) == 8),
    )
    rep = verify_capture(cap)
    assert rep.ok, rep.summary()
    assert not [w for w in rep.warnings if w.check == "telem_budget"], (
        rep.summary()
    )
    assert rep.stats["max_sites"] > sites.TELEM_SLOTS
    assert rep.stats.get("telem_waived", 0) >= 1


# ---------------------------------------------------------------------------
# Landing-view (canary) coverage (check 5)
# ---------------------------------------------------------------------------

def test_landing_view_coverage_closed_and_enforced():
    """ISSUE 11 satellite: the canary gap set is EMPTY — every chunked
    family (the former gap set included) declares its landing view — and
    the lint check is now a FAILURE, so a future chunk-signal put cannot
    land without opting into payload integrity."""
    for family, label in (
        ("allgather", "ring_1d/c2"),        # declared since ISSUE 8
        ("ag_gemm", "bm1024/c2"),           # the former gap set:
        ("reduce_scatter", "ring/bm256/c2"),
        ("gemm_rs", "ring/bm512/c2"),
    ):
        rep = verify_capture(_cap(family, 2, label))
        assert rep.ok, rep.summary()
        assert not [f for f in rep.errors + rep.warnings
                    if f.check == "landing_view"], rep.summary()
    # enforcement: strip one put's landing-view declaration — the report
    # must FAIL (error, not warning), naming the uncovered count
    cap = _cap("ag_gemm", 2, "bm1024/c2")
    for t in cap.traces:
        for e in t.launches[0].events:
            if e.op == C.PUT and e.meta.get("chunk_signal"):
                e.meta["landing_view"] = False
    rep = verify_capture(cap)
    hits = [f for f in rep.errors if f.check == "landing_view"]
    assert hits and "recv_view" in hits[0].message, rep.summary()
    assert not rep.ok


# ---------------------------------------------------------------------------
# kv_stream: the disaggregated KV handoff family (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [2, 4, 8])
def test_kv_stream_every_tuple_proves(world):
    """The whole KV_STREAM_TUNE_SPACE (wire × chunks) proves clean at
    each even world: credit balance, deadlock freedom, dense wait-site
    numbering — zero warnings (well inside the telemetry window)."""
    for label, spec in S.family_tuples("kv_stream", world):
        rep = verify_capture(_cap("kv_stream", world, label, spec))
        assert rep.ok, rep.summary()
        assert not rep.warnings, rep.summary()
        assert rep.stats["max_sites"] <= sites.TELEM_SLOTS


def test_kv_stream_chunk_major_and_landing_views():
    """Structure of the int8-wire capture: per-chunk signal-bearing puts
    in chunk-major order on BOTH the payload and the scales stream, and
    EVERY chunk-signal put declares its landing view (the canary opt-in
    a new chunked family cannot land without)."""
    cap = _cap("kv_stream", 4, "int8/c4")
    events = cap.traces[0].launches[0].events
    chunk_puts = [e for e in events
                  if e.op == C.PUT and e.meta.get("chunk_signal")]
    # c4 × (payload + scales) = 8 chunk puts, all canary-covered
    assert len(chunk_puts) == 8
    assert all(e.meta.get("landing_view") for e in chunk_puts)
    # chunk-major within each stream: slot indices ascend
    for stream in (chunk_puts[:4], chunk_puts[4:]):
        idx = [e.slot[1][-1] for e in stream]
        assert idx == sorted(idx), idx
    # and the mirror pairing: every put targets rank (me + n/2) mod n
    for t in cap.traces:
        peers = {e.dst for l in t.launches for e in l.events
                 if e.op == C.PUT}
        assert peers == {(t.rank + 2) % 4}, (t.rank, peers)


def test_kv_stream_capture_byte_identical():
    a = _cap("kv_stream", 4, "int8/c2").canonical()
    b = _cap("kv_stream", 4, "int8/c2").canonical()
    assert a == b


@pytest.mark.chaos
def test_kv_stream_seeded_defect_twin():
    """The seeded-defect twin (ISSUE 13 satellite): a dropped chunk
    signal on the kv_stream wire must be flagged as a deadlock naming
    the afflicted slot/site, while the clean twin stays silent."""
    cap = _cap("kv_stream", 4, "native/c2")
    clean = verify_capture(cap)
    assert clean.ok and not clean.warnings, clean.summary()
    seeded = D.seed_defect(cap, "dropped_signal")
    rep = verify_capture(seeded.capture)
    hits = [f for f in rep.errors if f.check == "deadlock"]
    assert hits, rep.summary()
    assert seeded.expect_naming in hits[0].message, rep.summary()
    assert "site" in hits[0].message
    # every other applicable mutation flags too (swap_chunk_order is
    # a2a-form-only by design), each naming its slot
    for kind in ("dropped_wait", "extra_signal", "missing_drain"):
        seeded_k = D.seed_defect(_cap("kv_stream", 4, "native/c2"), kind)
        rep_k = verify_capture(seeded_k.capture)
        hits_k = [f for f in rep_k.errors
                  if f.check == seeded_k.expect_check]
        assert hits_k, (kind, rep_k.summary())
        assert any(seeded_k.expect_naming in f.message for f in hits_k), (
            kind, rep_k.summary()
        )


def test_kv_stream_rejects_odd_world_and_wire_mismatch():
    import jax.numpy as jnp  # noqa: F811 — local, matches module import

    import triton_dist_tpu.ops.kv_stream as K

    with pytest.raises(ValueError, match="even world"):
        with mock.patch.object(K, "_axis_size", lambda axis: 3):
            K._kv_stream_fused(jnp.ones((8, 4)), axis="tp")
    with pytest.raises(ValueError, match="scales"):
        with mock.patch.object(K, "_axis_size", lambda axis: 4):
            K._kv_stream_fused(
                jnp.ones((8, 4), jnp.int8), axis="tp",
                config=K.KVStreamConfig(wire="int8"),
            )


# ---------------------------------------------------------------------------
# Cross-check: verifier site inventory == obs telemetry decode (satellite)
# ---------------------------------------------------------------------------

def test_wait_site_inventory_matches_telemetry_decode():
    """Drive the REAL in-kernel telemetry writer with the captured wait
    sites of a chunked ring launch and decode it with the REAL host
    decoder: the (site, kind) inventory must match the verifier's graph
    exactly — the three consumers of resilience/sites.py agree."""
    from triton_dist_tpu.resilience import watchdog as W

    cap = _cap("allgather", 2, "ring_1d/c2")
    launch = cap.traces[0].launches[0]
    waits = [(e.site, e.kind) for e in launch.events if e.op == C.WAIT]
    assert waits and len(waits) == launch.n_wait_sites

    class FakeSmem:
        def __init__(self):
            self.buf = np.zeros(T.TELEM_LEN, np.int64)

        def __getitem__(self, i):
            return jnp.int32(int(self.buf[i]))

        def __setitem__(self, i, v):
            self.buf[i] = int(v)

    def fake_when(cond):
        def deco(fn):
            if bool(cond):
                fn()
            return fn

        return deco

    ref = FakeSmem()
    scope = W.KernelDiagScope(None, launch.family, telem_ref=ref)
    scope.pe = jnp.int32(0)
    with mock.patch("jax.experimental.pallas.when", fake_when):
        for site, kind in waits:
            W._record_wait_telemetry(scope, site, kind, jnp.int32(1))
    ref.buf[T.H_FAMILY] = R.family_code_for(launch.family)
    (row,) = T.decode_telem(ref.buf.astype(np.int32))
    decoded = {(s["site"], s["kind"]) for s in row["sites"]}
    captured = {(site, sites.kind_name(kind)) for site, kind in waits}
    assert decoded == captured
    assert row["overflow_sites"] == 0


# ---------------------------------------------------------------------------
# The CLI (scripts/protocol_lint.py) smoke
# ---------------------------------------------------------------------------

def test_protocol_lint_cli_quick_subset(capsys):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "protocol_lint",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "protocol_lint.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--families", "allgather", "--worlds", "2",
                   "--no-defects"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "PASS" in out and "credit-balanced" in out
    assert mod.main(["--families", "nosuch"]) == 2


# ---------------------------------------------------------------------------
# Synthesized schedules in the sweep (ISSUE 14): the standing registry is
# enumerated STRUCTURALLY — the tune-space constants include it — so
# protocol_lint proves every admitted schedule permanently. The prove
# stage itself (three gates, probe rejection) is tests/test_synth.py.
# ---------------------------------------------------------------------------

def test_sweep_enumerates_admitted_synth_tuples():
    """Every standing registry entry surfaces as its own labeled tuple in
    the family sweep — a synthesized schedule cannot silently drop out of
    the lint's coverage."""
    from triton_dist_tpu.analysis.sweep import _gg_label
    from triton_dist_tpu.ops.group_gemm import GroupGemmConfig
    from triton_dist_tpu.synth.admitted import SYNTH_ADMITTED

    assert len(SYNTH_ADMITTED) >= 4  # >= 2 genuinely new families, both sides
    for family in ("ag_group_gemm", "moe_reduce_rs"):
        labels = dict(S.family_tuples(family, 4))
        for fam, kw in SYNTH_ADMITTED:
            if fam != family:
                continue
            cfg = GroupGemmConfig(**kw)
            label = _gg_label(cfg)
            assert cfg.span_policy in label  # distinct from the contig twin
            assert labels.get(label) == cfg


@pytest.mark.parametrize("family,label", [
    ("ag_group_gemm", "bm128/bn1024/c2/window"),
    ("ag_group_gemm", "bm128/bn1024/c1/torus2d"),
    ("moe_reduce_rs", "bm128/bn1024/c4/interleave"),
])
def test_synth_tuples_prove_at_world8(family, label):
    """The widest acceptance world for a sample of admitted schedules:
    credit-balanced, deadlock-free, zero warnings (telemetry density and
    landing views included — the 0-warning posture the lint gates)."""
    rep = verify_capture(_cap(family, 8, label))
    assert rep.ok, rep.summary()
    assert not rep.warnings, rep.summary()


@pytest.mark.chaos
def test_synth_window_defect_twin_flagged():
    """The static defect twin on a SYNTHESIZED AG schedule: a dropped
    chunk signal is flagged by slot/site, the clean twin stays silent
    (the moe_rs twin lives in tests/test_synth.py)."""
    cap = _cap("ag_group_gemm", 2, "bm128/bn1024/c4/window")
    assert verify_capture(cap).ok
    seeded = D.seed_defect(cap, "dropped_signal")
    rep = verify_capture(seeded.capture)
    hits = [f for f in rep.errors if f.check == seeded.expect_check]
    assert hits and any(seeded.expect_naming in f.message for f in hits), (
        rep.summary()
    )


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-x", "-q"]))
