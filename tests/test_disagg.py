"""Disaggregated prefill/decode serving with the fault-tolerant KV
handoff plane (ISSUE 13; ``serving/disagg.py`` + ``serving/handoff.py``
+ ``ops/kv_stream.py``, docs/serving.md "Disaggregated serving").

Tier structure mirrors tests/test_serving.py:

- **host tier**: the handoff plane's manifest/trie semantics, ladder
  arithmetic, pool-scoped FaultPlan selection, config validation — no
  device work at all;
- **engine tier**: real two-pool ``DisaggServingEngine`` runs on a
  4-device CPU mesh (2 prefill + 2 decode), pinned byte-identical to
  the unified engine — greedy AND seeded-sampled — with the transfer
  phase decomposing e2e exactly;
- **chaos tier** (``pytest.mark.chaos``, rides ``chaos_matrix.sh``):
  corrupt/dropped KV chunks mid-handoff walking the full guard ladder
  with attributed strikes, the prefill-pool shrink-mid-stream arc, the
  pool-collapse-to-unified arc, and the quick disagg soak campaign with
  bit-identical seeded replay.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_dist_tpu import config as tdt_config
from triton_dist_tpu import resilience
from triton_dist_tpu.models import init_params
from triton_dist_tpu.models.decode import ContinuousBatcher, Request
from triton_dist_tpu.models.tp_transformer import TransformerConfig
from triton_dist_tpu.ops.allgather_gemm import AGGemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import GemmRSConfig
from triton_dist_tpu.resilience import elastic, health, retry
from triton_dist_tpu.resilience.faults import FaultPlan, pool_scope
from triton_dist_tpu.resilience.records import DistTimeoutError
from triton_dist_tpu.serving import (
    DisaggServingConfig,
    DisaggServingEngine,
    Finished,
    HandoffConfig,
    HandoffPlane,
    ServingConfig,
    ServingEngine,
    TrafficSpec,
    generate_trace,
)


@pytest.fixture(autouse=True)
def _restore_config():
    cfg = tdt_config.get_config()
    snap = (cfg.timeout_iters, cfg.fault_plan, cfg.raise_on_timeout,
            cfg.fallback_to_xla, cfg.retry_policy, cfg.elastic,
            cfg.suspect_threshold, cfg.probation_probes, cfg.obs)
    resilience.reset(keep_env=True)
    elastic.reset()
    yield
    tdt_config.update(
        timeout_iters=snap[0], fault_plan=snap[1], raise_on_timeout=snap[2],
        fallback_to_xla=snap[3], retry_policy=snap[4], elastic=snap[5],
        suspect_threshold=snap[6], probation_probes=snap[7], obs=snap[8],
    )
    retry.set_clock(None)
    resilience.reset(keep_env=True)
    elastic.reset()


def _cfg(**over):
    base = dict(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=4, n_kv_heads=2,
        head_dim=8, batch=2, seq=8,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
    )
    base.update(over)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, init_params(jax.random.PRNGKey(1), cfg)


def _mesh(lo, hi):
    return Mesh(np.array(jax.devices()[lo:hi]), ("tp",))


def _serve_disagg(cfg, params, trace, *, serving=None, **kw):
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = DisaggServingEngine(
            cfg, params, _mesh(0, 4), s_max=16, clock=clock,
            serving=serving or DisaggServingConfig(
                prefill_pes=2, virtual_step_s=0.05,
                handoff=HandoffConfig(page_tokens=4, chunks_per_page=2,
                                      virtual_chunk_s=0.001),
            ),
            **kw,
        )
        done = eng.serve(trace)
    return eng, done


def _serve_unified(cfg, params, trace, *, n=2):
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = ServingEngine(
            cfg, params, _mesh(2, 2 + n), s_max=16, clock=clock,
            serving=ServingConfig(virtual_step_s=0.05),
        )
        done = eng.serve(trace)
    return eng, done


# ---------------------------------------------------------------------------
# Host tier: the handoff plane
# ---------------------------------------------------------------------------

def _plane(**over):
    kw = dict(page_tokens=4, chunks_per_page=2)
    kw.update(over)
    return HandoffPlane(HandoffConfig(**kw), s_max=16, prefill_world=2,
                        decode_world=2)


def test_manifest_is_the_trie_key_chain():
    """Page identity = the FULL token prefix through the page — the
    radix-trie node identity of models/prefix_cache.py — so two prompts
    sharing page-g TOKENS but diverging earlier are different pages."""
    p = _plane()
    m = p.manifest([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert [g for g, _ in m] == [0, 1, 2]
    assert m[0][1] == (1, 2, 3, 4)
    assert m[1][1] == (1, 2, 3, 4, 5, 6, 7, 8)
    assert m[2][1] == (1, 2, 3, 4, 5, 6, 7, 8, 9)  # partial final page
    # divergence at page 0 makes EVERY later page distinct
    m2 = p.manifest([9, 2, 3, 4, 5, 6, 7, 8])
    assert m2[1][1] != m[1][1]


def test_shared_prefixes_stream_once():
    p = _plane()
    sys_prompt = [7, 7, 7, 7, 1, 1, 1, 1]
    r1 = p.transfer("a", sys_prompt + [2, 3], now=0.0)
    assert (r1.outcome, r1.pages_streamed, r1.pages_deduped) == (
        "delivered", 3, 0)
    # the second reader of the same system prompt streams only its
    # divergent page (the trie-as-manifest contract)
    r2 = p.transfer("b", sys_prompt + [4, 5], now=1.0)
    assert (r2.pages_streamed, r2.pages_deduped) == (1, 2)
    # a third, byte-identical prompt streams nothing new
    r3 = p.transfer("c", sys_prompt + [2, 3], now=2.0)
    assert (r3.pages_streamed, r3.pages_deduped) == (0, 3)
    assert p.counters["pages_streamed"] == 4
    assert p.counters["pages_deduped"] == 5


def test_transfer_charges_virtual_time_on_landing():
    p = _plane(virtual_chunk_s=0.01)
    r = p.transfer("a", list(range(8)), now=5.0)  # 2 pages × 2 chunks
    assert r.t_start == 5.0
    assert r.t_landed == pytest.approx(5.0 + 4 * 0.01)


def test_ladder_corrupt_chunk_retries_then_delivers():
    """One bounded corruption: the canary mismatch re-sends in place
    (rung 1), the culprit decode PE is struck, the transfer delivers."""
    tdt_config.update(elastic=True, suspect_threshold=8)
    tdt_config.update(fault_plan=FaultPlan(
        "bitflip", pe=-1, pool="decode", max_triggers=1))
    try:
        p = _plane()
        r = p.transfer("a", list(range(8)), now=0.0)
    finally:
        tdt_config.update(fault_plan=None, elastic=False)
    assert r.outcome == "delivered"
    assert r.retries == 1 and r.restreams == 0
    assert p.counters["canary_mismatches"] == 1
    assert r.culprit_pe in (2, 3)  # a decode-pool GLOBAL index
    assert elastic.state(r.culprit_pe) == "suspect"
    assert health.counters().get(("kv_handoff", "handoff_retry")) == 1


def test_ladder_persistent_corruption_walks_to_fallback():
    """Persistent corruption exhausts re-sends, re-streams, and lands on
    the decode-local cold re-prefill rung — every rung recorded, the
    request never lost."""
    tdt_config.update(elastic=True, suspect_threshold=100)
    tdt_config.update(fault_plan=FaultPlan("nan_inject", pe=-1,
                                           pool="decode"))
    try:
        p = _plane(max_restreams=1)
        r = p.transfer("a", list(range(8)), now=0.0)
    finally:
        tdt_config.update(fault_plan=None, elastic=False)
    assert r.outcome == "fallback"
    assert r.restreams == 1
    hc = health.counters()
    assert hc.get(("kv_handoff", "handoff_restream")) == 1
    assert hc.get(("kv_handoff", "handoff_fallback")) == 1
    assert not health.is_healthy()


def test_ladder_dropped_chunk_names_prefill_sender():
    """A dropped chunk signal is a bounded-wait timeout: the silent
    PREFILL sender is the culprit (by absence), charged chunk_timeout_s
    plus the deterministic backoff."""
    tdt_config.update(elastic=True, suspect_threshold=8)
    tdt_config.update(fault_plan=FaultPlan(
        "drop_signal", pe=-1, pool="prefill", site=0, max_triggers=1))
    try:
        p = _plane(chunk_timeout_s=0.5)
        r = p.transfer("a", list(range(8)), now=0.0)
    finally:
        tdt_config.update(fault_plan=None, elastic=False)
    assert r.outcome == "delivered" and r.retries == 1
    assert p.counters["chunk_timeouts"] == 1
    assert r.culprit_pe in (0, 1)  # a prefill-pool GLOBAL index
    assert r.t_landed > 0.5  # the expired wait was charged


def test_fault_plan_pool_selector_scopes_injection():
    """The ISSUE 13 FaultPlan satellite: pool= targets exactly one side
    of the handoff; the wrong side (and the no-pool world) never fires,
    and existing single-pool plans (pool=None) are untouched."""
    from triton_dist_tpu.resilience import faults

    plan = FaultPlan("drop_signal", pool="prefill").validate()
    tdt_config.update(fault_plan=plan)
    try:
        assert faults.active_plan() is None  # outside any pool scope
        with pool_scope("decode"):
            assert faults.active_plan() is None
        with pool_scope("prefill"):
            assert faults.active_plan() is plan
            with pool_scope("decode"):  # innermost scope wins
                assert faults.active_plan() is None
        # pool=None (every pre-disagg plan): byte-unchanged semantics —
        # fires everywhere, scope or not
        tdt_config.update(fault_plan=FaultPlan("drop_signal"))
        assert faults.active_plan() is not None
        with pool_scope("prefill"):
            assert faults.active_plan() is not None
    finally:
        tdt_config.update(fault_plan=None)
    with pytest.raises(ValueError, match="pool"):
        FaultPlan("drop_signal", pool="").validate()
    # a pool-scoped chunk-corruption plan leaves the plane alone when it
    # names the OTHER side
    tdt_config.update(fault_plan=FaultPlan("bitflip", pe=-1,
                                           pool="prefill"))
    try:
        p = _plane()
        r = p.transfer("a", list(range(8)), now=0.0)
    finally:
        tdt_config.update(fault_plan=None)
    assert r.outcome == "delivered" and r.retries == 0


def test_disagg_config_validation():
    with pytest.raises(ValueError, match="virtual_step_s"):
        DisaggServingConfig(
            prefill=ServingConfig(virtual_step_s=0.05)).validate()
    with pytest.raises(ValueError, match="prefill_pes"):
        DisaggServingConfig(prefill_pes=0).validate()
    with pytest.raises(ValueError, match="wire"):
        HandoffConfig(wire="fp64").validate()
    # the device-tier tuple a handoff policy selects is a real member of
    # the verified tune space
    from triton_dist_tpu.ops.kv_stream import KV_STREAM_TUNE_SPACE

    assert HandoffConfig(chunks_per_page=2).kv_stream_config() in (
        KV_STREAM_TUNE_SPACE
    )


# ---------------------------------------------------------------------------
# Engine tier: the two-pool topology
# ---------------------------------------------------------------------------

def _traffic(n=6, seed=3, **over):
    kw = dict(
        rate_rps=20.0, n_requests=n, prompt_len=("uniform", 2, 5),
        output_len=("uniform", 2, 4), vocab=32, seed=seed,
    )
    kw.update(over)
    return generate_trace(TrafficSpec(**kw))


def test_disagg_byte_identical_to_unified_greedy(model):
    cfg, params = model
    trace = _traffic()
    eng, done = _serve_disagg(cfg, params, trace)
    _, done_u = _serve_unified(cfg, params, trace)
    assert set(done) == {a.request.uid for a in trace}
    for uid in done:
        assert isinstance(done[uid], Finished)
        assert done[uid].tokens == done_u[uid].tokens, uid
    snap = eng.snapshot()
    assert snap["requests"]["handoffs"] == len(
        [u for u in done if len(done[u].tokens) > 1]
    )
    assert snap["handoff"]["fallbacks"] == 0
    assert not eng.collapsed


def test_disagg_byte_identical_seeded_sampled(model):
    cfg, params = model
    trace = _traffic(seed=11, temperature=0.8, top_k=4)
    _, done = _serve_disagg(cfg, params, trace)
    _, done_u = _serve_unified(cfg, params, trace)
    for uid in done:
        assert done[uid].tokens == done_u[uid].tokens, uid


def test_cross_pool_first_token_consistency(model):
    """The decode pool regenerates the first token the prefill pool
    already served; the two derive it from the same prefix + seed and
    must agree — the cross-pool consistency pin."""
    cfg, params = model
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = DisaggServingEngine(
            cfg, params, _mesh(0, 4), s_max=16, clock=clock,
            serving=DisaggServingConfig(prefill_pes=2, virtual_step_s=0.05),
        )
        uid = eng.submit(Request([3, 1, 4, 1, 5], max_new_tokens=4,
                                 temperature=0.9, seed=7, uid="x"))
        eng.run_until_idle()
    fin = eng.results[uid]
    # TTFT came from the prefill pool; the decode stream regenerated the
    # same first token as position L's decode
    assert fin.t_first_token is not None
    assert len(fin.tokens) == 4


def test_transfer_phase_decomposes_e2e_exactly(model):
    """The ISSUE 13 obs satellite: queued → prefill → transfer → decode
    sums EXACTLY to e2e for every handed-off request, and the
    serving:transfer span carries the handoff attribution."""
    from triton_dist_tpu import obs

    cfg, params = model
    tdt_config.update(obs=obs.ObsConfig())
    obs.reset()
    try:
        eng, done = _serve_disagg(cfg, params, _traffic())
        spans = list(obs.tracer.spans())
        snap = eng.snapshot()
    finally:
        tdt_config.update(obs=None)
        obs.reset()
    by_req: dict = {}
    for s in spans:
        if s.name.startswith("serving:"):
            by_req.setdefault(s.track, {})[s.name] = s
    checked = 0
    for track, ss in by_req.items():
        if "serving:transfer" not in ss:
            continue
        checked += 1
        t = ss["serving:transfer"]
        assert t.attrs["outcome"] == "delivered"
        assert t.attrs["pages_streamed"] + t.attrs["pages_deduped"] >= 1
        # exact decomposition: each phase starts where the last ended
        assert ss["serving:queued"].t_end == ss["serving:prefill"].t_start
        assert ss["serving:prefill"].t_end == t.t_start
        assert t.t_end == ss["serving:decode"].t_start
        assert ss["serving:queued"].t_start == ss["serving:e2e"].t_start
        assert ss["serving:decode"].t_end == ss["serving:e2e"].t_end
    assert checked >= 1
    assert "serving:transfer" in snap["span_ms"]


def test_disagg_ttft_beats_unified_at_high_load(model):
    """The A/B the topology exists for: at an offered load that saturates
    the unified engine's slots, dedicated prefill slots keep TTFT down
    (first tokens keep flowing while decode is busy)."""
    cfg, params = model
    trace = _traffic(n=16, seed=5, rate_rps=40.0,
                     prompt_len=("uniform", 2, 4),
                     output_len=("uniform", 4, 6))
    eng, done = _serve_disagg(cfg, params, trace)
    uni, done_u = _serve_unified(cfg, params, trace)
    d = eng.snapshot()["latency_ms"]["ttft"]["p99"]
    u = uni.snapshot()["latency_ms"]["ttft"]["p99"]
    assert d < u, (d, u)


def test_prefill_overflow_sheds_to_decode_local(model):
    """A full prefill-pool queue routes new work decode-local (cold,
    correct, slower) instead of rejecting it."""
    cfg, params = model
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = DisaggServingEngine(
            cfg, params, _mesh(0, 4), s_max=16, clock=clock,
            serving=DisaggServingConfig(
                prefill_pes=2, virtual_step_s=0.05,
                prefill=ServingConfig(max_queue=1),
            ),
        )
        for i in range(8):
            res = eng.submit(Request([1, 2, 3, 4], max_new_tokens=3,
                                     uid=f"r{i}"))
            assert res == f"r{i}"  # never rejected: the decode pool absorbs
        done = eng.run_until_idle()
    assert len(done) == 8
    assert eng.snapshot()["requests"]["local_prefills"] >= 1
    from triton_dist_tpu.serving import Arrival

    _, done_u = _serve_unified(
        cfg, params,
        [Arrival(t_s=0.0, request=Request([1, 2, 3, 4], max_new_tokens=3,
                                          uid=f"r{i}"))
         for i in range(8)],
    )
    for uid in done:
        assert done[uid].tokens == done_u[uid].tokens


def test_w8_serving_params_quantized_once(model):
    """ISSUE 13 satellite (the tp_transformer.py:360 noted follow-up):
    a w8 MoE serving engine quantizes FLOAT expert banks ONCE at build —
    the batcher's params carry pre-quantized int8 pools + explicit
    scales (so resolve_w8's per-call quantize bank read+write never
    runs) — and the quantized-once tree is bit-identical to what the
    on-the-fly path quantizes per call."""
    from triton_dist_tpu.models.tp_transformer import (
        MoETransformerConfig, init_moe_params,
    )
    from triton_dist_tpu.ops.group_gemm import (
        GroupGemmConfig, quantize_expert_weights, resolve_w8,
    )

    cfg = MoETransformerConfig(
        vocab=32, hidden=32, ffn=64, n_layers=1, n_q_heads=4, n_kv_heads=2,
        head_dim=8, batch=2, seq=8, n_experts=4, topk=2,
        ag_config=AGGemmConfig(8, 16, 16), rs_config=GemmRSConfig(8, 16, 16),
        gg_config=GroupGemmConfig(block_m=8, block_n=16, w8=True),
    )
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(cfg, params, _mesh(0, 1), s_max=16)
    served = eng._batcher.params["layers"][0]
    assert served["w_up"].dtype == np.int8
    assert "w_up_scale" in served and "w_down_scale" in served
    # bit-identity vs the on-the-fly path (both route through
    # quantize_expert_weights)
    w_fly, s_fly = resolve_w8(params["layers"][0]["w_up"], None,
                              cfg.gg_config)
    w_once, s_once = quantize_expert_weights(params["layers"][0]["w_up"])
    assert np.array_equal(np.asarray(w_fly), np.asarray(w_once))
    assert np.array_equal(np.asarray(s_fly), np.asarray(s_once))
    # the cache serves ONE quantization for the engine's lifetime
    assert eng._serving_params() is eng._serving_params()
    # a non-w8 engine (or pre-quantized params) passes through untouched
    cfg2 = dataclasses.replace(
        cfg, gg_config=GroupGemmConfig(block_m=8, block_n=16))
    eng2 = ServingEngine(cfg2, params, _mesh(0, 1), s_max=16)
    assert eng2._serving_params() is params


def test_both_pools_full_reoffers_never_drops(model):
    """A burst larger than BOTH pools' queues: serve() re-offers each
    doubly-rejected arrival instead of dropping it — every offered uid
    still reaches exactly one terminal state."""
    from triton_dist_tpu.serving import Arrival

    cfg, params = model
    trace = [
        Arrival(t_s=0.0, request=Request([1, 2, 3], max_new_tokens=2,
                                         uid=f"b{i}"))
        for i in range(12)
    ]
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = DisaggServingEngine(
            cfg, params, _mesh(0, 4), s_max=16, clock=clock,
            serving=DisaggServingConfig(
                prefill_pes=2, virtual_step_s=0.05,
                prefill=ServingConfig(max_queue=1),
                decode=ServingConfig(max_queue=1),
            ),
        )
        done = eng.serve(trace)
    assert set(done) == {a.request.uid for a in trace}
    assert all(isinstance(r, Finished) for r in done.values())
    assert eng.snapshot()["requests"]["reoffered"] >= 1


def test_decode_rebuild_invalidates_transfer_manifest(model):
    """A decode-pool rebuild destroys its cache, so the transfer
    manifest must forget previously streamed pages — the next shared
    prefix re-streams instead of dedup'ing onto dead pages."""
    cfg, params = model
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng = DisaggServingEngine(
            cfg, params, _mesh(0, 4), s_max=16, clock=clock,
            serving=DisaggServingConfig(prefill_pes=2, virtual_step_s=0.05),
        )
        eng.submit(Request([1, 2, 3, 4, 5], max_new_tokens=2, uid="a"))
        eng.run_until_idle()
        assert eng.handoff_plane.snapshot()["pages_resident"] > 0
        # simulate a decode-pool rebuild having happened
        eng.decode.rebuilds += 1
        eng.submit(Request([1, 2, 3, 4, 5], max_new_tokens=2, uid="b"))
        eng.run_until_idle()
    ho = eng.handoff_plane.snapshot()
    # the second identical prompt re-streamed (no dedup onto dead pages)
    assert ho["pages_deduped"] == 0
    assert ho["pages_streamed"] >= 2


# ---------------------------------------------------------------------------
# Chaos tier
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_corrupt_chunk_mid_handoff_attributed_recovery(model):
    """THE acceptance arc: a corrupted KV chunk mid-handoff produces an
    attributed recovery — the named decode PE is struck through the
    elastic state machine, every rung lands in the health registry, and
    every request finishes byte-identically to unified-engine cold
    prefill (greedy AND seeded-sampled)."""
    cfg, params = model
    for temp_kw in ({}, dict(temperature=0.8, top_k=4)):
        resilience.reset(keep_env=True)
        elastic.reset()
        trace = _traffic(n=4, seed=5, prompt_len=("fixed", 5),
                         output_len=("fixed", 3), **temp_kw)
        tdt_config.update(elastic=True, suspect_threshold=2,
                          fault_plan=FaultPlan("bitflip", pe=-1,
                                               pool="decode", site=1,
                                               max_triggers=12))
        try:
            eng, done = _serve_disagg(cfg, params, trace)
        finally:
            tdt_config.update(fault_plan=None, elastic=False)
        snap = eng.snapshot()
        ho = snap["handoff"]
        assert ho["canary_mismatches"] > 0
        assert ho["restreams"] > 0 and ho["fallbacks"] > 0
        # the culprit decode PE is STRUCK by name (global index)
        struck = [pe for pe, st in elastic.peer_states().items()
                  if st != "healthy"]
        assert struck and all(pe >= 2 for pe in struck), (
            elastic.peer_states()
        )
        hc = health.counters()
        assert hc.get(("kv_handoff", "handoff_retry"), 0) > 0
        assert hc.get(("kv_handoff", "handoff_fallback"), 0) > 0
        # zero lost, byte-identical to unified cold prefill
        _, done_u = _serve_unified(cfg, params, trace)
        assert set(done) == {a.request.uid for a in trace}
        for uid in done:
            assert done[uid].tokens == done_u[uid].tokens, (uid, temp_kw)


@pytest.mark.chaos
def test_prefill_straggler_shrinks_pool_mid_stream(model):
    """A prefill-pool straggler quarantines (pool-scoped by-absence
    attribution at the GLOBAL index) and the POOL shrinks mid-stream —
    the decode pool never shrinks, and serving completes byte-identical."""
    cfg, params = model
    trace = _traffic(n=6, seed=9)
    tdt_config.update(elastic=True, suspect_threshold=2)
    real_step = ContinuousBatcher.step
    calls = {"n": 0}

    def flaky(self):
        from triton_dist_tpu.resilience import faults as F

        if F.current_pool() == "prefill":
            calls["n"] += 1
            if calls["n"] in (2, 3):
                w = int(self.mesh.shape["tp"])
                recs = [{"pe": p, "kind": "barrier_all", "site": 0,
                         "status": "timeout", "expected": 1, "observed": 0,
                         "budget": 16} for p in range(w) if p != 1]
                raise DistTimeoutError("batcher_step", recs, world_size=w)
        return real_step(self)

    ContinuousBatcher.step = flaky
    try:
        eng, done = _serve_disagg(cfg, params, trace)
    finally:
        ContinuousBatcher.step = real_step
        tdt_config.update(elastic=False)
    # pool position 1 == GLOBAL PE 1 quarantined; decode pool untouched
    assert elastic.state(1) == "quarantined"
    assert all(elastic.state(pe) == "healthy" for pe in (2, 3))
    snap = eng.snapshot()
    assert snap["pools"]["prefill"]["engine"]["world_size"] == 1
    assert snap["pools"]["decode"]["engine"]["world_size"] == 2
    assert not eng.collapsed
    _, done_u = _serve_unified(cfg, params, trace)
    assert set(done) == {a.request.uid for a in trace}
    for uid in done:
        assert done[uid].tokens == done_u[uid].tokens, uid


@pytest.mark.chaos
def test_prefill_pool_collapse_degrades_to_unified(model):
    """The prefill pool losing its last PE collapses the topology to the
    unified engine: every in-flight request replays into the decode pool
    and finishes — zero lost requests, byte-identical tokens, one
    attributed pool_collapse health event."""
    cfg, params = model
    trace = _traffic(n=8, seed=7, rate_rps=30.0)
    tdt_config.update(elastic=True, suspect_threshold=2)
    real_step = ContinuousBatcher.step
    calls = {"n": 0}

    def flaky(self):
        from triton_dist_tpu.resilience import faults as F

        if F.current_pool() == "prefill":
            calls["n"] += 1
            if calls["n"] >= 2:  # a storm the pool cannot survive
                w = int(self.mesh.shape["tp"])
                recs = [{"pe": p, "kind": "barrier_all", "site": 0,
                         "status": "timeout", "expected": 1, "observed": 0,
                         "budget": 16} for p in range(w) if p != 1]
                raise DistTimeoutError("batcher_step", recs, world_size=w)
        return real_step(self)

    ContinuousBatcher.step = flaky
    try:
        eng, done = _serve_disagg(
            cfg, params, trace,
            serving=DisaggServingConfig(
                prefill_pes=2, virtual_step_s=0.05,
                prefill=ServingConfig(max_step_failures=3),
                handoff=HandoffConfig(page_tokens=4, chunks_per_page=1),
            ),
        )
    finally:
        ContinuousBatcher.step = real_step
        tdt_config.update(elastic=False)
    assert eng.collapsed
    snap = eng.snapshot()
    assert snap["requests"]["pool_collapses"] == 1
    assert health.counters().get(("serving_disagg", "pool_collapse")) == 1
    assert not health.is_healthy()
    # zero lost requests, byte-identical to unified cold prefill
    assert set(done) == {a.request.uid for a in trace}
    assert all(isinstance(r, Finished) for r in done.values())
    _, done_u = _serve_unified(cfg, params, trace)
    for uid in done:
        assert done[uid].tokens == done_u[uid].tokens, uid
    # and the collapsed topology keeps serving new work (unified mode)
    clock = retry.FakeClock()
    with retry.clock_scope(clock):
        eng.clock = clock
        eng.decode.clock = clock
        uid = eng.submit(Request([1, 2, 3], max_new_tokens=2, uid="post"))
        eng.run_until_idle()
    assert isinstance(eng.results["post"], Finished)


@pytest.mark.chaos
def test_disagg_soak_campaign_quick_and_replay():
    """The chaos-matrix disagg soak cell: one seeded two-pool campaign
    (burst traffic × corrupt KV chunks mid-handoff × prefill straggler)
    passes every invariant and replays bit-identically from its seed."""
    from triton_dist_tpu.resilience import soak

    spec = soak.SoakSpec.disagg(seed=1)
    res = soak.run_campaign(spec)
    assert res.ok, (res.failures, res.error)
    again = soak.run_campaign(spec)
    assert again.fingerprint == res.fingerprint


@pytest.mark.chaos
def test_disagg_soak_collapse_campaign():
    """The scheduled-pool-collapse composition (every third seed): the
    campaign must actually collapse and still satisfy every invariant."""
    from triton_dist_tpu.resilience import soak

    spec = soak.SoakSpec.disagg(seed=0)
    assert spec.collapse_at_step > 0
    res = soak.run_campaign(spec)
    assert res.ok, (res.failures, res.error)
    assert res.snapshot["engine"]["collapsed"]


@pytest.mark.soak
def test_disagg_soak_campaign_set():
    """The full ISSUE 13 disagg set (5 seeds — what scripts/chaos_soak.py
    runs); soak marker ⇒ slow, never rides tier-1."""
    from triton_dist_tpu.resilience import soak

    for seed in range(200, 205):
        res = soak.run_campaign(soak.SoakSpec.disagg(seed=seed))
        assert res.ok, (seed, res.failures, res.error)
